#!/usr/bin/env python
"""Flagship-scale convergence run (VERDICT r2 item 7).

Trains the flagship DeepDFA configuration — input_dim 1002 (limit_all
1000 + 2), hidden 32, n_steps 5, batch 256, Adam 1e-3 / wd 1e-2,
per-epoch 1:1 undersampling — on a ~20k-graph synthetic corpus with
Big-Vul's class skew (~6% vulnerable) and CFG-size tail, mirroring the
reference recipe (DDFA/configs/config_default.yaml:43-47,
config_bigvul.yaml:1-8, config_ggnn.yaml:1-5; paper Table 5's 25-epoch
9-minute run). Records wall-clock, epochs, and per-epoch metrics to a
committed run log.

    python scripts/train_flagship.py --out docs/convergence_run.json
    DEEPDFA_TPU_PLATFORM=cpu python scripts/train_flagship.py ...
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _noise_ceiling(rate: float, noise: float) -> float | None:
    """Measured-F1 ceiling under injected label noise: a PERFECT model
    scores precision=(1-noise) and recall=p/(p+q) against the noisy
    labels, with p=rate*(1-noise) true positives still labeled 1 and
    q=(1-rate)*noise flipped negatives it can never flag."""
    if not noise:
        return None
    p = rate * (1 - noise)
    q = (1 - rate) * noise
    prec, rec = 1 - noise, p / (p + q)
    return round(2 * prec * rec / (prec + rec), 4)


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--n-examples", type=int, default=20_000)
    ap.add_argument("--vuln-rate", type=float, default=0.06)
    ap.add_argument("--max-epochs", type=int, default=25)
    ap.add_argument("--target-f1", type=float, default=0.9)
    ap.add_argument("--batch-graphs", type=int, default=256)
    ap.add_argument("--workers", type=int, default=0, help="pipeline mp workers")
    ap.add_argument("--seed", type=int, default=1)
    ap.add_argument("--corpus", choices=("v1", "v2"), default="v2",
                    help="v2 (default): order families + benign lookalikes "
                    "+ label noise + held-out-family split "
                    "(VERDICT r3 item 4); v1: the round-3 corpus")
    ap.add_argument("--label-noise", type=float, default=0.02)
    ap.add_argument("--lookalike-rate", type=float, default=0.5)
    ap.add_argument("--holdout-family", default="index_clamp_order",
                    help="bug family excluded from train/val and reported "
                    "separately on test ('' disables)")
    ap.add_argument("--feat-dropout", type=float, default=0.0,
                    help="train.feat_unknown_dropout: anonymize this "
                    "fraction of def buckets per step so decisions also "
                    "ride graph structure (cross-template transfer)")
    ap.add_argument("--gtype", choices=("cfg", "cfg+dep", "pdg"),
                    default="cfg+dep",
                    help="graph relation set (the reference's gtype/rdg "
                    "axis). v2's order families put the discriminating "
                    "signal ~5+ featureless expression-CFG hops from the "
                    "use, beyond n_steps=5 propagation on plain cfg — "
                    "typed data-dependence edges (cfg+dep) carry it "
                    "directly, which is the corpus's point: flow "
                    "structure, not token counts, decides the label")
    ap.add_argument("--struct-feats", action="store_true",
                    help="append family-invariant structural channels "
                    "(frontend/structfeat.py: operator class, cfg degree, "
                    "ast depth, def-use distance, reaching-def count) and "
                    "embed them alongside the vocab features — the "
                    "VERDICT r4 cross-template remedy: these survive "
                    "UNKNOWN-vocab collapse on held-out families")
    ap.add_argument("--out", default="docs/convergence_run.json")
    args = ap.parse_args()

    from deepdfa_tpu.core.backend import apply_platform_override

    apply_platform_override()
    import jax
    import numpy as np

    from deepdfa_tpu.core import Config, config as config_mod
    from deepdfa_tpu.data import (
        bigvul_stmt_sizes,
        build_dataset,
        generate,
        to_examples,
    )
    from deepdfa_tpu.data.synthetic import generate_v2
    from deepdfa_tpu.eval.trivial_baseline import logistic_control
    from deepdfa_tpu.graphs import shard_bucket_batches
    from deepdfa_tpu.models import DeepDFA
    from deepdfa_tpu.train import GraphTrainer, undersample_epoch

    platform = jax.devices()[0].platform
    t_start = time.perf_counter()

    # -- corpus through the full frontend pipeline --------------------------
    n = args.n_examples
    sizes = bigvul_stmt_sizes(n, seed=args.seed)
    if args.corpus == "v2":
        synth = generate_v2(
            n, vuln_rate=args.vuln_rate, seed=args.seed, stmt_sizes=sizes,
            lookalike_rate=args.lookalike_rate, label_noise=args.label_noise,
        )
    else:
        synth = generate(
            n, vuln_rate=args.vuln_rate, seed=args.seed, stmt_sizes=sizes
        )
    # reference split discipline: train-only vocab, fixed 80/10/10.
    # Cross-template constraint: every example of the holdout family goes
    # to TEST — the GGNN never sees that bug shape in training.
    rng = np.random.default_rng(args.seed)
    holdout = args.holdout_family if args.corpus == "v2" else ""
    held_ids = {
        s.id for s in synth
        if holdout and s.family.removeprefix("lookalike:") == holdout
    }
    free = np.array([gid for gid in range(n) if gid not in held_ids])
    perm = free[rng.permutation(len(free))]
    # fractions of the holdout-REDUCED pool, so the test split keeps its
    # 10% share instead of absorbing the whole holdout deficit
    n_train, n_val = int(len(free) * 0.8), int(len(free) * 0.1)
    train_ids = set(perm[:n_train].tolist())
    val_ids = set(perm[n_train : n_train + n_val].tolist())
    # headline test = seen families only; the held-out family (positives
    # AND its lookalikes) is its own split, reported separately — mixing
    # the never-seen template into the headline conflates in-distribution
    # effectiveness with cross-template generalization
    test_ids = set(perm[n_train + n_val :].tolist())
    specs, _ = build_dataset(
        to_examples(synth), train_ids=train_ids, limit_all=1000,
        limit_subkeys=1000, workers=args.workers, gtype=args.gtype,
        struct_feats=args.struct_feats,
    )
    t_data = time.perf_counter() - t_start
    by_split = {
        "train": [s for s in specs if s.graph_id in train_ids],
        "val": [s for s in specs if s.graph_id in val_ids],
        "test": [s for s in specs if s.graph_id in test_ids],
    }
    heldout_specs = [s for s in specs if s.graph_id in held_ids]
    labels = np.array([s.label for s in by_split["train"]])

    # -- flagship trainer ---------------------------------------------------
    from deepdfa_tpu.core.config import GTYPE_ETYPES

    overrides = [
        "model.hidden_dim=32",
        "model.n_steps=5",
        f"model.n_etypes={GTYPE_ETYPES[args.gtype]}",
        f"data.gtype={args.gtype}",
        f"train.max_epochs={args.max_epochs}",
        f"train.feat_unknown_dropout={args.feat_dropout}",
        f"model.struct_feats={'true' if args.struct_feats else 'false'}",
        f"data.feat.struct_feats={'true' if args.struct_feats else 'false'}",
    ]
    if platform != "cpu":
        overrides.append("model.scan_steps=true")  # keep the TPU compile small
    cfg = config_mod.apply_overrides(Config(), overrides)
    model = DeepDFA.from_config(cfg.model, input_dim=1002)
    trainer = GraphTrainer(model, cfg)

    def batches_for(split_specs):
        return list(
            shard_bucket_batches(
                split_specs, 1, args.batch_graphs, 16384, 65536,
                oversized="raise",
            )
        )

    val_batches = batches_for(by_split["val"])

    def train_batches(epoch):
        idx = undersample_epoch(labels, epoch, seed=args.seed)
        return batches_for([by_split["train"][i] for i in idx])

    state = trainer.init_state(val_batches[0], seed=args.seed)

    # -- epoch loop with per-epoch val F1 (reference monitors val loss;
    #    the convergence claim here is F1, so both are recorded) ------------
    epochs_log = []
    t_train0 = time.perf_counter()
    reached_at = None
    best_val_f1, best_epoch, best_params = -1.0, -1, None
    for epoch in range(args.max_epochs):
        t0 = time.perf_counter()
        # fit() counts its own epochs from 0; bind THIS epoch's
        # undersample so every epoch draws a fresh negative sample
        state = trainer.fit(
            state, lambda _e, ep=epoch: train_batches(ep), max_epochs=1
        )
        val_metrics, _ = trainer.evaluate(state, val_batches)
        rec = {
            "epoch": epoch,
            "epoch_seconds": round(time.perf_counter() - t0, 2),
            "val_f1": round(val_metrics["f1"], 4),
            "val_precision": round(val_metrics["precision"], 4),
            "val_recall": round(val_metrics["recall"], 4),
            "val_loss": round(val_metrics["loss"], 4),
        }
        epochs_log.append(rec)
        print(json.dumps(rec), flush=True)
        if val_metrics["f1"] > best_val_f1:
            # best-val checkpoint selection, the reference's protocol
            # (best-F1 checkpointing linevul_main.py:225-251; post-fit
            # best-ckpt selection main_cli.py:175-183) — test metrics
            # come from THIS state, not the last epoch's
            best_val_f1, best_epoch = val_metrics["f1"], epoch
            best_params = jax.device_get(state.params)
        if val_metrics["f1"] >= args.target_f1 and reached_at is None:
            reached_at = epoch
            break
    train_seconds = time.perf_counter() - t_train0

    if best_params is not None:
        state = dataclasses.replace(state, params=jax.device_put(best_params))
    test_metrics, _ = trainer.evaluate(state, batches_for(by_split["test"]))

    # -- trivial-baseline control: logistic regression over subkey
    #    histograms — the GGNN's margin over this is the corpus-hardness
    #    evidence (VERDICT r3 item 4) ---------------------------------------
    control_splits = {"val": by_split["val"], "test": by_split["test"]}
    if heldout_specs:
        control_splits["heldout_family"] = heldout_specs
    control = logistic_control(
        by_split["train"], control_splits, input_dim=1002, seed=args.seed
    )
    heldout_metrics = None
    if heldout_specs:
        hm, _ = trainer.evaluate(state, batches_for(heldout_specs))
        heldout_metrics = {k: round(hm[k], 4)
                          for k in ("f1", "precision", "recall")}

    record = {
        "recipe": {
            "input_dim": 1002, "hidden_dim": 32, "n_steps": 5,
            "batch_graphs": args.batch_graphs, "optimizer": "adam lr=1e-3 wd=1e-2",
            "undersample": "1:1 per epoch",
            "corpus": f"synthetic bigvul-style {args.corpus} n={n} "
            f"vuln_rate={args.vuln_rate} lookalike_rate="
            f"{args.lookalike_rate if args.corpus == 'v2' else 0} "
            f"label_noise={args.label_noise if args.corpus == 'v2' else 0} "
            f"(data/synthetic.py)",
            "gtype": args.gtype,
            "feat_unknown_dropout": args.feat_dropout,
            "struct_feats": args.struct_feats,
            "holdout_family": holdout or None,
            "reference": "config_default.yaml:43-47 + config_bigvul.yaml + config_ggnn.yaml",
        },
        "platform": platform,
        "scan_steps": cfg.model.scan_steps,
        "data_pipeline_seconds": round(t_data, 1),
        "train_seconds": round(train_seconds, 1),
        "epochs_run": len(epochs_log),
        "target_f1": args.target_f1,
        "label_noise_f1_ceiling": _noise_ceiling(
            args.vuln_rate, args.label_noise if args.corpus == "v2" else 0.0
        ),
        "reached_target_at_epoch": reached_at,
        "final_val_f1": epochs_log[-1]["val_f1"] if epochs_log else None,
        "best_val_f1": round(best_val_f1, 4),
        "best_val_epoch": best_epoch,
        "test_protocol": "best-val-F1 checkpoint (reference protocol)",
        "test_f1": round(test_metrics["f1"], 4),
        "test_precision": round(test_metrics["precision"], 4),
        "test_recall": round(test_metrics["recall"], 4),
        "heldout_family_ggnn": heldout_metrics,
        "trivial_baseline": {
            "model": "logistic regression over log1p subkey histograms "
            "(eval/trivial_baseline.py), balanced class weights",
            **{
                split: {k: round(v, 4) for k, v in m.items()}
                for split, m in control.items()
            },
        },
        "ggnn_minus_baseline_test_f1": round(
            test_metrics["f1"] - control["test"]["f1"], 4
        ),
        "epochs": epochs_log,
    }
    out = args.out
    os.makedirs(os.path.dirname(out) or ".", exist_ok=True)
    with open(out, "w") as f:
        json.dump(record, f, indent=1)
    print(json.dumps({k: v for k, v in record.items() if k != "epochs"}),
          flush=True)


if __name__ == "__main__":
    main()
