#!/usr/bin/env python
"""Serving throughput/latency benchmark (docs/serving.md).

Drives the online scoring stack — cached frontend -> dynamic batcher ->
AOT bucket executables (deepdfa_tpu/serve/) — over a synthetic corpus
and reports:

  serve_requests_per_sec      warm pass (feature-cache hits: the heavy-
                              traffic repeat-function case the cache
                              exists for)
  serve_cold_requests_per_sec first pass (frontend extraction included)
  serve_latency_p50_ms / serve_latency_p99_ms  (warm pass)
  serve_batch_occupancy_mean  mean fill fraction of executed batches
  serve_steady_state_recompiles  must be 0 after warmup
  serve_obs_overhead_fraction    warm-path cost of the FULL request
      observability stack (request tracing + flow events + SLO window
      ingest), measured with the PR-4 interleaved-reps method — plain
      and instrumented passes alternate so the box's minute-to-minute
      throughput drift cancels out of the comparison; documented bound
      <=2% (docs/slo.md)

Modes:
    python scripts/bench_serve.py --smoke   # tier-1 regression mode
    python scripts/bench_serve.py           # full mode (bigger corpus)

No checkpoint round trip: the model is a freshly initialized GGNN (the
benchmark measures the serving machinery, not the weights); the restore
path has its own e2e coverage (`deepdfa-tpu score --smoke`).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from pathlib import Path

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def bench_serve(
    n_examples: int = 256, smoke: bool = False, max_batch: int = 8
) -> dict:
    import jax

    from deepdfa_tpu.core import Config, config as config_mod
    from deepdfa_tpu.data import build_dataset, generate, to_examples
    from deepdfa_tpu.models import DeepDFA
    from deepdfa_tpu.obs import metrics as obs_metrics
    from deepdfa_tpu.serve.batcher import DynamicBatcher, GgnnExecutor
    from deepdfa_tpu.serve.frontend import RequestPreprocessor

    n = min(n_examples, 48) if smoke else int(n_examples)
    cfg = config_mod.apply_overrides(Config(), [
        'data.feat={"limit_all": 50, "limit_subkeys": 50}',
        "model.hidden_dim=8" if smoke else "model.hidden_dim=32",
        "model.n_steps=2" if smoke else "model.n_steps=5",
        f"serve.max_batch_graphs={max_batch}",
    ])
    synth = generate(n, seed=0)
    examples = to_examples(synth)
    # vocabularies straight from the corpus (no disk round trip)
    _, vocabs = build_dataset(
        examples, train_ids=range(n),
        limit_all=cfg.data.feat.limit_all,
        limit_subkeys=cfg.data.feat.limit_subkeys,
    )
    model = DeepDFA.from_config(
        cfg.model, input_dim=cfg.data.feat.input_dim
    )
    node_budget, edge_budget = 2048, 8192
    pre = RequestPreprocessor(cfg, vocabs, cache_entries=4 * n)
    from deepdfa_tpu.graphs.batch import pack

    params = model.init(
        jax.random.key(0),
        pack([], 1, node_budget, edge_budget),
    )
    executor = GgnnExecutor(
        model, lambda: params,
        node_budget=node_budget, edge_budget=edge_budget,
        max_batch_graphs=max_batch,
    )
    t0 = time.perf_counter()
    warm_report = executor.warmup()
    warmup_seconds = time.perf_counter() - t0
    lowerings0 = executor.jit_lowerings()

    def one_pass(slo=None, depth: int = 0):
        """One scoring pass; returns (dt, probs, latencies, pipeline
        stats). `depth` drives the pipelined executor path
        (docs/serving.md "Pipelined execution"); 0 = serial."""
        batcher = DynamicBatcher(
            executor, queue_limit=max(64, n),
            max_batch_delay_s=0.005, slo=slo, pipeline_depth=depth,
        )
        payloads = []
        for e in examples:
            try:
                payloads.append(pre.features(e.code, e.id))
            except Exception:
                pass
        t0 = time.perf_counter()
        reqs = batcher.score_all(payloads)
        if slo is not None:
            # the server epilogue per request: status + stage ingest
            for r in reqs:
                slo.observe_request(
                    200 if r.error is None else 500, r.latency_s,
                    queue_s=r.queue_wait_s, device_s=r.device_s,
                )
        dt = time.perf_counter() - t0
        latencies = sorted(batcher.recent_latencies)
        probs = [
            None if r.error is not None else r.result for r in reqs
        ]
        pstats = batcher.pipeline_stats()
        batcher.close()
        return dt, probs, latencies, pstats

    cold_dt, probs0, _, _ = one_pass()  # frontend runs (cache cold)
    warm_dt, _, lat, _ = one_pass()  # cache hits: batching + device only
    scored = len(probs0)

    # SLO + tracing tax on the warm path (ISSUE 6 satellite): plain vs
    # fully-instrumented (request tracing with flow events + SLO window
    # ingest) passes INTERLEAVED — this box's throughput drifts minute
    # to minute, so two sequential blocks would measure the drift, not
    # the instrumentation (the PR-4 obs_overhead_fraction method)
    import statistics
    import tempfile

    from deepdfa_tpu.obs import slo as obs_slo, trace as obs_trace

    reps = 3 if smoke else 5
    plain_dts: list[float] = []
    inst_dts: list[float] = []
    ambient_dir = os.environ.get(obs_trace.ENV_TRACE_DIR)
    try:
        with tempfile.TemporaryDirectory() as td:
            for i in range(2 * reps):
                instrumented = i % 2 == 1
                if instrumented:
                    obs_trace.enable(td, process_name="bench-serve")
                try:
                    dt_i, _, _, _ = one_pass(
                        slo=obs_slo.SloEngine() if instrumented
                        else None
                    )
                    (inst_dts if instrumented else plain_dts).append(
                        dt_i
                    )
                finally:
                    if instrumented:
                        obs_trace.disable()
    finally:
        if ambient_dir:
            obs_trace.enable(
                ambient_dir, process_name="bench-serve",
                export_env=True,
            )
    plain_rps = scored / statistics.median(plain_dts)
    inst_rps = scored / statistics.median(inst_dts)

    # pipelined-vs-serial comparison (ISSUE 17): same interleaved-reps
    # method as the obs-overhead measurement — serial (depth=0) and
    # pipelined (depth=2) warm passes alternate so throughput drift
    # cancels. The pipelined pass must also be BIT-IDENTICAL: the
    # packing, programs, and FIFO order are unchanged, only the sync
    # point moves to the fetch thread.
    pipeline_depth = 2
    serial_dts: list[float] = []
    pipe_dts: list[float] = []
    idle_fracs: list[float] = []
    serial_probs = pipe_probs = None
    for i in range(2 * reps):
        depth = pipeline_depth if i % 2 == 1 else 0
        dt_i, probs_i, _, pstats = one_pass(depth=depth)
        if depth:
            pipe_dts.append(dt_i)
            pipe_probs = probs_i
            if pstats["device_idle_fraction"] is not None:
                idle_fracs.append(pstats["device_idle_fraction"])
        else:
            serial_dts.append(dt_i)
            serial_probs = probs_i
    if serial_probs != pipe_probs:
        raise SystemExit(
            "pipelined scores diverged from the serial path "
            "(bit-identity contract, docs/serving.md)"
        )
    serial_rps = scored / statistics.median(serial_dts)
    pipe_rps = scored / statistics.median(pipe_dts)
    idle_frac = (
        round(statistics.median(idle_fracs), 4) if idle_fracs else None
    )

    from deepdfa_tpu.serve.batcher import percentile

    def pct_ms(p):
        v = percentile(lat, p)
        return None if v is None else round(1e3 * v, 3)

    return {
        "metric": "serve_requests_per_sec",
        "value": round(scored / warm_dt, 2) if warm_dt else 0.0,
        "unit": "requests/s",
        "serve_requests_per_sec": (
            round(scored / warm_dt, 2) if warm_dt else 0.0
        ),
        "serve_cold_requests_per_sec": (
            round(scored / cold_dt, 2) if cold_dt else 0.0
        ),
        "serve_latency_p50_ms": pct_ms(0.50),
        "serve_latency_p99_ms": pct_ms(0.99),
        "serve_batch_occupancy_mean": round(
            obs_metrics.REGISTRY.snapshot().get(
                "serve/batch_occupancy/mean", 0.0
            ), 4,
        ),
        "serve_scored": scored,
        "serve_warmup_seconds": round(warmup_seconds, 3),
        "serve_warmed_signatures": len(warm_report),
        "serve_jit_lowerings": executor.jit_lowerings(),
        "serve_steady_state_recompiles": (
            executor.jit_lowerings() - lowerings0
        ),
        "serve_instrumented_requests_per_sec": round(inst_rps, 2),
        "serve_obs_overhead_fraction": round(
            max(0.0, 1.0 - inst_rps / plain_rps), 4
        ) if plain_rps else None,
        "serve_obs_overhead_reps": reps,
        "serve_pipeline_depth": pipeline_depth,
        "serve_serial_req_per_sec": round(serial_rps, 2),
        "serve_pipeline_req_per_sec": round(pipe_rps, 2),
        "serve_pipeline_speedup": (
            round(pipe_rps / serial_rps, 4) if serial_rps else None
        ),
        "serve_device_idle_fraction": idle_frac,
        "n_examples": n,
        "max_batch_graphs": max_batch,
        "smoke": smoke,
    }


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--examples", type=int, default=256)
    ap.add_argument("--max-batch", type=int, default=8)
    ap.add_argument("--out", default=None)
    ap.add_argument(
        "--smoke", action="store_true",
        help="tier-1 regression mode: tiny corpus/model, asserts the "
        "zero-recompile serving contract",
    )
    args = ap.parse_args()

    from deepdfa_tpu.core.backend import apply_platform_override

    os.environ.setdefault("DEEPDFA_TPU_PLATFORM", "cpu")
    apply_platform_override()

    record = bench_serve(
        args.examples, smoke=args.smoke, max_batch=args.max_batch
    )
    from deepdfa_tpu.obs import run_stamp

    record.update(run_stamp())
    print(json.dumps(record), flush=True)
    if args.out:
        Path(args.out).write_text(json.dumps(record, indent=1))
    if args.smoke and record["serve_steady_state_recompiles"]:
        raise SystemExit(
            f"{record['serve_steady_state_recompiles']} steady-state "
            f"recompiles in smoke mode (expected 0)"
        )
    if args.smoke and record["serve_pipeline_speedup"] is not None:
        # accelerator platforms must show the overlap paying (device
        # compute runs on separate silicon, so pipelined >= serial);
        # on CPU host and "device" share the same cores — a single-core
        # box physically cannot overlap, so the floor is a near-tie
        # sanity bound there (full runs gate drift via bench_gate's
        # serve_pipeline_req_per_sec tolerance row either way)
        import jax

        floor = 1.0 if jax.default_backend() != "cpu" else 0.8
        if record["serve_pipeline_speedup"] < floor:
            raise SystemExit(
                f"pipelined drive at "
                f"{record['serve_pipeline_speedup']:.2f}x serial "
                f"req/s in smoke mode (floor {floor}x on "
                f"{jax.default_backend()})"
            )


if __name__ == "__main__":
    main()
