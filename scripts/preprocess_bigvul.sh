#!/usr/bin/env bash
# Big-Vul preprocessing (the reference's preprocess.sh pipeline):
#   prepare -> extract-vocab -> extract (optionally sharded over a cluster)
# Usage: preprocess_bigvul.sh /path/to/MSR_data_cleaned.csv [num_shards]
set -euo pipefail
cd "$(dirname "$0")/.."

CSV="${1:?usage: preprocess_bigvul.sh MSR_data_cleaned.csv [num_shards]}"
NUM_SHARDS="${2:-1}"

python -m deepdfa_tpu.cli prepare --source "$CSV" --dep-closure
python -m deepdfa_tpu.cli extract-vocab --workers "$(nproc)"

if [ "$NUM_SHARDS" -gt 1 ]; then
  # job-array style: run each shard (under SLURM, replace the loop with
  # --shard "$SLURM_ARRAY_TASK_ID")
  for s in $(seq 0 $((NUM_SHARDS - 1))); do
    python -m deepdfa_tpu.cli extract --workers "$(nproc)" \
        --shard "$s" --num-shards "$NUM_SHARDS"
  done
else
  python -m deepdfa_tpu.cli extract --workers "$(nproc)"
fi
