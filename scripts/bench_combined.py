#!/usr/bin/env python
"""Combined-model (codebert-scale, ~125M params) training benchmark + MFU.

The reference's headline transformer cost is LineVul fine-tuning:
10h19m for 10 epochs over the Big-Vul train split at bs 16 / 512 tokens
(paper Table 5; ~150k rows/epoch -> ~40 examples/s) with 48.32B MACs per
example. This measures the equivalent here: the combined
RoBERTa(768x12)+GGNN training step (forward + backward + AdamW) over
512-token rows with aligned graph batches, median steady-state window,
FLOPs/example + model FLOP/s + MFU from XLA's compiled-HLO cost
analysis — the utilization number VERDICT r2 asked for on the 125M
model, not just the 25k-param GGNN.

    python scripts/bench_combined.py                 # default backend
    DEEPDFA_TPU_PLATFORM=cpu python scripts/bench_combined.py --tiny

On CPU --tiny shrinks the encoder so the harness itself stays testable;
the full-size run needs the TPU chip.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# paper Table 5: 10 epochs x ~150k-row epochs in 10h19m on an RTX 3090
BASELINE_EXAMPLES_PER_SEC = 40.0

_PEAK_FLOPS = {
    ("tpu", "bfloat16"): 1.97e14,
    ("tpu", "float32"): 9.85e13,
}


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--rows", type=int, default=64, help="rows per batch")
    ap.add_argument("--seq", type=int, default=512)
    ap.add_argument("--reps", type=int, default=6)
    ap.add_argument("--tiny", action="store_true",
                    help="tiny encoder (harness validation on CPU)")
    ap.add_argument("--dtype", default=None, choices=["float32", "bfloat16"],
                    help="activation compute dtype (default: bfloat16 on "
                    "TPU — the native training dtype — else float32)")
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    from deepdfa_tpu.core.backend import (
        apply_platform_override,
        enable_compile_cache,
    )

    apply_platform_override()
    enable_compile_cache()
    import jax
    import numpy as np

    from deepdfa_tpu.core import Config, config as config_mod
    from deepdfa_tpu.data import build_dataset, generate, to_examples
    from deepdfa_tpu.data.text import collate_shards
    from deepdfa_tpu.data.tokenizer import HashTokenizer
    from deepdfa_tpu.eval.profiling import compiled_cost
    from deepdfa_tpu.models import combined as cmb
    from deepdfa_tpu.models.transformer import TransformerConfig
    from deepdfa_tpu.train.combined_loop import CombinedTrainer

    import dataclasses

    platform = jax.devices()[0].platform
    dtype = args.dtype or ("bfloat16" if platform != "cpu" else "float32")
    if args.tiny:
        enc = TransformerConfig.tiny(
            vocab_size=512, max_position_embeddings=args.seq + 4
        )
    else:
        # codebert-base geometry (the reference's checkpoint):
        # 12 x 768, 12 heads, 3072 FFN, 50k vocab -> ~125M params
        enc = TransformerConfig(
            vocab_size=50265, max_position_embeddings=args.seq + 2
        )
    enc = dataclasses.replace(enc, dtype=dtype)
    mcfg = cmb.CombinedConfig(encoder=enc, graph_input_dim=1002)
    cfg = Config()

    n = args.rows
    synth = generate(n, vuln_rate=0.06, seed=7)
    specs, _ = build_dataset(
        to_examples(synth), train_ids=range(n), limit_all=1000,
        limit_subkeys=1000,
    )
    by_id = {s.graph_id: s for s in specs}
    tok = HashTokenizer(vocab_size=enc.vocab_size)
    token_ids = tok.batch_encode([s.before for s in synth], max_length=args.seq)
    batch = collate_shards(
        token_ids, [s.label for s in synth], list(range(n)), by_id,
        num_shards=1, rows_per_shard=n, node_budget=4096, edge_budget=16384,
    )

    trainer = CombinedTrainer(cfg, mcfg)
    state = trainer.init_state(seed=0)
    key = jax.random.key(0)

    t0 = time.perf_counter()
    state, warm_loss = trainer.train_step(state, batch, key)  # compile+warmup
    float(warm_loss)  # fetch-bounded: compile_s must cover real completion
    compile_s = time.perf_counter() - t0

    # each rep times a WINDOW of chained steps with ONE host fetch at the
    # end: the state dependency chains step r+1 on step r, so the final
    # loss arriving on host transitively proves every step executed.
    # A host FETCH (not block_until_ready) is load-bearing: the
    # remote-TPU tunnel can report a buffer ready before execution
    # completes (observed as an impossible MFU 3.64 in the first
    # BENCH_TPU capture); fetching once per window keeps the tunnel
    # round-trip amortized instead of serialized into every step.
    steps_per_window = max(1, int(os.environ.get("DEEPDFA_BENCH_WINDOW", 4)))
    rates = []
    r = 0
    for _ in range(args.reps):
        t0 = time.perf_counter()
        loss = None
        for _ in range(steps_per_window):
            state, loss = trainer.train_step(
                state, batch, jax.random.fold_in(key, r)
            )
            r += 1
        float(loss)
        rates.append(n * steps_per_window / (time.perf_counter() - t0))
    value = float(np.median(rates))

    result = {
        "metric": "combined_train_examples_per_sec",
        "value": round(value, 2),
        "unit": "examples/s",
        "vs_baseline": round(value / BASELINE_EXAMPLES_PER_SEC, 2),
        "best_examples_per_sec": round(max(rates), 2),
        "platform": platform,
        "rows": n,
        "seq": args.seq,
        "encoder": "tiny" if args.tiny else "codebert-base(12x768)",
        "dtype": dtype,
        "compile_seconds": round(compile_s, 1),
        "n_params": int(
            sum(np.prod(x.shape) for x in jax.tree.leaves(state.params))
        ),
    }
    try:
        flops = compiled_cost(
            lambda s, b: trainer.train_step(s, b, key), state, batch
        )["flops"]
        if flops <= 0:
            raise RuntimeError("XLA cost analysis returned no flops")
        per_ex = flops / n
        model_fps = per_ex * value
        # MFU vs the peak of the ACTUAL compute dtype (bf16 and f32 run
        # the MXU at different rates)
        peak = _PEAK_FLOPS.get((platform, dtype))
        result.update(
            {
                "flops_per_example": round(per_ex, 1),
                "model_flops_per_sec": round(model_fps, 1),
                "mfu": round(model_fps / peak, 6) if peak else None,
            }
        )
    except Exception as e:
        result["mfu_error"] = f"{type(e).__name__}: {e}"[:200]
    if platform == "tpu":
        # measured dense-matmul ceiling sample (eval/profiling.py);
        # outside the mfu try-block so a probe failure can never be
        # mislabeled as an MFU failure
        from deepdfa_tpu.eval.profiling import ceiling_fields

        result.update(
            ceiling_fields(result.get("model_flops_per_sec", 0.0))
        )

    print(json.dumps(result), flush=True)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(result, f, indent=1)


if __name__ == "__main__":
    main()
