#!/usr/bin/env python
"""Combined-model (codebert-scale, ~125M params) training benchmark + MFU.

The reference's headline transformer cost is LineVul fine-tuning:
10h19m for 10 epochs over the Big-Vul train split at bs 16 / 512 tokens
(paper Table 5; ~150k rows/epoch -> ~40 examples/s) with 48.32B MACs per
example. This measures the equivalent here: the combined
RoBERTa(768x12)+GGNN training step (forward + backward + AdamW) over
512-token rows with aligned graph batches, median steady-state window,
FLOPs/example + model FLOP/s + MFU from XLA's compiled-HLO cost
analysis — the utilization number VERDICT r2 asked for on the 125M
model, not just the 25k-param GGNN.

On TPU this is also a (lowering x remat-policy x batch-rows) sweep: the
XLA einsum path anchors at rows=64 (its rows=256 scaling was measured
flat pre-flash, docs/bench_history.json "batch_scaling_note"), and the
fused Pallas flash kernel (nn/flash_attention.py) — same recipe
otherwise (bf16, attention-probs dropout 0.1) — gets the larger-rows
slots its removal of the [B,H,T,T] HBM temps makes reachable. The
headline is the best faithful variant; every variant's number records
its own rows, so a cross-rows comparison is explicit in the artifact,
and a like-for-like xla-vs-flash read should compare equal-rows
variants (or the forced --attn runs).
Before flash is benched, a PRNG self-check pins in-kernel dropout
determinism and keep-fraction on the real chip (the CPU interpreter
can't: its prng_random_bits returns zeros — tests/test_flash_attention.py
covers the math via injected bits instead).

    python scripts/bench_combined.py                 # default backend
    DEEPDFA_TPU_PLATFORM=cpu python scripts/bench_combined.py --tiny

On CPU --tiny shrinks the encoder so the harness itself stays testable;
the full-size run needs the TPU chip. --attn forces one lowering
(default: A/B on TPU, xla on CPU).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# paper Table 5: 10 epochs x ~150k-row epochs in 10h19m on an RTX 3090
BASELINE_EXAMPLES_PER_SEC = 40.0

_PEAK_FLOPS = {
    ("tpu", "bfloat16"): 1.97e14,
    ("tpu", "float32"): 9.85e13,
}


def _flash_selfcheck() -> dict:
    """In-kernel PRNG dropout sanity on the real chip: determinism per
    seed, seed sensitivity, keep fraction. Cheap (one tiny kernel)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from deepdfa_tpu.nn.flash_attention import flash_attention

    q0 = jnp.zeros((1, 1, 512, 64), jnp.bfloat16)
    ones = jnp.ones_like(q0)
    m0 = jnp.ones((1, 512), bool)

    def run(rate, seed):
        return np.asarray(
            jax.jit(
                lambda: flash_attention(
                    q0, q0, ones, m0, dropout_rate=rate,
                    seed=jnp.array([seed], jnp.int32))
            )()
        ).astype(np.float64)

    a, b, c = run(0.1, 7), run(0.1, 7), run(0.1, 8)
    # with q=k=0 every prob is 1/T, so out = keep_count/(T*keep_prob):
    # the mean recovers the empirical keep fraction exactly
    keep_frac = float(a.mean() * 0.9)
    return {
        "deterministic": bool((a == b).all()),
        "seed_sensitive": bool((a != c).any()),
        "keep_fraction_at_rate_0.1": round(keep_frac, 4),
        "ok": bool((a == b).all() and (a != c).any()
                   and abs(keep_frac - 0.9) < 0.02),
    }


def _measure(args, enc, label: str, rows: int | None = None) -> dict:
    """Build the combined trainer for one encoder config and time it."""
    import jax
    import numpy as np

    from deepdfa_tpu.eval.profiling import compiled_cost

    platform = jax.devices()[0].platform
    n = rows or args.rows
    from _combined_batch import build_trainer_and_batch

    trainer, state, batch = build_trainer_and_batch(
        enc, args.arch, n, args.seq)
    key = jax.random.key(0)

    t0 = time.perf_counter()
    state, warm_loss = trainer.train_step(state, batch, key)  # compile+warmup
    float(warm_loss)  # fetch-bounded: compile_s must cover real completion
    compile_s = time.perf_counter() - t0

    # each rep times a WINDOW of chained steps with ONE host fetch at the
    # end: the state dependency chains step r+1 on step r, so the final
    # loss arriving on host transitively proves every step executed.
    # A host FETCH (not block_until_ready) is load-bearing: the
    # remote-TPU tunnel can report a buffer ready before execution
    # completes (observed as an impossible MFU 3.64 in the first
    # BENCH_TPU capture); fetching once per window keeps the tunnel
    # round-trip amortized instead of serialized into every step.
    steps_per_window = max(1, int(os.environ.get("DEEPDFA_BENCH_WINDOW", 4)))
    rates = []
    r = 0
    for _ in range(args.reps):
        t0 = time.perf_counter()
        loss = None
        for _ in range(steps_per_window):
            state, loss = trainer.train_step(
                state, batch, jax.random.fold_in(key, r)
            )
            r += 1
        float(loss)
        rates.append(n * steps_per_window / (time.perf_counter() - t0))
    value = float(np.median(rates))

    # real-token observables (ISSUE 2): tokens/sec counts only non-pad
    # tokens in valid rows, so it stays comparable across pad targets;
    # padding_waste is the fraction of computed token slots holding pad
    from deepdfa_tpu.data.text import batch_token_counts

    real, padded, _ = batch_token_counts(
        batch.input_ids, batch.row_mask, enc.pad_token_id
    )
    result = {
        "attn_impl": label,
        "remat": enc.remat,
        "remat_policy": getattr(enc, "remat_policy", "full"),
        "rows": n,
        "value": round(value, 2),
        "vs_baseline": round(value / BASELINE_EXAMPLES_PER_SEC, 2),
        "best_examples_per_sec": round(max(rates), 2),
        "tokens_per_sec": round(value * real / n, 1),
        "padding_waste": round(1.0 - real / padded, 4) if padded else None,
        "compile_seconds": round(compile_s, 1),
        "n_params": int(
            sum(np.prod(x.shape) for x in jax.tree.leaves(state.params))
        ),
    }
    try:
        flops = compiled_cost(
            lambda s, b: trainer.train_step(s, b, key), state, batch
        )["flops"]
        if flops <= 0:
            raise RuntimeError("XLA cost analysis returned no flops")
        if label == "flash":
            # cost analysis cannot see inside pallas kernels: add the
            # attention matmul FLOPs analytically. Per layer+head+example,
            # in units of one [T,T]x[T,Dh]-class matmul (2*T^2*Dh flops):
            # fwd kernel 2 (QK^T, PV), dq 3 (S, dP, dS@K), dkv 4
            # (S, dP, dV, dK), plus a second fwd under remat. Recorded
            # so the adjustment is auditable.
            # the second fwd-kernel run exists only under FULL-layer
            # remat; the attn_saved policy reuses the named outputs
            full_remat = (enc.remat
                          and getattr(enc, "remat_policy", "full") == "full")
            units = 9 + (2 if full_remat else 0)
            if args.arch == "t5":
                units += 2  # dbias kernel: S and dP recomputes
            add = (enc.num_layers * enc.num_heads * units
                   * 2 * args.seq**2 * enc.head_dim)
            flops += add * n
            result["pallas_flops_added_per_example"] = float(add)
        per_ex = flops / n
        model_fps = per_ex * value
        peak = _PEAK_FLOPS.get((platform, enc.dtype))
        result.update(
            {
                "flops_per_example": round(per_ex, 1),
                "model_flops_per_sec": round(model_fps, 1),
                "mfu": round(model_fps / peak, 6) if peak else None,
            }
        )
    except Exception as e:
        result["mfu_error"] = f"{type(e).__name__}: {e}"[:200]
    return result


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--rows", type=int, default=64, help="rows per batch")
    ap.add_argument("--seq", type=int, default=512)
    ap.add_argument("--reps", type=int, default=6)
    ap.add_argument("--tiny", action="store_true",
                    help="tiny encoder (harness validation on CPU)")
    ap.add_argument("--dtype", default=None, choices=["float32", "bfloat16"],
                    help="activation compute dtype (default: bfloat16 on "
                    "TPU — the native training dtype — else float32)")
    ap.add_argument("--attn", default=None,
                    choices=["auto", "xla", "flash"],
                    help="force one attention lowering instead of the "
                    "TPU A/B sweep")
    ap.add_argument("--remat-policy", default="full",
                    choices=["full", "attn_saved"],
                    help="remat granularity for a forced --attn run "
                    "(the sweep covers both; this makes the winning "
                    "variant reproducible in isolation)")
    ap.add_argument("--arch", default="roberta", choices=["roberta", "t5"],
                    help="combined architecture: roberta (LineVul-style, "
                    "codebert geometry) or t5 (CodeT5-style defect model, "
                    "relative-bias flash operand)")
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    from deepdfa_tpu.core.backend import (
        apply_platform_override,
        enable_compile_cache,
    )

    apply_platform_override()
    enable_compile_cache()
    import dataclasses

    import jax

    platform = jax.devices()[0].platform
    dtype = args.dtype or ("bfloat16" if platform != "cpu" else "float32")
    if args.arch == "t5":
        from deepdfa_tpu.models.t5 import T5Config

        # codet5-base geometry (12 x 768, 12 heads, 64 head dim, 32k vocab)
        enc = T5Config.tiny(vocab_size=512) if args.tiny else T5Config()
    else:
        from deepdfa_tpu.models.transformer import TransformerConfig

        if args.tiny:
            enc = TransformerConfig.tiny(
                vocab_size=512, max_position_embeddings=args.seq + 4
            )
        else:
            # codebert-base geometry (the reference's checkpoint):
            # 12 x 768, 12 heads, 3072 FFN, 50k vocab -> ~125M params
            enc = TransformerConfig(
                vocab_size=50265, max_position_embeddings=args.seq + 2
            )
    enc = dataclasses.replace(enc, dtype=dtype)

    # which lowerings to measure: explicit --attn wins; otherwise a
    # (lowering x remat-policy x ROWS) sweep on TPU, single xla run
    # elsewhere (the pallas kernel does not lower on CPU). Rows is a
    # real lever, not a nuisance dimension: at rows=64 the flash step
    # is short enough that per-step overheads (optimizer, GGNN bridge,
    # tunnel dispatch) eat the kernel's win, and the XLA path's own
    # rows=256 scaling note ("same ex/s") predates flash — with the
    # [B,H,T,T] HBM temps gone, larger batches amortize differently.
    # flash+no-remat is known-OOM at rows>=64 w/ full activations
    # (24G > 16G, docs/attn_ab_tpu.json) but attn_saved keeps only the
    # kernel's named outputs, so it gets the big-rows slots.
    selfcheck = None
    if args.attn in ("xla", "flash"):
        plans = [(args.attn, enc.remat, args.remat_policy, args.rows)]
    elif platform == "tpu" and not args.tiny:
        plans = [("xla", True, "full", 64),
                 ("flash", True, "full", 128),
                 ("flash", True, "attn_saved", 128),
                 ("flash", True, "attn_saved", 256),
                 ("flash", True, "full", 256)]
        if args.arch == "t5":
            # the t5 capture runs under a tighter watchdog budget and
            # has no baseline row of its own: keep the grid to the
            # proven shapes so a timeout can't void the whole capture
            plans = plans[:3]
    else:
        plans = [("xla", enc.remat, "full", args.rows)]

    variants = []
    for impl, remat, policy, rows in plans:
        if impl == "flash":
            if selfcheck is None:
                try:
                    selfcheck = _flash_selfcheck()
                except Exception as e:
                    selfcheck = {"ok": False,
                                 "error": f"{type(e).__name__}: {e}"[:200]}
            if not selfcheck["ok"]:
                continue  # never bench a kernel whose RNG failed checks
        ec = dataclasses.replace(enc, attn_impl=impl, remat=remat)
        if policy != "full":
            ec = dataclasses.replace(ec, remat_policy=policy)
        try:
            variants.append(_measure(args, ec, impl, rows))
        except Exception as e:  # noqa: BLE001 — every variant must land
            # keep the diagnostic lines (OOM totals, mosaic errors) that
            # a blind prefix-truncation would drop — the variants list is
            # the auditable record of WHY a configuration lost
            detail = [ln.strip() for ln in str(e).splitlines()
                      if any(w in ln.lower() for w in
                             ("hbm", "memory", "oom", "exceed", "mosaic",
                              "error:"))][:8]
            variants.append({
                "attn_impl": impl, "remat": remat, "remat_policy": policy,
                "rows": rows,
                "error": f"{type(e).__name__}: {e}"[:300],
                "error_detail": detail,
            })
        if args.out:
            # incremental checkpoint: a watchdog-budget kill mid-sweep
            # (the window can close at any moment) keeps every variant
            # measured so far instead of voiding the capture
            with open(args.out, "w") as f:
                json.dump({"metric": "combined_train_examples_per_sec",
                           "partial": True, "arch": args.arch,
                           "platform": platform, "variants": variants}, f,
                          indent=1)

    scored = [v for v in variants if "value" in v]
    if not scored:
        print(json.dumps({"metric": "combined_train_examples_per_sec",
                          "error": "no variant completed",
                          "variants": variants}), flush=True)
        raise SystemExit(1)
    best = max(scored, key=lambda v: v["value"])

    result = {
        "metric": "combined_train_examples_per_sec",
        "unit": "examples/s",
        "platform": platform,
        "rows": args.rows,
        "seq": args.seq,
        "arch": args.arch,
        "encoder": ("tiny" if args.tiny else
                    "codet5-base(12x768)" if args.arch == "t5" else
                    "codebert-base(12x768)"),
        "dtype": dtype,
        **{k: v for k, v in best.items() if k != "remat"},
        "remat": best["remat"],
    }
    if len(variants) > 1:
        result["variants"] = variants
    if selfcheck is not None:
        result["flash_selfcheck"] = selfcheck
    if platform == "tpu":
        # measured dense-matmul ceiling sample (eval/profiling.py);
        # outside the mfu try-block so a probe failure can never be
        # mislabeled as an MFU failure
        from deepdfa_tpu.eval.profiling import ceiling_fields

        result.update(
            ceiling_fields(result.get("model_flops_per_sec", 0.0))
        )

    from deepdfa_tpu.obs import run_stamp

    result.update(run_stamp())
    print(json.dumps(result), flush=True)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(result, f, indent=1)


if __name__ == "__main__":
    main()
