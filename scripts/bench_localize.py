#!/usr/bin/env python
"""Combined-model INFERENCE + line-localization timing benchmark.

The perf story so far covers training (bench_combined.py) and the GGNN
(bench.py); this closes the remaining Table 5 row: DeepDFA+LineVul
*inference* at 15.4 ms/example on the reference's RTX 3090
(`/root/reference/paper.pdf` Table 5; BASELINE.md "Efficiency") =
64.9 examples/s, measured there with CUDA events around the forward
(reference `LineVul/linevul/linevul_main.py` eval loop +
`code_gnn/models/base_module.py:238-291` profiling hooks).

Here: the jitted combined RoBERTa(768x12)+GGNN forward over 512-token
rows with aligned graph batches, bf16 on TPU, fetch-bounded windows
(every timed window ends in a device->host copy — the tunnel can report
buffers ready early, docs/bench_history.json "timing_audit").

Alongside it, the localization methods (eval/localize.py — the
reference's linevul_main.py --do_local_explanation path with its
attention / Saliency / IG / LIG / DeepLift captum attributions) are
timed per-example so the explanation cost is on the record too:
attention (forward-only, encoder attention maps), saliency (one
gradient), integrated_gradients (n_steps gradient evaluations).

    python scripts/bench_localize.py                    # default backend
    DEEPDFA_TPU_PLATFORM=cpu python scripts/bench_localize.py --tiny
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# paper Table 5: DeepDFA+LineVul inference 15.4 ms/example on RTX 3090
BASELINE_MS_PER_EXAMPLE = 15.4
BASELINE_EXAMPLES_PER_SEC = 1000.0 / BASELINE_MS_PER_EXAMPLE


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--rows", type=int, default=64)
    ap.add_argument("--seq", type=int, default=512)
    ap.add_argument("--reps", type=int, default=6)
    ap.add_argument("--tiny", action="store_true",
                    help="tiny encoder (harness validation on CPU)")
    ap.add_argument("--methods", default="attention,saliency,lig",
                    help="comma list of localization methods to time")
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    from deepdfa_tpu.core.backend import (
        apply_platform_override,
        enable_compile_cache,
    )

    apply_platform_override()
    enable_compile_cache()
    import dataclasses

    import jax
    import jax.numpy as jnp
    import numpy as np

    from deepdfa_tpu.models.transformer import TransformerConfig

    platform = jax.devices()[0].platform
    dtype = "bfloat16" if platform != "cpu" else "float32"
    if args.tiny:
        enc = TransformerConfig.tiny(
            vocab_size=512, max_position_embeddings=args.seq + 4
        )
    else:
        enc = TransformerConfig(
            vocab_size=50265, max_position_embeddings=args.seq + 2
        )
    enc = dataclasses.replace(enc, dtype=dtype)

    from _combined_batch import build_trainer_and_batch

    trainer, state, batch = build_trainer_and_batch(
        enc, "roberta", args.rows, args.seq)
    mcfg = trainer.model_cfg
    params = state.params
    # drop the leading dp-shard axis (num_shards=1) for the plain forward
    input_ids = batch.input_ids[0]
    has_graph = batch.has_graph[0]
    graphs = jax.tree.map(lambda x: x[0], batch.graphs)

    from deepdfa_tpu.models import combined as cmb

    @jax.jit
    def infer(params, input_ids, graphs, has_graph):
        return jax.nn.softmax(
            cmb.forward(mcfg, params, input_ids, graphs, has_graph),
            axis=-1,
        )

    np.asarray(infer(params, input_ids, graphs, has_graph))  # compile+warm

    rates = []
    for _ in range(args.reps):
        t0 = time.perf_counter()
        out = infer(params, input_ids, graphs, has_graph)
        np.asarray(out)  # fetch-bounded window
        rates.append(args.rows / (time.perf_counter() - t0))
    value = float(np.median(rates))

    result = {
        "metric": "combined_infer_examples_per_sec",
        "value": round(value, 2),
        "unit": "examples/s",
        "vs_baseline": round(value / BASELINE_EXAMPLES_PER_SEC, 2),
        "baseline_ms_per_example": BASELINE_MS_PER_EXAMPLE,
        "ms_per_example": round(1000.0 / value, 3),
        "best_examples_per_sec": round(max(rates), 2),
        "platform": platform,
        "rows": args.rows,
        "seq": args.seq,
        "encoder": "tiny" if args.tiny else "codebert-base(12x768)",
        "dtype": dtype,
    }

    # localization methods: time token_scores end-to-end (it returns
    # numpy, so the fetch bound is built in). First call compiles; the
    # timed calls replay the jit cache — matching how eval/localize.py
    # is used over a dataset (one compile, thousands of rows).
    from deepdfa_tpu.eval.localize import token_scores

    loc = {}
    for method in [m.strip() for m in args.methods.split(",") if m.strip()]:
        try:
            token_scores(method, "roberta", mcfg, params, input_ids,
                         graphs, has_graph)  # compile+warm
            t0 = time.perf_counter()
            token_scores(method, "roberta", mcfg, params, input_ids,
                         graphs, has_graph)
            dt = time.perf_counter() - t0
            loc[method] = {
                "ms_per_example": round(1000.0 * dt / args.rows, 3),
                "examples_per_sec": round(args.rows / dt, 2),
            }
        except Exception as e:  # one broken method must not void the rest
            loc[method] = {"error": f"{type(e).__name__}: {e}"[:300]}
    result["localization"] = loc

    if platform == "tpu":
        from deepdfa_tpu.eval.profiling import ceiling_fields

        result.update(ceiling_fields(0.0))
        result.pop("mfu_vs_measured_ceiling", None)

    from deepdfa_tpu.obs import run_stamp

    result.update(run_stamp())
    print(json.dumps(result), flush=True)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(result, f, indent=1)


if __name__ == "__main__":
    main()
