#!/usr/bin/env python
"""Seq2seq (CodeT5 run_gen-style) training-step benchmark, xla vs flash.

The defect-path benches (bench_combined.py) cover the encoders; this
measures the teacher-forced encoder+decoder step the generation
trainers run (train/gen_loop.py) — the workload the decoder extensions
of the flash kernel (causal self-attention with dead-block skipping,
rectangular cross-attention) exist for. codet5-base geometry, 256
source / 128 target tokens (the CONCODE/summarize class of shapes).
No reference baseline exists for this step in BASELINE.md (the paper
reports defect-path costs only), so the record carries absolute ex/s
plus the A/B delta rather than a vs_baseline field.

    python scripts/bench_gen.py [--attn auto|xla|flash] [--out ...]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _measure(args, ecfg) -> dict:
    """Time the REAL GenTrainer step (train/gen_loop.py) — the published
    number must be the trainer users run, not a reconstruction."""
    import jax
    import numpy as np

    from deepdfa_tpu.core import Config
    from deepdfa_tpu.data import gen_data
    from deepdfa_tpu.models import t5_gen as t5g
    from deepdfa_tpu.train.gen_loop import GenTrainer

    gcfg = t5g.GenConfig(encoder=ecfg, max_target_length=args.tgt)
    rng = np.random.default_rng(0)
    src = rng.integers(3, ecfg.vocab_size - 1, (args.rows, args.src))
    tgt = rng.integers(3, ecfg.vocab_size - 1, (args.rows, args.tgt))
    batch = gen_data.batches_of(
        src.astype(np.int32), tgt.astype(np.int32),
        num_shards=1, rows_per_shard=args.rows)[0]

    trainer = GenTrainer(Config(), gcfg)
    state = trainer.init_state(seed=0)
    key = jax.random.key(0)

    t0 = time.perf_counter()
    state, loss = trainer.train_step(state, batch, key)
    float(loss)  # fetch-bounded (tunnel: block_until_ready can lie)
    compile_s = time.perf_counter() - t0

    window = max(1, int(os.environ.get("DEEPDFA_BENCH_WINDOW", 4)))
    rates = []
    r = 0
    for _ in range(args.reps):
        t0 = time.perf_counter()
        loss = None
        for _ in range(window):
            state, loss = trainer.train_step(
                state, batch, jax.random.fold_in(key, r))
            r += 1
        float(loss)
        rates.append(args.rows * window / (time.perf_counter() - t0))

    return {
        "attn_impl": ecfg.attn_impl,
        "value": round(float(np.median(rates)), 2),
        "best_examples_per_sec": round(max(rates), 2),
        "compile_seconds": round(compile_s, 1),
    }


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--rows", type=int, default=32)
    ap.add_argument("--src", type=int, default=256)
    ap.add_argument("--tgt", type=int, default=128)
    ap.add_argument("--reps", type=int, default=5)
    ap.add_argument("--tiny", action="store_true")
    ap.add_argument("--attn", default=None, choices=["auto", "xla", "flash"])
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    from deepdfa_tpu.core.backend import (
        apply_platform_override,
        enable_compile_cache,
    )

    apply_platform_override()
    enable_compile_cache()
    import dataclasses

    import jax

    from deepdfa_tpu.models.t5 import T5Config

    platform = jax.devices()[0].platform
    enc = T5Config.tiny(vocab_size=512) if args.tiny else T5Config()
    enc = dataclasses.replace(
        enc, dtype="bfloat16" if platform == "tpu" else "float32")

    if args.attn:
        plans = [args.attn]
    elif platform == "tpu" and not args.tiny:
        plans = ["xla", "flash"]
    else:
        plans = ["xla"]

    variants = []
    for impl in plans:
        try:
            variants.append(
                _measure(args, dataclasses.replace(enc, attn_impl=impl)))
        except Exception as e:
            variants.append({"attn_impl": impl,
                             "error": f"{type(e).__name__}: {e}"[:300]})

    scored = [v for v in variants if "value" in v]
    if not scored:
        print(json.dumps({"metric": "gen_train_examples_per_sec",
                          "error": "no variant completed",
                          "variants": variants}), flush=True)
        raise SystemExit(1)
    best = max(scored, key=lambda v: v["value"])
    result = {
        "metric": "gen_train_examples_per_sec",
        "unit": "examples/s",
        "platform": platform,
        "rows": args.rows,
        "src": args.src,
        "tgt": args.tgt,
        "encoder": "tiny" if args.tiny else "codet5-base(12x768)",
        "dtype": enc.dtype,
        **best,
    }
    if len(variants) > 1:
        result["variants"] = variants
    from deepdfa_tpu.obs import run_stamp

    result.update(run_stamp())
    print(json.dumps(result), flush=True)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(result, f, indent=1)


if __name__ == "__main__":
    main()
