#!/usr/bin/env python
"""Cascade accuracy-vs-device-time frontier benchmark (docs/cascade.md).

The ISSUE-12 acceptance drive: on one synthetic labeled dev set, serve
the SAME requests two ways and measure the frontier —

  combined-only   every request through the combined transformer
                  executor (the expensive family, fp32)
  cascade         every request through the trained GGNN screen; only
                  the calibrated uncertainty band escalates to the
                  combined executor, restored as its QUANTIZED
                  `best@int8` registry entry

and report:

  cascade_req_per_sec            end-to-end cascade throughput (warm)
  cascade_combined_req_per_sec   combined-only throughput (warm)
  cascade_speedup                ratio (the frontier headline: >1 means
                                 the cascade serves more requests per
                                 device-second)
  cascade_escalation_rate        fraction escalated at the FITTED band
                                 (eval/calibrate.py temperature + band
                                 from the dev set itself — the
                                 calibration recipe end to end)
  cascade_score_drift            max(0, combined AUC - cascade AUC):
                                 one-sided accuracy drift vs the
                                 combined-only baseline (bounded
                                 absolutely in obs/bench_gate.py)
  quant_param_bytes_fraction     the @int8 stage-2 entry's param bytes
                                 over its fp32 twin (the HBM ledger's
                                 density win)
  cascade_steady_state_recompiles  across BOTH family ladders

Unlike bench_serve this trains the stage-1 GGNN (a few tiny epochs via
the serve smoke builder) — the drift metric needs a screen that actually
ranks, not random weights — and restores both stages through the REAL
ModelRegistry, so the quantized-restore drift contract rides the bench.

Modes:
    python scripts/bench_cascade.py --smoke   # tier-1 regression mode
    python scripts/bench_cascade.py           # bigger corpus
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from pathlib import Path

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def bench_cascade(n_examples: int = 48, smoke: bool = False) -> dict:
    from deepdfa_tpu.core import config as config_mod
    from deepdfa_tpu.data import generate, to_examples
    from deepdfa_tpu.eval import calibrate as calibrate_mod
    from deepdfa_tpu.serve import cascade as cascade_mod, driver
    from deepdfa_tpu.serve.registry import ModelRegistry
    from deepdfa_tpu.serve.server import ScoringService, score_texts

    n = min(int(n_examples), 48) if smoke else int(n_examples)
    cfg, run_dir, _sources = driver.build_smoke_run(
        run_name="bench-cascade", dataset="bench-cascade",
        n_examples=n, max_epochs=10, seed=0,
        # balanced labels: AUC over the dataset's ~6% positive rate is
        # noise at bench sizes
        vuln_rate=0.5,
        extra_overrides=[
            # a screen worth trusting: big enough to rank the synthetic
            # corpus (tiny next to stage 2 either way)
            "model.hidden_dim=32",
            "serve.max_batch_graphs=16",
            # stage-2 batch rows: token_budget / max_length
            "data.token_budget=2048",
        ],
    )
    # the labeled dev set: same generator/seed the smoke builder wrote
    # the source files from, so names join back to labels
    examples = to_examples(generate(n, vuln_rate=0.5, seed=0))
    labels = {f"fn_{e.id:04d}": int(e.label or 0) for e in examples}
    texts = [(f"fn_{e.id:04d}", e.code) for e in examples]

    # stage 2: a TRAINED combined transformer sized so escalation cost
    # dominates the GGNN screen (the regime the cascade exists for)
    cascade_mod.train_stage2_smoke(
        run_dir, cfg, n_examples=n, vuln_rate=0.5, seed=0,
        hidden=48 if smoke else 64, layers=3, heads=4,
        max_length=128, vocab_size=512,
        max_epochs=8 if smoke else 10,
    )

    def matched_auc(rows) -> float | None:
        pairs = [
            (r["prob"], labels[r["name"]])
            for r in rows if r.get("ok") and r["name"] in labels
        ]
        return calibrate_mod.auc(
            [p for p, _ in pairs], [y for _, y in pairs]
        )

    # -- calibration pass: stage-1 probs over the dev set fit the
    # temperature + band (the docs/cascade.md recipe, end to end)
    reg1 = ModelRegistry(
        run_dir, family="deepdfa", checkpoint=cfg.serve.checkpoint,
        cfg=cfg,
    )
    svc1 = ScoringService(reg1, cfg)
    try:
        rows1 = score_texts(svc1, texts)  # also warms the feature cache
    finally:
        svc1.close()
    cal_pairs = [
        (r["prob"], labels[r["name"]]) for r in rows1 if r.get("ok")
    ]
    # ~0.27 target: the escalated band fills ONE stage-2 batch at the
    # bench sizes — a second nearly-empty batch would pad to full rows
    # and pay full device time (the collate contract), halving the win
    calib = calibrate_mod.calibrate(
        [p for p, _ in cal_pairs], [y for _, y in cal_pairs],
        target_escalation=0.27,
    )

    # -- combined-only baseline (fp32 entry)
    # timing convention: BEST of `reps` warm passes per mode — the
    # deterministic per-pass cost survives, this box's transient stalls
    # don't (the PR-10 overhead-bound lesson)
    reps = 3 if smoke else 5

    def best_pass(svc):
        rows, best = None, None
        for _ in range(reps):
            t0 = time.perf_counter()
            rows = score_texts(svc, texts)
            dt = time.perf_counter() - t0
            best = dt if best is None else min(best, dt)
        return rows, best

    regc = ModelRegistry(
        run_dir, family="combined", checkpoint="best", cfg=cfg
    )
    svcc = ScoringService(regc, cfg)
    try:
        score_texts(svcc, texts)  # warm
        rows_combined, combined_dt = best_pass(svcc)
    finally:
        svcc.close()
    combined_ok = sum(1 for r in rows_combined if r.get("ok"))
    combined_auc = matched_auc(rows_combined)

    # -- the cascade: trained GGNN screen + QUANTIZED stage 2 at the
    # fitted band/temperature
    ccfg = config_mod.apply_overrides(cfg, [
        "serve.cascade=true",
        f"serve.cascade_temperature={calib['temperature']}",
        "serve.cascade_band=" + json.dumps(calib["band"]),
        'serve.cascade_checkpoint="best@int8"',
    ])
    regx = ModelRegistry(
        run_dir, family="deepdfa", checkpoint=cfg.serve.checkpoint,
        cfg=ccfg,
    )
    svcx = ScoringService(regx, ccfg)
    try:
        score_texts(svcx, texts)  # warm
        esc0 = svcx.cascade.counters()
        rows_cascade, cascade_dt = best_pass(svcx)
        esc1 = svcx.cascade.counters()
        recompiles = svcx.steady_state_recompiles()
        quant_fraction = (
            svcx.cascade.service.registry.quant_bytes_fraction
        )
        quant_drift = svcx.cascade.service.registry.quant_drift
    finally:
        svcx.close()
    cascade_ok = sum(1 for r in rows_cascade if r.get("ok"))
    cascade_auc = matched_auc(rows_cascade)
    timed_reqs = esc1["requests"] - esc0["requests"]
    timed_escs = esc1["escalations"] - esc0["escalations"]
    escalation_rate = timed_escs / timed_reqs if timed_reqs else None

    combined_rps = combined_ok / combined_dt if combined_dt else 0.0
    cascade_rps = cascade_ok / cascade_dt if cascade_dt else 0.0
    drift = (
        max(0.0, combined_auc - cascade_auc)
        if combined_auc is not None and cascade_auc is not None
        else None
    )
    return {
        "metric": "cascade_req_per_sec",
        "value": round(cascade_rps, 2),
        "unit": "requests/s",
        "cascade_req_per_sec": round(cascade_rps, 2),
        "cascade_combined_req_per_sec": round(combined_rps, 2),
        "cascade_speedup": (
            round(cascade_rps / combined_rps, 3) if combined_rps else None
        ),
        "cascade_escalation_rate": (
            round(escalation_rate, 4)
            if escalation_rate is not None else None
        ),
        "cascade_auc": cascade_auc,
        "cascade_combined_auc": combined_auc,
        "cascade_stage1_auc": calib["dev_auc"],
        "cascade_score_drift": drift,
        "cascade_temperature": calib["temperature"],
        "cascade_band": calib["band"],
        "cascade_steady_state_recompiles": int(recompiles),
        "quant_param_bytes_fraction": quant_fraction,
        "quant_calibration_drift": quant_drift,
        "cascade_scored": cascade_ok,
        "n_examples": n,
        "smoke": smoke,
    }


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--examples", type=int, default=128)
    ap.add_argument("--out", default=None)
    ap.add_argument(
        "--smoke", action="store_true",
        help="tier-1 regression mode: tiny corpus/models, asserts the "
        "frontier (cascade strictly faster, drift inside the bound, "
        "zero recompiles)",
    )
    args = ap.parse_args()

    from deepdfa_tpu.core.backend import apply_platform_override

    os.environ.setdefault("DEEPDFA_TPU_PLATFORM", "cpu")
    apply_platform_override()
    if "DEEPDFA_TPU_STORAGE" not in os.environ:
        import tempfile

        tmp = tempfile.TemporaryDirectory(prefix="bench-cascade-")
        os.environ["DEEPDFA_TPU_STORAGE"] = tmp.name

    record = bench_cascade(args.examples, smoke=args.smoke)
    from deepdfa_tpu.obs import run_stamp

    record.update(run_stamp())
    print(json.dumps(record), flush=True)
    if args.out:
        Path(args.out).write_text(json.dumps(record, indent=1))
    if args.smoke:
        problems = []
        if record["cascade_steady_state_recompiles"]:
            problems.append(
                f"{record['cascade_steady_state_recompiles']} steady-"
                f"state recompiles (expected 0 across both ladders)"
            )
        if not (
            record["cascade_speedup"]
            and record["cascade_speedup"] > 1.0
        ):
            problems.append(
                f"cascade_speedup={record['cascade_speedup']} — the "
                f"cascade must strictly beat combined-only serving"
            )
        if record["cascade_score_drift"] is None or (
            record["cascade_score_drift"] > 0.05
        ):
            problems.append(
                f"cascade_score_drift={record['cascade_score_drift']} "
                f"outside the pinned 0.05 bound"
            )
        if not (
            record["quant_param_bytes_fraction"]
            and record["quant_param_bytes_fraction"] < 0.5
        ):
            problems.append(
                f"quant_param_bytes_fraction="
                f"{record['quant_param_bytes_fraction']} not under 0.5"
            )
        if problems:
            raise SystemExit(
                "cascade smoke contract violated:\n  "
                + "\n  ".join(problems)
            )


if __name__ == "__main__":
    main()
