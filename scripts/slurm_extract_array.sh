#!/usr/bin/env bash
#SBATCH --job-name=deepdfa-extract
#SBATCH --array=0-99%10
#SBATCH --cpus-per-task=4
#SBATCH --mem=8G
#SBATCH --time=04:00:00
#SBATCH --output=logs/extract_%a.out
# Sharded corpus extraction as a SLURM job array — the role of the
# reference's run_getgraphs.sh (#SBATCH --array=0-99%10 driving
# getgraphs.py --job_array_number, DDFA/scripts/run_getgraphs.sh).
# Each array task owns one shard of the corpus; shards write disjoint
# tagged artifact files, so no coordination is needed. Run
#   python -m deepdfa_tpu.cli extract-vocab   (once, before the array)
# then submit this, then any training job.
#
# Usage: sbatch [--array=0-(N-1)] scripts/slurm_extract_array.sh [overrides...]
set -euo pipefail
cd "$(dirname "$0")/.."

NUM_SHARDS="${NUM_SHARDS:-$((SLURM_ARRAY_TASK_MAX + 1))}"
export DEEPDFA_TPU_PLATFORM="${DEEPDFA_TPU_PLATFORM:-cpu}"

python -m deepdfa_tpu.cli extract \
    --workers "${SLURM_CPUS_PER_TASK:-4}" \
    --shard "${SLURM_ARRAY_TASK_ID}" \
    --num-shards "${NUM_SHARDS}" \
    "$@"
