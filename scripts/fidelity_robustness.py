#!/usr/bin/env python
"""Frontend robustness on real C the builder did not write (VERDICT r3 #5).

Harvests function definitions from third-party C sources present on this
box — BoringSSL's crypto tree (vendored under the tensorflow wheel),
CPython/Tcl/Tk build sources, and static-inline bodies in /usr/include —
and pushes every one through the full hermetic pipeline:

  preproc -> lexer -> parser -> CPG invariants -> reaching-defs fixpoint
  (python spec + C++ bitset solver agreement) -> abstract-dataflow
  features -> extract_graph

Per function it records: parser crash, CPG invariant violations (edge
endpoints in range, CFG lines within the source, entry-reachability),
solver termination + python/native agreement, absdf feature extraction
outcome, and end-to-end extract_graph success. The reference's analog is
Joern run on code its authors never saw (joern_session.py tests on
bundled X42.c; the Big-Vul corpus itself); the hermetic frontend must
hold up the same way.

Writes docs/fidelity_robustness_report.json; floors are pinned in
tests/test_fidelity_robustness_corpus.py (which re-harvests a fixed
sample live and skips when the source trees are absent).

    python scripts/fidelity_robustness.py --target 500
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

#: source roots, in priority order (first match wins per pattern)
HARVEST_GLOBS = [
    # BoringSSL crypto: real handwritten C, heavy pointer/loop/goto use
    "/root/.cache/uv/archive-v0/*/tensorflow/include/external/boringssl/src/crypto/**/*.c",
    # CPython/Tcl/Tk build + module sources
    "/mnt/sandboxing/model_tools_env/v1/python/build/**/*.c",
    "/mnt/sandboxing/model_tools_env/v1/python/install/lib/tcl8.6/*.c",
    "/mnt/sandboxing/model_tools_env/v1/python/install/lib/tk8.6/*.c",
    "/mnt/sandboxing/model_tools_env/v1/python/install/lib/python3.11/distutils/tests/xxmodule.c",
    # glibc / kernel headers: static inline bodies
    "/usr/include/**/*.h",
]

_FUNC_HEAD = re.compile(
    r"^(?:static\s+|inline\s+|extern\s+|const\s+|unsigned\s+|struct\s+\w+\s*\*?\s*|"
    r"[A-Za-z_]\w*[\s\*]+)+[A-Za-z_]\w*\s*\([^;{}]*\)\s*\{"
)
_SKIP_HEAD = re.compile(r"^\s*(typedef|struct|enum|union|#|//|/\*|\})")


def extract_functions(
    text: str, min_lines: int = 3, max_lines: int = 300, cap: int = 40
) -> list[str]:
    """Brace-matching scan for top-level function definitions. Heuristic
    on purpose: sloppy extraction only makes the robustness corpus
    nastier, which is the point."""
    out: list[str] = []
    lines = text.split("\n")
    i, n = 0, len(lines)
    depth = 0
    while i < n and len(out) < cap:
        line = lines[i]
        if depth == 0 and not _SKIP_HEAD.match(line):
            # join up to 4 physical lines to find `head(args) {`
            probe = line
            span = 1
            while span < 4 and "{" not in probe and ";" not in probe and i + span < n:
                probe = probe + " " + lines[i + span].strip()
                span += 1
            if _FUNC_HEAD.match(probe.strip()) and "=" not in probe.split("(")[0]:
                d = 0
                j = i
                body: list[str] = []
                while j < n:
                    body.append(lines[j])
                    d += lines[j].count("{") - lines[j].count("}")
                    j += 1
                    if d <= 0 and "{" in "".join(body):
                        break
                if d <= 0 and min_lines <= len(body) <= max_lines:
                    out.append("\n".join(body) + "\n")
                i = j
                depth = 0
                continue
        depth += line.count("{") - line.count("}")
        i += 1
    return out


def harvest(target: int, per_file_cap: int = 40) -> list[tuple[str, str]]:
    """[(source_path, function_text)], up to `target` functions,
    round-robin across the glob roots so no single tree (boringssl is
    large enough to fill any target alone) crowds out the others."""
    per_root: list[list[tuple[str, str]]] = []
    seen_files: set[str] = set()
    for pattern in HARVEST_GLOBS:
        bucket: list[tuple[str, str]] = []
        for path in sorted(glob.glob(pattern, recursive=True)):
            real = os.path.realpath(path)
            if real in seen_files:
                continue
            seen_files.add(real)
            try:
                text = open(path, errors="replace").read()
            except OSError:
                continue
            for fn in extract_functions(text, cap=per_file_cap):
                bucket.append((path, fn))
            if len(bucket) >= target:  # no root needs more than target
                break
        per_root.append(bucket)
    out: list[tuple[str, str]] = []
    i = 0
    while len(out) < target and any(per_root):
        took = False
        for bucket in per_root:
            if i < len(bucket):
                out.append(bucket[i])
                took = True
                if len(out) >= target:
                    break
        if not took:
            break
        i += 1
    return out


def check_one(code: str, audit: dict) -> None:
    from deepdfa_tpu.data.diffs import split_lines
    from deepdfa_tpu.data.pipeline import extract_graph
    from deepdfa_tpu.frontend import ReachingDefinitions, parse_function
    from deepdfa_tpu.frontend.absdf import graph_features
    from deepdfa_tpu.frontend.cpg import CFG

    audit["n"] += 1
    try:
        cpg = parse_function(code)
    except Exception as e:  # noqa: BLE001 — crash accounting is the point
        audit["parse_crash"] += 1
        audit.setdefault("crash_samples", []).append(
            f"{type(e).__name__}: {e}"[:160]
        )
        return
    n_lines = len(split_lines(code))

    # CPG invariants (cpg.nodes is a list indexed by node id)
    ok = True
    n_nodes = len(cpg.nodes)
    for s, d, _t in cpg.edges:
        if not (0 <= s < n_nodes and 0 <= d < n_nodes):
            ok = False
    cfg_nodes = cpg.cfg_nodes()
    for nid in cfg_nodes:
        ln = cpg.node(nid).line
        if ln is not None and not (1 <= int(ln) <= n_lines):
            ok = False
    if not ok:
        audit["invariant_violation"] += 1
        return
    # entry-reachability over CFG edges
    if cfg_nodes:
        adj: dict[int, list[int]] = {}
        for s, d, t in cpg.edges:
            if t == CFG:
                adj.setdefault(s, []).append(d)
        roots = [nid for nid in cfg_nodes if cpg.node(nid).label == "METHOD"]
        frontier = list(roots or cfg_nodes[:1])
        seen = set(frontier)
        while frontier:
            x = frontier.pop()
            for y in adj.get(x, ()):
                if y not in seen:
                    seen.add(y)
                    frontier.append(y)
        reach = len(seen & set(cfg_nodes)) / len(cfg_nodes)
        audit["reach_sum"] += reach
        audit["reach_n"] += 1

    # reaching-defs: python spec must terminate; native must agree
    if len(cfg_nodes) <= 3000:
        try:
            rd = ReachingDefinitions(cpg)
            ins_py = rd.solve(backend="python")
            audit["solver_ok"] += 1
            from deepdfa_tpu import native

            if native.available():
                ins_nat = rd.solve(backend="native")
                if ins_py == ins_nat:
                    audit["native_agree"] += 1
                else:
                    audit["native_disagree"] += 1
        except Exception as e:  # noqa: BLE001
            audit["solver_crash"] += 1
            audit.setdefault("solver_samples", []).append(
                f"{type(e).__name__}: {e}"[:160]
            )

    # absdf features: the reference RAISES on unhandled datatype shapes
    # (abstract_dataflow_full.py) and the pipeline skips-and-logs; both
    # outcomes are acceptable, a crash elsewhere is not
    try:
        graph_features(cpg)
        audit["absdf_ok"] += 1
    except Exception:  # noqa: BLE001 — spec-mirroring raise = skip class
        audit["absdf_raise"] += 1

    # end-to-end pipeline entry (None = reference skip-and-log behavior)
    try:
        g = extract_graph(code, graph_id=0)
        audit["extract_ok" if g is not None else "extract_skip"] += 1
    except Exception as e:  # noqa: BLE001
        audit["extract_crash"] += 1
        audit.setdefault("extract_samples", []).append(
            f"{type(e).__name__}: {e}"[:160]
        )


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--target", type=int, default=500)
    ap.add_argument("--out", default="docs/fidelity_robustness_report.json")
    args = ap.parse_args()

    t0 = time.time()
    funcs = harvest(args.target)
    by_root: dict[str, int] = {}
    for path, _ in funcs:
        root = (
            "boringssl" if "boringssl" in path
            else "usr_include" if path.startswith("/usr/include")
            else "python_build"
        )
        by_root[root] = by_root.get(root, 0) + 1

    audit: dict = {
        k: 0
        for k in (
            "n", "parse_crash", "invariant_violation", "solver_ok",
            "solver_crash", "native_agree", "native_disagree", "absdf_ok",
            "absdf_raise", "extract_ok", "extract_skip", "extract_crash",
        )
    }
    audit["reach_sum"] = 0.0
    audit["reach_n"] = 0
    for _path, fn in funcs:
        check_one(fn, audit)

    n = max(audit["n"], 1)
    report = {
        "harvested": len(funcs),
        "sources": by_root,
        "elapsed_seconds": round(time.time() - t0, 1),
        "parse_crash_rate": round(audit["parse_crash"] / n, 4),
        "invariant_violation_rate": round(audit["invariant_violation"] / n, 4),
        "mean_entry_reachability": round(
            audit["reach_sum"] / max(audit["reach_n"], 1), 4
        ),
        "solver_termination": {
            "ok": audit["solver_ok"], "crash": audit["solver_crash"],
        },
        "native_solver_agreement": {
            "agree": audit["native_agree"],
            "disagree": audit["native_disagree"],
        },
        "absdf": {"ok": audit["absdf_ok"], "spec_raise": audit["absdf_raise"]},
        "extract_graph": {
            "ok": audit["extract_ok"], "skip": audit["extract_skip"],
            "crash": audit["extract_crash"],
        },
        "samples": {
            k: audit.get(k, [])[:5]
            for k in ("crash_samples", "solver_samples", "extract_samples")
        },
        "method": "scripts/fidelity_robustness.py harvesting third-party C "
        "(BoringSSL crypto, CPython/Tcl build sources, /usr/include "
        "static inlines) through preproc->parse->invariants->reaching-defs"
        "(py+native)->absdf->extract_graph",
    }
    os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(report, f, indent=1)
    print(json.dumps({k: report[k] for k in (
        "harvested", "sources", "parse_crash_rate",
        "invariant_violation_rate", "mean_entry_reachability",
        "native_solver_agreement", "extract_graph",
    )}, indent=1))
    print(f"wrote {args.out}")


if __name__ == "__main__":
    main()
