#!/usr/bin/env python
"""Decision benchmark for the GGNN message-passing scatter (SURVEY §2.4)
plus the FUSED-STEP A/B for the Pallas GGNN kernel (ROADMAP item 1,
docs/ggnn_kernel.md).

Part 1 — scatter strategies for `a[v] = sum_{(u,v)} (W h)[u]` at the
flagship shape (node_budget 16384, edge_budget 65536, D=128), one JSON
line per strategy:

- xla_sorted:   gather + segment_sum(indices_are_sorted=True) — the
                production path in nn/gnn.py
- xla_unsorted: same without the sorted hint
- xla_bf16:     sorted path with bfloat16 messages
- cumsum:       dst-sorted run-sum via cumsum + boundary differences
                (the "CSR row-run accumulation" candidate)

Settled on a real v5e chip (2026-07-29): xla_sorted 40.9 ms,
xla_unsorted 299.7 ms, xla_bf16 300.3 ms, cumsum 520.2 ms, and a fused
Pallas VMEM gather+scatter kernel 517.7 ms. The sorted segment_sum path
beats that round's scatter-only Pallas kernel 12.6x, so it was deleted
(docs/DESIGN.md §3).

Part 2 (`bench_ggnn_step`) — the ISSUE-9 rematch at the right
granularity: not scatter-vs-scatter but the WHOLE GGNN step (transform
+ gather + scatter + GRU) as one fused `nn/ggnn_kernel.py` pass vs the
XLA-scheduled lax chain, per-step microseconds plus MFU measured
against the SAME-WINDOW matmul ceiling and gather-bandwidth roofline
(eval/profiling.py probes — spec peaks mislead on the time-shared
tunnel chip; docs/roofline.md). `ggnn_step_us` (lower is better) and
`ggnn_mfu` feed the bench-gate tolerance tables (obs/bench_gate.py), so
the MFU gap is a TRACKED number across rounds, not a guess.

    python scripts/bench_scatter.py            # default backend, full
    python scripts/bench_scatter.py --smoke    # tier-1 regression mode
    DEEPDFA_TPU_PLATFORM=cpu python scripts/bench_scatter.py
"""

from __future__ import annotations

import functools
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def make_inputs(n=16384, e=65536, d=128, avg_deg=2.0, seed=0):
    """Dst-sorted edges with a realistic CFG degree profile + padding tail."""
    rng = np.random.default_rng(seed)
    n_real_edges = int(min(e * 0.9, n * avg_deg))
    dst = np.sort(rng.integers(0, n - 1, n_real_edges)).astype(np.int32)
    src = rng.integers(0, n - 1, n_real_edges).astype(np.int32)
    edge_src = np.full((e,), n - 1, np.int32)
    edge_dst = np.full((e,), n - 1, np.int32)
    edge_src[:n_real_edges] = src
    edge_dst[:n_real_edges] = dst
    edge_mask = np.zeros((e,), bool)
    edge_mask[:n_real_edges] = True
    m = rng.standard_normal((n, d)).astype(np.float32)
    return m, edge_src, edge_dst, edge_mask


def xla_scatter(m, edge_src, edge_dst, edge_mask, *, sorted_hint, dtype=None):
    import jax

    if dtype is not None:
        m = m.astype(dtype)
    w = edge_mask.astype(m.dtype)[:, None]
    out = jax.ops.segment_sum(
        m[edge_src] * w,
        edge_dst,
        num_segments=m.shape[0],
        indices_are_sorted=sorted_hint,
    )
    return out.astype(np.float32)


def cumsum_scatter(m, edge_src, edge_dst, edge_mask, starts, ends):
    """Run-sum over the dst-sorted edge list: csum boundary differences.

    starts/ends are per-node [N] edge-range boundaries (precomputable per
    batch on the host, like the dst sort itself)."""
    import jax.numpy as jnp

    w = edge_mask.astype(m.dtype)[:, None]
    msg = m[edge_src] * w
    csum = jnp.concatenate(
        [jnp.zeros((1, m.shape[1]), m.dtype), jnp.cumsum(msg, axis=0)]
    )
    return csum[ends] - csum[starts]


def boundaries(edge_dst, n):
    starts = np.searchsorted(edge_dst, np.arange(n), side="left")
    ends = np.searchsorted(edge_dst, np.arange(n), side="right")
    return starts.astype(np.int32), ends.astype(np.int32)


def bench(fn, args, reps=20):
    import jax

    f = jax.jit(fn)
    out = f(*args)
    np.asarray(out)  # fetch-bounded compile + warmup
    t0 = time.perf_counter()
    for _ in range(reps):
        out = f(*args)
    # host FETCH, not block_until_ready: through the remote-TPU tunnel a
    # buffer can be reported ready before execution completes (bench.py
    # note); the reps are independent dispatches, so fetching the last
    # output alone would not even prove the earlier ones ran — but a
    # single device executes them serially, and the fetch pins the tail
    res = np.asarray(out)
    return (time.perf_counter() - t0) / reps * 1e3, res


def _step_workload(n: int, e: int, d: int, seed: int = 0):
    """A realistic padded GraphBatch + node features at the given
    budgets (CFG-degree dst-sorted edges with a padding tail — the same
    shape family `make_inputs` builds, wrapped as the batch the model
    paths consume)."""
    import jax.numpy as jnp

    from deepdfa_tpu.graphs.batch import GraphBatch

    m, src, dst, mask = make_inputs(n=n, e=e, d=d, seed=seed)
    ones_g = np.ones((1,), np.float32)
    batch = GraphBatch(
        node_feats=jnp.zeros((n, 4), jnp.int32),
        node_vuln=jnp.zeros((n,), jnp.int32),
        node_graph=jnp.zeros((n,), jnp.int32),
        node_mask=jnp.ones((n,), bool),
        edge_src=jnp.asarray(src),
        edge_dst=jnp.asarray(dst),
        edge_mask=jnp.asarray(mask),
        graph_label=jnp.asarray(ones_g),
        graph_mask=jnp.ones((1,), bool),
        graph_ids=jnp.zeros((1,), jnp.int32),
        num_graphs=1,
    )
    return batch, jnp.asarray(m)


def bench_ggnn_step(
    n: int = 16384,
    e: int = 65536,
    d: int = 128,
    n_steps: int = 5,
    reps: int = 10,
    smoke: bool = False,
) -> dict:
    """Fused-kernel vs lax A/B over `n_steps` GGNN steps; one record.

    Fields (the bench-gate contract): `ggnn_step_us` — per-step time of
    the kernel with scatter resolved for THIS platform (`"auto"`: mxu
    on TPU hardware, the bit-exact fold under the CPU interpreter) —
    LOWER IS BETTER; `ggnn_lax_step_us` the production lax chain;
    `ggnn_mfu` the lax path's achieved FLOP/s against the same-window
    measured matmul ceiling (and `ggnn_kernel_mfu` the kernel's);
    `ggnn_bytes_vs_gather_ceiling` the bandwidth side of the roofline;
    `ggnn_unroll_step_us` the WHOLE-UNROLL fusion (all steps in one
    pallas_call, h VMEM-resident) with `ggnn_unroll_speedup` vs the
    per-step kernel chain; `ggnn_kernel_int8_step_us` the int8-MXU
    variant. Numerics are asserted, not assumed: fold must be
    BIT-IDENTICAL to lax (fused unroll included), mxu within f32
    reassociation tolerance, bf16/int8 within the documented policy
    bounds. Each variant fails in isolation (`ggnn_<name>_error`) —
    a Mosaic gap in one never costs the record.
    """
    import jax
    import jax.numpy as jnp

    from deepdfa_tpu.eval.profiling import (
        compiled_cost,
        measure_gather_bandwidth,
        measure_matmul_ceiling,
    )
    from deepdfa_tpu.nn import GatedGraphConv

    if smoke:
        n, e, d, n_steps, reps = 512, 2048, 32, 3, 3

    platform = jax.devices()[0].platform
    batch, feat = _step_workload(n, e, d)
    lax_conv = GatedGraphConv(out_features=d, n_steps=n_steps)
    params = lax_conv.init(jax.random.key(0), batch, feat)

    def variant(**kw):
        conv = GatedGraphConv(out_features=d, n_steps=n_steps, **kw)
        return lambda f: conv.apply(params, batch, f)

    runs = {
        "lax": variant(),
        "kernel": variant(use_kernel=True),  # platform-resolved scatter
        "kernel_mxu": variant(use_kernel=True, kernel_scatter="mxu"),
        "kernel_bf16": variant(
            use_kernel=True, kernel_scatter="mxu", kernel_accum="bf16"
        ),
        # the whole-unroll fusion: every step inside ONE pallas_call,
        # h resident in VMEM — platform-resolved scatter so the fp32
        # bit-identity contract is asserted off-TPU (fold)
        "kernel_unroll": variant(use_kernel=True, kernel_unroll="fused"),
        # int8 activations on the MXU path under the drift admission
        # bound (nn/ggnn_kernel.py:INT8_DRIFT_BOUND)
        "kernel_int8": variant(
            use_kernel=True, kernel_scatter="mxu", kernel_accum="int8"
        ),
    }
    want = None
    rec: dict = {
        "metric": "ggnn_step_us",
        "unit": "us/step (fused kernel, platform-resolved scatter)",
        "platform": platform,
        "shape": f"n={n} e={e} d={d} steps={n_steps}",
    }
    for name, fn in runs.items():
        try:
            ms, out = bench(fn, (feat,), reps=reps)
        except Exception as exc:  # noqa: BLE001 — e.g. a Mosaic
            # lowering gap on new hardware must cost one variant's
            # fields, never the record (the lax number still lands)
            rec[f"ggnn_{name}_error"] = f"{type(exc).__name__}: {exc}"[:200]
            continue
        us = ms * 1e3 / n_steps
        if name == "lax":
            want = out
            rec["ggnn_lax_step_us"] = round(us, 2)
            continue
        if want is None:  # the lax reference itself failed: no parity
            rec["ggnn_step_us" if name == "kernel"
                else f"ggnn_{name}_step_us"] = round(us, 2)
            continue
        err = float(np.abs(out - want).max() / (np.abs(want).max() + 1e-9))
        # the numerics contract rides along with every measurement
        # (docs/ggnn_kernel.md): fold is bit-identical, mxu is f32
        # reassociation-only, bf16/int8 are the documented policy
        # bounds (int8 mirrors nn/ggnn_kernel.py:INT8_DRIFT_BOUND,
        # pinned in tests)
        tol = {"kernel_bf16": 0.05, "kernel_int8": 0.05,
               "kernel_mxu": 1e-5}.get(name, 1e-5)
        ok = bool(err <= tol)
        key = {
            "kernel": "ggnn_step_us",
            # the gate-tracked name for the fused unroll's per-step
            # time (obs/bench_gate.py:LOWER_IS_BETTER)
            "kernel_unroll": "ggnn_unroll_step_us",
        }.get(name, f"ggnn_{name}_step_us")
        rec[key] = round(us, 2)
        rec[f"ggnn_{name}_rel_err"] = round(err, 8)
        rec[f"ggnn_{name}_ok"] = ok
    if rec.get("ggnn_step_us") and rec.get("ggnn_lax_step_us"):
        rec["ggnn_kernel_speedup"] = round(
            rec["ggnn_lax_step_us"] / rec["ggnn_step_us"], 3
        )
    if rec.get("ggnn_step_us") and rec.get("ggnn_unroll_step_us"):
        # >1 means one fused pallas_call over all steps beats the
        # per-step kernel chain it replaces
        rec["ggnn_unroll_speedup"] = round(
            rec["ggnn_step_us"] / rec["ggnn_unroll_step_us"], 3
        )

    # MFU against the MEASURED same-window ceiling (spec peaks mislead
    # on a time-shared chip — eval/profiling.py; docs/roofline.md)
    try:
        cost = compiled_cost(runs["lax"], feat)
        flops = cost["flops"]
        if flops > 0:
            rec["ggnn_flops_per_step"] = round(flops / n_steps, 1)
            probe_n = 1024 if smoke or platform == "cpu" else 4096
            ceiling = measure_matmul_ceiling(
                n=probe_n, chain=2 if smoke else 8,
                reps=1 if smoke else 3,
                dtype=jnp.float32 if platform == "cpu" else None,
            )
            rec.update(ceiling)
            meas = ceiling["matmul_tflops_measured"] * 1e12
            for key, us_key in (
                ("ggnn_mfu", "ggnn_lax_step_us"),
                ("ggnn_kernel_mfu", "ggnn_step_us"),
            ):
                us = rec.get(us_key)
                if us and meas > 0:
                    rec[key] = round(
                        (flops / n_steps) / (us * 1e-6) / meas, 6
                    )
        byts = cost.get("bytes_accessed", 0.0)
        if byts > 0 and rec.get("ggnn_lax_step_us"):
            rec["ggnn_bytes_per_step"] = round(byts / n_steps, 1)
            gather = measure_gather_bandwidth(
                rows=min(n, 4096) if smoke else n,
                dim=d, idx_len=min(e, 16384) if smoke else e,
                chain=2 if smoke else 8, reps=1 if smoke else 3,
            )
            rec.update(gather)
            gbps = gather["gather_gbps_measured"] * 1e9
            if gbps > 0:
                rec["ggnn_bytes_vs_gather_ceiling"] = round(
                    (byts / n_steps)
                    / (rec["ggnn_lax_step_us"] * 1e-6) / gbps, 4
                )
    except Exception as exc:  # probes must never cost the A/B record
        rec["ggnn_roofline_error"] = f"{type(exc).__name__}: {exc}"[:200]

    from deepdfa_tpu.obs import run_stamp

    rec.update(run_stamp())
    rec["value"] = rec.get("ggnn_step_us")
    return rec


def run_smoke() -> dict:
    """Tier-1 regression mode (the bench_prefetch/bench_scan
    convention): a tiny fused-step A/B whose numerics contract is
    ASSERTED — fold bit-identical to lax, mxu within f32 reassociation
    tolerance, bf16 within the policy bound — plus the roofline fields
    present. Raises on any violation; prints + returns one record."""
    rec = bench_ggnn_step(smoke=True)
    import jax

    if jax.devices()[0].platform != "tpu":
        # "auto" resolves to the fold scatter off-TPU: bit-identity is
        # the contract, not a tolerance — for the fused unroll too
        # (fp32 fold fusion changes WHERE h lives, not one f32 op)
        for name, label in (
            ("kernel", "fold kernel"),
            ("kernel_unroll", "fused-unroll fold kernel"),
        ):
            if rec.get(f"ggnn_{name}_rel_err") != 0.0:
                raise AssertionError(
                    f"{label} not bit-identical to lax: rel_err="
                    f"{rec.get(f'ggnn_{name}_rel_err')}"
                )
    for name in (
        "kernel", "kernel_mxu", "kernel_bf16", "kernel_unroll",
        "kernel_int8",
    ):
        if not rec.get(f"ggnn_{name}_ok"):
            raise AssertionError(
                f"{name} numerics outside tolerance: "
                f"rel_err={rec.get(f'ggnn_{name}_rel_err')}"
            )
    if not rec.get("ggnn_step_us") or not rec.get("ggnn_lax_step_us"):
        raise AssertionError(f"missing step timings: {rec}")
    if not rec.get("ggnn_unroll_step_us"):
        raise AssertionError(f"missing fused-unroll timing: {rec}")
    print(json.dumps(rec))
    return rec


def main(argv=None):
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true")
    opts = ap.parse_args(argv)

    from deepdfa_tpu.core.backend import apply_platform_override

    apply_platform_override()
    import jax

    if opts.smoke:
        run_smoke()
        return

    m, src, dst, mask = make_inputs()
    n = m.shape[0]
    starts, ends = boundaries(dst, n)
    platform = jax.devices()[0].platform
    want = None

    strategies = {
        "xla_sorted": (
            functools.partial(xla_scatter, sorted_hint=True), (m, src, dst, mask)
        ),
        "xla_unsorted": (
            functools.partial(xla_scatter, sorted_hint=False), (m, src, dst, mask)
        ),
        "xla_bf16": (
            functools.partial(
                xla_scatter, sorted_hint=True, dtype=np.dtype("bfloat16")
            ),
            (m, src, dst, mask),
        ),
        "cumsum": (cumsum_scatter, (m, src, dst, mask, starts, ends)),
    }

    results = {}
    for name, (fn, args) in strategies.items():
        try:
            ms, out = bench(fn, args)
        except Exception as exc:  # noqa: BLE001 - report, don't die
            print(json.dumps({"strategy": name, "error": str(exc)[:300]}))
            continue
        if want is None:
            want = out
        # bf16 accumulates in lower precision; everything else must agree
        tol = 0.05 if "bf16" in name else 1e-3
        max_err = float(np.abs(out - want).max() / (np.abs(want).max() + 1e-9))
        if max_err < tol:
            # only numerically-correct strategies compete for "best"
            results[name] = ms
        print(
            json.dumps(
                {
                    "strategy": name,
                    "ms": round(ms, 3),
                    "platform": platform,
                    "rel_err_vs_first": round(max_err, 6),
                    "ok": max_err < tol,
                }
            )
        )
    if results:
        from deepdfa_tpu.obs import run_stamp

        best = min(results, key=results.get)
        print(json.dumps({
            "best": best, "ms": round(results[best], 3), **run_stamp(),
        }))

    # the fused-step rematch at full shape (see module docstring part 2)
    try:
        print(json.dumps(bench_ggnn_step()))
    except Exception as exc:  # noqa: BLE001 - report, don't die
        print(json.dumps({"strategy": "ggnn_step", "error": str(exc)[:300]}))


if __name__ == "__main__":
    main()
