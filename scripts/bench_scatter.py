#!/usr/bin/env python
"""Decision benchmark for the GGNN message-passing scatter (SURVEY §2.4).

Measures every implementation strategy for `a[v] = sum_{(u,v)} (W h)[u]`
at the flagship shape (node_budget 16384, edge_budget 65536, D=128) on
the current jax platform and prints one JSON line per strategy:

- xla_sorted:   gather + segment_sum(indices_are_sorted=True) — the
                production path in nn/gnn.py
- xla_unsorted: same without the sorted hint
- xla_bf16:     sorted path with bfloat16 messages
- cumsum:       dst-sorted run-sum via cumsum + boundary differences
                (the "CSR row-run accumulation" candidate)

Settled on a real v5e chip (2026-07-29): xla_sorted 40.9 ms,
xla_unsorted 299.7 ms, xla_bf16 300.3 ms, cumsum 520.2 ms, and a fused
Pallas VMEM gather+scatter kernel 517.7 ms. The sorted segment_sum path
beats the Pallas kernel 12.6x (and every other strategy by >=7.3x), so
the Pallas kernel was deleted (see docs/DESIGN.md
section 3); this script remains for re-evaluation on new hardware.

    python scripts/bench_scatter.py            # default backend
    DEEPDFA_TPU_PLATFORM=cpu python scripts/bench_scatter.py
"""

from __future__ import annotations

import functools
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def make_inputs(n=16384, e=65536, d=128, avg_deg=2.0, seed=0):
    """Dst-sorted edges with a realistic CFG degree profile + padding tail."""
    rng = np.random.default_rng(seed)
    n_real_edges = int(min(e * 0.9, n * avg_deg))
    dst = np.sort(rng.integers(0, n - 1, n_real_edges)).astype(np.int32)
    src = rng.integers(0, n - 1, n_real_edges).astype(np.int32)
    edge_src = np.full((e,), n - 1, np.int32)
    edge_dst = np.full((e,), n - 1, np.int32)
    edge_src[:n_real_edges] = src
    edge_dst[:n_real_edges] = dst
    edge_mask = np.zeros((e,), bool)
    edge_mask[:n_real_edges] = True
    m = rng.standard_normal((n, d)).astype(np.float32)
    return m, edge_src, edge_dst, edge_mask


def xla_scatter(m, edge_src, edge_dst, edge_mask, *, sorted_hint, dtype=None):
    import jax

    if dtype is not None:
        m = m.astype(dtype)
    w = edge_mask.astype(m.dtype)[:, None]
    out = jax.ops.segment_sum(
        m[edge_src] * w,
        edge_dst,
        num_segments=m.shape[0],
        indices_are_sorted=sorted_hint,
    )
    return out.astype(np.float32)


def cumsum_scatter(m, edge_src, edge_dst, edge_mask, starts, ends):
    """Run-sum over the dst-sorted edge list: csum boundary differences.

    starts/ends are per-node [N] edge-range boundaries (precomputable per
    batch on the host, like the dst sort itself)."""
    import jax.numpy as jnp

    w = edge_mask.astype(m.dtype)[:, None]
    msg = m[edge_src] * w
    csum = jnp.concatenate(
        [jnp.zeros((1, m.shape[1]), m.dtype), jnp.cumsum(msg, axis=0)]
    )
    return csum[ends] - csum[starts]


def boundaries(edge_dst, n):
    starts = np.searchsorted(edge_dst, np.arange(n), side="left")
    ends = np.searchsorted(edge_dst, np.arange(n), side="right")
    return starts.astype(np.int32), ends.astype(np.int32)


def bench(fn, args, reps=20):
    import jax

    f = jax.jit(fn)
    out = f(*args)
    np.asarray(out)  # fetch-bounded compile + warmup
    t0 = time.perf_counter()
    for _ in range(reps):
        out = f(*args)
    # host FETCH, not block_until_ready: through the remote-TPU tunnel a
    # buffer can be reported ready before execution completes (bench.py
    # note); the reps are independent dispatches, so fetching the last
    # output alone would not even prove the earlier ones ran — but a
    # single device executes them serially, and the fetch pins the tail
    res = np.asarray(out)
    return (time.perf_counter() - t0) / reps * 1e3, res


def main():
    from deepdfa_tpu.core.backend import apply_platform_override

    apply_platform_override()
    import jax

    m, src, dst, mask = make_inputs()
    n = m.shape[0]
    starts, ends = boundaries(dst, n)
    platform = jax.devices()[0].platform
    want = None

    strategies = {
        "xla_sorted": (
            functools.partial(xla_scatter, sorted_hint=True), (m, src, dst, mask)
        ),
        "xla_unsorted": (
            functools.partial(xla_scatter, sorted_hint=False), (m, src, dst, mask)
        ),
        "xla_bf16": (
            functools.partial(
                xla_scatter, sorted_hint=True, dtype=np.dtype("bfloat16")
            ),
            (m, src, dst, mask),
        ),
        "cumsum": (cumsum_scatter, (m, src, dst, mask, starts, ends)),
    }

    results = {}
    for name, (fn, args) in strategies.items():
        try:
            ms, out = bench(fn, args)
        except Exception as exc:  # noqa: BLE001 - report, don't die
            print(json.dumps({"strategy": name, "error": str(exc)[:300]}))
            continue
        if want is None:
            want = out
        # bf16 accumulates in lower precision; everything else must agree
        tol = 0.05 if "bf16" in name else 1e-3
        max_err = float(np.abs(out - want).max() / (np.abs(want).max() + 1e-9))
        if max_err < tol:
            # only numerically-correct strategies compete for "best"
            results[name] = ms
        print(
            json.dumps(
                {
                    "strategy": name,
                    "ms": round(ms, 3),
                    "platform": platform,
                    "rel_err_vs_first": round(max_err, 6),
                    "ok": max_err < tol,
                }
            )
        )
    if results:
        from deepdfa_tpu.obs import run_stamp

        best = min(results, key=results.get)
        print(json.dumps({
            "best": best, "ms": round(results[best], 3), **run_stamp(),
        }))


if __name__ == "__main__":
    main()
