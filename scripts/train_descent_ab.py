#!/usr/bin/env python
"""Training-quality A/B: the flash kernel must TRAIN like the XLA path.

Throughput parity is not training parity: the kernel's dropout uses a
different RNG stream (TPU PRNG vs threefry), so step-for-step losses
cannot match bitwise — what must match is the descent. This runs the
real combined trainer (roberta arch, flagship geometry) twice from the
IDENTICAL initialization on the identical batch stream — once per
attention lowering — and records both loss trajectories. Same recipe,
same optimizer, same data; the only difference is the attention
lowering and its dropout stream.

Invoked once per round by scripts/tpu_watchdog.py when a healthy window
appears and docs/train_descent_ab.json does not exist yet; by hand:

    python scripts/train_descent_ab.py [--steps 30] [--out ...]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--rows", type=int, default=64)
    ap.add_argument("--seq", type=int, default=512)
    ap.add_argument("--tiny", action="store_true",
                    help="tiny encoder (CPU harness validation)")
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    from deepdfa_tpu.core.backend import (
        apply_platform_override,
        enable_compile_cache,
    )

    apply_platform_override()
    enable_compile_cache()
    import dataclasses

    import jax
    import numpy as np

    from deepdfa_tpu.models.transformer import TransformerConfig

    platform = jax.devices()[0].platform
    if args.out and platform != "tpu" and not args.tiny:
        # a healthy-probe window that degraded to CPU before this
        # subprocess initialized JAX must NOT consume the one-shot
        # artifact slot — bail before burning CPU-hours on the 125M
        # model; the watchdog retries in a later window
        print("train_descent_ab: non-TPU backend, refusing to run the "
              "full-size A/B for --out", file=sys.stderr)
        raise SystemExit(3)
    if args.tiny:
        enc = TransformerConfig.tiny(
            vocab_size=512, max_position_embeddings=args.seq + 4)
    else:
        enc = TransformerConfig(
            vocab_size=50265, max_position_embeddings=args.seq + 2)
    enc = dataclasses.replace(
        enc, dtype="bfloat16" if platform == "tpu" else "float32")

    n = args.rows
    from _combined_batch import build_trainer_and_batch

    impls = ["xla", "flash"] if platform == "tpu" else ["xla"]
    record: dict = {
        "platform": platform,
        "steps": args.steps,
        "rows": n,
        "seq": args.seq,
        "encoder": "tiny" if args.tiny else "codebert-base(12x768)",
        "recipe": "identical init (seed 0), identical batch each step, "
                  "AdamW flagship defaults, dropout 0.1; only the "
                  "attention lowering (and thus its dropout RNG stream) "
                  "differs",
        "runs": {},
    }
    for impl in impls:
        ec = dataclasses.replace(enc, attn_impl=impl)
        trainer, state, batch = build_trainer_and_batch(
            ec, "roberta", n, args.seq, vuln_rate=0.25)
        key = jax.random.key(0)
        losses = []
        for r in range(args.steps):
            state, loss = trainer.train_step(
                state, batch, jax.random.fold_in(key, r))
            losses.append(round(float(loss), 5))
        record["runs"][impl] = {
            "losses": losses,
            "first": losses[0],
            "last": losses[-1],
            "min": min(losses),
        }

    if len(record["runs"]) == 2:
        lx = record["runs"]["xla"]
        lf = record["runs"]["flash"]
        # identical init => identical first loss up to bf16 noise (step-0
        # forward uses dropout, whose streams differ — compare minima and
        # final plateau instead of any single step)
        record["descent_comparable"] = bool(
            abs(lf["last"] - lx["last"]) < 0.15
            and abs(lf["min"] - lx["min"]) < 0.15)

    print(json.dumps(record), flush=True)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(record, f, indent=1)


if __name__ == "__main__":
    main()
