"""Shared flagship-workload builder for the combined-model bench scripts.

One definition of the synthetic corpus -> tokenized rows -> aligned
graph batch -> CombinedTrainer sequence, so bench_combined.py and
train_descent_ab.py measure the SAME recipe by construction (they
previously each carried a copy; a budget or tokenizer-framing change in
one silently diverged the other)."""

from __future__ import annotations


def build_trainer_and_batch(enc, arch: str, rows: int, seq: int,
                            vuln_rate: float = 0.06):
    """(trainer, state, batch) for one encoder config.

    enc: TransformerConfig (arch 'roberta') or T5Config (arch 't5').
    """
    from deepdfa_tpu.core import Config
    from deepdfa_tpu.data import build_dataset, generate, to_examples
    from deepdfa_tpu.data.text import collate_shards
    from deepdfa_tpu.data.tokenizer import HashTokenizer
    from deepdfa_tpu.train.combined_loop import CombinedTrainer

    if arch == "t5":
        from deepdfa_tpu.models import t5 as t5m

        mcfg = t5m.DefectConfig(encoder=enc, graph_input_dim=1002)
    else:
        from deepdfa_tpu.models import combined as cmb

        mcfg = cmb.CombinedConfig(encoder=enc, graph_input_dim=1002)

    synth = generate(rows, vuln_rate=vuln_rate, seed=7)
    specs, _ = build_dataset(
        to_examples(synth), train_ids=range(rows), limit_all=1000,
        limit_subkeys=1000,
    )
    by_id = {s.graph_id: s for s in specs}
    tok = HashTokenizer(vocab_size=enc.vocab_size, t5_frame=(arch == "t5"))
    token_ids = tok.batch_encode([s.before for s in synth], max_length=seq)
    batch = collate_shards(
        token_ids, [s.label for s in synth], list(range(rows)), by_id,
        num_shards=1, rows_per_shard=rows, node_budget=4096,
        edge_budget=16384,
    )
    trainer = CombinedTrainer(Config(), mcfg)
    state = trainer.init_state(seed=0)
    return trainer, state, batch
