#!/usr/bin/env python
"""Fuzz frontend/preproc.py against the real C preprocessor (gcc -E).

Same spirit as scripts/fuzz_diffs_vs_git.py: the hermetic conditional
evaluator (ISO C #if/#elif arithmetic, #ifdef/#define/#undef tables,
block-comment awareness) claims real-preprocessor semantics; this
harness generates random directive programs over marker declarations,
runs both `gcc -E -P` and evaluate_conditionals, and compares WHICH
markers survive. Expressions are drawn well-formed (gcc hard-errors on
malformed ones, where the hermetic pass intentionally stays permissive),
and macro names avoid gcc's built-in table.

Writes docs/preproc_fuzz_report.json; floors in tests/test_preproc.py's
slow section (added alongside this script).

    python scripts/fuzz_preproc_vs_gcc.py [--n 300]
"""

from __future__ import annotations

import argparse
import json
import random
import re
import shutil
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from deepdfa_tpu.frontend.preproc import evaluate_conditionals  # noqa: E402

_MARKER_RE = re.compile(r"\bm(\d+)\b")
MACROS = [f"MYFLAG_{c}" for c in "ABCDE"]


def gen_expr(rng: random.Random, depth: int = 0) -> str:
    if depth >= 3 or rng.random() < 0.35:
        k = rng.randrange(4)
        if k == 0:
            return str(rng.randrange(0, 6))
        if k == 1:
            return rng.choice(MACROS)
        if k == 2:
            return f"defined({rng.choice(MACROS)})"
        return f"defined {rng.choice(MACROS)}"
    op = rng.choice(["+", "-", "*", "&&", "||", "<", "<=", "==", "!=", "<<"])
    a = gen_expr(rng, depth + 1)
    b = gen_expr(rng, depth + 1)
    if op == "<<":
        b = str(rng.randrange(0, 8))
    if rng.random() < 0.2:
        return f"!({a} {op} {b})"
    if rng.random() < 0.15:
        c = gen_expr(rng, depth + 1)
        return f"(({a} {op} {b}) ? {c} : {gen_expr(rng, depth + 1)})"
    return f"({a} {op} {b})"


def gen_program(rng: random.Random) -> str:
    """Random nest of conditionals over marker declarations."""
    lines: list[str] = []
    marker = 0
    depth = 0

    def emit_markers():
        nonlocal marker
        for _ in range(rng.randrange(1, 3)):
            lines.append(f"int m{marker};")
            marker += 1

    for _ in range(rng.randrange(6, 18)):
        r = rng.random()
        if r < 0.22:
            kind = rng.randrange(3)
            if kind == 0:
                lines.append(f"#if {gen_expr(rng)}")
            elif kind == 1:
                lines.append(f"#ifdef {rng.choice(MACROS)}")
            else:
                lines.append(f"#ifndef {rng.choice(MACROS)}")
            depth += 1
        elif r < 0.32 and depth:
            lines.append(f"#elif {gen_expr(rng)}")
        elif r < 0.42 and depth:
            lines.append("#else")
        elif r < 0.55 and depth:
            lines.append("#endif")
            depth -= 1
        elif r < 0.65:
            v = rng.choice(["", " 1", " 0", f" {rng.randrange(2, 9)}"])
            lines.append(f"#define {rng.choice(MACROS)}{v}")
        elif r < 0.72:
            lines.append(f"#undef {rng.choice(MACROS)}")
        elif r < 0.78:
            lines.append(f"/* noise {rng.randrange(9)}")
            lines.append("#if this is commented out")
            lines.append("*/")
        else:
            emit_markers()
    while depth:
        lines.append("#endif")
        depth -= 1
    emit_markers()  # at least one unconditional tail marker
    return "\n".join(lines) + "\n"


def gcc_markers(program: str) -> set[int] | None:
    res = subprocess.run(
        ["gcc", "-E", "-P", "-xc", "-"],
        input=program, capture_output=True, text=True,
    )
    if res.returncode != 0:
        return None  # malformed for gcc; skip the case
    return {int(m) for m in _MARKER_RE.findall(res.stdout)}


def ours_markers(program: str) -> set[int]:
    return {int(m) for m in _MARKER_RE.findall(evaluate_conditionals(program))}


def run(n: int, seed: int, dump: int = 0) -> dict:
    rng = random.Random(seed)
    total = exact = skipped = 0
    dumped = 0
    while total < n:
        prog = gen_program(rng)
        want = gcc_markers(prog)
        if want is None:
            skipped += 1
            if skipped > 5 * n:
                break
            continue
        total += 1
        got = ours_markers(prog)
        if got == want:
            exact += 1
        elif dumped < dump:
            dumped += 1
            print("=== MISS ===")
            print(prog)
            print("gcc :", sorted(want))
            print("ours:", sorted(got))
    return {
        "n": total,
        "exact": exact,
        "pct": round(100.0 * exact / max(total, 1), 1),
        "gcc_rejected_skipped": skipped,
    }


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--n", type=int, default=300)
    ap.add_argument("--seed", type=int, default=20260730)
    ap.add_argument("--dump-misses", type=int, default=0)
    args = ap.parse_args()
    if shutil.which("gcc") is None:
        print("no gcc on this box"); return
    rec = run(args.n, args.seed, args.dump_misses)
    import datetime

    rec["_meta"] = {
        "seed": args.seed,
        "gcc": subprocess.run(["gcc", "--version"], capture_output=True,
                              text=True).stdout.splitlines()[0],
        "generated_at": datetime.datetime.now(
            datetime.timezone.utc
        ).strftime("%Y-%m-%dT%H:%M:%SZ"),
    }
    print(json.dumps({k: rec[k] for k in ("n", "exact", "pct")}))
    out = REPO / "docs" / "preproc_fuzz_report.json"
    out.write_text(json.dumps(rec, indent=1))
    print(f"wrote {out}")


if __name__ == "__main__":
    main()
