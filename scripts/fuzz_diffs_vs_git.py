#!/usr/bin/env python
"""Fuzz data/diffs.py against real `git diff --no-index` (VERDICT r3 #6).

Three corpora, hardest first:
- adversarial: random duplicate-line soups (tiny vocab, heavy repetition)
  — the regime where raw Myers output is ambiguous and git's
  xdl_change_compact (group sliding + align-to-other + indent heuristic)
  decides which of several minimal diffs is reported;
- fuzzed: C-like edit scripts over realistic function bodies (the round-3
  299/299 corpus shape);
- indented: soups with indentation/blank-line structure so the indent
  heuristic's scoring terms are actually exercised.

Prints one JSON line per corpus {corpus, n, exact, pct} and writes
docs/diff_fuzz_report.json. Exact = both the removed-in-before and
added-in-after 1-based line sets match git's parsed hunks byte-for-byte.

Run from the repo root:  python scripts/fuzz_diffs_vs_git.py [--n 297]
"""

from __future__ import annotations

import argparse
import json
import os
import random
import subprocess
import sys
import tempfile
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from deepdfa_tpu.data.diffs import diff_lines  # noqa: E402


def git_diff_lines(before: str, after: str) -> tuple[set[int], set[int]]:
    """The reference's invocation (DDFA/sastvd/helpers/git.py:21-36):
    git diff --no-index --no-prefix -U<huge>, parsed into -/+ lines."""
    with tempfile.TemporaryDirectory() as td:
        pb, pa = os.path.join(td, "before.c"), os.path.join(td, "after.c")
        with open(pb, "w") as f:
            f.write(before)
        with open(pa, "w") as f:
            f.write(after)
        res = subprocess.run(
            ["git", "diff", "--no-index", "--no-prefix", "-U100000", pb, pa],
            capture_output=True, text=True,
        )
    removed: set[int] = set()
    added: set[int] = set()
    old_ln = new_ln = 0
    in_hunk = False
    for line in res.stdout.splitlines():
        if line.startswith("@@"):
            seg = line.split()[1]  # -<start>[,<count>]
            old_ln = int(seg[1:].split(",")[0])
            seg = line.split()[2]
            new_ln = int(seg[1:].split(",")[0])
            in_hunk = True
            continue
        if not in_hunk:
            continue
        if line.startswith("-"):
            removed.add(old_ln)
            old_ln += 1
        elif line.startswith("+"):
            added.add(new_ln)
            new_ln += 1
        elif line.startswith(" ") or line == "":
            old_ln += 1
            new_ln += 1
    return removed, added


def mutate(rng: random.Random, lines: list[str], vocab: list[str], n_edits: int) -> list[str]:
    out = list(lines)
    for _ in range(n_edits):
        op = rng.randrange(3)
        if op == 0 and out:
            out.pop(rng.randrange(len(out)))
        elif op == 1:
            out.insert(rng.randrange(len(out) + 1), rng.choice(vocab))
        elif out:
            out[rng.randrange(len(out))] = rng.choice(vocab)
    return out


def corpus_adversarial(rng: random.Random, n: int):
    vocab = ["a;", "a;", "a;", "b;", "}", "{", "x = x + 1;"]
    for _ in range(n):
        before = [rng.choice(vocab) for _ in range(rng.randrange(4, 24))]
        after = mutate(rng, before, vocab, rng.randrange(1, 6))
        yield "\n".join(before) + "\n", "\n".join(after) + "\n"


def corpus_indented(rng: random.Random, n: int):
    vocab = [
        "int x = 0;", "  if (x) {", "    f(x);", "    f(x);", "  }",
        "", "  return x;", "}", "void g() {", "  f(x);",
    ]
    for _ in range(n):
        before = [rng.choice(vocab) for _ in range(rng.randrange(5, 28))]
        after = mutate(rng, before, vocab, rng.randrange(1, 5))
        yield "\n".join(before) + "\n", "\n".join(after) + "\n"


def corpus_fuzzed(rng: random.Random, n: int):
    body = [
        "int f(int *p, int n) {",
        "  int i, acc = 0;",
        "  for (i = 0; i < n; i++) {",
        "    acc += p[i];",
        "    if (acc > 100)",
        "      break;",
        "  }",
        "  return acc;",
        "}",
    ]
    extra = ["  acc = 0;", "  if (!p) return 0;", "  n--;", "  acc <<= 1;"]
    for _ in range(n):
        after = mutate(rng, body, extra, rng.randrange(1, 4))
        yield "\n".join(body) + "\n", "\n".join(after) + "\n"


def corpus_large_rewrite(rng: random.Random, n: int):
    """Thousand-line files with hundreds of edits: drives xdl_split past
    XDL_HEUR_MIN_COST / mxcost so the non-minimal cost heuristics (which
    `git diff` always has enabled) actually decide the script."""
    n = max(1, n // 10)  # each case is ~100x the small-corpus work
    vocab = [f"stmt_{i};" for i in range(40)] + ["}", "{", "return x;"]
    for _ in range(n):
        before = [rng.choice(vocab) for _ in range(rng.randrange(600, 1200))]
        after = mutate(rng, before, vocab, rng.randrange(250, 700))
        yield "\n".join(before) + "\n", "\n".join(after) + "\n"


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=297)
    ap.add_argument("--seed", type=int, default=20260730)
    ap.add_argument("--dump-misses", type=int, default=0,
                    help="print the first K mismatching pairs")
    args = ap.parse_args()

    report = {}
    for name, gen in [
        ("adversarial", corpus_adversarial),
        ("indented", corpus_indented),
        ("fuzzed", corpus_fuzzed),
        ("large_rewrite", corpus_large_rewrite),
    ]:
        rng = random.Random(args.seed)
        exact = 0
        total = 0
        missed = []
        for before, after in gen(rng, args.n):
            total += 1
            ours = diff_lines(before, after)
            theirs = git_diff_lines(before, after)
            if ours == theirs:
                exact += 1
            elif len(missed) < args.dump_misses:
                missed.append((before, after, ours, theirs))
        rec = {"corpus": name, "n": total, "exact": exact,
               "pct": round(100.0 * exact / total, 1)}
        print(json.dumps(rec), flush=True)
        report[name] = rec
        for before, after, ours, theirs in missed:
            print("=== MISS ===")
            print("--- before ---")
            print(before, end="")
            print("--- after ---")
            print(after, end="")
            print(f"ours:   removed={sorted(ours[0])} added={sorted(ours[1])}")
            print(f"git:    removed={sorted(theirs[0])} added={sorted(theirs[1])}")

    out = REPO / "docs" / "diff_fuzz_report.json"
    import datetime

    report["_meta"] = {
        "seed": args.seed,
        "git_version": subprocess.run(
            ["git", "--version"], capture_output=True, text=True
        ).stdout.strip(),
        "generated_at": datetime.datetime.now(datetime.timezone.utc).strftime(
            "%Y-%m-%dT%H:%M:%SZ"
        ),
        "invocation": "git diff --no-index --no-prefix -U100000",
    }
    out.write_text(json.dumps(report, indent=1))
    print(f"wrote {out}")


if __name__ == "__main__":
    main()
