#!/usr/bin/env python
"""TPU-window watchdog: poll backend health all session; bench the moment
a healthy window appears.

Round 1-3 each made ONE bench attempt at round end and kept losing the
tunnel lottery (see docs/ROUND3_NOTES.md; BENCH_r03.json records
``probe: backend probe timed out after 300s``). This watchdog inverts
the protocol: run it in the background for the whole build session,
cheaply probing the default (tunnel) backend every POLL_INTERVAL with a
bounded subprocess; the first healthy window triggers the full bench
suite (bench.py inference+train, scripts/bench_combined.py 125M-model
MFU) and commits ``BENCH_TPU_<utc-timestamp>.json`` plus the poll log.

Every poll — healthy or not — is appended to ``docs/tpu_poll_log.jsonl``
so a round that never sees a healthy window still produces a committed,
timestamped record proving the tunnel was down the whole time (the
VERDICT r3 "done" criterion).

Invocation (backgrounded for the session, from the repo root):

    nohup python scripts/tpu_watchdog.py >> docs/tpu_watchdog.out 2>&1 &

Environment knobs:
    DEEPDFA_WATCHDOG_INTERVAL   seconds between poll starts (default 600)
    DEEPDFA_WATCHDOG_DEADLINE   total seconds to keep polling (default 39600)
    DEEPDFA_WATCHDOG_PROBE_TIMEOUT  per-probe bound (default 240)
    DEEPDFA_WATCHDOG_ONESHOT    "1": poll once, bench if healthy, exit
    DEEPDFA_WATCHDOG_COOLDOWN   seconds between captures (default 3600)
    DEEPDFA_WATCHDOG_EXIT_ON_CAPTURE  "1": stop after the first TPU
        capture (pre-round-4 behavior); default keeps polling — the
        time-shared tunnel chip varies several-fold between windows,
        so every extra capture adds evidence

The probe subprocess inherits the default environment (no JAX_PLATFORMS /
DEEPDFA_TPU_PLATFORM overrides, PYTHONPATH untouched) so it resolves the
same backend the driver's own bench invocation would.
"""

from __future__ import annotations

import datetime
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

POLL_INTERVAL = float(os.environ.get("DEEPDFA_WATCHDOG_INTERVAL", 600))
DEADLINE = float(os.environ.get("DEEPDFA_WATCHDOG_DEADLINE", 39600))
PROBE_TIMEOUT = float(os.environ.get("DEEPDFA_WATCHDOG_PROBE_TIMEOUT", 240))
CAPTURE_COOLDOWN = float(os.environ.get("DEEPDFA_WATCHDOG_COOLDOWN", 3600))
LOG_PATH = os.path.join(REPO, "docs", "tpu_poll_log.jsonl")


def utcnow() -> str:
    return datetime.datetime.now(datetime.timezone.utc).strftime(
        "%Y-%m-%dT%H:%M:%SZ"
    )


def log_poll(record: dict) -> None:
    os.makedirs(os.path.dirname(LOG_PATH), exist_ok=True)
    with open(LOG_PATH, "a") as f:
        f.write(json.dumps(record) + "\n")
    print(json.dumps(record), flush=True)


def probe() -> tuple[bool, str, float]:
    """One bounded health probe of the DEFAULT backend; (ok, detail, secs)."""
    from deepdfa_tpu.core.backend import probe_default_backend

    t0 = time.time()
    ok, detail = probe_default_backend(PROBE_TIMEOUT, use_cache=False)
    return ok, detail, time.time() - t0


def last_json_line(text: str) -> dict | None:
    for line in reversed(text.strip().splitlines()):
        try:
            return json.loads(line)
        except (json.JSONDecodeError, ValueError):
            continue
    return None


def run_bench_suite(platform: str) -> dict:
    """Fire the full bench suite against the healthy backend; return the
    combined record. Each piece is bounded so one wedge cannot eat the
    window for the others."""
    record: dict = {
        "captured_at": utcnow(),
        "probe_platform": platform,
        "watchdog": True,
    }

    env = dict(os.environ)
    env.pop("DEEPDFA_TPU_PLATFORM", None)  # bench must see the default backend
    env["DEEPDFA_BENCH_TOTAL_BUDGET"] = env.get(
        "DEEPDFA_BENCH_TOTAL_BUDGET", "2400"
    )

    # cheap first: validate every flash-attention kernel path on the
    # chip (scripts/flash_tpu_check.py) so a window that dies mid-bench
    # still leaves the lowering/PRNG evidence
    try:
        res = subprocess.run(
            [sys.executable,
             os.path.join(REPO, "scripts", "flash_tpu_check.py")],
            capture_output=True, text=True, timeout=900, env=env, cwd=REPO,
        )
        fc = last_json_line(res.stdout)
        if fc is not None:
            record["flash_paths"] = fc
        else:
            record["flash_paths_error"] = (res.stderr or res.stdout)[-400:]
    except subprocess.TimeoutExpired:
        record["flash_paths_error"] = "flash_tpu_check.py exceeded 900s"

    try:
        res = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py")],
            capture_output=True, text=True, timeout=2700, env=env, cwd=REPO,
        )
        main_rec = last_json_line(res.stdout)
        if main_rec is not None:
            record["bench"] = main_rec
        else:
            record["bench_error"] = (res.stderr or res.stdout)[-500:]
    except subprocess.TimeoutExpired:
        record["bench_error"] = "bench.py exceeded 2700s"

    # both combined architectures: roberta (LineVul-style headline) and
    # t5 (CodeT5-style, exercises the flash kernel's bias operand)
    for arch, key, budget in (
        ("roberta", "bench_combined", 2400),
        ("t5", "bench_combined_t5", 1800),
    ):
        combined_out = os.path.join(
            REPO, "docs",
            "bench_combined_tpu.json" if arch == "roberta"
            else "bench_combined_t5_tpu.json",
        )
        launched_at = time.time()
        try:
            res = subprocess.run(
                [
                    sys.executable,
                    os.path.join(REPO, "scripts", "bench_combined.py"),
                    "--arch", arch, "--out", combined_out,
                ],
                capture_output=True, text=True, timeout=budget, env=env,
                cwd=REPO,
            )
            if res.returncode == 0 and os.path.exists(combined_out):
                with open(combined_out) as f:
                    record[key] = json.load(f)
            else:
                record[f"{key}_error"] = (res.stderr or res.stdout)[-500:]
                _load_partial(record, key, combined_out, launched_at)
        except subprocess.TimeoutExpired:
            record[f"{key}_error"] = f"bench_combined.py {arch} exceeded {budget}s"
            # the sweep checkpoints its out-file after every variant, so
            # a budget kill mid-sweep still leaves measured variants
            _load_partial(record, key, combined_out, launched_at)

    # inference + localization timings (the Table 5 15.4 ms/ex row and
    # the explanation-path cost) — cheap, forward-dominated
    loc_out = os.path.join(REPO, "docs", "bench_localize_tpu.json")
    try:
        res = subprocess.run(
            [sys.executable,
             os.path.join(REPO, "scripts", "bench_localize.py"),
             "--out", loc_out],
            capture_output=True, text=True, timeout=1200, env=env, cwd=REPO,
        )
        if res.returncode == 0 and os.path.exists(loc_out):
            with open(loc_out) as f:
                record["bench_localize"] = json.load(f)
        else:
            record["bench_localize_error"] = (res.stderr or res.stdout)[-400:]
    except subprocess.TimeoutExpired:
        record["bench_localize_error"] = "bench_localize.py exceeded 1200s"

    # gen-path A/B (seq2seq encoder+decoder step — the decoder flash
    # extensions' workload); bounded small since it has no baseline row
    gen_out = os.path.join(REPO, "docs", "bench_gen_tpu.json")
    try:
        res = subprocess.run(
            [sys.executable, os.path.join(REPO, "scripts", "bench_gen.py"),
             "--out", gen_out],
            capture_output=True, text=True, timeout=900, env=env, cwd=REPO,
        )
        if res.returncode == 0 and os.path.exists(gen_out):
            with open(gen_out) as f:
                record["bench_gen"] = json.load(f)
        else:
            record["bench_gen_error"] = (res.stderr or res.stdout)[-400:]
    except subprocess.TimeoutExpired:
        record["bench_gen_error"] = "bench_gen.py exceeded 900s"

    # LAST (the recurring headline captures above take priority in a
    # volatile window): one-shot flash-vs-xla loss-descent A/B. Skip only
    # when a COMPLETE TPU record exists — a degraded/partial file (the
    # script refuses to write non-TPU ones) or none at all retries.
    descent_out = os.path.join(REPO, "docs", "train_descent_ab.json")
    if not _descent_record_complete(descent_out):
        try:
            res = subprocess.run(
                [sys.executable,
                 os.path.join(REPO, "scripts", "train_descent_ab.py"),
                 "--out", descent_out],
                capture_output=True, text=True, timeout=1800, env=env,
                cwd=REPO,
            )
            if res.returncode == 0 and _descent_record_complete(descent_out):
                record["train_descent_ab"] = "captured"
            else:
                record["train_descent_ab_error"] = (
                    res.stderr or res.stdout)[-400:]
        except subprocess.TimeoutExpired:
            record["train_descent_ab_error"] = "exceeded 1800s"
    return record


def _load_partial(
    record: dict, key: str, path: str, launched_at: float
) -> None:
    """Fold a partial (checkpointed) sweep out-file into the record.

    Only a file the just-killed child actually wrote counts: the mtime
    must postdate the child's launch, and the 'partial' flag
    distinguishes a checkpoint from a completed record — without both
    guards a prior window's committed artifact could be resurrected as
    this window's evidence (the prior artifact itself is left on disk
    untouched)."""
    try:
        if os.path.getmtime(path) < launched_at - 1.0:
            return  # prior window's file: the child never wrote
        with open(path) as f:
            partial = json.load(f)
        if isinstance(partial, dict) and partial.get("partial") \
                and partial.get("variants"):
            record[f"{key}_partial"] = partial
    except (OSError, ValueError):
        pass


def _descent_record_complete(path: str) -> bool:
    """True when the committed descent A/B already holds a real TPU
    flash-vs-xla comparison (then re-running adds nothing)."""
    try:
        with open(path) as f:
            rec = json.load(f)
        return rec.get("platform") == "tpu" and "flash" in rec.get("runs", {})
    except (OSError, ValueError):
        return False


def commit_artifacts(paths: list[str], message: str) -> None:
    # a missing path (e.g. an arch bench that never produced its file)
    # must not abort the git add for everything else
    paths = [p for p in paths if os.path.exists(p)]
    if not paths:
        return
    try:
        subprocess.run(["git", "add", *paths], cwd=REPO, check=True)
        subprocess.run(
            ["git", "commit", "-m", message, "--", *paths],
            cwd=REPO, check=True, capture_output=True, text=True,
        )
    except subprocess.CalledProcessError as e:
        print(f"watchdog commit failed: {e.stderr or e}", file=sys.stderr)


def main() -> None:
    oneshot = os.environ.get("DEEPDFA_WATCHDOG_ONESHOT") == "1"
    t_end = time.time() + DEADLINE
    print(
        f"tpu_watchdog: interval={POLL_INTERVAL:.0f}s "
        f"probe_timeout={PROBE_TIMEOUT:.0f}s "
        f"deadline={DEADLINE / 3600:.1f}h",
        flush=True,
    )
    while True:
        t0 = time.time()
        ok, detail, elapsed = probe()
        healthy = ok and detail not in ("cpu", "unknown")
        log_poll(
            {
                "ts": utcnow(),
                "ok": ok,
                "platform_or_error": detail,
                "probe_seconds": round(elapsed, 1),
                "healthy_accelerator": healthy,
            }
        )
        if healthy:
            stamp = utcnow().replace(":", "").replace("-", "")
            out = os.path.join(REPO, f"BENCH_TPU_{stamp}.json")
            record = run_bench_suite(detail)
            with open(out, "w") as f:
                json.dump(record, f, indent=1)
            log_poll(
                {
                    "ts": utcnow(),
                    "event": "bench_captured",
                    "artifact": os.path.basename(out),
                    "value": record.get("bench", {}).get("value"),
                    "platform": record.get("bench", {}).get("platform"),
                }
            )
            commit_artifacts(
                [
                    out,
                    LOG_PATH,
                    os.path.join(REPO, "docs", "tpu_watchdog.out"),
                    os.path.join(REPO, "docs", "bench_combined_tpu.json"),
                    os.path.join(REPO, "docs", "bench_combined_t5_tpu.json"),
                    os.path.join(REPO, "docs", "bench_gen_tpu.json"),
                    os.path.join(REPO, "docs", "bench_localize_tpu.json"),
                    os.path.join(REPO, "docs", "train_descent_ab.json"),
                ],
                "Capture TPU bench from watchdog healthy-window "
                f"({os.path.basename(out)})",
            )
            if record.get("bench", {}).get("platform") == "tpu":
                if os.environ.get("DEEPDFA_WATCHDOG_EXIT_ON_CAPTURE") == "1":
                    print("tpu_watchdog: TPU record captured; exiting",
                          flush=True)
                    return
                # keep polling: later windows can be faster (the tunnel
                # chip is time-shared; window-to-window variance is
                # several-fold) and each capture strictly adds evidence.
                # Cool down so captures don't monopolize shared chip time
                # — but never sleep past the deadline or a oneshot exit.
                if oneshot or time.time() + CAPTURE_COOLDOWN > t_end:
                    return
                print(
                    "tpu_watchdog: TPU record captured; cooling down "
                    f"{CAPTURE_COOLDOWN:.0f}s then resuming polls", flush=True,
                )
                time.sleep(CAPTURE_COOLDOWN)
        if oneshot or time.time() > t_end:
            return
        time.sleep(max(0.0, POLL_INTERVAL - (time.time() - t0)))


if __name__ == "__main__":
    main()
