#!/usr/bin/env bash
#SBATCH --job-name=deepdfa-train
#SBATCH --cpus-per-task=8
#SBATCH --mem=32G
#SBATCH --time=12:00:00
#SBATCH --output=logs/train_%j.out
# Single-node training job — the role of the reference's scripts/sbatch.sh
# wrapper around train.sh. On a TPU pod slice, launch one task per host
# (e.g. --ntasks-per-node=1 over the slice's hosts); parallel/mesh.py's
# multi-host init picks up the JAX distributed environment automatically.
#
# Usage: sbatch scripts/sbatch_train.sh <cli-subcommand> [args...]
#   e.g. sbatch scripts/sbatch_train.sh train train.max_epochs=25
set -euo pipefail
cd "$(dirname "$0")/.."

python -m deepdfa_tpu.cli "$@"
