#!/usr/bin/env python
"""Autotuner search benchmark (deepdfa_tpu/tune/, docs/tuning.md).

Runs one REAL reduced search pass — kernel candidates compiled and
timed under the PR-8 numerics contract, the skewed-distribution ladder
fit, the lognormal seq-bucket fit — and stamps the fields the bench
gate reads (obs/bench_gate.py):

  tuned_ggnn_step_us          winner layout's measured per-step time
                              (lower is better, tol 0.25)
  tuned_ladder_padding_waste  fitted ladder's expected padded-compute
                              fraction on the skewed smoke distribution
                              (lower is better, tol 0.10)
  tune_search_seconds         search wall time (ABSOLUTE bound — the
                              search must stay a bounded offline pass)

    python scripts/bench_tune.py --smoke     # tier-1 regression mode
    DEEPDFA_TPU_PLATFORM=cpu python scripts/bench_tune.py
"""

from __future__ import annotations

import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def bench_tune(smoke: bool = False) -> dict:
    """One search pass into a scratch tuned.json; the bench record."""
    from deepdfa_tpu.tune import cache as tune_cache
    from deepdfa_tpu.tune import driver as tune_driver

    import jax

    platform = jax.devices()[0].platform
    with tempfile.TemporaryDirectory(prefix="bench-tune-") as d:
        out = os.path.join(d, "tuned.json")
        # the reduced search is the measured unit on every platform:
        # the full-budget search is an operator action with its own
        # compile budget, not a per-round bench
        report = tune_driver.run_tune_smoke(
            out_path=out, reps=2 if smoke else 3
        )
        verdict = tune_cache.validate_tuned_file(out)
    rec = {
        "metric": "tuned_ggnn_step_us",
        "unit": "us/step (winning tuned layout, smoke signature)",
        "value": report.get("tuned_ggnn_step_us"),
        "platform": platform,
        "tuned_ggnn_step_us": report.get("tuned_ggnn_step_us"),
        "tuned_lax_step_us": report.get("lax_step_us"),
        "tuned_winner": report.get("winner"),
        "tuned_candidates_timed": report.get("candidates_timed"),
        "tuned_candidates_rejected": report.get("candidates_rejected"),
        "tuned_ladder_padding_waste": report.get(
            "tuned_ladder_padding_waste"
        ),
        "tuned_pow2_ladder_padding_waste": report.get(
            "pow2_ladder_padding_waste"
        ),
        "tuned_seq_bucket_padding_waste": report.get(
            "seq_bucket_padding_waste"
        ),
        "tune_search_seconds": report.get("tune_search_seconds"),
        "tuned_valid": bool(verdict.get("ok")),
    }
    from deepdfa_tpu.obs import run_stamp

    rec.update(run_stamp())
    return rec


def run_smoke() -> dict:
    """Tier-1 regression mode (the bench_scatter convention): the
    search must complete, validate, pick a winner under the numerics
    contract, and the fitted ladder must strictly beat pow2."""
    rec = bench_tune(smoke=True)
    if not rec["tuned_valid"]:
        raise AssertionError(f"tuned.json failed validation: {rec}")
    if not rec["tuned_winner"] or not rec["tuned_ggnn_step_us"]:
        raise AssertionError(f"no measured winner: {rec}")
    if not (
        rec["tuned_ladder_padding_waste"]
        < rec["tuned_pow2_ladder_padding_waste"]
    ):
        raise AssertionError(
            f"ladder fit did not beat pow2: {rec}"
        )
    print(json.dumps(rec))
    return rec


def main(argv=None):
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true")
    opts = ap.parse_args(argv)

    from deepdfa_tpu.core.backend import apply_platform_override

    apply_platform_override()
    if opts.smoke:
        run_smoke()
        return
    print(json.dumps(bench_tune()))


if __name__ == "__main__":
    main()
