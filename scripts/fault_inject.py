#!/usr/bin/env python
"""Fault-injection scenarios for the resilience runtime
(train/resilience.py, docs/resilience.md).

Two modes:

``--smoke`` (tier-1, tests/test_fault_inject.py; well under a minute):
one process drives the REAL runtime end-to-end — a real SIGTERM through
the installed PreemptionHandler mid-train, checkpoint, resume, and a
bit-identical merged loss trajectory vs an uninterrupted run; a
packed-cache shard truncated the way a killed writer leaves it is
detected by digest verification, quarantined, and transparently
repacked; NaN-poisoned batches are skipped on device by the divergence
guard with params staying finite.

Default (full) mode: the same failure modes against the CLI in
SUBPROCESSES — `python -m deepdfa_tpu.cli train` over a synthetic corpus
in temp storage, asserting the process-level contracts: exit code 143
(EXIT_PREEMPTED) + resume manifest on SIGTERM with auto-resume on
re-run, survival of a truncated cache shard, skipped_steps in the epoch
records, and the watchdog's exit 113 + stage-attributed diagnostic on a
stalled producer. Each CLI subprocess pays ~40 s of interpreter+import
start-up on this box, which is why the sub-minute lane is in-process.

Prints one JSON verdict line; exit 0 iff every scenario passed.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))


# ---------------------------------------------------------------------------
# in-process scenarios (the --smoke lane)


def _tiny_setup(n_examples: int):
    """Tiny flagship-shaped trainer + deterministic batch stream."""
    import jax

    from deepdfa_tpu.core import Config, MeshConfig, config as config_mod
    from deepdfa_tpu.data import flagship_corpus
    from deepdfa_tpu.graphs import shard_bucket_batches
    from deepdfa_tpu.models import DeepDFA
    from deepdfa_tpu.parallel import make_mesh

    specs = flagship_corpus(n_examples)
    cfg = config_mod.apply_overrides(Config(), [
        "model.hidden_dim=8",
        "model.n_steps=2",
        "train.max_epochs=2",
        "train.prefetch_batches=0",  # exact fault step alignment
        "train.log_every_steps=1",
        'train.resilience={"enabled": true, "step_checkpoint_every": 2}',
    ])
    model = DeepDFA.from_config(cfg.model, input_dim=1002)
    mesh = make_mesh(MeshConfig(dp=1), devices=jax.devices()[:1])

    def batches(_epoch):
        return list(shard_bucket_batches(
            specs, num_shards=1, num_graphs=4, node_budget=2048,
            edge_budget=8192, oversized="drop",
        ))

    return cfg, model, mesh, specs, batches


def _fit(cfg, model, mesh, batches, run_dir, injector=None):
    """One fit through a fresh trainer + ResilientRunner; returns
    (per-step (step, loss) list, runner, state-or-None, Preempted-or-None)."""
    from deepdfa_tpu.models import DeepDFA  # noqa: F401  (keeps jit fresh)
    from deepdfa_tpu.train import GraphTrainer, Preempted, ResilientRunner

    trainer = GraphTrainer(model, cfg, mesh=mesh)
    state = trainer.init_state(batches(0)[0])
    runner = ResilientRunner(
        cfg.train.resilience, run_dir, seed=cfg.train.seed
    )
    steps: list[tuple[int, float]] = []
    stream = (
        (lambda e: injector.wrap(batches(e)))
        if injector is not None
        else batches
    )
    try:
        state = trainer.fit(
            state, stream,
            log_fn=lambda r: steps.append((r["step"], r["loss"]))
            if "loss" in r else None,
            resilience=runner,
        )
        return steps, runner, state, None
    except Preempted as p:
        return steps, runner, None, p


def inproc_sigterm(setup, tmp) -> dict:
    """Real SIGTERM mid-train -> checkpoint; resume -> bit-identical
    merged step-loss trajectory vs the uninterrupted reference."""
    from deepdfa_tpu.testing.faults import FaultInjector, FaultPlan

    cfg, model, mesh, _, batches = setup
    ref_dir = Path(tmp) / "ref-ckpt"
    ref_steps, _, _, _ = _fit(cfg, model, mesh, batches, ref_dir)
    assert len(ref_steps) >= 8, f"reference too short: {len(ref_steps)}"
    kill_at = max(3, len(ref_steps) // 2)

    run_dir = Path(tmp) / "faulted-ckpt"
    injector = FaultInjector(FaultPlan(sigterm_at_step=kill_at))
    first, _, _, preempted = _fit(
        cfg, model, mesh, batches, run_dir, injector=injector
    )
    assert preempted is not None, "SIGTERM did not preempt the run"
    assert (run_dir / "resume.json").exists(), "no resume manifest"

    second, runner2, state, _ = _fit(cfg, model, mesh, batches, run_dir)
    assert runner2.resumed_from_step == kill_at, (
        runner2.resumed_from_step, kill_at,
    )
    merged = first + second
    assert merged == ref_steps, (
        f"trajectory diverged: merged[{len(merged)}] != ref[{len(ref_steps)}]"
    )
    return {
        "killed_at_step": kill_at,
        "resumed_from_step": runner2.resumed_from_step,
        "steps_compared": len(merged),
        "trajectory_identical": True,
    }


def inproc_corrupt_shard(setup, tmp) -> dict:
    """Truncated cache shard -> digest verify -> quarantine -> repack,
    with the recovered stream bit-identical to direct packing."""
    import dataclasses

    import numpy as np

    from deepdfa_tpu.data.packed_cache import (
        PackedBatchCache, cache_key, corpus_digest,
    )
    from deepdfa_tpu.testing.faults import truncate_cache_file

    _, _, _, specs, batches = setup
    root = Path(tmp) / "packed"
    cache = PackedBatchCache(root)
    key = cache_key({"harness": "fault-inject"}, corpus_digest(specs))
    direct = batches(0)
    list(cache.write_through(key, iter(direct)))
    damaged = truncate_cache_file(root, key)

    recovered = list(cache.get_or_pack(key, lambda: iter(batches(0))))
    assert len(recovered) == len(direct)
    for a, b in zip(recovered, direct):
        for f in dataclasses.fields(a):
            va, vb = getattr(a, f.name), getattr(b, f.name)
            if f.name == "num_graphs" or va is None:
                assert va == vb if f.name == "num_graphs" else vb is None
                continue
            np.testing.assert_array_equal(np.asarray(va), np.asarray(vb))
    quarantined = list((root / "quarantine").iterdir())
    assert quarantined, "corrupt entry was not quarantined"
    assert cache.has(key), "entry was not repacked"
    return {
        "damaged_file": damaged.name,
        "quarantined_entries": len(quarantined),
        "stream_identical_after_repack": True,
    }


def inproc_nan(setup, tmp) -> dict:
    """NaN batches are skipped on device; params stay finite."""
    import jax
    import numpy as np

    from deepdfa_tpu.testing.faults import FaultInjector, FaultPlan

    cfg, model, mesh, _, batches = setup
    injector = FaultInjector(FaultPlan(nan_at_steps=frozenset({2, 3})))
    _, runner, state, _ = _fit(
        cfg, model, mesh, batches, Path(tmp) / "nan-ckpt", injector=injector
    )
    assert runner.skipped_steps == 2, runner.skipped_steps
    leaves = jax.tree.leaves(jax.device_get(state.params))
    assert all(np.isfinite(x).all() for x in leaves), "params poisoned"
    return {"skipped_steps": runner.skipped_steps, "params_finite": True}


def inproc_flight(setup, tmp) -> dict:
    """Flight-recorder coverage through the DEEPDFA_FAULTS harness
    (ISSUE 10): sigterm@N, nan@N (driven to a guard ROLLBACK), and
    stall@N (watchdog fire) each leave a schema-valid postmortem.json
    naming its trigger — validated by the same checker
    `scripts/check_obs_schema.py --postmortem` runs."""
    import dataclasses

    from deepdfa_tpu.obs import flight as obs_flight
    from deepdfa_tpu.testing.faults import FaultInjector, FaultPlan
    from deepdfa_tpu.train import GraphTrainer, Preempted, ResilientRunner

    cfg, model, mesh, _, batches = setup
    out: dict = {}

    def drive(name, rcfg_overrides, plan, expect_trigger, on_stall=None):
        run_dir = Path(tmp) / f"flight-{name}"
        pm_path = run_dir / "postmortem.json"
        recorder = obs_flight.install(pm_path, max_steps=16, max_events=32)
        try:
            c = dataclasses.replace(
                cfg,
                train=dataclasses.replace(
                    cfg.train,
                    resilience=dataclasses.replace(
                        cfg.train.resilience, **rcfg_overrides
                    ),
                ),
            )
            trainer = GraphTrainer(model, c, mesh=mesh)
            state = trainer.init_state(batches(0)[0])
            runner = ResilientRunner(
                c.train.resilience, run_dir, seed=c.train.seed,
                on_stall=on_stall,
            )
            injector = FaultInjector(plan)
            try:
                trainer.fit(
                    state, lambda e: injector.wrap(batches(e)),
                    resilience=runner,
                )
            except Preempted:
                pass
            assert pm_path.exists(), f"{name}: no postmortem dumped"
            verdict = obs_flight.validate_postmortem_file(pm_path)
            assert verdict["ok"], f"{name}: invalid postmortem: {verdict}"
            assert verdict["trigger"] == expect_trigger, (
                name, verdict["trigger"], expect_trigger,
            )
            assert verdict["steps"] > 0, f"{name}: empty step ring"
            out[name] = {
                "trigger": verdict["trigger"],
                "steps": verdict["steps"],
                "events": verdict["events"],
                "valid": True,
            }
        finally:
            obs_flight.uninstall()
        return recorder

    # sigterm@N -> preemption checkpoint -> postmortem trigger "sigterm"
    drive(
        "sigterm", {}, FaultPlan(sigterm_at_step=4), "sigterm",
    )
    # nan@N,N+1 with max_consecutive_bad=2 -> the second consecutive bad
    # step forces a guard ROLLBACK -> trigger "nan_rollback" (guard_lag
    # 0 so flags are consumed in step order, deterministic)
    drive(
        "nan",
        {"max_consecutive_bad": 2, "guard_lag": 0,
         "step_checkpoint_every": 2},
        FaultPlan(nan_at_steps=frozenset({3, 4})),
        "nan_rollback",
    )
    # stall@N (bounded) with a tight watchdog -> the watchdog fires,
    # dumps "watchdog_abort", and a no-op on_stall lets the in-process
    # run continue once the stall releases (the real default aborts the
    # process with exit 113 AFTER the same dump)
    drive(
        "stall",
        {"watchdog_timeout_s": 1.0, "watchdog_first_step_grace_s": 6.0},
        FaultPlan(stall_at_step=3, stall_seconds=4.0),
        "watchdog_abort",
        on_stall=lambda diag: None,
    )
    return out


def inproc_mesh_sigterm(setup, tmp) -> dict:
    """ISSUE 13 (docs/sharding.md): a SIGTERM mid-train on the 8-device
    mesh still writes exactly ONE (process-0) postmortem + resume
    manifest. The drill runs in a subprocess because the smoke's own
    platform is pinned to one CPU device — the child opts into cpu:8
    (the conftest-style 8-virtual-device mesh) and runs the REAL
    runtime: dp=8 GraphTrainer over 8 logical shards, flight recorder
    installed, sigterm fault -> Preempted -> postmortem validated; a
    simulated non-primary process (jax.process_index=1) then proves the
    obs.session gate installs NOTHING."""
    out_dir = Path(tmp) / "mesh-postmortem"
    out_dir.mkdir(parents=True, exist_ok=True)
    env = dict(
        os.environ, DEEPDFA_TPU_PLATFORM="cpu:8", JAX_PLATFORMS="cpu",
    )
    env.pop("DEEPDFA_FAULTS", None)
    env.pop("JAX_COMPILATION_CACHE_DIR", None)
    res = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--mesh-child",
         str(out_dir)],
        capture_output=True, text=True, env=env, timeout=280,
        cwd=str(REPO),
    )
    assert res.returncode == 0, (res.stdout + res.stderr)[-2000:]
    out = json.loads(res.stdout.strip().splitlines()[-1])
    assert out["preempted"], out
    assert out["verdict"]["ok"], out
    assert out["verdict"]["trigger"] == "sigterm", out
    assert out["postmortems"] == 1, out
    assert out["secondary_install"] is False, out
    return {
        "mesh": out["mesh"],
        "trigger": out["verdict"]["trigger"],
        "postmortems": out["postmortems"],
        "secondary_install": out["secondary_install"],
        "valid": True,
    }


def mesh_child(out_dir: str) -> None:
    """--mesh-child body (run under DEEPDFA_TPU_PLATFORM=cpu:8)."""
    from deepdfa_tpu.core.backend import apply_platform_override

    os.environ.setdefault("DEEPDFA_TPU_PLATFORM", "cpu:8")
    apply_platform_override()
    import unittest.mock as mock

    import jax

    from deepdfa_tpu import obs
    from deepdfa_tpu.core import Config, MeshConfig, config as config_mod
    from deepdfa_tpu.data import build_dataset, generate, to_examples
    from deepdfa_tpu.graphs import shard_bucket_batches
    from deepdfa_tpu.models import DeepDFA
    from deepdfa_tpu.obs import flight as obs_flight
    from deepdfa_tpu.parallel import make_mesh, sharding
    from deepdfa_tpu.testing.faults import FaultInjector, FaultPlan
    from deepdfa_tpu.train import GraphTrainer, Preempted, ResilientRunner

    assert len(jax.devices()) == 8, jax.devices()
    run_dir = Path(out_dir)
    synth = generate(32, seed=3)
    specs, _ = build_dataset(
        to_examples(synth), train_ids=range(32), limit_all=50,
        limit_subkeys=50,
    )
    cfg = config_mod.apply_overrides(Config(), [
        "model.hidden_dim=8",
        "model.n_steps=2",
        "train.max_epochs=2",
        "train.prefetch_batches=0",
        'train.resilience={"enabled": true, "step_checkpoint_every": 2}',
    ])
    model = DeepDFA.from_config(cfg.model, input_dim=52)
    mesh = make_mesh(MeshConfig(dp=8))

    def batches(_epoch):
        return list(shard_bucket_batches(
            specs, num_shards=8, num_graphs=1, node_budget=1024,
            edge_budget=4096, oversized="drop",
        ))

    pm_path = run_dir / "postmortem.json"
    obs_flight.install(pm_path, max_steps=16, max_events=32)
    preempted = False
    try:
        trainer = GraphTrainer(model, cfg, mesh=mesh)
        state = trainer.init_state(batches(0)[0])
        runner = ResilientRunner(
            cfg.train.resilience, run_dir / "ckpt", seed=cfg.train.seed
        )
        injector = FaultInjector(FaultPlan(sigterm_at_step=3))
        try:
            trainer.fit(
                state, lambda e: injector.wrap(batches(e)),
                resilience=runner,
            )
        except Preempted:
            preempted = True
    finally:
        obs_flight.uninstall()
    verdict = obs_flight.validate_postmortem_file(pm_path)
    # the process-0 contract: a non-primary host's obs.session installs
    # no flight recorder (and so can never write a competing postmortem)
    ocfg = config_mod.apply_overrides(cfg, ["obs.flight=true"])
    with mock.patch.object(jax, "process_index", return_value=1):
        with obs.session(ocfg, run_dir / "secondary"):
            secondary_install = obs_flight.installed()
    print(json.dumps({
        "preempted": preempted,
        "verdict": verdict,
        "postmortems": len(list(run_dir.glob("postmortem*.json"))),
        "resume_manifest": (run_dir / "ckpt" / "resume.json").exists(),
        "secondary_install": secondary_install,
        "mesh": sharding.mesh_record(mesh, 8),
    }))


def run_smoke(n_examples: int) -> dict:
    from deepdfa_tpu.core.backend import apply_platform_override

    os.environ.setdefault("DEEPDFA_TPU_PLATFORM", "cpu")
    apply_platform_override()
    record: dict = {"mode": "inproc", "scenarios": {}, "ok": True}
    scenarios = {
        "sigterm": inproc_sigterm,
        "corrupt-shard": inproc_corrupt_shard,
        "nan": inproc_nan,
        "flight": inproc_flight,
        "mesh-sigterm": inproc_mesh_sigterm,
    }
    with tempfile.TemporaryDirectory(prefix="fault-inject-") as tmp:
        t0 = time.perf_counter()
        setup = _tiny_setup(n_examples)
        record["setup_seconds"] = round(time.perf_counter() - t0, 1)
        for name, fn in scenarios.items():
            t0 = time.perf_counter()
            try:
                out = fn(setup, tmp)
                out["seconds"] = round(time.perf_counter() - t0, 1)
                record["scenarios"][name] = out
            except (AssertionError, RuntimeError) as e:
                record["ok"] = False
                record["scenarios"][name] = {
                    "error": f"{type(e).__name__}: {e}"[:2000],
                    "seconds": round(time.perf_counter() - t0, 1),
                }
    return record


# ---------------------------------------------------------------------------
# subprocess scenarios (full mode): process-level contracts

#: tiny flagship-shaped config: 1-device CPU, inline input pipeline
#: (prefetch 0 keeps fault step numbering exact), per-step logging,
#: undersampling off (the ~6% positive rate of the synthetic corpus
#: would shrink an undersampled epoch to a couple of batches), and the
#: resilience runtime on with a tight checkpoint cadence
BASE_OVERRIDES = [
    "model.hidden_dim=8",
    "model.n_steps=2",
    "data.undersample=false",
    "data.batch.graphs_per_batch=4",
    "data.batch.node_budget=512",
    "data.batch.edge_budget=2048",
    "train.max_epochs=2",
    "train.prefetch_batches=0",
    "train.log_every_steps=1",
    "train.eval_every_epochs=99",
    'train.resilience={"enabled": true, "step_checkpoint_every": 2}',
]


def run_cli(storage, *argv, faults=None, timeout=300):
    # deliberately NO shared XLA compile cache: a SIGTERM'd process can
    # die mid-cache-write and this jax version will segfault
    # deserializing the truncated entry — the harness must not inject
    # faults into itself
    env = dict(
        os.environ,
        DEEPDFA_TPU_STORAGE=str(storage),
        DEEPDFA_TPU_PLATFORM="cpu",
        JAX_PLATFORMS="cpu",
    )
    env.pop("DEEPDFA_FAULTS", None)
    env.pop("JAX_COMPILATION_CACHE_DIR", None)
    if faults:
        env["DEEPDFA_FAULTS"] = faults
    return subprocess.run(
        [sys.executable, "-m", "deepdfa_tpu.cli", *argv],
        capture_output=True, text=True, env=env, timeout=timeout,
        cwd=str(REPO),
    )


def prepare_corpus(storage, n=48) -> None:
    for argv in (
        ("prepare", "--source", "synthetic", "--n-examples", str(n)),
        ("extract",),
    ):
        res = run_cli(storage, *argv)
        if res.returncode != 0:
            raise RuntimeError(f"{argv[0]} failed:\n{res.stderr[-2000:]}")


def train(storage, run_name, *extra, faults=None, timeout=300):
    return run_cli(
        storage, "train", *BASE_OVERRIDES, f"run_name={run_name}", *extra,
        faults=faults, timeout=timeout,
    )


def read_log(storage, run_name):
    path = Path(storage) / "runs" / run_name / "train_log.jsonl"
    if not path.exists():
        return []
    return [json.loads(line) for line in path.read_text().splitlines()]


def step_losses(records):
    return [(r["step"], r["loss"]) for r in records if "loss" in r]


def scenario_sigterm(storage) -> dict:
    """Kill mid-epoch (exit 143 + manifest); the SAME command re-run
    resumes and reproduces the reference trajectory bit-for-bit."""
    from deepdfa_tpu.train.resilience import EXIT_PREEMPTED

    ref = train(storage, "ref")
    assert ref.returncode == 0, ref.stderr[-2000:]
    ref_losses = step_losses(read_log(storage, "ref"))
    assert len(ref_losses) >= 10, f"reference too short: {len(ref_losses)}"
    kill_at = max(3, len(ref_losses) // 2)

    first = train(storage, "faulted", faults=f"sigterm@{kill_at}")
    assert first.returncode == EXIT_PREEMPTED, (
        f"expected exit {EXIT_PREEMPTED}, got {first.returncode}: "
        f"{first.stderr[-2000:]}"
    )
    manifest = (
        Path(storage) / "runs" / "faulted" / "checkpoints-step" / "resume.json"
    )
    assert manifest.exists(), "no resume manifest after preemption"
    resumed_at = json.loads(manifest.read_text())["step"]

    second = train(storage, "faulted")
    assert second.returncode == 0, second.stderr[-2000:] or "(empty stderr)"
    records = read_log(storage, "faulted")
    merged = step_losses(records)
    assert merged == ref_losses, (
        f"trajectory diverged after resume: "
        f"{merged[:4]}... != {ref_losses[:4]}..."
    )
    assert any(r.get("resumed_from_step") for r in records), (
        "epoch records never reported resumed_from_step"
    )
    return {
        "killed_at_step": kill_at,
        "resumed_from_step": resumed_at,
        "steps_compared": len(merged),
        "trajectory_identical": True,
    }


def scenario_corrupt_shard(storage) -> dict:
    """Truncate a warm cache entry; the next run must quarantine+repack."""
    from deepdfa_tpu.data.packed_cache import PackedBatchCache
    from deepdfa_tpu.testing.faults import truncate_cache_file

    cache_overrides = (
        "data.packed_cache=true",
        "train.max_epochs=1",
    )
    warm = train(storage, "cache-a", *cache_overrides)
    assert warm.returncode == 0, warm.stderr[-2000:]
    cache_root = Path(storage) / "cache" / "bigvul" / "packed"
    damaged = truncate_cache_file(cache_root)

    rerun = train(storage, "cache-b", *cache_overrides)
    assert rerun.returncode == 0, (
        f"run died on the corrupt shard: {rerun.stderr[-2000:]}"
    )
    quarantine = cache_root / "quarantine"
    quarantined = list(quarantine.iterdir()) if quarantine.exists() else []
    assert quarantined, "corrupt entry was not quarantined"
    assert PackedBatchCache(cache_root).keys(), "no rebuilt entry on disk"
    return {
        "damaged_file": damaged.name,
        "quarantined_entries": len(quarantined),
        "repacked_and_completed": True,
    }


def scenario_nan(storage) -> dict:
    """Poisoned batches are skipped on device; the run self-reports."""
    res = train(storage, "nan", faults="nan@2,nan@3")
    assert res.returncode == 0, res.stderr[-2000:]
    records = read_log(storage, "nan")
    epochs = [r for r in records if "skipped_steps" in r]
    assert epochs, "no epoch records with skipped_steps"
    skipped = epochs[-1]["skipped_steps"]
    assert skipped == 2, f"expected 2 skipped steps, saw {skipped}"
    return {"skipped_steps": skipped, "completed": True}


def scenario_stall(storage) -> dict:
    """A stalled producer trips the watchdog's stage-attributed abort."""
    from deepdfa_tpu.train.resilience import EXIT_WATCHDOG

    res = train(
        storage, "stall",
        'train.resilience={"enabled": true, "watchdog_timeout_s": 3}',
        faults="stall@3",
        timeout=180,
    )
    assert res.returncode == EXIT_WATCHDOG, (
        f"expected watchdog exit {EXIT_WATCHDOG}, got {res.returncode}"
    )
    diag_path = (
        Path(storage) / "runs" / "stall" / "checkpoints-step"
        / "watchdog_diagnostic.json"
    )
    assert diag_path.exists(), "no watchdog diagnostic written"
    diag = json.loads(diag_path.read_text())
    assert diag["stalled_stage"] == "input", diag
    return {"stalled_stage": diag["stalled_stage"], "aborted": True}


SCENARIOS = {
    "sigterm": scenario_sigterm,
    "corrupt-shard": scenario_corrupt_shard,
    "nan": scenario_nan,
    "stall": scenario_stall,
}


def run_full(names, n_examples: int) -> dict:
    record: dict = {"mode": "subprocess", "scenarios": {}, "ok": True}
    with tempfile.TemporaryDirectory(prefix="fault-inject-") as storage:
        t0 = time.perf_counter()
        prepare_corpus(storage, n=n_examples)
        record["prepare_seconds"] = round(time.perf_counter() - t0, 1)

        def run_one(name):
            t0 = time.perf_counter()
            try:
                out = SCENARIOS[name](storage)
                out["seconds"] = round(time.perf_counter() - t0, 1)
                return name, out, True
            except (AssertionError, RuntimeError, subprocess.TimeoutExpired) as e:
                return name, {
                    "error": f"{type(e).__name__}: {e}"[:2000],
                    "seconds": round(time.perf_counter() - t0, 1),
                }, False

        # scenarios are independent chains of subprocesses over disjoint
        # run names — run them concurrently over the shared corpus
        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(max_workers=min(2, len(names))) as pool:
            for name, out, ok in pool.map(run_one, names):
                record["scenarios"][name] = out
                record["ok"] = record["ok"] and ok
    return record


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "--smoke", action="store_true",
        help="tier-1 in-process mode: sigterm + corrupt-shard + nan "
        "through the real runtime in one interpreter (<1 min)",
    )
    ap.add_argument(
        "--scenario", action="append", default=None,
        choices=sorted(SCENARIOS),
        help="full mode: run only the named subprocess scenario(s)",
    )
    ap.add_argument("--n-examples", type=int, default=48)
    ap.add_argument("--out", default=None)
    ap.add_argument(
        "--mesh-child", default=None, metavar="DIR",
        help="internal: the 8-device-mesh SIGTERM drill body "
        "(inproc_mesh_sigterm runs it under cpu:8)",
    )
    args = ap.parse_args()

    if args.mesh_child:
        mesh_child(args.mesh_child)
        return

    if args.smoke:
        record = run_smoke(args.n_examples)
    else:
        names = args.scenario if args.scenario else list(SCENARIOS)
        record = run_full(names, args.n_examples)
    record["smoke"] = args.smoke
    print(json.dumps(record), flush=True)
    if args.out:
        Path(args.out).write_text(json.dumps(record, indent=2))
    sys.exit(0 if record["ok"] else 1)


if __name__ == "__main__":
    main()
