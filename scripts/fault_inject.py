#!/usr/bin/env python
"""Fault-injection scenarios for the resilience runtime
(train/resilience.py, docs/resilience.md).

Two modes:

``--smoke`` (tier-1, tests/test_fault_inject.py; well under a minute):
one process drives the REAL runtime end-to-end — a real SIGTERM through
the installed PreemptionHandler mid-train, checkpoint, resume, and a
bit-identical merged loss trajectory vs an uninterrupted run; a
packed-cache shard truncated the way a killed writer leaves it is
detected by digest verification, quarantined, and transparently
repacked; NaN-poisoned batches are skipped on device by the divergence
guard with params staying finite.

Default (full) mode: the same failure modes against the CLI in
SUBPROCESSES — `python -m deepdfa_tpu.cli train` over a synthetic corpus
in temp storage, asserting the process-level contracts: exit code 143
(EXIT_PREEMPTED) + resume manifest on SIGTERM with auto-resume on
re-run, survival of a truncated cache shard, skipped_steps in the epoch
records, and the watchdog's exit 113 + stage-attributed diagnostic on a
stalled producer. Each CLI subprocess pays ~40 s of interpreter+import
start-up on this box, which is why the sub-minute lane is in-process.

Prints one JSON verdict line; exit 0 iff every scenario passed.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))


# ---------------------------------------------------------------------------
# in-process scenarios (the --smoke lane)


def _tiny_setup(n_examples: int):
    """Tiny flagship-shaped trainer + deterministic batch stream."""
    import jax

    from deepdfa_tpu.core import Config, MeshConfig, config as config_mod
    from deepdfa_tpu.data import flagship_corpus
    from deepdfa_tpu.graphs import shard_bucket_batches
    from deepdfa_tpu.models import DeepDFA
    from deepdfa_tpu.parallel import make_mesh

    specs = flagship_corpus(n_examples)
    cfg = config_mod.apply_overrides(Config(), [
        "model.hidden_dim=8",
        "model.n_steps=2",
        "train.max_epochs=2",
        "train.prefetch_batches=0",  # exact fault step alignment
        "train.log_every_steps=1",
        'train.resilience={"enabled": true, "step_checkpoint_every": 2}',
    ])
    model = DeepDFA.from_config(cfg.model, input_dim=1002)
    mesh = make_mesh(MeshConfig(dp=1), devices=jax.devices()[:1])

    def batches(_epoch):
        return list(shard_bucket_batches(
            specs, num_shards=1, num_graphs=4, node_budget=2048,
            edge_budget=8192, oversized="drop",
        ))

    return cfg, model, mesh, specs, batches


def _fit(cfg, model, mesh, batches, run_dir, injector=None):
    """One fit through a fresh trainer + ResilientRunner; returns
    (per-step (step, loss) list, runner, state-or-None, Preempted-or-None)."""
    from deepdfa_tpu.models import DeepDFA  # noqa: F401  (keeps jit fresh)
    from deepdfa_tpu.train import GraphTrainer, Preempted, ResilientRunner

    trainer = GraphTrainer(model, cfg, mesh=mesh)
    state = trainer.init_state(batches(0)[0])
    runner = ResilientRunner(
        cfg.train.resilience, run_dir, seed=cfg.train.seed
    )
    steps: list[tuple[int, float]] = []
    stream = (
        (lambda e: injector.wrap(batches(e)))
        if injector is not None
        else batches
    )
    try:
        state = trainer.fit(
            state, stream,
            log_fn=lambda r: steps.append((r["step"], r["loss"]))
            if "loss" in r else None,
            resilience=runner,
        )
        return steps, runner, state, None
    except Preempted as p:
        return steps, runner, None, p


def inproc_sigterm(setup, tmp) -> dict:
    """Real SIGTERM mid-train -> checkpoint; resume -> bit-identical
    merged step-loss trajectory vs the uninterrupted reference."""
    from deepdfa_tpu.testing.faults import FaultInjector, FaultPlan

    cfg, model, mesh, _, batches = setup
    ref_dir = Path(tmp) / "ref-ckpt"
    ref_steps, _, _, _ = _fit(cfg, model, mesh, batches, ref_dir)
    assert len(ref_steps) >= 8, f"reference too short: {len(ref_steps)}"
    kill_at = max(3, len(ref_steps) // 2)

    run_dir = Path(tmp) / "faulted-ckpt"
    injector = FaultInjector(FaultPlan(sigterm_at_step=kill_at))
    first, _, _, preempted = _fit(
        cfg, model, mesh, batches, run_dir, injector=injector
    )
    assert preempted is not None, "SIGTERM did not preempt the run"
    assert (run_dir / "resume.json").exists(), "no resume manifest"

    second, runner2, state, _ = _fit(cfg, model, mesh, batches, run_dir)
    assert runner2.resumed_from_step == kill_at, (
        runner2.resumed_from_step, kill_at,
    )
    merged = first + second
    assert merged == ref_steps, (
        f"trajectory diverged: merged[{len(merged)}] != ref[{len(ref_steps)}]"
    )
    return {
        "killed_at_step": kill_at,
        "resumed_from_step": runner2.resumed_from_step,
        "steps_compared": len(merged),
        "trajectory_identical": True,
    }


def inproc_corrupt_shard(setup, tmp) -> dict:
    """Truncated cache shard -> digest verify -> quarantine -> repack,
    with the recovered stream bit-identical to direct packing."""
    import dataclasses

    import numpy as np

    from deepdfa_tpu.data.packed_cache import (
        PackedBatchCache, cache_key, corpus_digest,
    )
    from deepdfa_tpu.testing.faults import truncate_cache_file

    _, _, _, specs, batches = setup
    root = Path(tmp) / "packed"
    cache = PackedBatchCache(root)
    key = cache_key({"harness": "fault-inject"}, corpus_digest(specs))
    direct = batches(0)
    list(cache.write_through(key, iter(direct)))
    damaged = truncate_cache_file(root, key)

    recovered = list(cache.get_or_pack(key, lambda: iter(batches(0))))
    assert len(recovered) == len(direct)
    for a, b in zip(recovered, direct):
        for f in dataclasses.fields(a):
            va, vb = getattr(a, f.name), getattr(b, f.name)
            if f.name == "num_graphs" or va is None:
                assert va == vb if f.name == "num_graphs" else vb is None
                continue
            np.testing.assert_array_equal(np.asarray(va), np.asarray(vb))
    quarantined = list((root / "quarantine").iterdir())
    assert quarantined, "corrupt entry was not quarantined"
    assert cache.has(key), "entry was not repacked"
    return {
        "damaged_file": damaged.name,
        "quarantined_entries": len(quarantined),
        "stream_identical_after_repack": True,
    }


def inproc_nan(setup, tmp) -> dict:
    """NaN batches are skipped on device; params stay finite."""
    import jax
    import numpy as np

    from deepdfa_tpu.testing.faults import FaultInjector, FaultPlan

    cfg, model, mesh, _, batches = setup
    injector = FaultInjector(FaultPlan(nan_at_steps=frozenset({2, 3})))
    _, runner, state, _ = _fit(
        cfg, model, mesh, batches, Path(tmp) / "nan-ckpt", injector=injector
    )
    assert runner.skipped_steps == 2, runner.skipped_steps
    leaves = jax.tree.leaves(jax.device_get(state.params))
    assert all(np.isfinite(x).all() for x in leaves), "params poisoned"
    return {"skipped_steps": runner.skipped_steps, "params_finite": True}


def inproc_flight(setup, tmp) -> dict:
    """Flight-recorder coverage through the DEEPDFA_FAULTS harness
    (ISSUE 10): sigterm@N, nan@N (driven to a guard ROLLBACK), and
    stall@N (watchdog fire) each leave a schema-valid postmortem.json
    naming its trigger — validated by the same checker
    `scripts/check_obs_schema.py --postmortem` runs."""
    import dataclasses

    from deepdfa_tpu.obs import flight as obs_flight
    from deepdfa_tpu.testing.faults import FaultInjector, FaultPlan
    from deepdfa_tpu.train import GraphTrainer, Preempted, ResilientRunner

    cfg, model, mesh, _, batches = setup
    out: dict = {}

    def drive(name, rcfg_overrides, plan, expect_trigger, on_stall=None):
        run_dir = Path(tmp) / f"flight-{name}"
        pm_path = run_dir / "postmortem.json"
        recorder = obs_flight.install(pm_path, max_steps=16, max_events=32)
        try:
            c = dataclasses.replace(
                cfg,
                train=dataclasses.replace(
                    cfg.train,
                    resilience=dataclasses.replace(
                        cfg.train.resilience, **rcfg_overrides
                    ),
                ),
            )
            trainer = GraphTrainer(model, c, mesh=mesh)
            state = trainer.init_state(batches(0)[0])
            runner = ResilientRunner(
                c.train.resilience, run_dir, seed=c.train.seed,
                on_stall=on_stall,
            )
            injector = FaultInjector(plan)
            try:
                trainer.fit(
                    state, lambda e: injector.wrap(batches(e)),
                    resilience=runner,
                )
            except Preempted:
                pass
            assert pm_path.exists(), f"{name}: no postmortem dumped"
            verdict = obs_flight.validate_postmortem_file(pm_path)
            assert verdict["ok"], f"{name}: invalid postmortem: {verdict}"
            assert verdict["trigger"] == expect_trigger, (
                name, verdict["trigger"], expect_trigger,
            )
            assert verdict["steps"] > 0, f"{name}: empty step ring"
            out[name] = {
                "trigger": verdict["trigger"],
                "steps": verdict["steps"],
                "events": verdict["events"],
                "valid": True,
            }
        finally:
            obs_flight.uninstall()
        return recorder

    # sigterm@N -> preemption checkpoint -> postmortem trigger "sigterm"
    drive(
        "sigterm", {}, FaultPlan(sigterm_at_step=4), "sigterm",
    )
    # nan@N,N+1 with max_consecutive_bad=2 -> the second consecutive bad
    # step forces a guard ROLLBACK -> trigger "nan_rollback" (guard_lag
    # 0 so flags are consumed in step order, deterministic)
    drive(
        "nan",
        {"max_consecutive_bad": 2, "guard_lag": 0,
         "step_checkpoint_every": 2},
        FaultPlan(nan_at_steps=frozenset({3, 4})),
        "nan_rollback",
    )
    # stall@N (bounded) with a tight watchdog -> the watchdog fires,
    # dumps "watchdog_abort", and a no-op on_stall lets the in-process
    # run continue once the stall releases (the real default aborts the
    # process with exit 113 AFTER the same dump)
    drive(
        "stall",
        {"watchdog_timeout_s": 1.0, "watchdog_first_step_grace_s": 6.0},
        FaultPlan(stall_at_step=3, stall_seconds=4.0),
        "watchdog_abort",
        on_stall=lambda diag: None,
    )
    return out


def inproc_mesh_sigterm(setup, tmp) -> dict:
    """ISSUE 13 (docs/sharding.md): a SIGTERM mid-train on the 8-device
    mesh still writes exactly ONE (process-0) postmortem + resume
    manifest. The drill runs in a subprocess because the smoke's own
    platform is pinned to one CPU device — the child opts into cpu:8
    (the conftest-style 8-virtual-device mesh) and runs the REAL
    runtime: dp=8 GraphTrainer over 8 logical shards, flight recorder
    installed, sigterm fault -> Preempted -> postmortem validated; a
    simulated non-primary process (jax.process_index=1) then proves the
    obs.session gate installs NOTHING."""
    out_dir = Path(tmp) / "mesh-postmortem"
    out_dir.mkdir(parents=True, exist_ok=True)
    env = dict(
        os.environ, DEEPDFA_TPU_PLATFORM="cpu:8", JAX_PLATFORMS="cpu",
    )
    env.pop("DEEPDFA_FAULTS", None)
    env.pop("JAX_COMPILATION_CACHE_DIR", None)
    res = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--mesh-child",
         str(out_dir)],
        capture_output=True, text=True, env=env, timeout=280,
        cwd=str(REPO),
    )
    assert res.returncode == 0, (res.stdout + res.stderr)[-2000:]
    out = json.loads(res.stdout.strip().splitlines()[-1])
    assert out["preempted"], out
    assert out["verdict"]["ok"], out
    assert out["verdict"]["trigger"] == "sigterm", out
    assert out["postmortems"] == 1, out
    assert out["secondary_install"] is False, out
    return {
        "mesh": out["mesh"],
        "trigger": out["verdict"]["trigger"],
        "postmortems": out["postmortems"],
        "secondary_install": out["secondary_install"],
        "valid": True,
    }


def mesh_child(out_dir: str) -> None:
    """--mesh-child body (run under DEEPDFA_TPU_PLATFORM=cpu:8)."""
    from deepdfa_tpu.core.backend import apply_platform_override

    os.environ.setdefault("DEEPDFA_TPU_PLATFORM", "cpu:8")
    apply_platform_override()
    import unittest.mock as mock

    import jax

    from deepdfa_tpu import obs
    from deepdfa_tpu.core import Config, MeshConfig, config as config_mod
    from deepdfa_tpu.data import build_dataset, generate, to_examples
    from deepdfa_tpu.graphs import shard_bucket_batches
    from deepdfa_tpu.models import DeepDFA
    from deepdfa_tpu.obs import flight as obs_flight
    from deepdfa_tpu.parallel import make_mesh, sharding
    from deepdfa_tpu.testing.faults import FaultInjector, FaultPlan
    from deepdfa_tpu.train import GraphTrainer, Preempted, ResilientRunner

    assert len(jax.devices()) == 8, jax.devices()
    run_dir = Path(out_dir)
    synth = generate(32, seed=3)
    specs, _ = build_dataset(
        to_examples(synth), train_ids=range(32), limit_all=50,
        limit_subkeys=50,
    )
    cfg = config_mod.apply_overrides(Config(), [
        "model.hidden_dim=8",
        "model.n_steps=2",
        "train.max_epochs=2",
        "train.prefetch_batches=0",
        'train.resilience={"enabled": true, "step_checkpoint_every": 2}',
    ])
    model = DeepDFA.from_config(cfg.model, input_dim=52)
    mesh = make_mesh(MeshConfig(dp=8))

    def batches(_epoch):
        return list(shard_bucket_batches(
            specs, num_shards=8, num_graphs=1, node_budget=1024,
            edge_budget=4096, oversized="drop",
        ))

    pm_path = run_dir / "postmortem.json"
    obs_flight.install(pm_path, max_steps=16, max_events=32)
    preempted = False
    try:
        trainer = GraphTrainer(model, cfg, mesh=mesh)
        state = trainer.init_state(batches(0)[0])
        runner = ResilientRunner(
            cfg.train.resilience, run_dir / "ckpt", seed=cfg.train.seed
        )
        injector = FaultInjector(FaultPlan(sigterm_at_step=3))
        try:
            trainer.fit(
                state, lambda e: injector.wrap(batches(e)),
                resilience=runner,
            )
        except Preempted:
            preempted = True
    finally:
        obs_flight.uninstall()
    verdict = obs_flight.validate_postmortem_file(pm_path)
    # the process-0 contract: a non-primary host's obs.session installs
    # no flight recorder (and so can never write a competing postmortem)
    ocfg = config_mod.apply_overrides(cfg, ["obs.flight=true"])
    with mock.patch.object(jax, "process_index", return_value=1):
        with obs.session(ocfg, run_dir / "secondary"):
            secondary_install = obs_flight.installed()
    print(json.dumps({
        "preempted": preempted,
        "verdict": verdict,
        "postmortems": len(list(run_dir.glob("postmortem*.json"))),
        "resume_manifest": (run_dir / "ckpt" / "resume.json").exists(),
        "secondary_install": secondary_install,
        "mesh": sharding.mesh_record(mesh, 8),
    }))


def run_smoke(n_examples: int) -> dict:
    from deepdfa_tpu.core.backend import apply_platform_override

    os.environ.setdefault("DEEPDFA_TPU_PLATFORM", "cpu")
    apply_platform_override()
    record: dict = {"mode": "inproc", "scenarios": {}, "ok": True}
    scenarios = {
        "sigterm": inproc_sigterm,
        "corrupt-shard": inproc_corrupt_shard,
        "nan": inproc_nan,
        "flight": inproc_flight,
        "mesh-sigterm": inproc_mesh_sigterm,
    }
    with tempfile.TemporaryDirectory(prefix="fault-inject-") as tmp:
        t0 = time.perf_counter()
        setup = _tiny_setup(n_examples)
        record["setup_seconds"] = round(time.perf_counter() - t0, 1)
        for name, fn in scenarios.items():
            t0 = time.perf_counter()
            try:
                out = fn(setup, tmp)
                out["seconds"] = round(time.perf_counter() - t0, 1)
                record["scenarios"][name] = out
            except (AssertionError, RuntimeError) as e:
                record["ok"] = False
                record["scenarios"][name] = {
                    "error": f"{type(e).__name__}: {e}"[:2000],
                    "seconds": round(time.perf_counter() - t0, 1),
                }
    return record


# ---------------------------------------------------------------------------
# subprocess scenarios (full mode): process-level contracts

#: tiny flagship-shaped config: 1-device CPU, inline input pipeline
#: (prefetch 0 keeps fault step numbering exact), per-step logging,
#: undersampling off (the ~6% positive rate of the synthetic corpus
#: would shrink an undersampled epoch to a couple of batches), and the
#: resilience runtime on with a tight checkpoint cadence
BASE_OVERRIDES = [
    "model.hidden_dim=8",
    "model.n_steps=2",
    "data.undersample=false",
    "data.batch.graphs_per_batch=4",
    "data.batch.node_budget=512",
    "data.batch.edge_budget=2048",
    "train.max_epochs=2",
    "train.prefetch_batches=0",
    "train.log_every_steps=1",
    "train.eval_every_epochs=99",
    'train.resilience={"enabled": true, "step_checkpoint_every": 2}',
]


def run_cli(storage, *argv, faults=None, timeout=300):
    # deliberately NO shared XLA compile cache: a SIGTERM'd process can
    # die mid-cache-write and this jax version will segfault
    # deserializing the truncated entry — the harness must not inject
    # faults into itself
    env = dict(
        os.environ,
        DEEPDFA_TPU_STORAGE=str(storage),
        DEEPDFA_TPU_PLATFORM="cpu",
        JAX_PLATFORMS="cpu",
    )
    env.pop("DEEPDFA_FAULTS", None)
    env.pop("JAX_COMPILATION_CACHE_DIR", None)
    if faults:
        env["DEEPDFA_FAULTS"] = faults
    return subprocess.run(
        [sys.executable, "-m", "deepdfa_tpu.cli", *argv],
        capture_output=True, text=True, env=env, timeout=timeout,
        cwd=str(REPO),
    )


def prepare_corpus(storage, n=48) -> None:
    for argv in (
        ("prepare", "--source", "synthetic", "--n-examples", str(n)),
        ("extract",),
    ):
        res = run_cli(storage, *argv)
        if res.returncode != 0:
            raise RuntimeError(f"{argv[0]} failed:\n{res.stderr[-2000:]}")


def train(storage, run_name, *extra, faults=None, timeout=300):
    return run_cli(
        storage, "train", *BASE_OVERRIDES, f"run_name={run_name}", *extra,
        faults=faults, timeout=timeout,
    )


def read_log(storage, run_name):
    path = Path(storage) / "runs" / run_name / "train_log.jsonl"
    if not path.exists():
        return []
    return [json.loads(line) for line in path.read_text().splitlines()]


def step_losses(records):
    return [(r["step"], r["loss"]) for r in records if "loss" in r]


def scenario_sigterm(storage) -> dict:
    """Kill mid-epoch (exit 143 + manifest); the SAME command re-run
    resumes and reproduces the reference trajectory bit-for-bit."""
    from deepdfa_tpu.train.resilience import EXIT_PREEMPTED

    ref = train(storage, "ref")
    assert ref.returncode == 0, ref.stderr[-2000:]
    ref_losses = step_losses(read_log(storage, "ref"))
    assert len(ref_losses) >= 10, f"reference too short: {len(ref_losses)}"
    kill_at = max(3, len(ref_losses) // 2)

    first = train(storage, "faulted", faults=f"sigterm@{kill_at}")
    assert first.returncode == EXIT_PREEMPTED, (
        f"expected exit {EXIT_PREEMPTED}, got {first.returncode}: "
        f"{first.stderr[-2000:]}"
    )
    manifest = (
        Path(storage) / "runs" / "faulted" / "checkpoints-step" / "resume.json"
    )
    assert manifest.exists(), "no resume manifest after preemption"
    resumed_at = json.loads(manifest.read_text())["step"]

    second = train(storage, "faulted")
    assert second.returncode == 0, second.stderr[-2000:] or "(empty stderr)"
    records = read_log(storage, "faulted")
    merged = step_losses(records)
    assert merged == ref_losses, (
        f"trajectory diverged after resume: "
        f"{merged[:4]}... != {ref_losses[:4]}..."
    )
    assert any(r.get("resumed_from_step") for r in records), (
        "epoch records never reported resumed_from_step"
    )
    return {
        "killed_at_step": kill_at,
        "resumed_from_step": resumed_at,
        "steps_compared": len(merged),
        "trajectory_identical": True,
    }


def scenario_corrupt_shard(storage) -> dict:
    """Truncate a warm cache entry; the next run must quarantine+repack."""
    from deepdfa_tpu.data.packed_cache import PackedBatchCache
    from deepdfa_tpu.testing.faults import truncate_cache_file

    cache_overrides = (
        "data.packed_cache=true",
        "train.max_epochs=1",
    )
    warm = train(storage, "cache-a", *cache_overrides)
    assert warm.returncode == 0, warm.stderr[-2000:]
    cache_root = Path(storage) / "cache" / "bigvul" / "packed"
    damaged = truncate_cache_file(cache_root)

    rerun = train(storage, "cache-b", *cache_overrides)
    assert rerun.returncode == 0, (
        f"run died on the corrupt shard: {rerun.stderr[-2000:]}"
    )
    quarantine = cache_root / "quarantine"
    quarantined = list(quarantine.iterdir()) if quarantine.exists() else []
    assert quarantined, "corrupt entry was not quarantined"
    assert PackedBatchCache(cache_root).keys(), "no rebuilt entry on disk"
    return {
        "damaged_file": damaged.name,
        "quarantined_entries": len(quarantined),
        "repacked_and_completed": True,
    }


def scenario_nan(storage) -> dict:
    """Poisoned batches are skipped on device; the run self-reports."""
    res = train(storage, "nan", faults="nan@2,nan@3")
    assert res.returncode == 0, res.stderr[-2000:]
    records = read_log(storage, "nan")
    epochs = [r for r in records if "skipped_steps" in r]
    assert epochs, "no epoch records with skipped_steps"
    skipped = epochs[-1]["skipped_steps"]
    assert skipped == 2, f"expected 2 skipped steps, saw {skipped}"
    return {"skipped_steps": skipped, "completed": True}


def scenario_stall(storage) -> dict:
    """A stalled producer trips the watchdog's stage-attributed abort."""
    from deepdfa_tpu.train.resilience import EXIT_WATCHDOG

    res = train(
        storage, "stall",
        'train.resilience={"enabled": true, "watchdog_timeout_s": 3}',
        faults="stall@3",
        timeout=180,
    )
    assert res.returncode == EXIT_WATCHDOG, (
        f"expected watchdog exit {EXIT_WATCHDOG}, got {res.returncode}"
    )
    diag_path = (
        Path(storage) / "runs" / "stall" / "checkpoints-step"
        / "watchdog_diagnostic.json"
    )
    assert diag_path.exists(), "no watchdog diagnostic written"
    diag = json.loads(diag_path.read_text())
    assert diag["stalled_stage"] == "input", diag
    return {"stalled_stage": diag["stalled_stage"], "aborted": True}


SCENARIOS = {
    "sigterm": scenario_sigterm,
    "corrupt-shard": scenario_corrupt_shard,
    "nan": scenario_nan,
    "stall": scenario_stall,
}


def run_full(names, n_examples: int) -> dict:
    record: dict = {"mode": "subprocess", "scenarios": {}, "ok": True}
    with tempfile.TemporaryDirectory(prefix="fault-inject-") as storage:
        t0 = time.perf_counter()
        prepare_corpus(storage, n=n_examples)
        record["prepare_seconds"] = round(time.perf_counter() - t0, 1)

        def run_one(name):
            t0 = time.perf_counter()
            try:
                out = SCENARIOS[name](storage)
                out["seconds"] = round(time.perf_counter() - t0, 1)
                return name, out, True
            except (AssertionError, RuntimeError, subprocess.TimeoutExpired) as e:
                return name, {
                    "error": f"{type(e).__name__}: {e}"[:2000],
                    "seconds": round(time.perf_counter() - t0, 1),
                }, False

        # scenarios are independent chains of subprocesses over disjoint
        # run names — run them concurrently over the shared corpus
        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(max_workers=min(2, len(names))) as pool:
            for name, out, ok in pool.map(run_one, names):
                record["scenarios"][name] = out
                record["ok"] = record["ok"] and ok
    return record


# ---------------------------------------------------------------------------
# fleet chaos scenarios (ISSUE 14, docs/fleet.md failure matrix): real
# replica subprocesses + the real router/HA stack, each scenario
# asserting its row's degradation contract AND the zero-recompile
# census across the event. `--fleet` runs them; `--smoke --fleet` runs
# the in-process tier-1 variants (kill-router + wedge-backend over
# stub registries, <60 s).

#: shared fleet config for the chaos drives: tight cadences so the
#: scenarios observe transitions in seconds, ONE ladder size so scores
#: are bit-comparable across replicas (the fleet-smoke rule), the
#: chaos admin endpoints armed, and a 5 s SLO window the rollout guard
#: can actually react inside
FLEET_OVERRIDES = [
    "serve.request_log=true",
    "serve.max_batch_graphs=1",
    "serve.slo_windows=[5, 60]",
    "fleet.heartbeat_interval_s=0.2",
    "fleet.heartbeat_timeout_s=5.0",
    "fleet.poll_interval_s=0.1",
    "fleet.drain_announce_s=0.3",
    "fleet.request_timeout_s=3.0",
    "fleet.rendezvous_interval_s=0.2",
    "fleet.router_failover_timeout_s=1.5",
    "fleet.summary_interval_s=0.5",
    "fleet.rollout_settle_s=0.5",
    "fleet.chaos=true",
    'fleet.tenants="{\\"drill\\": {\\"rate\\": 0.001, \\"burst\\": 50,'
    ' \\"priority\\": 1}}"',
]


def _documented_failover_bound(cfg) -> float:
    """The failover window docs/fleet.md documents: staleness detection
    + one bounded probe + one standby poll."""
    return (
        cfg.fleet.router_failover_timeout_s
        + min(2.0, cfg.fleet.router_failover_timeout_s)
        + cfg.fleet.rendezvous_interval_s
    )


class FleetHarness:
    """One real 2-replica fleet (subprocess replicas, in-process HA
    router) shared across the chaos scenarios — the same bring-up
    `fleet --smoke` uses, plus a deliberately bad checkpoint tag for
    the rollout-refusal arm."""

    def __init__(self, tmp: str):
        import jax
        import numpy as np

        from deepdfa_tpu.core import config as config_mod
        from deepdfa_tpu.fleet import ha as fleet_ha
        from deepdfa_tpu.fleet.replica import (
            spawn_replicas,
            wait_for_ready,
        )
        from deepdfa_tpu.serve import driver
        from deepdfa_tpu.train.checkpoint import CheckpointManager

        self.tmp = Path(tmp)
        self.cfg, self.run_dir, sources_dir = driver.build_smoke_run(
            run_name="fleet-chaos", dataset="fleet-chaos",
            n_examples=16, max_epochs=2,
            extra_overrides=FLEET_OVERRIDES,
        )
        self.fleet_dir = Path(
            self.cfg.fleet.fleet_dir or self.run_dir / "fleet"
        )
        self.codes = [
            f.read_text() for f in sorted(sources_dir.glob("*.c"))[:8]
        ]
        # the injected BAD checkpoint: the best params wildly perturbed
        # and saved under the "bad" tag — calibration drift is enormous
        # by construction, so a drift-gated rollout must refuse it
        from deepdfa_tpu.serve.registry import ModelRegistry

        registry = ModelRegistry(
            self.run_dir, family="deepdfa",
            checkpoint=self.cfg.serve.checkpoint, cfg=self.cfg,
        )
        good = jax.device_get(registry.params())
        bad = jax.tree.map(
            lambda x: (
                np.asarray(x) * -3.0 + 1.0
                if np.issubdtype(np.asarray(x).dtype, np.floating)
                else x
            ),
            good,
        )
        CheckpointManager(self.run_dir / "checkpoints").save(
            "bad", bad, metrics={}, step=9999
        )
        self.available_tags = sorted(
            p.name
            for p in (self.run_dir / "checkpoints").iterdir()
            if p.is_dir()
        )
        del registry

        self.procs = spawn_replicas(self.run_dir, self.fleet_dir, 2)
        self.rids = [rid for rid, _ in self.procs]
        beats = wait_for_ready(
            self.fleet_dir, self.rids, timeout_s=300.0,
            procs=self.procs,
        )
        self.replica_addr = {
            rid: (hb["host"], int(hb["port"]))
            for rid, hb in beats.items()
        }
        self.log_path = self.run_dir / "fleet_log.jsonl"
        self.ha = fleet_ha.HARouter(
            self.cfg, self.fleet_dir, router_id="router-main",
            log_path=self.log_path,
        )
        self.ha.start()
        assert self.ha.wait_active(30.0), "in-process router not active"
        # the bit-parity baseline every failover scenario compares
        # against: one scored pass through the router
        self.baseline: dict[int, float] = {}
        for i, code in enumerate(self.codes):
            status, resp = self.request({"code": code})
            assert status == 200, (status, resp)
            self.baseline[i] = resp["prob"]

    # -- plumbing ------------------------------------------------------------

    def router_addr(self):
        return (self.ha.host, self.ha.port)

    def request(self, payload, headers=None, timeout=60.0):
        from deepdfa_tpu.fleet import chaos as fleet_chaos

        host, port = self.router_addr()
        return fleet_chaos.http_json(
            host, port, "POST", "/score", payload, headers=headers,
            timeout=timeout,
        )

    def admin(self, rid: str, path: str, payload, timeout=300.0):
        from deepdfa_tpu.fleet import chaos as fleet_chaos

        host, port = self.replica_addr[rid]
        return fleet_chaos.http_json(
            host, port, "POST", path, payload, timeout=timeout,
        )

    def replica_healthz(self, rid: str):
        from deepdfa_tpu.fleet import chaos as fleet_chaos

        host, port = self.replica_addr[rid]
        return fleet_chaos.http_json(host, port, "GET", "/healthz")[1]

    def census_ok(self) -> bool:
        """Zero steady-state recompiles on every live replica — the
        Morphling invariant every scenario must leave intact."""
        for rid, proc in self.procs:
            if proc.poll() is not None:
                continue
            h = self.replica_healthz(rid)
            if h.get("steady_state_recompiles") != 0:
                return False
        return True

    def wait_routable(self, rid: str, timeout_s: float = 30.0,
                      want: bool = True) -> bool:
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            topo = self.ha.router.topology()
            state = {
                r["id"]: r["routable"] for r in topo["replicas"]
            }
            if state.get(rid, False) == want:
                return True
            time.sleep(0.05)
        return False

    def log_events(self) -> list[str]:
        names = []
        for line in self.log_path.read_text().splitlines():
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if "fleet_event" in rec:
                names.append(rec["fleet_event"].get("name"))
        return names

    def respawn(self, rid: str) -> None:
        from deepdfa_tpu.fleet import heartbeat
        from deepdfa_tpu.fleet.replica import replica_command

        idx = self.rids.index(rid)
        t_spawn = time.time()
        proc = subprocess.Popen(replica_command(
            self.run_dir, rid, self.fleet_dir
        ))
        self.procs[idx] = (rid, proc)
        # the DEAD replica's heartbeat lingers by design (crash
        # evidence), still saying `ready` at the old port — wait for
        # the NEW process's own announcement (fresher than the spawn)
        # before trusting the addr
        deadline = time.time() + 300
        hb = None
        while time.time() < deadline:
            assert proc.poll() is None, f"respawned {rid} died"
            cand = heartbeat.read_heartbeat(
                heartbeat.heartbeat_path(self.fleet_dir, rid)
            )
            if (
                cand is not None
                and cand.get("state") == heartbeat.READY
                and float(cand["t_unix"]) >= t_spawn
            ):
                hb = cand
                break
            time.sleep(0.1)
        assert hb is not None, f"{rid} never re-announced after respawn"
        self.replica_addr[rid] = (hb["host"], int(hb["port"]))
        assert self.wait_routable(rid, 30.0), f"{rid} not routable"

    def close(self) -> None:
        if self.ha is not None:
            try:
                self.ha.close()
            except Exception:
                pass
        for _, proc in self.procs:
            if proc.poll() is None:
                proc.kill()
                try:
                    proc.wait(timeout=30)
                except Exception:
                    pass


def fleet_corrupt_heartbeat(h: FleetHarness) -> dict:
    """A malformed announcement file quarantines THAT replica — the
    router keeps serving through the other one and never crashes; the
    replica's own next atomic rewrite heals the file and lifts the
    quarantine."""
    from deepdfa_tpu.fleet import heartbeat
    from deepdfa_tpu.obs import metrics as obs_metrics

    rid = h.rids[0]
    q0 = obs_metrics.REGISTRY.snapshot().get("fleet/quarantines", 0)
    # freeze the replica so its refresh cannot heal the file while the
    # quarantine is being observed (SIGSTOP: process alive, no writes)
    victim = dict(h.procs)[rid]
    os.kill(victim.pid, signal.SIGSTOP)
    try:
        path = heartbeat.heartbeat_path(h.fleet_dir, rid)
        path.write_text('{"heartbeat": {"replica_id": "%s", "state": '
                        '"zombie"' % rid)  # torn AND undeclared state
        deadline = time.time() + 15
        quarantined = False
        while time.time() < deadline:
            snap = obs_metrics.REGISTRY.snapshot()
            if snap.get("fleet/quarantines", 0) > q0:
                quarantined = True
                break
            time.sleep(0.05)
        assert quarantined, "router never quarantined the corrupt file"
        assert not h.wait_routable(rid, 1.0, want=True), (
            f"{rid} still routable behind a corrupt heartbeat"
        )
        # the router is alive and serving through the healthy replica
        statuses = []
        for i, code in enumerate(h.codes[:4]):
            status, resp = h.request({"code": code})
            statuses.append(status)
            assert resp.get("prob") == h.baseline[i], "score drifted"
        assert statuses == [200] * 4, statuses
    finally:
        os.kill(victim.pid, signal.SIGCONT)
    # the replica's own refresh heals the file; quarantine lifts
    assert h.wait_routable(rid, 20.0), "quarantine never lifted"
    assert "quarantine" in h.log_events()
    assert h.census_ok(), "recompiles across the event"
    return {
        "quarantined": True,
        "served_through_survivor": True,
        "healed_and_routable": True,
    }


def fleet_wedge_backend(h: FleetHarness) -> dict:
    """A wedged backend (process alive, health probe 503, scoring
    stalled) must be ejected off the forward timeout, kept out while
    its probe fails, and readmitted on recovery — with every request
    answered from the survivor meanwhile (no lost accepted request)."""
    import threading as _threading

    from deepdfa_tpu.obs import metrics as obs_metrics

    rid = h.rids[0]
    snap0 = obs_metrics.REGISTRY.snapshot()
    wedge_s = 8.0
    status, resp = h.admin(rid, "/admin/chaos", {"wedge_s": wedge_s})
    assert status == 200, (status, resp)
    t_wedge = time.time()
    results: list[dict] = []
    lock = _threading.Lock()

    def one(i: int, code: str) -> None:
        status, resp = h.request({"code": code}, timeout=120.0)
        with lock:
            results.append({
                "status": status,
                "bit_identical": resp.get("prob") == h.baseline[i],
            })

    threads = [
        _threading.Thread(target=one, args=(i, c))
        for i, c in enumerate(h.codes[:4])
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert all(
        r["status"] == 200 and r["bit_identical"] for r in results
    ), f"lost/mis-scored a request across the wedge: {results}"
    snap1 = obs_metrics.REGISTRY.snapshot()
    assert snap1.get("fleet/ejects", 0) > snap0.get("fleet/ejects", 0), (
        "wedged replica was never ejected"
    )
    # recovery: wedge expires -> healthz 200 + fresh heartbeat -> the
    # bounded probe readmits without operator action
    assert h.wait_routable(
        rid, wedge_s + _documented_failover_bound(h.cfg) + 20.0
    ), "wedged replica never readmitted after recovery"
    snap2 = obs_metrics.REGISTRY.snapshot()
    assert snap2.get("fleet/readmits", 0) > snap0.get(
        "fleet/readmits", 0
    ), "no readmit counted"
    status, resp = h.request({"code": h.codes[0]})
    assert status == 200
    assert h.census_ok(), "recompiles across the event"
    return {
        "requests_during_wedge": len(results),
        "all_ok": True,
        "ejected": True,
        "readmit_seconds": round(time.time() - t_wedge - wedge_s, 1),
        "readmitted": True,
    }


def fleet_slow_replica(h: FleetHarness) -> dict:
    """Injected scoring latency on every replica: the admission EWMA
    rises with real completions, and deadline-declaring requests are
    shed 503 `deadline` at the front door (no replica ever sees them);
    recovery drains the EWMA and deadlines admit again."""
    latency_s = 0.6
    for rid in h.rids:
        status, _ = h.admin(
            rid, "/admin/chaos",
            {"latency_s": latency_s, "duration_s": 60.0},
        )
        assert status == 200
    # slow completions calibrate the EWMA up
    for i in range(6):
        status, _ = h.request(
            {"code": h.codes[i % len(h.codes)]}, timeout=60.0
        )
        assert status == 200
    # front-door shed: estimate (outstanding/healthy + 1) * EWMA is
    # far past a 100 ms deadline now
    shed = []
    for i in range(4):
        status, resp = h.request({
            "code": h.codes[i % len(h.codes)], "deadline_ms": 100.0,
        })
        shed.append((status, resp.get("reason")))
    assert all(s == 503 and r == "deadline" for s, r in shed), shed
    # recovery: clear the fault, fast completions decay the EWMA
    for rid in h.rids:
        h.admin(rid, "/admin/chaos", {"clear": True})
    admitted_again = False
    for i in range(30):
        status, _ = h.request({"code": h.codes[i % len(h.codes)]})
        assert status == 200
        status, resp = h.request({
            "code": h.codes[i % len(h.codes)], "deadline_ms": 100.0,
        })
        if status == 200:
            admitted_again = True
            break
    assert admitted_again, "deadline traffic never admitted again"
    assert h.census_ok()
    return {
        "shed_while_slow": [s for s, _ in shed],
        "deadline_shed_engaged": True,
        "recovered": True,
    }


def fleet_partition(h: FleetHarness) -> dict:
    """Router->replica connections dropped via the injectable transport
    fault in the router's HTTP client: forwards fail over to the
    reachable replica, readmit probes fail too (the partition covers
    them), and healing the partition readmits the replica."""
    from deepdfa_tpu.obs import metrics as obs_metrics

    rid = h.rids[0]
    snap0 = obs_metrics.REGISTRY.snapshot()
    h.ha.router.transport_fault = (
        lambda r: "drill partition" if r == rid else None
    )
    try:
        for i, code in enumerate(h.codes[:6]):
            status, resp = h.request({"code": code})
            assert status == 200, (status, resp)
            assert resp.get("prob") == h.baseline[i]
        snap1 = obs_metrics.REGISTRY.snapshot()
        assert snap1.get("fleet/ejects", 0) > snap0.get(
            "fleet/ejects", 0
        ), "partitioned replica never ejected"
        # the partition also blocks the readmit probe: the replica must
        # STAY out while the fault holds (poll cadence is 0.1 s, so
        # give the probe loop plenty of chances to get it wrong)
        time.sleep(1.0)
        assert not h.wait_routable(rid, 1.0, want=True), (
            "replica readmitted THROUGH the partition"
        )
    finally:
        h.ha.router.transport_fault = None
    assert h.wait_routable(rid, 20.0), (
        "replica never readmitted after the partition healed"
    )
    snap2 = obs_metrics.REGISTRY.snapshot()
    assert snap2.get("fleet/readmits", 0) > snap0.get(
        "fleet/readmits", 0
    )
    assert h.census_ok()
    return {
        "no_request_lost": True,
        "ejected": True,
        "held_out_while_partitioned": True,
        "readmitted_after_heal": True,
    }


def fleet_kill_replica(h: FleetHarness) -> dict:
    """The promoted kill-replica-midstream drill: SIGKILL one replica
    with requests genuinely in flight; every request answers 200 with
    the bit-identical score off the survivor, the dead replica is
    ejected, and its last heartbeat stays behind as evidence."""
    import threading as _threading

    from deepdfa_tpu.fleet import heartbeat
    from deepdfa_tpu.obs import metrics as obs_metrics

    rid = h.rids[0]
    victim = dict(h.procs)[rid]
    snap0 = obs_metrics.REGISTRY.snapshot()
    # "midstream" must be deterministic, not a race the fleet can win:
    # inject scoring latency on BOTH replicas — outstanding work piles
    # up, so least-outstanding routing genuinely SPREADS the concurrent
    # burst (idle-fleet ties all break toward one replica) and the
    # victim holds requests mid-service when the SIGKILL lands (its
    # injected state dies with the process; the survivor's is cleared
    # below)
    for r in h.rids:
        status, resp = h.admin(
            r, "/admin/chaos", {"latency_s": 1.0, "duration_s": 60.0}
        )
        assert status == 200, (status, resp)
    results: list[dict] = []
    lock = _threading.Lock()

    def one(i: int) -> None:
        i = i % len(h.codes)
        status, resp = h.request({"code": h.codes[i]}, timeout=120.0)
        with lock:
            results.append({
                "status": status,
                "bit_identical": resp.get("prob") == h.baseline[i],
            })

    threads = []

    def launch(i: int) -> None:
        t = _threading.Thread(target=one, args=(i,))
        t.start()
        threads.append(t)

    for i in range(len(h.codes)):
        launch(i)
    # kill only once the victim PROVABLY holds requests mid-service —
    # never on a timer the fleet can win; top up traffic until the
    # router's own view shows outstanding work there
    deadline = time.time() + 30
    n = len(h.codes)
    while time.time() < deadline:
        topo = h.ha.router.topology()
        out = {r["id"]: r["outstanding"] for r in topo["replicas"]}
        if out.get(rid, 0) > 0:
            break
        launch(n)
        n += 1
        time.sleep(0.2)
    else:
        raise AssertionError(
            f"victim {rid} never held an in-flight request: "
            f"{h.ha.router.topology()}"
        )
    os.kill(victim.pid, signal.SIGKILL)
    for t in threads:
        t.join(timeout=120)
    victim.wait(timeout=30)
    for r in h.rids:
        if r != rid:
            h.admin(r, "/admin/chaos", {"clear": True})
    assert len(results) == len(threads)
    assert all(
        r["status"] == 200 and r["bit_identical"] for r in results
    ), f"failover lost or mis-scored a request: {results}"
    snap1 = obs_metrics.REGISTRY.snapshot()
    recent = [
        {k: r["request"].get(k) for k in ("replica", "retries", "status")}
        for r in (
            json.loads(line)
            for line in h.log_path.read_text().splitlines()[-14:]
            if line.strip()
        )
        if "request" in r
    ]
    assert snap1.get("fleet/ejects", 0) > snap0.get("fleet/ejects", 0), (
        f"no eject: topology={h.ha.router.topology()} recent={recent}"
    )
    # the crash evidence contract: the last heartbeat file lingers
    hb = heartbeat.read_heartbeat(
        heartbeat.heartbeat_path(h.fleet_dir, rid)
    )
    assert hb is not None, "dead replica's heartbeat evidence missing"
    assert h.census_ok()
    # restore the 2-replica fleet for whatever runs next
    h.respawn(rid)
    return {
        "killed": rid,
        "responses": len(results),
        "all_ok": True,
        "heartbeat_evidence": True,
        "respawned": True,
    }


def fleet_rollout(h: FleetHarness) -> dict:
    """The zero-downtime rollout drill under open-loop bench_load
    traffic: every replica swaps drain->swap->re-warm->readmit with the
    SLO guard quiet and the zero-recompile census intact; rolling back
    to the prior tag works the same way; and the injected bad
    checkpoint (drift past bound) halts at the first replica with
    everything still serving the prior tag."""
    import dataclasses

    from deepdfa_tpu.fleet.chaos import OpenLoopTraffic
    from deepdfa_tpu.fleet import ha as fleet_ha, rollout as fleet_rollout_mod

    # a real checkpoint tag that is not the serving one
    target = next(
        (t for t in h.available_tags if t.startswith("epoch-")), None
    )
    assert target, f"no epoch tag to roll to in {h.available_tags}"
    prior_step = {
        rid: h.replica_healthz(rid).get("checkpoint_step")
        for rid in h.rids
    }

    def resolve():
        return fleet_ha.resolve_router(h.fleet_dir)

    traffic = OpenLoopTraffic(
        resolve, h.codes, rate_per_sec=3.0, tenant="default",
        request_timeout_s=60.0,
    ).start()
    # age out the previous scenario's deliberate sheds (the 503s the
    # slow-replica drill just asserted on) from the guard's smallest
    # SLO window, refilling it with this drill's 200s — the guard must
    # judge THE ROLLOUT's traffic, not the last drill's residue
    time.sleep(min(h.cfg.serve.slo_windows) + 1.5)
    try:
        # arm 1: a good rollout — inter-epoch drift on this tiny model
        # is real but benign; the gate is sized for it here, and the
        # refusal arm below proves the same gate fires when it must
        cfg_ok = dataclasses.replace(
            h.cfg, fleet=dataclasses.replace(
                h.cfg.fleet, rollout_drift_bound=1.0,
            ),
        )
        report = fleet_rollout_mod.run_rollout(
            cfg_ok, h.fleet_dir, target,
            router_addr=h.router_addr(), log_path=h.log_path,
        )
        assert report["ok"], report
        assert sorted(report["swapped"]) == sorted(h.rids), report
        assert report["census_ok"], report
        assert not report["halted"], report
        # arm 2: roll back to the prior tag the same way, still under
        # traffic — the swap is symmetric
        report_back = fleet_rollout_mod.run_rollout(
            cfg_ok, h.fleet_dir, h.cfg.serve.checkpoint,
            router_addr=h.router_addr(), log_path=h.log_path,
        )
        assert report_back["ok"], report_back
        # arm 3: the injected bad checkpoint must be REFUSED at the
        # first replica (calibration drift gate) and halt the rollout
        # with every replica still on the prior tag
        cfg_bad = dataclasses.replace(
            h.cfg, fleet=dataclasses.replace(
                h.cfg.fleet, rollout_drift_bound=0.02,
            ),
        )
        report_bad = fleet_rollout_mod.run_rollout(
            cfg_bad, h.fleet_dir, "bad",
            router_addr=h.router_addr(), log_path=h.log_path,
        )
        assert report_bad["halted"], report_bad
        assert "refused" in report_bad["halt_reason"] or "drift" in (
            report_bad["halt_reason"]
        ), report_bad
        assert report_bad["swapped"] == [], report_bad
        after_step = {
            rid: h.replica_healthz(rid).get("checkpoint_step")
            for rid in h.rids
        }
        assert after_step == prior_step, (
            f"bad rollout moved a replica: {prior_step} -> {after_step}"
        )
        assert report_bad["census_ok"], report_bad
    finally:
        results = traffic.stop()
    # the traffic verdict: nothing the router accepted was lost — no
    # transport-dead requests, no 5xx beyond deliberate sheds
    lost = [r for r in results if r["status"] == 0]
    failed = [
        r for r in results
        if r["status"] not in (0, 200, 429) and r.get("reason") is None
    ]
    assert not lost, f"lost requests under rollout: {lost[:3]}"
    assert not failed, f"failed requests under rollout: {failed[:3]}"
    ok = [r for r in results if r["status"] == 200]
    assert ok, "traffic never landed during the rollout"
    return {
        "target": target,
        "rolled": True,
        "rolled_back": True,
        "bad_checkpoint_refused": True,
        "traffic_total": len(results),
        "traffic_ok": len(ok),
        "traffic_lost": 0,
    }


def fleet_kill_router(h: FleetHarness) -> dict:
    """Kill the ACTIVE router process under traffic: the standby
    health-checks it via the rendezvous file, takes over the front
    door within the documented bound, re-seeds admission token-bucket
    levels from the last summary record, and no replica state is lost
    — in-flight requests on the dead router are the client's retry
    (OpenLoopTraffic re-resolves and retries once)."""
    import sys as _sys

    from deepdfa_tpu.fleet.chaos import OpenLoopTraffic
    from deepdfa_tpu.fleet import chaos as fleet_chaos, ha as fleet_ha

    replica_pids = {
        rid: proc.pid for rid, proc in h.procs if proc.poll() is None
    }
    # hand the front door to a REAL router subprocess (the process the
    # scenario kills), retiring the harness's in-process active
    h.ha.close()
    h.ha = None
    env = dict(os.environ)
    active = subprocess.Popen(
        [_sys.executable, "-m", "deepdfa_tpu.cli", "fleet-router",
         "--run-dir", str(h.run_dir),
         "--fleet-dir", str(h.fleet_dir),
         "--router-id", "router-sub"],
        env=env, cwd=str(REPO),
    )
    try:
        deadline = time.time() + 120
        addr = None
        while time.time() < deadline:
            rv = fleet_ha.read_rendezvous(h.fleet_dir)
            if rv is not None and rv["router_id"] == "router-sub":
                try:
                    status, _ = fleet_chaos.http_json(
                        rv["host"], int(rv["port"]), "GET", "/healthz",
                        timeout=5.0,
                    )
                    if status == 200:
                        addr = (rv["host"], int(rv["port"]))
                        break
                except OSError:
                    pass
            time.sleep(0.1)
        assert addr is not None, "subprocess router never took over"
        epoch_before = fleet_ha.read_rendezvous(h.fleet_dir)["epoch"]
        # drain the drill tenant's token bucket through the subprocess
        # router so its summary records carry a level well under burst
        # (rate 0.001/s: no meaningful refill inside the drill); the
        # router is seconds old — transient transport errors while its
        # accept loop settles are the client's retry, not a failure
        sent = 0
        drain_deadline = time.time() + 60
        while sent < 10:
            try:
                status, _ = fleet_chaos.http_json(
                    *addr, "POST", "/score",
                    {"code": h.codes[sent % len(h.codes)],
                     "tenant": "drill"},
                )
            except OSError as e:
                assert time.time() < drain_deadline, (
                    f"router at {addr} unreachable for 60s: {e}"
                )
                time.sleep(0.2)
                continue
            assert status == 200, status
            sent += 1
        # one summary cadence so the levels are on disk
        time.sleep(2 * h.cfg.fleet.summary_interval_s + 0.5)
        # the in-process STANDBY joins the pair
        standby = fleet_ha.HARouter(
            h.cfg, h.fleet_dir, router_id="router-standby",
            log_path=h.log_path,
        )
        standby.start()
        time.sleep(0.5)
        assert standby.role == "standby", standby.role
        traffic = OpenLoopTraffic(
            lambda: fleet_ha.resolve_router(h.fleet_dir),
            h.codes, rate_per_sec=3.0, tenant="default",
            request_timeout_s=30.0,
        ).start()
        t_kill = time.monotonic()
        active.kill()
        took_over = standby.wait_active(timeout_s=60.0)
        failover_s = time.monotonic() - t_kill
        results = traffic.stop()
        assert took_over, "standby never took over"
        bound = _documented_failover_bound(h.cfg)
        rv = fleet_ha.read_rendezvous(h.fleet_dir)
        assert rv["router_id"] == "router-standby", rv
        assert rv["epoch"] > epoch_before, rv
        # bounded failover: the documented window plus generous slack
        # for this 1-cpu box (the MEASURED number is in the record)
        assert failover_s < bound + 10.0, (
            f"failover took {failover_s:.1f}s (documented bound "
            f"{bound:.1f}s)"
        )
        h.ha = standby  # the harness's router again, for teardown
        # no replica state lost: same pids, all still ready + routable
        for rid, pid in replica_pids.items():
            assert dict(h.procs)[rid].poll() is None, f"{rid} died"
            assert dict(h.procs)[rid].pid == pid
            assert h.wait_routable(rid, 20.0), f"{rid} not routable"
        # the new active answers, and its admission state was re-seeded
        # from the dead router's last summary (drill bucket well under
        # burst, not a fresh 50)
        status, resp = h.request({"code": h.codes[0]})
        assert status == 200, (status, resp)
        snap = h.ha.router.admission.snapshot()
        drill_level = snap["tokens"].get("drill")
        assert drill_level is not None and drill_level <= 45.0, (
            f"token bucket not re-seeded (drill level {drill_level})"
        )
        # client contract: post-failover, nothing stayed lost — every
        # transport-dead first attempt re-resolved and landed
        lost = [r for r in results if r["status"] == 0]
        assert not lost, f"requests lost across failover: {lost[:3]}"
        assert "takeover" in h.log_events()
        assert h.census_ok()
        return {
            "failover_seconds": round(failover_s, 2),
            "documented_bound_seconds": round(bound, 2),
            "epoch": rv["epoch"],
            "reseeded_drill_tokens": drill_level,
            "replicas_undisturbed": True,
            "traffic_total": len(results),
            "traffic_lost": 0,
        }
    finally:
        if active.poll() is None:
            active.kill()
            try:
                active.wait(timeout=30)
            except Exception:
                pass


FLEET_SCENARIOS = {
    "corrupt-heartbeat": fleet_corrupt_heartbeat,
    "wedge-backend": fleet_wedge_backend,
    "slow-replica": fleet_slow_replica,
    "partition": fleet_partition,
    "rollout": fleet_rollout,
    "kill-replica-midstream": fleet_kill_replica,
    "kill-router": fleet_kill_router,
}


def run_fleet(names) -> dict:
    """Full fleet chaos mode: one real bring-up, every scenario against
    it in a safe order (recoverable faults first, process kills last)."""
    from deepdfa_tpu.core.backend import apply_platform_override

    os.environ.setdefault("DEEPDFA_TPU_PLATFORM", "cpu")
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    apply_platform_override()
    sys.path.insert(0, str(REPO / "scripts"))
    record: dict = {"mode": "fleet", "scenarios": {}, "ok": True}
    with tempfile.TemporaryDirectory(prefix="fleet-chaos-") as tmp:
        os.environ["DEEPDFA_TPU_STORAGE"] = tmp
        t0 = time.perf_counter()
        h = FleetHarness(tmp)
        record["setup_seconds"] = round(time.perf_counter() - t0, 1)
        record["failover_bound_seconds"] = round(
            _documented_failover_bound(h.cfg), 2
        )
        try:
            for name in (
                n for n in FLEET_SCENARIOS if n in names
            ):
                t0 = time.perf_counter()
                try:
                    out = FLEET_SCENARIOS[name](h)
                    out["seconds"] = round(time.perf_counter() - t0, 1)
                    record["scenarios"][name] = out
                except (AssertionError, RuntimeError, OSError) as e:
                    import traceback

                    record["ok"] = False
                    record["scenarios"][name] = {
                        "error": f"{type(e).__name__}: {e}"[:2000],
                        "trace": traceback.format_exc()[-1500:],
                        "seconds": round(time.perf_counter() - t0, 1),
                    }
            # the shared log must validate with every new record shape
            # (quarantine/takeover events, rollout records) on board
            from deepdfa_tpu.fleet.router import validate_fleet_log

            log_verdict = validate_fleet_log(h.log_path)
            record["fleet_log"] = {
                k: log_verdict[k]
                for k in ("ok", "records", "events", "rollouts")
                if k in log_verdict
            }
            if not log_verdict["ok"]:
                record["ok"] = False
                record["fleet_log"]["problems"] = log_verdict["problems"]
        finally:
            h.close()
    return record


# ---------------------------------------------------------------------------
# in-process fleet tier (tier-1: --smoke --fleet; stub registries, no
# subprocess, <60 s): the kill-router + wedge-backend variants


def smoke_fleet(tmp: str) -> dict:
    from deepdfa_tpu.core import Config, config as config_mod
    from deepdfa_tpu.fleet import chaos as fleet_chaos, ha as fleet_ha
    from deepdfa_tpu.obs import metrics as obs_metrics

    cfg = config_mod.apply_overrides(Config(), [
        'data.feat={"limit_all": 50, "limit_subkeys": 50}',
        "model.hidden_dim=8", "model.n_steps=2",
        "serve.max_batch_graphs=1",
        "serve.node_budget=2048", "serve.edge_budget=8192",
        "serve.slo_windows=[5, 60]",
        # in-process stubs never refresh heartbeats; a large timeout
        # keeps them routable (the bench_load convention)
        "fleet.heartbeat_timeout_s=3600.0",
        "fleet.poll_interval_s=0.1",
        "fleet.request_timeout_s=1.0",
        "fleet.rendezvous_interval_s=0.1",
        "fleet.router_failover_timeout_s=0.8",
        "fleet.summary_interval_s=0.2",
        'fleet.tenants="{\\"drill\\": {\\"rate\\": 0.001, '
        '\\"burst\\": 50, \\"priority\\": 1}}"',
    ])
    model, params, vocabs, codes = fleet_chaos.build_stub_parts(cfg)
    record: dict = {}

    # -- wedge-backend, in-process: real ScoringServices + the real
    # router; r0's probe flips and scoring stalls, the router must
    # eject off the forward timeout and readmit on recovery
    fleet_dir = Path(tmp) / "wedge"
    replicas = [
        fleet_chaos.StubReplicaServer(
            cfg, fleet_dir, f"r{i}",
            fleet_chaos.stub_service(
                cfg, fleet_dir, f"r{i}", model, params, vocabs
            ),
        )
        for i in range(2)
    ]
    ha_router = fleet_ha.HARouter(
        cfg, fleet_dir, "router-a",
        log_path=fleet_dir / "fleet_log.jsonl",
    )
    try:
        ha_router.start()
        assert ha_router.wait_active(20.0)
        addr = (ha_router.host, ha_router.port)
        baseline = {}
        for i, code in enumerate(codes[:4]):
            status, resp = fleet_chaos.http_json(
                *addr, "POST", "/score", {"code": code}
            )
            assert status == 200, (status, resp)
            baseline[i] = resp["prob"]
        snap0 = obs_metrics.REGISTRY.snapshot()
        replicas[0].chaos.apply({"wedge_s": 3.0})
        wedge_results = []
        for i, code in enumerate(codes[:4]):
            status, resp = fleet_chaos.http_json(
                *addr, "POST", "/score", {"code": code}, timeout=60.0
            )
            wedge_results.append(
                status == 200 and resp.get("prob") == baseline[i]
            )
        assert all(wedge_results), wedge_results
        snap1 = obs_metrics.REGISTRY.snapshot()
        assert snap1.get("fleet/ejects", 0) > snap0.get(
            "fleet/ejects", 0
        ), "in-process wedge never ejected"
        deadline = time.time() + 30
        readmitted = False
        while time.time() < deadline:
            snap = obs_metrics.REGISTRY.snapshot()
            if snap.get("fleet/readmits", 0) > snap0.get(
                "fleet/readmits", 0
            ):
                readmitted = True
                break
            time.sleep(0.05)
        assert readmitted, "in-process wedge never readmitted"
        recompiles = sum(
            r.service.steady_state_recompiles() for r in replicas
        )
        assert recompiles == 0, recompiles
        record["wedge-backend"] = {
            "requests_ok": len(wedge_results),
            "ejected": True,
            "readmitted": True,
            "steady_state_recompiles": recompiles,
        }
    finally:
        ha_router.close()

    # -- kill-router, in-process: an active/standby pair over the same
    # stub replicas; the active dies abruptly (kill(): no rendezvous
    # handoff, exactly SIGKILL's residue), the standby takes over
    # within the bound and re-seeds the drill tenant's bucket level
    # from the last summary record
    fleet_dir2 = Path(tmp) / "killrouter"
    for r in replicas:
        r.fleet_dir = fleet_dir2
        r.beat()
    log_path = fleet_dir2 / "fleet_log.jsonl"
    active = fleet_ha.HARouter(cfg, fleet_dir2, "ra", log_path=log_path)
    standby = fleet_ha.HARouter(cfg, fleet_dir2, "rb", log_path=log_path)
    try:
        active.start()
        assert active.wait_active(20.0)
        addr = (active.host, active.port)
        for i in range(10):
            status, _ = fleet_chaos.http_json(
                *addr, "POST", "/score",
                {"code": codes[i % len(codes)], "tenant": "drill"},
            )
            assert status == 200, status
        # force a summary record so the bucket level is on disk
        active.router._last_summary = 0.0
        active.router._maybe_summarize()
        standby.start()
        time.sleep(0.3)
        assert standby.role == "standby", standby.role
        epoch0 = fleet_ha.read_rendezvous(fleet_dir2)["epoch"]
        t0 = time.monotonic()
        active.kill()
        assert standby.wait_active(timeout_s=30.0), "no takeover"
        failover_s = time.monotonic() - t0
        bound = (
            cfg.fleet.router_failover_timeout_s * 2
            + cfg.fleet.rendezvous_interval_s
        )
        rv = fleet_ha.read_rendezvous(fleet_dir2)
        assert rv["router_id"] == "rb" and rv["epoch"] > epoch0, rv
        addr2 = fleet_ha.resolve_router(fleet_dir2)
        status, resp = fleet_chaos.http_json(
            *addr2, "POST", "/score", {"code": codes[0]}
        )
        assert status == 200, (status, resp)
        drill = standby.router.admission.snapshot()["tokens"].get(
            "drill"
        )
        assert drill is not None and drill <= 45.0, (
            f"standby did not re-seed the drill bucket: {drill}"
        )
        record["kill-router"] = {
            "failover_seconds": round(failover_s, 2),
            "bound_seconds": round(bound + 5.0, 2),
            "within_bound": failover_s < bound + 5.0,
            "epoch": rv["epoch"],
            "reseeded_drill_tokens": drill,
        }
        assert record["kill-router"]["within_bound"], record
    finally:
        active.kill()
        standby.close()
        for r in replicas:
            r.close()
    return record


def run_smoke_fleet() -> dict:
    """The tier-1 fleet lane (`--smoke --fleet`): kill-router +
    wedge-backend against the in-process stub fleet, <60 s."""
    from deepdfa_tpu.core.backend import apply_platform_override

    os.environ.setdefault("DEEPDFA_TPU_PLATFORM", "cpu")
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    apply_platform_override()
    record: dict = {"mode": "fleet-inproc", "scenarios": {}, "ok": True}
    with tempfile.TemporaryDirectory(prefix="fleet-smoke-") as tmp:
        t0 = time.perf_counter()
        try:
            record["scenarios"] = smoke_fleet(tmp)
        except (AssertionError, RuntimeError, OSError) as e:
            record["ok"] = False
            record["error"] = f"{type(e).__name__}: {e}"[:2000]
        record["seconds"] = round(time.perf_counter() - t0, 1)
    return record


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "--smoke", action="store_true",
        help="tier-1 in-process mode: sigterm + corrupt-shard + nan "
        "through the real runtime in one interpreter (<1 min); with "
        "--fleet, the in-process kill-router + wedge-backend drills",
    )
    ap.add_argument(
        "--scenario", action="append", default=None,
        choices=sorted(SCENARIOS),
        help="full mode: run only the named subprocess scenario(s)",
    )
    ap.add_argument(
        "--fleet", action="store_true",
        help="fleet chaos mode (docs/fleet.md failure matrix): real "
        "replica subprocesses + the HA router stack; every scenario "
        "asserts its degradation contract and the zero-recompile "
        "census",
    )
    ap.add_argument(
        "--fleet-scenario", action="append", default=None,
        choices=sorted(FLEET_SCENARIOS),
        help="fleet mode: run only the named fleet scenario(s)",
    )
    ap.add_argument("--n-examples", type=int, default=48)
    ap.add_argument("--out", default=None)
    ap.add_argument(
        "--mesh-child", default=None, metavar="DIR",
        help="internal: the 8-device-mesh SIGTERM drill body "
        "(inproc_mesh_sigterm runs it under cpu:8)",
    )
    args = ap.parse_args()

    if args.mesh_child:
        mesh_child(args.mesh_child)
        return

    if args.smoke and args.fleet:
        record = run_smoke_fleet()
    elif args.smoke:
        record = run_smoke(args.n_examples)
    elif args.fleet:
        names = args.fleet_scenario or list(FLEET_SCENARIOS)
        record = run_fleet(names)
    else:
        names = args.scenario if args.scenario else list(SCENARIOS)
        record = run_full(names, args.n_examples)
    record["smoke"] = args.smoke
    print(json.dumps(record), flush=True)
    if args.out:
        Path(args.out).write_text(json.dumps(record, indent=2))
    sys.exit(0 if record["ok"] else 1)


if __name__ == "__main__":
    main()
