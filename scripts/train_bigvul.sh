#!/usr/bin/env bash
# DeepDFA flagship training (reference train.sh: config_bigvul+config_ggnn)
set -euo pipefail
cd "$(dirname "$0")/.."
python -m deepdfa_tpu.cli train --config configs/bigvul_deepdfa.json "$@"
python -m deepdfa_tpu.cli test --config configs/bigvul_deepdfa.json --profile "$@"
