#!/usr/bin/env python
"""Open-loop fleet load generator (docs/fleet.md).

Drives a real 2+-replica fleet — in-process replica servers (the same
ScoringService + HTTP handler the `fleet-replica` worker runs, minus the
checkpoint round trip) behind the real router + admission stack — with
OPEN-LOOP traffic: arrival times are drawn from a Poisson process at a
fixed offered rate and requests fire at those times whether or not
earlier ones completed. That is the only way to measure overload
honestly: a closed-loop client slows down with the server and never
observes the queue the paper's "millions of users" traffic would build.

The mix is deliberately hostile, per the ISSUE:
  - heavy-tail function sizes (Pareto-sampled over the size-sorted
    corpus: mostly small functions, a fat tail of big ones — the shape
    real repos have);
  - a tenant mix (interactive priority-0 with a tight deadline, batch
    priority-1 with a loose one, best-effort priority-2 behind a tiny
    token bucket);
  - an offered rate a multiple of the measured warm capacity
    (`--overload`, default 3x), so the fleet MUST shed.

Reported (bench-gated in obs/bench_gate.py, both lower-is-better):
  fleet_p99_overload_ms   p99 latency of ADMITTED (200) requests under
                          overload
  fleet_shed_rate         shed fraction at the fixed offered rate
plus throughput/accounting fields and the zero-steady-state-recompiles
census summed across replicas.

ISSUE 19 stamps (the fleet telemetry plane, docs/alerts.md):
  obs_fleet_overhead_fraction  closed-loop throughput cost of the
                          telemetry duty cycle (per-request alert
                          observation + cadenced snapshot publish +
                          rule evaluation), measured as INTERLEAVED
                          off/on reps so machine-load drift cancels —
                          absolute-bounded at 2% in bench_gate
  alert_mttd_s            wall-clock from an injected error burst to
                          the burn-rate rule's firing transition at the
                          production evaluation cadence (lower-is-better
                          gated)

ISSUE 20 stamps (the data flywheel, docs/flywheel.md):
  shadow_overhead_fraction  closed-loop throughput cost of shadow
                          mirror sampling on the router's reply path
                          (flywheel/shadow.py:ShadowSampler at
                          sample_rate=1.0 — worst case), interleaved
                          on/off reps; absolute-bounded at 2%
  shadow_agreement        agreement over a mini in-process shadow ride
                          where the candidate IS the incumbent's
                          checkpoint — a fall is comparison-plumbing
                          drift, not a model difference
  shadow_sample_lag_s     sampler-append to scorer-consume latency over
                          that ride (lower-is-better gated)

Modes:
    python scripts/bench_load.py --smoke   # tier-1 regression mode
    python scripts/bench_load.py           # full mode (bigger drive)
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
from pathlib import Path

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

#: tenant mix: (name, traffic share, per-request deadline_ms)
TENANT_MIX = (
    ("interactive", 0.5, 250.0),
    ("batch", 0.4, 2000.0),
    ("besteffort", 0.1, None),
)

#: admission policies for the mix (fleet/admission.py JSON spec):
#: best-effort sits behind a deliberately tiny bucket so rate-limit
#: shedding is exercised at any offered rate
TENANT_POLICIES = (
    '{"interactive": {"rate": 10000, "burst": 10000, "priority": 0},'
    ' "batch": {"rate": 10000, "burst": 10000, "priority": 1},'
    ' "besteffort": {"rate": 1, "burst": 2, "priority": 2}}'
)


def _measure_alert_mttd(
    cadence_s: float = 0.05, timeout_s: float = 5.0
) -> float | None:
    """One wall-clock detection episode for the burn-rate rule
    (obs/alerts.py): a healthy request stream, an error burst at t0,
    the engine evaluated on its cadence — the stamp is the firing
    transition's delay past the burst. Cadence granularity dominates,
    which is the point: the stamp tracks the real time-to-page."""
    import time as _time

    from deepdfa_tpu.obs.alerts import AlertEngine, AlertRule

    engine = AlertEngine([AlertRule(
        name="bench_burn", kind="burn_rate", threshold=1.0,
        windows=(0.5, 1.5), params={"budget": 0.05, "min_count": 3},
    )])
    for _ in range(50):
        engine.observe_request(200)
    t0 = _time.monotonic()
    for _ in range(50):
        engine.observe_request(500)
    while _time.monotonic() - t0 < timeout_s:
        _time.sleep(cadence_s)
        engine.evaluate()
        if "bench_burn" in engine.firing():
            return _time.monotonic() - t0
    return None


def _bench_registry(cfg, model, params, vocabs, run_dir):
    """Registry-shaped stub over freshly initialized params: the load
    bench measures the fleet machinery, not checkpoint IO (the restore
    path has its own e2e coverage in `fleet --smoke`). One
    implementation, shared with the chaos drills
    (fleet/chaos.py:StubRegistry)."""
    from deepdfa_tpu.fleet.chaos import StubRegistry

    return StubRegistry(cfg, model, params, vocabs, run_dir)


#: re-export: the open-loop start/stop traffic driver lives with the
#: other shared fleet-drill fixtures (deepdfa_tpu/fleet/chaos.py); the
#: `bench_load` function below keeps its own inline arrival loop
from deepdfa_tpu.fleet.chaos import OpenLoopTraffic  # noqa: F401,E402


def bench_load(
    n_requests: int = 600,
    n_replicas: int = 2,
    overload: float = 3.0,
    smoke: bool = False,
    seed: int = 0,
) -> dict:
    import numpy as np

    import jax

    from deepdfa_tpu.core import Config, config as config_mod
    from deepdfa_tpu.data import build_dataset, generate, to_examples
    from deepdfa_tpu.fleet import heartbeat
    from deepdfa_tpu.fleet.router import (
        BackgroundRouter,
        FleetLog,
        router_from_config,
    )
    from deepdfa_tpu.graphs.batch import pack
    from deepdfa_tpu.models import DeepDFA
    from deepdfa_tpu.obs.slo import percentile
    from deepdfa_tpu.serve.server import BackgroundServer, ScoringService

    n_requests = min(n_requests, 120) if smoke else int(n_requests)
    n_corpus = 32 if smoke else 128
    cfg = config_mod.apply_overrides(Config(), [
        'data.feat={"limit_all": 50, "limit_subkeys": 50}',
        "model.hidden_dim=8" if smoke else "model.hidden_dim=32",
        "model.n_steps=2" if smoke else "model.n_steps=5",
        "serve.max_batch_graphs=8",
        "serve.node_budget=2048", "serve.edge_budget=8192",
        # replicas serve through the pipelined executor path (ISSUE 17)
        # — the fleet drive is the online-mode overlap measurement
        "serve.pipeline_depth=2",
        # the tenants field is a JSON string; the override value must be
        # a JSON string literal (json.dumps of the spec)
        f"fleet.tenants={json.dumps(TENANT_POLICIES)}",
        # in-process replicas never refresh their heartbeat; a large
        # timeout keeps them routable for the whole drive
        "fleet.heartbeat_timeout_s=3600.0",
        "fleet.poll_interval_s=0.2",
    ])
    synth = generate(n_corpus, seed=seed)
    examples = to_examples(synth)
    _, vocabs = build_dataset(
        examples, train_ids=range(n_corpus),
        limit_all=cfg.data.feat.limit_all,
        limit_subkeys=cfg.data.feat.limit_subkeys,
    )
    model = DeepDFA.from_config(
        cfg.model, input_dim=cfg.data.feat.input_dim
    )
    params = model.init(
        jax.random.key(0), pack([], 1, 2048, 8192),
    )
    # heavy-tail size mix: Pareto index over the size-sorted corpus
    # (drawn from the SAME generator as the tenant/arrival draws so the
    # three are independent samples of one stream, not correlated
    # replays of identically-seeded streams)
    codes = sorted((e.code for e in examples), key=len)
    rng = np.random.default_rng(seed)
    pareto_idx = np.minimum(
        (rng.pareto(1.5, n_requests) * 4).astype(int),
        len(codes) - 1,
    )
    tenant_names = [t[0] for t in TENANT_MIX]
    tenant_p = np.asarray([t[1] for t in TENANT_MIX])
    tenant_deadline = {t[0]: t[2] for t in TENANT_MIX}
    tenant_draw = rng.choice(len(TENANT_MIX), n_requests, p=tenant_p)

    import tempfile

    with tempfile.TemporaryDirectory(prefix="bench-fleet-") as td:
        fleet_dir = Path(td) / "fleet"
        services: list[ScoringService] = []
        servers: list[BackgroundServer] = []
        try:
            for i in range(int(n_replicas)):
                registry = _bench_registry(
                    cfg, model, params, vocabs, fleet_dir / f"r{i}"
                )
                service = ScoringService(registry, cfg)
                services.append(service)
                server = BackgroundServer(service)
                servers.append(server)
                heartbeat.write_heartbeat(
                    fleet_dir, f"r{i}", server.host, server.port,
                )
            router = router_from_config(
                cfg, fleet_dir, log_path=Path(td) / "fleet_log.jsonl"
            )
            router_server = BackgroundRouter(router)

            def send(code: str, tenant: str, deadline_ms):
                payload: dict = {"code": code, "tenant": tenant}
                if deadline_ms is not None:
                    payload["deadline_ms"] = float(deadline_ms)
                t0 = time.monotonic()
                status, _ = router_server.request(
                    "POST", "/score", payload
                )
                return status, time.monotonic() - t0

            # closed-loop warm pass: compile-cache warmth + the
            # capacity measurement the offered rate is derived from
            n_warm = 16 if smoke else 64
            t0 = time.perf_counter()
            for i in range(n_warm):
                status, _ = send(codes[i % len(codes)], "batch", None)
                assert status == 200, f"warm request failed: {status}"
            warm_rps = n_warm / (time.perf_counter() - t0)

            # ISSUE 19: cost of the fleet telemetry plane, measured as
            # INTERLEAVED off/on closed-loop reps so machine-load drift
            # cancels instead of biasing one arm. The "on" arm runs the
            # production duty cycle — the router's alert engine
            # observing every request, plus the cadenced snapshot
            # publish and rule evaluation (obs/aggregate.py,
            # obs/alerts.py); the 2% ceiling lives in
            # bench_gate.ABSOLUTE_UPPER_BOUNDS.
            from deepdfa_tpu.obs.aggregate import SnapshotPublisher
            from deepdfa_tpu.obs.alerts import AlertEngine, default_rules

            publisher = SnapshotPublisher(
                fleet_dir, "bench-router",
                slo_engines=lambda: {"router": router.slo},
                interval_s=cfg.fleet.telemetry_interval_s,
            )
            alert_engine = AlertEngine(default_rules())
            obs_reps = 3 if smoke else 5
            obs_burst = 8 if smoke else 24

            def _obs_rep(telemetry_on: bool) -> float:
                t0 = time.perf_counter()
                for i in range(obs_burst):
                    status, _ = send(codes[i % len(codes)], "batch", None)
                    assert status == 200, f"overhead rep failed: {status}"
                    if telemetry_on:
                        publisher.maybe_publish()
                        router._maybe_alert()
                return obs_burst / (time.perf_counter() - t0)

            # one throwaway pair so neither arm pays first-touch costs
            # (publisher slot files, alert-state allocation), then
            # order-ALTERNATING pairs and the median of per-pair ratios:
            # a single slow rep (GC pause, scheduler hiccup) shifts one
            # ratio, not the estimate
            ratios: list[float] = []
            for pair in range(obs_reps + 1):
                on_first = pair % 2 == 1
                pair_rps = {}
                for arm in ((True, False) if on_first else (False, True)):
                    if arm:
                        router.alerts = alert_engine
                    try:
                        pair_rps[arm] = _obs_rep(arm)
                    finally:
                        router.alerts = None
                if pair > 0:  # pair 0 is the throwaway
                    ratios.append(pair_rps[True] / pair_rps[False])
            ratios.sort()
            obs_overhead = max(
                0.0, 1.0 - ratios[len(ratios) // 2]
            )
            alert_mttd = _measure_alert_mttd()

            # ISSUE 20: cost of shadow mirror sampling on the same
            # reply path, by the same interleaved on/off method. The
            # "on" arm attaches a ShadowSampler at sample_rate=1.0 —
            # worst case: EVERY 200 response pays the sample append +
            # backpressure check — against the 2% absolute ceiling in
            # bench_gate.ABSOLUTE_UPPER_BOUNDS.
            from deepdfa_tpu.flywheel.shadow import (
                ShadowSampler,
                ShadowScorer,
                http_score_fn,
            )

            shadow_sampler = ShadowSampler(
                fleet_dir, sample_rate=1.0, max_inflight=4096,
            )

            def _shadow_rep() -> float:
                t0 = time.perf_counter()
                for i in range(obs_burst):
                    status, _ = send(codes[i % len(codes)], "batch", None)
                    assert status == 200, f"shadow rep failed: {status}"
                return obs_burst / (time.perf_counter() - t0)

            ratios = []
            for pair in range(obs_reps + 1):
                on_first = pair % 2 == 1
                pair_rps = {}
                for arm in ((True, False) if on_first else (False, True)):
                    router.flywheel = shadow_sampler if arm else None
                    try:
                        pair_rps[arm] = _shadow_rep()
                    finally:
                        router.flywheel = None
                if pair > 0:  # pair 0 is the throwaway
                    ratios.append(pair_rps[True] / pair_rps[False])
            ratios.sort()
            shadow_overhead = max(0.0, 1.0 - ratios[len(ratios) // 2])

            # mini in-process shadow ride: the scorer tails the sample
            # stream and scores with replica r0 — the candidate IS the
            # incumbent's checkpoint, so agreement is a plumbing
            # invariant (sampled prob paired with the right scored
            # prob) and lag is the mirror stream's consumption latency
            n_ride = 12 if smoke else 32
            shadow_scorer = ShadowScorer(
                fleet_dir, "bench-candidate", "incumbent",
                http_score_fn(servers[0].host, servers[0].port),
                window=n_ride, min_samples=n_ride,
            )
            shadow_scorer.last_seq = shadow_sampler._seq
            router.flywheel = shadow_sampler
            try:
                for i in range(n_ride):
                    status, _ = send(codes[i % len(codes)], "batch", None)
                    assert status == 200, f"ride request failed: {status}"
            finally:
                router.flywheel = None
            shadow_scorer.poll()
            shadow_stats = shadow_scorer.comparator.stats()

            # open-loop overload drive: Poisson arrivals at
            # overload x measured capacity, fired on schedule
            offered_rate = max(1.0, overload * warm_rps)
            gaps = rng.exponential(1.0 / offered_rate, n_requests)
            arrivals = np.cumsum(gaps)
            results: list[tuple[str, int, float]] = []
            lock = threading.Lock()
            threads: list[threading.Thread] = []

            def fire(idx: int) -> None:
                tenant = tenant_names[tenant_draw[idx]]
                status, latency = send(
                    codes[int(pareto_idx[idx])], tenant,
                    tenant_deadline[tenant],
                )
                with lock:
                    results.append((tenant, status, latency))

            drive_t0 = time.monotonic()
            for i in range(n_requests):
                delay = arrivals[i] - (time.monotonic() - drive_t0)
                if delay > 0:
                    time.sleep(delay)
                t = threading.Thread(target=fire, args=(i,), daemon=True)
                t.start()
                threads.append(t)
            for t in threads:
                t.join(timeout=300)
            drive_s = time.monotonic() - drive_t0

            ok_lat = sorted(
                lat for _, st, lat in results if st == 200
            )
            shed = [r for r in results if r[1] in (429, 503)]
            other = [
                r for r in results if r[1] != 200 and r[1] not in (429, 503)
            ]
            recompiles = sum(
                s.steady_state_recompiles() for s in services
            )
            shed_by_tenant = {}
            for tenant, st, _ in results:
                agg = shed_by_tenant.setdefault(
                    tenant, {"requests": 0, "shed": 0}
                )
                agg["requests"] += 1
                agg["shed"] += 1 if st in (429, 503) else 0
            router_server.close()
            p99 = percentile(ok_lat, 0.99)
            p50 = percentile(ok_lat, 0.50)
            # in-process replicas share one metrics registry, so the
            # fleet-wide FIFO-union device busy/idle counters are the
            # summed pipelined-drive attribution (serve/batcher.py:
            # DeviceWindow)
            from deepdfa_tpu.obs import metrics as obs_metrics

            msnap = obs_metrics.REGISTRY.snapshot()
            busy = msnap.get("serve/pipeline/device_busy_seconds", 0.0)
            idle = msnap.get("serve/pipeline/device_idle_seconds", 0.0)
            idle_frac = (
                round(idle / (busy + idle), 4) if busy + idle > 0
                else None
            )
            return {
                "metric": "fleet_p99_overload_ms",
                "value": round(1e3 * p99, 3) if p99 else None,
                "unit": "ms",
                "fleet_p99_overload_ms": (
                    round(1e3 * p99, 3) if p99 else None
                ),
                "fleet_latency_p50_ms": (
                    round(1e3 * p50, 3) if p50 else None
                ),
                "fleet_shed_rate": round(len(shed) / len(results), 4),
                "fleet_requests_total": len(results),
                "fleet_admitted": len(ok_lat),
                "fleet_shed": len(shed),
                "fleet_failed_other": len(other),
                "fleet_requests_per_sec": round(
                    len(ok_lat) / drive_s, 2
                ),
                "fleet_offered_rate_per_sec": round(offered_rate, 2),
                "fleet_warm_requests_per_sec": round(warm_rps, 2),
                "fleet_replicas": int(n_replicas),
                "fleet_seconds": round(drive_s, 3),
                "fleet_steady_state_recompiles": recompiles,
                "obs_fleet_overhead_fraction": round(obs_overhead, 4),
                "alert_mttd_s": (
                    round(alert_mttd, 4) if alert_mttd is not None
                    else None
                ),
                "shadow_overhead_fraction": round(shadow_overhead, 4),
                "shadow_agreement": (
                    round(shadow_stats["agreement"], 4)
                    if "agreement" in shadow_stats else None
                ),
                "shadow_sample_lag_s": (
                    round(shadow_stats["lag_s"], 4)
                    if "lag_s" in shadow_stats else None
                ),
                "shadow_ride_samples": shadow_stats.get("samples", 0),
                "serve_pipeline_depth": cfg.serve.pipeline_depth,
                "serve_device_idle_fraction": idle_frac,
                "shed_by_tenant": shed_by_tenant,
                "overload_factor": float(overload),
            }
        finally:
            for server in servers:
                try:
                    server.close()
                except Exception:
                    pass


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--smoke", action="store_true",
                    help="tier-1 regression mode (~seconds)")
    ap.add_argument("--requests", type=int, default=600)
    ap.add_argument("--replicas", type=int, default=2)
    ap.add_argument("--overload", type=float, default=3.0,
                    help="offered rate as a multiple of measured warm "
                    "capacity")
    ap.add_argument("--out", default=None, help="write the record here")
    args = ap.parse_args()

    from deepdfa_tpu.core.backend import apply_platform_override

    os.environ.setdefault("DEEPDFA_TPU_PLATFORM", "cpu")
    apply_platform_override()

    record = bench_load(
        n_requests=args.requests,
        n_replicas=args.replicas,
        overload=args.overload,
        smoke=args.smoke,
    )
    import jax

    from deepdfa_tpu.obs import run_stamp

    record["platform"] = jax.devices()[0].platform
    record.update(run_stamp())
    print(json.dumps(record), flush=True)
    if args.out:
        Path(args.out).write_text(json.dumps(record, indent=1))


if __name__ == "__main__":
    main()
