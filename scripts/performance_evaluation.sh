#!/usr/bin/env bash
# Headline reproduction driver (reference scripts/performance_evaluation.sh:
# train DeepDFA, then the transformer baseline, then DeepDFA+combined).
#
# Hermetic by default: prepares + extracts a synthetic Big-Vul-style corpus
# first so the script runs end to end with zero downloads; point
# PREPARE_SOURCE at MSR_data_cleaned.csv for the real dataset.
#
#   PREPARE_SOURCE=synthetic N_EXAMPLES=2000 bash scripts/performance_evaluation.sh
#   PREPARE_SOURCE=/data/MSR_data_cleaned.csv bash scripts/performance_evaluation.sh
set -euo pipefail
cd "$(dirname "$0")/.."

PREPARE_SOURCE="${PREPARE_SOURCE:-synthetic}"
N_EXAMPLES="${N_EXAMPLES:-2000}"
SEED="${SEED:-1}"

prepare_args=(--source "$PREPARE_SOURCE")
if [ "$PREPARE_SOURCE" = "synthetic" ]; then
    prepare_args+=(--n-examples "$N_EXAMPLES")
fi
python -m deepdfa_tpu.cli prepare "${prepare_args[@]}" --dep-closure
python -m deepdfa_tpu.cli extract

# 1) DeepDFA (reference DDFA/scripts/train.sh, seed_everything 1)
bash scripts/train_bigvul.sh "train.seed=$SEED" "run_name=perf_deepdfa_s$SEED"

# 2) transformer baseline + 3) DeepDFA+combined (reference
#    msr_train_linevul.sh / msr_train_combined.sh; one command each here —
#    --no-graph drops the graph branch for the pure-transformer baseline)
python -m deepdfa_tpu.cli train-combined --no-graph \
    "train.seed=$SEED" "run_name=perf_linevul_s$SEED" "$@"
bash scripts/train_combined.sh "train.seed=$SEED" "run_name=perf_combined_s$SEED" "$@"
