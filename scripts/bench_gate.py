#!/usr/bin/env python
"""Bench regression gate: compare the newest bench record against the
committed BENCH_r*/BENCH_TPU_* trajectory with per-metric tolerances
and emit a pass/fail markdown verdict (deepdfa_tpu/obs/bench_gate.py,
docs/slo.md).

The failure classes the verdict distinguishes:
  regression    a gated metric fell outside tolerance vs the newest
                healthy same-platform reference
  cpu_fallback  the record ran on CPU because the accelerator probe
                failed — BENCH_r02..r05's silent failure mode, now an
                explicit gate failure (exit 2) instead of a buried
                "fallback_from" string
  error         the record is an error record

Modes:
  python scripts/bench_gate.py --record out.json      # gate one record
  python scripts/bench_gate.py                        # newest BENCH_r*
  python scripts/bench_gate.py --multichip [PATH]     # gate a
        MULTICHIP_r* artifact round-over-round (per-mesh-shape ledger
        sites, compile seconds, the serve ladder's zero-recompile pin)
        against the newest healthy same-device-count round; PATH
        defaults to the newest committed MULTICHIP_r*.json
  python scripts/bench_gate.py --tuned [PATH]         # gate a
        tuned.json / TUNED_r* document round-over-round (winner step
        time, fitted ladder waste, fit-beats-pow2, search-seconds
        bound) against the newest same-hardware-key round; PATH
        defaults to the newest committed TUNED_r*.json
  python scripts/bench_gate.py --drill [PATH]         # gate a
        DRILL_r* chaos-drill record round-over-round (measured
        failover/reseed/readmit/rollback times vs the newest healthy
        same-mode round, plus the documented 3.2 s failover bound as
        an absolute ceiling); PATH defaults to the newest committed
        DRILL_r*.json (fleet/drill.py, docs/fleet.md)
  python scripts/bench_gate.py --smoke                # tier-1: verify
        the classifier on synthetic pass/regression/fallback records

Exit codes: 0 pass, 1 regression/error, 2 cpu_fallback (the class the
driver should page on differently: the backend is sick, not the code).

Stdlib-only on purpose — the gate must run when jax/the backend is the
broken thing.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from pathlib import Path

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPO = Path(__file__).resolve().parent.parent


def newest_record(root: Path):
    from deepdfa_tpu.obs.bench_gate import load_trajectory

    trajectory = load_trajectory(root)
    rounds = [e for e in trajectory if e.get("round") is not None]
    for entry in reversed(rounds):
        if isinstance(entry.get("record"), dict):
            return entry["record"], entry["source"], trajectory
    raise SystemExit(
        f"no parseable BENCH_r*.json record under {root}"
    )


def run_smoke() -> int:
    """Tier-1 self-check: a synthetic trajectory plus three synthetic
    candidates must classify as pass / regression / cpu_fallback."""
    from deepdfa_tpu.obs import bench_gate as bg

    trajectory = [
        {
            "source": "BENCH_r01.json", "round": 1,
            "record": {
                "metric": "deepdfa_infer_graphs_per_sec",
                "value": 4000.0, "platform": "tpu",
                "train_graphs_per_sec": 3500.0, "mfu": 0.003,
            },
        },
        {
            "source": "BENCH_r02.json", "round": 2,
            "record": {
                "metric": "deepdfa_infer_graphs_per_sec",
                "value": 4100.0, "platform": "tpu",
                "train_graphs_per_sec": 3600.0, "mfu": 0.003,
            },
        },
    ]
    ok_rec = {
        "metric": "deepdfa_infer_graphs_per_sec",
        "value": 4050.0, "platform": "tpu",
        "train_graphs_per_sec": 3590.0, "mfu": 0.0031,
    }
    slow_rec = dict(ok_rec, value=2000.0)
    fallback_rec = {
        "metric": "deepdfa_infer_graphs_per_sec",
        "value": 370.0, "platform": "cpu",
        "fallback_from": "probe: backend probe timed out after 120s "
        "(compile service wedged?)",
    }
    results = {
        "pass": bg.gate(ok_rec, trajectory),
        "regression": bg.gate(slow_rec, trajectory),
        "cpu_fallback": bg.gate(fallback_rec, trajectory),
    }
    checks = [
        results["pass"]["verdict"] == "pass",
        results["regression"]["verdict"] == "fail",
        "regression" in results["regression"]["failure_classes"],
        results["cpu_fallback"]["verdict"] == "fail",
        "cpu_fallback" in results["cpu_fallback"]["failure_classes"],
        # a fallback record must not be judged against the tpu baseline
        not results["cpu_fallback"]["checks"],
        # the real committed trajectory parses (r1 has no record — a
        # failed round; r2..r4 parse; watchdog captures interleave)
        any(
            isinstance(e.get("record"), dict)
            for e in bg.load_trajectory(REPO)
        ),
    ]
    print(bg.render_markdown(results["regression"], slow_rec))
    print(json.dumps({
        "ok": all(checks),
        "checks_passed": sum(checks),
        "checks_total": len(checks),
        "verdicts": {
            k: {"verdict": v["verdict"], "classes": v["failure_classes"]}
            for k, v in results.items()
        },
    }), flush=True)
    print(f"bench_gate smoke {'OK' if all(checks) else 'FAILED'}")
    return 0 if all(checks) else 1


def run_multichip(args) -> int:
    """`--multichip [PATH]`: gate one MULTICHIP artifact against the
    committed MULTICHIP_r* trajectory (same exit-code contract as the
    bench gate: 0 pass, 1 regression/error)."""
    from deepdfa_tpu.obs.bench_gate import (
        gate_multichip,
        load_multichip_trajectory,
        render_markdown,
    )

    root = Path(args.root)
    trajectory = load_multichip_trajectory(root)
    exclude = None
    if args.multichip:
        path = Path(args.multichip)
        artifact = json.loads(path.read_text())
        source = str(path)
        if path.resolve().parent == root.resolve():
            exclude = path.name
    else:
        candidates = [
            e for e in trajectory if isinstance(e.get("artifact"), dict)
        ]
        if not candidates:
            raise SystemExit(
                f"no parseable MULTICHIP_r*.json under {root}"
            )
        artifact = candidates[-1]["artifact"]
        source = exclude = candidates[-1]["source"]

    tolerances = {}
    for spec in args.tolerance:
        metric, _, frac = spec.partition("=")
        tolerances[metric] = float(frac)
    result = gate_multichip(
        artifact, trajectory,
        tolerances=tolerances or None,
        exclude_source=exclude,
    )
    result["record_source"] = source
    md = render_markdown(result)
    print(md)
    print(json.dumps(result), flush=True)
    if args.out:
        Path(args.out).write_text(json.dumps(result, indent=1))
    if args.markdown_out:
        Path(args.markdown_out).write_text(md)
    return 0 if result["verdict"] == "pass" else 1


def run_tuned(args) -> int:
    """`--tuned [PATH]`: gate one tuned.json / TUNED_r* document against
    the committed TUNED_r* trajectory (deepdfa_tpu/tune/, docs/tuning.md;
    same exit-code contract: 0 pass, 1 regression/error)."""
    from deepdfa_tpu.obs.bench_gate import gate_tuned, render_markdown
    from deepdfa_tpu.tune.cache import load_tuned_trajectory

    root = Path(args.root)
    trajectory = load_tuned_trajectory(root)
    exclude = None
    if args.tuned:
        path = Path(args.tuned)
        artifact = json.loads(path.read_text())
        source = str(path)
        if path.resolve().parent == root.resolve():
            exclude = path.name
    else:
        candidates = [
            e for e in trajectory if isinstance(e.get("record"), dict)
        ]
        if not candidates:
            raise SystemExit(f"no parseable TUNED_r*.json under {root}")
        artifact = candidates[-1]["record"]
        source = exclude = candidates[-1]["source"]

    tolerances = {}
    for spec in args.tolerance:
        metric, _, frac = spec.partition("=")
        tolerances[metric] = float(frac)
    result = gate_tuned(
        artifact, trajectory,
        tolerances=tolerances or None,
        exclude_source=exclude,
    )
    result["record_source"] = source
    md = render_markdown(result)
    print(md)
    print(json.dumps(result), flush=True)
    if args.out:
        Path(args.out).write_text(json.dumps(result, indent=1))
    if args.markdown_out:
        Path(args.markdown_out).write_text(md)
    return 0 if result["verdict"] == "pass" else 1


def run_drill(args) -> int:
    """`--drill [PATH]`: gate one DRILL record against the committed
    DRILL_r* trajectory (fleet/drill.py, docs/fleet.md; same exit-code
    contract: 0 pass, 1 regression/error)."""
    from deepdfa_tpu.obs.bench_gate import (
        gate_drill,
        load_drill_trajectory,
        render_markdown,
    )

    root = Path(args.root)
    trajectory = load_drill_trajectory(root)
    exclude = None
    if args.drill:
        path = Path(args.drill)
        record = json.loads(path.read_text())
        source = str(path)
        if path.resolve().parent == root.resolve():
            exclude = path.name
    else:
        candidates = [
            e for e in trajectory if isinstance(e.get("record"), dict)
        ]
        if not candidates:
            raise SystemExit(f"no parseable DRILL_r*.json under {root}")
        record = candidates[-1]["record"]
        source = exclude = candidates[-1]["source"]

    tolerances = {}
    for spec in args.tolerance:
        metric, _, frac = spec.partition("=")
        tolerances[metric] = float(frac)
    result = gate_drill(
        record, trajectory,
        tolerances=tolerances or None,
        exclude_source=exclude,
    )
    result["record_source"] = source
    md = render_markdown(result)
    print(md)
    print(json.dumps(result), flush=True)
    if args.out:
        Path(args.out).write_text(json.dumps(result, indent=1))
    if args.markdown_out:
        Path(args.markdown_out).write_text(md)
    return 0 if result["verdict"] == "pass" else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--record", default=None,
                    help="candidate record JSON path (default: newest "
                    "parseable BENCH_r*.json round)")
    ap.add_argument("--root", default=str(REPO),
                    help="directory holding BENCH_r*/BENCH_TPU_* artifacts")
    ap.add_argument("--expect-platform", default=None,
                    help="fail as cpu_fallback unless the record ran "
                    "on this platform (e.g. tpu)")
    ap.add_argument("--tolerance", action="append", default=[],
                    metavar="METRIC=FRAC",
                    help="override a per-metric tolerance fraction")
    ap.add_argument("--out", default=None, help="write verdict JSON here")
    ap.add_argument("--markdown-out", default=None,
                    help="write the markdown verdict here")
    ap.add_argument("--multichip", nargs="?", const="", default=None,
                    metavar="PATH",
                    help="gate a MULTICHIP_r* artifact round-over-round "
                    "(per-mesh-shape ledger sites + the serve ladder's "
                    "zero-recompile pin) against the newest healthy "
                    "same-device-count round; default: the newest "
                    "committed MULTICHIP_r*.json")
    ap.add_argument("--tuned", nargs="?", const="", default=None,
                    metavar="PATH",
                    help="gate a tuned.json / TUNED_r* document "
                    "round-over-round (winner step time, fitted ladder "
                    "waste, fit-beats-pow2, search-seconds bound) "
                    "against the newest same-hardware round; default: "
                    "the newest committed TUNED_r*.json "
                    "(deepdfa_tpu/tune/, docs/tuning.md)")
    ap.add_argument("--drill", nargs="?", const="", default=None,
                    metavar="PATH",
                    help="gate a DRILL_r* chaos-drill record "
                    "round-over-round (measured recovery times vs the "
                    "newest healthy same-mode round + the 3.2 s "
                    "failover bound as an absolute ceiling); default: "
                    "the newest committed DRILL_r*.json "
                    "(fleet/drill.py, docs/fleet.md)")
    ap.add_argument("--smoke", action="store_true",
                    help="tier-1 classifier self-check on synthetic "
                    "records")
    args = ap.parse_args(argv)

    if args.smoke:
        return run_smoke()

    if args.multichip is not None:
        return run_multichip(args)

    if args.tuned is not None:
        return run_tuned(args)

    if args.drill is not None:
        return run_drill(args)

    from deepdfa_tpu.obs.bench_gate import (
        gate,
        load_trajectory,
        render_markdown,
    )

    root = Path(args.root)
    exclude = None
    if args.record:
        record = json.loads(Path(args.record).read_text())
        if isinstance(record, dict) and isinstance(
            record.get("parsed"), dict
        ):
            record = record["parsed"]  # accept a raw driver artifact
        trajectory = load_trajectory(root)
        source = args.record
        # a --record path naming a committed artifact is that artifact
        if Path(args.record).resolve().parent == root.resolve():
            exclude = Path(args.record).name
    else:
        record, source, trajectory = newest_record(root)
        exclude = source  # never judge the newest round against itself

    tolerances = {}
    for spec in args.tolerance:
        metric, _, frac = spec.partition("=")
        tolerances[metric] = float(frac)
    result = gate(
        record, trajectory,
        tolerances=tolerances or None,
        expect_platform=args.expect_platform,
        exclude_source=exclude,
    )
    result["record_source"] = source
    md = render_markdown(result, record)
    print(md)
    print(json.dumps(result), flush=True)
    if args.out:
        Path(args.out).write_text(json.dumps(result, indent=1))
    if args.markdown_out:
        Path(args.markdown_out).write_text(md)
    if result["verdict"] == "pass":
        return 0
    return 2 if "cpu_fallback" in result["failure_classes"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
