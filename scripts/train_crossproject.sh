#!/usr/bin/env bash
# Cross-project generalization flow (reference
# LineVul/linevul/scripts/cross_project_train_{linevul,combined}.sh +
# cross_project_eval_*.sh; paper Table 7): project-disjoint splits ->
# preprocess -> train -> test. The project column of the Big-Vul csv
# drives the split (readers.cross_project_splits).
# Usage: train_crossproject.sh MSR_data_cleaned.csv [seed] [extra cli args]
set -euo pipefail
cd "$(dirname "$0")/.."

CSV="${1:?usage: train_crossproject.sh MSR_data_cleaned.csv [seed]}"
SEED="${2:-0}"
shift $(( $# >= 2 ? 2 : 1 ))

python -m deepdfa_tpu.cli prepare --source "$CSV" --cross-project \
    --dep-closure data.seed="$SEED" "$@"
python -m deepdfa_tpu.cli extract-vocab --workers "$(nproc)" "$@"
python -m deepdfa_tpu.cli extract --workers "$(nproc)" "$@"
python -m deepdfa_tpu.cli train --config configs/bigvul_deepdfa.json \
    train.seed="$SEED" "$@"
python -m deepdfa_tpu.cli test --config configs/bigvul_deepdfa.json --export "$@"
