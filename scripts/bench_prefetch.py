#!/usr/bin/env python
"""Host input-pipeline benchmark: prefetch overlap + packed-batch cache.

Two measurements over the same flagship GraphSpec corpus (ISSUE 1):

1. prefetch_overlap_speedup — GraphTrainer.fit wall-clock with
   train.prefetch_batches=0 (inline assembly) vs the default 2
   (background producers + sharded device_put), same seed — numerics are
   bit-identical either way (tests/test_prefetch.py), so the only delta
   is wall-clock.

2. cache_replay_speedup — end-to-end epoch throughput of the CURRENT
   cold path (frontend extraction + per-epoch shard_bucket_batches
   repack + train) vs a WARM packed-batch cache (data/packed_cache.py:
   mmap replay + train). The cold path is what every re-run pays today;
   the warm path is what it pays once the content-keyed cache exists.
   Device compute is held small so the HOST pipeline — the thing this
   script regression-tests — dominates the way it does on TPU, where a
   step is ~ms and the host is the bound (BENCH_r05: 0.67% MFU).

On the 1-core CPU build box compute and assembly contend for the same
core, so the overlap win is a LOWER bound; on TPU the device computes
while the host assembles, which is where the overlap pays.

    DEEPDFA_TPU_PLATFORM=cpu python scripts/bench_prefetch.py
    python scripts/bench_prefetch.py --smoke   # tier-1 regression mode
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

BUDGETS = dict(
    num_shards=1, num_graphs=256, node_budget=16384, edge_budget=65536
)


def _make_trainer(cfg_overrides, sample_batch):
    from deepdfa_tpu.core import Config, config as config_mod
    from deepdfa_tpu.models import DeepDFA
    from deepdfa_tpu.train import GraphTrainer

    cfg = config_mod.apply_overrides(Config(), cfg_overrides)
    model = DeepDFA.from_config(cfg.model, input_dim=1002)
    trainer = GraphTrainer(model, cfg)
    state = trainer.init_state(sample_batch)
    return trainer, state


def _warm_compile(trainer, state, batch):
    """One step outside every timed window, with the SAME committed
    sharding the fit loop's device_placer uses — otherwise the first
    timed step would recompile inside the window."""
    import jax

    from deepdfa_tpu.data.prefetch import device_placer

    state, _ = trainer.train_step(state, device_placer(trainer.mesh)(batch))
    jax.block_until_ready(state.params)
    return state


def bench_overlap(specs, epochs: int, model_overrides) -> dict:
    """fit wall-clock, prefetch off vs on (per-epoch live repack both)."""
    import jax

    from deepdfa_tpu.graphs import shard_bucket_batches

    def train_batches(_epoch):
        # per-epoch assembly, as in the CLI trainer (this is the host
        # work the prefetch producers overlap with device compute)
        return shard_bucket_batches(specs, oversized="raise", **BUDGETS)

    first = next(iter(train_batches(0)))
    results = {}
    for depth in (0, 2):
        trainer, state = _make_trainer(
            [
                f"train.prefetch_batches={depth}",
                f"train.max_epochs={epochs}",
                *model_overrides,
            ],
            first,
        )
        state = _warm_compile(trainer, state, first)
        t0 = time.perf_counter()
        state = trainer.fit(state, train_batches)
        jax.block_until_ready(state.params)
        results[f"prefetch_{depth}"] = round(time.perf_counter() - t0, 2)
    off, on = results["prefetch_0"], results["prefetch_2"]
    return {
        "metric": "prefetch_overlap_speedup",
        "value": round(off / on, 3) if on else None,
        "unit": "x (fit wall-clock, prefetch off/on)",
        "seconds_prefetch_off": off,
        "seconds_prefetch_on": on,
    }


def bench_cache(
    specs, frontend_seconds: float, epochs: int, model_overrides
) -> dict:
    """End-to-end epoch throughput: cold (frontend + per-epoch repack +
    train) vs warm packed-batch cache (mmap replay + train)."""
    import jax

    from deepdfa_tpu.data.packed_cache import (
        PackedBatchCache,
        cache_key,
        corpus_digest,
    )
    from deepdfa_tpu.graphs import shard_bucket_batches

    def repack(_epoch):
        return shard_bucket_batches(specs, oversized="raise", **BUDGETS)

    first = next(iter(repack(0)))
    n_graphs = len(specs)
    overrides = [f"train.max_epochs={epochs}", *model_overrides]

    epoch_records: list[dict] = []

    def log_fn(rec):
        if "epoch" in rec:
            epoch_records.append(rec)

    # cold: what a fresh run pays today — frontend (already timed by the
    # caller) + per-epoch repack + train
    trainer, state = _make_trainer(overrides, first)
    state = _warm_compile(trainer, state, first)
    t0 = time.perf_counter()
    state = trainer.fit(state, repack, log_fn=log_fn)
    jax.block_until_ready(state.params)
    cold_seconds = frontend_seconds + (time.perf_counter() - t0)
    cold_pack = sum(r["host_pack_seconds"] for r in epoch_records)

    # warm: same batches, same order (tests/test_packed_cache.py pins
    # bit-identity), replayed zero-copy from the content-keyed cache
    with tempfile.TemporaryDirectory() as d:
        cache = PackedBatchCache(d)
        key = cache_key(BUDGETS, corpus_digest(specs))
        list(cache.get_or_pack(key, lambda: repack(0)))  # build, untimed
        epoch_records.clear()
        # train_step donates the state buffers, so the warm phase gets
        # its own (identically configured) trainer and fresh state
        trainer, state = _make_trainer(overrides, first)
        state = _warm_compile(trainer, state, first)
        t0 = time.perf_counter()
        state = trainer.fit(
            state, lambda e: cache.replay(key), log_fn=log_fn,
            source_stage="load",
        )
        jax.block_until_ready(state.params)
        warm_seconds = time.perf_counter() - t0
    warm_load = sum(r["host_load_seconds"] for r in epoch_records)
    warm_wait = sum(r["input_wait_seconds"] for r in epoch_records)

    return {
        "metric": "cache_replay_speedup",
        "value": round(cold_seconds / warm_seconds, 3) if warm_seconds else None,
        "unit": "x (epoch throughput, warm packed-batch cache vs cold "
        "frontend+repack)",
        "cold_seconds": round(cold_seconds, 2),
        "warm_seconds": round(warm_seconds, 2),
        "cold_frontend_seconds": round(frontend_seconds, 2),
        "cold_pack_seconds": round(cold_pack, 3),
        "warm_load_seconds": round(warm_load, 3),
        "warm_input_wait_seconds": round(warm_wait, 3),
        "cold_graphs_per_sec": round(epochs * n_graphs / cold_seconds, 1),
        "warm_graphs_per_sec": round(epochs * n_graphs / warm_seconds, 1),
    }


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--n-examples", type=int, default=1000)
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument(
        "--smoke",
        action="store_true",
        help="tier-1 regression mode: tiny corpus/model on CPU, exercises "
        "every pipeline stage (frontend -> pack -> cache -> prefetch -> "
        "place -> train) in well under a minute",
    )
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    if args.smoke:
        os.environ.setdefault("DEEPDFA_TPU_PLATFORM", "cpu")
        args.n_examples = min(args.n_examples, 128)
        args.epochs = min(args.epochs, 2)
    # device compute held small so the host pipeline — the thing this
    # script regression-tests — dominates the way it does on TPU
    # (docstring); both modes use the same model so smoke tracks the
    # full measurement
    model_overrides = ["model.hidden_dim=16", "model.n_steps=2"]

    from deepdfa_tpu.core.backend import apply_platform_override

    apply_platform_override()
    import jax

    from deepdfa_tpu.data import flagship_corpus

    t0 = time.perf_counter()
    specs = flagship_corpus(args.n_examples)
    frontend_seconds = time.perf_counter() - t0

    overlap = bench_overlap(specs, args.epochs, model_overrides)
    cache = bench_cache(specs, frontend_seconds, args.epochs, model_overrides)

    record = {
        **overlap,
        "cache": cache,
        "cache_replay_speedup": cache["value"],
        "platform": jax.devices()[0].platform,
        "n_examples": args.n_examples,
        "epochs": args.epochs,
        "smoke": args.smoke,
        "note": "1-core CPU hosts understate the overlap win (assembly "
        "and compute share the core); on TPU the host assembles while "
        "the device computes",
    }
    print(json.dumps(record), flush=True)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(record, f, indent=1)


if __name__ == "__main__":
    main()
