#!/usr/bin/env python
"""Measure the async-input-pipeline overlap win (VERDICT r2 item 6).

Times GraphTrainer.fit epochs over the same pre-built GraphSpec corpus
with train.prefetch_batches=0 (inline assembly) vs the default 2
(background thread + sharded device_put), same seed — numerics are
bit-identical either way (tests/test_prefetch.py), so the only delta is
wall-clock. Batch ASSEMBLY (bucketing/padding) runs per epoch inside the
train_batches callable, exactly as the CLI trainer does.

On the 1-core CPU build box, compute and assembly contend for the same
core, so the measured win is a LOWER bound; on TPU the device computes
while the host assembles, which is where the overlap pays.

    DEEPDFA_TPU_PLATFORM=cpu python scripts/bench_prefetch.py
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--n-examples", type=int, default=2000)
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    from deepdfa_tpu.core.backend import apply_platform_override

    apply_platform_override()
    import jax

    from deepdfa_tpu.core import Config, config as config_mod
    from deepdfa_tpu.data import flagship_corpus
    from deepdfa_tpu.data.prefetch import device_placer
    from deepdfa_tpu.graphs import shard_bucket_batches
    from deepdfa_tpu.models import DeepDFA
    from deepdfa_tpu.train import GraphTrainer

    n = args.n_examples
    specs = flagship_corpus(n)

    def train_batches(_epoch):
        # per-epoch assembly, as in the CLI trainer (this is the host
        # work the prefetch thread overlaps with device compute)
        return shard_bucket_batches(
            specs, 1, 256, 16384, 65536, oversized="raise"
        )

    results = {}
    for depth in (0, 2):
        cfg = config_mod.apply_overrides(
            Config(),
            [
                f"train.prefetch_batches={depth}",
                f"train.max_epochs={args.epochs}",
            ],
        )
        model = DeepDFA.from_config(cfg.model, input_dim=1002)
        trainer = GraphTrainer(model, cfg)
        state = trainer.init_state(next(iter(train_batches(0))))
        # compile outside the timed window — with the SAME committed
        # sharding the fit loop's device_placer uses, or the first timed
        # step would recompile inside both windows
        warm = device_placer(trainer.mesh)(next(iter(train_batches(0))))
        state, _ = trainer.train_step(state, warm)
        jax.block_until_ready(state.params)
        t0 = time.perf_counter()
        state = trainer.fit(state, train_batches)
        jax.block_until_ready(state.params)
        results[f"prefetch_{depth}"] = round(time.perf_counter() - t0, 2)

    off, on = results["prefetch_0"], results["prefetch_2"]
    record = {
        "metric": "prefetch_overlap_speedup",
        "value": round(off / on, 3) if on else None,
        "unit": "x (fit wall-clock, prefetch off/on)",
        "seconds_prefetch_off": off,
        "seconds_prefetch_on": on,
        "platform": jax.devices()[0].platform,
        "n_examples": n,
        "epochs": args.epochs,
        "note": "1-core CPU hosts understate the win (assembly and "
        "compute share the core); on TPU the host assembles while the "
        "device computes",
    }
    print(json.dumps(record), flush=True)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(record, f, indent=1)


if __name__ == "__main__":
    main()
