#!/usr/bin/env python
"""Host input-pipeline benchmark: prefetch overlap + packed-batch cache +
sequence-length bucketing.

Two measurements over the same flagship GraphSpec corpus (ISSUE 1), plus
one over a combined-model text workload (ISSUE 2):

1. prefetch_overlap_speedup — GraphTrainer.fit wall-clock with
   train.prefetch_batches=0 (inline assembly) vs the default 2
   (background producers + sharded device_put), same seed — numerics are
   bit-identical either way (tests/test_prefetch.py), so the only delta
   is wall-clock.

2. cache_replay_speedup — end-to-end epoch throughput of the CURRENT
   cold path (frontend extraction + per-epoch shard_bucket_batches
   repack + train) vs a WARM packed-batch cache (data/packed_cache.py:
   mmap replay + train). The cold path is what every re-run pays today;
   the warm path is what it pays once the content-keyed cache exists.
   Device compute is held small so the HOST pipeline — the thing this
   script regression-tests — dominates the way it does on TPU, where a
   step is ~ms and the host is the bound (BENCH_r05: 0.67% MFU).

3. combined_train_tokens_per_sec — the combined (transformer+graph)
   text path with pad-to-max_length collation vs sequence-length
   bucketing (data/text.py: pad-to-bucket + token-budget batch sizing +
   the trainer's warmup'd signature cache). Reports REAL-token
   throughput and padding-waste fraction alongside examples/sec — the
   shape-invariant numbers that make the bucketing win measurable on the
   CPU fallback too — and regression-checks bucket assignment (real
   tokens conserved vs the fixed path), packed-cache replay
   (bit-identical), and zero steady-state recompiles after warmup.

On the 1-core CPU build box compute and assembly contend for the same
core, so the overlap win is a LOWER bound; on TPU the device computes
while the host assembles, which is where the overlap pays.

    DEEPDFA_TPU_PLATFORM=cpu python scripts/bench_prefetch.py
    python scripts/bench_prefetch.py --smoke   # tier-1 regression mode
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

BUDGETS = dict(
    num_shards=1, num_graphs=256, node_budget=16384, edge_budget=65536
)


def _make_trainer(cfg_overrides, sample_batch):
    from deepdfa_tpu.core import Config, config as config_mod
    from deepdfa_tpu.models import DeepDFA
    from deepdfa_tpu.train import GraphTrainer

    cfg = config_mod.apply_overrides(Config(), cfg_overrides)
    model = DeepDFA.from_config(cfg.model, input_dim=1002)
    trainer = GraphTrainer(model, cfg)
    state = trainer.init_state(sample_batch)
    return trainer, state


def _warm_compile(trainer, state, batch):
    """One step outside every timed window, with the SAME committed
    sharding the fit loop's device_placer uses — otherwise the first
    timed step would recompile inside the window."""
    import jax

    from deepdfa_tpu.data.prefetch import device_placer

    state, _ = trainer.train_step(state, device_placer(trainer.mesh)(batch))
    jax.block_until_ready(state.params)
    return state


def bench_overlap(specs, epochs: int, model_overrides) -> dict:
    """fit wall-clock, prefetch off vs on (per-epoch live repack both)."""
    import jax

    from deepdfa_tpu.graphs import shard_bucket_batches

    def train_batches(_epoch):
        # per-epoch assembly, as in the CLI trainer (this is the host
        # work the prefetch producers overlap with device compute)
        return shard_bucket_batches(specs, oversized="raise", **BUDGETS)

    first = next(iter(train_batches(0)))
    results = {}
    for depth in (0, 2):
        trainer, state = _make_trainer(
            [
                f"train.prefetch_batches={depth}",
                f"train.max_epochs={epochs}",
                *model_overrides,
            ],
            first,
        )
        state = _warm_compile(trainer, state, first)
        t0 = time.perf_counter()
        state = trainer.fit(state, train_batches)
        jax.block_until_ready(state.params)
        results[f"prefetch_{depth}"] = round(time.perf_counter() - t0, 2)
    off, on = results["prefetch_0"], results["prefetch_2"]
    return {
        "metric": "prefetch_overlap_speedup",
        "value": round(off / on, 3) if on else None,
        "unit": "x (fit wall-clock, prefetch off/on)",
        "seconds_prefetch_off": off,
        "seconds_prefetch_on": on,
    }


def bench_cache(
    specs, frontend_seconds: float, epochs: int, model_overrides
) -> dict:
    """End-to-end epoch throughput: cold (frontend + per-epoch repack +
    train) vs warm packed-batch cache (mmap replay + train)."""
    import jax

    from deepdfa_tpu.data.packed_cache import (
        PackedBatchCache,
        cache_key,
        corpus_digest,
    )
    from deepdfa_tpu.graphs import shard_bucket_batches

    def repack(_epoch):
        return shard_bucket_batches(specs, oversized="raise", **BUDGETS)

    first = next(iter(repack(0)))
    n_graphs = len(specs)
    overrides = [f"train.max_epochs={epochs}", *model_overrides]

    epoch_records: list[dict] = []

    def log_fn(rec):
        if "epoch" in rec:
            epoch_records.append(rec)

    # cold: what a fresh run pays today — frontend (already timed by the
    # caller) + per-epoch repack + train
    trainer, state = _make_trainer(overrides, first)
    state = _warm_compile(trainer, state, first)
    t0 = time.perf_counter()
    state = trainer.fit(state, repack, log_fn=log_fn)
    jax.block_until_ready(state.params)
    cold_seconds = frontend_seconds + (time.perf_counter() - t0)
    cold_pack = sum(r["host_pack_seconds"] for r in epoch_records)

    # warm: same batches, same order (tests/test_packed_cache.py pins
    # bit-identity), replayed zero-copy from the content-keyed cache
    with tempfile.TemporaryDirectory() as d:
        cache = PackedBatchCache(d)
        key = cache_key(BUDGETS, corpus_digest(specs))
        list(cache.get_or_pack(key, lambda: repack(0)))  # build, untimed
        epoch_records.clear()
        # train_step donates the state buffers, so the warm phase gets
        # its own (identically configured) trainer and fresh state
        trainer, state = _make_trainer(overrides, first)
        state = _warm_compile(trainer, state, first)
        t0 = time.perf_counter()
        state = trainer.fit(
            state, lambda e: cache.replay(key), log_fn=log_fn,
            source_stage="load",
        )
        jax.block_until_ready(state.params)
        warm_seconds = time.perf_counter() - t0
    warm_load = sum(r["host_load_seconds"] for r in epoch_records)
    warm_wait = sum(r["input_wait_seconds"] for r in epoch_records)

    return {
        "metric": "cache_replay_speedup",
        "value": round(cold_seconds / warm_seconds, 3) if warm_seconds else None,
        "unit": "x (epoch throughput, warm packed-batch cache vs cold "
        "frontend+repack)",
        "cold_seconds": round(cold_seconds, 2),
        "warm_seconds": round(warm_seconds, 2),
        "cold_frontend_seconds": round(frontend_seconds, 2),
        "cold_pack_seconds": round(cold_pack, 3),
        "warm_load_seconds": round(warm_load, 3),
        "warm_input_wait_seconds": round(warm_wait, 3),
        "cold_graphs_per_sec": round(epochs * n_graphs / cold_seconds, 1),
        "warm_graphs_per_sec": round(epochs * n_graphs / warm_seconds, 1),
    }


def build_text_workload(n: int, seq: int, vocab: int = 512):
    """Synthetic combined-model text workload (also used by bench.py's
    --child-combined): corpus -> tokenized rows + aligned graphs.
    Returns (token_ids_by_id, labels_by_id, graphs_by_id, lengths, tok).
    """
    from deepdfa_tpu.data import build_dataset, generate, to_examples
    from deepdfa_tpu.data.text import token_lengths
    from deepdfa_tpu.data.tokenizer import HashTokenizer

    synth = generate(n, vuln_rate=0.3, seed=7)
    specs, _ = build_dataset(
        to_examples(synth), train_ids=range(n), limit_all=100,
        limit_subkeys=100,
    )
    tok = HashTokenizer(vocab_size=vocab)
    mat = tok.batch_encode([s.before for s in synth], max_length=seq)
    token_ids = {i: mat[i] for i in range(n)}
    labels = {i: int(s.label) for i, s in enumerate(synth)}
    by_id = {s.graph_id: s for s in specs}
    return token_ids, labels, by_id, token_lengths(mat, tok.pad_id), tok


def bench_bucketed(n_examples: int, epochs: int, smoke: bool = False) -> dict:
    """Fixed pad-to-max_length vs bucketed token-budget collation on the
    combined tiny model: examples/sec, REAL tokens/sec, padding waste."""
    import numpy as np

    from deepdfa_tpu.core import Config, config as config_mod
    from deepdfa_tpu.data.packed_cache import (
        PackedBatchCache,
        cache_key,
        text_corpus_digest,
    )
    from deepdfa_tpu.data.text import (
        TEXT_ARRAY_FIELDS,
        batch_token_counts,
        bucketed_collate_batches,
        collate_shards,
    )
    from deepdfa_tpu.models import combined as cmb
    from deepdfa_tpu.models.transformer import TransformerConfig
    from deepdfa_tpu.train.combined_loop import CombinedTrainer

    # the LineVul recipe's 512-token frame: synthetic function lengths
    # are lognormal-ish with median far below 512, which is exactly the
    # distribution the fixed pad-to-max collation wastes FLOPs on
    seq = 512
    bucket_edges = (128, 256, 512)
    # smoke bounds the corpus for tier-1; the full mode honors the
    # caller's size exactly (no silent cap — the record's n_examples is
    # what actually ran)
    n = min(n_examples, 64) if smoke else int(n_examples)
    epochs = max(1, epochs)
    rows = 16  # the legacy fixed recipe's batch rows
    token_budget = rows * seq  # same activation footprint per batch
    node_budget, edge_budget = 2048, 8192
    token_ids, labels, by_id, lengths, tok = build_text_workload(n, seq)

    mcfg = cmb.CombinedConfig(
        encoder=TransformerConfig.tiny(
            vocab_size=tok.vocab_size, dropout_rate=0.0,
            max_position_embeddings=seq + 4,
        ),
        graph_hidden_dim=8,
        graph_input_dim=102,
    )
    cfg = config_mod.apply_overrides(
        Config(), [f"train.max_epochs={epochs}"]
    )
    ids = list(range(n))
    fixed = []
    for k in range(0, n, rows):
        sel = ids[k : k + rows]
        fixed.append(
            collate_shards(
                np.stack([token_ids[i] for i in sel]),
                [labels[i] for i in sel], sel, by_id,
                num_shards=1, rows_per_shard=rows,
                node_budget=node_budget, edge_budget=edge_budget,
                pad_id=tok.pad_id,
            )
        )
    bucketed = list(
        bucketed_collate_batches(
            token_ids, labels, ids, by_id, bucket_edges, token_budget,
            1, node_budget, edge_budget, pad_id=tok.pad_id,
            lengths=lengths,
        )
    )
    # bucket-assignment regression check: the exact real-token mass must
    # be conserved across layouts (the property test pins the multiset;
    # this pins it end-to-end in the bench workload)
    real_of = lambda bs: sum(  # noqa: E731
        batch_token_counts(b.input_ids, b.row_mask, tok.pad_id)[0]
        for b in bs
    )
    if real_of(bucketed) != real_of(fixed):
        raise AssertionError(
            f"bucketed collation lost tokens: {real_of(bucketed)} != "
            f"{real_of(fixed)}"
        )

    def run(batches, warmup_buckets=None):
        import jax

        from deepdfa_tpu.parallel import make_mesh

        # batches are collated num_shards=1, so the trainer must run a
        # 1-device mesh — the default dp=-1 spans every chip and the
        # device_put dp-divisibility check would (rightly) refuse
        trainer = CombinedTrainer(
            cfg, mcfg, mesh=make_mesh(devices=jax.devices()[:1]),
            total_steps=len(batches) * epochs,
        )
        state = trainer.init_state(seed=0)
        warm_s = 0.0
        if warmup_buckets is not None:
            t0 = time.perf_counter()
            trainer.warmup(
                state, warmup_buckets, token_budget, node_budget,
                edge_budget,
            )
            warm_s = time.perf_counter() - t0
        else:
            # TWO warm steps: the first compiles against init_state's
            # shardings, the second against the (different) jit-output
            # state shardings the whole steady-state loop runs on —
            # one warm step would leave a recompile inside the timed
            # window. (The AOT warmup path is immune: the Compiled
            # executable's output state feeds back compatibly.)
            for _ in range(2):
                state, warm_loss = trainer.train_step(
                    state, trainer.place_batch(batches[0]), jax.random.key(0)
                )
                float(warm_loss)
        records = []
        state = trainer.fit(
            state, lambda e: batches,
            log_fn=lambda r: records.append(r) if "epoch" in r else None,
        )
        jax.block_until_ready(state.params)
        secs = sum(r["epoch_seconds"] for r in records)
        return {
            "seconds": secs,
            "examples_per_sec": round(epochs * n / secs, 2),
            "tokens_per_sec": round(
                sum(r["real_tokens"] for r in records) / secs, 1
            ),
            "padding_waste": records[-1]["padding_waste"],
            "warmup_compile_seconds": round(warm_s, 2),
            "lowerings": trainer.jit_lowerings(),
        }

    fixed_r = run(fixed)
    bucket_r = run(bucketed, warmup_buckets=bucket_edges)

    # replay regression: the bucketed stream must round-trip the
    # content-keyed cache bit-identically (bucket layout is in the key)
    with tempfile.TemporaryDirectory() as d:
        cache = PackedBatchCache(d)
        key = cache_key(
            dict(kind="text", seq_buckets=list(bucket_edges),
                 token_budget=token_budget, num_shards=1,
                 node_budget=node_budget, edge_budget=edge_budget,
                 pad_id=tok.pad_id),
            text_corpus_digest(token_ids, labels),
        )
        list(cache.write_through(key, iter(bucketed)))
        from deepdfa_tpu.graphs.batch import ARRAY_FIELDS

        def leaves(b):
            out = [np.asarray(getattr(b, f)) for f in TEXT_ARRAY_FIELDS]
            out += [
                np.asarray(v) for f in ARRAY_FIELDS
                if (v := getattr(b.graphs, f)) is not None
            ]
            return out

        replayed = list(cache.replay(key))
        replay_ok = len(replayed) == len(bucketed) and all(
            len(la) == len(lb) and all(map(np.array_equal, la, lb))
            for a, b in zip(replayed, bucketed)
            for la, lb in ((leaves(a), leaves(b)),)
        )
        if not replay_ok:
            raise AssertionError("bucketed cache replay diverged")

    return {
        "metric": "combined_train_tokens_per_sec",
        "value": bucket_r["tokens_per_sec"],
        "unit": "real tokens/s (combined tiny model, fit epochs)",
        "seq": seq,
        "buckets": list(bucket_edges),
        "token_budget": token_budget,
        "n_examples": n,
        "epochs": epochs,
        "n_batches_fixed": len(fixed),
        "n_batches_bucketed": len(bucketed),
        "examples_per_sec_fixed": fixed_r["examples_per_sec"],
        "examples_per_sec_bucketed": bucket_r["examples_per_sec"],
        "tokens_per_sec_fixed": fixed_r["tokens_per_sec"],
        "tokens_per_sec_bucketed": bucket_r["tokens_per_sec"],
        "padding_waste_fixed": fixed_r["padding_waste"],
        "padding_waste_bucketed": bucket_r["padding_waste"],
        "bucketed_examples_speedup": round(
            bucket_r["examples_per_sec"] / fixed_r["examples_per_sec"], 3
        ) if fixed_r["examples_per_sec"] else None,
        "warmup_compile_seconds": bucket_r["warmup_compile_seconds"],
        # len(buckets) warmup lowerings and not one more: the epoch loop
        # hit only warm signatures
        "steady_state_recompiles": bucket_r["lowerings"] - len(bucket_edges),
        "cache_replay_identical": replay_ok,
    }


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--n-examples", type=int, default=1000)
    ap.add_argument(
        "--bucketed-examples", type=int, default=256,
        help="corpus size for the bucketed (combined-model) measurement "
        "— it trains a model per layout, so it runs a smaller corpus "
        "than the pack/cache measurements by default",
    )
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument(
        "--smoke",
        action="store_true",
        help="tier-1 regression mode: tiny corpus/model on CPU, exercises "
        "every pipeline stage (frontend -> pack -> cache -> prefetch -> "
        "place -> train) in well under a minute",
    )
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    if args.smoke:
        os.environ.setdefault("DEEPDFA_TPU_PLATFORM", "cpu")
        args.n_examples = min(args.n_examples, 128)
        args.epochs = min(args.epochs, 2)
    # device compute held small so the host pipeline — the thing this
    # script regression-tests — dominates the way it does on TPU
    # (docstring); both modes use the same model so smoke tracks the
    # full measurement
    model_overrides = ["model.hidden_dim=16", "model.n_steps=2"]

    from deepdfa_tpu.core.backend import apply_platform_override

    apply_platform_override()
    import jax

    from deepdfa_tpu.data import flagship_corpus

    t0 = time.perf_counter()
    specs = flagship_corpus(args.n_examples)
    frontend_seconds = time.perf_counter() - t0

    overlap = bench_overlap(specs, args.epochs, model_overrides)
    cache = bench_cache(specs, frontend_seconds, args.epochs, model_overrides)
    bucketed = bench_bucketed(
        args.bucketed_examples, args.epochs, smoke=args.smoke
    )

    record = {
        **overlap,
        "cache": cache,
        "cache_replay_speedup": cache["value"],
        "bucketed": bucketed,
        "combined_train_tokens_per_sec": bucketed["value"],
        "combined_train_examples_per_sec": bucketed[
            "examples_per_sec_bucketed"
        ],
        "padding_waste": bucketed["padding_waste_bucketed"],
        "platform": jax.devices()[0].platform,
        "n_examples": args.n_examples,
        "epochs": args.epochs,
        "smoke": args.smoke,
        "note": "1-core CPU hosts understate the overlap win (assembly "
        "and compute share the core); on TPU the host assembles while "
        "the device computes",
    }
    # provenance stamp (ISSUE 4 satellite): comparable across PRs
    from deepdfa_tpu.obs import run_stamp

    record.update(run_stamp())
    print(json.dumps(record), flush=True)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(record, f, indent=1)


if __name__ == "__main__":
    main()
