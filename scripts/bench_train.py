#!/usr/bin/env python
"""Training-throughput benchmark: flagship DeepDFA train_step on one chip.

Reference baseline: 25 epochs over the Big-Vul train split (bs 256) in
9 minutes on an RTX 3090 (paper Table 5) — with the undersampled epoch at
~20k graphs that is roughly 925 graphs/s of training throughput.

Thin wrapper over bench.run_train_measurement (the same measurement the
driver captures into BENCH_r{N}.json as train_* fields): flagship config
(input_dim 1002, hidden 32, n_steps 5), Big-Vul-tail CFG sizes, full
train_step (forward + backward + AdamW), median steady-state window,
MFU from XLA cost analysis. scan_steps defaults on for TPU (the round-2
unrolled train compile wedged the remote compile service; lax.scan keeps
the program small) — DEEPDFA_BENCH_SCAN_STEPS=0 opts out.

    python scripts/bench_train.py                      # default backend
    DEEPDFA_TPU_PLATFORM=cpu python scripts/bench_train.py
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    from deepdfa_tpu.core.backend import apply_platform_override

    apply_platform_override()
    import jax

    import bench

    platform = jax.devices()[0].platform
    result = bench.run_train_measurement(platform)
    # same fields the driver merges, without the train_ prefix for
    # standalone readability
    from deepdfa_tpu.obs import run_stamp

    print(
        json.dumps(
            {
                "metric": "deepdfa_train_graphs_per_sec",
                "value": result["train_graphs_per_sec"],
                "unit": "graphs/s",
                "vs_baseline": result["train_vs_baseline"],
                **{
                    k.removeprefix("train_"): v
                    for k, v in result.items()
                    if k.startswith("train_")
                },
                **run_stamp(),
            }
        ),
        flush=True,
    )


if __name__ == "__main__":
    main()
