#!/usr/bin/env python
"""Training-throughput benchmark: flagship DeepDFA train_step on one chip.

Reference baseline: 25 epochs over the Big-Vul train split (bs 256) in
9 minutes on an RTX 3090 (paper Table 5) — with the undersampled epoch at
~20k graphs that is roughly 925 graphs/s of training throughput.

This measures the same flagship configuration (input_dim 1002, hidden 32,
n_steps 5) over Big-Vul-tail CFG sizes, full train_step (forward +
backward + AdamW update), and prints one JSON line with the median
steady-state window (best/mean alongside, same methodology as bench.py).

    python scripts/bench_train.py
    DEEPDFA_TPU_PLATFORM=cpu python scripts/bench_train.py

Status note (2026-07-29, axon-tunnel v5e): the *inference* benchmark
(bench.py) compiles and runs fine on the chip, but this train-step
compile (5 unrolled GGNN steps + backward + AdamW at node_budget 16384 /
edge_budget 65536) wedged the remote compile service twice at >20 min;
the script is validated end to end on CPU (93 graphs/s at 128 examples).
Re-run on the chip when the compile service recovers, or shrink budgets
via DEEPDFA_BENCH_EXAMPLES to reduce the compiled program.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# 25 epochs x ~20k undersampled graphs / 540 s (paper Table 5)
BASELINE_TRAIN_GRAPHS_PER_SEC = 25 * 20_000 / 540.0


def main() -> None:
    from deepdfa_tpu.core.backend import apply_platform_override

    apply_platform_override()
    import jax

    from deepdfa_tpu.core import Config
    from deepdfa_tpu.data import (
        bigvul_stmt_sizes,
        build_dataset,
        generate,
        to_examples,
    )
    from deepdfa_tpu.graphs import shard_bucket_batches
    from deepdfa_tpu.models import DeepDFA
    from deepdfa_tpu.train import GraphTrainer

    n_examples = int(os.environ.get("DEEPDFA_BENCH_EXAMPLES", 512))
    reps = int(os.environ.get("DEEPDFA_BENCH_REPS", 8))
    sizes = bigvul_stmt_sizes(n_examples, seed=7)
    synth = generate(n_examples, vuln_rate=0.06, seed=7, stmt_sizes=sizes)
    specs, _ = build_dataset(
        to_examples(synth), train_ids=range(n_examples), limit_all=1000,
        limit_subkeys=1000,
    )
    # single-shard dp batches (the 1-device path of the exact-sum
    # shard_map trainer); budgets as in bench.py so nothing is dropped
    batches = list(
        shard_bucket_batches(specs, 1, 256, 16384, 65536, oversized="raise")
    )

    cfg = Config()
    model = DeepDFA.from_config(cfg.model, input_dim=1002)
    trainer = GraphTrainer(model, cfg)
    state = trainer.init_state(batches[0])

    # compile + warmup
    state, _ = trainer.train_step(state, batches[0])
    jax.block_until_ready(state.params)

    n_per_pass = sum(int(np.asarray(b.graph_mask).sum()) for b in batches)
    rates = []
    for _ in range(reps):
        t0 = time.perf_counter()
        loss = None
        for b in batches:
            state, loss = trainer.train_step(state, b)
        jax.block_until_ready(loss)
        rates.append(n_per_pass / (time.perf_counter() - t0))

    value = float(np.median(rates))
    print(
        json.dumps(
            {
                "metric": "deepdfa_train_graphs_per_sec",
                "value": round(value, 1),
                "unit": "graphs/s",
                "vs_baseline": round(value / BASELINE_TRAIN_GRAPHS_PER_SEC, 2),
                "best_graphs_per_sec": round(max(rates), 1),
                "mean_graphs_per_sec": round(float(np.mean(rates)), 1),
                "platform": jax.devices()[0].platform,
                "n_examples": n_examples,
            }
        ),
        flush=True,
    )


if __name__ == "__main__":
    main()
