#!/usr/bin/env bash
# Inference-time + FLOPs profiling (reference
# LineVul/linevul/scripts/eval_{profiling,inferencetime}_*.sh; the _cpu
# variants are DEEPDFA_TPU_PLATFORM=cpu here — one knob instead of
# duplicated scripts).
# Usage: eval_profiling.sh [--config ...] [overrides]
#        DEEPDFA_TPU_PLATFORM=cpu eval_profiling.sh   # CPU variant
set -euo pipefail
cd "$(dirname "$0")/.."

python -m deepdfa_tpu.cli test --profile "$@"
python bench.py
