#!/usr/bin/env bash
# DeepDFA+LineVul-style combined training (reference msr_train_combined.sh)
# Usage: train_combined.sh [--tokenizer DIR] [--pretrained pytorch_model.bin]
set -euo pipefail
cd "$(dirname "$0")/.."
python -m deepdfa_tpu.cli train-combined \
    --config configs/bigvul_combined.json --encoder codebert-base "$@"
