#!/usr/bin/env bash
# End-to-end smoke run on a synthetic corpus — the framework's analog of
# the reference's sample-mode path (README "On sample data"): every
# pipeline stage on small data, no downloads, minutes not hours.
set -euo pipefail
cd "$(dirname "$0")/.."

export DEEPDFA_TPU_STORAGE="${DEEPDFA_TPU_STORAGE:-$(mktemp -d)/storage}"
echo "storage: $DEEPDFA_TPU_STORAGE"

OVERRIDES=(data.feat.limit_all=200 data.feat.limit_subkeys=200)

python -m deepdfa_tpu.cli prepare --source synthetic --n-examples 600
python -m deepdfa_tpu.cli extract --workers 4 "${OVERRIDES[@]}"
python -m deepdfa_tpu.cli coverage "${OVERRIDES[@]}"
python -m deepdfa_tpu.cli train run_name=smoke "${OVERRIDES[@]}" \
    model.hidden_dim=16 train.max_epochs=60 \
    train.optim.learning_rate=0.01 data.batch.graphs_per_batch=32
# argparse quirk: flags must precede the positional override list
python -m deepdfa_tpu.cli test --export run_name=smoke "${OVERRIDES[@]}" \
    model.hidden_dim=16 data.batch.graphs_per_batch=32
echo "smoke OK"
