#!/usr/bin/env python
"""Validate the run-log metric schema: every scalar tag a train run
emits (jsonl, and therefore TensorBoard — the tags come from the same
`flatten_scalars`) must match a declared pattern in
`deepdfa_tpu/obs/metrics.py:SCHEMA`.

This is the drift guard ISSUE 4 asks for: a new record key added in a
loop without a schema declaration fails tier-1
(tests/test_obs.py:test_check_obs_schema_smoke) instead of silently
growing an undocumented TensorBoard tag.

Modes:
  --smoke        run a tiny in-process smoke train (synthetic corpus,
                 obs.metrics on, val split, RunLogger) and validate the
                 train_log.jsonl it produces  [tier-1 default]
  --log <path>   validate an existing train_log.jsonl instead
  --serve-smoke  run the serve smoke (train a tiny checkpoint, score it
                 through the online path) and validate the
                 serve_log.jsonl it produces — the `serve/*` tag half of
                 the schema (docs/serving.md)
  --serve-log <path>  validate an existing serve_log.jsonl; when the
                 summary stamps serve_pipeline_depth > 0 the pipelined
                 stage evidence (serve/pipeline/* counters) must be
                 present too (docs/serving.md "Pipelined execution")
  --scan-log <path>   validate an existing scan_log.jsonl (the repo-
                 scanner's summary records, deepdfa_tpu/scan/ — the
                 `scan/*` + `localize/*` tag half of the schema,
                 docs/scanning.md)
  --cascade-log <path>  validate a cascade-mode serve_log.jsonl
                 (serve/cascade.py, docs/cascade.md): escalation fields
                 present in the summary's cascade section, per-request
                 entries declare their deciding stage (escalated ones
                 their cascade_stage2_ms), the SLO snapshot declares the
                 cascade stages, AND every flattened scalar tag declared
                 in SCHEMA — wired into `deepdfa-tpu serve --smoke`
  --fleet-log <path>  validate a fleet router's fleet_log.jsonl
                 (deepdfa_tpu/fleet/router.py, docs/fleet.md):
                 structural checks (per-request entries carry id +
                 status, lifecycle events carry a declared name +
                 t_unix, flywheel records — `shadow` entries carry a
                 declared event + candidate, `promotion`/`demotion`
                 entries a candidate + t_unix and demotions a declared
                 reason; docs/flywheel.md) AND every flattened scalar
                 tag declared in SCHEMA — wired into `deepdfa-tpu
                 fleet --smoke`
  --metrics <path>    validate a Prometheus `/metrics` scrape (saved
                 text, e.g. <run_dir>/metrics.prom from `serve --smoke`)
                 against the same registry: every line must parse as
                 exposition format 0.0.4, every family must carry its
                 `tag=` back-reference, and every tag must be declared
                 in SCHEMA (docs/slo.md)
  --fleet-metrics <path>  validate an AGGREGATED fleet scrape (the
                 router's /metrics when fleet.telemetry is on, saved to
                 a file, or `-` for stdin —
                 obs/aggregate.py:validate_fleet_scrape,
                 docs/alerts.md): per-replica families must carry
                 replica= labels, the merged latency family must
                 include the replica="fleet" series, and every replica
                 the scrape names must carry its staleness marker
  --postmortem <path> validate a crash flight-recorder dump
                 (postmortem.json, obs/flight.py / docs/efficiency.md):
                 format contract (version, declared trigger, bounded
                 step/event rings, ledger shape) AND every embedded
                 metrics tag declared in SCHEMA — wired into the
                 serve/scan smoke paths and scripts/fault_inject.py
  --tuned <path> validate a tuned.json / TUNED_r*.json record
                 (deepdfa_tpu/tune/cache.py:validate_tuned,
                 docs/tuning.md): hardware key complete, every
                 candidate row carries its numerics-contract verdict,
                 a winner present per signature, ladder fits carry
                 their pow2 baseline — wired into
                 `deepdfa-tpu tune --smoke`
  --drill <path> validate a DRILL_r*.json chaos-drill record
                 (deepdfa_tpu/fleet/drill.py:validate_drill_file,
                 docs/fleet.md): mode + cadence stamps, per-round
                 entries matching the declared round count, measured
                 failover/reseed/readmit timings numeric, the 3.2 s
                 bound recorded — wired into `deepdfa-tpu fleet-drill`
  --multichip <path>  validate a MULTICHIP record (the driver artifact
                 MULTICHIP_r*.json, or the raw `{"multichip": ...}`
                 line `__graft_entry__.py:dryrun_multichip` prints —
                 parallel/sharding.py:validate_multichip,
                 docs/sharding.md): per-mesh-shape topology stamps,
                 per-shard ledger fields, the sharded serve ladder's
                 zero-recompile pin, and every flattened tag declared
                 under `mesh/*` / `shard/*` in SCHEMA
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
from pathlib import Path

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def smoke_records() -> list[dict]:
    """One-epoch smoke train through the REAL loop + logger, metrics
    and step logging on, so the produced record set covers the epoch
    record, step records, val metrics, and the obs snapshot."""
    from deepdfa_tpu.core import Config, config as config_mod
    from deepdfa_tpu.data import build_dataset, generate, to_examples
    from deepdfa_tpu.graphs import shard_bucket_batches
    from deepdfa_tpu.models import DeepDFA
    from deepdfa_tpu.train import GraphTrainer
    from deepdfa_tpu.train.logging import RunLogger

    synth = generate(12, seed=0)
    specs, _ = build_dataset(
        to_examples(synth), train_ids=range(12), limit_all=50,
        limit_subkeys=50,
    )
    cfg = config_mod.apply_overrides(Config(), [
        "train.max_epochs=1", "train.log_every_steps=1",
        "model.hidden_dim=8", "model.n_steps=2",
        "obs.metrics=true",
    ])
    model = DeepDFA.from_config(cfg.model, input_dim=52)
    trainer = GraphTrainer(model, cfg)

    def batches(_e=0):
        return shard_bucket_batches(
            specs, 1, 4, 2048, 8192, oversized="raise"
        )

    state = trainer.init_state(next(iter(batches())))
    with tempfile.TemporaryDirectory() as d:
        with RunLogger(d, tensorboard=False) as lg:
            trainer.fit(
                state, batches, val_batches=batches, log_fn=lg.log
            )
        return [
            json.loads(line)
            for line in (Path(d) / "train_log.jsonl").read_text().splitlines()
            if line.strip()
        ]


def serve_smoke_records() -> list[dict]:
    """Serve smoke end to end (train a tiny checkpoint, score its corpus
    through the online batcher) and return the serve_log.jsonl records —
    the `serve/*` half of the declared schema."""
    from deepdfa_tpu.serve import driver

    cfg, run_dir, sources_dir = driver.build_smoke_run(
        run_name="schema-serve-smoke", dataset="schema-serve-smoke"
    )
    driver.run_score(
        cfg, run_dir, driver.collect_sources([str(sources_dir)])
    )
    return [
        json.loads(line)
        for line in (run_dir / "serve_log.jsonl").read_text().splitlines()
        if line.strip()
    ]


def check_metrics_scrape(text: str) -> dict:
    """Validate one Prometheus scrape against the declared registry
    schema. A histogram/summary family's tag maps to its `<tag>/count`
    declaration (the flattened-record spelling of the same metric)."""
    from deepdfa_tpu.obs import metrics
    from deepdfa_tpu.obs.slo import parse_exposition

    try:
        families = parse_exposition(text)
    except ValueError as e:
        return {"ok": False, "error": str(e)}
    undeclared: list[str] = []
    untagged: list[str] = []
    n_samples = 0
    for name, fam in sorted(families.items()):
        n_samples += len(fam["samples"])
        tag = fam.get("tag")
        if not tag:
            untagged.append(name)
            continue
        if not (
            metrics.declared(tag) or metrics.declared(f"{tag}/count")
        ):
            undeclared.append(f"{name} (tag={tag})")
    return {
        "ok": not undeclared and not untagged,
        "families": len(families),
        "samples": n_samples,
        "undeclared": undeclared,
        "untagged": untagged,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--smoke", action="store_true",
                    help="run the in-process smoke train (default when "
                    "no --log is given)")
    ap.add_argument("--log", default=None,
                    help="validate an existing train_log.jsonl")
    ap.add_argument("--serve-smoke", action="store_true",
                    help="run the serve smoke and validate its "
                    "serve_log.jsonl")
    ap.add_argument("--serve-log", default=None,
                    help="validate an existing serve_log.jsonl")
    ap.add_argument("--scan-log", default=None,
                    help="validate an existing scan_log.jsonl")
    ap.add_argument("--fleet-log", default=None,
                    help="validate a fleet router's fleet_log.jsonl "
                    "(deepdfa_tpu/fleet/, docs/fleet.md)")
    ap.add_argument("--cascade-log", default=None,
                    help="validate a cascade-mode serve_log.jsonl "
                    "(deepdfa_tpu/serve/cascade.py, docs/cascade.md)")
    ap.add_argument("--metrics", default=None,
                    help="validate a saved Prometheus /metrics scrape")
    ap.add_argument("--fleet-metrics", default=None,
                    help="validate an aggregated fleet /metrics scrape "
                    "(path or `-` for stdin; "
                    "obs/aggregate.py:validate_fleet_scrape)")
    ap.add_argument("--postmortem", default=None,
                    help="validate a dumped postmortem.json (crash "
                    "flight recorder, obs/flight.py)")
    ap.add_argument("--multichip", default=None,
                    help="validate a MULTICHIP record (driver artifact "
                    "or raw dryrun_multichip JSON line; "
                    "parallel/sharding.py:validate_multichip)")
    ap.add_argument("--tuned", default=None,
                    help="validate a tuned.json / TUNED_r*.json record "
                    "(deepdfa_tpu/tune/cache.py:validate_tuned, "
                    "docs/tuning.md)")
    ap.add_argument("--drill", default=None,
                    help="validate a DRILL_r*.json chaos-drill record "
                    "(deepdfa_tpu/fleet/drill.py:validate_drill_file, "
                    "docs/fleet.md)")
    ap.add_argument("--out", default=None)
    args = ap.parse_args(argv)

    from deepdfa_tpu.obs import metrics

    if args.fleet_log:
        from deepdfa_tpu.fleet.router import validate_fleet_log

        result = validate_fleet_log(args.fleet_log)
        print(json.dumps(result), flush=True)
        if args.out:
            Path(args.out).write_text(json.dumps(result, indent=1))
        if not result["ok"]:
            print(
                "fleet log validation failed (declare the tags in "
                "deepdfa_tpu/obs/metrics.py:SCHEMA or fix the "
                "router):\n  " + "\n  ".join(result.get("problems", [])),
                file=sys.stderr,
            )
            return 1
        return 0

    if args.cascade_log:
        from deepdfa_tpu.serve.cascade import validate_cascade_log

        result = validate_cascade_log(args.cascade_log)
        print(json.dumps(result), flush=True)
        if args.out:
            Path(args.out).write_text(json.dumps(result, indent=1))
        if not result["ok"]:
            print(
                "cascade log validation failed (declare the tags in "
                "deepdfa_tpu/obs/metrics.py:SCHEMA or fix the cascade "
                "emitters):\n  "
                + "\n  ".join(result.get("problems", [])),
                file=sys.stderr,
            )
            return 1
        return 0

    if args.tuned:
        from deepdfa_tpu.tune.cache import validate_tuned_file

        result = validate_tuned_file(args.tuned)
        print(json.dumps(result), flush=True)
        if args.out:
            Path(args.out).write_text(json.dumps(result, indent=1))
        if not result["ok"]:
            print(
                "tuned record validation failed (fix the search "
                "emitters in deepdfa_tpu/tune/ or re-run "
                "`deepdfa-tpu tune`):\n  "
                + "\n  ".join(result.get("problems", [])),
                file=sys.stderr,
            )
            return 1
        return 0

    if args.drill:
        from deepdfa_tpu.fleet.drill import validate_drill_file

        result = validate_drill_file(args.drill)
        print(json.dumps(result), flush=True)
        if args.out:
            Path(args.out).write_text(json.dumps(result, indent=1))
        if not result["ok"]:
            print(
                "drill record validation failed (fix the drill "
                "runner/recorder in deepdfa_tpu/fleet/drill.py or "
                "re-run `deepdfa-tpu fleet-drill`):\n  "
                + "\n  ".join(result.get("problems", [])),
                file=sys.stderr,
            )
            return 1
        return 0

    if args.multichip:
        from deepdfa_tpu.parallel.sharding import validate_multichip

        result = validate_multichip(
            json.loads(Path(args.multichip).read_text())
        )
        print(json.dumps(result), flush=True)
        if args.out:
            Path(args.out).write_text(json.dumps(result, indent=1))
        if not result["ok"]:
            print(
                "multichip record validation failed (declare the tags "
                "in deepdfa_tpu/obs/metrics.py:SCHEMA or fix "
                "dryrun_multichip):\n  "
                + "\n  ".join(result.get("problems", [])),
                file=sys.stderr,
            )
            return 1
        return 0

    if args.postmortem:
        from deepdfa_tpu.obs.flight import validate_postmortem_file

        result = validate_postmortem_file(args.postmortem)
        print(json.dumps(result), flush=True)
        if args.out:
            Path(args.out).write_text(json.dumps(result, indent=1))
        if not result["ok"]:
            print(
                "postmortem validation failed:\n  "
                + "\n  ".join(result.get("problems", [])),
                file=sys.stderr,
            )
            return 1
        return 0

    if args.fleet_metrics:
        from deepdfa_tpu.obs.aggregate import validate_fleet_scrape

        text = (
            sys.stdin.read() if args.fleet_metrics == "-"
            else Path(args.fleet_metrics).read_text()
        )
        result = validate_fleet_scrape(text)
        print(json.dumps(result), flush=True)
        if args.out:
            Path(args.out).write_text(json.dumps(result, indent=1))
        if not result["ok"]:
            print(
                "fleet scrape validation failed (declare the tags in "
                "deepdfa_tpu/obs/metrics.py:SCHEMA or fix the "
                "aggregator in deepdfa_tpu/obs/aggregate.py):\n  "
                + "\n  ".join(result.get("problems", [])),
                file=sys.stderr,
            )
            return 1
        return 0

    if args.metrics:
        result = check_metrics_scrape(Path(args.metrics).read_text())
        print(json.dumps(result), flush=True)
        if args.out:
            Path(args.out).write_text(json.dumps(result, indent=1))
        if not result["ok"]:
            print(
                "metrics scrape validation failed (declare the tags in "
                "deepdfa_tpu/obs/metrics.py:SCHEMA or fix the "
                "exporter):\n  " + "\n  ".join(
                    result.get("undeclared", [])
                    + result.get("untagged", [])
                    + ([result["error"]] if "error" in result else [])
                ),
                file=sys.stderr,
            )
            return 1
        return 0

    if args.log or args.serve_log or args.scan_log:
        records = [
            json.loads(line)
            for line in Path(args.log or args.serve_log or args.scan_log)
            .read_text().splitlines()
            if line.strip()
        ]
    else:
        from deepdfa_tpu.core.backend import apply_platform_override

        os.environ.setdefault("DEEPDFA_TPU_PLATFORM", "cpu")
        apply_platform_override()
        records = (
            serve_smoke_records() if args.serve_smoke else smoke_records()
        )

    from deepdfa_tpu.train.logging import flatten_scalars

    tags = sorted({t for r in records for t in flatten_scalars(r)})
    bad = metrics.undeclared_tags(records)
    problems: list[str] = []
    if args.serve_log or args.serve_smoke:
        # pipelined serve_log evidence (ISSUE 17, docs/serving.md): a
        # summary record claiming `serve_pipeline_depth > 0` must carry
        # the pipeline stage counters it implies — a depth stamp
        # without them means the pipelined path silently fell back
        pipelined = any(
            isinstance(r.get("serve_pipeline_depth"), (int, float))
            and r["serve_pipeline_depth"] > 0
            for r in records
        )
        if pipelined:
            required = (
                "serve/pipeline/batches",
                "serve/pipeline/device_busy_seconds",
                "serve/pipeline/device_idle_fraction",
            )
            problems.extend(
                f"pipelined serve_log missing evidence tag: {t}"
                for t in required if t not in tags
            )
    result = {
        "ok": not bad and not problems,
        "records": len(records),
        "tags": len(tags),
        "undeclared": bad,
        **({"problems": problems} if problems else {}),
    }
    print(json.dumps(result), flush=True)
    if args.out:
        Path(args.out).write_text(json.dumps(result, indent=1))
    if bad:
        print(
            "undeclared metric tags (declare them in "
            "deepdfa_tpu/obs/metrics.py:SCHEMA or fix the emitter):\n  "
            + "\n  ".join(bad),
            file=sys.stderr,
        )
        return 1
    if problems:
        print(
            "serve log validation failed:\n  " + "\n  ".join(problems),
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
