#!/usr/bin/env python
"""Repo-scan throughput benchmark (docs/scanning.md).

Drives the whole-repo scanner (deepdfa_tpu/scan/) over a synthetic
repository three ways and reports the CI-shaped numbers:

  scan_functions_per_sec              cold scan (walk + split + frontend
                                      + score + attribute, nothing cached)
  scan_warm_functions_per_sec         warm NON-incremental re-scan: the
                                      manifest is ignored but the shared
                                      content-keyed frontend cache is hot
                                      — extraction skipped, device re-run
  scan_cache_hit_fraction             frontend cache hits on that pass
  scan_incremental_functions_per_sec  incremental re-scan after ONE file
                                      edit: only the changed function
                                      re-extracts and re-scores
  scan_incremental_skip_fraction      manifest-reused fraction
  scan_steady_state_recompiles        must be 0 across every pass
                                      (score AND line-attribution paths)

Modes:
    python scripts/bench_scan.py --smoke   # tier-1 regression mode
    python scripts/bench_scan.py           # full mode (bigger repo)

The checkpoint round trip is real (a tiny GGNN is trained first via the
serve smoke-run builder) because the scanner's manifest identity pins
the restored checkpoint — the bench must measure the path `deepdfa-tpu
scan` actually takes.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from pathlib import Path

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def bench_scan(n_functions: int = 96, smoke: bool = False) -> dict:
    from deepdfa_tpu.core import config as config_mod
    from deepdfa_tpu.obs import metrics as obs_metrics
    from deepdfa_tpu.scan.scanner import (
        RepoScanner,
        _build_smoke_repo,
        _edit_one_function,
    )
    from deepdfa_tpu.serve import driver
    from deepdfa_tpu.serve.registry import ModelRegistry
    from deepdfa_tpu.serve.server import ScoringService

    n = min(n_functions, 24) if smoke else int(n_functions)
    cfg, run_dir, sources_dir = driver.build_smoke_run(
        run_name="scan-bench", dataset="scan-bench", n_examples=n,
        max_epochs=1,
        extra_overrides=[
            "scan.lines=true", "serve.lines_steps=2",
            "scan.threshold=0.0",
        ],
    )
    repo = _build_smoke_repo(run_dir, sources_dir, cfg)
    registry = ModelRegistry(
        run_dir, family="deepdfa", checkpoint=cfg.serve.checkpoint,
        cfg=cfg,
    )
    service = ScoringService(registry, cfg)
    try:
        scanner = RepoScanner(service, cfg)
        t0 = time.perf_counter()
        cold = scanner.scan(repo)
        cold_dt = time.perf_counter() - t0

        # warm, manifest OFF: measures the shared frontend cache alone
        cfg_nf = config_mod.apply_overrides(
            cfg, ["scan.incremental=false"]
        )
        # share the already-warmed attribution executables — a second
        # warmup would re-AOT the whole ladder and only inflate wall time
        warm_scanner = RepoScanner(
            service, cfg_nf, localizer=scanner.localizer
        )
        t0 = time.perf_counter()
        warm = warm_scanner.scan(repo)
        warm_dt = time.perf_counter() - t0

        _edit_one_function(repo)
        t0 = time.perf_counter()
        incr = scanner.scan(repo)
        incr_dt = time.perf_counter() - t0
    finally:
        service.close()

    fns = cold["scan_functions"]

    def fps(dt: float) -> float:
        return round(fns / dt, 2) if dt else 0.0

    recompiles = sum(
        s[k]
        for s in (cold, warm, incr)
        for k in ("scan_steady_state_recompiles",
                  "scan_lines_steady_state_recompiles")
    )
    return {
        "metric": "scan_functions_per_sec",
        "value": fps(cold_dt),
        "unit": "functions/s",
        "scan_functions_per_sec": fps(cold_dt),
        "scan_warm_functions_per_sec": fps(warm_dt),
        "scan_incremental_functions_per_sec": fps(incr_dt),
        "scan_cache_hit_fraction": warm["scan_cache_hit_fraction"],
        "scan_incremental_skip_fraction": (
            incr["scan_incremental_skip_fraction"]
        ),
        "scan_incremental_speedup": (
            round(cold_dt / incr_dt, 2) if incr_dt else None
        ),
        "scan_files": cold["scan_files"],
        "scan_functions": fns,
        "scan_findings": cold["scan_findings"],
        "scan_steady_state_recompiles": recompiles,
        "n_examples": n,
        "smoke": smoke,
    }


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--functions", type=int, default=96)
    ap.add_argument("--out", default=None)
    ap.add_argument(
        "--smoke", action="store_true",
        help="tier-1 regression mode: tiny repo/model, asserts the "
        "zero-recompile + incremental-skip contracts",
    )
    args = ap.parse_args()

    from deepdfa_tpu.core.backend import apply_platform_override

    os.environ.setdefault("DEEPDFA_TPU_PLATFORM", "cpu")
    apply_platform_override()
    if "DEEPDFA_TPU_STORAGE" not in os.environ:
        # the bench trains a throwaway checkpoint; keep it out of the
        # repo's real storage tree
        import tempfile

        tmp = tempfile.TemporaryDirectory(prefix="bench-scan-")
        os.environ["DEEPDFA_TPU_STORAGE"] = tmp.name

    record = bench_scan(args.functions, smoke=args.smoke)
    from deepdfa_tpu.obs import run_stamp

    record.update(run_stamp())
    print(json.dumps(record), flush=True)
    if args.out:
        Path(args.out).write_text(json.dumps(record, indent=1))
    if args.smoke:
        bad = []
        if record["scan_steady_state_recompiles"]:
            bad.append(
                f"{record['scan_steady_state_recompiles']} steady-state "
                f"recompiles (expected 0)"
            )
        if record["scan_incremental_skip_fraction"] < 0.9:
            bad.append(
                f"incremental skip fraction "
                f"{record['scan_incremental_skip_fraction']} < 0.9"
            )
        if record["scan_cache_hit_fraction"] < 0.9:
            bad.append(
                f"warm cache hit fraction "
                f"{record['scan_cache_hit_fraction']} < 0.9"
            )
        if bad:
            raise SystemExit("; ".join(bad))


if __name__ == "__main__":
    main()
