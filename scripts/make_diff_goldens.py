#!/usr/bin/env python
"""Generate tests/goldens/diff_labels.json from real `git diff --no-index`.

The reference computes changed-line labels by shelling out to git per
example (DDFA/sastvd/helpers/git.py:12-76: `git diff --no-index
--no-prefix -U<huge>` parsed into +/- line positions); the framework
computes them in-process (data/diffs.py). Hunk boundaries can differ
between git's Myers diff and difflib's Ratcliff-Obershelp on ambiguous
inputs, silently shifting vuln-line labels — so the expected
added/removed sets here come from the real git binary and are committed
as goldens (VERDICT r2 item 8).

For each before/after fixture pair this records:
- removed_before: 1-based line numbers of '-' lines, in BEFORE numbering
- added_after:    1-based line numbers of '+' lines, in AFTER numbering
- combined_removed/combined_added: positions in the full-context unified
  diff body — the reference's own coordinate system (git.py md_lines),
  used by its combined before/after views (git.py allfunc)

Run from the repo root:  python scripts/make_diff_goldens.py
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

PAIRS: dict[str, tuple[str, str]] = {
    "insert_only_guard": (
        "int f(int *p) {\n  int v = *p;\n  return v + 1;\n}\n",
        "int f(int *p) {\n  if (!p)\n    return 0;\n  int v = *p;\n  return v + 1;\n}\n",
    ),
    "replace_line": (
        "int g(int n) {\n  int r = n * 2;\n  return r;\n}\n",
        "int g(int n) {\n  int r = n << 1;\n  return r;\n}\n",
    ),
    "delete_only": (
        "void h(char *s) {\n  log(s);\n  debug_dump(s);\n  free(s);\n}\n",
        "void h(char *s) {\n  log(s);\n  free(s);\n}\n",
    ),
    "move_block": (
        "int m(int a, int b) {\n  int x = a + 1;\n  int y = b + 2;\n  check(x);\n  return x + y;\n}\n",
        "int m(int a, int b) {\n  int y = b + 2;\n  int x = a + 1;\n  check(x);\n  return x + y;\n}\n",
    ),
    "whitespace_churn": (
        "int w(int n) {\n  int s=0;\n  for(int i=0;i<n;i++) s+=i;\n  return s;\n}\n",
        "int w(int n) {\n  int s = 0;\n  for (int i = 0; i < n; i++)\n    s += i;\n  return s;\n}\n",
    ),
    "duplicate_lines_ambiguous": (
        "void d(void) {\n  step();\n  step();\n  step();\n  done();\n}\n",
        "void d(void) {\n  step();\n  step();\n  done();\n}\n",
    ),
    "replace_and_insert": (
        "int ri(char *buf, int n) {\n  memcpy(dst, buf, n);\n  return n;\n}\n",
        "int ri(char *buf, int n) {\n  if (n > CAP)\n    n = CAP;\n  memcpy(dst, buf, (size_t)n);\n  return n;\n}\n",
    ),
    "change_at_start": (
        "int cs(int a) {\n  return a;\n}\n",
        "long cs(int a) {\n  return a;\n}\n",
    ),
    "change_at_end": (
        "int ce(int a) {\n  use(a);\n  return a;\n}\n",
        "int ce(int a) {\n  use(a);\n  return a + 1;\n}\n",
    ),
    "append_tail": (
        "void at(void) {\n  one();\n}\n",
        "void at(void) {\n  one();\n  two();\n}\n",
    ),
    "no_trailing_newline": (
        "int nt(void) {\n  return 1;\n}",
        "int nt(void) {\n  return 2;\n}",
    ),
    "multi_hunk_spread": (
        "int mh(int a) {\n  int x = a;\n  keep1();\n  keep2();\n  keep3();\n  int y = x;\n  return y;\n}\n",
        "int mh(int a) {\n  int x = a + 1;\n  keep1();\n  keep2();\n  keep3();\n  int y = x - 1;\n  return y;\n}\n",
    ),
    "blank_line_insert": (
        "void bl(void) {\n  a();\n  b();\n}\n",
        "void bl(void) {\n  a();\n\n  b();\n}\n",
    ),
    "indent_shift_block": (
        "int ind(int c) {\n  run();\n  run2();\n  return c;\n}\n",
        "int ind(int c) {\n  if (c) {\n    run();\n    run2();\n  }\n  return c;\n}\n",
    ),
}


def git_diff_body(before: str, after: str) -> str:
    """Full-context unified diff body via real git (reference gitdiff)."""
    with tempfile.TemporaryDirectory() as d:
        old, new = Path(d) / "old", Path(d) / "new"
        old.write_text(before)
        new.write_text(after)
        ctx = len(before.splitlines()) + len(after.splitlines())
        res = subprocess.run(
            [
                "git", "diff", "--no-index", "--no-prefix", f"-U{ctx}",
                str(old), str(new),
            ],
            capture_output=True, text=True,
        )
    # rc 1 = differences found; 0 = identical
    lines = res.stdout.splitlines()
    # strip the file header (diff/index/---/+++) and the @@ hunk header
    body_start = next(
        (i + 1 for i, l in enumerate(lines) if l.startswith("@@")), len(lines)
    )
    return "\n".join(lines[body_start:])


def classify(body: str) -> dict:
    removed_before, added_after = [], []
    combined_removed, combined_added = [], []
    b_line = a_line = 0
    for pos, raw in enumerate(body.splitlines(), start=1):
        tag = raw[:1]
        if tag == "-":
            b_line += 1
            removed_before.append(b_line)
            combined_removed.append(pos)
        elif tag == "+":
            a_line += 1
            added_after.append(a_line)
            combined_added.append(pos)
        elif tag == "\\":  # "\ No newline at end of file"
            continue
        else:
            b_line += 1
            a_line += 1
    return {
        "removed_before": removed_before,
        "added_after": added_after,
        "combined_removed": combined_removed,
        "combined_added": combined_added,
    }


def main() -> None:
    out = {
        "_meta": {
            "generator": "scripts/make_diff_goldens.py",
            "git_version": subprocess.run(
                ["git", "--version"], capture_output=True, text=True
            ).stdout.strip(),
            "command": "git diff --no-index --no-prefix -U<len(before)+len(after)>",
        }
    }
    for name, (before, after) in PAIRS.items():
        body = git_diff_body(before, after)
        rec = {"before": before, "after": after, "diff_body": body}
        rec.update(classify(body))
        out[name] = rec
    dest = REPO / "tests" / "goldens" / "diff_labels.json"
    dest.parent.mkdir(parents=True, exist_ok=True)
    dest.write_text(json.dumps(out, indent=1))
    print(f"wrote {dest} ({len(PAIRS)} pairs)")


if __name__ == "__main__":
    main()
