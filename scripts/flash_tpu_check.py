#!/usr/bin/env python
"""On-chip validation of every flash-attention kernel path.

The CPU test suite (tests/test_flash_attention.py) pins the kernel math
through the Pallas interpreter, but two things only the real chip can
show: (a) the Mosaic lowering of each path actually compiles and runs
(the first roberta attempt surfaced real lowering constraints the
interpreter accepts — block tiling rules, the 2-value prng_seed cap),
and (b) the hardware PRNG stream behaves (the interpreter stubs it to
zeros). This script drives every kernel configuration on the
default backend and writes one JSON record:

  encoder     : square, scaled, kv-masked, probs-dropout (roberta)
  t5-encoder  : square, unscaled, additive [H,T,T] bias (+dbias grad)
  decoder-self: causal + bias (+ the dead-block skip)
  decoder-cross: rectangular Tq != Tk
  remat-policy: grads bit-identical between full-layer remat and the
                attn_saved selective policy

Each check compares fwd (and grads where cheap) against the XLA oracle
on the chip itself. Invoked by scripts/tpu_watchdog.py in every healthy
window (result embedded in BENCH_TPU_<ts>.json as "flash_paths");
runnable by hand:

    python scripts/flash_tpu_check.py [--out docs/flash_tpu_check.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _err(a, b, mask4=None):
    import numpy as np

    a = np.asarray(a, np.float32)
    b = np.asarray(b, np.float32)
    d = np.abs(a - b)
    if mask4 is not None:
        d = np.where(np.asarray(mask4), d, 0.0)
    return float(d.max())


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    from deepdfa_tpu.core.backend import apply_platform_override

    apply_platform_override()  # honor DEEPDFA_TPU_PLATFORM (cpu smoke)
    import jax
    import jax.numpy as jnp
    import numpy as np

    from deepdfa_tpu.nn.flash_attention import flash_attention
    from deepdfa_tpu.parallel.ring_attention import full_attention

    platform = jax.devices()[0].platform
    record: dict = {"platform": platform, "checks": {}}
    if platform == "cpu":
        record["note"] = "cpu backend: lowering checks are meaningless here"

    rng = np.random.default_rng(0)
    # full flagship shape on the chip; small on CPU (harness check only
    # — a 1-core host cannot afford the [B,H,T,T] oracle at size)
    B, H, T, D = (4, 4, 512, 64) if platform == "tpu" else (1, 2, 128, 16)
    dt = jnp.bfloat16 if platform == "tpu" else jnp.float32
    tol = 3e-2 if dt == jnp.bfloat16 else 1e-5
    q = jnp.asarray(rng.standard_normal((B, H, T, D)), dt)
    k = jnp.asarray(rng.standard_normal((B, H, T, D)), dt)
    v = jnp.asarray(rng.standard_normal((B, H, T, D)), dt)
    mask = jnp.asarray(np.arange(T)[None, :] < rng.integers(60, T, B)[:, None])
    m4 = np.asarray(mask)[:, None, :, None] & np.ones((B, H, T, D), bool)

    def run(name, fn):
        try:
            got = fn()
            record["checks"][name] = got
        except Exception as e:
            record["checks"][name] = {
                "ok": False, "error": f"{type(e).__name__}: {e}"[:300]}

    def enc():
        ref = np.asarray(jax.jit(
            lambda: full_attention(q, k, v, mask))())
        out = np.asarray(jax.jit(
            lambda: flash_attention(q, k, v, mask))())
        e = _err(out, ref, m4)
        # dropout path: deterministic + finite grad
        seed = jnp.array([7], jnp.int32)
        fd = jax.jit(lambda: flash_attention(
            q, k, v, mask, dropout_rate=0.1, seed=seed))
        det = bool((np.asarray(fd()) == np.asarray(fd())).all())
        return {"fwd_err_vs_xla": e, "dropout_deterministic": det,
                "ok": e < tol and det}

    def t5_enc():
        bias = jnp.asarray(rng.standard_normal((H, T, T)) * 0.3, dt)

        def oracle(q, k, v, bias):
            s = jnp.einsum("bhqd,bhkd->bhqk", q, k) + bias[None]
            s = jnp.where(mask[:, None, None, :], s,
                          jnp.finfo(s.dtype).min)
            p = jax.nn.softmax(s, axis=-1)
            return jnp.einsum("bhqk,bhkd->bhqd", p, v)

        ref = np.asarray(jax.jit(oracle)(q, k, v, bias))
        out = np.asarray(jax.jit(lambda: flash_attention(
            q, k, v, mask, scale=1.0, bias=bias))())
        e = _err(out, ref, m4)
        # dbias grad must compile + match the oracle's
        loss_o = jax.jit(jax.grad(
            lambda b_: jnp.sum(oracle(q, k, v, b_).astype(jnp.float32)
                               ** 2)))
        loss_f = jax.jit(jax.grad(
            lambda b_: jnp.sum(flash_attention(
                q, k, v, mask, scale=1.0, bias=b_).astype(jnp.float32)
                ** 2)))
        ge = _err(loss_f(bias), loss_o(bias))
        scale = float(np.abs(np.asarray(loss_o(bias), np.float32)).max())
        return {"fwd_err_vs_oracle": e, "dbias_err": ge,
                "dbias_scale": scale,
                "ok": e < tol and ge < max(tol * scale, tol)}

    def dec_self():
        bias = jnp.asarray(rng.standard_normal((H, T, T)) * 0.3, dt)
        causal = jnp.tril(jnp.ones((T, T), bool))
        fm = causal[None] & mask[:, None, :]

        def oracle():
            s = jnp.einsum("bhqd,bhkd->bhqk", q, k) + bias[None]
            s = jnp.where(fm[:, None], s, jnp.finfo(s.dtype).min)
            p = jax.nn.softmax(s, axis=-1)
            return jnp.einsum("bhqk,bhkd->bhqd", p, v)

        ref = np.asarray(jax.jit(oracle)())
        out = np.asarray(jax.jit(lambda: flash_attention(
            q, k, v, mask, scale=1.0, bias=bias, causal=True))())
        e = _err(out, ref, m4)
        return {"fwd_err_vs_oracle": e, "ok": e < tol}

    def dec_cross():
        Tq = T // 2
        q2 = jnp.asarray(rng.standard_normal((B, H, Tq, D)), dt)

        def oracle():
            s = jnp.einsum("bhqd,bhkd->bhqk", q2, k)
            s = jnp.where(mask[:, None, None, :], s,
                          jnp.finfo(s.dtype).min)
            p = jax.nn.softmax(s, axis=-1)
            return jnp.einsum("bhqk,bhkd->bhqd", p, v)

        ref = np.asarray(jax.jit(oracle)())
        out = np.asarray(jax.jit(lambda: flash_attention(
            q2, k, v, mask, scale=1.0))())
        e = _err(out, ref)
        return {"fwd_err_vs_oracle": e, "ok": e < tol}

    def remat_policies():
        # attn_saved must be a pure what-is-saved change: grads through
        # a checkpointed flash call are identical whether the backward
        # replays the kernel (full) or reuses the named outputs
        from jax.ad_checkpoint import checkpoint_name

        def attn(x):
            out = flash_attention(x, x, x, mask)
            return checkpoint_name(out, "attn_ctx")

        def loss(policy):
            fn = jax.checkpoint(attn, policy=policy) if policy else \
                jax.checkpoint(attn)
            return jax.jit(jax.grad(
                lambda x: jnp.sum(fn(x).astype(jnp.float32) ** 2)))

        g_full = np.asarray(loss(None)(q), np.float32)
        pol = jax.checkpoint_policies.save_only_these_names(
            "attn_ctx", "attn_lse")
        g_sel = np.asarray(loss(pol)(q), np.float32)
        e = float(np.abs(g_full - g_sel).max())
        return {"grad_diff_full_vs_attn_saved": e, "ok": e == 0.0}

    run("encoder", enc)
    run("t5_encoder", t5_enc)
    run("decoder_self_causal", dec_self)
    run("decoder_cross_rect", dec_cross)
    run("remat_policy_equivalence", remat_policies)
    record["ok"] = all(
        c.get("ok") for c in record["checks"].values())

    print(json.dumps(record), flush=True)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(record, f, indent=1)


if __name__ == "__main__":
    main()
