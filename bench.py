"""Headline benchmark: DeepDFA inference throughput on one TPU chip.

Prints ONE json line:
  {"metric": "deepdfa_infer_graphs_per_sec", "value": N, "unit": "graphs/s",
   "vs_baseline": R, "platform": "...", ...}

Baseline: the reference's single-RTX-3090 DeepDFA inference latency of
4.6 ms/example (paper Table 5, BASELINE.md "Efficiency") = 217.4 graphs/s.
The workload is the flagship configuration (input_dim 1002, hidden 32,
n_steps 5, concat_all_absdf) over CFGs whose size distribution matches
Big-Vul's heavy tail (lognormal median 14 stmts, p99 ~230, clipped 500 —
see data/synthetic.py:bigvul_stmt_sizes), produced by the full frontend
pipeline and batch-packed exactly as in training/eval.

Resilience: the TPU tunnel's compile service can wedge (round-1 failure:
rc=1 backend-init error / indefinite hang). The measurement therefore runs
in a *child* process bounded by a timeout, after a cheap subprocess health
probe; if the default backend is sick or the child hangs, the parent
re-runs the child on CPU, and if everything fails it still emits an
explicit failure JSON line instead of crashing — the driver always gets a
parseable record.
"""

from __future__ import annotations

import json
import os
import sys
import time

BASELINE_GRAPHS_PER_SEC = 1000.0 / 4.6  # reference: 4.6 ms/example on RTX 3090
_CHILD_TAG = "BENCHJSON:"

PROBE_TIMEOUT = float(os.environ.get("DEEPDFA_BENCH_PROBE_TIMEOUT", 240))
CHILD_TIMEOUT = float(os.environ.get("DEEPDFA_BENCH_CHILD_TIMEOUT", 1200))


def _build_workload(n_examples: int):
    from deepdfa_tpu.data import (
        bigvul_stmt_sizes,
        build_dataset,
        generate,
        to_examples,
    )
    from deepdfa_tpu.graphs import bucket_batches

    sizes = bigvul_stmt_sizes(n_examples, seed=7)
    synth = generate(n_examples, vuln_rate=0.06, seed=7, stmt_sizes=sizes)
    specs, _ = build_dataset(
        to_examples(synth), train_ids=range(n_examples), limit_all=1000,
        limit_subkeys=1000,
    )
    # one static batch signature; budgets sized so even the clipped p100
    # graph (~500 stmts -> ~1k nodes) fits and nothing is dropped
    num_graphs, node_budget, edge_budget = 256, 16384, 65536
    batches = list(
        bucket_batches(
            specs, num_graphs, node_budget, edge_budget, drop_oversized=False
        )
    )
    n_graphs = sum(int(b.graph_mask.sum()) for b in batches)
    assert n_graphs == len(specs), (n_graphs, len(specs))
    return batches


def run_measurement(platform: str) -> dict:
    """The actual benchmark; runs in the child process."""
    from deepdfa_tpu.core.backend import enable_compile_cache, force_cpu

    if platform == "cpu":
        force_cpu()
    enable_compile_cache()  # reuse executables across runs; makes the
    # measurement robust to the remote compile service's slow phases
    import jax
    import numpy as np

    from deepdfa_tpu.core import Config
    from deepdfa_tpu.models import DeepDFA

    n_examples = int(os.environ.get("DEEPDFA_BENCH_EXAMPLES", 512))
    reps = int(os.environ.get("DEEPDFA_BENCH_REPS", 8))
    if platform == "cpu":
        n_examples = min(n_examples, 256)
        reps = min(reps, 2)
    batches = _build_workload(n_examples)

    cfg = Config()
    model = DeepDFA.from_config(cfg.model, input_dim=1002)
    params = model.init(jax.random.key(0), batches[0])

    @jax.jit
    def forward(params, batch):
        return jax.nn.sigmoid(model.apply(params, batch))

    # bfloat16 inference (the TPU-native dtype): params cast to bf16 makes
    # the whole network compute in bf16 (bf16 x bf16 promotion); gated on
    # the probabilities agreeing with f32 so the speed never costs
    # correctness. DEEPDFA_BENCH_DTYPE=float32 opts out.
    want_bf16 = (
        os.environ.get("DEEPDFA_BENCH_DTYPE", "bfloat16") == "bfloat16"
        and platform != "cpu"
    )
    dtype = "float32"
    if want_bf16:
        import jax.numpy as jnp

        params_bf16 = jax.tree.map(
            lambda x: x.astype(jnp.bfloat16)
            if x.dtype == jnp.float32
            else x,
            params,
        )
        p32 = np.asarray(
            jax.device_get(forward(params, batches[0])), np.float32
        )
        p16 = np.asarray(
            jax.device_get(forward(params_bf16, batches[0])), np.float32
        )
        if float(np.abs(p32 - p16).max()) < 0.02:
            params, dtype = params_bf16, "bfloat16"

    # warmup / compile
    jax.block_until_ready(forward(params, batches[0]))

    # steady-state: each rep is one timed pass over the whole batch
    # stream. The headline is the MEDIAN window — comparable to the
    # baseline's average-latency figure while robust to the transient
    # host-side stalls the shared tunnel injects (which a single
    # all-reps window folds into the denominator); best and mean are
    # recorded alongside
    n_per_pass = sum(int(np.asarray(b.graph_mask).sum()) for b in batches)
    rates = []
    for _ in range(reps):
        t0 = time.perf_counter()
        out = None
        for b in batches:
            out = forward(params, b)
        jax.block_until_ready(out)
        rates.append(n_per_pass / (time.perf_counter() - t0))

    value = float(np.median(rates))
    return {
        "metric": "deepdfa_infer_graphs_per_sec",
        "value": round(value, 1),
        "unit": "graphs/s",
        "vs_baseline": round(value / BASELINE_GRAPHS_PER_SEC, 2),
        "best_graphs_per_sec": round(max(rates), 1),
        "mean_graphs_per_sec": round(float(np.mean(rates)), 1),
        "platform": jax.devices()[0].platform,
        "dtype": dtype,
        "n_examples": n_examples,
        "size_dist": "bigvul_lognormal(median=14,sigma=1.2,max=500)",
    }


def _run_child(platform: str, timeout: float) -> tuple[dict | None, str]:
    """Run the measurement in a watchdogged subprocess; (result, error)."""
    from deepdfa_tpu.core.backend import bounded_run

    res, err = bounded_run(
        [sys.executable, os.path.abspath(__file__), "--child", platform],
        timeout,
        what=f"{platform} bench child",
    )
    if res is None:
        return None, err
    for line in res.stdout.splitlines():
        if line.startswith(_CHILD_TAG):
            return json.loads(line[len(_CHILD_TAG) :]), ""
    return None, f"{platform} bench child emitted no result line"


def main() -> None:
    from deepdfa_tpu.core.backend import cpu_pinned, probe_default_backend

    errors: list[str] = []
    attempts: list[str] = []
    if cpu_pinned():
        attempts = ["cpu"]
    else:
        ok, detail = probe_default_backend(PROBE_TIMEOUT)
        if ok:
            attempts = [detail]
            if detail != "cpu":
                attempts.append("cpu")
        else:
            errors.append(detail)
            attempts = ["cpu"]

    for platform in attempts:
        result, err = _run_child(platform, CHILD_TIMEOUT)
        if result is not None:
            if errors:
                result["fallback_from"] = "; ".join(errors)
            print(json.dumps(result), flush=True)
            return
        errors.append(err)

    print(
        json.dumps(
            {
                "metric": "deepdfa_infer_graphs_per_sec",
                "value": 0.0,
                "unit": "graphs/s",
                "vs_baseline": 0.0,
                "error": "; ".join(errors),
            }
        ),
        flush=True,
    )


if __name__ == "__main__":
    if len(sys.argv) >= 3 and sys.argv[1] == "--child":
        print(_CHILD_TAG + json.dumps(run_measurement(sys.argv[2])), flush=True)
    else:
        main()
