"""Headline benchmark: DeepDFA inference throughput on one TPU chip.

Prints full json result lines, best-last (the driver records the LAST
line):
  {"metric": "deepdfa_infer_graphs_per_sec", "value": N, "unit": "graphs/s",
   "vs_baseline": R, "platform": "...", "mfu": ..., "train_graphs_per_sec": ...}

Baseline: the reference's single-RTX-3090 DeepDFA inference latency of
4.6 ms/example (paper Table 5, BASELINE.md "Efficiency") = 217.4 graphs/s.
The workload is the flagship configuration (input_dim 1002, hidden 32,
n_steps 5, concat_all_absdf) over CFGs whose size distribution matches
Big-Vul's heavy tail (lognormal median 14 stmts, p99 ~230, clipped 500 —
see data/synthetic.py:bigvul_stmt_sizes), produced by the full frontend
pipeline and batch-packed exactly as in training/eval.

Resilience (the round-1/round-2/round-3 failure modes): the TPU tunnel
can wedge either in the remote compile service (round 1: rc=1 /
indefinite compile hang) or in backend INIT itself (round 3: even
jax.devices() blocks), and in round 2 a single 240s health probe timed
out and the bench silently fell back to CPU. Hardened protocol (see
main()): healthy probe -> measure TPU; failed probe -> measure CPU FIRST
so a complete record is emitted within ~15 minutes, then spend remaining
budget on probe-gated retries (short probes spaced across the window;
a healthy one unlocks a full TPU measurement) and emit an upgraded line
if one lands. A CPU-fallback record embeds the newest committed
watchdog TPU capture under ``last_healthy_tpu`` so the driver artifact
carries dated TPU evidence even when its own window loses the tunnel
lottery. Every subprocess runs under a hard timeout against one total
wall-clock deadline (DEEPDFA_BENCH_TOTAL_BUDGET, default 3300s); the
compile-cache-enabled probe makes a once-successful probe a cache hit
forever after; the train step is measured in a SEPARATE bounded child
(scan_steps GGNN on TPU to keep the compiled program small) so a
train-side wedge cannot cost the inference fields.

MFU methodology: FLOPs come from XLA's compiled-HLO cost analysis
(eval/profiling.compiled_cost — the reference counts MACs with DeepSpeed's
FlopsProfiler, base_module.py:240-291); model_flops_per_sec = flops/example
x measured graphs/s; mfu divides by the chip's peak for the compute dtype.
"""

from __future__ import annotations

import json
import os
import sys
import time

BASELINE_GRAPHS_PER_SEC = 1000.0 / 4.6  # reference: 4.6 ms/example on RTX 3090
# 25 epochs x ~20k undersampled graphs / 540 s (paper Table 5, 9-min train)
BASELINE_TRAIN_GRAPHS_PER_SEC = 25 * 20_000 / 540.0
_CHILD_TAG = "BENCHJSON:"

#: 120s, not 300s: in a HEALTHY window the probe's tiny jit is a compile
#: -cache hit and completes in <30s; 300s only bought longer waits on a
#: wedged service (r1-r4 all burned the full budget exactly once). The
#: saved time funds RETRIES spread across the driver window instead.
PROBE_TIMEOUT = float(os.environ.get("DEEPDFA_BENCH_PROBE_TIMEOUT", 120))
PROBE_RETRIES = int(os.environ.get("DEEPDFA_BENCH_PROBE_RETRIES", 3))
CHILD_TIMEOUT = float(os.environ.get("DEEPDFA_BENCH_CHILD_TIMEOUT", 1500))
TRAIN_TIMEOUT = float(os.environ.get("DEEPDFA_BENCH_TRAIN_TIMEOUT", 1200))
COMBINED_TIMEOUT = float(
    os.environ.get("DEEPDFA_BENCH_COMBINED_TIMEOUT", 600)
)
SERVE_TIMEOUT = float(os.environ.get("DEEPDFA_BENCH_SERVE_TIMEOUT", 420))
SCAN_TIMEOUT = float(os.environ.get("DEEPDFA_BENCH_SCAN_TIMEOUT", 420))
SCATTER_TIMEOUT = float(
    os.environ.get("DEEPDFA_BENCH_SCATTER_TIMEOUT", 420)
)
FLEET_TIMEOUT = float(os.environ.get("DEEPDFA_BENCH_FLEET_TIMEOUT", 420))
CASCADE_TIMEOUT = float(
    os.environ.get("DEEPDFA_BENCH_CASCADE_TIMEOUT", 420)
)
TUNE_TIMEOUT = float(os.environ.get("DEEPDFA_BENCH_TUNE_TIMEOUT", 420))
TOTAL_BUDGET = float(os.environ.get("DEEPDFA_BENCH_TOTAL_BUDGET", 3300))

#: peak dense-matmul FLOP/s per chip, by (platform, dtype). v5e: 197
#: TFLOP/s bf16 (public spec); f32 runs the MXU at half rate. MFU on CPU
#: is not meaningful and is reported as null.
_PEAK_FLOPS = {
    ("tpu", "bfloat16"): 1.97e14,
    ("tpu", "float32"): 9.85e13,
}


def _mfu_fields(flops_per_example: float, graphs_per_sec: float,
                platform: str, dtype: str,
                bytes_per_example: float = 0.0,
                roofline: bool = False) -> dict:
    model_fps = flops_per_example * graphs_per_sec
    peak = _PEAK_FLOPS.get((platform, dtype))
    out = {
        "flops_per_example": round(flops_per_example, 1),
        "model_flops_per_sec": round(model_fps, 1),
        "mfu": round(model_fps / peak, 6) if peak else None,
    }
    if bytes_per_example > 0:
        out["bytes_per_example"] = round(bytes_per_example, 1)
        out["bytes_per_sec"] = round(bytes_per_example * graphs_per_sec, 1)
        out["arithmetic_intensity_flops_per_byte"] = round(
            flops_per_example / bytes_per_example, 3)
    if platform == "tpu":
        # spec-peak MFU misleads on a shared/tunneled chip: record the
        # MEASURED dense-matmul ceiling next to it (eval/profiling.py;
        # never raises — probe failures land in matmul_ceiling_error)
        from deepdfa_tpu.eval.profiling import ceiling_fields

        out.update(ceiling_fields(model_fps))
        if roofline and bytes_per_example > 0:
            # bandwidth side of the roofline (docs/roofline.md): the
            # GGNN step is gather/scatter traffic, so achieved bytes/s
            # vs the measured stream AND gather ceilings is the MFU
            # defense the flops-side number cannot give
            from deepdfa_tpu.eval.profiling import roofline_fields

            out.update(roofline_fields(bytes_per_example * graphs_per_sec))
    return out


def _build_workload(n_examples: int):
    from deepdfa_tpu.data import flagship_corpus
    from deepdfa_tpu.graphs import bucket_batches

    specs = flagship_corpus(n_examples)
    # one static batch signature; budgets sized so even the clipped p100
    # graph (~500 stmts -> ~1k nodes) fits and nothing is dropped
    num_graphs, node_budget, edge_budget = 256, 16384, 65536
    batches = list(
        bucket_batches(
            specs, num_graphs, node_budget, edge_budget, drop_oversized=False
        )
    )
    n_graphs = sum(int(b.graph_mask.sum()) for b in batches)
    assert n_graphs == len(specs), (n_graphs, len(specs))
    return batches


def run_measurement(platform: str) -> dict:
    """The inference benchmark; runs in the child process.

    `platform` is the REQUEST ("cpu" forces CPU; anything else measures
    on whatever the default backend resolves to). Workload caps and the
    bf16 path key off the RESOLVED platform, so a "default" request that
    lands on CPU still gets the capped CPU workload.
    """
    from deepdfa_tpu.core.backend import enable_compile_cache, force_cpu

    if platform == "cpu":
        force_cpu()
    enable_compile_cache()  # reuse executables across runs; makes the
    # measurement robust to the remote compile service's slow phases
    import jax
    import numpy as np

    from deepdfa_tpu.core import Config
    from deepdfa_tpu.eval.profiling import compiled_cost
    from deepdfa_tpu.models import DeepDFA

    platform = jax.devices()[0].platform
    n_examples = int(os.environ.get("DEEPDFA_BENCH_EXAMPLES", 512))
    reps = int(os.environ.get("DEEPDFA_BENCH_REPS", 8))
    if platform == "cpu":
        n_examples = min(n_examples, 256)
        reps = min(reps, 2)
    batches = _build_workload(n_examples)

    # efficiency ledger (ISSUE 10, docs/efficiency.md): the bench child
    # runs with the ledger ON (runtime measured ceilings included) so
    # the record carries `ledger_mfu/*` + `compile_seconds_total` —
    # the same accounting an obs.ledger-enabled production run emits
    from deepdfa_tpu.obs import ledger as obs_ledger

    obs_ledger.enable(ceilings=True)

    cfg = Config()
    model = DeepDFA.from_config(cfg.model, input_dim=1002)
    params = model.init(jax.random.key(0), batches[0])

    @jax.jit
    def forward(params, batch):
        return jax.nn.sigmoid(model.apply(params, batch))

    # bfloat16 inference (the TPU-native dtype): params cast to bf16 makes
    # the whole network compute in bf16 (bf16 x bf16 promotion); gated on
    # the probabilities agreeing with f32 so the speed never costs
    # correctness. DEEPDFA_BENCH_DTYPE=float32 opts out.
    want_bf16 = (
        os.environ.get("DEEPDFA_BENCH_DTYPE", "bfloat16") == "bfloat16"
        and platform != "cpu"
    )
    dtype = "float32"
    if want_bf16:
        import jax.numpy as jnp

        params_bf16 = jax.tree.map(
            lambda x: x.astype(jnp.bfloat16)
            if x.dtype == jnp.float32
            else x,
            params,
        )
        p32 = np.asarray(
            jax.device_get(forward(params, batches[0])), np.float32
        )
        p16 = np.asarray(
            jax.device_get(forward(params_bf16, batches[0])), np.float32
        )
        if float(np.abs(p32 - p16).max()) < 0.02:
            params, dtype = params_bf16, "bfloat16"

    # warmup / compile — fetch-bounded so no warmup execution can bleed
    # into the first timed window (same tunnel caveat as the windows)
    np.asarray(forward(params, batches[0]))

    # steady-state: each rep is one timed pass over the whole batch
    # stream. The headline is the MEDIAN window — comparable to the
    # baseline's average-latency figure while robust to the transient
    # host-side stalls the shared tunnel injects (which a single
    # all-reps window folds into the denominator); best and mean are
    # recorded alongside
    n_per_pass = sum(int(np.asarray(b.graph_mask).sum()) for b in batches)
    rates = []
    infer_seconds = 0.0
    for _ in range(reps):
        t0 = time.perf_counter()
        out = None
        for b in batches:
            out = forward(params, b)
        # host FETCH, not block_until_ready: through the remote-TPU
        # tunnel a buffer can be reported ready before the execution
        # completes, silently inflating rates (observed as MFU > 1.0);
        # a device->host copy of the result cannot lie
        np.asarray(out)
        dt = time.perf_counter() - t0
        infer_seconds += dt
        rates.append(n_per_pass / dt)
    # per-batch program executions against the measured window: the
    # ledger's rolling-MFU join (flops arrive from compiled_cost below)
    obs_ledger.observe_execution(
        "bench_infer", "G256", infer_seconds, n=reps * len(batches)
    )

    value = float(np.median(rates))
    result = {
        "metric": "deepdfa_infer_graphs_per_sec",
        "value": round(value, 1),
        "unit": "graphs/s",
        "vs_baseline": round(value / BASELINE_GRAPHS_PER_SEC, 2),
        "best_graphs_per_sec": round(max(rates), 1),
        "mean_graphs_per_sec": round(float(np.mean(rates)), 1),
        "platform": jax.devices()[0].platform,
        "dtype": dtype,
        "n_examples": n_examples,
        "size_dist": "bigvul_lognormal(median=14,sigma=1.2,max=500)",
    }
    try:
        cost = compiled_cost(
            lambda p, b: jax.nn.sigmoid(model.apply(p, b)),
            params, batches[0],
            ledger_tag="bench_infer", ledger_signature="G256",
        )
        flops = cost["flops"]
        if flops <= 0:  # cost analysis unavailable != "MFU is zero"
            raise RuntimeError("XLA cost analysis returned no flops")
        n_b = max(int(np.asarray(batches[0].graph_mask).sum()), 1)
        result.update(_mfu_fields(
            flops / n_b, value, result["platform"], dtype,
            bytes_per_example=cost.get("bytes_accessed", 0.0) / n_b,
        ))
    except Exception as e:  # cost analysis must never cost the headline
        result["mfu_error"] = f"{type(e).__name__}: {e}"[:200]
    # the ledger stamps (ISSUE 10): per-site MFU-vs-measured-ceiling +
    # total AOT compile wall time, gated in obs/bench_gate.py
    led = obs_ledger.get()
    if led is not None:
        result.update(led.mfu_record())
    return result


def run_train_measurement(platform: str) -> dict:
    """Flagship train-step throughput (forward+backward+AdamW); child.

    scan_steps GGNN on TPU: lax.scan over the 5 propagation steps keeps
    the compiled program small enough for the remote compile service
    (the round-2 unrolled train compile wedged it twice);
    DEEPDFA_BENCH_SCAN_STEPS=0 opts back into the unrolled body.
    """
    from deepdfa_tpu.core.backend import enable_compile_cache, force_cpu

    if platform == "cpu":
        force_cpu()
    enable_compile_cache()
    import dataclasses

    import jax
    import numpy as np

    from deepdfa_tpu.core import Config
    from deepdfa_tpu.data import flagship_corpus
    from deepdfa_tpu.eval.profiling import compiled_cost
    from deepdfa_tpu.graphs import shard_bucket_batches
    from deepdfa_tpu.models import DeepDFA
    from deepdfa_tpu.train import GraphTrainer

    platform = jax.devices()[0].platform
    n_examples = int(os.environ.get("DEEPDFA_BENCH_TRAIN_EXAMPLES", 512))
    reps = int(os.environ.get("DEEPDFA_BENCH_REPS", 8))
    if platform == "cpu":
        n_examples = min(n_examples, 128)
        reps = min(reps, 2)
    scan_env = os.environ.get("DEEPDFA_BENCH_SCAN_STEPS", "auto")
    scan = platform != "cpu" if scan_env == "auto" else scan_env == "1"

    specs = flagship_corpus(n_examples)
    t_pack = time.perf_counter()
    batches = list(
        shard_bucket_batches(specs, 1, 256, 16384, 65536, oversized="raise")
    )
    host_pack_seconds = time.perf_counter() - t_pack

    cfg = Config()
    cfg = dataclasses.replace(
        cfg, model=dataclasses.replace(cfg.model, scan_steps=scan)
    )
    model = DeepDFA.from_config(cfg.model, input_dim=1002)
    trainer = GraphTrainer(model, cfg)
    state = trainer.init_state(batches[0])

    from deepdfa_tpu.data.prefetch import PipelineStats, device_placer, prefetch

    placer = device_placer(trainer.mesh)
    # warm up with the SAME committed sharding the timed loop's
    # device_placer uses — a raw host batch here would leave the
    # placer-committed signature uncompiled and the first timed rep
    # would absorb a recompile (scripts/bench_prefetch.py:_warm_compile)
    state, warm_loss = trainer.train_step(state, placer(batches[0]))
    float(warm_loss)  # fetch-bounded (see inference warmup note)

    # efficiency ledger (ISSUE 10): ON for the train child too, so the
    # record carries the train step's cost-accounted compile + rolling
    # MFU next to the existing mfu fields
    from deepdfa_tpu.obs import ledger as obs_ledger

    obs_ledger.enable(ceilings=True)

    n_per_pass = sum(int(np.asarray(b.graph_mask).sum()) for b in batches)
    # batches ride the instrumented prefetch pipeline (pre-packed, so the
    # source stage is ~free): input_wait_fraction isolates how much of the
    # timed window the device sat waiting on host H2D — the host-vs-device
    # attribution a CPU-fallback record otherwise cannot make
    rates = []
    wait_fracs = []
    train_seconds = 0.0
    for _ in range(reps):
        stats = PipelineStats()
        t0 = time.perf_counter()
        loss = None
        for b in prefetch(iter(batches), 2, placer, stats=stats):
            state, loss = trainer.train_step(state, b)
        # host fetch (see inference note): the scalar's arrival on host
        # transitively proves every chained train_step completed
        float(loss)
        dt = time.perf_counter() - t0
        train_seconds += dt
        rates.append(n_per_pass / dt)
        wait_fracs.append(stats.wait_fraction(dt))
    obs_ledger.observe_execution(
        "bench_train", "G256", train_seconds, n=reps * len(batches)
    )

    # resilience-guard overhead (ISSUE 3): the same rep loop through the
    # divergence-guarded step (on-device finiteness select + lr_scale).
    # The ok flags are fetched lazily AFTER the timed window — exactly
    # the lagged-fetch pattern the runner uses, so this measures the
    # guard's steady-state cost, which must stay ~free (<=2%).
    guard_rates = []
    skipped = 0
    gstate = trainer.init_state(batches[0])
    for _ in range(2):  # warm both sharding signatures of the guarded jit
        gstate, warm_loss, _ok = trainer.train_step_guarded(
            gstate, placer(batches[0]), 1.0
        )
    float(warm_loss)
    for _ in range(reps):
        oks = []
        t0 = time.perf_counter()
        loss = None
        for b in prefetch(iter(batches), 2, placer):
            gstate, loss, ok = trainer.train_step_guarded(gstate, b, 1.0)
            oks.append(ok)
        float(loss)
        guard_rates.append(n_per_pass / (time.perf_counter() - t0))
        skipped += sum(1 for o in oks if not bool(np.asarray(o)))

    # tracing-overhead measurement (ISSUE 4 acceptance): identical rep
    # loops with the unified trace ENABLED vs disabled (the call sites
    # are the same either way — a disabled span is a no-op), INTERLEAVED
    # plain/traced because this box's throughput drifts ±40% minute to
    # minute: two sequential blocks would measure the drift, not the
    # tracer (the r1 guard-overhead measurement had the same hazard).
    # The delta of the interleaved medians is the enabled tracer's cost
    # — `obs_overhead_fraction`, bounded at <=2% on the smoke config.
    import tempfile

    from deepdfa_tpu.obs import trace as obs_trace

    obs_plain: list[float] = []
    obs_traced: list[float] = []
    # an in-process caller (scripts/bench_train.py) may be running under
    # an ambient tracing session (exported trace dir): snapshot it so
    # the measurement's enable/disable cycles hand it back intact
    ambient_dir = os.environ.get(obs_trace.ENV_TRACE_DIR)
    try:
        with tempfile.TemporaryDirectory() as td:
            for i in range(2 * reps):
                traced = i % 2 == 1
                if traced:
                    obs_trace.enable(td, process_name="bench-train")
                try:
                    t0 = time.perf_counter()
                    loss = None
                    for b in prefetch(iter(batches), 2, placer):
                        with obs_trace.span("train_step", cat="train"):
                            state, loss = trainer.train_step(state, b)
                    float(loss)
                    (obs_traced if traced else obs_plain).append(
                        n_per_pass / (time.perf_counter() - t0)
                    )
                finally:
                    if traced:
                        obs_trace.disable()
    finally:
        if ambient_dir:
            obs_trace.enable(
                ambient_dir, process_name="bench-train", export_env=True
            )

    # ledger-overhead measurement (ISSUE 10 acceptance): identical rep
    # loops with the ledger's per-step join (observe_step_seconds — the
    # dominant steady-state cost; the loops' once-per-signature compile
    # hook is warmup-only) vs without, INTERLEAVED for the same drift
    # reason as the obs measurement above. The observations go to a
    # SCRATCH site: these windows time async host dispatch, not device
    # steps, and must never pollute the bench_train site whose rolling
    # MFU is stamped below (a flops-less scratch site is excluded from
    # ledger_mfu by construction). <= 2% (obs/bench_gate.py).
    obs_ledger.set_step_site("bench_overhead_probe", "G256")
    led_plain: list[float] = []
    led_on: list[float] = []
    for i in range(2 * reps):
        ledgered = i % 2 == 1
        t0 = time.perf_counter()
        loss = None
        for b in prefetch(iter(batches), 2, placer):
            t_step = time.perf_counter()
            state, loss = trainer.train_step(state, b)
            if ledgered:
                obs_ledger.observe_step_seconds(
                    time.perf_counter() - t_step
                )
        float(loss)
        (led_on if ledgered else led_plain).append(
            n_per_pass / (time.perf_counter() - t0)
        )

    value = float(np.median(rates))
    guard_value = float(np.median(guard_rates))
    obs_value = float(np.median(obs_traced))
    obs_baseline = float(np.median(obs_plain))
    result = {
        "train_graphs_per_sec": round(value, 1),
        "train_vs_baseline": round(value / BASELINE_TRAIN_GRAPHS_PER_SEC, 2),
        "train_best_graphs_per_sec": round(max(rates), 1),
        "train_platform": jax.devices()[0].platform,
        "train_scan_steps": scan,
        "train_n_examples": n_examples,
        # host-side attribution (ISSUE 1 satellite): one-time packing cost
        # of the workload + fraction of a timed pass spent input-blocked
        "host_pack_seconds": round(host_pack_seconds, 3),
        "input_wait_fraction": round(float(np.median(wait_fracs)), 4),
        # self-healing observables (ISSUE 3, docs/resilience.md): the
        # guarded-step throughput tax plus the counters bench history
        # uses to show when a run healed itself (0s on a healthy bench)
        "train_guarded_graphs_per_sec": round(guard_value, 1),
        "train_guard_overhead_fraction": round(
            max(0.0, 1.0 - guard_value / value), 4
        ) if value else None,
        "resumed_from_step": 0,
        "skipped_steps": skipped,
        "rollbacks": 0,
        # unified-telemetry tax (ISSUE 4, docs/observability.md): the
        # interleaved traced-vs-plain medians; must stay <=2%
        "obs_traced_graphs_per_sec": round(obs_value, 1),
        "obs_overhead_fraction": round(
            max(0.0, 1.0 - obs_value / obs_baseline), 4
        ) if obs_baseline else None,
        # efficiency-ledger tax (ISSUE 10): interleaved with/without the
        # ledger's per-step join, comparing the BEST window of each
        # population — the ledger's per-step cost is deterministic (one
        # lock + three adds), so it survives into the best windows,
        # while this box's transient host stalls (which land on one
        # side at random with few reps) do not; bounded at <=2%
        # absolute in obs/bench_gate.py
        "obs_ledger_overhead_fraction": round(
            max(0.0, 1.0 - max(led_on) / max(led_plain)), 4
        ) if led_plain and led_on else None,
    }
    try:
        cost = compiled_cost(
            lambda s, b: trainer.train_step(s, b), state, batches[0],
            ledger_tag="bench_train", ledger_signature="G256",
        )
        flops = cost["flops"]
        if flops <= 0:
            raise RuntimeError("XLA cost analysis returned no flops")
        n_b = max(int(np.asarray(batches[0].graph_mask).sum()), 1)
        mfu = _mfu_fields(
            flops / n_b, value, result["train_platform"], "float32",
            bytes_per_example=cost.get("bytes_accessed", 0.0) / n_b,
            roofline=True,  # the train MFU is the number under defense
        )
        result.update({f"train_{k}": v for k, v in mfu.items()})
    except Exception as e:
        result["train_mfu_error"] = f"{type(e).__name__}: {e}"[:200]
    led = obs_ledger.get()
    if led is not None:
        # train_-prefixed so the merged record keeps BOTH children's
        # stamps (the infer child owns the unprefixed fields)
        result.update({
            f"train_{k}": v for k, v in led.mfu_record().items()
        })
    return result


def run_combined_measurement(platform: str) -> dict:
    """Combined (transformer+graph) text-path throughput with vs without
    sequence-length bucketing (ISSUE 2); child, CPU-viable.

    Delegates to scripts/bench_prefetch.py:bench_bucketed — the same
    fixed-vs-bucketed measurement tier-1 smokes — and prefixes the
    observables for the merged record: REAL-token throughput
    (`combined_train_tokens_per_sec`) and padding-waste fraction are
    shape-invariant, so the bucketing win is measurable on the CPU
    fallback too.
    """
    from deepdfa_tpu.core.backend import enable_compile_cache, force_cpu

    if platform == "cpu":
        force_cpu()
    enable_compile_cache()
    sys.path.insert(
        0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "scripts")
    )
    from bench_prefetch import bench_bucketed

    import jax

    platform = jax.devices()[0].platform
    smoke = platform == "cpu"
    rec = bench_bucketed(
        int(os.environ.get("DEEPDFA_BENCH_COMBINED_EXAMPLES",
                           64 if smoke else 256)),
        1 if smoke else 2,
        smoke=smoke,
    )
    return {
        "combined_train_tokens_per_sec": rec["value"],
        "combined_train_examples_per_sec": rec["examples_per_sec_bucketed"],
        "combined_tokens_per_sec_fixed": rec["tokens_per_sec_fixed"],
        "combined_padding_waste_fixed": rec["padding_waste_fixed"],
        "combined_padding_waste": rec["padding_waste_bucketed"],
        "combined_bucketed_examples_speedup": rec["bucketed_examples_speedup"],
        "combined_seq_buckets": rec["buckets"],
        "combined_steady_state_recompiles": rec["steady_state_recompiles"],
        "combined_platform": platform,
    }


def run_serve_measurement(platform: str) -> dict:
    """Online-serving observables (ISSUE 5); child, CPU-viable.

    Delegates to scripts/bench_serve.py:bench_serve — the same dynamic-
    batcher + AOT-bucket-executable drive tier-1 smokes — and prefixes
    the fields for the merged record. The zero-steady-state-recompiles
    invariant rides along as a measured field, so a serving-path
    regression shows up in BENCH_*.json, not just in tests."""
    from deepdfa_tpu.core.backend import enable_compile_cache, force_cpu

    if platform == "cpu":
        force_cpu()
    enable_compile_cache()
    sys.path.insert(
        0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "scripts")
    )
    from bench_serve import bench_serve

    import jax

    platform = jax.devices()[0].platform
    smoke = platform == "cpu"
    rec = bench_serve(
        int(os.environ.get("DEEPDFA_BENCH_SERVE_EXAMPLES",
                           48 if smoke else 256)),
        smoke=smoke,
    )
    return {
        "serve_requests_per_sec": rec["serve_requests_per_sec"],
        "serve_cold_requests_per_sec": rec["serve_cold_requests_per_sec"],
        "serve_latency_p50_ms": rec["serve_latency_p50_ms"],
        "serve_latency_p99_ms": rec["serve_latency_p99_ms"],
        "serve_batch_occupancy_mean": rec["serve_batch_occupancy_mean"],
        "serve_steady_state_recompiles": (
            rec["serve_steady_state_recompiles"]
        ),
        "serve_platform": platform,
    }


def run_scan_measurement(platform: str) -> dict:
    """Whole-repo scan observables (ISSUE 8); child, CPU-viable.

    Delegates to scripts/bench_scan.py:bench_scan — the cold / warm-
    cache / incremental-rescan drive tier-1 smokes — and prefixes the
    fields for the merged record. The incremental-skip and zero-
    recompile contracts ride along as measured fields."""
    from deepdfa_tpu.core.backend import enable_compile_cache, force_cpu

    if platform == "cpu":
        force_cpu()
    enable_compile_cache()
    sys.path.insert(
        0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "scripts")
    )
    if "DEEPDFA_TPU_STORAGE" not in os.environ:
        import tempfile

        tmp = tempfile.TemporaryDirectory(prefix="bench-scan-")
        os.environ["DEEPDFA_TPU_STORAGE"] = tmp.name
    from bench_scan import bench_scan

    import jax

    platform = jax.devices()[0].platform
    smoke = platform == "cpu"
    rec = bench_scan(
        int(os.environ.get("DEEPDFA_BENCH_SCAN_FUNCTIONS",
                           24 if smoke else 96)),
        smoke=smoke,
    )
    return {
        "scan_functions_per_sec": rec["scan_functions_per_sec"],
        "scan_warm_functions_per_sec": rec["scan_warm_functions_per_sec"],
        "scan_incremental_functions_per_sec": (
            rec["scan_incremental_functions_per_sec"]
        ),
        "scan_cache_hit_fraction": rec["scan_cache_hit_fraction"],
        "scan_incremental_skip_fraction": (
            rec["scan_incremental_skip_fraction"]
        ),
        "scan_steady_state_recompiles": (
            rec["scan_steady_state_recompiles"]
        ),
        "scan_platform": platform,
    }


def run_scatter_measurement(platform: str) -> dict:
    """Fused GGNN-step A/B observables (ISSUE 9); child, CPU-viable.

    Delegates to scripts/bench_scatter.py:bench_ggnn_step — the lax-vs-
    Pallas-kernel per-step time plus MFU against the same-window
    measured matmul ceiling and gather-bandwidth roofline tier-1 smokes
    — and prefixes nothing: the fields already carry the ggnn_* names
    the bench gate reads (`ggnn_step_us` / `ggnn_unroll_step_us`
    lower-is-better, `ggnn_mfu`, `ggnn_kernel_int8_rel_err` absolute-
    bounded), so the MFU gap and the fused-unroll/int8 numbers are
    tracked in BENCH_r*.json."""
    from deepdfa_tpu.core.backend import enable_compile_cache, force_cpu

    if platform == "cpu":
        force_cpu()
    enable_compile_cache()
    sys.path.insert(
        0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "scripts")
    )
    from bench_scatter import bench_ggnn_step

    import jax

    platform = jax.devices()[0].platform
    smoke = platform == "cpu"
    rec = bench_ggnn_step(smoke=smoke)
    out = {k: v for k, v in rec.items() if k.startswith("ggnn_")}
    # the probe ceilings ride under a ggnn_ prefix: the train child's
    # own matmul_*/gather_* window fields must survive the merged
    # record un-overwritten (its mfu_vs_measured_ceiling is computed
    # against THOSE, not this child's window)
    for k in ("matmul_tflops_measured", "matmul_probe",
              "gather_gbps_measured", "gather_probe"):
        if k in rec:
            out[f"ggnn_{k}"] = rec[k]
    out["scatter_platform"] = platform
    return out


def run_fleet_measurement(platform: str) -> dict:
    """Fleet-under-overload observables (ISSUE 11); child, CPU-viable.

    Delegates to scripts/bench_load.py:bench_load — the open-loop
    Poisson drive (heavy-tail size mix, tenant mix) against a real
    router + admission stack over in-process replicas — and passes the
    fields through: they already carry the fleet_* names the bench gate
    reads (`fleet_p99_overload_ms` and `fleet_shed_rate`, both
    lower-is-better)."""
    from deepdfa_tpu.core.backend import enable_compile_cache, force_cpu

    if platform == "cpu":
        force_cpu()
    enable_compile_cache()
    sys.path.insert(
        0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "scripts")
    )
    if "DEEPDFA_TPU_STORAGE" not in os.environ:
        import tempfile

        tmp = tempfile.TemporaryDirectory(prefix="bench-fleet-")
        os.environ["DEEPDFA_TPU_STORAGE"] = tmp.name
    from bench_load import bench_load

    import jax

    platform = jax.devices()[0].platform
    smoke = platform == "cpu"
    rec = bench_load(
        int(os.environ.get("DEEPDFA_BENCH_FLEET_REQUESTS",
                           120 if smoke else 600)),
        smoke=smoke,
    )
    out = {k: v for k, v in rec.items() if k.startswith("fleet_")}
    out["fleet_platform"] = platform
    return out


def run_cascade_measurement(platform: str) -> dict:
    """Cascaded-inference frontier observables (ISSUE 12); child,
    CPU-viable.

    Delegates to scripts/bench_cascade.py:bench_cascade — combined-only
    vs cascade throughput over one labeled synthetic dev set, the
    fitted-band escalation rate, the one-sided AUC drift, and the
    quantized stage-2 entry's param-bytes fraction — and passes the
    fields through: they already carry the cascade_*/quant_* names the
    bench gate reads (`cascade_score_drift` and
    `quant_param_bytes_fraction` are absolute-bounded)."""
    from deepdfa_tpu.core.backend import enable_compile_cache, force_cpu

    if platform == "cpu":
        force_cpu()
    enable_compile_cache()
    sys.path.insert(
        0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "scripts")
    )
    if "DEEPDFA_TPU_STORAGE" not in os.environ:
        import tempfile

        tmp = tempfile.TemporaryDirectory(prefix="bench-cascade-")
        os.environ["DEEPDFA_TPU_STORAGE"] = tmp.name
    from bench_cascade import bench_cascade

    import jax

    platform = jax.devices()[0].platform
    smoke = platform == "cpu"
    rec = bench_cascade(
        int(os.environ.get("DEEPDFA_BENCH_CASCADE_EXAMPLES",
                           48 if smoke else 128)),
        smoke=smoke,
    )
    out = {
        k: v for k, v in rec.items()
        if k.startswith(("cascade_", "quant_"))
    }
    out["cascade_platform"] = platform
    return out


def run_tune_measurement(platform: str) -> dict:
    """Autotuner search observables (ISSUE 15); child, CPU-viable.

    Delegates to scripts/bench_tune.py:bench_tune — one real reduced
    search pass (kernel candidates compiled + timed under the numerics
    contract, ladder + seq-bucket fits) — and passes the fields
    through: they already carry the tuned_*/tune_* names the bench gate
    reads (`tuned_ggnn_step_us` / `tuned_ladder_padding_waste`
    lower-is-better, `tune_search_seconds` absolute-bounded)."""
    from deepdfa_tpu.core.backend import enable_compile_cache, force_cpu

    if platform == "cpu":
        force_cpu()
    enable_compile_cache()
    sys.path.insert(
        0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "scripts")
    )
    if "DEEPDFA_TPU_STORAGE" not in os.environ:
        import tempfile

        tmp = tempfile.TemporaryDirectory(prefix="bench-tune-")
        os.environ["DEEPDFA_TPU_STORAGE"] = tmp.name
    from bench_tune import bench_tune

    import jax

    platform = jax.devices()[0].platform
    smoke = platform == "cpu"
    rec = bench_tune(smoke=smoke)
    out = {
        k: v for k, v in rec.items()
        if k.startswith(("tuned_", "tune_"))
    }
    out["tune_platform"] = platform
    return out


def _run_child(mode: str, platform: str, timeout: float) -> tuple[dict | None, str]:
    """Run one measurement in a watchdogged subprocess; (result, error)."""
    from deepdfa_tpu.core.backend import bounded_run

    res, err = bounded_run(
        [sys.executable, os.path.abspath(__file__), mode, platform],
        timeout,
        what=f"{platform} {mode.lstrip('-')}",
    )
    if res is None:
        return None, err
    for line in res.stdout.splitlines():
        if line.startswith(_CHILD_TAG):
            return json.loads(line[len(_CHILD_TAG) :]), ""
    return None, f"{platform} {mode.lstrip('-')} emitted no result line"


def _measure_full(
    platform: str, deadline: float, errors: list[str]
) -> dict | None:
    """Inference child + (optionally) train child on one platform;
    returns the merged record or None."""
    budget = min(CHILD_TIMEOUT, deadline - time.time())
    if budget < 60:
        errors.append(f"{platform} child skipped: budget exhausted")
        return None
    result, err = _run_child("--child", platform, budget)
    if result is None:
        errors.append(err)
        return None
    if os.environ.get("DEEPDFA_BENCH_TRAIN", "1") == "1":
        # train step in its own bounded child: a wedge here can only cost
        # the train_* fields, never the inference headline
        tbudget = min(TRAIN_TIMEOUT, deadline - time.time())
        if tbudget >= 120:
            train, terr = _run_child(
                "--child-train", result.get("platform", platform), tbudget
            )
            if train is not None:
                result.update(train)
            else:
                result["train_error"] = terr
        else:
            result["train_error"] = "skipped: total budget exhausted"
    if os.environ.get("DEEPDFA_BENCH_COMBINED", "1") == "1":
        # combined text-path (bucketing) observables, own bounded child
        # for the same wedge-isolation reason as the train child
        cbudget = min(COMBINED_TIMEOUT, deadline - time.time())
        if cbudget >= 120:
            combined, cerr = _run_child(
                "--child-combined", result.get("platform", platform), cbudget
            )
            if combined is not None:
                result.update(combined)
            else:
                result["combined_error"] = cerr
        else:
            result["combined_error"] = "skipped: total budget exhausted"
    if os.environ.get("DEEPDFA_BENCH_SERVE", "1") == "1":
        # online-serving observables (ISSUE 5), own bounded child for
        # the same wedge-isolation reason as the other children
        sbudget = min(SERVE_TIMEOUT, deadline - time.time())
        if sbudget >= 90:
            serve, serr = _run_child(
                "--child-serve", result.get("platform", platform), sbudget
            )
            if serve is not None:
                result.update(serve)
            else:
                result["serve_error"] = serr
        else:
            result["serve_error"] = "skipped: total budget exhausted"
    if os.environ.get("DEEPDFA_BENCH_SCAN", "1") == "1":
        # whole-repo scan observables (ISSUE 8), own bounded child for
        # the same wedge-isolation reason as the other children
        scbudget = min(SCAN_TIMEOUT, deadline - time.time())
        if scbudget >= 90:
            scan, scerr = _run_child(
                "--child-scan", result.get("platform", platform), scbudget
            )
            if scan is not None:
                result.update(scan)
            else:
                result["scan_error"] = scerr
        else:
            result["scan_error"] = "skipped: total budget exhausted"
    if os.environ.get("DEEPDFA_BENCH_SCATTER", "1") == "1":
        # fused GGNN-step A/B (ISSUE 9), own bounded child for the same
        # wedge-isolation reason as the other children
        stbudget = min(SCATTER_TIMEOUT, deadline - time.time())
        if stbudget >= 90:
            scat, sterr = _run_child(
                "--child-scatter", result.get("platform", platform),
                stbudget,
            )
            if scat is not None:
                result.update(scat)
            else:
                result["scatter_error"] = sterr
        else:
            result["scatter_error"] = "skipped: total budget exhausted"
    if os.environ.get("DEEPDFA_BENCH_FLEET", "0") == "1":
        # fleet-under-overload observables (ISSUE 11), opt-in via
        # DEEPDFA_BENCH_FLEET (the fleet layer is default-off), own
        # bounded child for the same wedge-isolation reason
        fbudget = min(FLEET_TIMEOUT, deadline - time.time())
        if fbudget >= 90:
            flt, ferr = _run_child(
                "--child-fleet", result.get("platform", platform),
                fbudget,
            )
            if flt is not None:
                result.update(flt)
            else:
                result["fleet_error"] = ferr
        else:
            result["fleet_error"] = "skipped: total budget exhausted"
    if os.environ.get("DEEPDFA_BENCH_CASCADE", "0") == "1":
        # cascaded-inference frontier (ISSUE 12), opt-in via
        # DEEPDFA_BENCH_CASCADE (the cascade is default-off), own
        # bounded child for the same wedge-isolation reason
        cabudget = min(CASCADE_TIMEOUT, deadline - time.time())
        if cabudget >= 90:
            casc, caerr = _run_child(
                "--child-cascade", result.get("platform", platform),
                cabudget,
            )
            if casc is not None:
                result.update(casc)
            else:
                result["cascade_error"] = caerr
        else:
            result["cascade_error"] = "skipped: total budget exhausted"
    if os.environ.get("DEEPDFA_BENCH_TUNE", "0") == "1":
        # autotuner search observables (ISSUE 15), opt-in via
        # DEEPDFA_BENCH_TUNE (the tune layer is default-off), own
        # bounded child for the same wedge-isolation reason
        tbudget = min(TUNE_TIMEOUT, deadline - time.time())
        if tbudget >= 90:
            tun, tunerr = _run_child(
                "--child-tune", result.get("platform", platform),
                tbudget,
            )
            if tun is not None:
                result.update(tun)
            else:
                result["tune_error"] = tunerr
        else:
            result["tune_error"] = "skipped: total budget exhausted"
    return result


def _latest_watchdog_capture() -> dict | None:
    """Most recent committed watchdog TPU capture (BENCH_TPU_*.json),
    summarized for embedding in a CPU-fallback record.

    The round-4 failure mode this closes: the driver's own window hit a
    wedged tunnel four rounds running, so the official BENCH_r*.json
    showed a CPU number while hours-fresher TPU evidence sat in sibling
    artifacts. Embedding the newest TPU capture (with its timestamp)
    under ``last_healthy_tpu`` makes the driver artifact self-contained
    evidence either way.
    """
    import glob

    here = os.path.dirname(os.path.abspath(__file__))
    best: tuple[str, str, dict] | None = None
    for path in glob.glob(os.path.join(here, "BENCH_TPU_*.json")):
        try:
            with open(path) as f:
                rec = json.load(f)
        except (OSError, ValueError):
            continue
        # files are hand-committable: tolerate any shape that isn't the
        # expected dict-with-dict-bench (e.g. a null "bench" key) — this
        # helper runs inside emit() and must never cost the record
        if not isinstance(rec, dict) or not isinstance(rec.get("bench"), dict):
            continue
        if rec["bench"].get("platform") != "tpu":
            continue
        stamp = str(rec.get("captured_at", ""))
        if best is None or stamp > best[0]:
            best = (stamp, os.path.basename(path), rec)
    if best is None:
        return None
    stamp, name, rec = best
    out: dict = {"captured_at": stamp, "artifact": name,
                 "bench": rec.get("bench")}
    for key in ("bench_combined", "bench_combined_t5", "bench_gen",
                "bench_localize"):
        sub = rec.get(key)
        if isinstance(sub, dict):
            out[key] = {
                k: sub[k]
                for k in ("metric", "value", "unit", "vs_baseline",
                          "platform", "rows", "mfu", "attn_impl",
                          "tokens_per_sec", "padding_waste")
                if k in sub
            }
    return out


def main() -> None:
    """Emission protocol: every completed measurement prints its own full
    JSON line, best-last — the driver records the LAST line, so a CPU
    fallback that finished early is never lost if a later (longer) TPU
    attempt is cut off by an outer timeout.

    Order: healthy probe -> measure TPU directly. Failed probe -> measure
    CPU FIRST (bounded, lands a record within ~15 min), then spend the
    remaining budget on PROBE-GATED retries: short (120s) probes spread
    across the window, with the expensive measurement children launched
    only after a probe succeeds — a wedge costs one cheap probe per
    retry, never a 1500s child timeout. Any CPU-fallback record embeds
    the newest committed watchdog TPU capture (``last_healthy_tpu``).
    """
    from deepdfa_tpu.core.backend import cpu_pinned
    from deepdfa_tpu.obs import health as obs_health

    # probes route through obs/health so every attempt lands in the
    # backend/* metrics (latency, retries, wedge detection) and the
    # fallback record can embed a structured backend_health summary
    # instead of only the concatenated fallback_from string (ISSUE 6)
    probe_default_backend = obs_health.probe_backend

    deadline = time.time() + TOTAL_BUDGET
    errors: list[str] = []

    def error_record() -> dict:
        return {
            "metric": "deepdfa_infer_graphs_per_sec",
            "value": 0.0,
            "unit": "graphs/s",
            "vs_baseline": 0.0,
            "error": "; ".join(errors),
        }

    def emit(result: dict) -> None:
        # provenance stamp (ISSUE 4 satellite): schema_version + git sha
        # + jax version make BENCH_*.json comparable across PRs
        from deepdfa_tpu.obs import run_stamp

        result.update(run_stamp())
        if errors and "error" not in result:
            if result.get("platform") == "cpu" and not cpu_pinned():
                result["fallback_from"] = "; ".join(errors)
                obs_health.record_fallback(result["fallback_from"])
                # the structured twin of fallback_from: probe count,
                # latencies, wedges — what scripts/bench_gate.py and
                # the diag backend section read
                result["backend_health"] = obs_health.summary()
            else:
                result["warnings"] = "; ".join(errors)
        if result.get("platform") != "tpu" and not cpu_pinned():
            try:
                healthy = _latest_watchdog_capture()
            except Exception:  # must never cost the record itself
                healthy = None
            if healthy is not None:
                result["last_healthy_tpu"] = healthy
        print(json.dumps(result), flush=True)

    if cpu_pinned():
        result = _measure_full("cpu", deadline, errors)
        emit(result if result is not None else error_record())
        return

    # the probe never eats the CPU fallback's budget (~420s reserve)
    probe_budget = min(PROBE_TIMEOUT, deadline - 420.0 - time.time())
    default_is_cpu = False
    if probe_budget >= 30:
        ok, detail = probe_default_backend(probe_budget)
        if ok and detail != "cpu":
            result = _measure_full(detail, deadline, errors)
            if result is not None:
                emit(result)
                return
        elif ok:
            default_is_cpu = True  # no accelerator: one CPU pass suffices
        else:
            errors.append(f"probe: {detail}")
    else:
        errors.append("probe skipped: total budget too small")

    # CPU fallback FIRST so a record exists early, then PROBE-GATED
    # retries with whatever budget remains: each retry spends a cheap
    # 120s probe, and only a HEALTHY probe unlocks the expensive
    # measurement children (the r4 second-chance went straight to a
    # full child and a wedge ate 1500s of window for nothing). Probes
    # are spaced so they sample different moments of the driver window
    # — the tunnel wedge clears on its own schedule.
    cpu_result = _measure_full("cpu", deadline, errors)
    emit(dict(cpu_result) if cpu_result is not None else error_record())

    n_errors_emitted = len(errors)
    retries = 0
    while (
        not default_is_cpu
        and retries < PROBE_RETRIES
        and time.time() < deadline - 300
    ):
        retries += 1
        probe_budget = min(PROBE_TIMEOUT, deadline - 180 - time.time())
        if probe_budget < 30:
            break
        ok, detail = probe_default_backend(probe_budget)
        if ok and detail != "cpu":
            retry_errors: list[str] = []
            tpu_result = _measure_full(detail, deadline, retry_errors)
            if tpu_result is not None and tpu_result.get("platform") != "cpu":
                from deepdfa_tpu.obs import run_stamp

                tpu_result.update(run_stamp())
                tpu_result["second_chance"] = True
                if errors:
                    tpu_result["warnings"] = "; ".join(errors)
                print(json.dumps(tpu_result), flush=True)
                return
            errors.extend(retry_errors)
        elif ok:
            break  # default resolves to CPU: nothing to retry for
        else:
            errors.append(f"probe retry {retries}: {detail}")
            # space the remaining probes across the window rather than
            # burning them back-to-back against the same wedge
            remaining = deadline - 300 - time.time()
            if retries < PROBE_RETRIES and remaining > 240:
                time.sleep(max(0.0, min(180.0, remaining - PROBE_TIMEOUT)))

    if cpu_result is not None and len(errors) > n_errors_emitted:
        # the retry diagnostics arrived after the record was printed:
        # re-emit it (best-last protocol — the driver keeps the LAST
        # line) so every probe that sampled the window is on the record
        emit(dict(cpu_result))


if __name__ == "__main__":
    if len(sys.argv) >= 3 and sys.argv[1] == "--child":
        print(_CHILD_TAG + json.dumps(run_measurement(sys.argv[2])), flush=True)
    elif len(sys.argv) >= 3 and sys.argv[1] == "--child-train":
        print(
            _CHILD_TAG + json.dumps(run_train_measurement(sys.argv[2])),
            flush=True,
        )
    elif len(sys.argv) >= 3 and sys.argv[1] == "--child-combined":
        print(
            _CHILD_TAG + json.dumps(run_combined_measurement(sys.argv[2])),
            flush=True,
        )
    elif len(sys.argv) >= 3 and sys.argv[1] == "--child-serve":
        print(
            _CHILD_TAG + json.dumps(run_serve_measurement(sys.argv[2])),
            flush=True,
        )
    elif len(sys.argv) >= 3 and sys.argv[1] == "--child-scan":
        print(
            _CHILD_TAG + json.dumps(run_scan_measurement(sys.argv[2])),
            flush=True,
        )
    elif len(sys.argv) >= 3 and sys.argv[1] == "--child-scatter":
        print(
            _CHILD_TAG + json.dumps(run_scatter_measurement(sys.argv[2])),
            flush=True,
        )
    elif len(sys.argv) >= 3 and sys.argv[1] == "--child-fleet":
        print(
            _CHILD_TAG + json.dumps(run_fleet_measurement(sys.argv[2])),
            flush=True,
        )
    elif len(sys.argv) >= 3 and sys.argv[1] == "--child-cascade":
        print(
            _CHILD_TAG + json.dumps(run_cascade_measurement(sys.argv[2])),
            flush=True,
        )
    elif len(sys.argv) >= 3 and sys.argv[1] == "--child-tune":
        print(
            _CHILD_TAG + json.dumps(run_tune_measurement(sys.argv[2])),
            flush=True,
        )
    else:
        main()
