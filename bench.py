"""Headline benchmark: DeepDFA inference throughput on one TPU chip.

Prints ONE json line:
  {"metric": "deepdfa_infer_graphs_per_sec", "value": N, "unit": "graphs/s",
   "vs_baseline": R}

Baseline: the reference's single-RTX-3090 DeepDFA inference latency of
4.6 ms/example (paper Table 5, BASELINE.md "Efficiency") = 217.4 graphs/s.
The workload is the flagship configuration (input_dim 1002, hidden 32,
n_steps 5, concat_all_absdf) over realistic CFGs produced by the full
frontend pipeline, batch-packed exactly as in training/eval.
"""

from __future__ import annotations

import json
import time

import numpy as np

BASELINE_GRAPHS_PER_SEC = 1000.0 / 4.6  # reference: 4.6 ms/example on RTX 3090


def main() -> None:
    import jax

    from deepdfa_tpu.core import Config
    from deepdfa_tpu.data import build_dataset, generate, to_examples
    from deepdfa_tpu.graphs import bucket_batches
    from deepdfa_tpu.models import DeepDFA

    n_examples = 512
    synth = generate(n_examples, vuln_rate=0.25, seed=7)
    specs, _ = build_dataset(
        to_examples(synth), train_ids=range(n_examples), limit_all=1000,
        limit_subkeys=1000,
    )
    # one static batch signature, test-batch-size-style packing
    num_graphs, node_budget, edge_budget = 256, 8192, 32768
    batches = list(
        bucket_batches(specs, num_graphs, node_budget, edge_budget)
    )

    cfg = Config()
    model = DeepDFA.from_config(cfg.model, input_dim=1002)
    params = model.init(jax.random.key(0), batches[0])

    @jax.jit
    def forward(params, batch):
        return jax.nn.sigmoid(model.apply(params, batch))

    # warmup / compile
    jax.block_until_ready(forward(params, batches[0]))

    # steady-state: loop the batch stream several times
    reps = 8
    n_graphs_done = 0
    t0 = time.perf_counter()
    out = None
    for _ in range(reps):
        for b in batches:
            out = forward(params, b)
            n_graphs_done += int(np.asarray(b.graph_mask).sum())
    jax.block_until_ready(out)
    dt = time.perf_counter() - t0

    value = n_graphs_done / dt
    print(
        json.dumps(
            {
                "metric": "deepdfa_infer_graphs_per_sec",
                "value": round(value, 1),
                "unit": "graphs/s",
                "vs_baseline": round(value / BASELINE_GRAPHS_PER_SEC, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
