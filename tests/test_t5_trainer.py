"""CombinedTrainer over the T5 defect model: dp x tp parity + learning."""

import numpy as np
import pytest

from deepdfa_tpu.core import Config, MeshConfig, config as config_mod
from deepdfa_tpu.data import build_dataset, generate, to_examples
from deepdfa_tpu.data.text import collate_shards
from deepdfa_tpu.data.tokenizer import HashTokenizer
from deepdfa_tpu.models import t5 as t5m
from deepdfa_tpu.parallel import make_mesh
from deepdfa_tpu.train.combined_loop import CombinedTrainer

# heavy compiles / subprocesses: excluded from the default fast lane
# (pyproject addopts); run via `pytest -m slow` or `pytest -m ""`
pytestmark = pytest.mark.slow


def _setup(n=16):
    synth = generate(n, vuln_rate=0.4, seed=13)
    specs, _ = build_dataset(to_examples(synth), train_ids=range(n), limit_all=50, limit_subkeys=50)
    by_id = {s.graph_id: s for s in specs}
    tok = HashTokenizer(vocab_size=256, t5_frame=True)
    token_ids = tok.batch_encode([s.before for s in synth], max_length=32)
    labels = [s.label for s in synth]
    mcfg = t5m.DefectConfig(
        encoder=t5m.T5Config.tiny(dropout_rate=0.0, remat=False),
        graph_hidden_dim=8,
        graph_input_dim=52,
    )
    cfg = config_mod.apply_overrides(
        Config(), ["train.optim.name=sgd", "train.optim.learning_rate=0.05"]
    )
    return token_ids, labels, by_id, mcfg, cfg, n


def test_t5_dp_tp_matches_single():
    import jax

    token_ids, labels, by_id, mcfg, cfg, n = _setup()
    mesh_p = make_mesh(MeshConfig(dp=2, tp=2, sp=1), devices=jax.devices()[:4])
    mesh_1 = make_mesh(MeshConfig(dp=1), devices=jax.devices()[:1])
    tp_tr = CombinedTrainer(cfg, mcfg, mesh=mesh_p)
    s_tr = CombinedTrainer(cfg, mcfg, mesh=mesh_1)
    bp = collate_shards(token_ids, labels, list(range(n)), by_id, 2, 8, 1024, 4096, pad_id=0)
    b1 = collate_shards(token_ids, labels, list(range(n)), by_id, 1, 16, 1024, 4096, pad_id=0)
    sp = tp_tr.init_state(seed=0)
    s1 = s_tr.init_state(seed=0)
    key = jax.random.key(7)
    for _ in range(2):
        sp, loss_p = tp_tr.train_step(sp, bp, key)
        s1, loss_1 = s_tr.train_step(s1, b1, key)
    np.testing.assert_allclose(
        float(jax.device_get(loss_p)), float(jax.device_get(loss_1)), rtol=5e-4
    )
    chex = pytest.importorskip("chex")
    chex.assert_trees_all_close(
        jax.device_get(sp.params), jax.device_get(s1.params), rtol=2e-3, atol=1e-5
    )
    mp, _ = tp_tr.evaluate(sp, [bp])
    m1, _ = s_tr.evaluate(s1, [b1])
    np.testing.assert_allclose(mp["loss"], m1["loss"], rtol=1e-3)


def test_t5_sp_constructs():
    """T5 + sequence parallelism is supported (ring attention with
    per-shard relative-bias blocks); construction must not raise.
    Numerical parity is covered by test_combined_parallel.py."""
    token_ids, labels, by_id, mcfg, cfg, n = _setup()
    mesh = make_mesh(MeshConfig(dp=1, tp=1, sp=8))
    trainer = CombinedTrainer(cfg, mcfg, mesh=mesh)
    assert trainer.sp
