"""Statement-level (node-labeled) GGNN training end-to-end.

The reference's LineVD-style configuration (label_style='node',
base_module get_label) trains per-statement vulnerability classifiers; the
node probabilities then feed the statement-level localization metrics.
"""

import numpy as np

from deepdfa_tpu.core import Config, MeshConfig, config as config_mod
from deepdfa_tpu.data import build_dataset, generate, to_examples
from deepdfa_tpu.eval.statements import RankedExample, statement_report
from deepdfa_tpu.graphs import pack_shards
from deepdfa_tpu.models import DeepDFA
from deepdfa_tpu.parallel import make_mesh
from deepdfa_tpu.train import GraphTrainer
import pytest

# heavy compiles / subprocesses: excluded from the default fast lane
# (pyproject addopts); run via `pytest -m slow` or `pytest -m ""`
pytestmark = pytest.mark.slow


def test_node_level_training_and_localization():
    import jax

    n = 200
    synth = generate(n, vuln_rate=0.3, seed=21)
    specs, _ = build_dataset(
        to_examples(synth), train_ids=range(n), limit_all=150, limit_subkeys=150
    )
    # node labels exist on positives
    assert any(s.node_vuln.sum() > 0 for s in specs)

    cfg = config_mod.apply_overrides(
        Config(),
        [
            "model.hidden_dim=8",
            "model.label_style=\"node\"",
            "train.max_epochs=80",
            "train.optim.learning_rate=0.005",
            # node-level positives are rare: weight them up instead of
            # graph-level undersampling (reference node resampling's role)
            "train.pos_weight=20.0",
        ],
    )
    mesh = make_mesh(MeshConfig(dp=8))
    model = DeepDFA.from_config(cfg.model, input_dim=152)
    assert model.label_style == "node"
    trainer = GraphTrainer(model, cfg, mesh=mesh)
    assert trainer.pos_weight == 20.0

    batch = pack_shards(specs, 8, 25, 4096, 16384)
    state = trainer.init_state(batch)
    state = trainer.fit(state, lambda e: [batch])
    metrics, _ = trainer.evaluate(state, [batch])
    # per-statement signal is learnable on the synthetic bug patterns
    assert metrics["recall"] > 0.6, metrics
    assert metrics["f1"] > 0.35, metrics  # statement-level F1 runs far below function-level (paper Table 6)

    # node probabilities -> statement localization metrics
    probs, labels, mask, _ = jax.device_get(trainer.eval_step(state.params, batch))
    probs, labels, mask = (np.asarray(x) for x in (probs, labels, mask))
    node_graph = np.asarray(batch.node_graph)
    ranked = []
    for shard in range(probs.shape[0]):
        for g in range(batch.num_graphs):
            sel = (node_graph[shard] == g) & mask[shard].astype(bool)
            if sel.sum() and labels[shard][sel].sum() > 0:
                ranked.append(
                    RankedExample(probs[shard][sel], labels[shard][sel] >= 0.5)
                )
    rep = statement_report(ranked)
    assert rep["top_10_acc"] > 0.8, rep


def test_feat_unknown_dropout_masks_and_trains():
    """drop_known_feats maps known buckets (>=2) to UNKNOWN (1) per
    dropped node, keeps 0s, and the trainer runs with it enabled."""
    import jax
    import numpy as np

    from deepdfa_tpu.train.loop import drop_known_feats

    feats = np.array(
        [[0, 2, 3, 0], [0, 0, 0, 0], [1, 5, 2, 2], [0, 4, 0, 0]], np.int32
    )
    out = np.asarray(drop_known_feats(feats, jax.random.key(0), 1.0))
    np.testing.assert_array_equal(
        out, [[0, 1, 1, 0], [0, 0, 0, 0], [1, 1, 1, 1], [0, 1, 0, 0]]
    )
    out0 = np.asarray(drop_known_feats(feats, jax.random.key(0), 0.0))
    np.testing.assert_array_equal(out0, feats)

    from deepdfa_tpu.core import Config, MeshConfig, config as config_mod
    from deepdfa_tpu.graphs import shard_bucket_batches
    from deepdfa_tpu.models import DeepDFA
    from deepdfa_tpu.parallel import make_mesh
    from deepdfa_tpu.train import GraphTrainer

    from tests.test_train import synthetic_dataset

    graphs = synthetic_dataset(np.random.default_rng(5), n_graphs=8)
    batch = next(
        iter(shard_bucket_batches(graphs, 1, 8, 256, 512, oversized="raise"))
    )
    cfg = config_mod.apply_overrides(
        Config(),
        ["model.hidden_dim=8", "train.feat_unknown_dropout=0.5"],
    )
    model = DeepDFA.from_config(cfg.model, input_dim=24, hidden_dim=8)
    mesh = make_mesh(MeshConfig(dp=1), devices=jax.devices()[:1])
    trainer = GraphTrainer(model, cfg, mesh=mesh)
    state = trainer.init_state(batch, seed=0)
    state, loss = trainer.train_step(state, batch)
    assert np.isfinite(float(jax.device_get(loss)))
    # deterministic per step: same state/batch give the same loss
    _, loss2 = trainer.train_step(
        trainer.init_state(batch, seed=0), batch
    )
    assert float(jax.device_get(loss)) == float(jax.device_get(loss2))
