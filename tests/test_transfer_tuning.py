"""Encoder transfer/freezing, hyperparameter search, run logging."""

import json

import numpy as np
import pytest

from deepdfa_tpu.core import Config, MeshConfig, config as config_mod
from deepdfa_tpu.train.tuning import SearchSpace, Tuner, grid_search, random_search

# heavy compiles / subprocesses: excluded from the default fast lane
# (pyproject addopts); run via `pytest -m slow` or `pytest -m ""`
pytestmark = pytest.mark.slow


def test_search_space_and_grid():
    space = SearchSpace(choices={"model.hidden_dim": [8, 16]})
    trials = list(grid_search(space))
    assert trials == [["model.hidden_dim=8"], ["model.hidden_dim=16"]]
    space2 = SearchSpace(
        choices={"a": [1]}, ranges={"lr": (1e-5, 1e-2, True)}
    )
    samples = list(random_search(space2, 5, seed=0))
    assert len(samples) == 5
    for s in samples:
        lr = float(s[1].split("=")[1])
        assert 1e-5 <= lr <= 1e-2
    # deterministic per seed
    assert samples == list(random_search(space2, 5, seed=0))


def test_tuner_ledger_and_best(tmp_path):
    tuner = Tuner(tmp_path / "ledger.jsonl", monitor="val_f1")

    def train_fn(overrides, report):
        report({"epoch": 0, "loss": 1.0})
        h = float(overrides[0].split("=")[1])
        return {"val_f1": h / 100.0}

    best = tuner.run(grid_search(SearchSpace(choices={"h": [10, 50, 30]})), train_fn)
    assert best["metric"] == 0.5
    assert best["overrides"] == ["h=50"]
    lines = [json.loads(l) for l in (tmp_path / "ledger.jsonl").read_text().splitlines()]
    assert len(lines) == 3
    assert lines[1]["is_best"]


def test_graph_encoder_transfer_and_freeze():
    import jax

    from deepdfa_tpu.graphs import GraphSpec, pack
    from deepdfa_tpu.models import DeepDFA, combined as cmb
    from deepdfa_tpu.models.transformer import TransformerConfig
    from deepdfa_tpu.train.transfer import (
        frozen_optimizer,
        graph_encoder_subset,
        load_graph_encoder,
    )
    import optax

    rng = np.random.default_rng(0)
    # a "trained" standalone DeepDFA
    model = DeepDFA(input_dim=52, hidden_dim=8)
    g = GraphSpec(
        0,
        rng.integers(0, 52, (5, 4)).astype(np.int32),
        np.zeros((5,), np.int32),
        np.array([0, 1], np.int32),
        np.array([1, 2], np.int32),
        1.0,
    )
    batch = pack([g], 2, 16, 64)
    dd_params = model.init(jax.random.key(0), batch)

    sub = graph_encoder_subset(dd_params)
    assert set(sub["params"]) == {"embedding", "ggnn", "pooling"}

    mcfg = cmb.CombinedConfig(
        encoder=TransformerConfig.tiny(vocab_size=64),
        graph_hidden_dim=8,
        graph_input_dim=52,
    )
    params = cmb.init_params(mcfg, jax.random.key(1))
    loaded = load_graph_encoder(params, dd_params)
    chex = pytest.importorskip("chex")
    chex.assert_trees_all_close(
        loaded["graph"]["params"]["ggnn"], dd_params["params"]["ggnn"]
    )

    # frozen optimizer: graph subtree gets zero updates — both the
    # params-now form and the callable-mask (params-at-init-time) form
    for tx in (
        frozen_optimizer(optax.sgd(0.1), loaded, frozen_top_keys=("graph",)),
        frozen_optimizer(optax.sgd(0.1), frozen_top_keys=("graph",)),
    ):
        opt_state = tx.init(loaded)
        grads = jax.tree.map(lambda x: jax.numpy.ones_like(x), loaded)
        updates, _ = tx.update(grads, opt_state, loaded)
        graph_updates = jax.tree.leaves(updates["graph"])
        assert all(float(jax.numpy.abs(u).max()) == 0.0 for u in graph_updates)
        head_updates = jax.tree.leaves(updates["head"])
        assert any(float(jax.numpy.abs(u).max()) > 0.0 for u in head_updates)


def test_run_logger(tmp_path):
    from deepdfa_tpu.train.logging import RunLogger

    with RunLogger(tmp_path / "run", tensorboard=True) as lg:
        lg.log({"epoch": 0, "train_loss": 1.5, "note": "x"})
        lg.log({"epoch": 1, "train_loss": 1.0})
    lines = (tmp_path / "run" / "train_log.jsonl").read_text().splitlines()
    assert len(lines) == 2
    if lg.has_tensorboard:
        assert list((tmp_path / "run" / "tb").glob("events*"))


def test_cross_project_splits(tmp_path):
    import pandas as pd

    from deepdfa_tpu.data.readers import cross_project_splits

    df = pd.DataFrame(
        {"project": ["chrome"] * 10 + ["linux"] * 10 + ["ffmpeg"] * 10}
    )
    p = tmp_path / "msr.csv"
    df.to_csv(p, index=True)
    splits = cross_project_splits(p, test_projects=["linux"])
    assert all(splits[i] == "test" for i in range(10, 20))
    assert all(splits[i] in ("train", "val") for i in range(10))
    # project-disjointness: no train/val ids share a project with test
    splits2 = cross_project_splits(p, holdout_frac=0.34, seed=1)
    test_ids = {i for i, s in splits2.items() if s == "test"}
    test_projects = {df.iloc[i]["project"] for i in test_ids}
    other_projects = {
        df.iloc[i]["project"] for i, s in splits2.items() if s != "test"
    }
    assert not (test_projects & other_projects)
