"""Fleet HA / rollout / chaos unit tests (deepdfa_tpu/fleet/{ha,
rollout,chaos}.py, docs/fleet.md) — the router-failover, admission
re-seed, quarantine, rollout-controller, and bounded-join halves
against stub HTTP endpoints: no model, no subprocess. The real-process
drills live in scripts/fault_inject.py --fleet (and the tier-1
in-process variants in `--smoke --fleet`, tests/test_fault_inject.py).
"""

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from deepdfa_tpu.core import Config, config as config_mod
from deepdfa_tpu.fleet import (
    admission as fleet_admission,
    chaos as fleet_chaos,
    ha as fleet_ha,
    heartbeat,
)
from deepdfa_tpu.fleet.router import (
    FleetLog,
    Router,
    validate_fleet_log,
)
from deepdfa_tpu.obs import metrics as obs_metrics


def ha_cfg(**extra):
    overrides = [
        "fleet.port=0",  # never fight other processes for 8470
        "fleet.rendezvous_interval_s=0.1",
        "fleet.router_failover_timeout_s=0.5",
        "fleet.summary_interval_s=0.2",
        "fleet.poll_interval_s=0.0",
        "fleet.heartbeat_timeout_s=5.0",
    ] + [f"{k}={v}" for k, v in extra.items()]
    return config_mod.apply_overrides(Config(), overrides)


def counter(name: str) -> float:
    return obs_metrics.REGISTRY.snapshot().get(name, 0.0)


# ---------------------------------------------------------------------------
# rendezvous file protocol


def test_rendezvous_round_trip_and_resolve(tmp_path):
    assert fleet_ha.read_rendezvous(tmp_path) is None
    assert fleet_ha.resolve_router(tmp_path) is None
    fleet_ha.write_rendezvous(tmp_path, "ra", "127.0.0.1", 8123, 3)
    rv = fleet_ha.read_rendezvous(tmp_path)
    assert rv["router_id"] == "ra"
    assert rv["epoch"] == 3
    assert fleet_ha.resolve_router(tmp_path) == ("127.0.0.1", 8123)


def test_rendezvous_malformed_reads_as_absent(tmp_path):
    path = fleet_ha.rendezvous_path(tmp_path)
    path.parent.mkdir(parents=True, exist_ok=True)
    for damage in (
        "not json",
        json.dumps({"router": "nope"}),
        json.dumps({"router": {"router_id": "ra"}}),  # missing fields
        json.dumps({"something": "else"}),
    ):
        path.write_text(damage)
        assert fleet_ha.read_rendezvous(tmp_path) is None


# ---------------------------------------------------------------------------
# active/standby negotiation (fleet/ha.py)


def test_ha_lone_starter_becomes_active_and_serves(tmp_path):
    cfg = ha_cfg()
    a = fleet_ha.HARouter(
        cfg, tmp_path, "ra", log_path=tmp_path / "fleet_log.jsonl"
    )
    try:
        a.start()
        assert a.wait_active(10.0)
        assert a.role == "active"
        rv = fleet_ha.read_rendezvous(tmp_path)
        assert rv["router_id"] == "ra"
        assert int(rv["port"]) == a.port
        # the front door answers (no replicas: healthz still 200s)
        status, body = fleet_chaos.http_json(
            a.host, a.port, "GET", "/healthz", timeout=5.0
        )
        assert status == 200, body
    finally:
        a.close()


def test_ha_standby_takes_over_stale_rendezvous_and_fences_loser(
    tmp_path,
):
    cfg = ha_cfg()
    log_path = tmp_path / "fleet_log.jsonl"
    a = fleet_ha.HARouter(cfg, tmp_path, "ra", log_path=log_path)
    b = fleet_ha.HARouter(cfg, tmp_path, "rb", log_path=log_path)
    try:
        a.start()
        assert a.wait_active(10.0)
        epoch_a = a.epoch
        b.step()
        assert b.role == "standby"
        # the active dies abruptly: loops dead, server down, rendezvous
        # left behind exactly as SIGKILL leaves it
        a.kill()
        deadline = time.time() + 30
        while time.time() < deadline and b.role != "active":
            b.step()
            time.sleep(0.1)
        assert b.role == "active"
        assert b.epoch > epoch_a
        rv = fleet_ha.read_rendezvous(tmp_path)
        assert rv["router_id"] == "rb"
        # fencing: the presumed-dead active observes the higher epoch
        # and steps down instead of fighting. A WEDGED (not killed)
        # active that resumes still holds its log handle — kill()
        # dropped ours (a real SIGKILL writes nothing more), so
        # re-attach one to pin the stepdown event write path too.
        a.router.log = FleetLog(log_path)
        with a._lock:
            a.role = "active"  # simulate it waking back up
        a.step()
        assert a.role == "standby"
        assert a.router.log is None  # step_down detached it again
        events = [
            json.loads(line)["fleet_event"]["name"]
            for line in log_path.read_text().splitlines()
            if "fleet_event" in line
        ]
        assert "takeover" in events
        assert "stepdown" in events
        verdict = validate_fleet_log(log_path)
        assert verdict["ok"], verdict["problems"]
    finally:
        a.kill()
        b.close()


def test_ha_standby_does_not_take_over_fresh_rendezvous(tmp_path):
    cfg = ha_cfg()
    a = fleet_ha.HARouter(cfg, tmp_path, "ra")
    b = fleet_ha.HARouter(cfg, tmp_path, "rb")
    try:
        a.start()
        assert a.wait_active(10.0)
        for _ in range(5):
            b.step()
            time.sleep(0.05)
        assert b.role == "standby"
        assert fleet_ha.read_rendezvous(tmp_path)["router_id"] == "ra"
    finally:
        a.close()
        b.close()


# ---------------------------------------------------------------------------
# admission token-bucket re-seed (the router-restart half of HA)


def drained_controller():
    ctrl = fleet_admission.AdmissionController(
        tenants=fleet_admission.parse_tenants(
            json.dumps({"t0": {"rate": 0.001, "burst": 40.0}})
        ),
    )
    for _ in range(25):
        ctrl.decide("t0", outstanding=0, healthy=1)
    return ctrl


def test_admission_snapshot_reseed_round_trip():
    ctrl = drained_controller()
    snap = ctrl.snapshot()
    level = snap["tokens"]["t0"]
    assert level <= 15.5  # 40 - 25 admitted (+epsilon refill)
    fresh = fleet_admission.AdmissionController(
        tenants=fleet_admission.parse_tenants(
            json.dumps({"t0": {"rate": 0.001, "burst": 40.0}})
        ),
    )
    n = fresh.reseed(snap)
    assert n >= 1
    assert fresh.snapshot()["tokens"]["t0"] == pytest.approx(
        level, abs=0.5
    )


def test_admission_reseed_clamps_to_burst_and_tolerates_garbage():
    ctrl = fleet_admission.AdmissionController(
        tenants=fleet_admission.parse_tenants(
            json.dumps({"t0": {"rate": 0.001, "burst": 40.0}})
        ),
    )
    # a stale record can never grant MORE than the policy's burst
    n = ctrl.reseed({"tokens": {"t0": 9999.0, "junk": "NaNish"}})
    assert n == 1
    assert ctrl.snapshot()["tokens"]["t0"] <= 40.0
    # malformed snapshots re-seed nothing, never crash
    assert ctrl.reseed("not a dict") == 0
    assert ctrl.reseed({"tokens": "nope"}) == 0
    assert ctrl.reseed({}) == 0
    # the service EWMA restores too
    ctrl.reseed({"service_ewma_ms": 123.0})
    assert ctrl.snapshot()["service_ewma_ms"] == pytest.approx(
        123.0, rel=0.01
    )


def test_router_reseed_from_log_last_summary(tmp_path):
    log_path = tmp_path / "fleet_log.jsonl"
    ctrl = drained_controller()
    router = Router(
        tmp_path, poll_interval_s=0.0, admission=ctrl,
        log=FleetLog(log_path),
    )
    level = ctrl.snapshot()["tokens"]["t0"]
    router.log.append(router.summary_record())
    router.close()
    restarted = Router(
        tmp_path, poll_interval_s=0.0,
        admission=fleet_admission.AdmissionController(
            tenants=fleet_admission.parse_tenants(
                json.dumps({"t0": {"rate": 0.001, "burst": 40.0}})
            ),
        ),
    )
    try:
        n = restarted.reseed_from_log(log_path)
        assert n >= 1
        assert restarted.admission.snapshot()["tokens"]["t0"] == (
            pytest.approx(level, abs=0.5)
        )
    finally:
        restarted.close()


def test_router_kill_writes_no_final_summary(tmp_path):
    """A 'SIGKILLed' in-process router (HARouter/Router.kill, the
    kill-router drill) must write NOTHING more to the shared fleet_log:
    no final summary record whose frozen admission snapshot a later
    takeover would wrongly re-seed from. Graceful close() still does."""
    log_path = tmp_path / "fleet_log.jsonl"
    router = Router(
        tmp_path, poll_interval_s=0.0, admission=drained_controller(),
        log=FleetLog(log_path),
    )
    router.kill()
    assert not log_path.exists() or log_path.read_text() == ""
    router.close()  # idempotent after kill: still no summary
    assert not log_path.exists() or log_path.read_text() == ""

    graceful = Router(
        tmp_path, poll_interval_s=0.0, admission=drained_controller(),
        log=FleetLog(log_path),
    )
    graceful.close()
    summaries = [
        json.loads(line) for line in log_path.read_text().splitlines()
        if line.strip()
    ]
    assert any("fleet_admission" in rec for rec in summaries)


def test_router_reseed_from_missing_empty_corrupt_log(tmp_path):
    router = Router(tmp_path, poll_interval_s=0.0)
    try:
        # absent
        assert router.reseed_from_log(tmp_path / "nope.jsonl") == 0
        # empty
        empty = tmp_path / "empty.jsonl"
        empty.write_text("")
        assert router.reseed_from_log(empty) == 0
        # corrupt lines + a summary record with a malformed snapshot:
        # fresh buckets, no crash
        corrupt = tmp_path / "corrupt.jsonl"
        corrupt.write_text(
            "{torn json\n"
            + json.dumps({"fleet_admission": "not a dict",
                          "fleet": {}}) + "\n"
            + "also not json\n"
        )
        assert router.reseed_from_log(corrupt) == 0
    finally:
        router.close()


# ---------------------------------------------------------------------------
# ledger-driven replica planning (ROADMAP item 2 remainder)


def test_plan_replicas_unbudgeted_falls_back_to_default():
    n, plan = fleet_admission.plan_replicas({"default": 1e6}, 0.0)
    assert n == 2
    assert plan["reason"] == "unbudgeted"


def test_plan_replicas_ledger_math_and_clamps():
    # 1 MB params * 4x headroom = 4 MB working set; 10 MB budget -> 2
    n, plan = fleet_admission.plan_replicas({"default": 1e6}, 10e6)
    assert n == 2
    assert plan["reason"] == "ledger"
    assert plan["per_replica_bytes"] == pytest.approx(4e6)
    # a huge budget clamps at max_replicas
    n, _ = fleet_admission.plan_replicas(
        {"default": 1e6}, 1e12, max_replicas=16
    )
    assert n == 16
    # a budget below one working set still runs one replica
    n, _ = fleet_admission.plan_replicas({"default": 1e6}, 1e6)
    assert n == 1
    # unmeasurable entries (0 bytes) fall back to the default
    n, plan = fleet_admission.plan_replicas({"default": 0.0}, 10e6)
    assert n == 2
    assert plan["reason"] == "unmeasured"


def test_plan_replicas_arbitrates_entries_against_budget():
    # two entries, budget fits only the first's working set after
    # plan_coserving refuses the second
    entries = {"default": 1e6, "huge": 1e9}
    n, plan = fleet_admission.plan_replicas(entries, 8e6)
    assert plan["loaded"] == ["default"]
    assert "huge" in plan["refused"]
    assert n == 2  # 8 MB // 4 MB


# ---------------------------------------------------------------------------
# heartbeat validation + router quarantine


def test_validate_heartbeat_reasons():
    ok = {
        "heartbeat": {
            "replica_id": "r0", "host": "h", "port": 8000,
            "state": "ready", "t_unix": 1.0,
        },
    }
    hb, reason = heartbeat.validate_heartbeat(ok)
    assert hb is not None and reason is None
    cases = [
        ("nope", "not a JSON object"),
        ({}, "no heartbeat object"),
        ({"heartbeat": {"replica_id": "r0"}}, "missing fields"),
        ({"heartbeat": dict(ok["heartbeat"], state="zombie")},
         "unknown state"),
        ({"heartbeat": dict(ok["heartbeat"], port="eighty")},
         "not numeric"),
        ({"heartbeat": dict(ok["heartbeat"], port=0)}, "out of range"),
    ]
    for doc, expect in cases:
        hb, reason = heartbeat.validate_heartbeat(doc)
        assert hb is None
        assert expect in reason, (reason, expect)


def test_scan_heartbeats_verbose_reports_invalid_by_filename(tmp_path):
    heartbeat.write_heartbeat(tmp_path, "good", "127.0.0.1", 8000)
    (tmp_path / "replica-torn.json").write_text('{"heartbeat": {')
    beats, invalid = heartbeat.scan_heartbeats_verbose(tmp_path)
    assert set(beats) == {"good"}
    assert set(invalid) == {"torn"}
    assert "not JSON" in invalid["torn"]


def test_router_quarantines_corrupt_heartbeat_and_heals(tmp_path):
    log_path = tmp_path / "fleet_log.jsonl"
    heartbeat.write_heartbeat(tmp_path, "r0", "127.0.0.1", 18000)
    heartbeat.write_heartbeat(tmp_path, "r1", "127.0.0.1", 18001)
    router = Router(
        tmp_path, poll_interval_s=0.0, log=FleetLog(log_path),
    )
    try:
        q0 = counter("fleet/quarantines")
        assert {
            r["id"] for r in router.topology()["replicas"]
            if r["routable"]
        } == {"r0", "r1"}
        # damage r0's announcement
        heartbeat.heartbeat_path(tmp_path, "r0").write_text(
            '{"heartbeat": {"state": "zombie"'
        )
        router.poll(force=True)
        router.poll(force=True)  # second poll must not re-log
        assert counter("fleet/quarantines") == q0 + 1
        topo = {
            r["id"]: r for r in router.topology()["replicas"]
        }
        assert topo["r0"]["quarantined"] and not topo["r0"]["routable"]
        assert topo["r1"]["routable"]
        # the replica's own refresh heals the file; quarantine lifts
        heartbeat.write_heartbeat(tmp_path, "r0", "127.0.0.1", 18000)
        router.poll(force=True)
        topo = {
            r["id"]: r for r in router.topology()["replicas"]
        }
        assert not topo["r0"]["quarantined"] and topo["r0"]["routable"]
        events = [
            json.loads(line)["fleet_event"]["name"]
            for line in log_path.read_text().splitlines()
            if "fleet_event" in line
        ]
        assert events.count("quarantine") == 1
    finally:
        router.close()


# ---------------------------------------------------------------------------
# fleet-log validation: the new record shapes


def test_validate_fleet_log_accepts_ha_and_rollout_records(tmp_path):
    path = tmp_path / "fleet_log.jsonl"
    path.write_text("\n".join([
        json.dumps({"fleet_event": {
            "name": "takeover", "t_unix": 1.0, "router": "ra",
            "epoch": 2, "reseeded_buckets": 1,
            "takeover_seconds": 0.01,
        }}),
        json.dumps({"fleet_event": {
            "name": "stepdown", "t_unix": 1.0, "router": "rb",
            "epoch": 1,
        }}),
        json.dumps({"fleet_event": {
            "name": "quarantine", "t_unix": 1.0, "replica": "r0",
        }}),
        json.dumps({"rollout": {
            "event": "start", "checkpoint": "epoch-0001",
            "t_unix": 1.0, "replicas": 2, "drift_bound": 0.05,
        }}),
        json.dumps({"rollout": {
            "event": "swap", "checkpoint": "epoch-0001",
            "t_unix": 1.0, "replica": "r0", "drift": 0.001,
        }}),
        json.dumps({"rollout": {
            "event": "halt", "checkpoint": "bad", "t_unix": 1.0,
        }}),
    ]) + "\n")
    result = validate_fleet_log(path)
    assert result["ok"], result["problems"]
    assert result["events"] == 3
    assert result["rollouts"] == 3


def test_validate_fleet_log_rejects_bad_rollout_records(tmp_path):
    path = tmp_path / "fleet_log.jsonl"
    path.write_text("\n".join([
        json.dumps({"rollout": {"event": "explode", "t_unix": 1.0,
                                "checkpoint": "x"}}),
        json.dumps({"rollout": {"event": "swap"}}),  # missing fields
    ]) + "\n")
    result = validate_fleet_log(path)
    assert not result["ok"]
    joined = "\n".join(result["problems"])
    assert "explode" in joined
    assert "missing" in joined


# ---------------------------------------------------------------------------
# rollout controller against stub replicas (fleet/rollout.py)


class _RolloutStubHandler(BaseHTTPRequestHandler):
    """Stub replica admin surface: scripted /admin/rollout answers,
    /healthz reports a zero-recompile census."""

    replica_id = "stub"
    swap_status = 200
    calls: list  # class-level: (replica_id, payload) in arrival order

    def log_message(self, fmt, *args):
        pass

    def _reply(self, status, doc):
        body = json.dumps(doc).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802
        self._reply(200, {
            "ok": True, "steady_state_recompiles": 0,
            "checkpoint": "epoch-0000",
        })

    def do_POST(self):  # noqa: N802
        n = int(self.headers.get("Content-Length", 0))
        payload = json.loads(self.rfile.read(n) or b"{}")
        type(self).calls.append((self.replica_id, payload))
        if payload.get("rollback"):
            self._reply(200, {"ok": True, "checkpoint": "epoch-0000"})
            return
        if self.swap_status == 200:
            self._reply(200, {
                "ok": True, "checkpoint": payload.get("checkpoint"),
                "drift": 0.001, "checkpoint_step": 7, "recompiles": 0,
                "steady_state_recompiles": 0,
            })
        else:
            self._reply(self.swap_status, {
                "ok": False, "refused": True,
                "error": "calibration score drift 0.9 exceeds bound",
            })


def _stub_rollout_fleet(tmp_path, swap_statuses):
    """N stub replicas with scripted swap answers + their heartbeats."""
    calls: list = []
    servers = []
    for i, status in enumerate(swap_statuses):
        handler = type(
            f"RolloutStub{i}", (_RolloutStubHandler,),
            {"replica_id": f"r{i}", "swap_status": status,
             "calls": calls},
        )
        httpd = ThreadingHTTPServer(("127.0.0.1", 0), handler)
        thread = threading.Thread(
            target=httpd.serve_forever, daemon=True
        )
        thread.start()
        servers.append((httpd, thread))
        heartbeat.write_heartbeat(
            tmp_path, f"r{i}", "127.0.0.1", httpd.server_address[1]
        )
    return calls, servers


def _stop_stub_fleet(servers):
    for httpd, thread in servers:
        httpd.shutdown()
        httpd.server_close()
        thread.join(timeout=5)


def test_rollout_controller_swaps_every_replica(tmp_path):
    from deepdfa_tpu.fleet import rollout as fleet_rollout

    cfg = ha_cfg(**{"fleet.rollout_settle_s": 0.0})
    calls, servers = _stub_rollout_fleet(tmp_path, [200, 200])
    try:
        report = fleet_rollout.run_rollout(
            cfg, tmp_path, "epoch-0001",
            log_path=tmp_path / "fleet_log.jsonl",
        )
    finally:
        _stop_stub_fleet(servers)
    assert report["ok"], report
    assert sorted(report["swapped"]) == ["r0", "r1"]
    assert not report["halted"]
    assert report["census_ok"]
    # one swap POST per replica, in replica-id order
    assert [c[0] for c in calls] == ["r0", "r1"]
    verdict = validate_fleet_log(tmp_path / "fleet_log.jsonl")
    assert verdict["ok"], verdict["problems"]
    assert verdict["rollouts"] >= 3  # start + 2 swaps + complete


def test_rollout_controller_halts_on_refusal_and_rolls_back(tmp_path):
    from deepdfa_tpu.fleet import rollout as fleet_rollout

    cfg = ha_cfg(**{"fleet.rollout_settle_s": 0.0})
    # r0 accepts, r1 refuses (drift past bound) -> halt + r0 rollback
    calls, servers = _stub_rollout_fleet(tmp_path, [200, 409])
    try:
        report = fleet_rollout.run_rollout(
            cfg, tmp_path, "bad-tag",
            log_path=tmp_path / "fleet_log.jsonl",
        )
    finally:
        _stop_stub_fleet(servers)
    assert report["halted"], report
    assert not report["ok"]
    assert "drift" in report["halt_reason"]
    assert report["swapped"] == ["r0"]
    assert [r["replica"] for r in report["rolled_back"]] == ["r0"]
    rollback_calls = [c for c in calls if c[1].get("rollback")]
    assert [c[0] for c in rollback_calls] == ["r0"]


def test_rollout_controller_no_ready_replicas(tmp_path):
    from deepdfa_tpu.fleet import rollout as fleet_rollout

    report = fleet_rollout.run_rollout(ha_cfg(), tmp_path, "tag")
    assert not report["ok"]
    assert "no ready replicas" in report["error"]


# ---------------------------------------------------------------------------
# SLO guard (fleet/rollout.py:SloGuard) against a canned /stats


class _StatsHandler(BaseHTTPRequestHandler):
    slo: dict = {}

    def log_message(self, fmt, *args):
        pass

    def do_GET(self):  # noqa: N802
        body = json.dumps({"slo": self.slo}).encode()
        self.send_response(200)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


def _with_stats(slo: dict):
    handler = type("Stats", (_StatsHandler,), {"slo": slo})
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), handler)
    thread = threading.Thread(target=httpd.serve_forever, daemon=True)
    thread.start()
    return httpd, thread, httpd.server_address


def test_slo_guard_reads_smallest_window_and_breaches():
    from deepdfa_tpu.fleet.rollout import SloGuard

    slo = {
        "5s": {
            "latency_ms": {"total": {"p99": 900.0}},
            # 5 genuine 500s in 100: guard error rate 0.05; the 429/503
            # sheds (designed admission behavior) must NOT count
            "status": {"200": 75, "429": 10, "503": 10, "500": 5},
            "error_rate": 0.25,
        },
        "60s": {
            "latency_ms": {"total": {"p99": 50.0}},
            "status": {"200": 100},
            "error_rate": 0.0,
        },
        "queue_depth": 0,
    }
    httpd, thread, (host, port) = _with_stats(slo)
    try:
        # p99 arm disabled (0): server-error rate 0.05 under guard -> ok
        # even though the window's RAW error_rate (0.25, sheds counted)
        # would breach — sheds are load shedding working, not failures
        out = SloGuard(0.0, 0.25).read(host, port)
        assert out["ok"] and out["window"] == "5s"
        assert out["p99_ms"] == 900.0
        assert out["error_rate"] == 0.05
        # p99 arm armed: the SMALLEST window's 900ms breaches, even
        # though the 60s window looks fine
        out = SloGuard(500.0, 0.25).read(host, port)
        assert not out["ok"]
        assert "p99" in out["reason"]
        # error-rate arm: 0.05 genuine failures > 0.01 guard
        out = SloGuard(0.0, 0.01).read(host, port)
        assert not out["ok"]
        assert "error rate" in out["reason"]
        # error-rate arm disabled (0): even genuine failures pass
        out = SloGuard(0.0, 0.0).read(host, port)
        assert out["ok"]
    finally:
        httpd.shutdown()
        httpd.server_close()
        thread.join(timeout=5)


def test_slo_guard_tolerates_empty_windows():
    from deepdfa_tpu.fleet.rollout import SloGuard

    httpd, thread, (host, port) = _with_stats({"queue_depth": 0})
    try:
        out = SloGuard(100.0, 0.1).read(host, port)
        assert out["ok"]  # no window data yet is not a breach
    finally:
        httpd.shutdown()
        httpd.server_close()
        thread.join(timeout=5)


# ---------------------------------------------------------------------------
# chaos switchboard (fleet/chaos.py)


def test_chaos_state_apply_view_and_rejection():
    st = fleet_chaos.ChaosState()
    assert st.view()["wedge_remaining_s"] == 0.0
    out = st.apply({"wedge_s": 5.0}, now=100.0)
    assert out["wedge_remaining_s"] == pytest.approx(5.0)
    assert st.wedged(now=104.9) > 0
    assert st.wedged(now=105.1) == 0.0
    out = st.apply({"latency_s": 0.2, "duration_s": 10.0}, now=100.0)
    assert out["latency_s"] == 0.2
    assert st.view(now=110.1)["latency_s"] == 0.0  # expired
    out = st.apply({"clear": True}, now=100.0)
    assert out["wedge_remaining_s"] == 0.0
    assert out["latency_s"] == 0.0
    with pytest.raises(ValueError, match="unknown chaos keys"):
        st.apply({"explode": 1})


# ---------------------------------------------------------------------------
# bounded handler-thread join (the docs/fleet.md thread audit)


def test_draining_server_bounded_join_abandons_wedged_handler():
    from deepdfa_tpu.fleet.replica import _DrainingServer

    release = threading.Event()

    class _Stuck(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):
            pass

        def do_GET(self):  # noqa: N802
            release.wait(30.0)  # wedged far past the join budget
            self.send_response(200)
            self.end_headers()

    srv = _DrainingServer(("127.0.0.1", 0), _Stuck)
    srv.join_timeout_s = 1.0
    port = srv.server_address[1]
    serve = threading.Thread(target=srv.serve_forever, daemon=True)
    serve.start()

    def fire():
        import http.client

        try:
            conn = http.client.HTTPConnection(
                "127.0.0.1", port, timeout=20
            )
            conn.request("GET", "/")
            conn.getresponse()
        except OSError:
            pass

    t = threading.Thread(target=fire, daemon=True)
    t.start()
    time.sleep(0.3)  # the handler is now inside release.wait
    srv.shutdown()
    t0 = time.monotonic()
    srv.server_close()  # must NOT hang on the wedged handler
    took = time.monotonic() - t0
    assert took < 5.0, f"server_close blocked {took:.1f}s"
    release.set()
    serve.join(timeout=5)


def test_ha_close_joins_with_timeout(tmp_path):
    cfg = ha_cfg()
    a = fleet_ha.HARouter(cfg, tmp_path, "ra")
    a.start()
    assert a.wait_active(10.0)
    t0 = time.monotonic()
    a.close()
    assert time.monotonic() - t0 < 15.0
    assert a._loop_thread is None
    assert a._serve_thread is None


# ---------------------------------------------------------------------------
# MULTICHIP round-over-round gating (obs/bench_gate.py)


def _mc_artifact(n=8, flops=1e9, compile_s=3.0, recompiles=0, rc=0,
                 ok=True):
    return {
        "n_devices": n, "rc": rc, "ok": ok, "skipped": [],
        "parsed": {"multichip": {
            "n_devices": n,
            "serve": {"steady_state_recompiles": recompiles},
            "shard": {
                "train_dp8/S8": {
                    "flops_per_sec": flops,
                    "per_shard_flops_per_sec": flops / 8,
                    "compile_seconds": compile_s,
                },
                "serve_score/G1": {
                    "flops_per_sec": flops / 10,
                    "compile_seconds": compile_s / 2,
                },
            },
            "compile_seconds_total": compile_s * 4,
        }},
    }


def _mc_trajectory():
    from deepdfa_tpu.obs import bench_gate as bg

    entries = []
    for i, art in enumerate([
        _mc_artifact(rc=124, ok=False),        # failed round
        _mc_artifact(flops=1.2e9),             # healthy baseline
    ], start=1):
        entries.append({
            "source": f"MULTICHIP_r{i:02d}.json", "round": i,
            "artifact": art,
            "record": bg.multichip_record(art),
        })
    return entries


def test_multichip_gate_pass_and_regression():
    from deepdfa_tpu.obs import bench_gate as bg

    traj = _mc_trajectory()
    ok = bg.gate_multichip(_mc_artifact(flops=1.1e9), traj)
    assert ok["verdict"] == "pass", ok
    # the reference is the healthy round, never the failed one
    assert all(
        c["ref_source"] in ("MULTICHIP_r02.json", "absolute_bound")
        for c in ok["checks"]
    )
    slow = bg.gate_multichip(_mc_artifact(flops=0.4e9), traj)
    assert slow["verdict"] == "fail"
    assert "regression" in slow["failure_classes"]
    compile_blowup = bg.gate_multichip(
        _mc_artifact(flops=1.2e9, compile_s=30.0), traj
    )
    assert compile_blowup["verdict"] == "fail"


def test_multichip_gate_recompile_pin_and_error_class():
    from deepdfa_tpu.obs import bench_gate as bg

    traj = _mc_trajectory()
    recompiled = bg.gate_multichip(
        _mc_artifact(flops=1.2e9, recompiles=2), traj
    )
    assert recompiled["verdict"] == "fail"
    assert any(
        c["metric"] == "serve/steady_state_recompiles" and not c["ok"]
        for c in recompiled["checks"]
    )
    failed = bg.gate_multichip(_mc_artifact(rc=1, ok=False), traj)
    assert "error" in failed["failure_classes"]


def test_multichip_gate_scale_mismatch_skips_reference():
    from deepdfa_tpu.obs import bench_gate as bg

    traj = _mc_trajectory()
    other_scale = bg.gate_multichip(_mc_artifact(n=4), traj)
    # no 4-device reference: only the absolute recompile pin runs
    assert other_scale["verdict"] == "pass"
    assert all(
        c["ref_source"] == "absolute_bound"
        for c in other_scale["checks"]
    )
    assert any("no healthy" in n for n in other_scale["notes"])


def test_multichip_real_trajectory_loads_and_gates():
    from pathlib import Path

    from deepdfa_tpu.obs import bench_gate as bg

    repo = Path(__file__).resolve().parent.parent
    traj = bg.load_multichip_trajectory(repo)
    assert traj, "no committed MULTICHIP_r*.json found"
    healthy = [e for e in traj if bg._multichip_healthy(e)]
    assert healthy, "no healthy multichip round in the repo"
    newest = healthy[-1]
    verdict = bg.gate_multichip(
        newest["artifact"], traj,
    )
    # gated against the trajectory INCLUDING itself: must pass (the
    # CLI excludes the candidate; this pins record/parse integrity)
    assert verdict["verdict"] == "pass", verdict


# ---------------------------------------------------------------------------
# on-disk param-bytes estimation (fleet/replica.py, the planner input)


def test_estimate_param_bytes_on_disk(tmp_path):
    from deepdfa_tpu.fleet.replica import estimate_param_bytes_on_disk

    ckpt = tmp_path / "checkpoints" / "best"
    ckpt.mkdir(parents=True)
    (ckpt / "params.bin").write_bytes(b"x" * 1000)
    (ckpt / "meta.json").write_bytes(b"y" * 24)
    got = estimate_param_bytes_on_disk(tmp_path, "deepdfa", "best")
    assert got == 1024.0
    # @int8 strips to the base tag (served bytes differ; disk is fp32)
    assert estimate_param_bytes_on_disk(
        tmp_path, "deepdfa", "best@int8"
    ) == 1024.0
    # "last" resolves through the manifest
    (tmp_path / "checkpoints" / "manifest.json").write_text(
        json.dumps({"last": {"tag": "best"}})
    )
    assert estimate_param_bytes_on_disk(
        tmp_path, "deepdfa", "last"
    ) == 1024.0
    # unresolvable -> 0.0, never a crash
    assert estimate_param_bytes_on_disk(
        tmp_path, "deepdfa", "missing-tag"
    ) == 0.0
