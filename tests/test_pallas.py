"""Pallas edge-scatter kernel parity (interpret mode on the CPU mesh).

Compiled-TPU parity + timing is exercised on real hardware during bench /
verification; here the kernel logic is pinned against the XLA reference.
"""

import numpy as np
import pytest

from deepdfa_tpu.nn.pallas_ops import edge_scatter_reference, pallas_edge_scatter


@pytest.mark.parametrize("n,d,e", [(64, 128, 500), (8, 128, 3), (128, 128, 2048)])
def test_scatter_parity_interpret(rng, n, d, e):
    m = rng.standard_normal((n, d)).astype(np.float32)
    src = rng.integers(0, n, e).astype(np.int32)
    dst = rng.integers(0, n, e).astype(np.int32)
    mask = rng.random(e) > 0.25
    got = np.asarray(pallas_edge_scatter(m, src, dst, mask, interpret=True))
    want = np.asarray(edge_scatter_reference(m, src, dst, mask))
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)


def test_scatter_all_masked(rng):
    m = rng.standard_normal((16, 128)).astype(np.float32)
    src = np.zeros(10, np.int32)
    dst = np.zeros(10, np.int32)
    mask = np.zeros(10, bool)
    got = np.asarray(pallas_edge_scatter(m, src, dst, mask, interpret=True))
    np.testing.assert_allclose(got, 0.0)


def test_ggnn_with_pallas_flag_matches(rng):
    """GatedGraphConv(use_pallas=True) == use_pallas=False (interpret on CPU
    via the kernel's interpret fallback is not wired through the module, so
    compare on tiny shapes where the interpreter path runs via jit on CPU)."""
    import jax

    if jax.default_backend() != "tpu":
        pytest.skip("module-level pallas path needs compiled TPU lowering")
