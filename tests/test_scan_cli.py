"""CLI `scan` smoke path via real subprocesses (the argparse wiring
can't rot silently), plus the scan halves of the schema checker, diag,
and the bench script — ISSUE 8 satellites.

Subprocess-only by design (tests/conftest.py:run_cli): the CLI
normalizes to a 1-device CPU platform, which must never leak into this
8-virtual-device pytest process.
"""

import json
import os
import subprocess
import sys
from pathlib import Path

from tests.conftest import run_cli

REPO = Path(__file__).resolve().parent.parent


def _last_json(stdout: str) -> dict:
    lines = [ln for ln in stdout.splitlines() if ln.startswith("{")]
    assert lines, f"no JSON line in output: {stdout[-800:]}"
    return json.loads(lines[-1])


def test_scan_smoke_end_to_end(tmp_path):
    """`scan --smoke`: train a tiny checkpoint, scan a synthetic repo
    cold (valid SARIF 2.1.0 + findings JSONL with line attributions),
    edit one function, re-scan incrementally re-extracting ONLY it,
    with zero steady-state recompiles on the score and line paths —
    the ISSUE 8 acceptance drive. The produced scan_log validates
    against the declared schema and diag renders a scan section from
    it."""
    res = run_cli(tmp_path, "scan", "--smoke", timeout=420)
    report = _last_json(res.stdout)
    cold, incr = report["cold"], report["incremental"]

    # cold coverage: every function of every discovered file scored,
    # the .git decoy and the oversized generated file were pruned
    assert cold["scan_functions"] > 0
    assert cold["scan_reused"] == 0
    assert report["findings"] == cold["scan_functions"]
    assert report["findings_ok"] == cold["scan_scored"]
    assert report["findings_with_lines"] > 0
    assert report["sarif_problems"] == []
    assert report["sarif_results"] > 0

    # the incremental contract
    assert incr["scan_extracted"] == 1
    assert incr["scan_reused"] == incr["scan_functions"] - 1
    assert incr["scan_files_reused"] == incr["scan_files"] - 1

    # zero steady-state recompiles, both paths, both scans
    for s in (cold, incr):
        assert s["scan_steady_state_recompiles"] == 0
        assert s["scan_lines_steady_state_recompiles"] == 0

    # SARIF document on disk parses and re-validates here
    sarif = json.loads(Path(cold["sarif_path"]).read_text())
    assert sarif["version"] == "2.1.0"
    assert sarif["runs"][0]["results"]

    # scan_log.jsonl is schema-clean (check_obs_schema --scan-log)
    scan_log = Path(report["scan_log"])
    assert scan_log.exists()
    proc = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "check_obs_schema.py"),
         "--scan-log", str(scan_log)],
        env=dict(os.environ, DEEPDFA_TPU_PLATFORM="cpu",
                 JAX_PLATFORMS="cpu"),
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, (proc.stdout + proc.stderr)[-2000:]
    record = json.loads(proc.stdout.splitlines()[0])
    assert record["ok"] is True and record["undeclared"] == []

    # diag renders the scan section from the same log
    diag = run_cli(
        tmp_path, "diag", report["run_dir"], "--json", timeout=120
    )
    diag_report = _last_json(diag.stdout)
    scan_sec = diag_report["scan"]
    assert scan_sec["scan_functions"] == incr["scan_functions"]
    assert scan_sec["scan_incremental_skip_fraction"] == (
        incr["scan_incremental_skip_fraction"]
    )
    assert scan_sec["stage_seconds"]
    assert scan_sec["scans"] == 2


def test_bench_scan_smoke(tmp_path):
    """scripts/bench_scan.py --smoke: stamped record with the scanning
    headline numbers (bench.py --child-scan consumes the same fn)."""
    out = tmp_path / "scan_bench.json"
    proc = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "bench_scan.py"),
         "--smoke", "--out", str(out)],
        env=dict(os.environ, DEEPDFA_TPU_PLATFORM="cpu",
                 JAX_PLATFORMS="cpu",
                 DEEPDFA_TPU_STORAGE=str(tmp_path)),
        cwd=REPO, capture_output=True, text=True, timeout=420,
    )
    assert proc.returncode == 0, (proc.stdout + proc.stderr)[-2000:]
    record = json.loads(out.read_text())
    assert record["metric"] == "scan_functions_per_sec"
    assert record["value"] > 0
    assert record["scan_warm_functions_per_sec"] > 0
    assert record["scan_incremental_functions_per_sec"] > 0
    assert record["scan_cache_hit_fraction"] == 1.0
    assert record["scan_incremental_skip_fraction"] >= 0.9
    assert record["scan_steady_state_recompiles"] == 0
    # provenance stamp, like every other bench record
    for k in ("schema_version", "git_sha", "jax_version"):
        assert k in record
