"""Test harness: force an 8-virtual-device CPU platform before JAX imports.

This is the standard JAX trick for exercising multi-chip sharding without
hardware (fills the reference's "multi-node without a cluster" gap noted in
SURVEY.md §4): every test sees jax.device_count() == 8 on CPU.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("JAX_ENABLE_X64", "0")

# The axon sitecustomize (TPU tunnel) imports jax at interpreter start and
# forces jax_platforms="axon,cpu", overriding the env var — so force the
# config back to cpu here, before any backend is initialized.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    import jax

    devs = jax.devices()
    assert len(devs) == 8, devs
    return devs


@pytest.fixture()
def rng():
    return np.random.default_rng(0)
