"""Test harness: force an 8-virtual-device CPU platform before JAX imports.

This is the standard JAX trick for exercising multi-chip sharding without
hardware (fills the reference's "multi-node without a cluster" gap noted in
SURVEY.md §4): every test sees jax.device_count() == 8 on CPU.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    import jax

    devs = jax.devices()
    assert len(devs) == 8, devs
    return devs


@pytest.fixture()
def rng():
    return np.random.default_rng(0)
