"""Test harness: force an 8-virtual-device CPU platform before JAX imports.

This is the standard JAX trick for exercising multi-chip sharding without
hardware (fills the reference's "multi-node without a cluster" gap noted in
SURVEY.md §4): every test sees jax.device_count() == 8 on CPU.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("JAX_ENABLE_X64", "0")

# The axon sitecustomize (TPU tunnel) imports jax at interpreter start and
# forces jax_platforms="axon,cpu", overriding the env var — so force the
# config back to cpu here, before any backend is initialized.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def load_script_module(name: str):
    """Import a module from the repo's scripts/ dir (the fuzz/robustness
    harnesses live there as runnable scripts; their floor tests reuse the
    corpus generators). Path hygiene in one place."""
    import importlib
    import pathlib
    import sys

    scripts = str(pathlib.Path(__file__).parents[1] / "scripts")
    sys.path.insert(0, scripts)
    try:
        return importlib.import_module(name)
    finally:
        sys.path.remove(scripts)


@pytest.fixture(scope="session")
def devices():
    import jax

    devs = jax.devices()
    assert len(devs) == 8, devs
    return devs


@pytest.fixture()
def rng():
    return np.random.default_rng(0)


def run_cli(storage, *argv, expect_rc=0, expect_err=None, timeout=600):
    """Drive the real CLI in a SUBPROCESS (argv + env surface; also, the
    accumulated in-process XLA state of many trainings inside one pytest
    process has produced spurious fatal aborts on this box — fresh
    processes never reproduce them). Shared by the CLI test files."""
    import pathlib
    import subprocess
    import sys

    # Plain "cpu" is normalized to ONE device by apply_platform_override,
    # so the 8-device XLA_FLAGS this pytest process exports (above) cannot
    # leak an 8-way in-process-collective mesh into CLI subprocesses on a
    # 1-core host (round-3 red test: SIGABRT in XLA's CPU rendezvous).
    # Multi-device CLI subprocess tests opt in with cpu:N explicitly.
    env = dict(
        os.environ,
        DEEPDFA_TPU_STORAGE=str(storage),
        DEEPDFA_TPU_PLATFORM="cpu",
    )
    res = subprocess.run(
        [sys.executable, "-m", "deepdfa_tpu.cli", *argv],
        capture_output=True, text=True, env=env, timeout=timeout,
        cwd=str(pathlib.Path(__file__).parents[1]),
    )
    if expect_rc == 0:
        assert res.returncode == 0, res.stderr[-2000:]
    else:
        assert res.returncode != 0, res.stdout[-500:]
    if expect_err is not None:
        assert expect_err in res.stderr, res.stderr[-2000:]
    return res
