"""Unified sharding layer (parallel/sharding.py, docs/sharding.md).

The load-bearing contracts:

- path-pattern rules resolve a params pytree to PartitionSpecs (first
  match wins, stacked pp rules, shape-aware fitting, loud unknown-axis
  errors) and the family builders reproduce the documented layouts;
- the logical-shard GraphTrainer step is BIT-IDENTICAL across dp
  topologies that divide num_shards (the jit-vs-eager and
  cross-ladder-size traps do not apply: every topology runs the same
  vmapped per-shard program and ONE fixed-shape reduction);
- elastic resume: a step checkpoint written at dp=8 restores at dp=4
  and dp=1 and the merged step-loss trajectory equals the uninterrupted
  dp=8 run exactly;
- a sharded checkpoint serves through the warmed executor ladder with
  zero steady-state recompiles and score parity;
- process-0 gating: non-primary processes build no single-writer
  resources and obs.session installs nothing;
- the MULTICHIP record validator accepts the dryrun's shape and rejects
  damage.
"""

import dataclasses
import shutil

import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from deepdfa_tpu.core import Config, MeshConfig, config as config_mod
from deepdfa_tpu.data import build_dataset, generate, to_examples
from deepdfa_tpu.graphs import pack_shards, shard_bucket_batches
from deepdfa_tpu.models import DeepDFA
from deepdfa_tpu.parallel import make_mesh, sharding

NB, EB = 1024, 4096


@pytest.fixture(scope="module")
def corpus():
    synth = generate(32, vuln_rate=0.25, seed=0)
    specs, vocabs = build_dataset(
        to_examples(synth), train_ids=range(32), limit_all=30,
        limit_subkeys=30,
    )
    return specs, vocabs


@pytest.fixture(scope="module")
def tiny_model():
    cfg = config_mod.apply_overrides(
        Config(), ["model.hidden_dim=8", "model.n_steps=2"]
    )
    return cfg, DeepDFA.from_config(cfg.model, input_dim=32)


# ---------------------------------------------------------------------------
# rules


def test_rule_resolution_first_match_and_stacked():
    rules = sharding.parse_rules([
        "encoder/layers/wq=-,-,tp,-",
        "head/*=",
        "*/kernel=-,fsdp",
    ])
    smap = sharding.ShardingMap(rules=rules, stacked=(("encoder/*", "pp"),))
    assert smap.spec_for("encoder/layers/wq") == P("pp", None, "tp", None)
    assert smap.spec_for("graph/dense/kernel") == P(None, "fsdp")
    # an earlier rule wins: head/* pins replicated ahead of */kernel
    assert smap.spec_for("head/out/kernel") == P()
    assert smap.spec_for("unmatched/bias") == P()


def test_operator_rule_pins_through_stacked_pp():
    """A `pattern=` operator pin survives the family map's pp stacked
    transform (operator rules are FINAL — docs/sharding.md)."""
    smap = sharding.sharding_map_for(
        "t5", mesh_shape={"tp": 2, "pp": 2},
        extra_rules=["encoder/layers/wq="],
    )
    assert smap.spec_for("encoder/layers/wq") == P()
    # non-pinned siblings still stage-shard
    assert smap.spec_for("encoder/layers/wk") == P("pp", None, "tp", None)


def test_read_only_runner_restores_but_never_writes(
    corpus, tiny_model, tmp_path
):
    """Multi-host non-primary mode: the runner restores the shared
    step-checkpoint tree (state + cursor re-align on every host) but
    writes nothing — process 0 owns the saves (docs/sharding.md)."""
    import jax

    from deepdfa_tpu.train import ResilientRunner, ResumeCursor

    specs, _ = corpus
    cfg, model = tiny_model
    cfg = config_mod.apply_overrides(cfg, [
        'train.resilience={"enabled": true, "step_checkpoint_every": 1}',
    ])
    mesh = make_mesh(MeshConfig(dp=1), devices=jax.devices()[:1])
    from deepdfa_tpu.train import GraphTrainer

    trainer = GraphTrainer(model, cfg, mesh=mesh)
    batch = _batch8(specs)
    state = trainer.init_state(batch, seed=0)
    ckpt_dir = tmp_path / "shared"
    writer = ResilientRunner(cfg.train.resilience, ckpt_dir, seed=1)
    writer.after_step(state, None, ResumeCursor(0, 1, 1))
    assert (ckpt_dir / "resume.json").exists()

    reader = ResilientRunner(
        cfg.train.resilience, ckpt_dir, seed=1, read_only=True
    )
    before = sorted(p.name for p in ckpt_dir.iterdir())
    restored, cursor = reader.maybe_resume(state, lambda host: host)
    assert cursor is not None and cursor.step == 1
    # a full pass of after_step checkpoints writes NOTHING new
    reader.after_step(restored, None, ResumeCursor(0, 2, 2))
    reader.finish(restored, ResumeCursor(1, 0, 2))
    assert sorted(p.name for p in ckpt_dir.iterdir()) == before


def test_rule_parse_rejects_malformed_and_unknown_axis():
    with pytest.raises(ValueError, match="pattern=axes"):
        sharding.parse_rules(["no-equals-sign"])
    smap = sharding.ShardingMap(
        rules=sharding.parse_rules(["*/kernel=-,bogus"])
    )
    with pytest.raises(ValueError, match="unknown mesh axis 'bogus'"):
        smap.validate()


def test_spec_fitting_replicates_non_divisible_dims(devices):
    mesh = make_mesh(MeshConfig(dp=1, fsdp=8), devices=devices)
    smap = sharding.ShardingMap(
        rules=sharding.parse_rules(["*/kernel=-,fsdp"])
    )
    tree = {
        "a": {"kernel": np.zeros((4, 16))},   # 16 % 8 == 0 -> sharded
        "b": {"kernel": np.zeros((4, 1))},    # 1 % 8 != 0 -> replicated
        "c": {"bias": np.zeros((3,))},
    }
    specs = smap.param_specs(tree, mesh_shape=dict(mesh.shape))
    assert specs["a"]["kernel"] == P(None, "fsdp")
    assert specs["b"]["kernel"] == P(None, None)
    assert specs["c"]["bias"] == P()
    placed = smap.place(mesh, tree)
    assert placed["a"]["kernel"].sharding.spec == P(None, "fsdp")


def test_family_map_combined_layouts():
    from deepdfa_tpu.models import combined as cmb
    from deepdfa_tpu.models import t5 as t5m
    from deepdfa_tpu.models.transformer import TransformerConfig

    import jax

    mcfg = cmb.CombinedConfig(
        encoder=TransformerConfig.tiny(
            vocab_size=64, max_position_embeddings=40
        ),
        graph_hidden_dim=8, graph_input_dim=32,
    )
    example = jax.eval_shape(lambda: cmb.init_params(mcfg, jax.random.key(0)))
    # tp + pp: the Megatron layer table with the stacked axis resharded
    smap = sharding.sharding_map_for(
        "combined", model_cfg=mcfg, mesh_shape={"tp": 2, "pp": 2}
    )
    specs = smap.param_specs(example)
    assert specs["encoder"]["layers"]["wq"] == P("pp", None, "tp", None)
    assert specs["encoder"]["layers"]["ln1_scale"] == P("pp", None)
    assert specs["encoder"]["embeddings"]["word"] == P()
    assert specs["head"]["dense_w"] == P()
    # dp-only mesh: everything replicated (size-1 axes collapse)
    flat = sharding.sharding_map_for(
        "combined", model_cfg=mcfg, mesh_shape={"dp": 8}
    ).param_specs(example)
    import jax as _jax

    assert all(
        s == P() for s in _jax.tree.leaves(
            flat, is_leaf=lambda x: isinstance(x, P)
        )
    )
    # t5 tp: rel_bias heads shard
    t5cfg = t5m.DefectConfig(
        encoder=t5m.T5Config.tiny(vocab_size=64, remat=False),
        graph_hidden_dim=8, graph_input_dim=32,
    )
    t5_example = jax.eval_shape(
        lambda: t5m.init_defect_params(t5cfg, jax.random.key(0))
    )
    t5_specs = sharding.sharding_map_for(
        "t5", model_cfg=t5cfg, mesh_shape={"tp": 2}
    ).param_specs(t5_example)
    assert t5_specs["encoder"]["rel_bias"] == P(None, "tp")
    assert t5_specs["encoder"]["layers"]["wi"] == P(None, None, "tp")

    with pytest.raises(ValueError, match="unknown model family"):
        sharding.sharding_map_for("nope")


# ---------------------------------------------------------------------------
# the logical-shard step: bit-identity across dp topologies


def _batch8(specs):
    return pack_shards(specs, 8, num_graphs=4, node_budget=256,
                       edge_budget=EB // 4)


def _run_steps(model, cfg, batch, dp, n_steps=2):
    import jax

    from deepdfa_tpu.data.prefetch import device_placer
    from deepdfa_tpu.train import GraphTrainer

    mesh = make_mesh(MeshConfig(dp=dp), devices=jax.devices()[:dp])
    t = GraphTrainer(model, cfg, mesh=mesh)
    s = t.init_state(batch, seed=0)
    b = device_placer(mesh)(batch)
    losses = []
    for _ in range(n_steps):
        s, loss = t.train_step(s, b)
        losses.append(np.asarray(jax.device_get(loss)).tobytes())
    return losses, jax.device_get(s.params), t, b, s


def test_dp_topology_bit_identity(corpus, tiny_model, devices):
    """dp in {1, 4, 8} over the SAME 8-logical-shard batch: step-loss
    trajectories AND updated params bitwise equal (adamw default)."""
    import jax

    specs, _ = corpus
    cfg, model = tiny_model
    batch = _batch8(specs)
    ref_losses, ref_params, *_ = _run_steps(model, cfg, batch, dp=1)
    for dp in (4, 8):
        losses, params, *_ = _run_steps(model, cfg, batch, dp=dp)
        assert losses == ref_losses, (dp, losses, ref_losses)
        for a, b in zip(jax.tree.leaves(ref_params), jax.tree.leaves(params)):
            assert np.asarray(a).tobytes() == np.asarray(b).tobytes(), dp


def test_eval_step_matches_across_dp(corpus, tiny_model):
    specs, _ = corpus
    cfg, model = tiny_model
    batch = _batch8(specs)
    _, _, t1, b1, s1 = _run_steps(model, cfg, batch, dp=1, n_steps=1)
    _, _, t8, b8, s8 = _run_steps(model, cfg, batch, dp=8, n_steps=1)
    m1, _ = t1.evaluate(s1, [b1])
    m8, _ = t8.evaluate(s8, [b8])
    assert m1 == m8


def test_logical_shard_validation(devices):
    mesh = make_mesh(MeshConfig(dp=8), devices=devices)
    assert sharding.check_logical_shards(16, mesh) == 2
    with pytest.raises(ValueError, match="not divisible"):
        sharding.check_logical_shards(6, mesh)
    assert sharding.logical_shards(MeshConfig(num_shards=16), mesh) == 16
    assert sharding.logical_shards(MeshConfig(), mesh) == 8


# ---------------------------------------------------------------------------
# elastic resume


def _fit_logged(model, cfg, batches, dp, run_dir, injector=None):
    import jax

    from deepdfa_tpu.testing.faults import FaultInjector  # noqa: F401
    from deepdfa_tpu.train import GraphTrainer, Preempted, ResilientRunner

    mesh = make_mesh(MeshConfig(dp=dp), devices=jax.devices()[:dp])
    t = GraphTrainer(model, cfg, mesh=mesh)
    state = t.init_state(batches(0)[0], seed=0)
    runner = ResilientRunner(
        cfg.train.resilience, run_dir, seed=cfg.train.seed
    )
    steps = []
    stream = (
        (lambda e: injector.wrap(batches(e)))
        if injector is not None else batches
    )
    try:
        t.fit(
            state, stream,
            log_fn=lambda r: steps.append((r["step"], r["loss"]))
            if "loss" in r else None,
            resilience=runner,
        )
        return steps, runner, None
    except Preempted as p:
        return steps, runner, p


def test_elastic_resume_bit_identical(corpus, tiny_model, tmp_path):
    """Checkpoint at dp=8 (SIGTERM mid-run), restore at dp=4 AND dp=1:
    each merged step-loss trajectory equals the uninterrupted dp=8 run
    EXACTLY — elastic resume is bit-exact because the logical-shard
    layout fixes both the batch stream and the reduction tree
    (docs/sharding.md)."""
    import json as _json

    from deepdfa_tpu.testing.faults import FaultInjector, FaultPlan

    specs, _ = corpus
    cfg, model = tiny_model
    cfg = config_mod.apply_overrides(cfg, [
        "train.max_epochs=2",
        "train.prefetch_batches=0",
        "train.log_every_steps=1",
        'train.resilience={"enabled": true, "step_checkpoint_every": 2}',
    ])

    def batches(_epoch):
        return list(shard_bucket_batches(
            specs, num_shards=8, num_graphs=2, node_budget=256,
            edge_budget=EB // 4, oversized="drop",
        ))

    ref, _, _ = _fit_logged(model, cfg, batches, 8, tmp_path / "ref")
    assert len(ref) >= 4, ref

    kill_at = max(2, len(ref) // 2)
    faulted_dir = tmp_path / "faulted"
    injector = FaultInjector(FaultPlan(sigterm_at_step=kill_at))
    first, _, preempted = _fit_logged(
        model, cfg, batches, 8, faulted_dir, injector=injector
    )
    assert preempted is not None
    manifest = _json.loads((faulted_dir / "resume.json").read_text())
    # the manifest carries the topology stamp (elastic-resume audit)
    assert manifest["mesh"]["num_shards"] == 8
    assert manifest["mesh"]["axes"] == {"dp": 8}

    for dp in (4, 1):
        resume_dir = tmp_path / f"resume-dp{dp}"
        shutil.copytree(faulted_dir, resume_dir)
        second, runner, _ = _fit_logged(
            model, cfg, batches, dp, resume_dir
        )
        assert runner.resumed_from_step == kill_at
        merged = first + second
        assert merged == ref, (
            dp, merged[:3], ref[:3], len(merged), len(ref),
        )


# ---------------------------------------------------------------------------
# serving through the sharded layer


def test_serve_mesh_parity_and_census(corpus, tiny_model, devices, tmp_path):
    """fsdp-sharded params serve through the warmed ladder: zero
    steady-state recompiles, scores match single-device serving, and a
    restore_for_inference(shardings=) checkpoint lands pre-sharded."""
    import jax

    from deepdfa_tpu.graphs.batch import pack
    from deepdfa_tpu.serve.batcher import DynamicBatcher, GgnnExecutor
    from deepdfa_tpu.train.checkpoint import CheckpointManager

    specs, _ = corpus
    cfg, model = tiny_model
    params = model.init(jax.random.key(0), pack([], 1, NB, EB))

    mesh = make_mesh(MeshConfig(dp=1, fsdp=8), devices=devices)
    smap = sharding.sharding_map_for("deepdfa", mesh_shape=dict(mesh.shape))
    # elastic placement at restore: the checkpoint commits straight to
    # the serving mesh's resolved shardings
    mgr = CheckpointManager(tmp_path / "ckpt")
    host = jax.device_get(params)
    mgr.save("best", host, {"val_loss": 1.0}, step=0)
    restored = mgr.restore_for_inference(
        "best", host, shardings=smap.shardings(mesh, host)
    )
    emb = restored["params"]["embedding"]["embed_api"]["embedding"]
    assert emb.sharding.spec == P(None, "fsdp")

    ex_plain = GgnnExecutor(
        model, lambda: jax.device_put(host),
        node_budget=NB, edge_budget=EB, max_batch_graphs=4,
    )
    ex_mesh = GgnnExecutor(
        model, lambda: restored,
        node_budget=NB, edge_budget=EB, max_batch_graphs=4, mesh=mesh,
    )
    ex_plain.warmup()
    ex_mesh.warmup()
    low0 = ex_mesh.jit_lowerings()
    batcher = DynamicBatcher(ex_mesh, queue_limit=64)
    reqs = batcher.score_all(specs[:6])
    got = np.array([r.result for r in reqs])
    want = np.array([
        ex_plain.execute("graph", [s])[0] for s in specs[:6]
    ])
    np.testing.assert_allclose(got, want, atol=1e-6)
    assert ex_mesh.jit_lowerings() == low0  # zero steady-state lowerings

    # BOTH serve ladders: the line-attribution executables warm over
    # the same sizes and hold the census sharded too
    from deepdfa_tpu.serve.frontend import Features
    from deepdfa_tpu.serve.localize import GgnnLocalizer

    def localizer(params_fn, mesh_=None):
        return GgnnLocalizer(
            model, params_fn, node_budget=NB, edge_budget=EB,
            sizes=ex_mesh.sizes, method="saliency", top_k=5, mesh=mesh_,
        )

    loc_plain = localizer(lambda: jax.device_put(host))
    loc_mesh = localizer(lambda: restored, mesh_=mesh)
    loc_plain.warmup()
    loc_mesh.warmup()
    llow0 = loc_mesh.jit_lowerings()
    feats = [
        Features(spec=s, node_lines=np.arange(1, s.num_nodes + 1,
                                              dtype=np.int32))
        for s in specs[:3]
    ]
    out_plain = loc_plain.attribute_all(feats)
    out_mesh = loc_mesh.attribute_all(feats)
    assert loc_mesh.jit_lowerings() == llow0
    for (pa, la), (pb, lb) in zip(out_plain, out_mesh):
        np.testing.assert_allclose(pa, pb, atol=1e-6)
        assert [d["line"] for d in la] == [d["line"] for d in lb]


def test_serve_mesh_helper(devices):
    from deepdfa_tpu.serve.registry import serve_mesh

    cfg = Config()
    assert serve_mesh(cfg) is None  # default path untouched
    cfg = config_mod.apply_overrides(
        Config(), ["serve.sharded=true", "serve.mesh.fsdp=8",
                   "serve.mesh.dp=1"]
    )
    mesh = serve_mesh(cfg)
    assert mesh is not None and mesh.shape["fsdp"] == 8


# ---------------------------------------------------------------------------
# multi-host coordination


def test_primary_gating(monkeypatch, tmp_path):
    import jax

    from deepdfa_tpu import obs
    from deepdfa_tpu.obs import flight as obs_flight
    from deepdfa_tpu.train.logging import NullRunLogger

    assert sharding.is_primary()  # single-process: always the primary
    assert sharding.if_primary(lambda: "built") == "built"

    monkeypatch.setattr(jax, "process_index", lambda: 1)
    assert not sharding.is_primary()
    assert sharding.if_primary(lambda: "built", fallback=None) is None
    # obs.session installs nothing off-primary (flight requested but
    # never installed; no files created)
    cfg = config_mod.apply_overrides(
        Config(), ["obs.flight=true", "obs.metrics=true"]
    )
    with obs.session(cfg, tmp_path):
        assert not obs_flight.installed()
    assert not (tmp_path / "postmortem.json").exists()
    with NullRunLogger() as lg:
        lg.log({"step": 1, "loss": 0.5})
    assert not (tmp_path / "train_log.jsonl").exists()


def test_mesh_record_and_publish(devices):
    from deepdfa_tpu.obs import metrics as obs_metrics

    mesh = make_mesh(MeshConfig(dp=4, tp=2), devices=devices)
    rec = sharding.mesh_record(mesh, num_shards=8)
    assert rec["axes"] == {"dp": 4, "tp": 2}
    assert rec["devices"] == 8
    assert rec["processes"] == 1
    assert rec["num_shards"] == 8
    sharding.publish_mesh(mesh, num_shards=8)
    snap = obs_metrics.REGISTRY.snapshot()
    assert snap["mesh/dp"] == 4.0
    assert snap["mesh/num_shards"] == 8.0
    assert obs_metrics.declared("mesh/dp")
    assert obs_metrics.declared("shard/train_dp8/S8/flops")


# ---------------------------------------------------------------------------
# the MULTICHIP record contract


def _multichip_record():
    return {
        "multichip": {
            "n_devices": 8,
            "num_shards": 8,
            "mesh_shapes": {
                "dp8": {"axes": {"dp": 8}, "devices": 8, "processes": 1,
                        "num_shards": 8},
            },
            "serve": {"ladder": [1, 2, 4], "steady_state_recompiles": 0,
                      "mesh": {"axes": {"fsdp": 8}}},
            "shard": {
                "train_dp8/S8": {
                    "flops": 1.0, "compile_seconds": 0.5, "executions": 3,
                    "device_seconds": 0.1, "flops_per_sec": 30.0,
                },
            },
            "hbm": {},
            "compile_seconds_total": 0.5,
        }
    }


def test_validate_multichip_accepts_and_rejects():
    ok = sharding.validate_multichip(_multichip_record())
    assert ok["ok"], ok
    # driver-artifact shape: the record under `parsed`
    wrapped = {"n": 7, "rc": 0, "parsed": _multichip_record()}
    assert sharding.validate_multichip(wrapped)["ok"]

    damaged = _multichip_record()
    del damaged["multichip"]["shard"]
    out = sharding.validate_multichip(damaged)
    assert not out["ok"] and any("shard" in p for p in out["problems"])

    recompiled = _multichip_record()
    recompiled["multichip"]["serve"]["steady_state_recompiles"] = 2
    out = sharding.validate_multichip(recompiled)
    assert not out["ok"]
    assert any("recompiled" in p for p in out["problems"])

    assert not sharding.validate_multichip({"parsed": None})["ok"]


def test_meshconfig_roundtrip_and_fsdp_axis(devices):
    cfg = config_mod.apply_overrides(Config(), [
        "train.mesh.fsdp=2", "train.mesh.dp=4",
        "train.mesh.num_shards=8",
        'train.mesh.rules=["*/embedding=-,fsdp"]',
    ])
    d = config_mod.from_dict(
        __import__("json").loads(config_mod.to_json(cfg))
    )
    assert d.train.mesh.fsdp == 2
    assert d.train.mesh.num_shards == 8
    assert d.train.mesh.rules == ("*/embedding=-,fsdp",)
    mesh = make_mesh(d.train.mesh, devices=devices)
    assert mesh.shape["dp"] == 4 and mesh.shape["fsdp"] == 2
