int session_close(struct sess *s) {
  int rc = 0;
  if (s->buf) {
    free(s->buf);
    s->buf = 0;
  }
  if (s->fd >= 0) {
    rc = close(s->fd);
    s->fd = -1;
  }
  return rc;
}
