int split_csv(char *line, char **cols, int max) {
  int n = 0;
  char *tok = strtok(line, ",");
  while (tok && n < max) {
    cols[n] = tok;
    n = n + 1;
    tok = strtok(0, ",");
  }
  return n;
}
