int alloc_table(struct entry **out, int n) {
  int bytes = n * sizeof(struct entry);
  *out = malloc(bytes);
  if (!*out)
    return -1;
  memset(*out, 0, bytes);
  return bytes;
}
