int sum_all(std::vector<int> &v) {
  int total = 0;
  for (int x : v) {
    total += x;
  }
  return total;
}
