void AES_encrypt(const uint8_t *in, uint8_t *out, const AES_KEY *key) {
  if (hwaes_capable()) {
    aes_hw_encrypt(in, out, key);
  } else if (vpaes_capable()) {
    vpaes_encrypt(in, out, key);
  } else {
    aes_nohw_encrypt(in, out, key);
  }
}
