int sum_vec(std::vector<int> &v) {
  int total = 0;
  for (size_t i = 0; i < v.size(); i++) {
    total += v[i];
  }
  return total;
}
