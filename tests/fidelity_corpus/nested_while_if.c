int find_pair(int *a, int n, int want) {
  int i = 0;
  while (i < n) {
    int j = i + 1;
    while (j < n) {
      if (a[i] + a[j] == want)
        return i;
      j = j + 1;
    }
    i = i + 1;
  }
  return -1;
}
