int pump(int n) {
  int got = 0;
  do {
    int r = fill(n);
    if (r < 0)
      break;
    got += r;
  } while (got < n);
  return got;
}
