int checked_div(int a, int b) {
  if (b == 0)
    throw std::runtime_error("div0");
  return a / b;
}
