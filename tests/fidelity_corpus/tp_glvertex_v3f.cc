inline void
glVertex (const IMATH_INTERNAL_NAMESPACE::V3f& v)
{
    glVertex3f (v.x, v.y, v.z);
}
