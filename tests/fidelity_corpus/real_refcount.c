void conn_put(struct conn *c) {
  if (!c)
    return;
  c->refs = c->refs - 1;
  if (c->refs == 0) {
    close_sock(c->fd);
    free(c);
  }
}
