int proto_step(struct pstate *ps, int ev) {
  int next = ps->state;
  switch (ps->state) {
  case 0:
    if (ev == 1)
      next = 1;
    break;
  case 1:
    if (ev == 2)
      next = 2;
    else if (ev == 0)
      next = 0;
    break;
  case 2:
    next = 0;
    break;
  }
  ps->state = next;
  return next;
}
