int retry_send(int fd, int n) {
  int tries = 0;
again:
  tries = tries + 1;
  if (send(fd, n) < 0 && tries < 3)
    goto again;
  return tries;
}
