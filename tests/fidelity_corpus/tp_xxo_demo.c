static PyObject *
Xxo_demo(XxoObject *self, PyObject *args)
{
    if (!PyArg_ParseTuple(args, ":demo"))
        return NULL;
    Py_INCREF(Py_None);
    return Py_None;
}
