unsigned long mix_bits(void *p, int n) {
  unsigned long base = (unsigned long)p;
  unsigned char lo = (unsigned char)(n & 0xff);
  return base ^ (unsigned long)lo;
}
