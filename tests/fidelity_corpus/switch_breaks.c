int classify(int x) {
  int kind = 0;
  switch (x) {
  case 0:
    kind = 1;
    break;
  case 1:
    kind = 2;
    break;
  default:
    kind = 3;
    break;
  }
  return kind;
}
