int tsum(int n, int flag) {
  int acc = 0;
  for (int i = flag ? 1 : 0; i < n; i++) {
    acc += i;
  }
  return acc;
}
