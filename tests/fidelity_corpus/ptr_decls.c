int swap_max(int *a, int *b) {
  int *hi = *a > *b ? a : b;
  int tmp = *hi;
  *hi = *a + *b;
  return tmp;
}
