static void aes_nohw_to_batch(AES_NOHW_BATCH *out, const uint8_t *in,
                              size_t num_blocks) {
  // Don't leave unused blocks uninitialized.
  memset(out, 0, sizeof(AES_NOHW_BATCH));
  assert(num_blocks <= AES_NOHW_BATCH_SIZE);
  for (size_t i = 0; i < num_blocks; i++) {
    aes_word_t block[AES_NOHW_BLOCK_WORDS];
    aes_nohw_compact_block(block, in + 16 * i);
    aes_nohw_batch_set(out, block, i);
  }

  aes_nohw_transpose(out);
}
