int take_head(char **list, char **out) {
  char *head = list[0];
  if (!head)
    return -1;
  *out = head;
  list[0] = 0;
  return 0;
}
