int wait_ready(int dev) {
  int spins = 0;
  for (;;) {
    spins++;
    if (poll_dev(dev))
      break;
  }
  return spins;
}
