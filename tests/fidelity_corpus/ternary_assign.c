int clampv(int x, int lo, int hi) {
  int y = x < lo ? lo : x;
  int z = y > hi ? hi : y;
  return z;
}
