int absval(int x) {
  return x < 0 ? -x : x;
}
