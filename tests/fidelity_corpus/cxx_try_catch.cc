int parse_num(const char *s) {
  int v = 0;
  try {
    v = std::stoi(s);
    v = v * 2;
  } catch (const std::exception &e) {
    log_err(e);
    v = -1;
  }
  return v;
}
