int rb_push(struct ring *r, int v) {
  int next = (r->head + 1) % r->cap;
  if (next == r->tail)
    return -1;
  r->data[r->head] = v;
  r->head = next;
  r->count = r->count + 1;
  return 0;
}
