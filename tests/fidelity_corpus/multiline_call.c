int submit(struct req *r) {
  int rc = enqueue(r->ring,
                   r->payload,
                   r->len);
  if (rc < 0)
    rc = retry_enqueue(r);
  return rc;
}
