int first_key(std::map<int, int> &m) {
  auto it = m.begin();
  if (it == m.end())
    return -1;
  return it->first;
}
