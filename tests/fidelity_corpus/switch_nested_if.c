int route(int op, int flag) {
  int out = 0;
  switch (op) {
  case 1:
    if (flag) {
      out = 10;
    } else {
      out = 20;
    }
    break;
  default:
    out = 30;
  }
  return out;
}
