int read_limit(void) {
  int lim = config::get_limit();
  if (lim < 0)
    lim = defaults::LIMIT;
  return lim;
}
