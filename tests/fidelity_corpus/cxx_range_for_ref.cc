int max_len(std::vector<std::string> &names) {
  int best = 0;
  for (const auto &nm : names) {
    if ((int)nm.size() > best)
      best = nm.size();
  }
  return best;
}
