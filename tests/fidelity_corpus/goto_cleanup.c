int init_pair(int **a, int **b) {
  int rc = -1;
  *a = malloc(4);
  if (!*a)
    goto out;
  *b = malloc(4);
  if (!*b)
    goto free_a;
  rc = 0;
  goto out;
free_a:
  free(*a);
out:
  return rc;
}
