int parse_hdr(char *p, int len) {
  if (!p)
    return -1;
  if (len < 4)
    return -2;
  int ver = p[0];
  if (ver != 2)
    return -3;
  return ver;
}
