int checksum(char *p, int n) {
  int sum = 0;
#if 0
  sum = legacy_sum(p, n);
#endif
  for (int i = 0; i < n; i++) {
    sum += p[i];
  }
  return sum;
}
