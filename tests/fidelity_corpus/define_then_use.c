#define MAXLEN 128
int bounded_len(char *s) {
  int n = strnlen(s, MAXLEN);
  if (n == MAXLEN)
    n = n - 1;
  return n;
}
