int table_get(struct tbl *t, int idx) {
  if (idx < 0 || idx >= t->n)
    return 0;
  int v = t->rows[idx];
  if (v < 0)
    v = 0;
  return v;
}
