int turnstile(int people) {
  int count = 0;
  count++;
  ++count;
  people--;
  count -= people;
  return count;
}
