int copy_name(char *dst, int cap, const char *src) {
  int n = strlen(src);
  if (n >= cap)
    n = cap - 1;
  memcpy(dst, src, n);
  dst[n] = 0;
  return n;
}
