static void aes_nohw_from_batch(uint8_t *out, size_t num_blocks,
                                const AES_NOHW_BATCH *batch) {
  AES_NOHW_BATCH copy = *batch;
  aes_nohw_transpose(&copy);

  assert(num_blocks <= AES_NOHW_BATCH_SIZE);
  for (size_t i = 0; i < num_blocks; i++) {
    aes_word_t block[AES_NOHW_BLOCK_WORDS];
    aes_nohw_batch_get(&copy, block, i);
    aes_nohw_uncompact_block(out + 16 * i, block);
  }
}
