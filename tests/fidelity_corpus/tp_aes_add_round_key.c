static void aes_nohw_add_round_key(AES_NOHW_BATCH *batch,
                                   const AES_NOHW_BATCH *key) {
  for (size_t i = 0; i < 8; i++) {
    batch->w[i] = aes_nohw_xor(batch->w[i], key->w[i]);
  }
}
