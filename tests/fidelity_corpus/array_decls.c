int histo8(int *v, int n) {
  int bins[8] = {0};
  for (int i = 0; i < n; i++) {
    bins[v[i] & 7]++;
  }
  return bins[0];
}
