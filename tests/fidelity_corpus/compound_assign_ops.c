int fold(int a, int b) {
  a += b;
  a <<= 2;
  a |= b & 7;
  a %= 97;
  return a;
}
