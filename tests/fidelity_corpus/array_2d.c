int corner_sum(int g[4][4]) {
  int acc = 0;
  acc = acc + g[0][0];
  acc = acc + g[3][3];
  return acc;
}
