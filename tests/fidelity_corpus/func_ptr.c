int apply2(int (*fn)(int), int x) {
  int once = fn(x);
  int twice = fn(once);
  return twice;
}
