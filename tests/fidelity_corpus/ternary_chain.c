int sign3(int x) {
  int s = x > 0 ? 1 : x < 0 ? -1 : 0;
  log_value(s);
  return s;
}
