int scan(int *buf, int n) {
  int hits = 0;
  for (int i = 0; i < n; i++) {
    if (buf[i] == 0)
      continue;
    if (buf[i] < 0)
      break;
    hits++;
  }
  return hits;
}
