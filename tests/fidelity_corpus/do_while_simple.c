int drain(int n) {
  int total = 0;
  do {
    total += step(n);
    n = n - 1;
  } while (n > 0);
  return total;
}
