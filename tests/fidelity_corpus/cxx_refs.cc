int bump(int &counter, const int &step) {
  counter = counter + step;
  if (counter > 100)
    counter = 0;
  return counter;
}
