int open_dev(char *path) {
  int flags = 0;
#ifdef O_CLOEXEC
  flags = flags | O_CLOEXEC;
#endif
  int fd = open(path, flags);
  return fd;
}
