int count_odd(int *v, int n) {
  int c = 0;
  int i = 0;
  while (i < n) {
    i = i + 1;
    if (v[i - 1] % 2 == 0)
      continue;
    c = c + 1;
  }
  return c;
}
