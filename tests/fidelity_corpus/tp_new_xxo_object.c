static XxoObject *
newXxoObject(PyObject *arg)
{
    XxoObject *self;
    self = PyObject_New(XxoObject, &Xxo_Type);
    if (self == NULL)
        return NULL;
    self->x_attr = NULL;
    return self;
}
