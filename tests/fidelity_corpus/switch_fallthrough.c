int accumulate(int x) {
  int acc = 0;
  switch (x) {
  case 2:
    acc += 2;
  case 1:
    acc += 1;
  case 0:
    acc += 10;
    break;
  default:
    acc = -1;
  }
  return acc;
}
