int sum_to(int n) {
  int acc = 0;
  for (int i = 0; i < n; i++) {
    acc += i;
  }
  return acc;
}
