static inline aes_word_t aes_nohw_and(aes_word_t a, aes_word_t b) {
  return _mm_and_si128(a, b);
}
