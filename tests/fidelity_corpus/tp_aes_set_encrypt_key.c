int AES_set_encrypt_key(const uint8_t *key, unsigned bits, AES_KEY *aeskey) {
  if (bits != 128 && bits != 192 && bits != 256) {
    return -2;
  }
  if (hwaes_capable()) {
    return aes_hw_set_encrypt_key(key, bits, aeskey);
  } else if (vpaes_capable()) {
    return vpaes_set_encrypt_key(key, bits, aeskey);
  } else {
    return aes_nohw_set_encrypt_key(key, bits, aeskey);
  }
}
