int guarded_read(int fd, char *buf, int n) {
  CHECK_FD(fd);
  int got = read(fd, buf, n);
  LOG_DEBUG("read bytes", got);
  return got;
}
