int bucket(int v) {
  int b = 0;
  if (v < 10) {
    b = 1;
  } else if (v < 100) {
    b = 2;
  } else if (v < 1000) {
    b = 3;
  } else {
    b = 4;
  }
  return b;
}
