int roundtrip(int n) {
  int *buf = new int[n];
  buf[0] = n;
  int head = buf[0];
  delete[] buf;
  return head;
}
