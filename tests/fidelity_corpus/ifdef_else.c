int page_size(void) {
  int sz = 0;
#ifdef SMALL_PAGES
  sz = 4096;
#else
  sz = 65536;
#endif
  return sz;
}
