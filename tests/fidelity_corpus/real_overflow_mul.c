int alloc_frames(struct dev *d, int count, int size) {
  if (count <= 0 || size <= 0)
    return -1;
  if (count > INT_MAX / size)
    return -1;
  d->frames = malloc(count * size);
  if (!d->frames)
    return -2;
  d->nframes = count;
  return 0;
}
