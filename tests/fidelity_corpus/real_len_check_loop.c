int parse_opts(unsigned char *p, int len) {
  int off = 0;
  int seen = 0;
  while (off + 2 <= len) {
    int t = p[off];
    int l = p[off + 1];
    if (off + 2 + l > len)
      return -1;
    if (t == 9)
      seen = seen + 1;
    off = off + 2 + l;
  }
  return seen;
}
