int matsum(int **m, int r, int c) {
  int total = 0;
  for (int i = 0; i < r; i++) {
    for (int j = 0; j < c; j++) {
      total += m[i][j];
    }
  }
  return total;
}
