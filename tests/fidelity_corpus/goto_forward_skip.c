int check(int v) {
  int st = 0;
  if (v < 0)
    goto done;
  st = normalize(v);
  st = st + 1;
done:
  return st;
}
