int pick(int mode, int a, int b) {
  int r = a;
  switch (mode) {
  case 4:
    r = b;
    break;
  case 7:
    r = a + b;
    break;
  }
  return r;
}
