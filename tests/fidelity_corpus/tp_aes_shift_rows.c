static void aes_nohw_shift_rows(AES_NOHW_BATCH *batch) {
  for (size_t i = 0; i < 8; i++) {
    aes_word_t row0 = aes_nohw_and(batch->w[i], AES_NOHW_ROW0_MASK);
    aes_word_t row1 = aes_nohw_and(batch->w[i], AES_NOHW_ROW1_MASK);
    aes_word_t row2 = aes_nohw_and(batch->w[i], AES_NOHW_ROW2_MASK);
    aes_word_t row3 = aes_nohw_and(batch->w[i], AES_NOHW_ROW3_MASK);
    row1 = aes_nohw_rotate_cols_right(row1, 1);
    row2 = aes_nohw_rotate_cols_right(row2, 2);
    row3 = aes_nohw_rotate_cols_right(row3, 3);
    batch->w[i] = aes_nohw_or(aes_nohw_or(row0, row1), aes_nohw_or(row2, row3));
  }
}
