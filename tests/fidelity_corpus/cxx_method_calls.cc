int drain_queue(Queue &q) {
  int n = 0;
  while (!q.empty()) {
    q.pop();
    n++;
  }
  return n;
}
