int read_be16(const unsigned char *p, int *out) {
  int hi = p[0];
  int lo = p[1];
  int v = (hi << 8) | lo;
  if (v > 32767)
    v = v - 65536;
  *out = v;
  return 0;
}
