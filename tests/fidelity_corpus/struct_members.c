int conn_cost(struct conn *c) {
  int rtt = c->peer->rtt;
  int depth = c->queue.depth;
  if (rtt > 100)
    depth = depth * 2;
  return rtt + depth;
}
