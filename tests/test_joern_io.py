"""Joern export import: reference-format JSON -> Cpg -> downstream parity."""

import json

import pytest

from deepdfa_tpu.frontend import ReachingDefinitions, decl_features, is_decl
from deepdfa_tpu.frontend.joern_io import load_joern_cpg


@pytest.fixture()
def joern_files(tmp_path):
    """Hand-built export for: int f(int a) { int x = a + 1; return x; }
    in joern's node/edge schema."""
    nodes = [
        {"id": 1000100, "_label": "METHOD", "name": "f", "code": "f",
         "lineNumber": 1, "order": 1},
        {"id": 1000101, "_label": "METHOD_PARAMETER_IN", "name": "a",
         "code": "int a", "lineNumber": 1, "order": 1, "typeFullName": "int"},
        {"id": 1000102, "_label": "LOCAL", "name": "x", "code": "int x",
         "lineNumber": 2, "order": 1, "typeFullName": "int"},
        {"id": 1000103, "_label": "CALL", "name": "<operator>.assignment",
         "code": "x = a + 1", "lineNumber": 2, "order": 1},
        {"id": 1000104, "_label": "IDENTIFIER", "name": "x", "code": "x",
         "lineNumber": 2, "order": 1, "typeFullName": "int"},
        {"id": 1000105, "_label": "CALL", "name": "<operator>.addition",
         "code": "a + 1", "lineNumber": 2, "order": 2},
        {"id": 1000106, "_label": "IDENTIFIER", "name": "a", "code": "a",
         "lineNumber": 2, "order": 1, "typeFullName": "int"},
        {"id": 1000107, "_label": "LITERAL", "name": "", "code": "1",
         "lineNumber": 2, "order": 2},
        {"id": 1000108, "_label": "RETURN", "name": "return",
         "code": "return x;", "lineNumber": 3, "order": 2},
        {"id": 1000109, "_label": "IDENTIFIER", "name": "x", "code": "x",
         "lineNumber": 3, "order": 1, "typeFullName": "int"},
        {"id": 1000110, "_label": "METHOD_RETURN", "name": "RET",
         "code": "RET", "lineNumber": 1, "order": 3},
        {"id": 1000111, "_label": "COMMENT", "name": "", "code": "// junk",
         "lineNumber": 1, "order": 0},
    ]
    # [innode, outnode, etype, dataflow] — outnode is the source
    edges = [
        [1000103, 1000100, "AST", ""],
        [1000104, 1000103, "AST", ""], [1000104, 1000103, "ARGUMENT", ""],
        [1000105, 1000103, "AST", ""], [1000105, 1000103, "ARGUMENT", ""],
        [1000106, 1000105, "AST", ""], [1000106, 1000105, "ARGUMENT", ""],
        [1000107, 1000105, "AST", ""], [1000107, 1000105, "ARGUMENT", ""],
        [1000108, 1000100, "AST", ""],
        [1000109, 1000108, "AST", ""], [1000109, 1000108, "ARGUMENT", ""],
        # CFG: METHOD -> assignment -> return -> METHOD_RETURN
        [1000103, 1000100, "CFG", ""],
        [1000108, 1000103, "CFG", ""],
        [1000110, 1000108, "CFG", ""],
        # filtered edge types
        [1000103, 1000100, "CONTAINS", ""],
        [1000108, 1000100, "DOMINATE", ""],
    ]
    p = tmp_path / "1.c"
    (tmp_path / "1.c.nodes.json").write_text(json.dumps(nodes))
    (tmp_path / "1.c.edges.json").write_text(json.dumps(edges))
    return p


def test_load_and_analyze(joern_files):
    cpg = load_joern_cpg(joern_files)
    assert cpg.method_name == "f"
    labels = [n.label for n in cpg.nodes]
    assert "COMMENT" not in labels
    # filtered edges are gone
    assert all(t not in ("CONTAINS", "DOMINATE") for _, _, t in cpg.edges)

    # reaching definitions over the imported CFG
    rd = ReachingDefinitions(cpg)
    assert {d.code for d in rd.domain} == {"x = a + 1"}
    in_sets = rd.solve()
    ret = next(n.id for n in cpg.nodes if n.label == "RETURN")
    assert {d.code for d in in_sets[ret]} == {"x = a + 1"}

    # abstract-dataflow features from the imported AST
    decls = [n.id for n in cpg.nodes if is_decl(cpg, n.id)]
    assert len(decls) == 1
    fields = dict(decl_features(cpg, decls[0]))
    assert fields["datatype"] == "int"
    assert fields["literal"] == "1"
    assert fields["operator"] == "addition"


def test_load_joern_dataflow_roundtrip(tmp_path):
    import json

    from deepdfa_tpu.frontend.joern_io import load_joern_dataflow

    payload = {
        "f": {"in": {"7": [0, 2], "9": []}, "out": {"7": [1]}},
        "g": {"in": {}, "out": {}},
    }
    p = tmp_path / "x.c.dataflow.json"
    p.write_text(json.dumps(payload))
    sol = load_joern_dataflow(p)
    assert sol["f"]["in"][7] == frozenset({0, 2})
    assert sol["f"]["in"][9] == frozenset()
    assert sol["f"]["out"][7] == frozenset({1})
    assert sol["g"] == {"in": {}, "out": {}}


def test_load_joern_dataflow_tolerates_tostring_keys(tmp_path):
    import json

    from deepdfa_tpu.frontend.joern_io import load_joern_dataflow

    p = tmp_path / "y.c.dataflow.json"
    p.write_text(json.dumps(
        {"f": {"in": {"Call[label=CALL; id=42]": [1]}, "out": {}}}
    ))
    assert load_joern_dataflow(p)["f"]["in"][42] == frozenset({1})
