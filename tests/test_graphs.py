import numpy as np
import pytest

from deepdfa_tpu.graphs import (
    BudgetExceeded,
    GraphSpec,
    GraphStore,
    bucket_batches,
    pack,
    pack_shards,
)


def make_graph(rng, gid, n, e, label=0.0):
    return GraphSpec(
        graph_id=gid,
        node_feats=rng.integers(0, 100, (n, 4)).astype(np.int32),
        node_vuln=rng.integers(0, 2, (n,)).astype(np.int32),
        edge_src=rng.integers(0, n, (e,)).astype(np.int32),
        edge_dst=rng.integers(0, n, (e,)).astype(np.int32),
        label=label,
    )


def test_pack_shapes_and_masks(rng):
    gs = [make_graph(rng, i, 5 + i, 8, label=float(i % 2)) for i in range(3)]
    b = pack(gs, num_graphs=4, node_budget=32, edge_budget=64)
    assert b.node_feats.shape == (32, 4)
    assert b.edge_src.shape == (64,)
    assert b.graph_label.shape == (4,)
    n_tot = sum(g.num_nodes for g in gs)
    e_tot = sum(g.num_edges for g in gs) + n_tot  # self loops
    assert b.node_mask.sum() == n_tot
    assert b.edge_mask.sum() == e_tot
    assert b.graph_mask.tolist() == [True, True, True, False]
    # padding nodes map to the dummy segment
    assert (np.asarray(b.node_graph)[n_tot:] == 4).all()
    # per-node segment ids count each graph's nodes
    for i, g in enumerate(gs):
        assert (np.asarray(b.node_graph) == i).sum() == g.num_nodes
    # self loops present: last e_tot section has src == dst
    src, dst, em = map(np.asarray, (b.edge_src, b.edge_dst, b.edge_mask))
    loops = (src == dst) & em
    assert loops.sum() >= n_tot


def test_pack_budget_errors(rng):
    gs = [make_graph(rng, 0, 100, 10)]
    with pytest.raises(BudgetExceeded):
        pack(gs, num_graphs=1, node_budget=50, edge_budget=500)
    with pytest.raises(BudgetExceeded):
        pack(gs, num_graphs=1, node_budget=500, edge_budget=50)
    # graph-count budget too, not just node/edge budgets
    gs2 = [make_graph(rng, i, 4, 4) for i in range(3)]
    with pytest.raises(BudgetExceeded):
        pack(gs2, num_graphs=2, node_budget=500, edge_budget=500)
    # edge budget accounts for the implied self loops
    gs3 = [make_graph(rng, 0, 40, 30)]
    with pytest.raises(BudgetExceeded):
        pack(gs3, num_graphs=1, node_budget=64, edge_budget=60)
    assert pack(
        gs3, num_graphs=1, node_budget=64, edge_budget=60,
        add_self_loops=False,
    ).edge_mask.sum() == 30


def test_bucket_batches_covers_all(rng):
    gs = [make_graph(rng, i, int(rng.integers(3, 40)), 10) for i in range(50)]
    batches = list(
        bucket_batches(gs, num_graphs=8, node_budget=128, edge_budget=512)
    )
    ids = [i for b in batches for i in np.asarray(b.graph_ids).tolist() if i >= 0]
    assert sorted(ids) == list(range(50))
    for b in batches:
        assert b.node_feats.shape == (128, 4)


def test_bucket_batches_drops_oversized(rng):
    gs = [make_graph(rng, 0, 1000, 10), make_graph(rng, 1, 5, 4)]
    batches = list(
        bucket_batches(gs, num_graphs=4, node_budget=64, edge_budget=256)
    )
    ids = [i for b in batches for i in np.asarray(b.graph_ids).tolist() if i >= 0]
    assert ids == [1]
    with pytest.raises(BudgetExceeded):
        list(
            bucket_batches(
                gs, num_graphs=4, node_budget=64, edge_budget=256,
                drop_oversized=False,
            )
        )


def test_shard_bucket_batches_covers_all_heavy_tail(rng):
    """Eval semantics: with oversized='singleton' EVERY graph is scored,
    including ones over the per-shard budgets; overflow batches use pow2
    budgets so extra XLA signatures stay bounded."""
    from deepdfa_tpu.graphs import shard_bucket_batches

    gs = [make_graph(rng, i, int(rng.integers(3, 50)), 10) for i in range(40)]
    gs.append(make_graph(rng, 40, 300, 60))  # > node_budget
    gs.append(make_graph(rng, 41, 10, 600))  # > edge_budget
    gs.append(make_graph(rng, 42, 310, 60))  # same pow2 signature as 40
    stats: dict = {}
    batches = list(
        shard_bucket_batches(
            gs, num_shards=4, num_graphs=8, node_budget=128, edge_budget=512,
            oversized="singleton", stats=stats,
        )
    )
    ids = [
        i for b in batches for i in np.asarray(b.graph_ids).flatten().tolist()
        if i >= 0
    ]
    assert sorted(ids) == list(range(43))
    assert stats["oversized"] == 3
    assert stats["dropped"] == 0
    # 40 and 42 round to the same (512-node) signature -> share one batch
    assert stats["overflow_signatures"] == 2
    for b in batches:
        nb = b.node_feats.shape[-2]
        assert nb == 128 or (nb & (nb - 1)) == 0  # base or pow2 overflow
        # budgets respected per shard
        for s in range(b.node_mask.shape[0]):
            assert np.asarray(b.node_mask[s]).sum() <= nb


def test_shard_bucket_batches_drop_and_raise(rng):
    from deepdfa_tpu.graphs import shard_bucket_batches

    gs = [make_graph(rng, 0, 300, 10), make_graph(rng, 1, 5, 4)]
    stats: dict = {}
    batches = list(
        shard_bucket_batches(
            gs, num_shards=2, num_graphs=4, node_budget=64, edge_budget=256,
            oversized="drop", stats=stats,
        )
    )
    ids = [
        i for b in batches for i in np.asarray(b.graph_ids).flatten().tolist()
        if i >= 0
    ]
    assert ids == [1] and stats["dropped"] == 1
    with pytest.raises(BudgetExceeded):
        list(
            shard_bucket_batches(
                gs, num_shards=2, num_graphs=4, node_budget=64,
                edge_budget=256, oversized="raise",
            )
        )


def test_shard_bucket_batches_rejects_unknown_oversized(rng):
    from deepdfa_tpu.graphs import shard_bucket_batches

    gs = [make_graph(rng, 0, 5, 4)]
    with pytest.raises(ValueError, match="oversized"):
        list(
            shard_bucket_batches(
                gs, num_shards=1, num_graphs=4, node_budget=64,
                edge_budget=256, oversized="truncate",
            )
        )


def test_plan_then_pack_matches_fused_batcher(rng):
    """The plan/pack split (BatchPlan + pack_plan) is what the process
    pool and the packed-batch cache distribute; replaying the plans
    through pack_plan must reproduce shard_bucket_batches exactly."""
    import jax

    from deepdfa_tpu.graphs import (
        pack_plan,
        plan_shard_bucket_batches,
        shard_bucket_batches,
    )

    gs = [make_graph(rng, i, int(rng.integers(3, 50)), 10) for i in range(30)]
    gs.append(make_graph(rng, 30, 300, 10))  # singleton overflow
    kw = dict(num_shards=2, num_graphs=4, node_budget=128, edge_budget=512)
    fused = list(shard_bucket_batches(gs, oversized="singleton", **kw))
    plans = list(plan_shard_bucket_batches(gs, oversized="singleton", **kw))
    assert len(plans) == len(fused)
    for plan, want in zip(plans, fused):
        got = pack_plan(gs, plan)
        for a, b in zip(jax.tree.leaves(got), jax.tree.leaves(want)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_pack_shards_stacks_and_balances(rng):
    gs = [make_graph(rng, i, int(rng.integers(3, 30)), 8) for i in range(16)]
    b = pack_shards(gs, num_shards=4, num_graphs=8, node_budget=128, edge_budget=512)
    assert b.node_feats.shape == (4, 128, 4)
    assert b.graph_label.shape == (4, 8)
    ids = np.asarray(b.graph_ids)
    assert sorted(i for i in ids.flatten().tolist() if i >= 0) == list(range(16))
    # edges in each shard index into that shard's local node space
    assert np.asarray(b.edge_src).max() < 128


def test_store_roundtrip(tmp_path, rng):
    gs = [make_graph(rng, i, int(rng.integers(1, 20)), 6, float(i % 2)) for i in range(25)]
    store = GraphStore(tmp_path / "graphs")
    nshards = store.write(gs, shard_size=10)
    assert nshards == 3
    back = store.load_all()
    assert set(back) == set(range(25))
    for g in gs:
        g2 = back[g.graph_id]
        np.testing.assert_array_equal(g.node_feats, g2.node_feats)
        np.testing.assert_array_equal(g.edge_src, g2.edge_src)
        assert g.label == g2.label


def test_store_uncompressed_mmap_roundtrip(tmp_path, rng):
    """compressed=False shards load as read-only page-cache-backed views
    (mmap=True) with content identical to the compressed path."""
    gs = [make_graph(rng, i, int(rng.integers(1, 20)), 6, float(i % 2)) for i in range(12)]
    store = GraphStore(tmp_path / "raw")
    store.write(gs, shard_size=5, compressed=False)
    back = store.load_all(mmap=True)
    assert set(back) == set(range(12))
    for g in gs:
        g2 = back[g.graph_id]
        np.testing.assert_array_equal(g.node_feats, g2.node_feats)
        np.testing.assert_array_equal(g.node_vuln, g2.node_vuln)
        np.testing.assert_array_equal(g.edge_src, g2.edge_src)
        np.testing.assert_array_equal(g.edge_dst, g2.edge_dst)
        assert g.label == g2.label
        assert not g2.node_feats.flags.writeable  # view, not a copy


def test_store_mmap_rejects_compressed_shards(tmp_path, rng):
    gs = [make_graph(rng, 0, 5, 4)]
    store = GraphStore(tmp_path / "cmp")
    store.write(gs, compressed=True)
    with pytest.raises(ValueError, match="deflated"):
        store.load_all(mmap=True)


def test_store_digest_tracks_shards(tmp_path, rng):
    gs = [make_graph(rng, i, 5, 4) for i in range(4)]
    store = GraphStore(tmp_path / "d")
    store.write(gs[:2], shard_size=2)
    base = store.digest()
    assert base == store.digest()  # stable across calls
    store.write(gs[2:], shard_size=2, tag="extra")
    assert store.digest() != base  # any added shard invalidates


def test_batch_is_pytree(rng):
    import jax

    gs = [make_graph(rng, i, 5, 5) for i in range(2)]
    b = pack(gs, num_graphs=2, node_budget=16, edge_budget=32)
    leaves = jax.tree.leaves(b)
    assert len(leaves) == 10
    # static field survives tree.map
    b2 = jax.tree.map(lambda x: x, b)
    assert b2.num_graphs == 2


def make_typed_graph(rng, gid, n, e, n_etypes=3, label=0.0):
    g = make_graph(rng, gid, n, e, label=label)
    import dataclasses

    return dataclasses.replace(
        g, edge_type=rng.integers(0, n_etypes, (e,)).astype(np.int32)
    )


def test_pack_edge_types_follow_dst_sort(rng):
    gs = [make_typed_graph(rng, i, 6, 10) for i in range(2)]
    b = pack(gs, num_graphs=2, node_budget=16, edge_budget=48)
    assert b.edge_type is not None and b.edge_type.shape == (48,)
    # per-edge (src, dst, type) multisets survive packing for each graph
    for gi, g in enumerate(gs):
        off = sum(x.num_nodes for x in gs[:gi])
        want = sorted(
            zip(g.edge_src + off, g.edge_dst + off, g.edge_type)
        )
        rows = [
            (int(s), int(d), int(t))
            for s, d, t, m, seg in zip(
                b.edge_src, b.edge_dst, b.edge_type, b.edge_mask,
                b.node_graph[b.edge_dst],
            )
            if m and seg == gi and int(s) != int(d)
        ]
        # self loops (src == dst, type 0) were added on top; drop
        # same-node real edges from `want` too for a fair comparison
        want = [(int(s), int(d), int(t)) for s, d, t in want if s != d]
        assert sorted(rows) == sorted(want)
    # self-loop and padding slots carry type 0
    assert (np.asarray(b.edge_type)[~np.asarray(b.edge_mask)] == 0).all()


def test_pack_mixed_edge_type_presence_raises(rng):
    gs = [make_graph(rng, 0, 4, 6), make_typed_graph(rng, 1, 4, 6)]
    with pytest.raises(ValueError, match="mixed edge_type"):
        pack(gs, num_graphs=2, node_budget=16, edge_budget=32)


def test_pack_shards_edge_types_uniform_structure(rng):
    # an empty shard still gets an edge_type array when siblings have one
    gs = [make_typed_graph(rng, i, 4, 6) for i in range(2)]
    b = pack_shards(gs, num_shards=4, num_graphs=1, node_budget=8,
                    edge_budget=16)
    assert b.edge_type is not None and b.edge_type.shape == (4, 16)


def test_store_roundtrip_edge_types(tmp_path, rng):
    gs = [make_typed_graph(rng, i, 5, 8) for i in range(3)]
    store = GraphStore(tmp_path / "s")
    store.write(gs)
    back = store.load_all()
    assert set(back) == {0, 1, 2}
    for g in gs:
        np.testing.assert_array_equal(back[g.graph_id].edge_type, g.edge_type)
