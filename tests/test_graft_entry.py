"""The driver contract: entry() compiles single-chip, dryrun_multichip runs."""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

import numpy as np


def test_entry_jits():
    import jax

    import __graft_entry__ as ge

    fn, args = ge.entry()
    out = jax.jit(fn)(*args)
    out = np.asarray(jax.device_get(out))
    assert out.shape == (32,)
    assert np.isfinite(out).all()


def test_dryrun_multichip_8():
    import __graft_entry__ as ge

    ge.dryrun_multichip(8)  # asserts internally
