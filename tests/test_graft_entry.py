"""The driver contract: entry() compiles single-chip, dryrun_multichip runs.

dryrun_multichip runs in a FRESH subprocess, exactly as the driver
invokes it: it compiles a dozen sharded training programs, and running it
at the tail of a long-lived pytest process has produced an XLA CPU
`Fatal Python error: Aborted` from accumulated in-process executable
state that no fresh-process invocation reproduces. The subprocess is the
contract under test.
"""

import pytest

import os
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

import numpy as np

# heavy compiles / subprocesses: excluded from the default fast lane
# (pyproject addopts); run via `pytest -m slow` or `pytest -m ""`
pytestmark = pytest.mark.slow


def test_entry_jits():
    import jax

    import __graft_entry__ as ge

    fn, args = ge.entry()
    out = jax.jit(fn)(*args)
    out = np.asarray(jax.device_get(out))
    assert out.shape == (32,)
    assert np.isfinite(out).all()


def test_dryrun_multichip_8():
    env = dict(os.environ, DEEPDFA_TPU_PLATFORM="cpu:8")
    res = subprocess.run(
        [
            sys.executable,
            "-c",
            "import __graft_entry__ as ge; ge.dryrun_multichip(8)",
        ],
        cwd=REPO,
        env=env,
        capture_output=True,
        text=True,
        timeout=1500,
    )
    assert res.returncode == 0, res.stderr[-2000:] + res.stdout[-1000:]
    # every composition printed its line
    for tag in (
        "GGNN dp train loss",
        "combined dp2xtp2xsp2",
        "t5-combined dp2xtp2xsp2",
        "combined dp2xtp2xpp2",
        "dp1xtp2xsp2xpp2",
        "t5-combined dp2xpp2",
        "combined dp2xtp2xep2",
        "pp2 GPipe encoder parity",
        "ep2 MoE parity",
    ):
        assert tag in res.stdout, (tag, res.stdout)
