"""Corpus v2 hardness properties (data/synthetic.py:generate_v2,
eval/trivial_baseline.py — VERDICT r3 item 4)."""

import numpy as np
import pytest

from deepdfa_tpu.data.synthetic import V2_FAMILIES, generate_v2

ORDER_FAMILIES = (
    "clamp_order", "null_check_order", "use_after_free", "index_clamp_order"
)


def test_order_families_share_statement_multiset():
    """The defining v2 property: an order family's buggy and fixed forms
    are permutations of the SAME lines, so any bag-of-tokens/features
    view of the two is identical — only flow order separates them."""
    for name in ORDER_FAMILIES:
        fn = V2_FAMILIES[name]
        assert sorted(fn(True)) == sorted(fn(False)), name
        assert fn(True) != fn(False), name  # but the order differs


def test_generate_v2_lookalikes_and_noise():
    synth = generate_v2(
        400, vuln_rate=0.3, seed=3, lookalike_rate=0.6, label_noise=0.05
    )
    fams = {s.family for s in synth}
    assert any(f.startswith("lookalike:") for f in fams)
    n_noisy = sum(s.noisy for s in synth)
    assert 2 <= n_noisy <= 50  # ~5% of 400
    # noisy "benign" examples carry no line labels
    for s in synth:
        if s.label == 0:
            assert not s.vuln_lines, s.id
    # lookalikes are genuinely unchanged functions
    for s in synth:
        if s.family.startswith("lookalike:") and not s.noisy:
            assert s.before == s.after and s.label == 0


def test_order_pair_has_identical_subkey_histograms():
    """Through the REAL pipeline: a buggy instance and its benign twin
    (same filler, same placement) produce identical subkey histograms —
    the trivial baseline literally cannot tell them apart."""
    from deepdfa_tpu.data import build_dataset, to_examples
    from deepdfa_tpu.data.synthetic import SynthExample
    from deepdfa_tpu.eval.trivial_baseline import subkey_histograms

    fn = V2_FAMILIES["clamp_order"]
    decls = ["    char buf[64];", "    int i = 0;", "    int total = 0;"]
    mk = lambda lines, gid: (
        f"int fn_{gid}(char *src, int len) {{\n"
        + "\n".join(decls + lines)
        + "\n    return total;\n}\n"
    )
    pair = [
        SynthExample(id=0, before=mk(fn(True), 0), after=mk(fn(False), 0),
                     label=1, vuln_lines=frozenset({4})),
        SynthExample(id=1, before=mk(fn(False), 1), after=mk(fn(False), 1),
                     label=0, vuln_lines=frozenset()),
    ]
    specs, _ = build_dataset(
        to_examples(pair), train_ids=[0, 1], limit_all=64, limit_subkeys=64
    )
    X = subkey_histograms(specs, input_dim=66)
    np.testing.assert_array_equal(X[0], X[1])


def test_logistic_control_learns_separable_and_fails_identical():
    from deepdfa_tpu.eval.trivial_baseline import (
        binary_metrics,
        predict_proba,
        train_logistic,
    )

    rng = np.random.default_rng(0)
    # separable: feature 3 decides the label
    X = rng.normal(size=(200, 8))
    y = (X[:, 3] > 0).astype(np.int64)
    w, b = train_logistic(X, y)
    m = binary_metrics(predict_proba(X, w, b), y)
    assert m["f1"] > 0.95, m
    # identical feature rows with mixed labels: no better than chance
    X2 = np.ones((100, 8))
    y2 = (np.arange(100) % 2).astype(np.int64)
    w2, b2 = train_logistic(X2, y2)
    m2 = binary_metrics(predict_proba(X2, w2, b2), y2)
    assert m2["acc"] <= 0.6, m2


@pytest.mark.slow
def test_order_family_ggnn_beats_counting_via_dataflow_edges():
    """The round-4 effectiveness claim, pinned end to end: on a pure
    ORDER-family corpus (identical token/feature multisets, only flow
    order differs) the counting baseline is near chance while a GGNN
    over cfg+dep graphs (typed data-dependence edges — the reference's
    gtype/rdg axis) separates the classes. This is paper Table 3's
    'dataflow, not tokens' dynamic in miniature."""
    import jax

    from deepdfa_tpu.core import Config, MeshConfig, config as config_mod
    from deepdfa_tpu.data import build_dataset, to_examples
    from deepdfa_tpu.eval.trivial_baseline import logistic_control
    from deepdfa_tpu.graphs import shard_bucket_batches
    from deepdfa_tpu.models import DeepDFA
    from deepdfa_tpu.parallel import make_mesh
    from deepdfa_tpu.train import GraphTrainer, undersample_epoch

    n = 600
    synth = generate_v2(
        n, vuln_rate=0.5, seed=2, lookalike_rate=1.0, label_noise=0.0,
        families=["index_clamp_order"], min_stmts=1, max_stmts=4,
    )
    ids = np.random.default_rng(0).permutation(n)
    tr = set(ids[:480].tolist())
    te = set(ids[480:].tolist())
    specs, _ = build_dataset(
        to_examples(synth), train_ids=tr, limit_all=64, limit_subkeys=64,
        gtype="cfg+dep",
    )
    trs = [s for s in specs if s.graph_id in tr]
    tes = [s for s in specs if s.graph_id in te]

    control = logistic_control(trs, {"test": tes}, input_dim=66)
    assert control["test"]["f1"] <= 0.75, control  # counting ~ chance

    cfg = config_mod.apply_overrides(
        Config(),
        ["model.hidden_dim=32", "model.n_etypes=3", "data.gtype=cfg+dep"],
    )
    model = DeepDFA.from_config(cfg.model, input_dim=66)
    # single-device mesh: the harness forces 8 virtual CPU devices, and
    # the single-shard batches here must not be dp-8 sharded
    mesh = make_mesh(MeshConfig(dp=1), devices=jax.devices()[:1])
    trainer = GraphTrainer(model, cfg, mesh=mesh)

    def bf(ss):
        return list(
            shard_bucket_batches(ss, 1, 256, 16384, 65536, oversized="raise")
        )

    state = trainer.init_state(bf(trs)[0], seed=0)
    labels = np.array([s.label for s in trs])
    best = 0.0
    for ep in range(8):
        idx = undersample_epoch(labels, ep, seed=0)
        state = trainer.fit(
            state, lambda _e, i=idx: bf([trs[j] for j in i]), max_epochs=1
        )
        m, _ = trainer.evaluate(state, bf(tes))
        best = max(best, m["f1"])
        if best >= 0.85:
            break
    assert best >= 0.85, best
    assert best - control["test"]["f1"] >= 0.15, (best, control)

