"""CLI coverage for the generation trainers (train-gen / train-multi-gen).

Drives the installed console entry in a subprocess on tiny synthetic
task files — the same surface the reference exercises through
run_gen.py / run_multi_gen.py argparse mains (CodeT5/run_gen.py:1,
run_multi_gen.py:178)."""

import json
import os
import subprocess
import sys

import pytest

# heavy compiles / subprocesses: excluded from the default fast lane
# (pyproject addopts); run via `pytest -m slow` or `pytest -m ""`
pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def task_files(tmp_path_factory):
    root = tmp_path_factory.mktemp("gen_tasks")
    files = {}
    for name, n in [("a", 8), ("b", 6)]:
        for split in ("train", "dev"):
            p = root / f"{name}.{split}.jsonl"
            with p.open("w") as f:
                for i in range(n):
                    f.write(json.dumps({
                        "idx": i,
                        "code_tokens": ["int", "f", "(", ")", "{",
                                        f"return {i};", "}"],
                        "docstring_tokens": ["returns", str(i)],
                    }) + "\n")
            files[f"{name}.{split}"] = str(p)
    return files


def _run(args, storage, timeout=420):
    from tests.conftest import run_cli

    return run_cli(storage, *args, timeout=timeout).stdout


TINY = [
    "--tiny", "--batch-size", "4", "--vocab-size", "128",
    "--max-source-length", "32", "--max-target-length", "16",
]


def test_train_gen_cli(task_files, tmp_path):
    out = _run(
        ["train-gen", "--task", "summarize",
         "--train-file", task_files["a.train"],
         "--dev-file", task_files["a.dev"],
         *TINY, "run_name=cli-gen", "train.max_epochs=2"],
        tmp_path,
    )
    assert "val_ppl" in out
    best = tmp_path / "runs" / "cli-gen" / "checkpoints-gen" / "best"
    assert best.exists()


def test_train_gen_cli_test_outputs(task_files, tmp_path):
    """--test-file decodes from the saved best-ppl params and writes the
    run_gen.py output/gold prediction files plus a BLEU/EM json line."""
    out = _run(
        ["train-gen", "--task", "summarize",
         "--train-file", task_files["a.train"],
         "--dev-file", task_files["a.dev"],
         "--test-file", task_files["a.dev"],
         "--beam-size", "2",
         *TINY, "run_name=cli-gen-test", "train.max_epochs=2"],
        tmp_path, timeout=600,
    )
    scores = json.loads(out.strip().splitlines()[-1])
    assert {"test_em", "test_bleu"} <= set(scores)
    res = tmp_path / "runs" / "cli-gen-test" / "results"
    hyp = (res / "test_best-ppl.output").read_text().strip().splitlines()
    gold = (res / "test_best-ppl.gold").read_text().strip().splitlines()
    assert len(hyp) == len(gold) == 8  # a.dev has 8 examples
    # reference file shape: "<idx>\t<space-separated tokens>"
    assert all("\t" in line for line in hyp + gold)


def test_train_multi_gen_cli(task_files, tmp_path):
    out = _run(
        ["train-multi-gen",
         "--task-spec",
         f"summarize_a={task_files['a.train']}:{task_files['a.dev']}",
         "--task-spec", f"summarize_b={task_files['b.train']}",
         "--max-steps", "8", "--eval-every", "4",
         *TINY, "run_name=cli-mgen"],
        tmp_path,
    )
    summary = json.loads(out.strip().splitlines()[-1])
    assert set(summary["tasks"]) == {"summarize_a", "summarize_b"}
    # the dev-evaluated task records a finite best ppl; the dev-less one
    # records null (never evaluated), not Infinity
    assert summary["tasks"]["summarize_a"]["best_ppl"] is not None
    assert summary["tasks"]["summarize_b"]["best_ppl"] is None
    best = (
        tmp_path / "runs" / "cli-mgen" / "checkpoints-multi-summarize_a"
        / "best"
    )
    assert best.exists()
