"""CLI prepare/extract round-trip: artifacts, missing-ids manifest, gtype."""

import numpy as np
import pytest

# heavy compiles / subprocesses: excluded from the default fast lane
# (pyproject addopts); run via `pytest -m slow` or `pytest -m ""`
pytestmark = pytest.mark.slow


@pytest.fixture
def storage(tmp_path, monkeypatch):
    monkeypatch.setenv("DEEPDFA_TPU_STORAGE", str(tmp_path))
    return tmp_path


def test_prepare_extract_writes_missing_ids(storage):
    from deepdfa_tpu.cli.main import main
    from deepdfa_tpu.core import paths

    main(["prepare", "--source", "synthetic", "--n-examples", "24"])
    out_dir = paths.processed_dir("bigvul")
    # poison one example so extraction fails for it
    import pickle

    with (out_dir / "examples.pkl").open("rb") as f:
        examples = pickle.load(f)
    import dataclasses

    examples[3] = dataclasses.replace(examples[3], code="%%% not C at all")
    with (out_dir / "examples.pkl").open("wb") as f:
        pickle.dump(examples, f)

    main(["extract", "data.feat.limit_all=64", "data.feat.limit_subkeys=64"])
    stores = [p for p in out_dir.iterdir() if p.is_dir()]
    assert len(stores) == 1
    manifest = stores[0] / "missing_ids.txt"
    assert manifest.exists()
    missing = [int(x) for x in manifest.read_text().split()]
    assert examples[3].id in missing


def test_extract_cfg_dep_gtype_separate_store(storage):
    from deepdfa_tpu.cli.main import main
    from deepdfa_tpu.core import paths
    from deepdfa_tpu.graphs import GraphStore

    main(["prepare", "--source", "synthetic", "--n-examples", "12"])
    main([
        "extract", "data.feat.limit_all=64", "data.feat.limit_subkeys=64",
        "data.gtype=cfg+dep", "model.n_etypes=3",
    ])
    out_dir = paths.processed_dir("bigvul")
    dirs = [p.name for p in out_dir.iterdir() if p.is_dir()]
    typed_dirs = [d for d in dirs if d.endswith("_gtype_cfg+dep")]
    assert typed_dirs, dirs
    specs = GraphStore(out_dir / typed_dirs[0]).load_all()
    assert specs and all(s.edge_type is not None for s in specs.values())
    assert any(
        set(np.asarray(s.edge_type).tolist()) - {0} for s in specs.values()
    )


def test_gtype_n_etypes_mismatch_rejected(storage):
    from deepdfa_tpu.cli.main import main

    with pytest.raises(ValueError, match="n_etypes"):
        main(["prepare", "--source", "synthetic", "--n-examples", "4",
              "data.gtype=cfg+dep"])


def test_combined_rejects_typed_gtype(storage):
    from deepdfa_tpu.cli.main import main

    with pytest.raises(SystemExit, match="gtype=cfg only"):
        main(["train-combined", "data.gtype=cfg+dep", "model.n_etypes=3"])


def test_prepare_export_codet5(storage):
    """--export-codet5 writes per-split defect jsonl that the CodeT5
    defect reader round-trips (the unixcoder export hook,
    unixcoder/linevul_main.py:1400-1424)."""
    import json

    from deepdfa_tpu.cli.main import main
    from deepdfa_tpu.core import paths
    from deepdfa_tpu.data.gen_data import read_defect_gen_examples

    main(["prepare", "--source", "synthetic", "--n-examples", "20",
          "--export-codet5"])
    c5 = paths.processed_dir("bigvul") / "codet5"
    counts = {}
    for fname in ("train", "valid", "test"):
        p = c5 / f"{fname}.jsonl"
        assert p.exists()
        rows = [json.loads(line) for line in p.open()]
        counts[fname] = len(rows)
        assert all(set(r) == {"idx", "code", "target"} for r in rows)
    assert sum(counts.values()) == 20 and counts["train"] > 0
    ex = read_defect_gen_examples(c5 / "train.jsonl")
    assert len(ex) == counts["train"]
    assert all(e.target in ("true", "false") for e in ex)


def test_removed_config_key_tolerated():
    from deepdfa_tpu.core import config as config_mod

    cfg = config_mod.from_dict({"model": {"use_pallas": False, "hidden_dim": 16}})
    assert cfg.model.hidden_dim == 16
    with pytest.raises(KeyError, match="unknown config key"):
        config_mod.from_dict({"model": {"definitely_not_a_key": 1}})


def _cli(storage, *argv, expect_rc=0, expect_err=None):
    from tests.conftest import run_cli

    return run_cli(storage, *argv, expect_rc=expect_rc, expect_err=expect_err)


def test_test_command_restores_run_config(storage):
    """`test run_name=X` must rebuild the model from the RUN's saved
    config.json (train writes it), not CLI defaults — a run trained with
    non-default dims previously crashed with a param shape error
    (found by a corpus-scale pipeline drive in round 3)."""
    _cli(storage, "prepare", "--source", "synthetic", "--n-examples", "24")
    _cli(storage, "extract", "data.feat.limit_all=64",
         "data.feat.limit_subkeys=64")
    # warmup_frac in the saved config also regression-tests cmd_test's
    # eval-only optimizer construction (total_steps=1): a run trained with
    # a warmup schedule previously crashed `test` with
    # "warmup_frac requires total_steps"
    _cli(storage, "train", "run_name=cfg_roundtrip", "train.max_epochs=1",
         "model.hidden_dim=16", "train.optim.warmup_frac=0.2",
         "data.feat.limit_all=64", "data.feat.limit_subkeys=64")
    # no model/data overrides here: the saved run config must supply them
    _cli(storage, "test", "run_name=cfg_roundtrip")
    # and explicit overrides still win over the saved config: forcing a
    # different width must reach the model and fail at checkpoint
    # restore with a SHAPE error (not be silently ignored)
    _cli(storage, "test", "run_name=cfg_roundtrip", "model.hidden_dim=8",
         expect_rc=1, expect_err="ScopeParamShapeError")


def test_train_combined_with_warmup_schedule(storage):
    """The flagship combined config uses 20%-linear-warmup AdamW
    (configs/bigvul_combined.json, reference linevul_main.py:150-162);
    cmd_train_combined must derive total_steps for the schedule — it
    previously crashed with 'warmup_frac requires total_steps' (found by
    driving scripts/performance_evaluation.sh)."""
    _cli(storage, "prepare", "--source", "synthetic", "--n-examples", "24")
    _cli(storage, "extract", "data.feat.limit_all=64",
         "data.feat.limit_subkeys=64")
    _cli(storage, "train-combined", "--max-length", "48",
         "run_name=warmup_check", "train.max_epochs=1",
         "train.optim.warmup_frac=0.2",
         "data.feat.limit_all=64", "data.feat.limit_subkeys=64")


def test_cli_subprocess_normalizes_inherited_device_flags():
    """Regression for the round-3 red test: this pytest process exports
    ``--xla_force_host_platform_device_count=8`` into its environment, and
    CLI subprocesses inherit it. Plain ``DEEPDFA_TPU_PLATFORM=cpu`` must
    normalize the device count to 1 — otherwise ``MeshConfig.dp=-1`` builds
    an 8-way mesh whose in-process CPU collectives starve past XLA's 40s
    rendezvous termination on a 1-core host and SIGABRT the trainer
    (xla rendezvous.cc "Expected 8 threads to join... only 2 arrived").
    ``cpu:N`` stays the explicit multi-device opt-in."""
    import os
    import subprocess
    import sys

    src = (
        "from deepdfa_tpu.core.backend import apply_platform_override\n"
        "apply_platform_override()\n"
        "import jax\n"
        "print('NDEV:' + str(len(jax.devices())))\n"
    )
    assert "xla_force_host_platform_device_count" in os.environ.get(
        "XLA_FLAGS", ""
    )  # the hazard this test exists for must actually be present
    for spec, want in [("cpu", 1), ("cpu:8", 8)]:
        env = dict(os.environ, DEEPDFA_TPU_PLATFORM=spec)
        res = subprocess.run(
            [sys.executable, "-c", src], capture_output=True, text=True,
            env=env, timeout=300,
        )
        assert res.returncode == 0, res.stderr[-2000:]
        assert f"NDEV:{want}" in res.stdout, (spec, res.stdout, res.stderr[-500:])
