"""CodeBLEU evaluator (eval/codebleu.py).

Golden values for the BLEU core come from the published doctest examples
the reference ships inside its NLTK-derived bleu.py (corpus_bleu ==
0.5920..., brevity-penalty edge cases) — an independent oracle for this
from-the-formula implementation.
"""

import numpy as np
import pytest

from deepdfa_tpu.eval.codebleu import (
    KEYWORDS,
    corpus_bleu,
    corpus_dataflow_match,
    corpus_syntax_match,
    get_codebleu,
    weighted_corpus_bleu,
)

HYP1 = (
    "It is a guide to action which ensures that the military always obeys "
    "the commands of the party"
).split()
REF1A = (
    "It is a guide to action that ensures that the military will forever "
    "heed Party commands"
).split()
REF1B = (
    "It is the guiding principle which guarantees the military forces "
    "always being under the command of the Party"
).split()
REF1C = (
    "It is the practical guide for the army always to heed the directions "
    "of the party"
).split()
HYP2 = "he read the book because he was interested in world history".split()
REF2A = "he was interested in world history because he read the book".split()


def test_corpus_bleu_reference_doctest_value():
    score = corpus_bleu([[REF1A, REF1B, REF1C], [REF2A]], [HYP1, HYP2])
    assert abs(score - 0.5920) < 5e-4, score


def test_sentence_bleu_average_doctest_value():
    s1 = corpus_bleu([[REF1A, REF1B, REF1C]], [HYP1])
    s2 = corpus_bleu([[REF2A]], [HYP2])
    assert abs((s1 + s2) / 2 - 0.6223) < 5e-4, (s1, s2)


def test_perfect_match_scores_one():
    assert corpus_bleu([[HYP1]], [HYP1]) == pytest.approx(1.0)
    w = weighted_corpus_bleu([[HYP1]], [HYP1], KEYWORDS["c"])
    assert w == pytest.approx(1.0)


def test_weighted_favors_keyword_agreement():
    """Two candidates with one wrong token each: getting the KEYWORD wrong
    must cost more than getting an identifier wrong."""
    # wrong tokens sit at mirror positions (1 and 3 of 5) so the
    # unweighted n>=2 orders break identically; only the weighted unigram
    # order distinguishes the candidates
    ref = ["a if b x c".split()]
    good_kw = "a if b z c".split()  # identifier wrong
    bad_kw = "a while b x c".split()  # keyword wrong
    w_good = weighted_corpus_bleu([ref], [good_kw], KEYWORDS["c"])
    w_bad = weighted_corpus_bleu([ref], [bad_kw], KEYWORDS["c"])
    assert w_good > w_bad


CODE_REF = """int f(int a, int b) {
  int s = a + b;
  if (s > 10) {
    s = s - 1;
  }
  return s;
}"""

CODE_RENAMED = """int f(int p, int q) {
  int t = p + q;
  if (t > 10) {
    t = t - 1;
  }
  return t;
}"""

CODE_DIFFERENT = """int f(int a, int b) {
  int s = 0;
  while (s < b) {
    s = s + a;
  }
  return s;
}"""


def test_syntax_match_identical_is_one():
    assert corpus_syntax_match([[CODE_REF]], [CODE_REF]) == pytest.approx(1.0)


def test_syntax_match_renamed_is_one_different_is_less():
    """tree-sitter sexps carry node types only, so alpha-renaming preserves
    the syntax score while a structurally different body lowers it."""
    renamed = corpus_syntax_match([[CODE_REF]], [CODE_RENAMED])
    different = corpus_syntax_match([[CODE_REF]], [CODE_DIFFERENT])
    assert renamed == pytest.approx(1.0)
    assert different < 1.0


def test_dataflow_match_invariant_to_renaming():
    assert corpus_dataflow_match(
        [[CODE_REF]], [CODE_RENAMED]
    ) == pytest.approx(1.0)
    assert corpus_dataflow_match(
        [[CODE_REF]], [CODE_DIFFERENT]
    ) < 1.0


def test_dataflow_degenerates_to_zero_with_warning(caplog):
    import logging

    with caplog.at_level(logging.WARNING):
        score = corpus_dataflow_match([["not c at all ]]]"]], ["x"])
    assert score == 0.0
    assert "degenerates" in caplog.text


def test_statement_snippets_parse_via_wrapper():
    ref = "int x = a + 1;\nreturn x;"
    assert corpus_syntax_match([[ref]], [ref]) == pytest.approx(1.0)


def test_get_codebleu_composite():
    out = get_codebleu(
        [CODE_REF, CODE_REF], [CODE_RENAMED, CODE_DIFFERENT], lang="c"
    )
    assert set(out) == {
        "ngram_match", "weighted_ngram_match", "syntax_match",
        "dataflow_match", "codebleu",
    }
    expected = 0.25 * sum(
        out[k]
        for k in (
            "ngram_match", "weighted_ngram_match", "syntax_match",
            "dataflow_match",
        )
    )
    assert out["codebleu"] == pytest.approx(expected)
    assert 0.0 < out["codebleu"] <= 1.0
    # the renamed candidate scores strictly better than the different one
    solo_renamed = get_codebleu([CODE_REF], [CODE_RENAMED], lang="c")
    solo_diff = get_codebleu([CODE_REF], [CODE_DIFFERENT], lang="c")
    assert solo_renamed["codebleu"] > solo_diff["codebleu"]


def test_unsupported_language_raises():
    with pytest.raises(ValueError):
        corpus_syntax_match([["x"]], ["x"], lang="js")


# ---------------------------------------------------------------------------
# python language backend (stdlib ast; reference parser/DFG.py DFG_python)


PY_REF = "def add(a, b):\n    total = a + b\n    return total\n"
PY_SAME_RENAMED = "def add(x, y):\n    result = x + y\n    return result\n"
PY_DIFFERENT = "def mul(a, b):\n    if a > b:\n        return a * b\n    return 0\n"


def test_python_syntax_match_identical_is_one():
    from deepdfa_tpu.eval.codebleu import corpus_syntax_match

    assert corpus_syntax_match([[PY_REF]], [PY_REF], lang="python") == 1.0


def test_python_syntax_match_ranks_structure():
    from deepdfa_tpu.eval.codebleu import corpus_syntax_match

    renamed = corpus_syntax_match([[PY_REF]], [PY_SAME_RENAMED], lang="python")
    different = corpus_syntax_match([[PY_REF]], [PY_DIFFERENT], lang="python")
    assert renamed == 1.0  # sexps carry node types only
    assert different < renamed


def test_python_dataflow_invariant_to_alpha_renaming():
    from deepdfa_tpu.eval.codebleu import corpus_dataflow_match

    assert (
        corpus_dataflow_match([[PY_REF]], [PY_SAME_RENAMED], lang="python")
        == 1.0
    )
    assert (
        corpus_dataflow_match([[PY_REF]], [PY_DIFFERENT], lang="python") < 1.0
    )


def test_python_composite_and_keywords():
    from deepdfa_tpu.eval.codebleu import get_codebleu

    res = get_codebleu([PY_REF], [PY_SAME_RENAMED], lang="python")
    assert 0.0 < res["codebleu"] < 1.0
    assert res["syntax_match"] == 1.0
    perfect = get_codebleu([PY_REF], [PY_REF], lang="python")
    assert perfect["codebleu"] == 1.0


def test_python_dataflow_triples_cover_defs_and_uses():
    from deepdfa_tpu.eval.codebleu import _parse_py, _py_dataflow_triples

    tree = _parse_py(
        "n = base\nfor i in items:\n    n += i\nprint(n)\n"
    )
    triples = _py_dataflow_triples(tree)
    rels = {(t[0], t[1]) for t in triples}
    assert ("n", "computedFrom") in rels
    assert ("i", "computedFrom") in rels  # for-target
    assert ("n", "comesFrom") in rels  # the print(n) use


def test_unsupported_lang_still_raises():
    import pytest

    from deepdfa_tpu.eval.codebleu import get_codebleu

    with pytest.raises(ValueError, match="descoped"):
        get_codebleu(["x = 1"], ["x = 1"], lang="swift")


JAVA_REF = """public int sumPositive(int[] xs) {
  int total = 0;
  for (int i = 0; i < xs.length; i++) {
    if (xs[i] > 0) {
      total += xs[i];
    }
  }
  return total;
}"""

JAVA_RESTRUCTURED = """public int sumPositive(int[] xs) {
  int total = 0;
  int i = 0;
  while (i < xs.length) {
    total += Math.max(xs[i], 0);
    i++;
  }
  return total;
}"""


def test_java_syntax_match_identical_is_one():
    from deepdfa_tpu.eval.codebleu import corpus_syntax_match, get_codebleu

    assert corpus_syntax_match([[JAVA_REF]], [JAVA_REF], lang="java") == 1.0
    perfect = get_codebleu([JAVA_REF], [JAVA_REF], lang="java")
    assert perfect["codebleu"] == 1.0


def test_java_syntax_match_ranks_structure():
    """A structurally different (while vs for) but semantically close
    candidate must score strictly between 0 and the identical one, and
    above an unrelated snippet — the ordering the AST term exists for."""
    from deepdfa_tpu.eval.codebleu import corpus_syntax_match

    close = corpus_syntax_match([[JAVA_REF]], [JAVA_RESTRUCTURED], lang="java")
    far = corpus_syntax_match(
        [[JAVA_REF]],
        ["public int noop(int[] xs) { int total = 0; return total; }"],
        lang="java",
    )
    assert 0.0 < far < close < 1.0


def test_java_signatures_parse_modifiers_generics_throws():
    """CONCODE-style method shapes: modifiers before non-keyword return
    types, generic type-parameter lists, throws clauses, enhanced for,
    instanceof — all must produce a CPG (UNKNOWN-node recovery ok,
    parser crash not)."""
    from deepdfa_tpu.frontend.parser import parse_function

    shapes = [
        "public String name() throws IOException { return this.n; }",
        "public static <T> T first(List<T> xs) { return xs.get(0); }",
        "protected synchronized void add(int[] xs) throws Exception {\n"
        "  for (int x : xs) { this.sum += x; }\n}",
        "public boolean eq(Object o) {\n"
        "  return (o instanceof Point) && ((Point) o).x == x;\n}",
    ]
    for code in shapes:
        cpg = parse_function(code)
        assert cpg.cfg_nodes(), code


def test_java_dataflow_match_sees_def_use():
    from deepdfa_tpu.eval.codebleu import corpus_dataflow_match

    assert corpus_dataflow_match([[JAVA_REF]], [JAVA_REF], lang="java") == 1.0
    # alpha-renaming robustness (reference normalize_dataflow semantics);
    # not exactly 1.0: per-node uses are emitted in sorted order, so a
    # rename can permute triple order and shift the var_i numbering
    renamed = JAVA_REF.replace("total", "acc").replace("xs", "arr")
    assert corpus_dataflow_match([[JAVA_REF]], [renamed], lang="java") >= 0.9


# --- c_sharp (the reference translate task's target language; with java
# it is the COMPLETE runnable surface of the reference evaluator — its
# keywords/ dir ships only java.txt + c_sharp.txt, calc_code_bleu.py:39)


CSHARP_REF = """public virtual int SumPositive(int[] xs) {
  int total = 0;
  foreach (int x in xs) {
    if (x > 0) {
      total += x;
    }
  }
  return total;
}"""

CSHARP_RESTRUCTURED = """public virtual int SumPositive(int[] xs) {
  int total = 0;
  for (int i = 0; i < xs.Length; i++) {
    total += Math.Max(xs[i], 0);
  }
  return total;
}"""


def test_csharp_identical_is_one():
    from deepdfa_tpu.eval.codebleu import corpus_syntax_match, get_codebleu

    assert (
        corpus_syntax_match([[CSHARP_REF]], [CSHARP_REF], lang="c_sharp")
        == 1.0
    )
    perfect = get_codebleu([CSHARP_REF], [CSHARP_REF], lang="c_sharp")
    assert perfect["codebleu"] == 1.0


def test_csharp_syntax_match_ranks_structure():
    from deepdfa_tpu.eval.codebleu import corpus_syntax_match

    close = corpus_syntax_match(
        [[CSHARP_REF]], [CSHARP_RESTRUCTURED], lang="c_sharp"
    )
    far = corpus_syntax_match(
        [[CSHARP_REF]],
        ["public void Log(string msg) { Console.WriteLine(msg); }"],
        lang="c_sharp",
    )
    assert 0.0 <= far < close < 1.0


def test_csharp_method_shapes_parse_clean():
    """Translate-task method shapes (java->cs ports of Lucene-style code):
    modifiers, foreach/in, is + (T) casts, string[] array types, out/ref
    args, using/lock, try/finally, ?? — all must parse with NO UNKNOWN
    recovery nodes."""
    from deepdfa_tpu.frontend.parser import parse_function

    shapes = [
        "public override bool Equals(object o) {\n"
        "  if (o is Point) { Point p = (Point)o; return p.x == x; }\n"
        "  return false;\n}",
        "public virtual void Add(int[] values) {\n"
        "  foreach (int v in values) { this.sum += v; }\n}",
        "internal static string Join(string[] parts) {\n"
        "  string acc = parts[0];\n"
        "  for (int i = 1; i < parts.Length; i++) { acc += parts[i]; }\n"
        "  return acc;\n}",
        "public bool TryRead(string s) {\n"
        "  if (int.TryParse(s, out int n)) { this.val = n; return true; }\n"
        "  return false;\n}",
        "public void Run() {\n"
        "  using (var r = File.Open(path)) { r.Read(); }\n"
        "  lock (gate) { count++; }\n"
        "  try { Work(); } catch (Exception e) { Log(e); }"
        " finally { Close(); }\n}",
        "public int Pick(int? a, int b) { return a ?? b; }",
    ]
    for code in shapes:
        cpg = parse_function(code, dialect="cs")
        # synthetic nodes (e.g. the `out`-arg def source) are fine;
        # parse-error recovery nodes are not
        unknowns = [
            n for n in cpg.nodes
            if n.label == "UNKNOWN" and n.code == "<parse error>"
        ]
        assert not unknowns, (code, [n.code for n in unknowns])
        assert cpg.cfg_nodes(), code


def test_csharp_dataflow_match_sees_def_use():
    from deepdfa_tpu.eval.codebleu import corpus_dataflow_match

    assert (
        corpus_dataflow_match([[CSHARP_REF]], [CSHARP_REF], lang="c_sharp")
        == 1.0
    )
    renamed = CSHARP_REF.replace("total", "acc").replace("xs", "arr")
    assert (
        corpus_dataflow_match([[CSHARP_REF]], [renamed], lang="c_sharp")
        >= 0.9
    )


def test_csharp_foreach_defines_loop_var():
    """The foreach desugaring must register a definition of the loop
    variable (reaching-defs gen), like the C++ range-for path."""
    from deepdfa_tpu.frontend.parser import parse_function
    from deepdfa_tpu.frontend.reaching import ReachingDefinitions

    cpg = parse_function(
        "int Sum(int[] xs) { int t = 0; foreach (int v in xs)"
        " { t += v; } return t; }",
        dialect="cs",
    )
    rd = ReachingDefinitions(cpg)
    rd.solve()
    defined = {d.var for defs in rd.gen_set.values() for d in defs}
    assert "v" in defined and "t" in defined


def test_java_dialect_parses_instanceof_and_casts_clean():
    """Under dialect='java' (what eval/codebleu.py now passes) the shapes
    that previously hit UNKNOWN recovery — instanceof, (Foo)o casts,
    try-with-resources, finally, >>> — parse clean."""
    from deepdfa_tpu.frontend.parser import parse_function

    shapes = [
        "public boolean eq(Object o) {\n"
        "  return (o instanceof Point) && ((Point) o).x == x;\n}",
        "public int shift(int v) { return v >>> 2; }",
        "public String read(String p) {\n"
        "  try (Reader r = open(p)) { return r.readAll(); }\n"
        "  finally { log(p); }\n}",
    ]
    for code in shapes:
        cpg = parse_function(code, dialect="java")
        unknowns = [n for n in cpg.nodes if n.label == "UNKNOWN"]
        assert not unknowns, (code, [n.code for n in unknowns])


def test_csharp_modern_shapes_parse_clean():
    """Review-pass regressions: qualified types after is/instanceof,
    null-conditional access, ??=, lambdas, out-arg definitions."""
    from deepdfa_tpu.frontend.parser import parse_function
    from deepdfa_tpu.frontend.reaching import ReachingDefinitions

    shapes = [
        ("cs", "bool F(object o) { return o is System.IDisposable; }"),
        ("java",
         "public boolean f(Object o) { return o instanceof java.util.List; }"),
        ("cs", "void F() { x?.Run(); }"),
        ("cs", "void F() { a ??= b; }"),
        ("cs", "int F() { f = x => x + 1; return f(2); }"),
    ]
    for dialect, code in shapes:
        cpg = parse_function(code, dialect=dialect)
        bad = [
            n.code for n in cpg.nodes
            if n.label == "UNKNOWN" and n.code == "<parse error>"
        ]
        assert not bad, (code, bad)

    cpg = parse_function(
        "bool T(string s) { if (int.TryParse(s, out int n))"
        " { v = n; } return true; }",
        dialect="cs",
    )
    rd = ReachingDefinitions(cpg)
    rd.solve()
    defined = {d.var for defs in rd.gen_set.values() for d in defs}
    assert "n" in defined  # out-argument IS a definition


# --- javascript (reference DFG.py ships DFG_javascript but no keywords
# file, so its evaluator could never run js; here it is a first-class
# structural-match language via the js frontend dialect)


JS_REF = """function sumPositive(xs) {
  let total = 0;
  for (const x of xs) {
    if (x > 0) { total += x; }
  }
  return total;
}"""


def test_js_identical_is_one():
    from deepdfa_tpu.eval.codebleu import corpus_syntax_match, get_codebleu

    assert corpus_syntax_match([[JS_REF]], [JS_REF], lang="javascript") == 1.0
    assert get_codebleu([JS_REF], [JS_REF], lang="javascript")["codebleu"] == 1.0


def test_js_shapes_parse_clean():
    """Representative js method-body shapes: let/const/var declarations,
    for-of/for-in, object + array literals, template literals, ===,
    typeof, ??, arrow + anonymous functions — no parse-error recovery."""
    from deepdfa_tpu.frontend.parser import parse_function

    shapes = [
        "function f(xs) { for (const x of xs) { use(x); } }",
        "function f(obj) { for (var k in obj) { use(obj[k]); } }",
        "function f() { const o = {a: 1, b: [2, 3]}; return o.a; }",
        'function f(x) { if (typeof x === "string") { return x ?? ""; } }',
        "function f() { var g = function(a) { return a + 1; };"
        " const h = (a, b) => a + b; return g(h(1, 2)); }",
        "function f(t) { return `value ${t}`; }",
        "function f(a) { a ??= 0; return a >>> 2; }",
    ]
    for code in shapes:
        cpg = parse_function(code, dialect="js")
        bad = [
            n.code for n in cpg.nodes
            if n.label == "UNKNOWN" and n.code == "<parse error>"
        ]
        assert not bad, (code, bad)


def test_js_dataflow_and_ranking():
    from deepdfa_tpu.eval.codebleu import (
        corpus_dataflow_match,
        corpus_syntax_match,
    )

    assert (
        corpus_dataflow_match([[JS_REF]], [JS_REF], lang="javascript") == 1.0
    )
    renamed = JS_REF.replace("total", "acc").replace("xs", "arr")
    assert (
        corpus_dataflow_match([[JS_REF]], [renamed], lang="javascript") >= 0.9
    )
    far = corpus_syntax_match(
        [[JS_REF]],
        ["function log(m) { console.log(m); }"],
        lang="javascript",
    )
    close = corpus_syntax_match(
        [[JS_REF]],
        ["function sum(xs) { let t = 0; for (const v of xs)"
         " { t += v; } return t; }"],
        lang="javascript",
    )
    assert 0.0 <= far < close <= 1.0


# --- php / go (reference DFG.py ships DFG_php/DFG_go but no keyword
# files — its evaluator cannot run them; here they are first-class)


PHP_REF = """function sumPositive($xs) {
  $total = 0;
  foreach ($xs as $x) {
    if ($x > 0) { $total += $x; }
  }
  return $total;
}"""

GO_REF = """func sumPositive(xs []int) int {
	total := 0
	for _, x := range xs {
		if x > 0 {
			total += x
		}
	}
	return total
}"""


def test_php_identical_is_one_and_ranks():
    from deepdfa_tpu.eval.codebleu import corpus_syntax_match, get_codebleu

    assert corpus_syntax_match([[PHP_REF]], [PHP_REF], lang="php") == 1.0
    assert get_codebleu([PHP_REF], [PHP_REF], lang="php")["codebleu"] == 1.0
    far = corpus_syntax_match(
        [[PHP_REF]],
        ['function log($m) { echo $m; }'],
        lang="php",
    )
    assert 0.0 <= far < 1.0


def test_php_shapes_parse_clean():
    from deepdfa_tpu.frontend.parser import parse_function

    shapes = [
        'function f($xs) { foreach ($xs as $k => $v) { use_($k, $v); } }',
        'function f($a) { $s = "x: " . $a; $s .= "!"; echo $s; return $s; }',
        'function f($o) { return $o?->name ?? "none"; }',
        'function f($a, $b) { if ($a === $b and $a instanceof Foo)'
        ' { return true; } return false; }',
        'public static function f(&$x) { $x **= 2; global $cfg;'
        ' return $x <=> $cfg; }',
    ]
    for code in shapes:
        cpg = parse_function(code, dialect="php")
        bad = [
            n.code for n in cpg.nodes
            if n.label == "UNKNOWN" and n.code == "<parse error>"
        ]
        assert not bad, (code, bad)


def test_php_dataflow_sees_sigil_vars():
    from deepdfa_tpu.eval.codebleu import corpus_dataflow_match

    assert corpus_dataflow_match([[PHP_REF]], [PHP_REF], lang="php") == 1.0
    renamed = PHP_REF.replace("$total", "$acc").replace("$xs", "$arr")
    assert corpus_dataflow_match([[PHP_REF]], [renamed], lang="php") >= 0.9


def test_go_identical_is_one_and_ranks():
    from deepdfa_tpu.eval.codebleu import corpus_syntax_match, get_codebleu

    assert corpus_syntax_match([[GO_REF]], [GO_REF], lang="go") == 1.0
    assert get_codebleu([GO_REF], [GO_REF], lang="go")["codebleu"] == 1.0
    far = corpus_syntax_match(
        [[GO_REF]], ["func log(m string) { fmt.Println(m) }"], lang="go"
    )
    assert 0.0 <= far < 1.0


def test_go_shapes_parse_clean():
    """go-spec shapes: :=, multi-assign, range loops, paren-less
    if/for/switch with init clauses, var decls, defer + anonymous func,
    channel ops — no parse-error recovery, ASI supplies semicolons."""
    from deepdfa_tpu.frontend.parser import parse_function

    shapes = [
        "func f(xs []int) int {\n\ts := 0\n\tfor i, x := range xs {\n"
        "\t\ts += x * i\n\t}\n\treturn s\n}",
        "func f(m map[string]int, k string) int {\n"
        "\tif v, ok := m[k]; ok {\n\t\treturn v\n\t}\n\treturn 0\n}",
        "func f(n int) int {\n\tvar acc int = 1\n"
        "\tfor i := 0; i < n; i++ {\n\t\tacc *= 2\n\t}\n\treturn acc\n}",
        "func f(a int, b int) (int, int) {\n\ta, b = b, a\n"
        "\treturn a, b\n}",
        "func (s *Server) Run(ch chan bool) {\n\tdefer func() {"
        " ch <- true }()\n\tx := <-ch\n\t_ = x\n}",
        "func f(n int) string {\n\tswitch n {\n\tcase 0:\n"
        "\t\treturn \"zero\"\n\tdefault:\n\t\treturn \"n\"\n\t}\n}",
    ]
    for code in shapes:
        cpg = parse_function(code, dialect="go")
        bad = [
            n.code for n in cpg.nodes
            if n.label == "UNKNOWN" and n.code == "<parse error>"
        ]
        assert not bad, (code, bad)


def test_go_dataflow_short_decl_is_def():
    from deepdfa_tpu.frontend.parser import parse_function
    from deepdfa_tpu.frontend.reaching import ReachingDefinitions

    cpg = parse_function(GO_REF, dialect="go")
    rd = ReachingDefinitions(cpg)
    rd.solve()
    defined = {d.var for defs in rd.gen_set.values() for d in defs}
    assert {"total", "x"} <= defined


# --- ruby (the last DFG.py grammar: end-delimited, newline-terminated;
# reference evaluator cannot run it — no keywords/ruby.txt)


RUBY_REF = """def sum_positive(xs)
  total = 0
  xs.each do |x|
    if x > 0
      total += x
    end
  end
  total
end"""


def test_ruby_identical_is_one_and_ranks():
    from deepdfa_tpu.eval.codebleu import corpus_syntax_match, get_codebleu

    assert corpus_syntax_match([[RUBY_REF]], [RUBY_REF], lang="ruby") == 1.0
    assert get_codebleu([RUBY_REF], [RUBY_REF], lang="ruby")["codebleu"] == 1.0
    far = corpus_syntax_match(
        [[RUBY_REF]], ["def log(m)\n  puts m\nend"], lang="ruby"
    )
    assert 0.0 <= far < 1.0


def test_ruby_shapes_parse_clean():
    """Ruby method shapes: iterator blocks (do/end and braces), trailing
    if/unless modifiers, case/when, begin/rescue/ensure, until, symbols,
    @ivars, string interpolation, ?/! method names, ranges."""
    from deepdfa_tpu.frontend.parser import parse_function

    shapes = [
        "def f(xs)\n  xs.map { |i| i * 2 }\nend",
        "def f(v)\n  return nil if v.nil?\n  v\nend",
        "def f(n)\n  case n\n  when 0 then :zero\n  when 1..9\n"
        "    :small\n  else\n    :big\n  end\nend",
        "def f\n  begin\n    risky!\n  rescue StandardError => e\n"
        "    raise e\n  ensure\n    cleanup\n  end\nend",
        "def f(items, limit)\n  for v in items\n    next if v > limit\n"
        "    use v\n  end\nend",
        "def f(s)\n  @msg = \"got #{s}\"\n  puts @msg unless s.empty?\n"
        "  until done?\n    wait 1\n  end\nend",
    ]
    for code in shapes:
        cpg = parse_function(code, dialect="ruby")
        bad = [
            n.code for n in cpg.nodes
            if n.label == "UNKNOWN" and n.code == "<parse error>"
        ]
        assert not bad, (code, bad)


def test_ruby_dataflow_block_param_is_def():
    from deepdfa_tpu.eval.codebleu import corpus_dataflow_match
    from deepdfa_tpu.frontend.parser import parse_function
    from deepdfa_tpu.frontend.reaching import ReachingDefinitions

    cpg = parse_function(RUBY_REF, dialect="ruby")
    rd = ReachingDefinitions(cpg)
    rd.solve()
    defined = {d.var for defs in rd.gen_set.values() for d in defs}
    assert {"total", "x"} <= defined
    assert corpus_dataflow_match([[RUBY_REF]], [RUBY_REF], lang="ruby") == 1.0
    renamed = RUBY_REF.replace("total", "acc").replace("xs", "arr")
    assert corpus_dataflow_match([[RUBY_REF]], [renamed], lang="ruby") >= 0.9


def test_every_reference_dfg_language_is_supported():
    """parser/DFG.py ships extractors for python, java, ruby, go, php,
    javascript, c_sharp — all must be scoreable here (the reference
    itself could only run java + c_sharp, its only keyword files)."""
    from deepdfa_tpu.eval.codebleu import LANG_DIALECT

    reference_dfg_langs = {
        "python", "java", "ruby", "go", "php", "javascript", "c_sharp",
    }
    assert reference_dfg_langs <= set(LANG_DIALECT) | {"python"}


def test_ruby_review_regressions():
    """Review-pass regressions: guard keywords never swallowed as
    command args, numeric ranges lex as num op num, setter/operator
    method names keep their parameters."""
    from deepdfa_tpu.frontend.parser import parse_function
    from deepdfa_tpu.frontend.reaching import ReachingDefinitions

    shapes = [
        "def f\n  cleanup unless failed\nend",
        "def f\n  save and notify\nend",
        "def f(n)\n  for i in 1..n\n    use i\n  end\nend",
        "def name=(value)\n  @name = value\nend",
        "def []=(k, v)\n  @h[k] = v\nend",
    ]
    for code in shapes:
        cpg = parse_function(code, dialect="ruby")
        bad = [
            n.code for n in cpg.nodes
            if n.label == "UNKNOWN" and n.code == "<parse error>"
        ]
        assert not bad, (code, bad)

    cpg = parse_function(
        "def f\n  cleanup unless failed\nend", dialect="ruby"
    )
    assert any(
        n.code.startswith("!(") for n in cpg.nodes if n.label == "CALL"
    )  # the unless guard survives as a negated condition
    cpg = parse_function("def name=(value)\n  @name = value\nend",
                         dialect="ruby")
    assert [n.name for n in cpg.nodes
            if n.label == "METHOD_PARAMETER_IN"] == ["value"]
    cpg = parse_function("def f(n)\n  for i in 1..n\n    use i\n  end\nend",
                         dialect="ruby")
    rd = ReachingDefinitions(cpg)
    rd.solve()
    assert "i" in {d.var for defs in rd.gen_set.values() for d in defs}
