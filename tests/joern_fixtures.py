"""Hand-specified Joern-schema CPG exports for fidelity measurement.

Each fixture encodes what the real Joern (v1.1.1072, the reference's pin)
emits for a small C function, at the granularity the model consumes:
statement-level CFG nodes with line numbers, assignment CALLs with
AST/ARGUMENT children (LHS IDENTIFIER first), LOCALs carrying
typeFullName, LITERAL/CALL descendants for the feature extractor. Node
ids use Joern's large-offset style. Built by hand from the schema in
tests/test_joern_io.py — NOT derived from the hermetic parser (that
would make agreement trivially 1.0).
"""

from __future__ import annotations

import json


class JoernExportBuilder:
    def __init__(self, method_name: str, method_line: int = 1):
        self._next = 1000100
        self.nodes: list[dict] = []
        self.edges: list[list] = []
        self.method = self.node("METHOD", name=method_name, code=method_name,
                                line=method_line)
        self.ret = self.node("METHOD_RETURN", name="RET", code="RET",
                             line=method_line, order=99)

    def node(self, label, name="", code="", line=None, order=1, typ=None):
        nid = self._next
        self._next += 1
        row = {"id": nid, "_label": label, "name": name, "code": code,
               "order": order}
        if line is not None:
            row["lineNumber"] = line
        if typ is not None:
            row["typeFullName"] = typ
        self.nodes.append(row)
        return nid

    def edge(self, src, dst, etype):
        # export rows are [inNode, outNode, label, dataflow]: out -> in
        self.edges.append([dst, src, etype, ""])

    def ast(self, parent, child, argument=False):
        self.edge(parent, child, "AST")
        if argument:
            self.edge(parent, child, "ARGUMENT")

    def local(self, name, typ, line):
        nid = self.node("LOCAL", name=name, code=f"{typ} {name}", line=line,
                        typ=typ)
        self.ast(self.method, nid)
        return nid

    def identifier(self, name, typ, line, order=1):
        return self.node("IDENTIFIER", name=name, code=name, line=line,
                         order=order, typ=typ)

    def literal(self, text, line, order=2):
        return self.node("LITERAL", name="", code=text, line=line, order=order)

    def call(self, name, code, line, args, order=1):
        nid = self.node("CALL", name=name, code=code, line=line, order=order)
        self.ast(self.method, nid)
        for a in args:
            self.ast(nid, a, argument=True)
        return nid

    def subcall(self, name, code, line, args, order=2):
        """A nested (non-statement) call: child of an expression."""
        nid = self.node("CALL", name=name, code=code, line=line, order=order)
        for a in args:
            self.ast(nid, a, argument=True)
        return nid

    def assign(self, lhs_name, lhs_type, rhs_nodes, line, code):
        lhs = self.identifier(lhs_name, lhs_type, line, order=1)
        return self.call("<operator>.assignment", code, line,
                         [lhs, *rhs_nodes])

    def cfg(self, *chain):
        for a, b in zip(chain, chain[1:]):
            self.edge(a, b, "CFG")

    def write(self, tmp_path, stem):
        prefix = tmp_path / f"{stem}.c"
        (tmp_path / f"{stem}.c.nodes.json").write_text(json.dumps(self.nodes))
        (tmp_path / f"{stem}.c.edges.json").write_text(json.dumps(self.edges))
        return str(prefix)


SOURCES = {
    "assign_return": (
        "int f(int a) {\n"
        "  int x = a + 1;\n"
        "  return x;\n"
        "}\n"
    ),
    "if_else": (
        "int g(int a) {\n"
        "  int r = 0;\n"
        "  if (a > 0) {\n"
        "    r = a;\n"
        "  } else {\n"
        "    r = 0 - a;\n"
        "  }\n"
        "  return r;\n"
        "}\n"
    ),
    "while_call": (
        "int h(int n) {\n"
        "  int s = 0;\n"
        "  int i = 0;\n"
        "  while (i < n) {\n"
        "    s = s + bar(i);\n"
        "    i = i + 1;\n"
        "  }\n"
        "  return s;\n"
        "}\n"
    ),
    "switch_break": (
        "int sw(int x) {\n"
        "  int r = 0;\n"
        "  switch (x) {\n"
        "  case 0:\n"
        "    r = 1;\n"
        "    break;\n"
        "  default:\n"
        "    r = 2;\n"
        "  }\n"
        "  return r;\n"
        "}\n"
    ),
    "loop_continue": (
        "int lc(int n) {\n"
        "  int s = 0;\n"
        "  for (int i = 0; i < n; i++) {\n"
        "    if (n % 2)\n"
        "      continue;\n"
        "    s = s + i;\n"
        "  }\n"
        "  return s;\n"
        "}\n"
    ),
}


def build_assign_return(tmp_path):
    b = JoernExportBuilder("f")
    b.local("x", "int", 2)
    add = b.subcall(
        "<operator>.addition", "a + 1", 2,
        [b.identifier("a", "int", 2, 1), b.literal("1", 2, 2)],
    )
    asg = b.assign("x", "int", [add], 2, "x = a + 1")
    retv = b.identifier("x", "int", 3)
    ret = b.call("RETURN", "return x;", 3, [retv])
    b.nodes[-4 if False else 0] = b.nodes[0]  # no-op; keep ids stable
    # joern labels return statements RETURN, not CALL
    for n in b.nodes:
        if n["id"] == ret:
            n["_label"] = "RETURN"
            n["name"] = "return"
    b.cfg(b.method, asg, ret, b.ret)
    return b.write(tmp_path, "assign_return")


def build_if_else(tmp_path):
    b = JoernExportBuilder("g")
    b.local("r", "int", 2)
    asg0 = b.assign("r", "int", [b.literal("0", 2)], 2, "r = 0")
    cond = b.call(
        "<operator>.greaterThan", "a > 0", 3,
        [b.identifier("a", "int", 3, 1), b.literal("0", 3, 2)],
    )
    asg1 = b.assign("r", "int", [b.identifier("a", "int", 4, 2)], 4, "r = a")
    sub = b.subcall(
        "<operator>.subtraction", "0 - a", 6,
        [b.literal("0", 6, 1), b.identifier("a", "int", 6, 2)],
    )
    asg2 = b.assign("r", "int", [sub], 6, "r = 0 - a")
    retv = b.identifier("r", "int", 8)
    ret = b.call("RETURN", "return r;", 8, [retv])
    for n in b.nodes:
        if n["id"] == ret:
            n["_label"] = "RETURN"
            n["name"] = "return"
    b.cfg(b.method, asg0, cond)
    b.cfg(cond, asg1, ret, b.ret)
    b.cfg(cond, asg2, ret)
    return b.write(tmp_path, "if_else")


def build_while_call(tmp_path):
    b = JoernExportBuilder("h")
    b.local("s", "int", 2)
    b.local("i", "int", 3)
    asg_s = b.assign("s", "int", [b.literal("0", 2)], 2, "s = 0")
    asg_i = b.assign("i", "int", [b.literal("0", 3)], 3, "i = 0")
    cond = b.call(
        "<operator>.lessThan", "i < n", 4,
        [b.identifier("i", "int", 4, 1), b.identifier("n", "int", 4, 2)],
    )
    barc = b.subcall("bar", "bar(i)", 5, [b.identifier("i", "int", 5, 1)])
    add = b.subcall(
        "<operator>.addition", "s + bar(i)", 5,
        [b.identifier("s", "int", 5, 1), barc],
    )
    asg_body = b.assign("s", "int", [add], 5, "s = s + bar(i)")
    inc = b.subcall(
        "<operator>.addition", "i + 1", 6,
        [b.identifier("i", "int", 6, 1), b.literal("1", 6, 2)],
    )
    asg_inc = b.assign("i", "int", [inc], 6, "i = i + 1")
    retv = b.identifier("s", "int", 8)
    ret = b.call("RETURN", "return s;", 8, [retv])
    for n in b.nodes:
        if n["id"] == ret:
            n["_label"] = "RETURN"
            n["name"] = "return"
    b.cfg(b.method, asg_s, asg_i, cond, asg_body, asg_inc, cond)
    b.cfg(cond, ret, b.ret)
    return b.write(tmp_path, "while_call")


def build_switch_break(tmp_path):
    """Joern emits JUMP_TARGET nodes per case/default label and keeps
    break statements in the CFG as CONTROL_STRUCTURE nodes; the dispatch
    edges run switch-cond -> each jump target."""
    b = JoernExportBuilder("sw")
    b.local("r", "int", 2)
    asg0 = b.assign("r", "int", [b.literal("0", 2)], 2, "r = 0")
    swcond = b.identifier("x", "int", 3)
    b.ast(b.method, swcond)
    jt0 = b.node("JUMP_TARGET", name="case 0", code="case 0:", line=4)
    asg1 = b.assign("r", "int", [b.literal("1", 5)], 5, "r = 1")
    brk = b.node("CONTROL_STRUCTURE", name="break", code="break;", line=6)
    jt1 = b.node("JUMP_TARGET", name="default", code="default:", line=7)
    asg2 = b.assign("r", "int", [b.literal("2", 8)], 8, "r = 2")
    retv = b.identifier("r", "int", 10)
    ret = b.call("RETURN", "return r;", 10, [retv])
    for n in b.nodes:
        if n["id"] == ret:
            n["_label"] = "RETURN"
            n["name"] = "return"
    b.cfg(b.method, asg0, swcond, jt0, asg1, brk, ret, b.ret)
    b.cfg(swcond, jt1, asg2, ret)
    return b.write(tmp_path, "switch_break")


def build_loop_continue(tmp_path):
    """continue stays in Joern's CFG as a CONTROL_STRUCTURE node wired
    to the for-loop's update expression."""
    b = JoernExportBuilder("lc")
    b.local("s", "int", 2)
    asg_s = b.assign("s", "int", [b.literal("0", 2)], 2, "s = 0")
    b.local("i", "int", 3)
    asg_i = b.assign("i", "int", [b.literal("0", 3)], 3, "i = 0")
    cond = b.call(
        "<operator>.lessThan", "i < n", 3,
        [b.identifier("i", "int", 3, 1), b.identifier("n", "int", 3, 2)],
    )
    ifc = b.call(
        "<operator>.modulo", "n % 2", 4,
        [b.identifier("n", "int", 4, 1), b.literal("2", 4, 2)],
    )
    cont = b.node("CONTROL_STRUCTURE", name="continue", code="continue;",
                  line=5)
    add = b.subcall(
        "<operator>.addition", "s + i", 6,
        [b.identifier("s", "int", 6, 1), b.identifier("i", "int", 6, 2)],
    )
    asg_b = b.assign("s", "int", [add], 6, "s = s + i")
    inc = b.call(
        "<operator>.postIncrement", "i++", 3,
        [b.identifier("i", "int", 3, 1)],
    )
    retv = b.identifier("s", "int", 8)
    ret = b.call("RETURN", "return s;", 8, [retv])
    for n in b.nodes:
        if n["id"] == ret:
            n["_label"] = "RETURN"
            n["name"] = "return"
    b.cfg(b.method, asg_s, asg_i, cond, ifc, cont, inc, cond)
    b.cfg(ifc, asg_b, inc)
    b.cfg(cond, ret, b.ret)
    return b.write(tmp_path, "loop_continue")


BUILDERS = {
    "assign_return": build_assign_return,
    "if_else": build_if_else,
    "while_call": build_while_call,
    "switch_break": build_switch_break,
    "loop_continue": build_loop_continue,
}
