"""Coordination-backend contract, chaos-drill scheduling + gating, and
predictive-autoscale controller units (deepdfa_tpu/fleet/{coord,drill,
autoscale}.py + the obs/bench_gate.py drill trajectory gate,
docs/fleet.md) — ISSUE 18.

The backend contract suite runs against BOTH backends: the default
LocalDirBackend and the drills' FaultableBackend with no faults
programmed must be indistinguishable; the injected faults are then
pinned observable ONLY through the faultable wrapper."""

import json
import time
from pathlib import Path

import pytest

from deepdfa_tpu.fleet import autoscale, coord, drill
from deepdfa_tpu.obs import bench_gate as bg
from deepdfa_tpu.obs import metrics as obs_metrics


def counter(name: str) -> float:
    return obs_metrics.REGISTRY.snapshot().get(name, 0.0)


class FakeClock:
    """A deterministic clock whose sleep advances it (poll/cadence
    schedules become exact assertions, not wall-clock races)."""

    def __init__(self):
        self.t = 0.0

    def __call__(self) -> float:
        return self.t

    def sleep(self, s: float) -> None:
        self.t += s


# ---------------------------------------------------------------------------
# poll_until: the one shared bounded poll helper


def test_poll_until_returns_first_truthy_value():
    calls = []

    def pred():
        calls.append(1)
        return {"ready": True}

    # timeout_s=0 still checks once ("check now")
    out = coord.poll_until(pred, 0.0, sleep=lambda s: None)
    assert out == {"ready": True}
    assert len(calls) == 1


def test_poll_until_exhaustion_backoff_and_counter():
    clk = FakeClock()
    sleeps: list[float] = []

    def sleep(s):
        sleeps.append(s)
        clk.sleep(s)

    before = counter("coord/poll_exhausted")
    out = coord.poll_until(
        lambda: None, 1.0, interval_s=0.1, max_interval_s=0.4,
        jitter=0.0, clock=clk, sleep=sleep,
    )
    assert out is None
    assert counter("coord/poll_exhausted") == before + 1
    # exponential: 0.1, 0.2, then capped at 0.4; the final sleep is
    # clamped to the deadline — total never overshoots timeout_s
    assert sleeps[:3] == [pytest.approx(0.1), pytest.approx(0.2),
                         pytest.approx(0.4)]
    assert all(s <= 0.4 + 1e-9 for s in sleeps)
    assert sum(sleeps) == pytest.approx(1.0)


def test_poll_until_propagates_predicate_exceptions():
    # a predicate that can tell the waited-for thing DIED raises; the
    # helper must not swallow that into more polling
    def pred():
        raise RuntimeError("replica exited rc=1")

    with pytest.raises(RuntimeError, match="exited"):
        coord.poll_until(pred, 5.0, sleep=lambda s: None)


# ---------------------------------------------------------------------------
# the backend contract, against BOTH backends


@pytest.fixture(params=["local", "faultable"])
def backend(request):
    if request.param == "local":
        return coord.LocalDirBackend()
    return coord.FaultableBackend()


def test_backend_doc_round_trip_and_absent_raises(backend, tmp_path):
    p = tmp_path / "hb" / "r0.json"
    with pytest.raises(OSError):
        backend.read_doc(p)
    backend.write_doc(p, '{"state": "ready"}')
    assert backend.read_doc(p) == '{"state": "ready"}'
    backend.write_doc(p, '{"state": "drained"}')
    assert backend.read_doc(p) == '{"state": "drained"}'


def test_backend_scan_sorted_and_missing_dir(backend, tmp_path):
    assert backend.scan(tmp_path / "nope", "*.json") == []
    for name in ("b.json", "a.json", "c.txt"):
        backend.write_doc(tmp_path / name, "{}")
    assert [p.name for p in backend.scan(tmp_path, "*.json")] == [
        "a.json", "b.json",
    ]


def test_backend_log_append_tail_and_torn_tolerance(backend, tmp_path):
    log = tmp_path / "fleet_log.jsonl"
    handle = backend.open_log(log)
    handle.write_line(json.dumps({"request": {"id": "a"}}))
    handle.write_line(json.dumps({"request": {"id": "b"}}))
    handle.close()
    assert handle.closed
    # a crashed writer's torn final line: half a record, no newline
    with log.open("a") as f:
        f.write('{"request": {"id": "c"')
    recs = backend.tail_records(log, 1 << 20)
    assert [r["request"]["id"] for r in recs] == ["a", "b"]
    # a byte-bounded tail also tears the FIRST line at the seek; torn
    # lines cost one record each, never the read
    small = backend.tail_records(log, 30)
    assert all(
        r["request"]["id"] in ("a", "b") for r in small
    )
    with pytest.raises(OSError):
        backend.tail(tmp_path / "missing.jsonl", 1 << 20)


def test_backend_rendezvous_epoch_fencing_contract(backend, tmp_path):
    path = tmp_path / coord.ROUTER_FILE
    assert backend.read_rendezvous(path) is None
    assert backend.publish_rendezvous(
        path, "ra", "127.0.0.1", 8123, 1
    ) is None
    rv = backend.read_rendezvous(path)
    assert (rv["router_id"], rv["epoch"]) == ("ra", 1)

    # a refresh at a STALE epoch is fenced: the winning record comes
    # back, the file stays untouched
    before = counter("coord/fenced_publishes")
    fenced = backend.publish_rendezvous(
        path, "rb", "127.0.0.1", 8200, 0, force=False
    )
    assert (fenced["router_id"], fenced["epoch"]) == ("ra", 1)
    assert counter("coord/fenced_publishes") == before + 1
    assert backend.read_rendezvous(path)["router_id"] == "ra"

    # equal epoch: the lexically smaller id wins the tie-break — "rb"
    # is refused by "ra", but "r0" supersedes it
    assert backend.publish_rendezvous(
        path, "rb", "127.0.0.1", 8200, 1, force=False
    ) is not None
    assert backend.publish_rendezvous(
        path, "r0", "127.0.0.1", 8300, 1, force=False
    ) is None
    assert backend.read_rendezvous(path)["router_id"] == "r0"
    # a router's own refresh of its own record always lands
    assert backend.publish_rendezvous(
        path, "r0", "127.0.0.1", 8301, 1, force=False
    ) is None
    # a takeover (force=True, epoch+1) publishes unconditionally, and
    # the higher epoch now fences everyone below it
    assert backend.publish_rendezvous(
        path, "rz", "127.0.0.1", 8400, 2
    ) is None
    assert backend.read_rendezvous(path)["epoch"] == 2
    assert backend.publish_rendezvous(
        path, "ra", "127.0.0.1", 8123, 1, force=False
    )["router_id"] == "rz"


def test_backend_read_rendezvous_malformed_is_absent(backend, tmp_path):
    path = tmp_path / coord.ROUTER_FILE
    for damage in (
        "not json",
        json.dumps({"something": "else"}),
        json.dumps({"router": {"router_id": "ra"}}),  # missing fields
    ):
        backend.write_doc(path, damage)
        assert backend.read_rendezvous(path) is None


def test_backend_registry_and_config_default():
    assert isinstance(
        coord.make_backend("local"), coord.LocalDirBackend
    )
    assert isinstance(
        coord.make_backend("faultable"), coord.FaultableBackend
    )
    with pytest.raises(ValueError, match="zookeeper"):
        coord.make_backend("zookeeper")

    from deepdfa_tpu.core import Config, config as config_mod

    # the default path allocates nothing new: the shared singleton
    assert coord.backend_from_config(Config()) is coord.LOCAL
    cfg = config_mod.apply_overrides(
        Config(), ["fleet.coord_backend=faultable"]
    )
    faulted = coord.backend_from_config(cfg)
    assert isinstance(faulted, coord.FaultableBackend)
    assert faulted is not coord.LOCAL


# ---------------------------------------------------------------------------
# injected faults: observable ONLY through the FaultableBackend


def test_faultable_latency_delays_and_counts(tmp_path):
    fb = coord.FaultableBackend()
    p = tmp_path / "slow.json"
    fb.set_fault("slow.json", latency_s=0.02)
    before = counter("coord/faults/latency")
    t0 = time.monotonic()
    fb.write_doc(p, "{}")
    assert time.monotonic() - t0 >= 0.02
    assert counter("coord/faults/latency") == before + 1


def test_faultable_stale_lost_and_torn_writes(tmp_path):
    fb = coord.FaultableBackend()
    p = tmp_path / "doc.json"
    fb.write_doc(p, "v1")
    fb.write_doc(p, "v2")

    # a lagging replica of the store serves the overwritten version —
    # exactly once per budgeted stale read
    fb.set_fault("doc.json", stale_reads=1)
    before = counter("coord/faults/stale_read")
    assert fb.read_doc(p) == "v1"
    assert counter("coord/faults/stale_read") == before + 1
    assert fb.read_doc(p) == "v2"
    fb.clear_faults()

    # a lost write is dropped silently; the inner file — what a plain
    # LocalDirBackend sees — is untouched (the fault does not leak)
    fb.set_fault("doc.json", lose_writes=1)
    fb.write_doc(p, "v3")
    assert fb.read_doc(p) == "v2"
    assert coord.LocalDirBackend().read_doc(p) == "v2"
    fb.clear_faults()

    # a torn write lands NON-atomically truncated — the damage
    # atomic_write_text exists to prevent; readers must see "absent",
    # not crash
    rv_doc = json.dumps({"router": {
        "router_id": "ra", "host": "h", "port": 1, "epoch": 1,
        "t_unix": 0.0,
    }})
    fb.set_fault("doc.json", torn_writes=1)
    before = counter("coord/faults/torn_write")
    fb.write_doc(p, rv_doc)
    assert counter("coord/faults/torn_write") == before + 1
    assert fb.read_rendezvous(p) is None


def test_faultable_partition_blocks_until_cleared(tmp_path):
    fb = coord.FaultableBackend()
    p = tmp_path / "hb.json"
    fb.write_doc(p, "{}")
    fb.set_fault("hb.json", partitioned=True)
    before = counter("coord/faults/partition")
    with pytest.raises(OSError, match="injected partition"):
        fb.read_doc(p)
    with pytest.raises(OSError, match="injected partition"):
        fb.write_doc(p, "{}")
    assert counter("coord/faults/partition") == before + 2
    fb.clear_faults()
    assert fb.read_doc(p) == "{}"


def test_faultable_log_lost_and_torn_appends(tmp_path):
    fb = coord.FaultableBackend()
    log = tmp_path / "fleet_log.jsonl"
    handle = fb.open_log(log)
    handle.write_line(json.dumps({"request": {"id": "a"}}))
    fb.set_fault("fleet_log.jsonl", lose_writes=1, torn_writes=1)
    handle.write_line(json.dumps({"request": {"id": "lost"}}))
    handle.write_line(json.dumps({"request": {"id": "torn-entry"}}))
    handle.write_line(json.dumps({"request": {"id": "b"}}))
    handle.close()
    # the lost line vanished, the torn line is unparseable — the
    # torn-tolerant tail skips both and keeps the survivors
    recs = fb.tail_records(log, 1 << 20)
    assert [r["request"]["id"] for r in recs] == ["a", "b"]


# ---------------------------------------------------------------------------
# autoscale: forecast + controller


def test_forecast_rate_trend_and_degenerate_cases():
    assert autoscale.forecast_rate([], 5.0) == 0.0
    assert autoscale.forecast_rate([(0.0, 3.0)], 5.0) == 3.0
    # an exact linear trend extrapolates exactly: slope 2/s over 5 s
    rising = [(float(t), 2.0 * t) for t in range(8)]
    assert autoscale.forecast_rate(rising, 5.0) == pytest.approx(24.0)
    # a falling trend clamps at zero, never a negative rate
    falling = [(float(t), 10.0 - 3.0 * t) for t in range(4)]
    assert autoscale.forecast_rate(falling, 100.0) == 0.0


def test_controller_ctor_validation():
    with pytest.raises(ValueError, match="capacity_rps"):
        autoscale.AutoscaleController(0.0)
    with pytest.raises(ValueError, match="down_fraction"):
        autoscale.AutoscaleController(
            10.0, up_fraction=0.5, down_fraction=0.5
        )


def test_controller_ladder_escalates_one_rung_per_bucket():
    c = autoscale.AutoscaleController(
        10.0, cooldown_s=5.0, max_replicas=3
    )
    d1 = c.decide(9.0, 1, now=0.0)
    assert (d1["action"], d1["stage"]) == ("shed_stage2", 1)
    d2 = c.decide(9.0, 1, now=1.0)
    assert (d2["action"], d2["stage"]) == ("tighten_admission", 2)
    d3 = c.decide(9.0, 1, now=2.0)
    assert d3["action"] == "scale_up" and d3["target_replicas"] == 2
    # cooldown gates the next replica; the admission ladder stays on
    d4 = c.decide(19.0, 2, now=3.0)
    assert (d4["action"], d4["reason"]) == ("hold", "cooldown")
    d5 = c.decide(29.0, 2, now=10.0)
    assert d5["action"] == "scale_up" and d5["target_replicas"] == 3
    d6 = c.decide(29.0, 3, now=30.0)
    assert (d6["action"], d6["reason"]) == ("hold", "at_max_replicas")
    for d in (d1, d2, d3, d4, d5, d6):
        from deepdfa_tpu.fleet.router import AUTOSCALE_ACTIONS

        assert d["action"] in AUTOSCALE_ACTIONS


def test_controller_deescalates_relax_then_scale_down():
    c = autoscale.AutoscaleController(10.0, cooldown_s=0.0)
    c.decide(9.0, 1, now=0.0)  # ladder stage 1 applied
    d = c.decide(1.0, 2, now=1.0)
    assert (d["action"], d["stage"]) == ("relax", 0)
    d2 = c.decide(1.0, 2, now=2.0)
    assert d2["action"] == "scale_down" and d2["target_replicas"] == 1
    d3 = c.decide(1.0, 1, now=3.0)
    assert (d3["action"], d3["reason"]) == ("hold", "at_min_replicas")
    # the band between the fractions is deliberately dead (hysteresis)
    d4 = c.decide(5.0, 1, now=4.0)
    assert (d4["action"], d4["reason"]) == ("hold", "in_band")


class _Admission:
    """The two attributes apply_to touches on the real controller."""

    def __init__(self):
        self.shed_fraction = 0.5
        self.cascade_shed_fraction = 0.4


def test_apply_to_mutates_admission_and_relax_restores():
    c = autoscale.AutoscaleController(10.0)
    adm = _Admission()
    c.apply_to(adm, {"action": "shed_stage2"})
    assert adm.cascade_shed_fraction == pytest.approx(0.2)
    assert adm.shed_fraction == 0.5
    c.apply_to(adm, {"action": "tighten_admission"})
    assert adm.shed_fraction == pytest.approx(0.4)
    # the scale rungs are the caller's; admission policy is untouched
    c.apply_to(adm, {"action": "scale_up"})
    assert adm.shed_fraction == pytest.approx(0.4)
    assert adm.cascade_shed_fraction == pytest.approx(0.2)
    c.apply_to(adm, {"action": "relax"})
    assert adm.shed_fraction == 0.5
    assert adm.cascade_shed_fraction == 0.4


def test_replay_escalates_ahead_and_tracks_replicas():
    c = autoscale.AutoscaleController(
        10.0, cooldown_s=0.0, max_replicas=2
    )
    rates = [(float(t), 2.0 + 1.5 * t) for t in range(10)]
    decisions = autoscale.replay(rates, c, replicas=1)
    actions = [d["action"] for d in decisions]
    assert actions.count("scale_up") == 1
    i = actions.index("scale_up")
    # the full ladder ran before the replica was paid for
    assert "shed_stage2" in actions[:i]
    assert "tighten_admission" in actions[:i]
    # ...and the scale decision landed while offered < capacity: the
    # forecast's lead time, not a reaction to saturation
    assert decisions[i]["offered_rps"] < c.capacity_rps
    assert decisions[i]["replicas"] == 1
    assert decisions[i]["target_replicas"] == 2
    assert all(d["replicas"] == 2 for d in decisions[i + 1:])
    assert [d["offered_rps"] for d in decisions] == [
        pytest.approx(r) for _, r in rates
    ]


def test_arrival_rates_from_log_buckets_gaps_and_torn_tail(tmp_path):
    log = tmp_path / "fleet_log.jsonl"
    lines = [
        {"request": {"id": "a", "t_unix": 100.2}},
        {"request": {"id": "b", "t_unix": 100.9}},
        {"fleet_event": {"name": "join", "t_unix": 101.0}},
        {"request": {"id": "c", "t_unix": 103.4}},
    ]
    log.write_text("\n".join(json.dumps(ln) for ln in lines) + "\n")
    with log.open("a") as f:
        f.write('{"request": {"id": "torn", "t_unix": 104')
    rates = autoscale.arrival_rates_from_log(log, bucket_s=1.0)
    # non-request lines and the torn final line cost nothing; the idle
    # buckets are real 0.0 observations, not missing data
    assert rates == [
        (100.0, 2.0), (101.0, 0.0), (102.0, 0.0), (103.0, 1.0),
    ]
    assert autoscale.arrival_rates_from_log(
        tmp_path / "missing.jsonl"
    ) == []


def test_max_replicas_from_ledger_caps_configured_max():
    n, plan = autoscale.max_replicas_from_ledger(4, {}, 0.0)
    assert n == 4 and plan["reason"] == "unbudgeted"
    # 100 MB of params x4 headroom = 400 MB/replica; a 900 MB budget
    # fits 2 stacks — the ledger cap beats the configured max
    n2, plan2 = autoscale.max_replicas_from_ledger(
        4, {"deepdfa": 100e6}, 900e6
    )
    assert n2 == 2 and plan2["reason"] == "ledger"


def test_autoscale_decisions_are_schema_valid_fleet_log_records(tmp_path):
    from deepdfa_tpu.fleet.router import validate_fleet_log

    c = autoscale.AutoscaleController(10.0, cooldown_s=0.0)
    decisions = autoscale.replay(
        [(0.0, 2.0), (1.0, 9.0), (2.0, 9.5)], c, replicas=1
    )
    log = tmp_path / "fleet_log.jsonl"
    with log.open("w") as f:
        for d in decisions:
            f.write(json.dumps(
                autoscale.AutoscaleController.log_record(d)
            ) + "\n")
    result = validate_fleet_log(log)
    assert result["ok"] is True, result["problems"]
    assert result["autoscale"] == len(decisions)


# ---------------------------------------------------------------------------
# drill: scheduler, record, validation


def test_drill_scheduler_cadence_aggregation_and_counters():
    clk = FakeClock()
    starts: list[float] = []

    def runner(i):
        starts.append(clk.t)
        clk.t += 2.0  # each round takes 2 s of "wall" time
        return {
            "ok": True, "failover_s": 0.5 + 0.1 * i,
            "readmit_s": 1.0, "reseed_s": 0.2,
        }

    before = counter("drill/rounds")
    rec = drill.DrillScheduler(
        runner, rounds=3, interval_s=10.0, mode="smoke",
        sleep=clk.sleep, clock=clk,
    ).run()
    assert counter("drill/rounds") == before + 3
    # cadence between round STARTS: a 2 s round eats into its own gap
    assert starts == [0.0, 10.0, 20.0]
    assert rec["rounds"] == 3 and rec["cadence_s"] == 10.0
    # aggregates hold the trajectory to the WORST round
    assert rec["drill_failover_s"] == pytest.approx(0.7)
    assert rec["drill_readmit_s"] == 1.0
    assert rec["drill_rollback_s"] is None
    assert rec["ok"] is True
    assert [r["round"] for r in rec["per_round"]] == [0, 1, 2]
    assert all(r["seconds"] == 2.0 for r in rec["per_round"])
    assert drill.validate_drill_record(rec) == []


def test_drill_scheduler_folds_round_failure_into_the_record():
    before = counter("drill/failures")

    def runner(i):
        if i == 1:
            raise AssertionError("standby never took over")
        return {"ok": True, "failover_s": 0.4}

    rec = drill.DrillScheduler(
        runner, rounds=2, interval_s=0.0, sleep=lambda s: None
    ).run()
    assert counter("drill/failures") == before + 1
    assert rec["ok"] is False
    bad = rec["per_round"][1]
    assert bad["ok"] is False
    assert "standby never took over" in bad["error"]
    # the failed record still validates structurally — the gate (not
    # the schema) is what rejects it
    assert drill.validate_drill_record(rec) == []


def test_drill_record_ok_requires_measured_failover_under_bound():
    ok_round = {"ok": True, "failover_s": 3.19}
    slow_round = {"ok": True, "failover_s": 3.3}
    mk = lambda rounds: drill.drill_record(  # noqa: E731
        "smoke", 0.0, ("kill-router",), rounds
    )
    assert drill.DRILL_BOUND_S == 3.2
    assert mk([ok_round])["ok"] is True
    assert mk([slow_round])["ok"] is False
    assert mk([{"ok": True}])["ok"] is False  # unmeasured is not ok
    assert mk([])["ok"] is False


def _drill_rec(failover_s: float, mode: str = "smoke") -> dict:
    return drill.drill_record(mode, 0.0, ("kill-router",), [{
        "ok": True, "failover_s": failover_s, "readmit_s": 1.0,
        "reseed_s": 0.1, "round": 0, "seconds": 2.0,
    }])


def test_drill_trajectory_write_next_slot_and_load(tmp_path):
    p1 = drill.write_drill_record(_drill_rec(0.5), tmp_path)
    p2 = drill.write_drill_record(_drill_rec(0.6), tmp_path)
    assert (p1.name, p2.name) == ("DRILL_r01.json", "DRILL_r02.json")
    assert drill.validate_drill_file(p1)["ok"] is True
    traj = bg.load_drill_trajectory(tmp_path)
    assert [e["source"] for e in traj] == [
        "DRILL_r01.json", "DRILL_r02.json",
    ]
    assert traj[0]["round"] == 1
    assert traj[0]["record"]["drill_failover_s"] == 0.5


def test_validate_drill_record_problem_cases():
    assert drill.validate_drill_record("nope") == ["not a JSON object"]
    rec = _drill_rec(0.5)
    assert drill.validate_drill_record(rec) == []
    probs = drill.validate_drill_record(dict(rec, mode="chaos"))
    assert any("mode" in p for p in probs)
    probs = drill.validate_drill_record(dict(rec, rounds=2))
    assert any("per_round has 1" in p for p in probs)
    probs = drill.validate_drill_record(dict(rec, per_round=[{}]))
    assert any("missing ok" in p for p in probs)
    probs = drill.validate_drill_record(dict(rec, drill_failover_s=None))
    assert any("drill_failover_s" in p for p in probs)
    probs = drill.validate_drill_record(dict(rec, scenarios=[]))
    assert any("scenarios" in p for p in probs)


def test_validate_drill_file_unreadable_and_not_json(tmp_path):
    missing = drill.validate_drill_file(tmp_path / "DRILL_r09.json")
    assert missing["ok"] is False
    assert "unreadable" in missing["problems"][0]
    p = tmp_path / "DRILL_r01.json"
    p.write_text("{torn")
    broken = drill.validate_drill_file(p)
    assert broken["ok"] is False
    assert "not JSON" in broken["problems"][0]


# ---------------------------------------------------------------------------
# the drill trajectory gate (obs/bench_gate.py)


def test_drill_gate_bound_pinned_to_the_drill_module():
    # bench_gate must stay importable without the fleet stack, so the
    # bound is mirrored, not imported — this pin is the contract
    assert bg.DRILL_FAILOVER_BOUND_S == drill.DRILL_BOUND_S == 3.2


def test_drill_gate_pass_then_regression_vs_reference(tmp_path):
    drill.write_drill_record(_drill_rec(0.5), tmp_path)
    traj = bg.load_drill_trajectory(tmp_path)
    ok = bg.gate_drill(_drill_rec(0.9), traj)
    assert ok["verdict"] == "pass" and ok["failure_classes"] == []
    ref_checks = [
        c for c in ok["checks"] if c["ref_source"] == "DRILL_r01.json"
    ]
    assert any(c["metric"] == "drill_failover_s" for c in ref_checks)
    # 0.9 vs 0.5 sits inside the ±100% tolerance; 1.5 (3x) does not
    slow = bg.gate_drill(_drill_rec(1.5), traj)
    assert slow["verdict"] == "fail"
    assert slow["failure_classes"] == ["regression"]
    failing = [c for c in slow["checks"] if not c["ok"]]
    assert failing and failing[0]["metric"] == "drill_failover_s"


def test_drill_gate_absolute_bound_fails_without_any_reference():
    rec = _drill_rec(3.5)
    assert rec["ok"] is False  # the recorder already refuses the bound
    res = bg.gate_drill(rec, [])
    assert res["verdict"] == "fail"
    assert "error" in res["failure_classes"]
    assert "regression" in res["failure_classes"]
    bound = [
        c for c in res["checks"]
        if c["ref_source"] == "absolute_bound"
    ]
    assert bound and bound[0]["ok"] is False
    assert bound[0]["direction"] == "bound"
    assert bound[0]["reference"] == 3.2


def test_drill_gate_invalid_record_is_an_error():
    res = bg.gate_drill({"mode": "smoke"}, [])
    assert res["verdict"] == "fail"
    assert "error" in res["failure_classes"]
    assert any(n.startswith("schema:") for n in res["notes"])


def test_drill_gate_mode_mismatch_skips_reference(tmp_path):
    # a smoke drill's in-process stub timings gated against a full
    # drill's subprocess timings compare nothing
    drill.write_drill_record(_drill_rec(0.5, mode="full"), tmp_path)
    traj = bg.load_drill_trajectory(tmp_path)
    res = bg.gate_drill(_drill_rec(2.0, mode="smoke"), traj)
    assert res["verdict"] == "pass"
    assert any(
        "no healthy smoke-mode reference" in n for n in res["notes"]
    )


def test_drill_gate_failed_round_never_rebaselines(tmp_path):
    drill.write_drill_record(_drill_rec(0.5), tmp_path)  # healthy
    drill.write_drill_record(_drill_rec(3.5), tmp_path)  # over bound
    traj = bg.load_drill_trajectory(tmp_path)
    res = bg.gate_drill(_drill_rec(0.9), traj)
    refs = [
        c for c in res["checks"]
        if c["metric"] == "drill_failover_s"
        and c["ref_source"] != "absolute_bound"
    ]
    assert refs and refs[0]["ref_source"] == "DRILL_r01.json"
    assert refs[0]["reference"] == 0.5


def test_committed_drill_trajectory_gates_green():
    """The repo's own DRILL_r* trajectory must load and the newest
    round must pass its gate — `scripts/bench_gate.py --drill` runs the
    same functions in CI."""
    root = Path(__file__).resolve().parents[1]
    traj = bg.load_drill_trajectory(root)
    assert traj, "no committed DRILL_r*.json at the repo root"
    newest = traj[-1]
    assert newest["record"] is not None, newest
    res = bg.gate_drill(
        newest["record"], traj, exclude_source=newest["source"]
    )
    assert res["verdict"] == "pass", res
