"""Fleet telemetry plane tests (ISSUE 19: deepdfa_tpu/obs/aggregate.py
+ deepdfa_tpu/obs/alerts.py; docs/alerts.md) — exact mergeable
histograms, snapshot federation under coordination faults, cross-host
trace stitching, and the burn-rate/drift alert engine. Pure-python over
synthetic clocks and the FaultableBackend; the live-router end-to-end
phase rides in via fleet/smoke.py:run_telemetry_smoke."""

import json
import random
import time
from pathlib import Path

import pytest

from deepdfa_tpu.fleet.coord import FaultableBackend
from deepdfa_tpu.obs import trace as obs_trace
from deepdfa_tpu.obs.aggregate import (
    FixedBucketHistogram,
    FleetAggregator,
    SnapshotPublisher,
    TraceShipper,
    build_snapshot,
    flow_chains,
    read_trace_segments,
    stitch_events,
    stitch_fleet_trace,
    validate_fleet_scrape,
    validate_snapshot,
)
from deepdfa_tpu.obs.alerts import (
    AlertEngine,
    AlertRule,
    default_rules,
    replay_fleet_log,
    validate_alert_record,
)
from deepdfa_tpu.obs.slo import QUANTILES, SloEngine, percentile


# ---------------------------------------------------------------------------
# exact mergeable histograms


def _sample_engines(n_engines=3, n_samples=200, seed=7):
    rng = random.Random(seed)
    return [
        [rng.lognormvariate(-3.0, 1.2) for _ in range(n_samples)]
        for _ in range(n_engines)
    ]


def test_histogram_merge_percentiles_exact_vs_brute_force():
    """THE acceptance property: merging per-replica fixed-bucket
    histograms then taking p50/p95/p99 equals (float-equal, not close)
    the repo percentile rule applied to the union of the quantized
    per-replica multisets."""
    per_replica = _sample_engines()
    hists = []
    union: list[float] = []
    for samples in per_replica:
        h = FixedBucketHistogram()
        h.observe_all(samples)
        hists.append(h)
        union.extend(h.expand())
    merged = FixedBucketHistogram.merged(hists)
    union.sort()
    for q in QUANTILES:
        assert merged.percentile(q) == percentile(union, q)
    assert merged.total() == len(union)


def test_histogram_quantization_is_bounded():
    """The grid's representative (lower edge) never overstates a sample
    and understates it by at most one bucket's relative width."""
    h = FixedBucketHistogram()
    samples = [3.7e-3, 0.25, 1.0, 599.0]
    h.observe_all(samples)
    expanded = sorted(h.expand())
    assert len(expanded) == len(samples)
    for s, e in zip(sorted(samples), expanded):
        assert e <= s * (1 + 1e-9), "representative must not overstate"
        # one log-bucket width: exp(ln(hi/lo)/n) ~ 3.2% relative
        assert e >= s * 0.96, "representative within one bucket width"
    # out-of-range samples clamp to the edge buckets, still counted
    h2 = FixedBucketHistogram()
    h2.observe_all([1e-9, 1e6])
    assert h2.total() == 2
    # in-range samples keep ~the grid's relative resolution
    mid = 0.25
    h2 = FixedBucketHistogram()
    h2.observe(mid)
    (e2,) = h2.expand()
    assert abs(e2 - mid) / mid < 0.033


def test_histogram_merge_rejects_grid_mismatch():
    a = FixedBucketHistogram()
    b = FixedBucketHistogram(n=64)
    with pytest.raises(ValueError):
        FixedBucketHistogram.merged([a, b])


def test_histogram_doc_roundtrip():
    h = FixedBucketHistogram()
    h.observe_all([0.001, 0.01, 0.1, 1.0])
    doc = h.to_doc()
    json.loads(json.dumps(doc))  # JSON-safe
    h2 = FixedBucketHistogram.from_doc(doc)
    assert h2.expand() == h.expand()


# ---------------------------------------------------------------------------
# snapshot federation


def _engine_with(n=50, seed=3):
    rng = random.Random(seed)
    eng = SloEngine(windows=(60.0,))
    for _ in range(n):
        eng.observe_request(200, rng.lognormvariate(-3.0, 1.0))
    return eng


def test_snapshot_builds_and_validates(tmp_path):
    eng = _engine_with()
    doc = build_snapshot("r0", {"primary": eng}, seq=0)
    assert validate_snapshot(doc) == []
    snap = doc["fleet_snapshot"]
    assert snap["source"] == "r0"
    assert snap["requests_total"] == 50
    assert "anchor_unix_us" in snap and "anchor_mono_us" in snap


def test_staleness_marked_never_dropped(tmp_path):
    """A replica that stops publishing ages into `stale` but keeps its
    last snapshot in the fleet view — marked, not dropped."""
    clock = {"t": 1000.0}
    eng = _engine_with()
    pub = SnapshotPublisher(
        tmp_path, "r0", slo_engines=lambda: {"primary": eng},
        clock=lambda: clock["t"],
    )
    pub.publish()
    agg = FleetAggregator(
        tmp_path, stale_after_s=10.0, clock=lambda: clock["t"]
    )
    col = agg.collect()
    assert col["replicas"]["r0"]["stale"] is False
    clock["t"] += 60.0  # r0 goes quiet for a minute
    col = agg.collect()
    assert "r0" in col["replicas"], "stale replica must stay visible"
    assert col["replicas"]["r0"]["stale"] is True
    assert col["stale"] == ["r0"]
    # and the scrape carries the staleness marker
    text = agg.exposition()
    assert 'deepdfa_fleet_replica_stale{replica="r0"' in text


def test_torn_snapshot_write_survives_via_other_slot(tmp_path):
    backend = FaultableBackend()
    eng = _engine_with()
    pub = SnapshotPublisher(
        tmp_path, "r0", slo_engines=lambda: {"primary": eng},
        backend=backend,
    )
    pub.publish()  # seq 0, slot a, clean
    backend.set_fault("metrics-r0-*.json", torn_writes=1)
    eng.observe_request(200, 0.5)
    pub.publish()  # seq 1, slot b, torn
    col = FleetAggregator(tmp_path, backend=backend).collect()
    assert "r0" in col["replicas"]
    assert col["replicas"]["r0"]["snapshot"]["seq"] == 0
    assert col["problems"], "the torn slot must be reported, not hidden"


def test_partition_served_from_cache_then_heals(tmp_path):
    backend = FaultableBackend()
    eng = _engine_with()
    pub = SnapshotPublisher(
        tmp_path, "r0", slo_engines=lambda: {"primary": eng},
        backend=backend,
    )
    pub.publish()
    agg = FleetAggregator(tmp_path, backend=backend)
    assert "r0" in agg.collect()["replicas"]
    backend.set_fault("metrics-*", partitioned=True)
    col = agg.collect()
    assert "r0" in col["replicas"], "partition must not erase the view"
    assert col["replicas"]["r0"]["cached"] is True
    backend.clear_faults()
    col = agg.collect()
    assert col["replicas"]["r0"]["cached"] is False


def test_fleet_scrape_validates(tmp_path):
    for rid, seed in (("r0", 1), ("r1", 2)):
        eng = _engine_with(seed=seed)
        SnapshotPublisher(
            tmp_path, rid, slo_engines=lambda eng=eng: {"primary": eng}
        ).publish()
    agg = FleetAggregator(tmp_path)
    text = agg.exposition()
    report = validate_fleet_scrape(text)
    assert report["ok"], report["problems"]
    assert report["replicas"] == ["r0", "r1"]
    # mutating a family name out of schema must fail the check
    broken = text.replace("deepdfa_fleet_agg_latency_ms", "made_up_fam")
    assert not validate_fleet_scrape(broken)["ok"]


# ---------------------------------------------------------------------------
# cross-host trace stitching


def _emit_flow(tmp_path, backend, torn=False):
    """Router + replica tracers shipping one X-Request-Id flow chain;
    optionally a torn write on the replica's second shipped segment."""
    fleet_dir = tmp_path / "fleet"
    fleet_dir.mkdir(exist_ok=True)
    tr_router = obs_trace.Tracer(tmp_path / "tr_r", process_name="router")
    tr_replica = obs_trace.Tracer(
        tmp_path / "tr_p", process_name="replica-r0"
    )
    fid = "req-1"
    t0 = obs_trace.Tracer.now_us()
    tr_router.emit({
        "name": "request", "cat": "fleet", "ph": "s", "id": fid,
        "ts": t0,
    })
    t1 = obs_trace.Tracer.now_us()
    tr_replica.emit({
        "name": "request", "cat": "fleet", "ph": "t", "id": fid,
        "ts": t1,
    })
    ship_r = TraceShipper(
        fleet_dir, "router", backend=backend, tracer=tr_router
    )
    ship_p = TraceShipper(
        fleet_dir, "r0", backend=backend, tracer=tr_replica
    )
    ship_r.ship()
    ship_p.ship()  # anchor + arrival, clean
    if torn:
        backend.set_fault("trace-seg-r0.jsonl", torn_writes=1)
    for i, name in enumerate(("pack", "dispatch", "fetch")):
        tr_replica.emit({
            "name": name, "cat": "serve", "ph": "X",
            "ts": t1 + 10.0 * (i + 1), "dur": 8.0,
        })
    tr_replica.emit({
        "name": "request", "cat": "fleet", "ph": "f", "id": fid,
        "ts": t1 + 50.0,
    })
    ship_p.ship()
    return fleet_dir, fid


def test_stitched_flow_chain_unbroken(tmp_path):
    backend = FaultableBackend()
    fleet_dir, fid = _emit_flow(tmp_path, backend)
    out = stitch_fleet_trace(
        fleet_dir, tmp_path / "trace.json", backend=backend
    )
    assert fid in out["unbroken_flows"]
    assert out["broken_flows"] == []
    doc = json.loads((tmp_path / "trace.json").read_text())
    events = doc["traceEvents"]
    # the two processes land on DISTINCT synthetic pids with
    # source-prefixed names, and every non-metadata ts is on the
    # stitched unix timebase (same clock, so ordering holds)
    names = {
        ev["args"]["name"] for ev in events
        if ev.get("ph") == "M" and ev.get("name") == "process_name"
    }
    assert names == {"router:router", "r0:replica-r0"}
    pids = {ev["pid"] for ev in events}
    assert len(pids) == 2


def test_stitched_flow_survives_torn_segment_write(tmp_path):
    backend = FaultableBackend()
    fleet_dir, fid = _emit_flow(tmp_path, backend, torn=True)
    out = stitch_fleet_trace(
        fleet_dir, tmp_path / "trace.json", backend=backend
    )
    assert fid in out["unbroken_flows"], (
        "a torn span line must cost that span, never the flow chain"
    )
    segs = read_trace_segments(fleet_dir, backend=backend)
    replica_names = [e.get("name") for e in segs["r0"]["events"]]
    assert "pack" not in replica_names, "the torn line must be dropped"
    assert "dispatch" in replica_names and "fetch" in replica_names


def test_unanchored_source_flagged(tmp_path):
    """A segment whose anchor line was lost keeps its events but is
    reported unanchored (its clock cannot be stitched)."""
    fleet_dir = tmp_path / "fleet"
    fleet_dir.mkdir()
    (fleet_dir / "trace-seg-rx.jsonl").write_text(
        json.dumps({
            "name": "pack", "cat": "serve", "ph": "X", "ts": 10.0,
            "dur": 5.0, "pid": 1, "tid": 1,
        }) + "\n"
    )
    segments = read_trace_segments(fleet_dir)
    events, summary = stitch_events(segments)
    assert summary["unanchored"] == ["rx"]
    assert any(ev.get("name") == "pack" for ev in events)


def test_flow_chains_census():
    events = [
        {"ph": "s", "id": "a", "pid": 1, "ts": 0},
        {"ph": "t", "id": "a", "pid": 2, "ts": 1},
        {"ph": "f", "id": "a", "pid": 2, "ts": 2},
        {"ph": "s", "id": "b", "pid": 1, "ts": 0},  # never arrives
    ]
    chains = flow_chains(events)
    assert chains["a"]["unbroken"] is True
    assert chains["b"]["unbroken"] is False


# ---------------------------------------------------------------------------
# alert engine


def test_burn_rate_fires_on_burst_and_resolves():
    """Multi-window burn rate with an explicit clock: both windows must
    burn for the rule to fire, and clean traffic drains the fast window
    back under budget."""
    rule = AlertRule(
        name="burn", kind="burn_rate", threshold=1.0,
        windows=(60.0, 300.0), params={"budget": 0.01, "min_count": 5},
    )
    eng = AlertEngine([rule])
    t = 1000.0
    for _ in range(100):
        eng.observe_request(200, now=t)
    assert eng.evaluate(now=t) == []  # healthy
    for _ in range(50):
        eng.observe_request(500, now=t + 10.0)
    recs = eng.evaluate(now=t + 11.0)
    states = [r["alert"]["state"] for r in recs]
    assert states == ["pending", "firing"]  # for_s=0: same tick
    for r in recs:
        assert validate_alert_record(r) == []
    # 400s later the slow window still "remembers" nothing (evicted) —
    # and either way the min-of-windows observed burn is below threshold
    recs = eng.evaluate(now=t + 411.0)
    assert [r["alert"]["state"] for r in recs] == ["resolved"]
    assert eng.firing() == []


def test_burn_rate_sub_second_windows_hold_their_counts():
    """Regression: sub-second windows must count exactly (the SLO
    engine's per-second bucketing would evict the live second partway
    through — obs/alerts.py keeps exact event timestamps below 5 s)."""
    rule = AlertRule(
        name="fast", kind="burn_rate", threshold=1.0,
        windows=(0.5, 1.5), params={"budget": 0.05, "min_count": 3},
    )
    eng = AlertEngine([rule])
    t = 123.9  # fractional part past the horizon: the old failure mode
    for _ in range(10):
        eng.observe_request(500, now=t)
    recs = eng.evaluate(now=t + 0.05)
    assert [r["alert"]["state"] for r in recs] == ["pending", "firing"]


def test_burn_rate_slow_window_guards_stale_blip():
    """An error burst that already aged out of the fast window must not
    fire, even while the slow window still contains it: min-of-windows
    is what distinguishes an incident from a memory."""
    rule = AlertRule(
        name="burn", kind="burn_rate", threshold=1.0,
        windows=(60.0, 300.0), params={"budget": 0.01, "min_count": 5},
    )
    eng = AlertEngine([rule])
    t = 1000.0
    for _ in range(50):
        eng.observe_request(500, now=t)
    for _ in range(50):
        eng.observe_request(200, now=t + 100.0)
    assert eng.evaluate(now=t + 100.0) == []
    assert eng.firing() == []


def test_drift_alert_on_injected_calibration_shift():
    """The PR-12 reuse: per-tenant calibrated in-band fraction drifts
    past target -> firing; the shift healing -> resolved. Probabilities
    go through the same temperature_scale/in_band helpers calibrate.py
    serves with."""
    pytest.importorskip("numpy")
    rule = AlertRule(
        name="acme_drift", kind="drift", threshold=0.2,
        windows=(30.0,),
        params={
            "tenant": "acme", "temperature": 1.0,
            "band": (0.4, 0.6), "target": 0.1, "min_samples": 10,
        },
    )
    eng = AlertEngine([rule])
    t = 500.0
    # healthy: ~10% of probs in the uncertainty band
    for i in range(40):
        prob = 0.5 if i % 10 == 0 else 0.9
        eng.observe_request(200, tenant="acme", prob=prob, now=t)
    assert eng.evaluate(now=t + 1.0) == []
    # injected shift: everything collapses into the band
    for _ in range(40):
        eng.observe_request(200, tenant="acme", prob=0.5, now=t + 2.0)
    recs = eng.evaluate(now=t + 3.0)
    assert [r["alert"]["state"] for r in recs] == ["pending", "firing"]
    assert recs[-1]["alert"]["tenant"] == "acme"
    for r in recs:
        assert validate_alert_record(r) == []
    # the window forgets the shift -> healthy mix again -> resolved
    t2 = t + 40.0
    for i in range(40):
        prob = 0.5 if i % 10 == 0 else 0.9
        eng.observe_request(200, tenant="acme", prob=prob, now=t2)
    recs = eng.evaluate(now=t2 + 1.0)
    assert [r["alert"]["state"] for r in recs] == ["resolved"]


def test_other_tenant_probs_do_not_feed_drift():
    rule = AlertRule(
        name="acme_drift", kind="drift", threshold=0.2,
        windows=(30.0,),
        params={
            "tenant": "acme", "temperature": 1.0,
            "band": (0.4, 0.6), "target": 0.1, "min_samples": 10,
        },
    )
    eng = AlertEngine([rule])
    for _ in range(40):
        eng.observe_request(200, tenant="other", prob=0.5, now=100.0)
    assert eng.evaluate(now=101.0) == []


def test_for_s_requires_sustained_condition():
    rule = AlertRule(
        name="burn", kind="burn_rate", threshold=1.0, for_s=5.0,
        windows=(60.0,), params={"budget": 0.01, "min_count": 1},
    )
    eng = AlertEngine([rule])
    t = 0.0
    eng.observe_request(500, now=t)
    recs = eng.evaluate(now=t + 1.0)
    assert [r["alert"]["state"] for r in recs] == ["pending"]
    recs = eng.evaluate(now=t + 3.0)
    assert recs == []  # still pending, not yet for_s
    recs = eng.evaluate(now=t + 7.0)
    assert [r["alert"]["state"] for r in recs] == ["firing"]


def test_default_rules_cover_issue_catalog():
    kinds = {r.kind for r in default_rules()}
    names = {r.name for r in default_rules()}
    assert "burn_rate" in kinds
    assert {"coord_backend_faults", "coord_poll_exhausted",
            "autoscale_saturated"} <= names


def test_alert_records_schema_valid_and_fleet_log_grows(tmp_path):
    from deepdfa_tpu.fleet.router import FleetLog, validate_fleet_log

    log_path = tmp_path / "fleet_log.jsonl"
    log = FleetLog(log_path)
    rule = AlertRule(
        name="burn", kind="burn_rate", threshold=1.0,
        windows=(60.0,), params={"budget": 0.01, "min_count": 1},
    )
    eng = AlertEngine([rule], sink=log.append)
    eng.observe_request(500, now=10.0)
    eng.evaluate(now=11.0)
    log.close()
    report = validate_fleet_log(log_path)
    assert report["ok"], report["problems"]
    assert report["alerts"] == 2  # pending + firing
    # a malformed alert record must fail validation
    with log_path.open("a") as f:
        f.write(json.dumps({"alert": {"rule": "x"}}) + "\n")
    report = validate_fleet_log(log_path)
    assert not report["ok"]


def test_replay_fleet_log_detects_recorded_burst(tmp_path):
    from deepdfa_tpu.fleet.router import FleetLog

    log_path = tmp_path / "fleet_log.jsonl"
    log = FleetLog(log_path)
    t = 1000.0
    for i in range(30):
        log.append({
            "request": {
                "id": f"ok-{i}", "status": 200, "latency_ms": 5.0,
                "t_unix": t + i * 0.01,
            }
        })
    for i in range(30):
        log.append({
            "request": {
                "id": f"err-{i}", "status": 500, "latency_ms": 5.0,
                "t_unix": t + 1.0 + i * 0.01,
            }
        })
    log.close()
    out = replay_fleet_log(log_path, rules=[AlertRule(
        name="burn", kind="burn_rate", threshold=1.0,
        windows=(60.0,), params={"budget": 0.01, "min_count": 5},
    )])
    assert out["records"] == 60
    assert out["fired"] == ["burn"]
    for rec in out["transitions"]:
        assert validate_alert_record(rec) == []


# ---------------------------------------------------------------------------
# the end-to-end phase (live router, real scrape, real fleet log)


def test_telemetry_smoke_phase(tmp_path):
    from deepdfa_tpu.fleet.smoke import run_telemetry_smoke

    t0 = time.monotonic()
    out = run_telemetry_smoke(tmp_path)
    wall = time.monotonic() - t0
    assert out["ok"], out
    assert out["merged_p99_exact"], out
    assert out["trace"]["unbroken_flow"], out
    assert out["alerts"]["burn_fired_resolved"], out
    assert out["alerts"]["drift_fired_resolved"], out
    assert wall < 60.0, f"telemetry phase took {wall:.1f}s"


def test_smoke_verdict_flags_telemetry_failures():
    from deepdfa_tpu.fleet.smoke import smoke_verdict

    bad = smoke_verdict({})
    assert any("histogram merge must be exact" in b for b in bad)
    assert any("flow chain" in b for b in bad)
    assert any("burn-rate or drift" in b for b in bad)
