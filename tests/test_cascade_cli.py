"""CLI surface of the cascade/quant layer (docs/cascade.md): the
calibration command, the cascade-log schema checker mode, and the
accuracy-vs-device-time frontier bench (the ISSUE-12 acceptance
drive)."""

import json
import os
import pathlib
import subprocess
import sys

REPO = pathlib.Path(__file__).parents[1]

from conftest import run_cli  # noqa: E402


def test_cascade_calibrate_cli(tmp_path):
    """`cascade-calibrate`: labeled scores jsonl -> temperature + band
    overrides."""
    import numpy as np

    rng = np.random.default_rng(0)
    z = rng.normal(0, 1.2, 200)
    probs = 1 / (1 + np.exp(-z * 2.0))  # over-sharpened
    labels = (rng.random(200) < 1 / (1 + np.exp(-z))).astype(int)
    scores = tmp_path / "scores.jsonl"
    with scores.open("w") as f:
        for p, y in zip(probs, labels):
            f.write(json.dumps({"prob": float(p), "label": int(y)}) + "\n")
    out = tmp_path / "calib.json"
    res = run_cli(
        tmp_path, "cascade-calibrate", "--scores", str(scores),
        "--target-escalation", "0.3", "--out", str(out), timeout=120,
    )
    rec = json.loads(out.read_text())
    assert rec["temperature"] > 1.2  # softened back
    lo, hi = rec["band"]
    assert 0.0 <= lo < 0.5 < hi <= 1.0
    assert abs(rec["dev_escalation_rate"] - 0.3) < 0.07
    assert any(
        ov.startswith("serve.cascade_band=") for ov in rec["overrides"]
    )
    assert res.returncode == 0


def test_check_obs_schema_cascade_log(tmp_path):
    """`check_obs_schema --cascade-log` accepts a well-formed cascade
    serve_log and rejects one whose escalated entry lost its stage-2
    attribution."""
    from deepdfa_tpu.obs.slo import CASCADE_STAGES, STAGES, SloEngine

    eng = SloEngine(stages=STAGES + CASCADE_STAGES)
    eng.observe_request(
        200, 0.01, extra={"cascade_stage1": 0.002, "cascade_stage2": 0.006}
    )
    good = tmp_path / "good.jsonl"
    entries = [
        {"request": {
            "id": "r0", "status": 200, "latency_ms": 10.0,
            "t_unix": 1.0, "stage": 2, "stage1_prob": 0.5,
            "calibrated_prob": 0.5, "cascade_stage1_ms": 2.0,
            "cascade_stage2_ms": 6.0,
        }},
        {"request": {
            "id": "r1", "status": 200, "latency_ms": 3.0,
            "t_unix": 1.5, "stage": 1, "stage1_prob": 0.9,
            "calibrated_prob": 0.9, "cascade_stage1_ms": 2.0,
        }},
        {"serve": {"requests": 2.0},
         "serve_slo": eng.snapshot(),
         "cascade": {"requests": 2.0, "escalations": 1.0, "sheds": 0.0,
                     "escalation_rate": 0.5,
                     "stage2_steady_state_recompiles": 0}},
    ]
    good.write_text("".join(json.dumps(e) + "\n" for e in entries))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    res = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "check_obs_schema.py"),
         "--cascade-log", str(good)],
        capture_output=True, text=True, env=env, timeout=120,
    )
    assert res.returncode == 0, res.stdout + res.stderr

    bad_entries = [dict(entries[0]), entries[1], entries[2]]
    bad_entries[0] = {"request": {
        k: v for k, v in entries[0]["request"].items()
        if k != "cascade_stage2_ms"
    }}
    bad = tmp_path / "bad.jsonl"
    bad.write_text("".join(json.dumps(e) + "\n" for e in bad_entries))
    res = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "check_obs_schema.py"),
         "--cascade-log", str(bad)],
        capture_output=True, text=True, env=env, timeout=120,
    )
    assert res.returncode == 1
    assert "cascade_stage2_ms" in res.stdout + res.stderr


def test_bench_cascade_smoke(tmp_path):
    """scripts/bench_cascade.py --smoke: the frontier acceptance drive —
    cascade req/s strictly exceeds combined-only, AUC within the pinned
    drift bound, quantized stage-2 under half the fp32 bytes, zero
    steady-state recompiles across both family ladders (the script
    itself raises on any violation; bench.py --child-cascade consumes
    the same fn)."""
    out = tmp_path / "cascade_bench.json"
    proc = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "bench_cascade.py"),
         "--smoke", "--out", str(out)],
        env=dict(os.environ, DEEPDFA_TPU_PLATFORM="cpu",
                 JAX_PLATFORMS="cpu",
                 DEEPDFA_TPU_STORAGE=str(tmp_path)),
        cwd=REPO, capture_output=True, text=True, timeout=400,
    )
    assert proc.returncode == 0, (proc.stdout + proc.stderr)[-2000:]
    record = json.loads(out.read_text())
    assert record["metric"] == "cascade_req_per_sec"
    assert record["cascade_speedup"] > 1.0
    assert 0.0 < record["cascade_escalation_rate"] < 1.0
    assert record["cascade_score_drift"] <= 0.05
    assert record["quant_param_bytes_fraction"] < 0.5
    assert record["quant_calibration_drift"] <= 0.05
    assert record["cascade_steady_state_recompiles"] == 0
    # the trained screen actually ranks (the drift metric's premise)
    assert record["cascade_stage1_auc"] > 0.7
    # gate round trip: the record passes the bench gate's new entries
    from deepdfa_tpu.obs import bench_gate

    verdict = bench_gate.gate(
        {**record, "platform": "cpu"},
        bench_gate.load_trajectory(REPO),
    )
    failed = [c for c in verdict["checks"] if not c["ok"]]
    assert not [
        c for c in failed
        if c["metric"].startswith(("cascade_", "quant_"))
    ], failed
