"""GenTrainer: dp-sharded seq2seq training overfits a tiny copy task."""

import numpy as np
import pytest

from deepdfa_tpu.core import Config, MeshConfig
from deepdfa_tpu.core.config import apply_overrides
from deepdfa_tpu.data import gen_data
from deepdfa_tpu.models import t5 as t5m
from deepdfa_tpu.models import t5_gen as gen
from deepdfa_tpu.parallel import make_mesh
from deepdfa_tpu.train.gen_loop import GenTrainer

# heavy compiles / subprocesses: excluded from the default fast lane
# (pyproject addopts); run via `pytest -m slow` or `pytest -m ""`
pytestmark = pytest.mark.slow

EOS, PAD = 2, 0


def _copy_task(rng, n, src_len=10, tgt_len=8):
    """source = random tokens + eos; target = first tgt_len-1 tokens + eos."""
    src = np.zeros((n, src_len), np.int32)
    tgt = np.zeros((n, tgt_len), np.int32)
    for i in range(n):
        L = rng.integers(3, tgt_len - 1)
        toks = rng.integers(3, 20, L)
        src[i, :L] = toks
        src[i, L] = EOS
        tgt[i, :L] = toks
        tgt[i, L] = EOS
    return src, tgt


@pytest.fixture(scope="module")
def trained():
    rng = np.random.default_rng(0)
    src, tgt = _copy_task(rng, 32)
    cfg = apply_overrides(
        Config(),
        ["train.optim.name=adamw", "train.optim.learning_rate=0.01",
         "train.optim.warmup_frac=0.0"],
    )
    gcfg = gen.GenConfig(
        encoder=t5m.T5Config.tiny(vocab_size=32, remat=False, dropout_rate=0.0),
        max_target_length=8,
        beam_size=2,
    )
    import jax

    mesh = make_mesh(MeshConfig(dp=2), devices=jax.devices()[:2])
    trainer = GenTrainer(cfg, gcfg, mesh=mesh)
    state = trainer.init_state(seed=0)
    batches = gen_data.batches_of(src, tgt, num_shards=2, rows_per_shard=16)
    ppl0 = trainer.eval_ppl(state, batches)
    import jax

    for step in range(60):
        state, loss = trainer.train_step(
            state, batches[0], jax.random.key(step)
        )
    return trainer, state, batches, src, tgt, ppl0


def test_loss_decreases_and_ppl_improves(trained):
    trainer, state, batches, _, _, ppl0 = trained
    ppl1 = trainer.eval_ppl(state, batches)
    assert np.isfinite(ppl1)
    assert ppl1 < ppl0 / 2, (ppl0, ppl1)


def test_overfit_decodes_copy(trained):
    trainer, state, _, src, tgt, _ = trained
    preds = trainer.decode(state, src[:8], beam_size=2, batch_rows=8)
    refs = gen.trim_at_eos(tgt[:8], EOS, PAD)
    match = sum(p == r for p, r in zip(preds, refs))
    assert match >= 6, (preds, refs)


def test_eval_bleu_em(trained):
    trainer, state, _, src, tgt, _ = trained
    refs = gen.trim_at_eos(tgt[:8], EOS, PAD)
    scores = trainer.eval_bleu_em(state, src[:8], refs, beam_size=2)
    assert scores["em"] >= 75.0
    assert scores["bleu"] > 50.0
    assert scores["bleu_em"] == scores["bleu"] + scores["em"]


def test_fit_early_stopping_and_checkpoints(tmp_path, trained):
    """fit() saves best-ppl checkpoints and early-stops on dual counters."""
    import jax

    trainer, state, batches, src, tgt, _ = trained
    ckpt = trainer.make_checkpoints(tmp_path / "ppl")
    seen = []
    state = trainer.fit(
        state,
        train_batches=lambda _e: batches,
        val_batches=lambda: batches,
        checkpoints=ckpt,
        max_epochs=2,
        patience=1,
        log_fn=seen.append,
    )
    assert len(seen) >= 1
    assert all("val_ppl" in r for r in seen)
    best = ckpt.best_metrics()
    assert best is not None and "val_ppl" in best


def test_gen_readers_roundtrip(tmp_path):
    import json

    f = tmp_path / "dev.jsonl"
    rows = [
        {"code_tokens": ["int", "x", "=", "1", ";"], "docstring_tokens": ["set", "x"]},
        {"idx": 7, "code_tokens": ["return", "0", ";"], "docstring_tokens": ["done"]},
    ]
    f.write_text("\n".join(json.dumps(r) for r in rows))
    ex = gen_data.read_summarize_examples(str(f))
    assert len(ex) == 2
    assert ex[0].source == "int x = 1 ;"
    assert ex[1].idx == 7 and ex[1].target == "done"

    src = tmp_path / "a.src"
    trg = tmp_path / "a.trg"
    src.write_text("x = 1\ny = 2\n")
    trg.write_text("X = 1\nY = 2\n")
    ex = gen_data.read_translate_examples(f"{src},{trg}")
    assert [e.target for e in ex] == ["X = 1", "Y = 2"]

    d = tmp_path / "defect.jsonl"
    d.write_text(
        json.dumps({"idx": 1, "code": "int  main()", "target": 1}) + "\n"
    )
    ex = gen_data.read_defect_gen_examples(str(d))
    assert ex[0].target == "true" and ex[0].source == "int main()"

    # clone: pair index + sibling data.jsonl
    (tmp_path / "data.jsonl").write_text(
        "\n".join(
            json.dumps({"idx": i, "func": f"void f{i}()  {{}}"})
            for i in range(3)
        )
    )
    idx = tmp_path / "train.txt"
    idx.write_text("0\t1\t1\n1\t2\t0\n0\t9\t1\n")
    ex = gen_data.read_clone_examples(str(idx))
    assert len(ex) == 2  # url 9 missing -> skipped
    assert ex[0].label == 1 and ex[1].label == 0
    assert ex[0].source == "void f0() {}"
