"""Device efficiency ledger + crash flight recorder (ISSUE 10,
deepdfa_tpu/obs/ledger.py + obs/flight.py, docs/efficiency.md).

The load-bearing contracts, in-process:

- the ONE cost-analysis reader (list-vs-dict shim) feeds both Table-5
  profiling (eval/profiling.compiled_cost is a thin client) and the
  runtime ledger;
- per-signature sites accumulate compiles + executions into rolling
  FLOP/s and a roofline position against injected/measured ceilings;
- the HBM ledger max-merges per-phase watermarks and books per-entry
  param bytes; OOM exceptions are recognized;
- the flight recorder's rings are bounded, its postmortems are
  schema-valid for every declared trigger, and validation rejects
  malformed documents (the `check_obs_schema.py --postmortem` surface);
- zero-steady-state-recompile census pinned WITH the ledger on: serve
  executor lowerings and scores are unchanged vs ledger-off, and the
  GraphTrainer epoch record's ledger section shows exactly one compile
  per signature across epochs with a loss trajectory identical to a
  ledger-off run (default path byte-identical).
"""

import json

import numpy as np
import pytest

from deepdfa_tpu.core import Config, MeshConfig, config as config_mod
from deepdfa_tpu.obs import flight as obs_flight, ledger as obs_ledger
from deepdfa_tpu.obs import metrics as obs_metrics, trace as obs_trace

NODE_BUDGET, EDGE_BUDGET = 2048, 8192


@pytest.fixture(autouse=True)
def _clean_singletons():
    """Every test starts and ends without an installed ledger/recorder
    (module singletons must not leak across the suite)."""
    obs_ledger.disable()
    obs_flight.uninstall()
    yield
    obs_ledger.disable()
    obs_flight.uninstall()


# ---------------------------------------------------------------------------
# the one cost-analysis reader


def test_read_cost_analysis_and_thin_client():
    import jax
    import jax.numpy as jnp

    compiled = jax.jit(lambda x: x @ x).lower(
        jnp.ones((32, 32), jnp.float32)
    ).compile()
    cost = obs_ledger.read_cost_analysis(compiled)
    assert cost["flops"] > 0
    assert "cost_analysis" in cost

    # eval/profiling.compiled_cost reads through the SAME reader and,
    # with a tag, books the compile as a ledger site
    from deepdfa_tpu.eval.profiling import compiled_cost

    led = obs_ledger.enable()
    out = compiled_cost(
        lambda x: x @ x, jnp.ones((32, 32), jnp.float32),
        ledger_tag="profiling", ledger_signature="S32",
    )
    assert out["flops"] == cost["flops"]
    site = led.snapshot()["sites"]["profiling/S32"]
    assert site["flops"] == cost["flops"]
    assert site["compiles"] == 1
    assert site["compile_seconds"] > 0


def test_site_rollup_mfu_and_gauges():
    reg = obs_metrics.MetricsRegistry()
    led = obs_ledger.enable(
        ceilings={"matmul_flops_per_sec": 1e9,
                  "gather_bytes_per_sec": 1e8},
        registry=reg,
    )
    led.record_compile(
        "train_step", "G4", None, 1.5,
        flops=2e6, bytes_accessed=4e5, live_bytes=1e6,
    )
    led.observe_execution("train_step", "G4", 0.5, n=50)
    view = led.snapshot()["sites"]["train_step/G4"]
    # 2e6 flops x 50 execs / 0.5 s = 2e8 FLOP/s; ceiling 1e9 -> 0.2
    assert view["flops_per_sec"] == pytest.approx(2e8)
    assert view["mfu_vs_measured_ceiling"] == pytest.approx(0.2)
    # 4e5 x 50 / 0.5 = 4e7 B/s; gather ceiling 1e8 -> 0.4
    assert view["bytes_vs_gather_ceiling"] == pytest.approx(0.4)
    assert led.snapshot()["compile_seconds_total"] == pytest.approx(1.5)

    led.publish_gauges()
    snap = reg.snapshot()
    assert snap["ledger/train_step/G4/mfu_vs_measured_ceiling"] == (
        pytest.approx(0.2)
    )
    # every emitted registry tag is covered by the declared schema
    for tag in snap:
        assert obs_metrics.declared(tag) or obs_metrics.declared(
            f"{tag}/count"
        ), tag
    # the bench stamp view
    stamp = led.mfu_record()
    assert stamp["ledger_mfu"]["train_step/G4"] == pytest.approx(0.2)
    assert stamp["compile_seconds_total"] == pytest.approx(1.5)


def test_step_site_join_memory_params_and_oom():
    reg = obs_metrics.MetricsRegistry()
    led = obs_ledger.enable(registry=reg)
    led.record_compile("train_step", "G2", None, 0.1, flops=1e6)
    led.set_step_site("train_step", "G2")
    obs_ledger.observe_step_seconds(0.25)  # the StepTimer join
    obs_ledger.observe_step_seconds(0.25)
    site = led.snapshot()["sites"]["train_step/G2"]
    assert site["executions"] == 2
    assert site["device_seconds"] == pytest.approx(0.5)

    # per-phase watermark max-merges
    led.record_memory("epoch", stats={"peak_bytes_in_use": 100.0})
    led.record_memory("epoch", stats={"peak_bytes_in_use": 70.0})
    assert led.snapshot()["memory"]["epoch"]["peak_bytes_in_use"] == 100.0

    # per-entry param bytes: 1000 f32 + 10 int8 = 4010 bytes
    n = led.record_params("deepdfa:run:best", {
        "a": np.zeros((10, 100), np.float32),
        "b": np.zeros((10,), np.int8),
    })
    assert n == 4010.0
    assert led.snapshot()["params"]["deepdfa:run:best"] == 4010.0

    class FakeOom(RuntimeError):
        pass

    assert obs_ledger.is_oom(FakeOom("RESOURCE_EXHAUSTED: out of memory"))
    assert not obs_ledger.is_oom(ValueError("shape mismatch"))


# ---------------------------------------------------------------------------
# flight recorder


def test_flight_rings_bounded_and_postmortem_valid(tmp_path):
    pm = tmp_path / "postmortem.json"
    rec = obs_flight.install(pm, max_steps=4, max_events=3)
    for i in range(10):
        obs_flight.note_step(i)
    # instants mirror into the ring with tracing OFF
    for i in range(5):
        obs_trace.instant("step_skipped", cat="resilience", consecutive=i)
    path = obs_flight.crash_dump("manual", extra={"reason": "test"})
    assert path == pm and pm.exists()
    doc = json.loads(pm.read_text())
    verdict = obs_flight.validate_postmortem(doc)
    assert verdict["ok"], verdict
    assert verdict["trigger"] == "manual"
    assert verdict["steps"] == 4  # bounded at max_steps
    assert verdict["events"] == 3  # bounded at max_events
    assert doc["postmortem"]["steps"][-1]["step"] == 9  # newest kept
    assert rec.dumps == 1 and rec.last_trigger == "manual"


def test_flight_exception_classification(tmp_path):
    obs_flight.install(tmp_path / "postmortem.json")
    path = obs_flight.note_exception(
        RuntimeError("RESOURCE_EXHAUSTED: failed to allocate 2.1G"),
        where="serve_batch",
    )
    doc = json.loads(path.read_text())["postmortem"]
    assert doc["trigger"] == "oom"
    assert doc["extra"]["where"] == "serve_batch"
    path = obs_flight.note_exception(ValueError("boom"))
    assert json.loads(path.read_text())["postmortem"]["trigger"] == (
        "exception"
    )


def test_flight_ledger_embedded_in_dump(tmp_path):
    led = obs_ledger.enable(registry=obs_metrics.MetricsRegistry())
    led.record_compile("serve_score", "G2", None, 0.2, flops=1e6)
    led.record_memory("warmup", stats={"peak_bytes_in_use": 5e8})
    obs_flight.install(tmp_path / "postmortem.json")
    path = obs_flight.crash_dump("oom")
    pm = json.loads(path.read_text())["postmortem"]
    assert pm["ledger"]["sites"]["serve_score/G2"]["flops"] == 1e6
    assert pm["ledger"]["memory"]["warmup"]["peak_bytes_in_use"] == 5e8
    assert obs_flight.validate_postmortem({"postmortem": pm})["ok"]


def test_validate_postmortem_rejects_malformed():
    assert not obs_flight.validate_postmortem({})["ok"]
    bad = {"postmortem": {
        "version": obs_flight.POSTMORTEM_VERSION,
        "trigger": "not-a-trigger",
        "t_unix": 1.0, "pid": 1, "steps": [], "events": [],
        "metrics": {"made/up/undeclared_tag": 1.0},
    }}
    verdict = obs_flight.validate_postmortem(bad)
    assert not verdict["ok"]
    text = " ".join(verdict["problems"])
    assert "trigger" in text and "undeclared" in text

    ok = {"postmortem": {
        "version": obs_flight.POSTMORTEM_VERSION,
        "trigger": "sigterm",
        "t_unix": 1.0, "pid": 1, "steps": [], "events": [],
        "metrics": {},
    }}
    assert obs_flight.validate_postmortem(ok)["ok"]


def test_check_obs_schema_postmortem_cli(tmp_path):
    import importlib.util
    import sys
    from pathlib import Path

    obs_flight.install(tmp_path / "postmortem.json")
    obs_flight.note_step(1)
    obs_flight.crash_dump("smoke_test")
    repo = Path(__file__).resolve().parent.parent
    spec = importlib.util.spec_from_file_location(
        "check_obs_schema", repo / "scripts" / "check_obs_schema.py"
    )
    mod = importlib.util.module_from_spec(spec)
    sys.modules["check_obs_schema"] = mod
    spec.loader.exec_module(mod)
    assert mod.main(
        ["--postmortem", str(tmp_path / "postmortem.json")]
    ) == 0
    (tmp_path / "bad.json").write_text('{"not": "a postmortem"}')
    assert mod.main(["--postmortem", str(tmp_path / "bad.json")]) == 1


# ---------------------------------------------------------------------------
# census pins: ledger on adds zero lowerings and changes zero bits


@pytest.fixture(scope="module")
def served_model():
    import jax

    from deepdfa_tpu.data import build_dataset, generate, to_examples
    from deepdfa_tpu.graphs.batch import pack
    from deepdfa_tpu.models import DeepDFA

    synth = generate(8, seed=5)
    specs, _ = build_dataset(
        to_examples(synth), train_ids=range(8), limit_all=50,
        limit_subkeys=50,
    )
    cfg = config_mod.apply_overrides(Config(), [
        'data.feat={"limit_all": 50, "limit_subkeys": 50}',
        "model.hidden_dim=8", "model.n_steps=2",
    ])
    model = DeepDFA.from_config(cfg.model, input_dim=cfg.data.feat.input_dim)
    params = model.init(
        jax.random.key(0), pack([], 1, NODE_BUDGET, EDGE_BUDGET)
    )
    return cfg, model, params, specs


def _executor(model, params):
    from deepdfa_tpu.serve.batcher import GgnnExecutor

    return GgnnExecutor(
        model, lambda: params,
        node_budget=NODE_BUDGET, edge_budget=EDGE_BUDGET,
        max_batch_graphs=4,
    )


def test_serve_executor_ledger_census_and_bit_parity(served_model):
    _, model, params, specs = served_model

    # reference: ledger OFF
    ex_off = _executor(model, params)
    ex_off.warmup()
    low_off = ex_off.jit_lowerings()
    scores_off = ex_off.execute("graph", specs[:3])
    assert ex_off.jit_lowerings() == low_off  # steady state

    # ledger ON: same lowerings, bit-identical scores, sites recorded
    led = obs_ledger.enable(registry=obs_metrics.MetricsRegistry())
    ex_on = _executor(model, params)
    report = ex_on.warmup()
    assert ex_on.jit_lowerings() == low_off
    scores_on = ex_on.execute("graph", specs[:3])
    assert ex_on.jit_lowerings() == low_off  # census pinned with ledger
    np.testing.assert_array_equal(scores_on, scores_off)
    sites = led.snapshot()["sites"]
    assert set(sites) == {f"serve_score/G{s}" for s in (1, 2, 4)}
    for label in report:
        site = sites[f"serve_score/{label}"]
        assert site["compiles"] == 1
        assert site["flops"] > 0
    # the executed signature accumulated device time
    assert sites["serve_score/G4"]["executions"] == 1
    assert sites["serve_score/G4"]["device_seconds"] > 0


def test_graph_trainer_ledger_epoch_record(served_model):
    import jax

    from deepdfa_tpu.graphs import shard_bucket_batches
    from deepdfa_tpu.parallel import make_mesh
    from deepdfa_tpu.train import GraphTrainer

    cfg, model, _, specs = served_model
    cfg = config_mod.apply_overrides(cfg, [
        "train.max_epochs=2", "train.prefetch_batches=0",
        "train.log_every_steps=1000",
    ])
    mesh = make_mesh(MeshConfig(dp=1), devices=jax.devices()[:1])

    def batches(_e=0):
        # 2 graphs/batch -> enough steps per epoch for the lagged
        # StepTimer to observe inter-completion step seconds (lag 1)
        return list(shard_bucket_batches(
            specs, 1, 2, NODE_BUDGET, EDGE_BUDGET, oversized="drop"
        ))

    def fit(ledger_on):
        if ledger_on:
            obs_ledger.enable(registry=obs_metrics.MetricsRegistry())
        else:
            obs_ledger.disable()
        trainer = GraphTrainer(model, cfg, mesh=mesh)
        state = trainer.init_state(batches()[0])
        records = []
        trainer.fit(state, batches, log_fn=records.append)
        return [r for r in records if "epoch" in r]

    plain = fit(False)
    ledgered = fit(True)
    # default path byte-identical: the ledger adds accounting, never
    # numerics — per-epoch losses match bit for bit
    assert [r["train_loss"] for r in plain] == [
        r["train_loss"] for r in ledgered
    ]
    assert all("ledger" not in r for r in plain)
    sigs = [
        k for k in ledgered[0]["ledger"]["sites"]
        if k.startswith("train_step/")
    ]
    assert len(sigs) == 1  # one batch signature this run
    for rec in ledgered:
        site = rec["ledger"]["sites"][sigs[0]]
        # exactly ONE compile, and it never grows across epochs — the
        # zero-steady-state-recompile pin with the ledger on
        assert site["compiles"] == 1
        assert site["flops"] > 0
        assert site["live_bytes"] > 0
    # the StepTimer join fed device seconds for the epochs' steps
    last = ledgered[-1]["ledger"]["sites"][sigs[0]]
    assert last["executions"] > 0
    assert last["device_seconds"] > 0
    # every flattened ledger tag is schema-declared
    from deepdfa_tpu.train.logging import flatten_scalars

    for tag in flatten_scalars(ledgered[-1]):
        assert obs_metrics.declared(tag), tag


# ---------------------------------------------------------------------------
# bench gate: the absolute ledger-overhead bound


def test_bench_gate_ledger_bounds():
    from deepdfa_tpu.obs import bench_gate as bg

    base = {
        "metric": "deepdfa_infer_graphs_per_sec", "value": 100.0,
        "unit": "graphs/s", "platform": "cpu",
    }
    ok = bg.gate({**base, "obs_ledger_overhead_fraction": 0.01}, [])
    assert ok["verdict"] == "pass"
    bad = bg.gate({**base, "obs_ledger_overhead_fraction": 0.05}, [])
    assert bad["verdict"] == "fail"
    assert "regression" in bad["failure_classes"]
    check = next(
        c for c in bad["checks"]
        if c["metric"] == "obs_ledger_overhead_fraction"
    )
    assert check["direction"] == "bound" and not check["ok"]
    # compile time gates lower-is-better against a reference
    traj = [{"source": "BENCH_r98.json", "round": 98, "record": {
        **base, "compile_seconds_total": 10.0,
    }}]
    slow = bg.gate({**base, "compile_seconds_total": 25.0}, traj)
    assert slow["verdict"] == "fail"
    fast = bg.gate({**base, "compile_seconds_total": 12.0}, traj)
    assert fast["verdict"] == "pass"
