"""Native C++ kernels: build, parity with the Python spec, and speed."""

import shutil
import time

import numpy as np
import pytest

pytestmark = pytest.mark.skipif(
    shutil.which("g++") is None, reason="no C++ toolchain"
)


@pytest.fixture(scope="module")
def native():
    from deepdfa_tpu import native as nat

    assert nat.available()
    return nat


PROGRAMS = [
    """
int f(int a) {
    int x = 1;
    if (a) { x = 2; } else { x = 3; }
    while (a--) { x += 1; }
    return x;
}
""",
    """
int g(char *s, int n) {
    int i = 0, total = 0;
    for (i = 0; i < n; i++) {
        if (s[i] == 'x') { total++; } else { total--; }
    }
    switch (total) { case 1: total = 5; break; default: total = 6; }
    return total;
}
""",
    "void h(void) { }",
]


@pytest.mark.parametrize("code", PROGRAMS, ids=range(len(PROGRAMS)))
def test_reaching_defs_parity(native, code):
    from deepdfa_tpu.frontend import ReachingDefinitions, parse_function

    cpg = parse_function(code)
    rd = ReachingDefinitions(cpg)
    py = rd.solve(backend="python")
    nat = rd.solve(backend="native")
    assert set(py) == set(nat)
    for n in py:
        assert py[n] == nat[n], (n, cpg.nodes[n].code)


@pytest.mark.parametrize("code", PROGRAMS, ids=range(len(PROGRAMS)))
def test_lexer_parity(native, code):
    from deepdfa_tpu.frontend.tokens import tokenize

    py = [(t.kind, t.text, t.line) for t in tokenize(code, backend="python") if t.kind != "eof"]
    nat = [(t.kind, t.text, t.line) for t in native.lex_c_native(code)]
    assert py == nat


def test_lexer_parity_edge_cases(native):
    from deepdfa_tpu.frontend.tokens import tokenize

    cases = [
        'char *s = "a\\"b\\\\";',
        "int x = 0xFF + 1.5e-3 - 07u;",
        "#define FOO(a) \\\n  (a+1)\nint y;",
        "/* multi\nline */ int z; // tail",
        'a <<= 2; b >>= 1; c ...',
        '"unterminated',
        # comments embedded in preprocessor directives (the python spec
        # strips comments before the '#' skip sees them)
        "#define A /* multi\nline */ int q;",
        "#define B /* inline */ junk\nint r;",
        "#define C // tail comment\nint s;",
        "#define D \\\n  cont /* x\ny */ int t;",
    ]
    for code in cases:
        py = [(t.kind, t.text, t.line) for t in tokenize(code, backend="python") if t.kind != "eof"]
        nat = [(t.kind, t.text, t.line) for t in native.lex_c_native(code)]
        assert py == nat, code


def test_native_rd_scales(native):
    """A long linear chain with many defs: native must agree and be fast."""
    from deepdfa_tpu.frontend import ReachingDefinitions, parse_function

    n = 300
    body = "".join(f"x{i % 7} = {i};\n" for i in range(n))
    cpg = parse_function("int big(int a) {\nint x0,x1,x2,x3,x4,x5,x6;\n" + body + "return x0;\n}")
    rd = ReachingDefinitions(cpg)
    t0 = time.perf_counter()
    py = rd.solve(backend="python")
    t_py = time.perf_counter() - t0
    t0 = time.perf_counter()
    nat = rd.solve(backend="native")
    t_nat = time.perf_counter() - t0
    assert py == nat
    # native should not be slower than python at this size (usually ~10x+)
    assert t_nat < t_py * 2, (t_py, t_nat)
