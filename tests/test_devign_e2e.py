"""Devign-style (graph-label-only) dataset end-to-end + long-context sp."""

import json

import numpy as np
import pytest

from deepdfa_tpu.core import Config, MeshConfig, config as config_mod
from deepdfa_tpu.data import build_dataset
from deepdfa_tpu.data.readers import read_devign
from deepdfa_tpu.graphs import pack_shards
from deepdfa_tpu.models import DeepDFA
from deepdfa_tpu.parallel import make_mesh
from deepdfa_tpu.train import GraphTrainer

# heavy compiles / subprocesses: excluded from the default fast lane
# (pyproject addopts); run via `pytest -m slow` or `pytest -m ""`
pytestmark = pytest.mark.slow


def test_devign_reader_to_training(tmp_path, rng):
    """Graph-level labels only (no line annotations) must flow through the
    stored graph_label into the loss."""
    from deepdfa_tpu.data.synthetic import generate

    synth = generate(80, vuln_rate=0.4, seed=6)
    rows = [{"func": s.before, "target": s.label} for s in synth]
    p = tmp_path / "function.json"
    p.write_text(json.dumps(rows))

    examples = read_devign(p)
    assert all(e.vuln_lines == frozenset() for e in examples)
    specs, _ = build_dataset(examples, train_ids=range(80), limit_all=100,
                             limit_subkeys=100)
    # no node labels anywhere, but graph labels survive
    assert all(s.node_vuln.sum() == 0 for s in specs)
    assert any(s.label == 1.0 for s in specs)

    cfg = config_mod.apply_overrides(
        Config(),
        ["model.hidden_dim=8", "train.max_epochs=60",
         "train.optim.learning_rate=0.01"],
    )
    mesh = make_mesh(MeshConfig(dp=8))
    model = DeepDFA.from_config(cfg.model, input_dim=102)
    trainer = GraphTrainer(model, cfg, mesh=mesh)
    batch = pack_shards(specs, 8, 10, 2048, 8192)
    state = trainer.init_state(batch)
    state = trainer.fit(state, lambda e: [batch])
    metrics, _ = trainer.evaluate(state, [batch])
    # learnable via stored graph labels alone
    assert metrics["f1"] > 0.8, metrics


def test_ring_attention_long_context(rng):
    """sp=8 over a 512-token sequence: exact vs full attention."""
    import jax
    from functools import partial
    from jax.sharding import Mesh, PartitionSpec as P

    from deepdfa_tpu.parallel.ring_attention import full_attention, ring_attention

    try:
        from jax import shard_map
    except ImportError:
        from jax.experimental.shard_map import shard_map

    b, h, t, d = 1, 2, 512, 16
    q = rng.standard_normal((b, h, t, d)).astype(np.float32)
    k = rng.standard_normal((b, h, t, d)).astype(np.float32)
    v = rng.standard_normal((b, h, t, d)).astype(np.float32)
    mask = np.ones((b, t), bool)
    mask[:, -37:] = False

    want = np.asarray(full_attention(q, k, v, mask))
    mesh = Mesh(np.array(jax.devices()), ("sp",))
    ring = shard_map(
        partial(ring_attention, axis_name="sp"),
        mesh=mesh,
        in_specs=(P(None, None, "sp", None),) * 3 + (P(None, "sp"),),
        out_specs=P(None, None, "sp", None),
        check_vma=False,
    )
    got = np.asarray(jax.jit(ring)(q, k, v, mask))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=1e-4)
