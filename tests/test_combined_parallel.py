"""dp x tp x sp combined training must match single-device numerics."""

import math

import numpy as np
import pytest

from deepdfa_tpu.core import Config, MeshConfig, config as config_mod
from deepdfa_tpu.data import build_dataset, generate, to_examples
from deepdfa_tpu.data.text import collate_shards
from deepdfa_tpu.data.tokenizer import HashTokenizer
from deepdfa_tpu.models import combined as cmb
from deepdfa_tpu.models.transformer import TransformerConfig
from deepdfa_tpu.parallel import make_mesh
from deepdfa_tpu.train.combined_loop import CombinedTrainer

# heavy compiles / subprocesses: excluded from the default fast lane
# (pyproject addopts); run via `pytest -m slow` or `pytest -m ""`
pytestmark = pytest.mark.slow


def _setup():
    n = 16
    synth = generate(n, vuln_rate=0.4, seed=9)
    specs, _ = build_dataset(to_examples(synth), train_ids=range(n), limit_all=50, limit_subkeys=50)
    by_id = {s.graph_id: s for s in specs}
    tok = HashTokenizer(vocab_size=256)
    token_ids = tok.batch_encode([s.before for s in synth], max_length=32)
    labels = [s.label for s in synth]
    mcfg = cmb.CombinedConfig(
        encoder=TransformerConfig.tiny(
            vocab_size=256, dropout_rate=0.0, max_position_embeddings=40
        ),
        graph_hidden_dim=8,
        graph_input_dim=52,
        head_dropout=0.0,
    )
    cfg = config_mod.apply_overrides(
        Config(), ["train.optim.name=sgd", "train.optim.learning_rate=0.05"]
    )
    return token_ids, labels, by_id, mcfg, cfg, n


@pytest.mark.parametrize("mesh_cfg,sp_variant", [
    (dict(dp=2, tp=2, sp=2), "ring"),
    (dict(dp=1, tp=4, sp=2), "ring"),
    (dict(dp=8, tp=1, sp=1), "ring"),
    (dict(dp=1, tp=1, sp=8), "ring"),
    (dict(dp=2, tp=2, sp=2), "ulysses"),
    (dict(dp=2, tp=1, sp=4), "ulysses"),
    (dict(dp=4, pp=2), "ring"),
    (dict(dp=2, tp=2, pp=2), "ring"),
    # pp x sp compositions (the guard removed in round 3): ring attention
    # inside the GPipe stage body, sp-offset embedding in the pipeline
    (dict(dp=1, sp=2, pp=2), "ring"),
    (dict(dp=1, tp=2, sp=2, pp=2), "ring"),
    (dict(dp=1, sp=2, pp=2), "ulysses"),
])
def test_parallel_matches_single(mesh_cfg, sp_variant):
    import dataclasses as dc

    import jax

    token_ids, labels, by_id, mcfg, cfg, n = _setup()
    if sp_variant != "ring":
        mcfg = dc.replace(
            mcfg, encoder=dc.replace(mcfg.encoder, sp_variant=sp_variant)
        )

    n_dev = math.prod(mesh_cfg.values())
    mesh_p = make_mesh(MeshConfig(**mesh_cfg), devices=jax.devices()[:n_dev])
    mesh_1 = make_mesh(MeshConfig(dp=1), devices=jax.devices()[:1])

    tp_trainer = CombinedTrainer(cfg, mcfg, mesh=mesh_p)
    s_trainer = CombinedTrainer(cfg, mcfg, mesh=mesh_1)

    dp = mesh_cfg["dp"]
    batch_p = collate_shards(
        token_ids, labels, list(range(n)), by_id,
        num_shards=dp, rows_per_shard=n // dp,
        node_budget=1024, edge_budget=4096,
    )
    batch_1 = collate_shards(
        token_ids, labels, list(range(n)), by_id,
        num_shards=1, rows_per_shard=n,
        node_budget=1024, edge_budget=4096,
    )

    sp_state = tp_trainer.init_state(seed=0)
    s_state = s_trainer.init_state(seed=0)

    key = jax.random.key(123)
    for _ in range(2):
        sp_state, loss_p = tp_trainer.train_step(sp_state, batch_p, key)
        s_state, loss_1 = s_trainer.train_step(s_state, batch_1, key)

    np.testing.assert_allclose(
        float(jax.device_get(loss_p)), float(jax.device_get(loss_1)), rtol=5e-4
    )
    chex = pytest.importorskip("chex")
    chex.assert_trees_all_close(
        jax.device_get(sp_state.params),
        jax.device_get(s_state.params),
        rtol=2e-3,
        atol=1e-5,
    )

    # eval parity
    mp, _ = tp_trainer.evaluate(sp_state, [batch_p])
    m1, _ = s_trainer.evaluate(s_state, [batch_1])
    np.testing.assert_allclose(mp["loss"], m1["loss"], rtol=1e-3)
    assert mp["f1"] == m1["f1"]


def test_t5_encode_sp_matches_dense(rng):
    """Ring-attention T5 encode with per-shard relative-bias blocks must
    equal the dense single-device encode."""
    import jax
    from functools import partial
    from jax.sharding import Mesh, PartitionSpec as P

    from deepdfa_tpu.models import t5 as t5m

    try:
        from jax import shard_map
    except ImportError:
        from jax.experimental.shard_map import shard_map

    cfg = t5m.T5Config.tiny(vocab_size=128, dropout_rate=0.0, remat=False)
    params = t5m.init_params(cfg, jax.random.key(0))
    ids = rng.integers(3, 128, (2, 64)).astype(np.int32)
    ids[:, -5:] = 0
    ids[:, -6] = 2

    want = np.asarray(t5m.encode(cfg, params, ids))

    mesh = Mesh(np.array(jax.devices()), ("sp",))
    sp_encode = shard_map(
        partial(t5m.encode, cfg, params, sp_axis="sp"),
        mesh=mesh,
        in_specs=P(None, "sp"),
        out_specs=P(None, "sp", None),
        check_vma=False,
    )
    got = np.asarray(jax.jit(sp_encode)(ids))
    valid = ids != 0
    np.testing.assert_allclose(got[valid], want[valid], rtol=2e-4, atol=2e-4)


def test_t5_encode_ulysses_matches_dense(rng):
    """Ulysses T5 encode (all-to-all head sharding, head-sliced global
    relative bias) must equal the dense single-device encode — the t5
    sp_variant previously supported ring only."""
    import jax
    from functools import partial
    from jax.sharding import Mesh, PartitionSpec as P

    from deepdfa_tpu.models import t5 as t5m
    from deepdfa_tpu.parallel.compat import shard_map

    cfg = t5m.T5Config.tiny(
        vocab_size=128, dropout_rate=0.0, remat=False, sp_variant="ulysses"
    )
    params = t5m.init_params(cfg, jax.random.key(0))
    ids = rng.integers(3, 128, (2, 64)).astype(np.int32)
    ids[:, -5:] = 0
    ids[:, -6] = 2

    want = np.asarray(t5m.encode(cfg, params, ids))

    mesh = Mesh(np.array(jax.devices()[:4]), ("sp",))  # 4 heads -> sp<=4
    sp_encode = shard_map(
        partial(t5m.encode, cfg, params, sp_axis="sp"),
        mesh=mesh,
        in_specs=P(None, "sp"),
        out_specs=P(None, "sp", None),
        check_vma=False,
    )
    got = np.asarray(jax.jit(sp_encode)(ids))
    valid = ids != 0
    np.testing.assert_allclose(got[valid], want[valid], rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("mesh_cfg,sp_variant", [
    (dict(dp=2, tp=2, sp=2), "ring"),
    (dict(dp=1, tp=1, sp=8), "ring"),
    # pp compositions (round-3: the t5+pp guard removed): GPipe over the
    # T5 encoder stack, rel-bias computed per stage, alone and with sp
    (dict(dp=2, pp=2), "ring"),
    (dict(dp=1, tp=2, pp=2), "ring"),
    (dict(dp=1, sp=2, pp=2), "ring"),
    (dict(dp=1, tp=2, sp=2, pp=2), "ring"),
    # round-3: t5 ulysses (head-sliced global rel bias), alone + with pp
    (dict(dp=2, tp=1, sp=2), "ulysses"),
    (dict(dp=1, sp=2, pp=2), "ulysses"),
])
def test_t5_parallel_matches_single(mesh_cfg, sp_variant):
    """T5 combined training on dp x tp x sp x pp == single device (the
    t5-pp and sp-pp paths previously raised NotImplementedError)."""
    import jax

    from deepdfa_tpu.models import t5 as t5m

    n = 8
    from deepdfa_tpu.data import build_dataset, generate, to_examples

    synth = generate(n, vuln_rate=0.4, seed=11)
    specs, _ = build_dataset(
        to_examples(synth), train_ids=range(n), limit_all=50, limit_subkeys=50
    )
    by_id = {s.graph_id: s for s in specs}
    tok = HashTokenizer(vocab_size=256, t5_frame=True)
    token_ids = tok.batch_encode([s.before for s in synth], max_length=32)
    labels = [s.label for s in synth]
    mcfg = t5m.DefectConfig(
        encoder=t5m.T5Config.tiny(
            vocab_size=256, dropout_rate=0.0, remat=False,
            sp_variant=sp_variant,
        ),
        graph_hidden_dim=8,
        graph_input_dim=52,
    )
    cfg = config_mod.apply_overrides(
        Config(), ["train.optim.name=sgd", "train.optim.learning_rate=0.05"]
    )

    n_dev = math.prod(mesh_cfg.values())
    mesh_p = make_mesh(MeshConfig(**mesh_cfg), devices=jax.devices()[:n_dev])
    mesh_1 = make_mesh(MeshConfig(dp=1), devices=jax.devices()[:1])
    p_trainer = CombinedTrainer(cfg, mcfg, mesh=mesh_p)
    s_trainer = CombinedTrainer(cfg, mcfg, mesh=mesh_1)

    dp = mesh_cfg["dp"]
    batch_p = collate_shards(
        token_ids, labels, list(range(n)), by_id,
        num_shards=dp, rows_per_shard=n // dp,
        node_budget=1024, edge_budget=4096, pad_id=tok.pad_id,
    )
    batch_1 = collate_shards(
        token_ids, labels, list(range(n)), by_id,
        num_shards=1, rows_per_shard=n,
        node_budget=1024, edge_budget=4096, pad_id=tok.pad_id,
    )

    p_state = p_trainer.init_state(seed=0)
    s_state = s_trainer.init_state(seed=0)
    key = jax.random.key(7)
    for _ in range(2):
        p_state, loss_p = p_trainer.train_step(p_state, batch_p, key)
        s_state, loss_1 = s_trainer.train_step(s_state, batch_1, key)
    np.testing.assert_allclose(
        float(jax.device_get(loss_p)), float(jax.device_get(loss_1)), rtol=5e-4
    )
    chex = pytest.importorskip("chex")
    chex.assert_trees_all_close(
        jax.device_get(p_state.params),
        jax.device_get(s_state.params),
        rtol=2e-3,
        atol=1e-5,
    )


def _boost_moe(state, trainer, scale=15.0):
    """Scale the expert blocks well above their 0.02-std init: at init
    the MoE->encoder cotangent is O(std^2) and hides inside assertion
    tolerances, so an ep gradient bug on that path would go undetected
    (this is how the missing region_start on x originally slipped by)."""
    import jax

    from deepdfa_tpu.train.state import TrainState

    params = jax.device_get(state.params)
    params["moe"] = jax.tree.map(
        lambda v: v * scale if v.ndim == 3 else v, params["moe"]
    )
    params = jax.device_put(params, trainer.param_shardings)
    return TrainState(
        params=params, opt_state=trainer.tx.init(params), step=state.step
    )


def test_moe_ep_grads_match_single():
    """ep-sharding alone must reproduce single-device training EXACTLY
    (boosted experts; dp=1 so the per-local-batch capacity and aux terms
    see the identical token set): expert slices local-true, router psum
    over ep, aux through its rank-0 region_end, and the x region_start
    psum-ing the main path's per-rank-partial encoder cotangent."""
    import dataclasses as dc

    import jax

    token_ids, labels, by_id, mcfg, cfg, n = _setup()
    mcfg = dc.replace(mcfg, moe_experts=4, moe_top_k=2)

    mesh_p = make_mesh(MeshConfig(dp=1, ep=2), devices=jax.devices()[:2])
    mesh_1 = make_mesh(MeshConfig(dp=1), devices=jax.devices()[:1])
    p_trainer = CombinedTrainer(cfg, mcfg, mesh=mesh_p)
    s_trainer = CombinedTrainer(cfg, mcfg, mesh=mesh_1)

    batch = collate_shards(
        token_ids, labels, list(range(n)), by_id,
        num_shards=1, rows_per_shard=n,
        node_budget=1024, edge_budget=4096,
    )
    p_state = _boost_moe(p_trainer.init_state(seed=0), p_trainer)
    s_state = _boost_moe(s_trainer.init_state(seed=0), s_trainer)
    key = jax.random.key(123)
    for _ in range(2):
        p_state, loss_p = p_trainer.train_step(p_state, batch, key)
        s_state, loss_1 = s_trainer.train_step(s_state, batch, key)

    np.testing.assert_allclose(
        float(jax.device_get(loss_p)), float(jax.device_get(loss_1)),
        rtol=5e-4,
    )
    chex = pytest.importorskip("chex")
    chex.assert_trees_all_close(
        jax.device_get(p_state.params),
        jax.device_get(s_state.params),
        rtol=5e-3, atol=1e-4,
    )


@pytest.mark.parametrize("mesh_cfg", [
    dict(dp=4, ep=2),
    dict(dp=2, tp=2, ep=2),
])
def test_moe_combined_matches_single(mesh_cfg):
    """MoE composed with dp/tp stays close to single-device training.

    Close, not exact: the Switch capacity and the load-balancing aux are
    defined per LOCAL batch (standard Switch semantics), so resharding
    rows over dp changes which tokens overflow capacity and how the aux
    means group — a real semantic layout dependence, not a grad bug.
    At init scale those effects sit well inside the tolerances; the
    exactness of the ep grad machinery itself is pinned by
    test_moe_ep_grads_match_single above."""
    import dataclasses as dc

    import jax

    token_ids, labels, by_id, mcfg, cfg, n = _setup()
    mcfg = dc.replace(mcfg, moe_experts=4, moe_top_k=2)

    n_dev = math.prod(mesh_cfg.values())
    mesh_p = make_mesh(MeshConfig(**mesh_cfg), devices=jax.devices()[:n_dev])
    mesh_1 = make_mesh(MeshConfig(dp=1), devices=jax.devices()[:1])
    p_trainer = CombinedTrainer(cfg, mcfg, mesh=mesh_p)
    s_trainer = CombinedTrainer(cfg, mcfg, mesh=mesh_1)

    dp = mesh_cfg["dp"]
    batch_p = collate_shards(
        token_ids, labels, list(range(n)), by_id,
        num_shards=dp, rows_per_shard=n // dp,
        node_budget=1024, edge_budget=4096,
    )
    batch_1 = collate_shards(
        token_ids, labels, list(range(n)), by_id,
        num_shards=1, rows_per_shard=n,
        node_budget=1024, edge_budget=4096,
    )

    p_state = p_trainer.init_state(seed=0)
    s_state = s_trainer.init_state(seed=0)
    key = jax.random.key(123)
    for _ in range(2):
        p_state, loss_p = p_trainer.train_step(p_state, batch_p, key)
        s_state, loss_1 = s_trainer.train_step(s_state, batch_1, key)

    np.testing.assert_allclose(
        float(jax.device_get(loss_p)), float(jax.device_get(loss_1)),
        rtol=5e-4,
    )
    chex = pytest.importorskip("chex")
    # atol covers psum reduction-order float noise (observed ~3e-5 on
    # near-zero embedding grads) plus the per-local-batch capacity/aux
    # layout dependence at init scale
    chex.assert_trees_all_close(
        jax.device_get(p_state.params),
        jax.device_get(s_state.params),
        rtol=5e-3, atol=1e-4,
    )


def test_ep_mesh_without_moe_rejected():
    token_ids, labels, by_id, mcfg, cfg, n = _setup()
    mesh = make_mesh(MeshConfig(dp=4, ep=2))
    with pytest.raises(ValueError, match="MoE"):
        CombinedTrainer(cfg, mcfg, mesh=mesh)
