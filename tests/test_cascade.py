"""Two-stage cascaded inference (serve/cascade.py, eval/calibrate.py,
docs/cascade.md).

In-process invariants (the CLI/e2e surface rides `serve --smoke`,
`fleet --smoke`, and scripts/bench_cascade.py):

- temperature scaling recovers a known miscalibration and the fitted
  band hits its target escalation fraction;
- the cascade service routes by the calibrated band: out-of-band
  requests answer with the stage-1 score, in-band requests carry the
  stage-2 score, both stages stay at zero steady-state lowerings;
- the combined family serves through the SAME ScoringService surface
  (model_cfg.json manifest round trip);
- the admission layer sheds stage-2 escalations BEFORE stage-1 traffic
  under overload (the docs/cascade.md shed order);
- `fleet.models` entries parse the [family:] prefix.
"""

import json

import numpy as np
import pytest

from deepdfa_tpu.core import Config, config as config_mod
from deepdfa_tpu.eval import calibrate as cal


# ---------------------------------------------------------------------------
# calibration utility


def test_temperature_recovers_miscalibration(rng):
    """Probs sharpened by a known factor T*: the fitted temperature
    approximately undoes it (NLL optimum near T*)."""
    z = rng.normal(0.0, 1.5, size=4000)
    y = (rng.random(4000) < 1 / (1 + np.exp(-z))).astype(int)
    t_star = 2.5
    over_sharp = 1 / (1 + np.exp(-z * t_star))
    t = cal.fit_temperature(over_sharp, y)
    assert 1.8 < t < 3.4, t
    # scaling back by the fitted T improves NLL vs the raw probs
    assert cal.nll(over_sharp, y, t) < cal.nll(over_sharp, y, 1.0)


def test_fit_temperature_needs_both_classes():
    with pytest.raises(ValueError):
        cal.fit_temperature([0.2, 0.8], [1, 1])


def test_band_hits_target_escalation(rng):
    probs = rng.random(500)
    labels = (rng.random(500) < probs).astype(int)
    band = cal.fit_band(probs, labels, temperature=1.0,
                        target_escalation=0.3)
    frac = np.mean([cal.in_band(p, band) for p in probs])
    assert abs(frac - 0.3) < 0.05
    # empty band escalates nothing
    assert cal.fit_band(probs, target_escalation=0.0) == (0.5, 0.5)
    assert not cal.in_band(0.5, (0.5, 0.5))


def test_auc_rank_with_ties():
    assert cal.auc([0.1, 0.4, 0.35, 0.8], [0, 0, 1, 1]) == 0.75
    assert cal.auc([0.5, 0.5], [0, 1]) == 0.5  # tie averaged
    assert cal.auc([0.1, 0.2], [0, 0]) is None  # one class


# ---------------------------------------------------------------------------
# slo stages


def test_slo_engine_cascade_stages():
    from deepdfa_tpu.obs.slo import CASCADE_STAGES, STAGES, SloEngine

    t = [0.0]
    eng = SloEngine(windows=(60,), clock=lambda: t[0],
                    stages=STAGES + CASCADE_STAGES)
    eng.observe_request(
        200, 0.010, frontend_s=0.001,
        extra={"cascade_stage1": 0.004, "cascade_stage2": 0.005},
    )
    snap = eng.snapshot()
    lat = snap["60s"]["latency_ms"]
    assert lat["cascade_stage1"]["p50"] == 4.0
    assert lat["cascade_stage2"]["p50"] == 5.0
    # an undeclared extra stage is ignored, never a KeyError
    eng.observe_request(200, 0.010, extra={"bogus_stage": 1.0})
    assert "bogus_stage" not in eng.snapshot()["60s"]["latency_ms"]


# ---------------------------------------------------------------------------
# the cascade service, end to end in-process


@pytest.fixture(scope="module")
def cascade_run(tmp_path_factory):
    """A tiny GGNN run dir + stage-2 combined artifacts, once per
    module (real checkpoints, no training loop)."""
    import jax

    from deepdfa_tpu.core import paths
    from deepdfa_tpu.data import build_dataset, generate, to_examples
    from deepdfa_tpu.graphs.batch import pack
    from deepdfa_tpu.models import DeepDFA
    from deepdfa_tpu.serve import cascade as cascade_mod
    from deepdfa_tpu.train.checkpoint import CheckpointManager
    import os

    tmp = tmp_path_factory.mktemp("cascade-run")
    old = os.environ.get("DEEPDFA_TPU_STORAGE")
    os.environ["DEEPDFA_TPU_STORAGE"] = str(tmp)
    try:
        synth = generate(16, seed=3)
        examples = to_examples(synth)
        _, vocabs = build_dataset(
            examples, train_ids=range(16), limit_all=50, limit_subkeys=50
        )
        cfg = config_mod.apply_overrides(Config(), [
            'run_name="casc-e2e"', 'data.dataset="casc-e2e"',
            'data.feat={"limit_all": 50, "limit_subkeys": 50}',
            "model.hidden_dim=8", "model.n_steps=2",
            "serve.max_batch_graphs=2",
            "serve.node_budget=2048", "serve.edge_budget=8192",
            "data.token_budget=128",
        ])
        (paths.processed_dir("casc-e2e")
         / f"vocab{cfg.data.feat.name}.json").write_text(
            json.dumps({k: v.to_json() for k, v in vocabs.items()})
        )
        model = DeepDFA.from_config(
            cfg.model, input_dim=cfg.data.feat.input_dim
        )
        params = model.init(
            jax.random.key(0), pack([], 1, 2048, 8192)
        )
        run_dir = paths.runs_dir("casc-e2e")
        config_mod.to_json(cfg, run_dir / "config.json")
        CheckpointManager(
            run_dir / "checkpoints", monitor="val_loss"
        ).save(
            "epoch-0001", jax.device_get(params), {"val_loss": 1.0},
            step=1,
        )
        tok, mcfg = cascade_mod.build_stage2_smoke(
            run_dir, cfg, family="combined"
        )
        yield cfg, run_dir, examples, tok, mcfg
    finally:
        if old is None:
            os.environ.pop("DEEPDFA_TPU_STORAGE", None)
        else:
            os.environ["DEEPDFA_TPU_STORAGE"] = old


def test_model_setup_manifest_roundtrip(cascade_run):
    from deepdfa_tpu.serve import cascade as cascade_mod

    cfg, run_dir, _, tok, mcfg = cascade_run
    tok2, mcfg2, max_length = cascade_mod.load_model_setup(
        run_dir, "combined"
    )
    assert mcfg2 == mcfg  # dataclass equality: full config round trip
    assert tok2.vocab_size == tok.vocab_size
    assert tok2.pad_id == tok.pad_id
    assert max_length == 32
    with pytest.raises(ValueError):
        cascade_mod.load_model_setup(run_dir, "t5")  # wrong family


def test_combined_family_scoring_service(cascade_run):
    """The combined family serves through the SAME ScoringService
    surface (frontend tokenization + CombinedExecutor), registry
    rebuilt from the manifest alone."""
    from deepdfa_tpu.serve.registry import ModelRegistry
    from deepdfa_tpu.serve.server import ScoringService, score_texts

    cfg, run_dir, examples, _, _ = cascade_run
    registry = ModelRegistry(
        run_dir, family="combined", checkpoint="best", cfg=cfg
    )
    service = ScoringService(registry, cfg)
    try:
        rows = score_texts(
            service, [(f"fn{e.id}", e.code) for e in examples[:4]]
        )
        assert all(r.get("ok") for r in rows)
        assert all(0.0 <= r["prob"] <= 1.0 for r in rows)
        assert service.steady_state_recompiles() == 0
    finally:
        service.close()


def test_cascade_routes_by_band(cascade_run):
    """Band (0,1) escalates everything; band (x,x) escalates nothing —
    and the stage verdicts + counters + SLO stages agree, at zero
    steady-state lowerings across both ladders."""
    from deepdfa_tpu.serve.registry import ModelRegistry
    from deepdfa_tpu.serve.server import ScoringService, score_texts

    cfg, run_dir, examples, _, _ = cascade_run
    texts = [(f"fn{e.id}", e.code) for e in examples[:4]]

    def run_with_band(band):
        ccfg = config_mod.apply_overrides(cfg, [
            "serve.cascade=true",
            "serve.cascade_band=" + json.dumps(band),
        ])
        registry = ModelRegistry(
            run_dir, family="deepdfa",
            checkpoint=cfg.serve.checkpoint, cfg=ccfg,
        )
        service = ScoringService(registry, ccfg)
        try:
            c0 = service.cascade.counters()
            rows = score_texts(service, texts)
            c1 = service.cascade.counters()
            recompiles = service.steady_state_recompiles()
            slo = service.slo.snapshot()
        finally:
            service.close()
        return rows, {
            k: c1[k] - c0[k]
            for k in ("requests", "escalations", "sheds")
        }, recompiles, slo

    rows, counters, recompiles, slo = run_with_band([0.0, 1.0])
    assert all(r["stage"] == 2 for r in rows)
    assert all("stage1_prob" in r for r in rows)
    # escalated scores come from stage 2: they differ from the screen's
    assert all(r["prob"] != r["stage1_prob"] for r in rows)
    assert counters == {"requests": 4, "escalations": 4, "sheds": 0}
    assert recompiles == 0
    lat = slo["60s"]["latency_ms"]
    assert "cascade_stage1" in lat and "cascade_stage2" in lat

    rows, counters, recompiles, _ = run_with_band([0.5, 0.5])
    assert all(r["stage"] == 1 for r in rows)
    assert all(r["prob"] == r["stage1_prob"] for r in rows)
    assert counters == {"requests": 4, "escalations": 0, "sheds": 0}
    assert recompiles == 0


def test_cascade_log_validates(cascade_run, tmp_path):
    """A cascade-mode serve_log validates; a log missing the cascade
    section is rejected with a named problem."""
    from deepdfa_tpu.serve import cascade as cascade_mod
    from deepdfa_tpu.serve.registry import ModelRegistry
    from deepdfa_tpu.serve.server import (
        ScoringService,
        score_texts,
        write_serve_log,
    )

    cfg, run_dir, examples, _, _ = cascade_run
    log_path = run_dir / "serve_log.jsonl"
    if log_path.exists():
        log_path.unlink()
    ccfg = config_mod.apply_overrides(cfg, [
        "serve.cascade=true", "serve.request_log=true",
        "serve.cascade_band=[0.0, 1.0]",
    ])
    registry = ModelRegistry(
        run_dir, family="deepdfa", checkpoint=cfg.serve.checkpoint,
        cfg=ccfg,
    )
    service = ScoringService(registry, ccfg)
    try:
        score_texts(
            service, [(f"fn{e.id}", e.code) for e in examples[:4]]
        )
        rec = service.serve_record()
        write_serve_log(run_dir, [rec])
    finally:
        service.close()
    res = cascade_mod.validate_cascade_log(log_path)
    assert res["ok"], res["problems"]
    assert res["escalated"] == 4

    # a plain (non-cascade) log is rejected with a named problem
    plain = tmp_path / "plain_log.jsonl"
    plain.write_text(json.dumps({"serve": {"requests": 1.0}}) + "\n")
    res = cascade_mod.validate_cascade_log(plain)
    assert not res["ok"]
    assert any("cascade section" in p for p in res["problems"])


# ---------------------------------------------------------------------------
# fleet integration: spec parsing + cascade-aware shedding


def test_parse_model_spec_family():
    from deepdfa_tpu.fleet.replica import parse_model_spec

    assert parse_model_spec("m=/runs/x") == (
        "m", "deepdfa", "/runs/x", "best"
    )
    assert parse_model_spec("m=/runs/x:last") == (
        "m", "deepdfa", "/runs/x", "last"
    )
    assert parse_model_spec("s2=combined:/runs/x:best@int8") == (
        "s2", "combined", "/runs/x", "best@int8"
    )
    assert parse_model_spec("s2=t5:/runs/x") == (
        "s2", "t5", "/runs/x", "best"
    )
    with pytest.raises(ValueError):
        parse_model_spec("bad-spec")
    with pytest.raises(ValueError):
        parse_model_spec("m=combined:")


def test_admission_sheds_stage2_before_stage1():
    """The docs/cascade.md shed order: between the cascade threshold and
    the overload threshold, stage-2 escalations shed 503
    `cascade_overload` while plain stage-1 traffic still admits."""
    from deepdfa_tpu.fleet.admission import AdmissionController

    t = [0.0]
    ctl = AdmissionController(
        replica_capacity=10, shed_fraction=1.0,
        cascade_shed_fraction=0.5, default_rate=1e9,
        default_burst=1e9, clock=lambda: t[0],
    )
    # below both thresholds: everyone admits
    d1 = ctl.decide("t", outstanding=2, healthy=1, cascade_stage=2)
    assert d1.admit
    # past 50% of capacity: stage-2 sheds, stage-1 still admits
    d2 = ctl.decide("t", outstanding=6, healthy=1, cascade_stage=2)
    assert not d2.admit and d2.reason == "cascade_overload"
    assert d2.status == 503
    d3 = ctl.decide("t", outstanding=6, healthy=1)
    assert d3.admit
    # past full capacity: batch-priority stage-1 sheds too
    d4 = ctl.decide("t", outstanding=10, healthy=1)
    assert not d4.admit and d4.reason == "overload"
    # an INTERACTIVE-class tenant survives overload — but its stage-2
    # escalations still shed first (the cascade threshold is not a
    # priority carve-out: every escalation already holds a stage-1
    # answer to degrade to)
    ctl2 = AdmissionController(
        replica_capacity=10, shed_fraction=1.0,
        cascade_shed_fraction=0.5, default_rate=1e9,
        default_burst=1e9, default_priority=0, clock=lambda: t[0],
    )
    d5 = ctl2.decide("t", outstanding=10, healthy=1)
    assert d5.admit
    d6 = ctl2.decide("t", outstanding=10, healthy=1, cascade_stage=2)
    assert not d6.admit and d6.reason == "cascade_overload"


def test_cascade_degrades_on_stage2_failure(cascade_run, monkeypatch):
    """A stage-2 failure (timeout/queue-full/executor error) DEGRADES
    to the stage-1 score on the online path — never a failed request —
    counted as a failure, not an escalation."""
    from deepdfa_tpu.serve.registry import ModelRegistry
    from deepdfa_tpu.serve.server import ScoringService

    cfg, run_dir, examples, _, _ = cascade_run
    ccfg = config_mod.apply_overrides(cfg, [
        "serve.cascade=true", "serve.cascade_band=[0.0, 1.0]",
    ])
    registry = ModelRegistry(
        run_dir, family="deepdfa", checkpoint=cfg.serve.checkpoint,
        cfg=ccfg,
    )
    service = ScoringService(registry, ccfg)
    try:
        def boom(code, request_id=None):
            raise TimeoutError("stage-2 wedged")

        monkeypatch.setattr(service.cascade, "escalate", boom)
        c0 = service.cascade.counters()
        prob, info, extra = service.cascade.decide(
            examples[0].code, 0.42
        )
        c1 = service.cascade.counters()
        assert prob == 0.42  # the screen's answer survives
        assert info["stage"] == 1 and info["cascade_failed"] == 1
        assert "cascade_stage2" not in extra
        assert c1["failures"] - c0["failures"] == 1
        assert c1["escalations"] == c0["escalations"]
    finally:
        service.close()


def test_cascade_service_shed_on_stage2_backlog(cascade_run):
    """The service-level degradation: a saturated stage-2 queue makes
    new escalations answer with their stage-1 score (cascade_shed),
    never queue more device time."""
    from deepdfa_tpu.serve.registry import ModelRegistry
    from deepdfa_tpu.serve.server import ScoringService

    cfg, run_dir, examples, _, _ = cascade_run
    ccfg = config_mod.apply_overrides(cfg, [
        "serve.cascade=true",
        "serve.cascade_band=[0.0, 1.0]",
        "serve.cascade_shed_depth_fraction=0.0",  # always overloaded
    ])
    registry = ModelRegistry(
        run_dir, family="deepdfa", checkpoint=cfg.serve.checkpoint,
        cfg=ccfg,
    )
    service = ScoringService(registry, ccfg)
    try:
        assert service.cascade.overloaded()
        prob, info, extra = service.cascade.decide(
            examples[0].code, 0.5
        )
        assert prob == 0.5  # the stage-1 answer
        assert info["stage"] == 1 and info["cascade_shed"] == 1
        assert "cascade_stage2" not in extra
    finally:
        service.close()
