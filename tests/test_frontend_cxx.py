"""C++ tolerance: the Big-Vul corpus is ~half C++ (Chromium etc.)."""

import pytest

from deepdfa_tpu.data import extract_graph
from deepdfa_tpu.frontend import ReachingDefinitions, decl_features, is_decl, parse_function

CASES = {
    "qualified_method": "int Foo::bar(const std::string& name, int x) {\n  int n = name.size();\n  return n + x;\n}",
    "reference_params": "void f(std::vector<int>& v, int& out) {\n  out = v.size();\n}",
    "new_delete": "int g(int n) {\n  char* p = new char[n];\n  p[0] = 1;\n  delete[] p;\n  return 0;\n}",
    "template_fn": "template <typename T>\nT max3(T a, T b) {\n  T m = a > b ? a : b;\n  return m;\n}",
    "namespaced_types": "static base::Value* j(const base::DictionaryValue* dict) {\n  base::Value* out = NULL;\n  dict->Get(\"key\", &out);\n  return out;\n}",
    "cxx_casts": "int k(void* p) {\n  int v = static_cast<int>(reinterpret_cast<long>(p));\n  return v;\n}",
    "qualified_call": "int m() {\n  int v = std::max(1, 2);\n  return v;\n}",
    "try_catch": "int h() {\n  try {\n    int x = risky();\n    return x;\n  } catch (const std::exception& e) {\n    return -1;\n  }\n}",
}


@pytest.mark.parametrize("name", CASES)
def test_cxx_extracts_with_defs(name):
    code = CASES[name]
    eg = extract_graph(code, 0)
    assert eg is not None, name
    assert eg.num_nodes > 3
    assert eg.def_fields, name  # at least one definition node with features
    # reaching defs terminates on the full CPG
    rd = ReachingDefinitions(parse_function(code))
    rd.solve()


def test_cxx_feature_semantics():
    cpg = parse_function(CASES["new_delete"])
    decls = {
        cpg.nodes[n.id].code: dict(decl_features(cpg, n.id))
        for n in cpg.nodes
        if is_decl(cpg, n.id)
    }
    assert decls["p = new char[n]"]["operator"] == "new"
    assert decls["p = new char[n]"]["datatype"] == "char*"

    cpg2 = parse_function(CASES["namespaced_types"])
    decls2 = {
        cpg2.nodes[n.id].code: dict(decl_features(cpg2, n.id))
        for n in cpg2.nodes
        if is_decl(cpg2, n.id)
    }
    assert decls2["out = NULL"]["datatype"] == "base::Value*"

    cpg3 = parse_function(CASES["qualified_call"])
    decls3 = {
        cpg3.nodes[n.id].code: dict(decl_features(cpg3, n.id))
        for n in cpg3.nodes
        if is_decl(cpg3, n.id)
    }
    assert decls3["v = std::max(1, 2)"]["api"] == "std::max"

    cpg4 = parse_function(CASES["cxx_casts"])
    decls4 = {
        cpg4.nodes[n.id].code: dict(decl_features(cpg4, n.id))
        for n in cpg4.nodes
        if is_decl(cpg4, n.id)
    }
    assert decls4["v = static_cast<int>(reinterpret_cast<long>(p))"]["operator"] == "cast"


def test_method_name_qualified():
    assert parse_function(CASES["qualified_method"]).method_name == "Foo::bar"


def test_ctor_member_initializer_list_body_parses():
    """`: x_(1), y_{v}` between ) and the body: the brace-init group must
    not be mistaken for the function body (code-review r4 — previously
    the body statements vanished from the CFG)."""
    from deepdfa_tpu.frontend.parser import parse_function

    cpg = parse_function(
        "Foo::Foo(int v) : x_(1), y_{v}, base::type{v, 2} {\n"
        "  total = v;\n"
        "  helper(total);\n"
        "}\n"
    )
    codes = [n.code or "" for n in cpg.nodes]
    assert any("total = v" in c for c in codes), codes
    assert any("helper" in c for c in codes), codes
    stmt_lines = {
        n.line for n in (cpg.node(i) for i in cpg.cfg_nodes())
        if n.label not in ("METHOD", "METHOD_RETURN")
    }
    assert {2, 3} <= stmt_lines, stmt_lines


def test_ctor_templated_base_brace_init():
    """`: Base<int>{v}` — template args inside the initializer list must
    not break the body detection (code-review r4)."""
    from deepdfa_tpu.frontend.parser import parse_function

    cpg = parse_function(
        "Foo::Foo(int v) : base_type<int>{v}, Base<T>::Nested(v), "
        "m_(init<a, b>(v)) {\n"
        "  total = v;\n"
        "  helper(total);\n"
        "}\n"
    )
    codes = [n.code or "" for n in cpg.nodes]
    assert any("total = v" in c for c in codes), codes
    assert any("helper" in c for c in codes), codes


def test_operator_overload_after_attribute_macro():
    """`MYMACRO Vec operator*(...)`: the soup recovery must leave the
    overload's op token to the operator handler (code-review r4)."""
    from deepdfa_tpu.frontend.parser import parse_function

    cpg = parse_function(
        "MYMACRO Vec operator*(Vec a, Vec b) { return a; }"
    )
    m = cpg.node(cpg.method_id)
    assert m.name == "operator*", m.name
    assert "*" not in (m.type_full_name or ""), m.type_full_name
