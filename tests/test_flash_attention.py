"""Flash-attention Pallas kernel: parity with the XLA attention path.

Everything here runs on CPU via the Pallas interpreter:
- no-dropout fwd + custom-vjp grads vs `full_attention` (the XLA oracle),
  multi-block, ragged kv masks, f32 and bf16;
- exact dropout math via the `debug_bits` hook: the kernels read the
  injected bits instead of the TPU PRNG, so a pure-jnp oracle given the
  same keep-mask pins fwd AND all three grads;
- the encode() integration path (DEEPDFA_TPU_FLASH_INTERPRET) under
  scan/jit/grad.

What cannot run on CPU — the real `pltpu.prng_random_bits` stream (the
interpreter returns zeros, which by the kernel's `keep = bits <
threshold` convention means keep-all) — is exercised on the real chip by
scripts/bench_combined.py's self-check (keep fraction, determinism)
before the flash variant is benched; see docs/bench_history.json.
"""

from __future__ import annotations

import dataclasses
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepdfa_tpu.nn.flash_attention import flash_attention
from deepdfa_tpu.parallel.ring_attention import full_attention


def _qkv(rng, B, H, T, D, dtype):
    mk = lambda: jnp.asarray(rng.standard_normal((B, H, T, D)), dtype)
    return mk(), mk(), mk()


def _ragged_mask(T, lens):
    return jnp.asarray(np.arange(T)[None, :] < np.asarray(lens)[:, None])


@pytest.mark.parametrize("dtype,tol", [("float32", 2e-6), ("bfloat16", 2e-2)])
def test_fwd_matches_full_attention(rng, dtype, tol):
    B, H, T, D = 2, 3, 256, 64
    q, k, v = _qkv(rng, B, H, T, D, jnp.dtype(dtype))
    mask = _ragged_mask(T, [200, 77])
    ref = full_attention(q, k, v, mask)
    out = flash_attention(q, k, v, mask, block_q=128, block_k=128,
                          interpret=True)
    assert out.dtype == jnp.dtype(dtype)
    # compare on valid q rows (padded rows are garbage on both paths and
    # masked out downstream)
    valid = mask[:, None, :, None]
    err = jnp.abs(jnp.where(valid, out.astype(jnp.float32) - ref.astype(jnp.float32), 0.0))
    assert float(err.max()) < tol


def test_grads_match_full_attention(rng):
    B, H, T, D = 2, 2, 256, 32
    q, k, v = _qkv(rng, B, H, T, D, jnp.float32)
    mask = _ragged_mask(T, [256, 130])
    w = jnp.asarray(rng.standard_normal((B, H, T, D)), jnp.float32)

    def loss(fn):
        return lambda q, k, v: jnp.sum(
            jnp.where(mask[:, None, :, None], fn(q, k, v), 0.0) * w)

    g_ref = jax.grad(loss(lambda q, k, v: full_attention(q, k, v, mask)),
                     (0, 1, 2))(q, k, v)
    g_fl = jax.grad(loss(lambda q, k, v: flash_attention(
        q, k, v, mask, block_q=128, block_k=128, interpret=True)),
        (0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_fl):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-6, rtol=1e-4)


def test_dropout_exact_math_via_debug_bits(rng):
    """Injected bits -> the jnp oracle with the same keep-mask must agree
    with the kernel exactly (fwd and all three custom-vjp grads)."""
    B, H, T, D = 2, 2, 256, 32
    RATE = 0.1
    q, k, v = _qkv(rng, B, H, T, D, jnp.float32)
    mask = _ragged_mask(T, [230, 120])
    bits = jnp.asarray(rng.integers(0, 2**32, (B, H, T, T), dtype=np.uint32))
    keep_thresh = np.uint32(min(int(round((1 - RATE) * 2**32)), 2**32 - 1))
    keep = jnp.asarray(np.asarray(bits) < keep_thresh)
    assert 0.85 < float(keep.mean()) < 0.95  # bits really are uniform

    def oracle(q, k, v):
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(D)
        s = jnp.where(mask[:, None, None, :], s, -1e30)
        m = jnp.max(s, -1, keepdims=True)
        p = jnp.where(mask[:, None, None, :], jnp.exp(s - m), 0.0)
        denom = jnp.maximum(p.sum(-1, keepdims=True),
                            np.finfo(np.float32).tiny)
        # dropout(softmax): numerator dropped+rescaled, denom undropped
        pd = jnp.where(keep, p / (1 - RATE), 0.0)
        return jnp.einsum("bhqk,bhkd->bhqd", pd, v) / denom

    def fl(q, k, v):
        return flash_attention(q, k, v, mask, dropout_rate=RATE,
                               debug_bits=bits, block_q=128, block_k=128,
                               interpret=True)

    np.testing.assert_allclose(np.asarray(fl(q, k, v)),
                               np.asarray(oracle(q, k, v)), atol=2e-6)

    w = jnp.asarray(rng.standard_normal((B, H, T, D)), jnp.float32)

    def loss(fn):
        return lambda q, k, v: jnp.sum(
            jnp.where(mask[:, None, :, None], fn(q, k, v), 0.0) * w)

    g_ref = jax.grad(loss(oracle), (0, 1, 2))(q, k, v)
    g_fl = jax.grad(loss(fl), (0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_fl):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-6, rtol=1e-4)


def test_bias_fwd_and_grads_match_t5_oracle(rng):
    """T5-style call: no 1/sqrt(d) scaling, additive [H,T,T] relative
    bias. dbias comes from the batch-accumulating backward kernel."""
    B, H, T, D = 2, 3, 256, 32
    q, k, v = _qkv(rng, B, H, T, D, jnp.float32)
    bias = jnp.asarray(rng.standard_normal((H, T, T)) * 0.5, jnp.float32)
    mask = _ragged_mask(T, [256, 130])
    m4 = mask[:, None, :, None]

    def oracle(q, k, v, bias):
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k) + bias[None]
        s = jnp.where(mask[:, None, None, :], s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        p = jnp.where(mask[:, None, None, :], p, 0.0)
        return jnp.einsum("bhqk,bhkd->bhqd", p, v)

    def fl(q, k, v, bias):
        return flash_attention(q, k, v, mask, scale=1.0, bias=bias,
                               block_q=128, block_k=128, interpret=True)

    o_r, o_f = oracle(q, k, v, bias), fl(q, k, v, bias)
    assert float(jnp.abs(jnp.where(m4, o_r - o_f, 0.0)).max()) < 1e-5

    w = jnp.asarray(rng.standard_normal(o_r.shape), jnp.float32)

    def loss(fn):
        return lambda *a: jnp.sum(jnp.where(m4, fn(*a), 0.0) * w)

    g_r = jax.grad(loss(oracle), (0, 1, 2, 3))(q, k, v, bias)
    g_f = jax.grad(loss(fl), (0, 1, 2, 3))(q, k, v, bias)
    for a, b in zip(g_r, g_f):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-5, rtol=1e-4)


def test_bias_composes_with_dropout_debug_bits(rng):
    """bias + dropout together (no current caller uses both — roberta
    has no bias, t5 no probs-dropout — but the kernel allows it and the
    math must stay pinned)."""
    B, H, T, D = 1, 2, 128, 16
    RATE = 0.2
    q, k, v = _qkv(rng, B, H, T, D, jnp.float32)
    bias = jnp.asarray(rng.standard_normal((H, T, T)) * 0.3, jnp.float32)
    mask = _ragged_mask(T, [100])
    bits = jnp.asarray(rng.integers(0, 2**32, (B, H, T, T), dtype=np.uint32))
    keep = jnp.asarray(
        np.asarray(bits) < np.uint32(int(round((1 - RATE) * 2**32))))

    def oracle(q, k, v, bias):
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(D) + bias[None]
        s = jnp.where(mask[:, None, None, :], s, -1e30)
        m = jnp.max(s, -1, keepdims=True)
        p = jnp.where(mask[:, None, None, :], jnp.exp(s - m), 0.0)
        denom = jnp.maximum(p.sum(-1, keepdims=True),
                            np.finfo(np.float32).tiny)
        pd = jnp.where(keep, p / (1 - RATE), 0.0)
        return jnp.einsum("bhqk,bhkd->bhqd", pd, v) / denom

    def fl(q, k, v, bias):
        return flash_attention(q, k, v, mask, dropout_rate=RATE, bias=bias,
                               debug_bits=bits, block_q=128, block_k=128,
                               interpret=True)

    m4 = mask[:, None, :, None]
    w = jnp.asarray(rng.standard_normal((B, H, T, D)), jnp.float32)

    def loss(fn):
        return lambda *a: jnp.sum(jnp.where(m4, fn(*a), 0.0) * w)

    np.testing.assert_allclose(np.asarray(fl(q, k, v, bias)),
                               np.asarray(oracle(q, k, v, bias)), atol=5e-6)
    g_r = jax.grad(loss(oracle), (0, 1, 2, 3))(q, k, v, bias)
    g_f = jax.grad(loss(fl), (0, 1, 2, 3))(q, k, v, bias)
    for a, b in zip(g_r, g_f):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-5, rtol=1e-4)


@pytest.mark.slow  # interpreter e2e (slow lane; the fast lane covers
# the interpret fallback itself in test_interpret_tpu_mode_fallback)
def test_t5_encode_integration_interpret(rng, monkeypatch):
    """T5 encoder with attn_impl=flash: bias threads through the kernel;
    eval output matches the XLA lowering; grads (incl. rel_bias) flow."""
    monkeypatch.setenv("DEEPDFA_TPU_FLASH_INTERPRET", "1")
    from deepdfa_tpu.models import t5 as t5m

    cfg_f = dataclasses.replace(t5m.T5Config.tiny(), attn_impl="flash",
                                remat=False)
    cfg_x = dataclasses.replace(cfg_f, attn_impl="xla")
    params = t5m.init_params(cfg_f, jax.random.key(0))
    ids = jnp.asarray(rng.integers(3, 250, (2, 64)), jnp.int32)
    ids = ids.at[0, 40:].set(cfg_f.pad_token_id)

    h_f = t5m.encode(cfg_f, params, ids)
    h_x = t5m.encode(cfg_x, params, ids)
    np.testing.assert_allclose(np.asarray(h_f), np.asarray(h_x), atol=2e-5)

    def loss(p):
        return jnp.sum(t5m.encode(cfg_f, p, ids,
                                  dropout_key=jax.random.key(1)) ** 2)

    g = jax.jit(jax.grad(loss))(params)
    leaves = jax.tree.leaves(g)
    assert all(bool(jnp.isfinite(x).all()) for x in leaves)
    # the relative-bias table must receive gradient THROUGH the kernel
    rb = g["rel_bias"] if "rel_bias" in g else g["encoder"]["rel_bias"]
    assert float(jnp.abs(rb).max()) > 0.0


def test_causal_with_bias_matches_decoder_oracle(rng):
    """Decoder self-attention shape: causal mask + causal-bucketed
    relative bias, T5 scaling. All four grads incl. dbias."""
    B, H, T, D = 2, 2, 256, 32
    q, k, v = _qkv(rng, B, H, T, D, jnp.float32)
    bias = jnp.asarray(rng.standard_normal((H, T, T)) * 0.4, jnp.float32)
    mask = _ragged_mask(T, [256, 180])
    full_mask = jnp.tril(jnp.ones((T, T), bool))[None] & mask[:, None, :]
    m4 = mask[:, None, :, None]

    def oracle(q, k, v, bias):
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k) + bias[None]
        s = jnp.where(full_mask[:, None], s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.einsum("bhqk,bhkd->bhqd", p, v)

    def fl(q, k, v, bias):
        return flash_attention(q, k, v, mask, scale=1.0, bias=bias,
                               causal=True, block_q=128, block_k=128,
                               interpret=True)

    err = jnp.abs(jnp.where(m4, oracle(q, k, v, bias) - fl(q, k, v, bias),
                            0.0))
    assert float(err.max()) < 1e-5
    w = jnp.asarray(rng.standard_normal((B, H, T, D)), jnp.float32)

    def loss(fn):
        return lambda *a: jnp.sum(jnp.where(m4, fn(*a), 0.0) * w)

    g_r = jax.grad(loss(oracle), (0, 1, 2, 3))(q, k, v, bias)
    g_f = jax.grad(loss(fl), (0, 1, 2, 3))(q, k, v, bias)
    for a, b in zip(g_r, g_f):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-5, rtol=1e-4)


def test_rectangular_cross_attention(rng):
    """Cross-attention: Tq != Tk (decoder queries over encoder keys)."""
    B, H, Tq, Tk, D = 2, 2, 128, 256, 32
    q = jnp.asarray(rng.standard_normal((B, H, Tq, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, H, Tk, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, H, Tk, D)), jnp.float32)
    mask = _ragged_mask(Tk, [256, 140])

    def oracle(q, k, v):
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k)  # t5 cross: no scaling
        s = jnp.where(mask[:, None, None, :], s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.einsum("bhqk,bhkd->bhqd", p, v)

    def fl(q, k, v):
        return flash_attention(q, k, v, mask, scale=1.0,
                               block_q=128, block_k=128, interpret=True)

    np.testing.assert_allclose(np.asarray(fl(q, k, v)),
                               np.asarray(oracle(q, k, v)), atol=5e-6)
    g_r = jax.grad(lambda *a: jnp.sum(oracle(*a) ** 2), (0, 1, 2))(q, k, v)
    g_f = jax.grad(lambda *a: jnp.sum(fl(*a) ** 2), (0, 1, 2))(q, k, v)
    for a, b in zip(g_r, g_f):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-4, rtol=1e-4)
    with pytest.raises(ValueError, match="causal"):
        flash_attention(q, k, v, mask, causal=True, interpret=True)


@pytest.mark.slow  # interpreter e2e (see note on the t5 twin above)
def test_decode_train_integration_interpret(rng, monkeypatch):
    """decode_train with flash: causal+bias self-attn and rectangular
    cross-attn must reproduce the XLA lowering end to end."""
    monkeypatch.setenv("DEEPDFA_TPU_FLASH_INTERPRET", "1")
    from deepdfa_tpu.models import t5 as t5m
    from deepdfa_tpu.models import t5_gen as t5g

    ecfg = dataclasses.replace(t5m.T5Config.tiny(), attn_impl="flash",
                               remat=False)
    gcfg = t5g.GenConfig(encoder=ecfg)
    params = t5g.init_gen_params(gcfg, jax.random.key(0))
    src_ids = jnp.asarray(rng.integers(3, 250, (2, 64)), jnp.int32)
    tgt_ids = jnp.asarray(rng.integers(3, 250, (2, 32)), jnp.int32)
    enc_hidden = t5m.encode(ecfg, params["encoder"], src_ids)
    enc_mask = src_ids != ecfg.pad_token_id
    dec_in = t5g.shift_right(ecfg, tgt_ids)
    dec_mask = jnp.ones_like(dec_in, bool)

    logits_f = t5g.decode_train(gcfg, params, dec_in, dec_mask,
                                enc_hidden, enc_mask)
    ecfg_x = dataclasses.replace(ecfg, attn_impl="xla")
    gcfg_x = t5g.GenConfig(encoder=ecfg_x)
    logits_x = t5g.decode_train(gcfg_x, params, dec_in, dec_mask,
                                enc_hidden, enc_mask)
    np.testing.assert_allclose(np.asarray(logits_f), np.asarray(logits_x),
                               atol=2e-4)

    def loss(p):
        return jnp.sum(
            t5g.decode_train(gcfg, p, dec_in, dec_mask, enc_hidden,
                             enc_mask) ** 2)

    g = jax.jit(jax.grad(loss))(params)
    assert all(bool(jnp.isfinite(x).all()) for x in jax.tree.leaves(g))
    assert float(jnp.abs(g["decoder"]["rel_bias"]).max()) > 0.0


def test_decode_train_forced_flash_rejects_untileable_encoder(rng,
                                                              monkeypatch):
    """A FORCED flash lowering must fail loudly when the encoder length
    cannot tile (auto falls back quietly; forcing may not)."""
    monkeypatch.setenv("DEEPDFA_TPU_FLASH_INTERPRET", "1")
    from deepdfa_tpu.models import t5 as t5m
    from deepdfa_tpu.models import t5_gen as t5g

    ecfg = dataclasses.replace(t5m.T5Config.tiny(), attn_impl="flash",
                               remat=False)
    gcfg = t5g.GenConfig(encoder=ecfg)
    params = t5g.init_gen_params(gcfg, jax.random.key(0))
    S = 640  # > 512 and not a multiple of 512
    enc_hidden = jnp.zeros((1, S, ecfg.hidden_size), jnp.float32)
    enc_mask = jnp.ones((1, S), bool)
    dec_in = jnp.zeros((1, 32), jnp.int32)
    with pytest.raises(ValueError, match="encoder length"):
        t5g.decode_train(gcfg, params, dec_in, jnp.ones((1, 32), bool),
                         enc_hidden, enc_mask)


def test_long_sequence_multiblock(rng):
    """T=1024 (two 512-blocks per axis): the streaming-softmax tiling is
    what makes long single-chip sequences feasible at all — the XLA path
    materializes [B,H,T,T], which at 8k tokens is GBs per layer; the
    kernel's working set stays O(block_q x block_k) VMEM regardless of
    T. Parity vs the materializing oracle at a T the oracle can still
    afford."""
    B, H, T, D = 1, 1, 1024, 64
    q, k, v = _qkv(rng, B, H, T, D, jnp.float32)
    mask = _ragged_mask(T, [900])
    ref = full_attention(q, k, v, mask)
    out = flash_attention(q, k, v, mask, interpret=True)  # blocks 512x512
    valid = mask[:, None, :, None]
    err = jnp.abs(jnp.where(valid, out - ref, 0.0))
    assert float(err.max()) < 2e-6


def test_dropout_needs_seed(rng):
    q, k, v = _qkv(rng, 1, 1, 128, 16, jnp.float32)
    with pytest.raises(ValueError, match="seed"):
        flash_attention(q, k, v, jnp.ones((1, 128), bool),
                        dropout_rate=0.1, interpret=True)


def test_block_divisibility_enforced(rng):
    q, k, v = _qkv(rng, 1, 1, 192, 16, jnp.float32)
    with pytest.raises(ValueError, match="divide"):
        flash_attention(q, k, v, jnp.ones((1, 192), bool),
                        block_q=128, block_k=128, interpret=True)


def _tiny_cfgs():
    from deepdfa_tpu.models import transformer as tfm

    cfg = tfm.TransformerConfig.tiny(vocab_size=128,
                                     max_position_embeddings=96)
    return (dataclasses.replace(cfg, attn_impl="flash", remat=False),
            dataclasses.replace(cfg, attn_impl="xla", remat=False))


def test_interpret_tpu_mode_fallback(rng):
    """interpret="tpu" must work on every supported jax: with
    InterpretParams absent it falls back to the legacy interpreter, and
    the PRNG dropout path degrades to the documented keep-all — so
    flash-with-dropout == flash-without-dropout / keep_prob exactly."""
    q, k, v = _qkv(rng, 1, 2, 128, 16, jnp.float32)
    mask = _ragged_mask(128, [100])
    base = flash_attention(q, k, v, mask, interpret="tpu")
    drop = flash_attention(q, k, v, mask, dropout_rate=0.1,
                           seed=jnp.zeros((1,), jnp.int32),
                           interpret="tpu")
    valid = mask[:, None, :, None]
    err = jnp.abs(jnp.where(valid, drop - base / 0.9, 0.0))
    assert float(err.max()) < 1e-6


@pytest.mark.slow  # interpreter e2e (see the note on the t5 twin)
def test_encode_integration_interpret(rng, monkeypatch):
    """encode() with attn_impl=flash under scan + jit + grad on CPU.

    remat=False here: the Pallas TPU interpreter implements kernels via
    io_callback, whose effect cannot be partial-eval'ed under
    jax.checkpoint — a CPU-interpreter limitation only (the compiled TPU
    kernel has no callback effect; the flagship recipe keeps remat on).
    """
    monkeypatch.setenv("DEEPDFA_TPU_FLASH_INTERPRET", "1")
    from deepdfa_tpu.models import transformer as tfm

    cfg_f, cfg_x = _tiny_cfgs()
    params = tfm.init_params(cfg_f, jax.random.key(0))
    ids = jnp.asarray(rng.integers(2, 128, (2, 64)), jnp.int32)
    ids = ids.at[0, 40:].set(cfg_f.pad_token_id)

    # eval mode: flash == xla on every position of every valid row
    h_f = tfm.encode(cfg_f, params, ids)
    h_x = tfm.encode(cfg_x, params, ids)
    np.testing.assert_allclose(np.asarray(h_f), np.asarray(h_x), atol=1e-5)

    # train mode traces, runs, differentiates; deterministic per key
    def loss(p):
        return jnp.sum(tfm.encode(cfg_f, p, ids,
                                  dropout_key=jax.random.key(1)) ** 2)

    g = jax.jit(jax.grad(loss))(params)
    assert bool(jnp.isfinite(g["layers"]["wq"]).all())
    h1 = tfm.encode(cfg_f, params, ids, dropout_key=jax.random.key(1))
    h2 = tfm.encode(cfg_f, params, ids, dropout_key=jax.random.key(1))
    assert bool(jnp.all(h1 == h2))


@pytest.mark.slow  # 8-device interpreter mesh, the heaviest file member
def test_ulysses_flash_matches_xla_on_mesh(rng, devices, monkeypatch):
    """Ulysses sp with the flash local kernel == Ulysses with XLA local
    attention, on the 8-device CPU mesh (interpret mode inside
    shard_map). Covers both the plain (roberta) and biased (t5) forms."""
    from functools import partial

    from jax.sharding import Mesh, PartitionSpec as P

    from deepdfa_tpu.parallel.compat import shard_map
    from deepdfa_tpu.parallel.ulysses import ulysses_attention

    monkeypatch.setenv("DEEPDFA_TPU_FLASH_INTERPRET", "1")
    n_sp = 4
    mesh = Mesh(np.array(devices[:n_sp]).reshape(n_sp), ("sp",))
    B, H, T, D = 2, 4, 256, 16  # T = global sequence; T/n_sp per shard
    q, k, v = _qkv(rng, B, H, T, D, jnp.float32)
    mask = _ragged_mask(T, [230, 140])
    bias = jnp.asarray(rng.standard_normal((H, T, T)) * 0.3, jnp.float32)

    def run(impl, bias_slice):
        @partial(shard_map, mesh=mesh,
                 in_specs=(P(None, None, "sp", None),) * 3
                 + (P(None, "sp"),),
                 out_specs=P(None, None, "sp", None), check_vma=False)
        def f(ql, kl, vl, ml):
            b = None
            if bias_slice:
                # each device's head slice of the global bias (the t5
                # contract: ulysses shards heads after the all-to-all)
                idx = jax.lax.axis_index("sp")
                b = jax.lax.dynamic_slice_in_dim(
                    bias, idx * (H // n_sp), H // n_sp, axis=0)
            return ulysses_attention(
                ql, kl, vl, ml, axis_name="sp",
                scale=1.0 if bias_slice else None, bias=b,
                attn_impl=impl, flash_interpret=True)

        return np.asarray(f(q, k, v, mask))

    for biased in (False, True):
        out_x = run("xla", biased)
        out_f = run("flash", biased)
        np.testing.assert_allclose(out_f, out_x, atol=2e-5,
                                   err_msg=f"biased={biased}")

    # custom-VJP through shard_map + the two all-to-alls: dq cotangent
    # must survive the layout round-trip identically to XLA's
    def grad_run(impl):
        @partial(shard_map, mesh=mesh,
                 in_specs=(P(None, None, "sp", None),) * 3
                 + (P(None, "sp"),),
                 out_specs=P(None, None, "sp", None), check_vma=False)
        def f(ql, kl, vl, ml):
            return ulysses_attention(ql, kl, vl, ml, axis_name="sp",
                                     attn_impl=impl, flash_interpret=True)

        return np.asarray(jax.grad(
            lambda q_: jnp.sum(f(q_, k, v, mask) ** 2))(q))

    np.testing.assert_allclose(grad_run("flash"), grad_run("xla"),
                               atol=5e-5, rtol=1e-4)

    # dropout/seed branch executes inside shard_map: the interpreter's
    # PRNG yields zeros -> keep-all, and keep-all dropout is a uniform
    # 1/keep_prob scaling of the numerator (denominator undropped), so
    # flash-with-dropout == xla-without-dropout / 0.9 exactly
    # (exercises ulysses' derive_seed wiring; the real stream is
    # validated on-chip by scripts/flash_tpu_check.py)
    @partial(shard_map, mesh=mesh,
             in_specs=(P(None, None, "sp", None),) * 3 + (P(None, "sp"),),
             out_specs=P(None, None, "sp", None), check_vma=False)
    def f_drop(ql, kl, vl, ml):
        return ulysses_attention(
            ql, kl, vl, ml, axis_name="sp", dropout_rate=0.1,
            dropout_key=jax.random.key(3), attn_impl="flash",
            flash_interpret=True)

    np.testing.assert_allclose(np.asarray(f_drop(q, k, v, mask)),
                               run("xla", False) / 0.9, atol=2e-5)


def test_remat_policy_preserves_numerics(rng):
    """remat_policy changes WHAT is saved across fwd/bwd, never what is
    computed: grads must be bit-identical between full-layer remat and
    the attn_saved selective policy (xla lowering on CPU; the flash
    variant of the same equivalence holds by the policy mechanism being
    identical — the named values just additionally cover the kernel's
    custom-vjp residuals)."""
    from deepdfa_tpu.models import transformer as tfm

    cfg = tfm.TransformerConfig.tiny(vocab_size=128,
                                     max_position_embeddings=96)
    params = tfm.init_params(cfg, jax.random.key(0))
    ids = jnp.asarray(rng.integers(2, 128, (2, 64)), jnp.int32)

    def grads(policy):
        c = dataclasses.replace(cfg, remat_policy=policy)

        def loss(p):
            return jnp.sum(tfm.encode(c, p, ids,
                                      dropout_key=jax.random.key(1)) ** 2)

        return jax.jit(jax.grad(loss))(params)

    ga, gb = grads("full"), grads("attn_saved")
    for a, b in zip(jax.tree.leaves(ga), jax.tree.leaves(gb)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_resolve_impl_shapes_and_bias_cap():
    """The single resolution source of truth: tileability per axis, the
    biased VMEM sequence cap, forced-flash raising vs auto fallback."""
    from deepdfa_tpu.nn.flash_attention import flash_shape_ok, resolve_impl

    assert flash_shape_ok(512, 64)
    assert flash_shape_ok(1024, 64)          # tiles: 1024 % 512 == 0
    assert not flash_shape_ok(640, 64)       # does not tile
    assert not flash_shape_ok(512, 256)      # head_dim over the cap
    assert flash_shape_ok(128, 16, Tk=256)   # rectangular
    assert not flash_shape_ok(128, 16, Tk=640)
    # biased: the [block_q, Tk] bias strip caps the sequence at 4096
    assert flash_shape_ok(4096, 64, biased=True)
    assert not flash_shape_ok(8192, 64, biased=True)
    assert flash_shape_ok(8192, 64, biased=False)  # unbiased streams on

    # hardware requires 128-aligned T (Mosaic tilings are only on-chip
    # validated at aligned lengths); the interpreter hook relaxes it
    assert not flash_shape_ok(200, 64)
    assert not flash_shape_ok(512, 64, Tk=300)
    assert flash_shape_ok(200, 64, lax_alignment=True)
    assert flash_shape_ok(384, 64)           # aligned sub-512 still ok

    # forced flash raises where auto falls back
    assert resolve_impl("auto", 640, 64) == "xla"
    assert resolve_impl("auto", 200, 64) == "xla"  # unaligned -> xla
    with pytest.raises(ValueError, match="cannot tile"):
        resolve_impl("flash", 200, 64)
    assert resolve_impl("flash", 200, 64, interpret_hint=True) == "flash"
    assert resolve_impl("auto", 8192, 64, biased=True) == "xla"
    with pytest.raises(ValueError, match="cannot tile"):
        resolve_impl("flash", 8192, 64, biased=True)
    assert resolve_impl("flash", 8192, 64, interpret_hint=True) == "flash"
    with pytest.raises(ValueError, match="unknown attn_impl"):
        resolve_impl("bogus", 512, 64)


def test_auto_resolution_cpu_is_xla():
    """attn_impl=auto must NOT pick the Pallas kernel on a CPU backend
    (it would fail to lower); the env hook opts tests in explicitly."""
    from deepdfa_tpu.models.transformer import _resolve_attn_impl

    cfg_f, _ = _tiny_cfgs()
    cfg_auto = dataclasses.replace(cfg_f, attn_impl="auto")
    assert os.environ.get("DEEPDFA_TPU_FLASH_INTERPRET", "") != "1"
    assert _resolve_attn_impl(cfg_auto, 512, 64) == (
        "flash" if jax.default_backend() == "tpu" else "xla")
    # ill-shaped sequences always fall back
    assert _resolve_attn_impl(cfg_auto, 640, 64) == "xla"
    with pytest.raises(ValueError):
        _resolve_attn_impl(dataclasses.replace(cfg_auto, attn_impl="flash"),
                           640, 64)
