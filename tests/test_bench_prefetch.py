"""Tier-1 smoke for scripts/bench_prefetch.py --smoke: the whole input
pipeline (frontend -> pack -> cache -> prefetch -> place -> train) must
run end-to-end on CPU and emit the throughput record — so pipeline
breakage fails tests instead of only showing in BENCH artifacts."""

import json
import os
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent


def test_bench_prefetch_smoke(tmp_path):
    out = tmp_path / "record.json"
    env = dict(
        os.environ,
        DEEPDFA_TPU_PLATFORM="cpu",
        JAX_PLATFORMS="cpu",
    )
    proc = subprocess.run(
        [
            sys.executable,
            str(REPO / "scripts" / "bench_prefetch.py"),
            "--smoke",
            "--n-examples", "64",
            "--epochs", "1",
            "--out", str(out),
        ],
        env=env,
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=560,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    record = json.loads(out.read_text())
    assert record["smoke"] is True
    assert record["platform"] == "cpu"
    # both pipeline measurements ran and produced positive ratios
    assert record["metric"] == "prefetch_overlap_speedup"
    assert record["value"] > 0
    cache = record["cache"]
    assert cache["metric"] == "cache_replay_speedup"
    assert cache["value"] > 0
    assert cache["warm_graphs_per_sec"] > 0
    # stage attribution present: cold path packed, warm path only loaded
    assert cache["cold_pack_seconds"] > 0
    assert cache["warm_load_seconds"] > 0
