"""Parser robustness + reaching-defs property tests."""

import numpy as np
import pytest

from deepdfa_tpu.frontend import ReachingDefinitions, parse_function
from deepdfa_tpu.frontend.cpg import CFG

NASTY = [
    # function pointers, casts, ternaries
    "int f(void (*cb)(int), int x) { cb(x); return (int)(x ? x : -x); }",
    # comma operator, nested calls, string escapes
    'void g(char *s) { int a = 1, b = 2; a = (b++, strlen("a\\"b")), b += a; }',
    # do-while with continue/break
    "int h(int n) { int i = 0; do { if (n & 1) continue; if (!n) break; i += n; } while (n--); return i; }",
    # gnu asm / unknown constructs
    "void k(void) { __asm__ volatile(\"nop\" ::: ); }",
    # preprocessor remnants mid-function
    "int m(int a) {\n#ifdef X\n  a += 1;\n#endif\n  return a; }",
    # struct member chains and array-of-pointer
    "void p(struct s *q) { q->a.b[3]->c = sizeof(struct s); }",
    # old-style K&R-ish noise and varargs
    "int q(const char *fmt, ...) { return 0; }",
    # empty body, void params
    "void r(void) { }",
    # labels and gotos galore
    "int s(int a) { if (a) goto x; a = 1; x: if (!a) goto y; y: return a; }",
    # switch fallthrough without braces
    "int t(int a) { switch(a) { case 1: a=1; case 2: a=2; break; default: a=3; } return a; }",
    # deeply nested parens/conditionals
    "int u(int a){ return ((((a))+((a)*(a)))) ? ((a)) : (((a)-1)); }",
    # declarations shadowing in nested blocks
    "int v(int a){ int x = 1; { int x = 2; a += x; } return x + a; }",
    # unicode / stray bytes
    "int w(int a){ int \xc3\xa9 = 1; return a; }",
    # missing closing brace (truncated function)
    "int z(int a){ if (a) { a = 1; return a; ",
]


@pytest.mark.parametrize("code", NASTY, ids=range(len(NASTY)))
def test_parser_never_hangs_or_crashes(code):
    cpg = parse_function(code)
    # CFG must stay connected method -> method_return (when return exists)
    rd = ReachingDefinitions(cpg)
    rd.solve()  # must terminate


def test_fuzz_token_soup():
    rng = np.random.default_rng(0)
    vocab = list("abcxyz01(){}[];,*&-+=<>!~?:.\"'%^|/ \n\t") + [
        "int", "if", "while", "for", "return", "case", "switch", "goto",
    ]
    for trial in range(50):
        n = int(rng.integers(10, 200))
        soup = "int f(int a){" + "".join(
            str(vocab[int(i)]) for i in rng.integers(0, len(vocab), n)
        ) + "}"
        try:
            cpg = parse_function(soup)
            ReachingDefinitions(cpg).solve()
        except ValueError:
            pass  # lexer/parser may reject, but must not hang/crash otherwise


def _sweep_solver(rd: ReachingDefinitions, iters=200):
    """Round-robin chaotic iteration — an independent fixpoint strategy."""
    out = {n: set() for n in rd.cfg_nodes}
    for _ in range(iters):
        changed = False
        for n in rd.cfg_nodes:
            new_in = set()
            for p in rd.cpg.predecessors(n, CFG):
                new_in |= out[p]
            new_out = set(rd.gen(n)) | (new_in - rd.kill(n, new_in))
            if new_out != out[n]:
                out[n] = new_out
                changed = True
        if not changed:
            break
    in_ = {}
    for n in rd.cfg_nodes:
        s = set()
        for p in rd.cpg.predecessors(n, CFG):
            s |= out[p]
        in_[n] = s
    return in_


@pytest.mark.parametrize("code", NASTY[:10], ids=range(10))
def test_worklist_matches_sweep_fixpoint(code):
    cpg = parse_function(code)
    rd = ReachingDefinitions(cpg)
    assert rd.solve() == _sweep_solver(rd)


def test_random_cfg_reaching_property(rng):
    """On random programs: every def reaching a node has a CFG path from the
    def to the node not passing through a killing redefinition."""
    progs = [
        "int f(int a){ int x=1; int y=2; if(a){x=3;}else{y=4;} while(a--){x+=y;} return x+y; }",
        "int g(int a){ int x=0; for(int i=0;i<a;i++){ if(i%2){x=i;} } return x; }",
    ]
    for code in progs:
        cpg = parse_function(code)
        rd = ReachingDefinitions(cpg)
        in_sets = rd.solve()
        for n, defs in in_sets.items():
            for d in defs:
                # BFS from def node, blocked at redefinitions of d.var
                seen, stack = set(), [d.node]
                found = False
                while stack:
                    cur = stack.pop()
                    for s in cpg.successors(cur, CFG):
                        if s == n:
                            found = True
                            stack = []
                            break
                        if s in seen:
                            continue
                        seen.add(s)
                        # blocked by another def of same var
                        v = rd.assigned_variable(s)
                        if v == d.var and s != d.node:
                            continue
                        stack.append(s)
                assert found, (cpg.nodes[n].code, d)
