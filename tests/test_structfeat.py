"""Family-invariant structural features (frontend/structfeat.py)."""

import jax
import numpy as np
import pytest

from deepdfa_tpu.frontend import parser as cparser
from deepdfa_tpu.frontend.structfeat import (
    NUM_STRUCT_FEATS,
    STRUCT_VOCAB,
    struct_features,
)


def _features(code: str):
    cpg = cparser.parse_function(code)
    keep = [n for n in cpg.cfg_nodes() if cpg.nodes[n].line is not None]
    return cpg, keep, struct_features(cpg, keep)


def test_shapes_and_vocab_ranges():
    cpg, keep, sf = _features(
        "int f(int a) {\n  int b = a + 1;\n  if (b > 0) {\n"
        "    b = b - 1;\n  }\n  return b;\n}"
    )
    assert sf.shape == (len(keep), NUM_STRUCT_FEATS)
    for col, vocab in enumerate(STRUCT_VOCAB):
        assert sf[:, col].min() >= 0
        assert sf[:, col].max() < vocab, (col, sf[:, col].max())


def test_op_class_buckets():
    cpg, keep, sf = _features(
        "int f(int a) {\n  a = a + 1;\n  if (a > 0) {\n"
        "    g(a);\n  }\n  return a;\n}"
    )
    by_code = {cpg.nodes[nid].code: sf[row] for row, nid in enumerate(keep)}
    assert by_code["a = a + 1"][0] == 1   # assign class
    assert by_code["a > 0"][0] == 3       # compare class
    assert by_code["g(a)"][0] == 5        # plain call class
    assert by_code["return a"][0] == 8    # jump class


def test_reach_count_separates_order_family():
    """The VERDICT r4 target in miniature: the guarded-use order family's
    buggy and fixed forms have IDENTICAL token multisets, but the use
    statement sees 1 reaching def (buggy: use before clamp) vs 2 (fixed:
    the clamp's conditional redefinition also reaches). That count is
    channel 4 — local, and independent of which family's tokens appear."""
    from deepdfa_tpu.data.synthetic import V2_FAMILIES

    def use_row(vuln: bool):
        body = V2_FAMILIES["index_clamp_order"](vuln)
        code = (
            "int f(int len, int total) {\n  char buf[64];\n  int i;\n"
            + "\n".join(body) + "\n  return total;\n}"
        )
        cpg, keep, sf = _features(code)
        for row, nid in enumerate(keep):
            if cpg.nodes[nid].code.startswith("total +="):
                return sf[row]
        raise AssertionError("use statement not found")

    buggy, fixed = use_row(True), use_row(False)
    assert buggy[4] == 1
    assert fixed[4] == 2
    # every other channel agrees — the discriminator is the dataflow
    # count, not an accidental layout difference
    assert list(buggy[:3]) == list(fixed[:3])


def test_pipeline_appends_struct_columns():
    from deepdfa_tpu.data import build_dataset, generate, to_examples
    from deepdfa_tpu.graphs.batch import NUM_SUBKEY_FEATS, pack

    synth = generate(6, vuln_rate=0.5, seed=3)
    specs, _ = build_dataset(
        to_examples(synth), train_ids=range(6), limit_all=64,
        limit_subkeys=64, struct_feats=True,
    )
    width = NUM_SUBKEY_FEATS + NUM_STRUCT_FEATS
    assert all(s.node_feats.shape[1] == width for s in specs)
    batch = pack(specs, 8, 512, 2048)
    assert batch.node_feats.shape[1] == width


def test_model_trains_with_struct_feats():
    import dataclasses

    from deepdfa_tpu.core import Config, config as config_mod
    from deepdfa_tpu.data import build_dataset, generate, to_examples
    from deepdfa_tpu.graphs import pack_shards
    from deepdfa_tpu.models import DeepDFA
    from deepdfa_tpu.train import GraphTrainer

    synth = generate(6, vuln_rate=0.5, seed=4)
    specs, _ = build_dataset(
        to_examples(synth), train_ids=range(6), limit_all=64,
        limit_subkeys=64, struct_feats=True,
    )
    batch = pack_shards(specs, 1, 8, 512, 2048)
    cfg = config_mod.apply_overrides(
        Config(), ["model.hidden_dim=8", "model.struct_feats=true"]
    )
    model = DeepDFA.from_config(cfg.model, input_dim=66)
    assert model.out_dim == 2 * 8 * (4 + NUM_STRUCT_FEATS)
    from deepdfa_tpu.core import MeshConfig
    from deepdfa_tpu.parallel import make_mesh

    mesh = make_mesh(MeshConfig(dp=1), devices=jax.devices()[:1])
    trainer = GraphTrainer(model, cfg, mesh=mesh)
    state = trainer.init_state(batch)
    state, loss = trainer.train_step(state, batch)
    assert np.isfinite(float(loss))
    # the struct embedding tables exist and receive gradients
    names = [k for k in state.params["params"]["embedding"]]
    assert any(k.startswith("embed_struct_") for k in names)


def test_struct_model_rejects_planar_batch():
    from deepdfa_tpu.core import Config, config as config_mod
    from deepdfa_tpu.data import build_dataset, generate, to_examples
    from deepdfa_tpu.graphs import pack
    from deepdfa_tpu.models import DeepDFA

    synth = generate(4, vuln_rate=0.5, seed=5)
    specs, _ = build_dataset(
        to_examples(synth), train_ids=range(4), limit_all=64,
        limit_subkeys=64,  # extracted WITHOUT struct columns
    )
    batch = pack(specs, 4, 256, 1024)
    cfg = config_mod.apply_overrides(
        Config(), ["model.hidden_dim=8", "model.struct_feats=true"]
    )
    model = DeepDFA.from_config(cfg.model, input_dim=66)
    with pytest.raises(ValueError, match="struct_feats=True"):
        model.init(jax.random.key(0), batch)


def test_feat_dropout_spares_struct_columns():
    from deepdfa_tpu.train.loop import drop_known_feats

    feats = np.array(
        [[5, 7, 2, 9, 3, 15, 7, 6, 2]] * 32, np.int32
    )  # 4 vocab + 5 struct columns
    out = np.asarray(
        drop_known_feats(jax.numpy.asarray(feats), jax.random.key(0), 1.0)
    )
    # rate 1.0: every vocab bucket anonymized to UNKNOWN...
    assert (out[:, :4] == 1).all()
    # ...while the struct columns pass through untouched
    np.testing.assert_array_equal(out[:, 4:], feats[:, 4:])
