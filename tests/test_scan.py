"""Whole-repo scanning + line-level localization (deepdfa_tpu/scan/,
serve/localize.py, docs/scanning.md).

The load-bearing invariants, in-process (the CLI surface is covered by
tests/test_scan_cli.py subprocesses):

- the function splitter is lexing-robust: braces in comments/strings/
  macros never corrupt spans, line ranges are exact;
- the incremental property: after editing ONE function, a re-scan
  re-extracts and re-scores exactly that function (moves/renames reuse
  content-keyed results);
- served line attributions are BIT-IDENTICAL to the offline
  eval/localize.py path on the same checkpoint, and co-batching a
  function changes nothing (the serve invariant, extended to grads);
- the recomposed embedding-injected GGNN forward equals model.apply
  exactly (the drift guard for every gradient method);
- scan and serve share ONE frontend-cache namespace;
- SARIF output is structurally valid and the scan_log record is
  schema-declared.
"""

import dataclasses
import json
import types
from pathlib import Path

import numpy as np
import pytest

from deepdfa_tpu.core import Config, config as config_mod
from deepdfa_tpu.data import build_dataset, generate, to_examples
from deepdfa_tpu.scan.manifest import ScanManifest
from deepdfa_tpu.scan.sarif import sarif_report, validate_sarif
from deepdfa_tpu.scan.scanner import RepoScanner
from deepdfa_tpu.scan.walker import (
    split_functions,
    walk_repo,
)

NODE_BUDGET, EDGE_BUDGET = 2048, 8192


# ---------------------------------------------------------------------------
# walker + splitter


TRICKY = """/* file comment with { brace */
#include <stdio.h>
#define WRAP(x) { (x)++; }

static const int table[] = { 1, 2, 3 };

struct ops { int (*fn)(void); };

int add(int a, int b) {
  const char *s = "{ not a brace }";
  // } also not a brace
  return a + b;
}

static inline unsigned long
get_value(struct ops *o)
{
  if (o->fn) {
    return o->fn();
  }
  return 0;
}

int (*pick(void))(void) {
  return 0;
}

namespace foo {
extern "C" {
int inner(int x) { return x * 2; }
}
}

class Widget {
  int method() { return 1; }
};
"""


def test_split_functions_tricky_source():
    spans = split_functions(TRICKY)
    names = [s.name for s in spans]
    # the table initializer, struct/class bodies and the in-class method
    # are NOT functions; the namespace/extern block is transparent
    assert names == ["add", "get_value", "pick", "inner"]
    add = spans[0]
    assert (add.start_line, add.end_line) == (9, 13)
    assert add.code.splitlines()[0] == "int add(int a, int b) {"
    assert add.code.splitlines()[-1] == "}"
    gv = spans[1]
    # multi-line header: the span starts at the return type line
    assert gv.code.splitlines()[0] == "static inline unsigned long"
    inner = spans[3]
    assert inner.start_line == inner.end_line


def test_split_functions_declarations_inside_transparent_blocks():
    """Statement boundaries must reset INSIDE namespace / extern "C"
    blocks too — a `= 0;` declaration before a function used to poison
    its header and silently drop it (code-review regression)."""
    src = (
        'extern "C" {\n'
        "int g_x = 0;\n"
        "void api(void) { g_x++; }\n"
        "}\n"
        "namespace ns {\n"
        "static int counter = 3;\n"
        "int f(int a) { return a + counter; }\n"
        "}\n"
    )
    assert [s.name for s in split_functions(src)] == ["api", "f"]


def test_split_functions_line_coordinates_roundtrip():
    text = TRICKY
    lines = text.split("\n")
    for s in split_functions(text):
        assert s.code == "\n".join(lines[s.start_line - 1 : s.end_line])


def test_walk_repo_rules(tmp_path):
    (tmp_path / "src").mkdir()
    (tmp_path / "src" / "a.c").write_text("int a(void) { return 0; }\n")
    (tmp_path / "src" / "b.txt").write_text("not source")
    (tmp_path / ".git").mkdir()
    (tmp_path / ".git" / "decoy.c").write_text("int g(void) { return 0; }\n")
    (tmp_path / "vendor").mkdir()
    (tmp_path / "vendor" / "v.c").write_text("int v(void) { return 0; }\n")
    (tmp_path / "big.c").write_text("int big;\n" * 10000)

    stats = {}
    files = walk_repo(
        tmp_path, suffixes=(".c",), exclude_dirs=("vendor",),
        max_file_bytes=1024, stats=stats,
    )
    assert [f.rel for f in files] == ["src/a.c"]
    assert stats["files_too_large"] == 1


# ---------------------------------------------------------------------------
# shared model fixtures (the test_serve pattern)


@pytest.fixture(scope="module")
def corpus():
    synth = generate(12, seed=5)
    examples = to_examples(synth)
    specs, vocabs = build_dataset(
        examples, train_ids=range(12), limit_all=50, limit_subkeys=50
    )
    return examples, specs, vocabs


@pytest.fixture(scope="module")
def served_model(corpus):
    import jax

    from deepdfa_tpu.graphs.batch import pack
    from deepdfa_tpu.models import DeepDFA

    cfg = config_mod.apply_overrides(Config(), [
        'data.feat={"limit_all": 50, "limit_subkeys": 50}',
        "model.hidden_dim=8", "model.n_steps=2",
        "serve.max_batch_graphs=4",
        "serve.node_budget=2048", "serve.edge_budget=8192",
    ])
    model = DeepDFA.from_config(cfg.model, input_dim=cfg.data.feat.input_dim)
    params = model.init(
        jax.random.key(0), pack([], 1, NODE_BUDGET, EDGE_BUDGET)
    )
    return cfg, model, params


# ---------------------------------------------------------------------------
# GGNN attribution: recomposition parity + method contracts


def test_ggnn_forward_matches_model_apply(corpus, served_model):
    """The embedding-injected recomposed forward is bit-identical to
    model.apply — the drift guard for every gradient method."""
    import jax

    from deepdfa_tpu.eval import localize as L
    from deepdfa_tpu.graphs.batch import pack

    _, specs, _ = corpus
    _, model, params = served_model
    batch = pack(specs[:4], 4, NODE_BUDGET, EDGE_BUDGET)
    ref = np.asarray(model.apply(params, batch))
    fn, rows = L.ggnn_forward(model, params, batch)
    logits, attn = fn(rows)
    assert np.array_equal(ref, np.asarray(logits))
    # the pooling gate is a per-graph softmax over real nodes
    sums = np.asarray(jax.ops.segment_sum(
        attn, batch.node_graph, batch.num_graphs + 1
    ))[:4]
    np.testing.assert_allclose(sums, 1.0, atol=1e-5)


def test_ggnn_methods_shapes_and_masking(corpus, served_model):
    import jax

    from deepdfa_tpu.eval import localize as L
    from deepdfa_tpu.graphs.batch import pack

    _, specs, _ = corpus
    _, model, params = served_model
    batch = pack(specs[:3], 4, NODE_BUDGET, EDGE_BUDGET)
    mask = np.asarray(batch.node_mask)
    for method in L.GGNN_METHODS:
        probs, scores = jax.jit(L.ggnn_score_fn(method, model, n_steps=2))(
            params, batch
        )
        probs, scores = np.asarray(probs), np.asarray(scores)
        assert probs.shape == (4,)
        assert scores.shape == (NODE_BUDGET,)
        assert np.all(scores[~mask] == 0), method
        assert np.isfinite(scores).all(), method
        assert np.abs(scores[mask]).max() > 0, method


def test_unknown_method_and_node_label_style_rejected(served_model):
    from deepdfa_tpu.eval import localize as L

    _, model, _ = served_model
    with pytest.raises(ValueError, match="unknown GGNN method"):
        L.ggnn_score_fn("nope", model)
    node_model = dataclasses.replace(model, label_style="node")
    with pytest.raises(ValueError, match="label_style"):
        L.ggnn_forward(node_model, {"params": {}}, None)


def _features(pre, examples, n):
    out = []
    for e in examples[:n]:
        out.append(pre.features_full(e.code, e.id))
    return out


def test_served_lines_bit_identical_to_offline(corpus, served_model):
    """The acceptance invariant: attributions served through the AOT
    localizer equal the offline eval/localize.py path on the same
    checkpoint EXACTLY — and co-batching changes nothing."""
    import jax

    from deepdfa_tpu.eval import localize as L
    from deepdfa_tpu.graphs.batch import pack
    from deepdfa_tpu.serve.frontend import RequestPreprocessor
    from deepdfa_tpu.serve.localize import GgnnLocalizer

    examples, _, vocabs = corpus
    cfg, model, params = served_model
    pre = RequestPreprocessor(cfg, vocabs, cache_entries=64)
    feats = _features(pre, examples, 4)

    localizer = GgnnLocalizer(
        model, lambda: params,
        node_budget=NODE_BUDGET, edge_budget=EDGE_BUDGET,
        sizes=(1, 2, 4), method="saliency", n_steps=2, top_k=0,
    )
    localizer.warmup()
    n0 = localizer.jit_lowerings()
    assert n0 == 3

    # offline: the SAME attribution function, plain jit, singleton pack
    offline = jax.jit(L.ggnn_score_fn("saliency", model, n_steps=2))
    served_alone = {}
    for f in feats:
        batch = pack([f.spec], 1, NODE_BUDGET, EDGE_BUDGET)
        probs, scores = offline(params, batch)
        ref = L.node_line_attributions(
            np.asarray(scores)[: f.spec.num_nodes], f.node_lines
        )
        [(prob, lines)] = localizer.attribute([f])
        assert lines == ref, "served != offline (singleton)"
        assert prob == float(np.asarray(probs)[0])
        served_alone[f.spec.graph_id] = lines

    # co-batched: same ranking, scores equal to float32 reduction
    # tolerance (the BACKWARD pass reassociates reductions across pad
    # shapes, unlike the forward score path — so the bit-identity
    # contract is singleton-vs-offline, and co-batching is pinned to
    # tolerance; docs/scanning.md)
    batched = localizer.attribute(feats)
    for f, (_, lines) in zip(feats, batched):
        ref = served_alone[f.spec.graph_id]
        assert [d["line"] for d in lines] == [d["line"] for d in ref]
        np.testing.assert_allclose(
            [d["score"] for d in lines], [d["score"] for d in ref],
            rtol=1e-5, atol=1e-7,
        )
    # zero steady-state lowerings across all of the above
    assert localizer.jit_lowerings() == n0


def test_localizer_pipelined_matches_serial(corpus, served_model):
    """ISSUE 17: the software-pipelined attribute_all drive (bounded
    dispatch window, sync-oldest) returns EXACTLY what the serial drive
    returns — same chunking, same programs, only the sync point moves —
    and lowers nothing new in steady state."""
    from deepdfa_tpu.serve.frontend import RequestPreprocessor
    from deepdfa_tpu.serve.localize import GgnnLocalizer

    examples, _, vocabs = corpus
    cfg, model, params = served_model
    pre = RequestPreprocessor(cfg, vocabs, cache_entries=64)
    feats = _features(pre, examples, 6)

    piped = GgnnLocalizer(
        model, lambda: params, pipeline_depth=2,
        node_budget=NODE_BUDGET, edge_budget=EDGE_BUDGET,
        sizes=(1, 2, 4), method="saliency", n_steps=2, top_k=0,
    )
    piped.warmup()
    n0 = piped.jit_lowerings()

    # serial reference: per-chunk attribute() IS the serial composition
    # of the same stages (and the depth-0 attribute_all code path), over
    # the same greedy chunking the pipelined drive uses
    ref, chunk = [], []
    for f in feats:
        if chunk and not piped.fits(chunk, f):
            ref.extend(piped.attribute(chunk))
            chunk = []
        chunk.append(f)
    ref.extend(piped.attribute(chunk))

    out = piped.attribute_all(feats)
    assert out == ref, "pipelined attribute_all != serial"
    assert piped.jit_lowerings() == n0


def test_shared_frontend_cache_namespace(corpus, served_model):
    """Satellite 6: two preprocessors handed the shared store hit each
    other's entries (scan warm-fills serve, and vice versa)."""
    from deepdfa_tpu.obs import metrics as obs_metrics
    from deepdfa_tpu.serve import frontend as fe

    examples, _, vocabs = corpus
    cfg, _, _ = served_model
    shared = fe.shared_cache(64)
    a = fe.RequestPreprocessor(cfg, vocabs, cache=shared)
    b = fe.RequestPreprocessor(cfg, vocabs, cache=shared)
    assert a.cache is b.cache
    code = examples[0].code
    a.features(code)
    hits = obs_metrics.REGISTRY.counter("serve/cache_hits")
    before = hits.value
    sb = b.features(code)
    assert hits.value == before + 1
    assert sb is a.features(code)
    # growing never shrinks
    assert fe.shared_cache(8).max_entries >= 64


# ---------------------------------------------------------------------------
# manifest


def test_manifest_identity_invalidation(tmp_path):
    path = tmp_path / "m.json"
    m = ScanManifest(path, {"config_digest": "aaa", "lines": False})
    m.record_file("a.c", "sha1", [{"key": "k1", "name": "f",
                                   "start_line": 1, "end_line": 3}])
    m.record_result("k1", {"ok": True, "prob": 0.5})
    m.save()

    same = ScanManifest.load(path, {"config_digest": "aaa",
                                    "lines": False})
    assert same.resumed and same.result("k1")["prob"] == 0.5
    assert same.file_functions("a.c", "sha1")[0]["key"] == "k1"
    assert same.file_functions("a.c", "CHANGED") is None

    other = ScanManifest.load(path, {"config_digest": "bbb",
                                     "lines": False})
    assert not other.resumed and other.result("k1") is None

    # a file entry whose function result is missing forces a re-split
    same.functions.pop("k1")
    assert same.file_functions("a.c", "sha1") is None


def test_manifest_prune_and_atomicity(tmp_path):
    path = tmp_path / "m.json"
    m = ScanManifest(path, {"v": 1})
    for i in range(3):
        m.record_result(f"k{i}", {"ok": True, "prob": 0.1 * i})
        m.record_file(f"f{i}.c", f"s{i}", [])
    m.prune({"f0.c"}, {"k0"})
    m.save()
    back = ScanManifest.load(path, {"v": 1})
    assert set(back.functions) == {"k0"} and set(back.files) == {"f0.c"}
    # no stray tmp files (atomic_write_text renamed into place)
    assert [p.name for p in tmp_path.iterdir()] == ["m.json"]


# ---------------------------------------------------------------------------
# sarif


def _finding(prob=0.7, lines=None):
    return {
        "file": "src/a.c", "function": "f", "start_line": 3,
        "end_line": 9, "ok": True, "prob": prob,
        **({"lines": lines} if lines else {}),
    }


def test_sarif_report_valid_and_mapped(tmp_path):
    doc = sarif_report(
        [
            _finding(0.95, lines=[{"line": 5, "score": 0.4}]),
            _finding(0.6),
            _finding(0.2),  # below threshold
            {"file": "b.c", "function": "g", "start_line": 1,
             "end_line": 2, "ok": False, "error": "unparseable"},
        ],
        tmp_path, threshold=0.5,
    )
    assert validate_sarif(doc) == []
    results = doc["runs"][0]["results"]
    assert len(results) == 2
    assert results[0]["level"] == "error"  # >= 0.9
    assert results[1]["level"] == "warning"
    region = results[0]["locations"][0]["physicalLocation"]["region"]
    assert (region["startLine"], region["endLine"]) == (3, 9)
    rel = results[0]["relatedLocations"][0]
    assert rel["physicalLocation"]["region"]["startLine"] == 5


def test_sarif_validator_rejects_structural_damage(tmp_path):
    doc = sarif_report([_finding()], tmp_path, threshold=0.5)
    bad = json.loads(json.dumps(doc))
    bad["version"] = "2.0.0"
    bad["runs"][0]["results"][0]["locations"][0]["physicalLocation"][
        "region"]["startLine"] = 0
    problems = validate_sarif(bad)
    assert any("version" in p for p in problems)
    assert any("startLine" in p for p in problems)


# ---------------------------------------------------------------------------
# the incremental-rescan property, end to end in-process


@pytest.fixture()
def scan_service(corpus, served_model, tmp_path):
    """A real scan engine over a stub registry — the pieces RepoScanner
    touches, none of the checkpoint round trip (test_scan_cli covers
    that in subprocesses)."""
    from deepdfa_tpu.serve.batcher import DynamicBatcher, GgnnExecutor
    from deepdfa_tpu.serve.frontend import RequestPreprocessor

    examples, _, vocabs = corpus
    cfg, model, params = served_model
    cfg = config_mod.apply_overrides(cfg, [
        "scan.lines=true", "serve.lines_steps=2", "scan.threshold=0.0",
    ])
    run_dir = tmp_path / "run"
    run_dir.mkdir()
    executor = GgnnExecutor(
        model, lambda: params,
        node_budget=NODE_BUDGET, edge_budget=EDGE_BUDGET,
        max_batch_graphs=4,
    )
    executor.warmup()
    registry = types.SimpleNamespace(
        run_dir=run_dir, config_digest="cfg0", vocab_digest="voc0",
        checkpoint="best", _loaded_step=0, model=model,
        params=lambda: params,
        _feat_width=lambda: 4,
    )
    service = types.SimpleNamespace(
        cfg=cfg,
        registry=registry,
        frontend=RequestPreprocessor(cfg, vocabs, cache_entries=256),
        executor=executor,
        batcher=DynamicBatcher(executor, queue_limit=64),
        localizer=None,
    )
    return service, cfg, examples


def _write_repo(repo: Path, examples, per_file=2):
    repo.mkdir(parents=True, exist_ok=True)
    codes = [e.code for e in examples]
    for i in range(0, len(codes), per_file):
        (repo / f"mod_{i // per_file}.c").write_text(
            "\n".join(codes[i : i + per_file]) + "\n"
        )


def test_incremental_rescan_property(scan_service, tmp_path):
    service, cfg, examples = scan_service
    scanner = RepoScanner(service, cfg)
    repo = tmp_path / "repo"
    _write_repo(repo, examples[:8], per_file=2)

    cold = scanner.scan(repo)
    assert cold["scan_functions"] == 8
    assert cold["scan_extracted"] == 8 and cold["scan_reused"] == 0
    assert cold["scan_steady_state_recompiles"] == 0
    assert cold["scan_lines_steady_state_recompiles"] == 0

    # no edit -> nothing re-extracts, every file split is reused
    idle = scanner.scan(repo)
    assert idle["scan_extracted"] == 0
    assert idle["scan_reused"] == 8
    assert idle["scan_files_reused"] == idle["scan_files"]

    # edit ONE function (insert a statement) -> exactly one re-extract,
    # and later functions in the same file (shifted lines, same bytes)
    # are still reused
    target = repo / "mod_0.c"
    text = target.read_text()
    spans = split_functions(text)
    lines = text.split("\n")
    lines.insert(spans[0].start_line, "  int edited_marker = 1;")
    target.write_text("\n".join(lines))
    incr = scanner.scan(repo)
    assert incr["scan_extracted"] == 1
    assert incr["scan_reused"] == incr["scan_functions"] - 1
    assert incr["scan_steady_state_recompiles"] == 0
    assert incr["scan_lines_steady_state_recompiles"] == 0

    # findings reflect the shifted absolute lines of the UNCHANGED
    # second function
    findings = {
        (f["file"], f["function"], i): f
        for i, f in enumerate(
            json.loads(ln)
            for ln in Path(incr["scores_path"]).read_text().splitlines()
        )
    }
    moved = [
        f for f in findings.values()
        if f["file"] == "mod_0.c"
    ]
    assert moved[1]["start_line"] == spans[1].start_line + 1

    # a rename re-splits the file but reuses every content-keyed score
    target.rename(repo / "renamed.c")
    ren = scanner.scan(repo)
    assert ren["scan_extracted"] == 0
    assert ren["scan_reused"] == ren["scan_functions"]


def test_scan_log_record_is_schema_declared(scan_service, tmp_path):
    from deepdfa_tpu.obs import metrics as obs_metrics

    service, cfg, examples = scan_service
    scanner = RepoScanner(service, cfg)
    repo = tmp_path / "repo2"
    _write_repo(repo, examples[:4])
    scanner.scan(repo)
    records = [
        json.loads(ln)
        for ln in (service.registry.run_dir / "scan_log.jsonl")
        .read_text().splitlines()
    ]
    assert records
    assert obs_metrics.undeclared_tags(records) == []


def test_identity_drift_forces_cold_scan(scan_service, tmp_path):
    """A new checkpoint step must never serve manifest-cached scores."""
    service, cfg, examples = scan_service
    scanner = RepoScanner(service, cfg)
    repo = tmp_path / "repo3"
    _write_repo(repo, examples[:4])
    assert scanner.scan(repo)["scan_extracted"] == 4
    service.registry._loaded_step = 7  # hot-swap advanced the tag
    redo = scanner.scan(repo)
    assert redo["scan_reused"] == 0  # NO manifest reuse
    assert redo["scan_scored"] == 4  # every function re-scored...
    assert redo["scan_cache_hit_fraction"] == 1.0  # ...off the warm cache
