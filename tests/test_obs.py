"""Unified run telemetry (deepdfa_tpu/obs/, ISSUE 4): Chrome-trace
round-trip validity, cross-process span forwarding through the spawn
packer pool, the metrics registry + declared schema, the lagged
step-timer, xprof capture control, the diag CLI smoke, and the logging
satellites (single-handle RunLogger, non-finite TB guard, deterministic
flatten collisions)."""

import json
import math
import threading
import time
from collections import defaultdict

import numpy as np

from deepdfa_tpu.obs import metrics as obs_metrics, trace, xprof
from tests.conftest import run_cli
from tests.test_graphs import make_graph


def _grouped(events):
    by_thread = defaultdict(list)
    for e in events:
        if e.get("ph") in ("X", "i"):
            by_thread[(e["pid"], e["tid"])].append(e)
    return by_thread


def test_trace_span_roundtrip_valid_chrome_trace(tmp_path):
    tdir = tmp_path / "trace"
    trace.enable(tdir, process_name="main")
    try:
        for i in range(5):
            with trace.span("pack", cat="input", i=i):
                pass
            with trace.span("train_step", cat="train", step=i):
                with trace.span("inner", cat="train"):
                    pass
        trace.instant("rollback", cat="resilience", step=3)
        trace.counter("queue_depth", 2.0)

        done = threading.Event()

        def worker():
            with trace.span("place", cat="input"):
                time.sleep(0.001)
            done.set()

        t = threading.Thread(target=worker, name="batch-prefetch-0")
        t.start()
        t.join()
        assert done.is_set()
    finally:
        trace.disable()

    # parseable merged Chrome trace
    out = tmp_path / "trace.json"
    n = trace.write_chrome_trace(tdir, out)
    doc = json.loads(out.read_text())
    events = doc["traceEvents"]
    assert len(events) == n and n > 0
    # every complete event is well-formed
    for e in events:
        if e.get("ph") == "X":
            assert e["dur"] >= 0
            assert {"name", "cat", "ts", "pid", "tid"} <= set(e)
    # strictly monotonic per-thread timestamps (the tie-nudge contract)
    for (_, _), evs in _grouped(events).items():
        ts = [e["ts"] for e in evs]
        assert ts == sorted(ts)
        assert len(set(ts)) == len(ts), "duplicate per-thread timestamps"
    # both threads present, with thread_name metadata
    names = {
        e["args"]["name"] for e in events if e.get("name") == "thread_name"
    }
    assert any("batch-prefetch" in s for s in names)
    # instants + counters survived the round trip
    assert any(
        e.get("ph") == "i" and e["name"] == "rollback" for e in events
    )
    assert any(e.get("ph") == "C" for e in events)


def test_trace_disabled_is_noop(tmp_path):
    assert not trace.enabled()
    with trace.span("x", cat="t"):
        pass
    trace.instant("y")
    assert trace.span("x") is trace.span("z")  # shared null singleton


def test_trace_multiprocess_packer_workers(tmp_path, rng):
    """Spans from spawn-pool packer workers land in the merged timeline.

    The contract under test is CROSS-PROCESS FORWARDING: a worker that
    packed anything must have self-enabled from the exported env var and
    contributed spans under its own pid. It deliberately does NOT assert
    that BOTH pool workers packed: with a small corpus on a small host,
    the first spawned worker routinely drains every queued plan before
    the second finishes interpreter startup — pool load balance is a
    scheduling property, not a tracing one (this assertion was the
    PR-4..PR-5 flake: `len(pids) >= 3` failed whenever worker 2 started
    late and got no work)."""
    from deepdfa_tpu.data.mp_pack import mp_shard_bucket_batches
    from deepdfa_tpu.data.prefetch import prefetch

    corpus = [
        make_graph(rng, i, int(rng.integers(3, 20)), 10, label=float(i % 2))
        for i in range(10)
    ]
    tdir = tmp_path / "trace"
    trace.enable(tdir, process_name="main", export_env=True)
    try:
        stream = mp_shard_bucket_batches(
            corpus, 1, 2, 64, 256, workers=2
        )
        batches = list(prefetch(stream, 2, producers=1))
        assert batches
    finally:
        trace.disable()
    events = [e for e in trace.merge(tdir) if e.get("ph") == "X"]
    pids = {e["pid"] for e in events}
    import os

    assert os.getpid() in pids, f"no main-process spans, got {pids}"
    worker_spans = [e for e in events if e.get("cat") == "pack_worker"]
    assert worker_spans, "no packer-worker spans in the merged trace"
    worker_pids = {e["pid"] for e in worker_spans} - {os.getpid()}
    assert worker_pids, "pack_worker spans did not come from worker pids"
    # the consumer side contributed input-stage spans too
    assert any(e.get("cat") == "input" for e in events)


def test_metrics_registry_and_schema():
    r = obs_metrics.MetricsRegistry()
    r.counter("obs/resilience/rollbacks").inc()
    r.counter("obs/resilience/rollbacks").inc(2)
    r.gauge("obs/resilience/resumed_from_step").set(42)
    h = r.histogram("obs/step/seconds")
    for v in (0.1, 0.2, 0.3):
        h.observe(v)
    h.observe(float("nan"))  # ignored
    snap = r.snapshot()
    assert snap["obs/resilience/rollbacks"] == 3.0
    assert snap["obs/resilience/resumed_from_step"] == 42.0
    assert snap["obs/step/seconds/count"] == 3.0
    assert math.isclose(snap["obs/step/seconds/mean"], 0.2)
    assert snap["obs/step/seconds/max"] == 0.3
    # every snapshot tag of this registry is schema-declared
    assert not [k for k in snap if not obs_metrics.declared(k)]
    # undeclared detection works
    bad = obs_metrics.undeclared_tags(
        [{"epoch": 1, "totally_new_metric": 3.0}]
    )
    assert bad == ["totally_new_metric"]
    assert obs_metrics.undeclared_tags([{"epoch": 1, "val_f1": 0.5}]) == []


def test_step_timer_lagged_fetch():
    r = obs_metrics.MetricsRegistry()
    timer = xprof.StepTimer(lag=1, registry=r)
    for i in range(4):
        timer.dispatched(np.float32(i))
    timer.drain()
    snap = r.snapshot()
    # 4 dispatched, lag 1 -> 3 fetched -> 2 completion intervals
    assert snap["obs/step/fetch_wait_seconds/count"] == 3.0
    assert snap["obs/step/seconds/count"] == 2.0


def test_step_timer_device_track_keeps_backdated_starts(tmp_path):
    """step_device windows are reconstructed BACKDATED (ts = dispatch
    time, observed at the lagged fetch) and live on the synthetic device
    track — the per-thread monotonic nudge must not shift them onto the
    next step's timestamps."""
    import jax  # noqa: F401  pre-import: the first dispatched() would
    # otherwise absorb the jax import and skew window 0

    trace.enable(tmp_path / "trace", process_name="m")
    try:
        timer = xprof.StepTimer(lag=1, registry=obs_metrics.MetricsRegistry())
        for i in range(4):
            with trace.span("train_step", cat="train", step=i):
                time.sleep(0.005)
            timer.dispatched(np.float32(i))
    finally:
        trace.disable()
    events = trace.merge(tmp_path / "trace")
    steps = [
        (e["ts"], e["dur"]) for e in events if e.get("name") == "train_step"
    ]
    dev = [
        (e["ts"], e["tid"]) for e in events if e.get("name") == "step_device"
    ]
    assert len(dev) == 3
    for k, (ts, tid) in enumerate(dev):
        assert tid == trace.DEVICE_TRACK_TID
        # window k starts when dispatch k returned (end of its span),
        # never a whole (5ms-sleep) step later
        dispatch_k = steps[k][0] + steps[k][1]
        assert abs(ts - dispatch_k) < 4000, (k, ts, dispatch_k)
    names = {
        e["args"]["name"] for e in events if e.get("name") == "thread_name"
    }
    assert "device-steps" in names


def test_xprof_controller_window_and_trigger(tmp_path):
    import jax
    import jax.numpy as jnp

    ctrl = xprof.XprofController(
        tmp_path / "xprof", start_step=2, num_steps=1, trigger=True
    )
    try:
        ctrl.on_step(0)
        assert ctrl._active_until is None
        ctrl.on_step(2)  # window start
        assert ctrl._active_until == 3
        (jnp.ones((4, 4)) @ jnp.ones((4, 4))).block_until_ready()
        ctrl.on_step(3)  # window end
        assert ctrl._active_until is None
        assert (tmp_path / "xprof" / "step-00000002").is_dir()
        # trigger file arms a second capture on a poll boundary
        ctrl.trigger_path.touch()
        ctrl.on_step(20)
        assert ctrl._active_until == 21
        ctrl.on_step(21)
        assert ctrl._captures == 2
        assert not ctrl.trigger_path.exists()  # consumed
    finally:
        ctrl.close()
    del jax


def test_device_memory_stats_shape():
    stats = xprof.device_memory_stats()
    # CPU backends report nothing; whatever is reported must be floats
    assert all(isinstance(v, float) for v in stats.values())


def test_flatten_collision_last_write_wins():
    from deepdfa_tpu.train.logging import flatten_scalars

    before = obs_metrics.REGISTRY.counter(
        "obs/logging/flatten_collisions"
    ).value
    out = flatten_scalars({"a/b": 1.0, "a": {"b": 2.0}, "c": 3.0})
    assert out == {"a/b": 2.0, "c": 3.0}  # deterministic: last write wins
    after = obs_metrics.REGISTRY.counter(
        "obs/logging/flatten_collisions"
    ).value
    assert after == before + 1


class _FakeTB:
    def __init__(self):
        self.calls = []

    def add_scalar(self, k, v, global_step):
        self.calls.append((k, v, global_step))

    def flush(self):
        pass

    def close(self):
        pass


def test_runlogger_single_handle_and_nonfinite_guard(tmp_path):
    from deepdfa_tpu.train.logging import RunLogger

    lg = RunLogger(tmp_path / "run", tensorboard=False)
    lg._tb = _FakeTB()
    with lg:
        first_file = lg._file
        lg.log({"step": 1, "loss": float("nan"), "grad_norm": float("inf"),
                "ok_metric": 1.5})
        lg.log({"step": 2, "loss": 0.25})
        assert lg._file is first_file  # one handle, no reopen per record
    # jsonl keeps the non-finite values verbatim (honest record)
    lines = (tmp_path / "run" / "train_log.jsonl").read_text().splitlines()
    assert len(lines) == 2
    assert math.isnan(json.loads(lines[0])["loss"])
    # the TB mirror dropped-and-counted them instead of crashing
    assert lg.nonfinite_dropped == 2
    tags = {k for k, _, _ in lg._tb.calls}
    assert tags == {"ok_metric", "loss"}  # record-2 loss is finite
    assert all(math.isfinite(v) for _, v, _ in lg._tb.calls)


def test_diag_smoke_cli(tmp_path):
    """The acceptance-criteria tier-1 surface: `deepdfa-tpu diag --smoke`
    builds a synthetic run dir through the real emitters and every diag
    section materializes."""
    res = run_cli(tmp_path, "diag", "--smoke", timeout=300)
    assert "diag smoke OK" in res.stdout
    assert "throughput timeline" in res.stdout
    assert "stage attribution" in res.stdout
    assert "resilience events" in res.stdout


def test_diag_reads_real_run_dir(tmp_path):
    """diag over a dir produced by the real RunLogger + tracer computes
    matching stage attribution from records and from the event stream."""
    from deepdfa_tpu.obs import diag
    from deepdfa_tpu.train.logging import RunLogger

    run_dir = tmp_path / "run"
    with RunLogger(run_dir, tensorboard=False) as lg:
        lg.log({
            "epoch": 0, "train_loss": 0.5, "epoch_seconds": 1.0,
            "host_pack_seconds": 0.4, "input_wait_seconds": 0.1,
            "input_wait_fraction": 0.1,
        })
    trace.enable(run_dir / "trace", process_name="main")
    try:
        with trace.span("pack", cat="input"):
            time.sleep(0.002)
    finally:
        trace.disable()
    report = diag.diagnose(run_dir)
    assert report["summary"]["epochs"] == 1
    attr = report["stage_attribution"]
    assert attr["from_records"]["pack"] == 0.4
    assert attr["from_trace"]["pack"] > 0
    assert len(report["timeline"]) == 1
