"""Robustness floors on real third-party C (VERDICT r3 item 5).

Live-harvests functions the builder did not write (BoringSSL crypto,
CPython/Tcl build sources, /usr/include static inlines — see
scripts/fidelity_robustness.py) and pushes them through the full
frontend pipeline. The committed full-sweep evidence is
docs/fidelity_robustness_report.json (1671 functions); this test pins
floors on a smaller live sample so regressions in the parser/solvers
show up in the lane. Skips when none of the source trees exist."""

import pytest

from tests.conftest import load_script_module

pytestmark = pytest.mark.slow


def test_third_party_corpus_floors():
    fr = load_script_module("fidelity_robustness")
    funcs = fr.harvest(80)
    if len(funcs) < 40:
        pytest.skip(f"only {len(funcs)} third-party functions on this box")
    audit = {
        k: 0
        for k in (
            "n", "parse_crash", "invariant_violation", "solver_ok",
            "solver_crash", "native_agree", "native_disagree", "absdf_ok",
            "absdf_raise", "extract_ok", "extract_skip", "extract_crash",
        )
    }
    audit["reach_sum"] = 0.0
    audit["reach_n"] = 0
    for _path, fn in funcs:
        fr.check_one(fn, audit)
    n = audit["n"]
    # floors: parser survives real C (<=2% crash), invariants always hold,
    # both solvers terminate and agree, the pipeline never crashes
    # (skip-and-log is fine, reference getgraphs.py:57-59)
    assert audit["parse_crash"] / n <= 0.02, audit
    assert audit["invariant_violation"] == 0, audit
    assert audit["solver_crash"] == 0, audit
    assert audit["native_disagree"] == 0, audit
    assert audit["extract_crash"] == 0, audit
    if audit["reach_n"]:
        assert audit["reach_sum"] / audit["reach_n"] >= 0.97, audit
