"""Quantized serving executables (serve/quant.py, docs/cascade.md).

The load-bearing invariants:

- per-channel symmetric int8 round-trips weights within the per-channel
  scale's quantization step (one outlier channel cannot poison the
  others);
- a registry `tag@int8` entry restores REAL int8/bf16 params (the HBM
  density win the per-entry param-bytes ledger measures), scores within
  the drift bound of the fp32 entry through the SAME AOT machinery, and
  never recompiles post-warmup;
- an over-bound quantization is refused loudly with the offending param
  paths named (CheckpointMismatch style), at load AND at hot swap.
"""

import json

import numpy as np
import pytest

from deepdfa_tpu.core import Config, config as config_mod
from deepdfa_tpu.data import build_dataset, generate, to_examples
from deepdfa_tpu.serve import quant
from deepdfa_tpu.serve.batcher import DynamicBatcher, GgnnExecutor

NODE_BUDGET, EDGE_BUDGET = 2048, 8192


@pytest.fixture(scope="module")
def corpus():
    synth = generate(16, seed=3)
    examples = to_examples(synth)
    specs, vocabs = build_dataset(
        examples, train_ids=range(16), limit_all=50, limit_subkeys=50
    )
    return examples, specs, vocabs


@pytest.fixture(scope="module")
def served_model():
    import jax

    from deepdfa_tpu.graphs.batch import pack
    from deepdfa_tpu.models import DeepDFA

    cfg = config_mod.apply_overrides(Config(), [
        'data.feat={"limit_all": 50, "limit_subkeys": 50}',
        "model.hidden_dim=8", "model.n_steps=2",
    ])
    model = DeepDFA.from_config(
        cfg.model, input_dim=cfg.data.feat.input_dim
    )
    params = model.init(
        jax.random.key(0), pack([], 1, NODE_BUDGET, EDGE_BUDGET)
    )
    return cfg, model, params


def _write_run(tmp_path, cfg, params, vocabs, dataset):
    """Real run-dir artifacts (config.json + vocab + checkpoints/best)
    without a training loop — the registry restore path's fixture."""
    import jax

    from deepdfa_tpu.core import paths
    from deepdfa_tpu.train.checkpoint import CheckpointManager

    (paths.processed_dir(dataset) / f"vocab{cfg.data.feat.name}.json"
     ).write_text(json.dumps({k: v.to_json() for k, v in vocabs.items()}))
    run_dir = tmp_path / "runs" / cfg.run_name
    run_dir.mkdir(parents=True, exist_ok=True)
    config_mod.to_json(cfg, run_dir / "config.json")
    CheckpointManager(run_dir / "checkpoints", monitor="val_loss").save(
        "epoch-0001", jax.device_get(params), {"val_loss": 1.0}, step=1
    )
    return run_dir


# ---------------------------------------------------------------------------
# pure quantizer properties


def test_per_channel_roundtrip_bounded_error(rng):
    w = rng.normal(size=(16, 32)).astype(np.float32)
    # one huge outlier CHANNEL: per-tensor scaling would flatten every
    # other channel to ~zero; per-channel must keep them accurate
    w[:, 7] *= 1000.0
    q = quant.quantize_leaf(w)
    assert q["int8"].dtype == np.int8
    assert q["scale"].shape == (32,)
    deq = q["int8"].astype(np.float32) * q["scale"]
    per_channel_step = np.max(np.abs(w), axis=0) / 127.0
    assert np.all(
        np.max(np.abs(w - deq), axis=0) <= per_channel_step + 1e-7
    )
    # the non-outlier channels specifically stay tight
    others = [j for j in range(32) if j != 7]
    assert np.max(np.abs((w - deq)[:, others])) < 0.05


def test_quantize_params_policy(rng):
    """ndim>=2 floats -> int8 dicts; 1-d floats -> bf16; ints pass."""
    import jax.numpy as jnp

    params = {
        "dense": {
            "kernel": rng.normal(size=(8, 4)).astype(np.float32),
            "bias": np.ones(4, np.float32),
        },
        "steps": np.int32(3),
    }
    qt = quant.quantize_params(params)
    assert quant.is_quantized_leaf(qt["dense"]["kernel"])
    assert qt["dense"]["bias"].dtype == jnp.bfloat16
    assert qt["steps"] == 3
    # bytes shrink: 8x4x4 + 4x4 = 144 fp32 -> 32 int8 + 16 scale + 8 bf16
    assert quant.tree_bytes(qt) < 0.5 * quant.tree_bytes(params)
    deq = quant.dequantize_params(qt)
    assert deq["dense"]["bias"].dtype == jnp.float32
    np.testing.assert_allclose(
        np.asarray(deq["dense"]["kernel"]),
        params["dense"]["kernel"], atol=0.05,
    )


def test_dequantize_inside_jit(rng):
    """The serving contract: dequant runs under jit (tracer-safe) and
    matches the eager dequant bit for bit."""
    import jax

    params = {"k": rng.normal(size=(6, 6)).astype(np.float32),
              "b": rng.normal(size=(6,)).astype(np.float32)}
    qt = quant.quantize_params(params)

    def f(q):
        d = quant.dequantize_params(q)
        return d["k"] @ d["b"]

    eager = np.asarray(f(qt))
    jitted = np.asarray(jax.jit(f)(jax.device_put(qt)))
    np.testing.assert_allclose(eager, jitted, rtol=1e-6)


def test_check_drift_refuses_and_names_paths(rng):
    params = {"layer": {"kernel": rng.normal(size=(8, 8)).astype(np.float32)}}
    qt = quant.quantize_params(params)

    def score(p, batch):
        return 1 / (1 + np.exp(-(batch @ p["layer"]["kernel"]).sum(-1)))

    batches = [rng.normal(size=(4, 8)).astype(np.float32)]
    # generous bound passes and returns the measured drift
    drift = quant.check_drift(score, params, qt, batches, bound=1.0)
    assert 0.0 <= drift < 1.0
    # impossible bound refuses, naming the quantized param path
    with pytest.raises(quant.QuantizationError) as ei:
        quant.check_drift(score, params, qt, batches, bound=1e-15)
    assert "layer/kernel" in str(ei.value)
    assert "quant_drift_bound" in str(ei.value)


# ---------------------------------------------------------------------------
# the registry @int8 entry, end to end


def test_registry_int8_roundtrip_and_drift_bound(
    tmp_path, monkeypatch, corpus, served_model
):
    import jax

    from deepdfa_tpu.obs import ledger as obs_ledger
    from deepdfa_tpu.serve.registry import ModelRegistry, RegistryError

    monkeypatch.setenv("DEEPDFA_TPU_STORAGE", str(tmp_path))
    _, specs, vocabs = corpus
    cfg, model, params = served_model
    cfg = config_mod.apply_overrides(
        cfg, ['run_name="quant-reg"', 'data.dataset="quant-reg"']
    )
    run_dir = _write_run(tmp_path, cfg, params, vocabs, "quant-reg")

    obs_ledger.enable()
    try:
        reg_fp = ModelRegistry(
            run_dir, family="deepdfa", checkpoint="best", cfg=cfg
        )
        reg_q = ModelRegistry(
            run_dir, family="deepdfa", checkpoint="best@int8", cfg=cfg
        )
        # the quantized tree actually serves int8 weights
        leaves = jax.tree.leaves(reg_q.params())
        assert any(
            np.asarray(leaf).dtype == np.int8 for leaf in leaves
        )
        info = reg_q.info()
        assert info["quantized"] == "int8"
        assert info["quant_drift"] <= cfg.serve.quant_drift_bound
        assert info["quant_param_bytes_fraction"] < 0.5
        # the per-entry param-bytes ledger shows the density win,
        # keyed by the @int8 alternate entry tag
        led = obs_ledger.snapshot_or_none()
        tags = led["params"]
        fp_tag = "deepdfa:quant-reg:best"
        q_tag = "deepdfa:quant-reg:best@int8"
        assert tags[q_tag] < 0.5 * tags[fp_tag]
    finally:
        obs_ledger.disable()

    # drift bound vs fp32 through the REAL AOT executables (not just
    # the calibration pass), plus zero steady-state lowerings
    ex_fp = GgnnExecutor(
        reg_fp.model, reg_fp.params,
        node_budget=NODE_BUDGET, edge_budget=EDGE_BUDGET,
        max_batch_graphs=2,
        params_transform=reg_fp.params_transform,
    )
    ex_q = GgnnExecutor(
        reg_q.model, reg_q.params,
        node_budget=NODE_BUDGET, edge_budget=EDGE_BUDGET,
        max_batch_graphs=2,
        params_transform=reg_q.params_transform,
    )
    ex_fp.warmup()
    ex_q.warmup()
    n0 = ex_q.jit_lowerings()
    rows_fp = DynamicBatcher(ex_fp, queue_limit=32).score_all(specs[:8])
    rows_q = DynamicBatcher(ex_q, queue_limit=32).score_all(specs[:8])
    drift = max(
        abs(a.result - b.result) for a, b in zip(rows_fp, rows_q)
    )
    assert drift <= cfg.serve.quant_drift_bound
    assert ex_q.jit_lowerings() == n0

    # an impossible bound is refused LOUDLY with the offending param
    # paths named (CheckpointMismatch style)
    tight = config_mod.apply_overrides(
        cfg, ["serve.quant_drift_bound=1e-15"]
    )
    with pytest.raises(RegistryError) as ei:
        ModelRegistry(
            run_dir, family="deepdfa", checkpoint="best@int8", cfg=tight
        )
    msg = str(ei.value)
    assert "quantization refused" in msg
    assert "params/" in msg  # named param paths


def test_registry_int8_hot_swap_keeps_quantizing(
    tmp_path, monkeypatch, corpus, served_model
):
    """A hot swap on a quantized entry re-quantizes the NEW weights
    (drift re-checked) without recompiling the executables."""
    import jax

    from deepdfa_tpu.serve.registry import ModelRegistry
    from deepdfa_tpu.train.checkpoint import CheckpointManager

    monkeypatch.setenv("DEEPDFA_TPU_STORAGE", str(tmp_path))
    _, specs, vocabs = corpus
    cfg, model, params = served_model
    cfg = config_mod.apply_overrides(
        cfg, ['run_name="quant-swap"', 'data.dataset="quant-swap"']
    )
    run_dir = _write_run(tmp_path, cfg, params, vocabs, "quant-swap")
    reg = ModelRegistry(
        run_dir, family="deepdfa", checkpoint="best@int8", cfg=cfg
    )
    executor = GgnnExecutor(
        reg.model, reg.params,
        node_budget=NODE_BUDGET, edge_budget=EDGE_BUDGET,
        max_batch_graphs=2, params_transform=reg.params_transform,
    )
    executor.warmup()
    n0 = executor.jit_lowerings()
    batcher = DynamicBatcher(
        executor, queue_limit=8, on_batch=reg.maybe_reload
    )
    [r1] = batcher.score_all([specs[0]])
    params2 = jax.tree.map(lambda a: a + 0.05, jax.device_get(params))
    CheckpointManager(run_dir / "checkpoints", monitor="val_loss").save(
        "epoch-0002", params2, {"val_loss": 0.5}, step=2
    )
    [r2] = batcher.score_all([specs[0]])
    assert reg.reloads == 1
    assert r2.result != r1.result  # new (quantized) weights serve
    leaves = jax.tree.leaves(reg.params())
    assert any(np.asarray(leaf).dtype == np.int8 for leaf in leaves)
    assert executor.jit_lowerings() == n0
