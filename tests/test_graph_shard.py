"""Edge-sharded GGNN message passing (parallel/graph_shard.py): parity
with the unsharded model — the graph-dimension analog of sequence
parallelism (SURVEY §2.5b)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepdfa_tpu.core import Config, MeshConfig, config as config_mod
from deepdfa_tpu.graphs import pack
from deepdfa_tpu.models import DeepDFA
from deepdfa_tpu.parallel import edge_sharded_apply, make_mesh

from tests.test_train import synthetic_dataset


@pytest.fixture(scope="module")
def setup():
    graphs = synthetic_dataset(np.random.default_rng(11), n_graphs=12)
    batch = pack(graphs, num_graphs=12, node_budget=256, edge_budget=512)
    model = DeepDFA.from_config(
        config_mod.apply_overrides(Config(), []).model,
        input_dim=24, hidden_dim=8,
    )
    params = model.init(jax.random.key(0), batch)
    return model, params, batch


@pytest.mark.parametrize("n_shards", [2, 4])
def test_edge_sharded_matches_single_device(setup, n_shards):
    model, params, batch = setup
    mesh = make_mesh(
        MeshConfig(dp=n_shards), devices=jax.devices()[:n_shards]
    )
    want = np.asarray(model.apply(params, batch))
    got = np.asarray(
        jax.jit(
            lambda p, b: edge_sharded_apply(model, p, b, mesh)
        )(params, batch)
    )
    np.testing.assert_allclose(got, want, rtol=2e-6, atol=2e-6)


def test_edge_sharded_gradients_match(setup):
    model, params, batch = setup
    mesh = make_mesh(MeshConfig(dp=2), devices=jax.devices()[:2])

    def loss_single(p):
        return jnp.sum(model.apply(p, batch) ** 2)

    def loss_sharded(p):
        return jnp.sum(edge_sharded_apply(model, p, batch, mesh) ** 2)

    g1 = jax.jit(jax.grad(loss_single))(params)
    g2 = jax.jit(jax.grad(loss_sharded))(params)
    for a, b in zip(jax.tree.leaves(g1), jax.tree.leaves(g2)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-5, atol=1e-6
        )


def test_indivisible_edge_budget_rejected(setup):
    model, params, batch = setup
    mesh = make_mesh(MeshConfig(dp=3), devices=jax.devices()[:3])
    graphs = synthetic_dataset(np.random.default_rng(11), n_graphs=12)
    odd = pack(graphs, num_graphs=12, node_budget=256, edge_budget=511)
    with pytest.raises(ValueError, match="not divisible"):
        edge_sharded_apply(model, params, odd, mesh)


def test_plain_params_drive_the_sharded_model(setup):
    """The axis knob adds no parameters: the PLAIN model's init tree is
    what edge_sharded_apply consumes (the parity tests above already
    prove it numerically); clone() must only flip the axis attr."""
    model, params, batch = setup
    sharded = model.clone(edge_axis="dp")
    assert sharded.edge_axis == "dp" and model.edge_axis is None
    assert sharded.hidden_dim == model.hidden_dim
    assert sharded.n_steps == model.n_steps


def test_dataflow_label_styles_rejected(setup):
    """BitvectorPropagation has no cross-shard reduction; silently
    running it on a shard's edge slice produced wrong node states
    (review finding) — must be rejected loudly."""
    model, params, batch = setup
    mesh = make_mesh(MeshConfig(dp=2), devices=jax.devices()[:2])
    df_model = model.clone(label_style="dataflow_solution_in")
    with pytest.raises(ValueError, match="graph/node label styles"):
        edge_sharded_apply(df_model, params, batch, mesh)
