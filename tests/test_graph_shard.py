"""Edge-sharded GGNN message passing (parallel/graph_shard.py): parity
with the unsharded model — the graph-dimension analog of sequence
parallelism (SURVEY §2.5b)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepdfa_tpu.core import Config, MeshConfig, config as config_mod
from deepdfa_tpu.graphs import pack
from deepdfa_tpu.models import DeepDFA
from deepdfa_tpu.parallel import edge_sharded_apply, make_mesh

from tests.test_train import synthetic_dataset


@pytest.fixture(scope="module")
def setup():
    graphs = synthetic_dataset(np.random.default_rng(11), n_graphs=12)
    batch = pack(graphs, num_graphs=12, node_budget=256, edge_budget=512)
    model = DeepDFA.from_config(
        config_mod.apply_overrides(Config(), []).model,
        input_dim=24, hidden_dim=8,
    )
    params = model.init(jax.random.key(0), batch)
    return model, params, batch


@pytest.mark.parametrize("n_shards", [2, 4])
def test_edge_sharded_matches_single_device(setup, n_shards):
    model, params, batch = setup
    mesh = make_mesh(
        MeshConfig(dp=n_shards), devices=jax.devices()[:n_shards]
    )
    want = np.asarray(model.apply(params, batch))
    got = np.asarray(
        jax.jit(
            lambda p, b: edge_sharded_apply(model, p, b, mesh)
        )(params, batch)
    )
    np.testing.assert_allclose(got, want, rtol=2e-6, atol=2e-6)


def test_edge_sharded_gradients_match(setup):
    model, params, batch = setup
    mesh = make_mesh(MeshConfig(dp=2), devices=jax.devices()[:2])

    def loss_single(p):
        return jnp.sum(model.apply(p, batch) ** 2)

    def loss_sharded(p):
        return jnp.sum(edge_sharded_apply(model, p, batch, mesh) ** 2)

    g1 = jax.jit(jax.grad(loss_single))(params)
    g2 = jax.jit(jax.grad(loss_sharded))(params)
    for a, b in zip(jax.tree.leaves(g1), jax.tree.leaves(g2)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-5, atol=1e-6
        )


def test_indivisible_edge_budget_rejected(setup):
    model, params, batch = setup
    mesh = make_mesh(MeshConfig(dp=3), devices=jax.devices()[:3])
    graphs = synthetic_dataset(np.random.default_rng(11), n_graphs=12)
    odd = pack(graphs, num_graphs=12, node_budget=256, edge_budget=511)
    with pytest.raises(ValueError, match="not divisible"):
        edge_sharded_apply(model, params, odd, mesh)


def test_plain_params_drive_the_sharded_model(setup):
    """The axis knob adds no parameters: the PLAIN model's init tree is
    what edge_sharded_apply consumes (the parity tests above already
    prove it numerically); clone() must only flip the axis attr."""
    model, params, batch = setup
    sharded = model.clone(edge_axis="dp")
    assert sharded.edge_axis == "dp" and model.edge_axis is None
    assert sharded.hidden_dim == model.hidden_dim
    assert sharded.n_steps == model.n_steps


@pytest.fixture(scope="module")
def dataflow_setup():
    """dataflow_solution model + bit-labeled batch (exercises the
    bitvector fixpoint's cross-shard union, nn/bitprop.py)."""
    from deepdfa_tpu.data import build_dataset, generate, to_examples

    synth = generate(10, vuln_rate=0.3, seed=5)
    specs, _ = build_dataset(
        to_examples(synth), train_ids=range(10), limit_all=32,
        limit_subkeys=32, max_defs=8,
    )
    batch = pack(
        specs, num_graphs=len(specs), node_budget=512, edge_budget=1024
    )
    model = DeepDFA.from_config(
        config_mod.apply_overrides(
            Config(), ["model.label_style=dataflow_solution_in"]
        ).model,
        input_dim=34, hidden_dim=8,
    )
    params = model.init(jax.random.key(1), batch)
    return model, params, batch


def test_dataflow_label_style_parity(dataflow_setup):
    """The bitvector reaching-definitions fixpoint is also axis-aware
    (per-shard partial IN sets combine through the union monoid, psum'd
    in transformed space) — edge-sharded apply must equal the unsharded
    one for the dataflow_solution label styles too (an earlier version
    silently ran on each shard's edge slice; review repro: 0.219 max
    error)."""
    model, params, batch = dataflow_setup
    mesh = make_mesh(MeshConfig(dp=2), devices=jax.devices()[:2])
    want = np.asarray(model.apply(params, batch))
    got = np.asarray(
        jax.jit(
            lambda p, b: edge_sharded_apply(model, p, b, mesh)
        )(params, batch)
    )
    np.testing.assert_allclose(got, want, rtol=2e-6, atol=2e-6)


def test_dataflow_label_style_gradient_parity(dataflow_setup):
    """The dataflow styles exist to be TRAINED (learned_gate): gradients
    through the clip + transformed-space psum must match the unsharded
    backward."""
    model, params, batch = dataflow_setup
    mesh = make_mesh(MeshConfig(dp=2), devices=jax.devices()[:2])

    def loss_single(p):
        return jnp.sum(model.apply(p, batch) ** 2)

    def loss_sharded(p):
        return jnp.sum(edge_sharded_apply(model, p, batch, mesh) ** 2)

    g1 = jax.jit(jax.grad(loss_single))(params)
    g2 = jax.jit(jax.grad(loss_sharded))(params)
    for a, b in zip(jax.tree.leaves(g1), jax.tree.leaves(g2)):
        # the log/exp + psum reassociation perturbs the last float bits
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-4, atol=5e-6
        )
