"""End-to-end training tests on synthetic graphs (8-device CPU mesh)."""

import numpy as np
import pytest

from deepdfa_tpu.core import Config, MeshConfig, config as config_mod
from deepdfa_tpu.graphs import GraphSpec, pack_shards
from deepdfa_tpu.models import DeepDFA
from deepdfa_tpu.parallel import make_mesh
from deepdfa_tpu.train import (
    BinaryClassificationMetrics,
    GraphTrainer,
    positive_weight,
    undersample_epoch,
)

# heavy compiles / subprocesses: excluded from the default fast lane
# (pyproject addopts); run via `pytest -m slow` or `pytest -m ""`
pytestmark = pytest.mark.slow


def synthetic_dataset(rng, n_graphs=64, vocab=20):
    """Graphs whose label = presence of feature token 7 on any node."""
    graphs = []
    for gid in range(n_graphs):
        n = int(rng.integers(4, 16))
        feats = rng.integers(2, vocab, (n, 4)).astype(np.int32)
        vuln = np.zeros((n,), np.int32)
        if gid % 2 == 0:
            k = int(rng.integers(0, n))
            feats[k, 0] = 7
            vuln[k] = 1
        src = np.arange(n - 1, dtype=np.int32)
        dst = src + 1
        graphs.append(
            GraphSpec(
                graph_id=gid,
                node_feats=feats,
                node_vuln=vuln,
                edge_src=src,
                edge_dst=dst,
                label=float(vuln.max()),
            )
        )
    return graphs


@pytest.fixture(scope="module")
def dataset():
    return synthetic_dataset(np.random.default_rng(42))


def _batches(graphs, mesh_dp, epoch=0):
    return [
        pack_shards(
            graphs,
            num_shards=mesh_dp,
            num_graphs=max(1, len(graphs) // mesh_dp),
            node_budget=256,
            edge_budget=1024,
        )
    ]


def test_train_learns_synthetic_signal(dataset):
    cfg = config_mod.apply_overrides(
        Config(),
        ["model.hidden_dim=8", "train.max_epochs=30", "train.optim.learning_rate=0.01"],
    )
    mesh = make_mesh(MeshConfig(dp=8), devices=None)
    model = DeepDFA.from_config(cfg.model, input_dim=32)
    trainer = GraphTrainer(model, cfg, mesh=mesh)

    batch = _batches(dataset, 8)[0]
    state = trainer.init_state(batch)
    state = trainer.fit(state, lambda epoch: _batches(dataset, 8, epoch))
    metrics, _ = trainer.evaluate(state, _batches(dataset, 8))
    assert metrics["f1"] > 0.9, metrics
    assert metrics["loss"] < 0.3, metrics


def test_eval_covers_every_graph_including_over_budget(dataset):
    """VERDICT round-1 item: eval must never silently drop examples. A graph
    over the per-shard budgets rides a pow2 overflow batch and is scored."""
    from deepdfa_tpu.graphs import shard_bucket_batches

    rng = np.random.default_rng(3)
    big_n = 600  # > node_budget=256
    feats = rng.integers(2, 20, (big_n, 4)).astype(np.int32)
    feats[0, 0] = 7
    big = GraphSpec(
        graph_id=999,
        node_feats=feats,
        node_vuln=np.zeros((big_n,), np.int32),
        edge_src=np.arange(big_n - 1, dtype=np.int32),
        edge_dst=np.arange(1, big_n, dtype=np.int32),
        label=1.0,
    )
    graphs = list(dataset) + [big]
    cfg = config_mod.apply_overrides(Config(), ["model.hidden_dim=8"])
    mesh = make_mesh(MeshConfig(dp=8), devices=None)
    model = DeepDFA.from_config(cfg.model, input_dim=32)
    trainer = GraphTrainer(model, cfg, mesh=mesh)

    stats: dict = {}
    batches = list(
        shard_bucket_batches(
            graphs, num_shards=8, num_graphs=8, node_budget=256,
            edge_budget=1024, oversized="singleton", stats=stats,
        )
    )
    assert stats["oversized"] == 1
    ids = [
        i for b in batches for i in np.asarray(b.graph_ids).flatten().tolist()
        if i >= 0
    ]
    assert sorted(ids) == sorted(g.graph_id for g in graphs)
    state = trainer.init_state(batches[0])
    metrics, m = trainer.evaluate(state, batches)
    assert m.count == len(graphs), (m.count, len(graphs))
    assert np.isfinite(metrics["loss"])


def test_dp_matches_single_device(dataset):
    """Grad psum over 8 shards must reproduce the 1-shard result."""
    import jax

    # sgd: parity must hold bit-tight; adamw's m/sqrt(v) normalization
    # amplifies float32 summation-order noise on near-zero first grads
    cfg = config_mod.apply_overrides(
        Config(), ["model.hidden_dim=8", "train.optim.name=sgd"]
    )
    model = DeepDFA.from_config(cfg.model, input_dim=32)

    mesh8 = make_mesh(MeshConfig(dp=8))
    mesh1 = make_mesh(MeshConfig(dp=1), devices=jax.devices()[:1])

    t8 = GraphTrainer(model, cfg, mesh=mesh8)
    t1 = GraphTrainer(model, cfg, mesh=mesh1)

    b8 = pack_shards(dataset, 8, num_graphs=8, node_budget=128, edge_budget=512)
    b1 = pack_shards(dataset, 1, num_graphs=64, node_budget=1024, edge_budget=4096)

    s8 = t8.init_state(b8, seed=0)
    s1 = t1.init_state(b1, seed=0)
    chex = pytest.importorskip("chex")
    chex.assert_trees_all_close(
        jax.device_get(s8.params), jax.device_get(s1.params), rtol=1e-6
    )

    for _ in range(3):
        s8, loss8 = t8.train_step(s8, b8)
        s1, loss1 = t1.train_step(s1, b1)

    np.testing.assert_allclose(
        float(jax.device_get(loss8)), float(jax.device_get(loss1)), rtol=2e-4
    )
    chex.assert_trees_all_close(
        jax.device_get(s8.params), jax.device_get(s1.params), rtol=5e-4, atol=1e-6
    )


def test_dp_unequal_shards_match_single_device(dataset):
    """Exact sum/count psum: global mean is right even when shard graph
    counts differ (65 graphs over 8 shards)."""
    import jax

    cfg = config_mod.apply_overrides(
        Config(), ["model.hidden_dim=8", "train.optim.name=sgd"]
    )
    model = DeepDFA.from_config(cfg.model, input_dim=32)
    uneven = dataset + [dataset[0]]  # 65 graphs
    t8 = GraphTrainer(model, cfg, mesh=make_mesh(MeshConfig(dp=8)))
    t1 = GraphTrainer(model, cfg, mesh=make_mesh(MeshConfig(dp=1), devices=jax.devices()[:1]))
    b8 = pack_shards(uneven, 8, num_graphs=9, node_budget=160, edge_budget=640)
    b1 = pack_shards(uneven, 1, num_graphs=65, node_budget=1280, edge_budget=5120)
    s8 = t8.init_state(b8, seed=0)
    s1 = t1.init_state(b1, seed=0)
    s8, loss8 = t8.train_step(s8, b8)
    s1, loss1 = t1.train_step(s1, b1)
    np.testing.assert_allclose(
        float(jax.device_get(loss8)), float(jax.device_get(loss1)), rtol=2e-4
    )
    chex = pytest.importorskip("chex")
    chex.assert_trees_all_close(
        jax.device_get(s8.params), jax.device_get(s1.params), rtol=5e-4, atol=1e-6
    )


def test_graph_label_fallback_when_no_node_labels(rng):
    """Graph-only-labeled dataset (node_vuln all zero, label=1) trains as
    positive via the stored graph_label."""
    import jax

    from deepdfa_tpu.graphs import pack
    from deepdfa_tpu.train import graph_labels

    g = GraphSpec(
        graph_id=0,
        node_feats=rng.integers(0, 10, (6, 4)).astype(np.int32),
        node_vuln=np.zeros((6,), np.int32),
        edge_src=np.array([0, 1], np.int32),
        edge_dst=np.array([1, 2], np.int32),
        label=1.0,
    )
    b = pack([g], num_graphs=2, node_budget=16, edge_budget=64)
    labels = np.asarray(graph_labels(b))
    assert labels[0] == 1.0
    assert labels[1] == 0.0


def test_undersampler_balance():
    labels = np.array([1] * 10 + [0] * 90)
    idx = undersample_epoch(labels, epoch=0, seed=0)
    assert len(idx) == 20
    assert labels[idx].sum() == 10
    idx2 = undersample_epoch(labels, epoch=1, seed=0)
    assert sorted(idx) != sorted(idx2)  # fresh negatives each epoch
    assert positive_weight(labels) == 9.0


def test_metrics_basic():
    m = BinaryClassificationMetrics()
    m.update([0.9, 0.1, 0.8, 0.4], [1, 0, 0, 1], [True, True, True, True])
    c = m.compute()
    assert c["acc"] == 0.5
    assert m.confusion_matrix().tolist() == [[1, 1], [1, 1]]
    # masked slots are excluded
    m2 = BinaryClassificationMetrics()
    m2.update([0.9, 0.9], [1, 0], [True, False])
    assert m2.count == 1


def test_checkpoint_best_selection(tmp_path, dataset):
    import jax

    from deepdfa_tpu.train import CheckpointManager

    cfg = config_mod.apply_overrides(Config(), ["model.hidden_dim=8"])
    model = DeepDFA.from_config(cfg.model, input_dim=32)
    mesh = make_mesh(MeshConfig(dp=8))
    trainer = GraphTrainer(model, cfg, mesh=mesh)
    batch = _batches(dataset, 8)[0]
    state = trainer.init_state(batch)

    mgr = CheckpointManager(tmp_path / "ckpt", monitor="val_loss", mode="min")
    params = jax.device_get(state.params)
    assert mgr.save("epoch-0", params, {"val_loss": 1.0}, step=0)
    assert not mgr.save("epoch-1", params, {"val_loss": 2.0}, step=1)
    assert mgr.save("epoch-2", params, {"val_loss": 0.5}, step=2)
    assert mgr.best_metrics()["val_loss"] == 0.5
    restored = mgr.restore("best", params)
    chex = pytest.importorskip("chex")
    chex.assert_trees_all_close(restored, params)
