"""CombinedTrainer signature-keyed step cache (ISSUE 2): bounded LRU,
ahead-of-time warmup over the configured bucket signatures, and the
zero-steady-state-recompiles invariant guarded by a jit-lowering
counter."""

import dataclasses

import numpy as np
import pytest

from deepdfa_tpu.core import Config, MeshConfig, config as config_mod
from deepdfa_tpu.data.text import (
    bucketed_collate_batches,
    collate_shards,
    rows_for_bucket,
    token_lengths,
)
from deepdfa_tpu.models import combined as cmb
from deepdfa_tpu.models.transformer import TransformerConfig
from deepdfa_tpu.parallel import make_mesh
from deepdfa_tpu.train.combined_loop import CombinedTrainer

from tests.test_text_bucketing import make_rows, make_spec

# trainer compiles are heavy on CPU: excluded from the default fast lane
# (as tests/test_combined.py); run via `pytest -m slow` or `pytest -m ""`
pytestmark = pytest.mark.slow

PAD = 1
NODE_BUDGET, EDGE_BUDGET = 256, 1024


def _model_cfg():
    return cmb.CombinedConfig(
        encoder=TransformerConfig.tiny(
            dropout_rate=0.0, max_position_embeddings=72
        ),
        graph_hidden_dim=8,
        graph_input_dim=6,
    )


def _trainer(overrides=(), dp=8, **cfg_kw):
    cfg = config_mod.apply_overrides(Config(), list(overrides))
    if cfg_kw:
        cfg = dataclasses.replace(
            cfg, data=dataclasses.replace(cfg.data, **cfg_kw)
        )
    cfg = dataclasses.replace(
        cfg,
        data=dataclasses.replace(
            cfg.data,
            batch=dataclasses.replace(
                cfg.data.batch,
                node_budget=NODE_BUDGET,
                edge_budget=EDGE_BUDGET,
            ),
        ),
    )
    mesh = make_mesh(MeshConfig(dp=dp))
    trainer = CombinedTrainer(cfg, _model_cfg(), mesh=mesh, total_steps=8)
    return trainer, trainer.init_state(seed=0)


def _corpus(rng, n=48, max_t=64):
    rows, lengths = make_rows(rng, n, max_t, PAD)
    token_ids = {i: rows[i] for i in range(n)}
    labels = {i: int(i % 2) for i in range(n)}
    graphs = {i: make_spec(rng, i) for i in range(n) if i % 3}
    return token_ids, labels, graphs, lengths


def test_warmup_compiles_exactly_bucket_signatures(rng):
    buckets, budget = (16, 32), 256
    trainer, state = _trainer()
    report = trainer.warmup(
        state, buckets, budget, NODE_BUDGET, EDGE_BUDGET
    )
    assert len(report) == len(buckets)
    assert trainer.jit_lowerings() == len(buckets)
    assert len(trainer._step_cache) == len(buckets)
    for T in buckets:
        rows = rows_for_bucket(T, budget, 8)
        sig = f"T{T}xR{rows}xG{rows}"
        assert trainer.signature_stats[sig]["compiles"] == 1
        assert trainer.signature_stats[sig]["compile_seconds"] > 0
    # idempotent: a second warmup never recompiles
    assert trainer.warmup(
        state, buckets, budget, NODE_BUDGET, EDGE_BUDGET
    ) == {}
    assert trainer.jit_lowerings() == len(buckets)


def test_warmup_rejects_overflowing_bucket_set(rng):
    trainer, state = _trainer(["train.step_cache_entries=2"])
    with pytest.raises(ValueError, match="step_cache_entries"):
        trainer.warmup(state, (8, 16, 32), 256, NODE_BUDGET, EDGE_BUDGET)


def test_zero_steady_state_recompiles_full_epoch(rng):
    """Acceptance (ISSUE 2): with data.seq_buckets configured, fit()
    warmups before step 1 and one full epoch over the synthetic corpus
    triggers ZERO new jit lowerings."""
    buckets, budget = (16, 32, 64), 512
    trainer, state = _trainer(
        ["train.max_epochs=1"], seq_buckets=buckets, token_budget=budget
    )
    token_ids, labels, graphs, lengths = _corpus(rng)
    batches = list(
        bucketed_collate_batches(
            token_ids, labels, list(range(len(token_ids))), graphs,
            buckets, budget, 8, NODE_BUDGET, EDGE_BUDGET, pad_id=PAD,
            lengths=lengths,
        )
    )
    assert len({b.input_ids.shape for b in batches}) > 1, (
        "corpus must exercise several signatures"
    )
    records = []
    state = trainer.fit(
        state, lambda epoch: batches,
        log_fn=lambda r: records.append(r) if "epoch" in r else None,
    )
    assert trainer.jit_lowerings() == len(buckets)
    assert sum(
        s["compiles"] for s in trainer.signature_stats.values()
    ) == len(buckets)
    # epoch record surfaces the bucketing observables
    rec = records[-1]
    assert rec["jit_lowerings"] == len(buckets)
    assert rec["real_tokens"] == int(np.asarray(lengths).sum())
    assert 0.0 <= rec["padding_waste"] < 1.0
    assert rec["train_tokens_per_sec"] > 0
    assert set(rec["step_signatures"]) == set(trainer.signature_stats)


def test_step_cache_lru_eviction_and_recompile_counting(rng):
    trainer, state = _trainer(["train.step_cache_entries=2"])
    token_ids, labels, graphs, lengths = _corpus(rng, n=24, max_t=32)

    import jax

    def batch_at(T, rows):
        ids = list(range(rows * 8))
        mat = np.stack([token_ids[i][:T] for i in ids])
        return collate_shards(
            mat, [labels[i] for i in ids], ids, graphs, num_shards=8,
            rows_per_shard=rows, node_budget=NODE_BUDGET,
            edge_budget=EDGE_BUDGET, pad_id=PAD,
        )

    key = jax.random.key(0)
    sigs = [(8, 1), (16, 1), (32, 1)]
    for T, rows in sigs:
        state, _ = trainer.train_step(
            state, trainer.place_batch(batch_at(T, rows)), key
        )
    # bound of 2: the (8, 1, ...) entry — least recently used — evicted
    assert len(trainer._step_cache) == 2
    assert (8, 1, 1) not in trainer._step_cache
    assert (32, 1, 1) in trainer._step_cache
    lowerings = trainer.jit_lowerings()
    assert trainer.signature_stats["T8xR1xG1"]["compiles"] == 1

    # touching the evicted signature recompiles it (counted), and the
    # monotonic lowering counter keeps the evicted entry's history
    state, _ = trainer.train_step(
        state, trainer.place_batch(batch_at(8, 1)), key
    )
    assert trainer.signature_stats["T8xR1xG1"]["compiles"] == 2
    assert trainer.jit_lowerings() > lowerings
    assert len(trainer._step_cache) == 2
    # hit counters accumulate across the eviction
    assert trainer.signature_stats["T8xR1xG1"]["train_steps"] == 2


def test_evaluate_over_bucketed_batches(rng):
    buckets, budget = (16, 32), 256
    trainer, state = _trainer(seq_buckets=buckets, token_budget=budget)
    token_ids, labels, graphs, lengths = _corpus(rng, n=24, max_t=32)
    batches = list(
        bucketed_collate_batches(
            token_ids, labels, list(range(24)), graphs, buckets, budget,
            8, NODE_BUDGET, EDGE_BUDGET, pad_id=PAD, lengths=lengths,
        )
    )
    metrics, _ = trainer.evaluate(state, batches)
    assert np.isfinite(metrics["loss"])
    assert sum(
        s["eval_steps"] for s in trainer.signature_stats.values()
    ) == len(batches)
