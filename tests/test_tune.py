"""Ledger-driven autotuner (deepdfa_tpu/tune/, docs/tuning.md).

The load-bearing invariants:

- candidate enumeration prunes illegal layouts (divisibility, sublane
  alignment, the VMEM working-set bound) BEFORE any compile;
- the numerics-contract verdict rides on every candidate row and a
  broken candidate can never win, no matter how fast it timed;
- the ladder DP beats the pow2 baseline on a skewed distribution and
  always keeps the capacity rung;
- tuned.json round-trips, validates, and any hardware-key mismatch
  falls back to defaults LOUDLY;
- a tuned warmup ladder keeps the serving contracts: zero steady-state
  recompiles and batched-vs-singleton bit-parity (on the tier-1
  8-virtual-device CPU mesh, like every serve test).
"""

import json
import logging

import numpy as np
import pytest

from deepdfa_tpu.core import Config, config as config_mod
from deepdfa_tpu.tune import cache as tune_cache
from deepdfa_tpu.tune import kernel as tune_kernel
from deepdfa_tpu.tune import ladder as tune_ladder

from conftest import run_cli  # noqa: E402

NODE_BUDGET, EDGE_BUDGET = 2048, 8192


# ---------------------------------------------------------------------------
# candidate enumeration


def test_enumerate_candidates_divisibility_and_vmem():
    cands, pruned = tune_kernel.enumerate_candidates(
        256, 512, 32, block_nodes=(48, 64, 256), block_edges=(128, 512),
        scatters=("fold",),
    )
    assert cands, "legal layouts must survive"
    for c in cands:
        assert 256 % c.block_n == 0 and 512 % c.block_e == 0
    # 48 does not divide 256: pruned with the reason named
    labels = {c.label for c in cands}
    assert not any(c.block_n == 48 for c in cands)
    assert any(
        "does not divide" in p["reason"] for p in pruned
    ), pruned
    # a starvation-level VMEM limit prunes EVERYTHING, each row naming
    # the estimate that ruled it out
    cands2, pruned2 = tune_kernel.enumerate_candidates(
        256, 512, 32, block_nodes=(64, 256), block_edges=(128, 512),
        scatters=("fold",), vmem_limit_bytes=1024,
    )
    assert not cands2
    assert all("VMEM estimate" in p["reason"] for p in pruned2)
    # the mxu one-hot block costs VMEM the fold body doesn't
    c_fold = tune_kernel.Candidate(256, 512, "fold")
    c_mxu = tune_kernel.Candidate(256, 512, "mxu")
    assert tune_kernel.estimate_vmem_bytes(
        256, 512, 32, c_mxu
    ) > tune_kernel.estimate_vmem_bytes(256, 512, 32, c_fold)
    assert labels  # sanity: non-empty survivor set exercised above


def test_enumerate_unroll_axis_and_fused_residency_prune():
    """The PR-16 axes: enumeration carries unroll (and int8) rows, and
    a fused candidate whose state-chain residency cannot fit VMEM is
    pruned BEFORE compile with the reason naming the residency term
    and the step count — while its per-step twin survives."""
    cands, pruned = tune_kernel.enumerate_candidates(
        256, 512, 32, block_nodes=(256,), block_edges=(512,),
        scatters=("fold",), accums=("fp32", "int8"),
        unrolls=("per_step", "fused"), n_steps=5,
    )
    by_label = {c.label for c in cands}
    assert "bn256-be512-fold-fp32" in by_label
    assert "bn256-be512-fold-fp32-fused" in by_label
    assert "bn256-be512-fold-int8" in by_label
    # labels only grow a suffix off the per_step default: committed
    # pre-PR-16 rows keep naming the layout they always named
    for c in cands:
        assert c.label.endswith("-fused") == (c.unroll == "fused")
        assert c.as_dict()["unroll"] == c.unroll
    # a budget that fits the per-step working set but not the fused
    # n_steps residency prunes ONLY the fused rows, reason named
    per_step_need = tune_kernel.estimate_vmem_bytes(
        256, 512, 32, tune_kernel.Candidate(256, 512), n_steps=5
    )
    fused_need = tune_kernel.estimate_vmem_bytes(
        256, 512, 32,
        tune_kernel.Candidate(256, 512, "fold", "fp32", "fused"),
        n_steps=5,
    )
    assert fused_need > per_step_need
    tight = (per_step_need + fused_need) // 2
    cands2, pruned2 = tune_kernel.enumerate_candidates(
        256, 512, 32, block_nodes=(256,), block_edges=(512,),
        scatters=("fold",), accums=("fp32",),
        unrolls=("per_step", "fused"), n_steps=5,
        vmem_limit_bytes=tight,
    )
    assert [c.unroll for c in cands2] == ["per_step"]
    assert len(pruned2) == 1
    assert "fused unroll residency" in pruned2[0]["reason"]
    assert "VMEM estimate" in pruned2[0]["reason"]
    assert "5 steps" in pruned2[0]["reason"]


def test_search_kernel_carries_unroll_axis_and_verdicts():
    """A real reduced search over the new axes: every row carries its
    unroll value and numerics verdict, fused fp32 is bit-identical
    (fold), int8 lands inside its bound, and the winner row names its
    unroll mode for kernel_layout_from."""
    out = tune_kernel.search_kernel(
        [(128, 256, 8)], n_steps=2,
        candidates=[
            tune_kernel.Candidate(128, 256),
            tune_kernel.Candidate(128, 256, "fold", "fp32", "fused"),
            tune_kernel.Candidate(128, 256, "fold", "int8"),
        ],
        reps=1,
    )
    rec = out["128x256x8"]
    rows = {r["candidate"]: r for r in rec["candidates"]}
    assert set(rows) == {
        "bn128-be256-fold-fp32",
        "bn128-be256-fold-fp32-fused",
        "bn128-be256-fold-int8",
    }
    for row in rows.values():
        assert row["unroll"] in ("per_step", "fused")
        assert isinstance(row["numerics"]["ok"], bool)
    fused = rows["bn128-be256-fold-fp32-fused"]
    assert fused["numerics"]["ok"] and fused["numerics"]["rel_err"] == 0.0
    int8 = rows["bn128-be256-fold-int8"]
    assert int8["numerics"]["ok"]
    assert int8["numerics"]["rel_err"] <= tune_kernel.INT8_TOLERANCE
    assert rec["winner_unroll"] in ("per_step", "fused")
    assert rec["winner"] == rows[rec["winner"]]["candidate"]


def test_sublane_alignment_pruned():
    _, pruned = tune_kernel.enumerate_candidates(
        # 4 divides both budgets but is below the f32 sublane tile
        256, 512, 32, block_nodes=(4,), block_edges=(128,),
        scatters=("fold",),
    )
    assert any("sublane" in p["reason"] for p in pruned)


# ---------------------------------------------------------------------------
# numerics contract


def test_numerics_verdict_rejects_broken_candidate():
    ref = np.linspace(-1, 1, 64, dtype=np.float32).reshape(8, 8)
    fold = tune_kernel.Candidate(64, 128, "fold", "fp32")
    ok = tune_kernel.numerics_verdict(ref.copy(), ref, fold)
    assert ok["ok"] and ok["rel_err"] == 0.0 and ok["tolerance"] == 0.0
    # fold/fp32 is a BIT-IDENTITY contract: one flipped value rejects
    broken = ref.copy()
    broken[3, 3] += 1e-6
    bad = tune_kernel.numerics_verdict(broken, ref, fold)
    assert not bad["ok"] and bad["rel_err"] > 0.0
    # bf16 rides the documented 5e-2 policy bound, not bit-identity
    bf16 = tune_kernel.Candidate(64, 128, "mxu", "bf16")
    assert tune_kernel.numerics_verdict(broken, ref, bf16)["ok"]
    assert not tune_kernel.numerics_verdict(ref + 1.0, ref, bf16)["ok"]


def test_search_excludes_numerics_rejected_winner(monkeypatch):
    """A deliberately broken candidate (verdict forced to fail) can
    never win, even when it times fastest; its row still carries the
    failed verdict — the tuned.json audit trail."""
    broken = tune_kernel.Candidate(64, 512)
    real_verdict = tune_kernel.numerics_verdict

    def rigged(got, ref, cand, tolerances=None):
        v = real_verdict(got, ref, cand, tolerances=tolerances)
        if cand == broken:
            v = {**v, "ok": False, "rel_err": 1.0}
        return v

    monkeypatch.setattr(tune_kernel, "numerics_verdict", rigged)
    out = tune_kernel.search_kernel(
        [(128, 256, 8)], n_steps=1,
        candidates=[broken, tune_kernel.Candidate(128, 256)],
        reps=1,
    )
    rec = out["128x256x8"]
    assert rec["winner"] == "bn128-be256-fold-fp32"
    rows = {r["candidate"]: r for r in rec["candidates"]}
    assert rows[broken.label]["numerics"]["ok"] is False
    assert rows[broken.label].get("step_us") is not None


# ---------------------------------------------------------------------------
# ladder fitting


def test_fit_rungs_beats_pow2_on_skewed_distribution():
    sizes = [5] * 50 + [9] * 30 + [3] * 10 + [16] * 5
    rungs = tune_ladder.fit_rungs(sizes, max_rungs=4, capacity=16)
    assert rungs[-1] == 16  # capacity always the top rung
    assert list(rungs) == sorted(set(rungs))
    fitted = tune_ladder.padding_waste(sizes, rungs)
    pow2 = tune_ladder.padding_waste(
        sizes, tune_ladder.pow2_rungs(16)
    )
    assert fitted < pow2
    assert fitted == 0.0  # 4 rungs cover the 4 distinct sizes exactly
    # every size still maps to a rung >= it
    for s in set(sizes):
        assert tune_ladder.rung_for(s, rungs) >= s
    # tighter budgets trade waste for compiles, monotonically
    w3 = tune_ladder.padding_waste(
        sizes, tune_ladder.fit_rungs(sizes, 3, 16)
    )
    w2 = tune_ladder.padding_waste(
        sizes, tune_ladder.fit_rungs(sizes, 2, 16)
    )
    assert 0.0 <= w3 <= w2 < pow2 + 1e-9


def test_fit_rungs_guards():
    with pytest.raises(ValueError):
        tune_ladder.fit_rungs([32], max_rungs=2, capacity=16)
    assert tune_ladder.fit_rungs([], 4, 8) == (8,)
    # the compile budget caps the ladder length
    assert tune_ladder.max_rungs_for_budget(10.0, 3.0, 6) == 3
    assert tune_ladder.max_rungs_for_budget(0.0, 3.0, 6) == 6
    assert tune_ladder.max_rungs_for_budget(1.0, 3.0, 6) == 1


def test_batch_size_replay_reconstructs_batches(tmp_path):
    """Request entries carry their batch's size; the replay divides per
    size so a batch of 4 doesn't count 4x — and non-request lines are
    ignored."""
    log = tmp_path / "serve_log.jsonl"
    entries = (
        [{"request": {"id": f"a{i}", "batch_size": 4}} for i in range(8)]
        + [{"request": {"id": "b", "batch_size": 1}}]
        + [{"serve_slo": {"60s": {}}}, {"not": "json-request"}]
    )
    log.write_text("\n".join(json.dumps(e) for e in entries))
    sizes = tune_ladder.batch_sizes_from_log(log)
    assert sorted(sizes) == [1, 4, 4]


def test_lengths_from_manifest(tmp_path):
    arr = tmp_path / "lengths.json"
    arr.write_text("[4, 9, 12]")
    assert tune_ladder.lengths_from_manifest(arr) == [4, 9, 12]
    jl = tmp_path / "manifest.jsonl"
    jl.write_text(
        '{"length": 7}\n{"tokens": 3}\n{"other": 1}\n5\n'
    )
    assert tune_ladder.lengths_from_manifest(jl) == [7, 3, 5]


# ---------------------------------------------------------------------------
# tuned.json cache


def _fake_record(hw, waste=0.1, step_us=100.0):
    return tune_cache.make_record(
        hw,
        kernel={
            "2048x8192x32": {
                "winner": "bn256-be512-fold-fp32",
                "winner_step_us": step_us,
                "winner_block_n": 256,
                "winner_block_e": 512,
                "winner_scatter": "fold",
                "winner_accum": "fp32",
                "candidates": [{
                    "candidate": "bn256-be512-fold-fp32",
                    "step_us": step_us,
                    "numerics": {"ok": True, "rel_err": 0.0},
                }],
            }
        },
        ladders={
            "serve": {
                "rungs": [1, 3, 4], "pow2_rungs": [1, 2, 4],
                "padding_waste": waste, "pow2_padding_waste": 0.3,
                "samples": 10,
            },
        },
        search_seconds=1.5,
    )


def test_tuned_roundtrip_and_hw_mismatch_falls_back_loudly(
    tmp_path, caplog
):
    hw = tune_cache.hardware_key(NODE_BUDGET, EDGE_BUDGET)
    doc = tune_cache.upsert_record(
        tune_cache.empty_doc(), _fake_record(hw)
    )
    path = tmp_path / "tuned.json"
    tune_cache.save_tuned(path, doc)
    loaded = tune_cache.load_tuned(path)
    assert tune_cache.validate_tuned(loaded)["ok"]
    assert tune_cache.find_record(loaded, hw) is not None
    # matching key: the consumers read the tuned layout
    cfg = config_mod.apply_overrides(Config(), [
        "tune.enabled=true", f"tune.path={json.dumps(str(path))}",
        f'data.batch={{"node_budget": {NODE_BUDGET}, '
        f'"edge_budget": {EDGE_BUDGET}}}',
    ])
    rec = tune_cache.record_for_config(cfg, NODE_BUDGET, EDGE_BUDGET)
    assert rec is not None
    assert tune_cache.serve_rungs_from(rec, 4) == (1, 3, 4)
    # hardware-key mismatch (different budgets): LOUD fallback to None
    caplog.clear()
    with caplog.at_level(logging.WARNING, logger="deepdfa_tpu.tune.cache"):
        rec2 = tune_cache.record_for_config(cfg, 64, 128)
    assert rec2 is None
    assert any(
        "no tuned record matches" in r.message for r in caplog.records
    )
    # missing file: equally loud
    cfg_missing = config_mod.apply_overrides(cfg, [
        f"tune.path={json.dumps(str(tmp_path / 'absent.json'))}",
    ])
    caplog.clear()
    with caplog.at_level(logging.WARNING, logger="deepdfa_tpu.tune.cache"):
        assert tune_cache.record_for_config(
            cfg_missing, NODE_BUDGET, EDGE_BUDGET
        ) is None
    assert any(
        "no usable tuned.json" in r.message for r in caplog.records
    )


def test_serve_rungs_capacity_drift_falls_back_loudly(caplog):
    """A ladder fitted at one capacity clamped to a smaller one would
    LOSE the small rungs the pow2 default keeps — capacity drift must
    fall back to defaults, loudly, never degrade silently."""
    hw = tune_cache.hardware_key(NODE_BUDGET, EDGE_BUDGET)
    rec = tune_cache.make_record(hw, ladders={
        "serve": {
            "rungs": [3, 5, 9, 16, 32], "pow2_rungs": [1, 2, 4, 32],
            "padding_waste": 0.05, "pow2_padding_waste": 0.2,
            "samples": 40, "capacity": 32,
        },
    }, search_seconds=1.0)
    assert tune_cache.serve_rungs_from(rec, 32) == (3, 5, 9, 16, 32)
    with caplog.at_level(logging.WARNING, logger="deepdfa_tpu.tune.cache"):
        assert tune_cache.serve_rungs_from(rec, 4) is None
    assert any(
        "fitted at capacity" in r.message for r in caplog.records
    )


def test_upsert_replaces_same_hardware_key(tmp_path):
    hw = tune_cache.hardware_key(NODE_BUDGET, EDGE_BUDGET)
    doc = tune_cache.upsert_record(
        tune_cache.empty_doc(), _fake_record(hw, step_us=100.0)
    )
    doc = tune_cache.upsert_record(doc, _fake_record(hw, step_us=90.0))
    assert len(doc["records"]) == 1
    assert doc["records"][0]["kernel"]["2048x8192x32"][
        "winner_step_us"
    ] == 90.0
    other = dict(hw, node_budget=64)
    doc = tune_cache.upsert_record(doc, _fake_record(other))
    assert len(doc["records"]) == 2


def test_validate_tuned_names_problems():
    hw = tune_cache.hardware_key(NODE_BUDGET, EDGE_BUDGET)
    good = tune_cache.upsert_record(
        tune_cache.empty_doc(), _fake_record(hw)
    )
    assert tune_cache.validate_tuned(good)["ok"]
    # incomplete hardware key
    bad_hw = json.loads(json.dumps(good))
    del bad_hw["records"][0]["hardware"]["device_kind"]
    v = tune_cache.validate_tuned(bad_hw)
    assert not v["ok"] and any(
        "hardware key incomplete" in p for p in v["problems"]
    )
    # candidate row without its numerics verdict
    bad_verdict = json.loads(json.dumps(good))
    del bad_verdict["records"][0]["kernel"]["2048x8192x32"][
        "candidates"
    ][0]["numerics"]
    v = tune_cache.validate_tuned(bad_verdict)
    assert not v["ok"] and any(
        "numerics-contract verdict" in p for p in v["problems"]
    )
    # winner missing per signature
    bad_winner = json.loads(json.dumps(good))
    del bad_winner["records"][0]["kernel"]["2048x8192x32"]["winner"]
    v = tune_cache.validate_tuned(bad_winner)
    assert not v["ok"] and any("no winner" in p for p in v["problems"])
    # ladder without its pow2 baseline
    bad_ladder = json.loads(json.dumps(good))
    del bad_ladder["records"][0]["ladders"]["serve"][
        "pow2_padding_waste"
    ]
    v = tune_cache.validate_tuned(bad_ladder)
    assert not v["ok"]
    # axis values are optional (the _fake_record rows above carry no
    # unroll and validate — pre-PR-16 compat) but when present must
    # name a replayable mode
    bad_axis = json.loads(json.dumps(good))
    bad_axis["records"][0]["kernel"]["2048x8192x32"]["candidates"][0][
        "unroll"
    ] = "chunked"
    v = tune_cache.validate_tuned(bad_axis)
    assert not v["ok"] and any(
        "unknown unroll" in p for p in v["problems"]
    )
    bad_accum = json.loads(json.dumps(good))
    bad_accum["records"][0]["kernel"]["2048x8192x32"]["candidates"][0][
        "accum"
    ] = "fp8"
    v = tune_cache.validate_tuned(bad_accum)
    assert not v["ok"] and any(
        "unknown accum" in p for p in v["problems"]
    )


def test_failed_search_never_clobbers_good_record(tmp_path, caplog):
    """A run_tune pass that produces an invalid record (no evidence
    sections) must leave the existing good tuned.json untouched."""
    from deepdfa_tpu.tune import driver as tune_driver

    hw = tune_cache.hardware_key(NODE_BUDGET, EDGE_BUDGET)
    path = tmp_path / "tuned.json"
    tune_cache.save_tuned(
        path,
        tune_cache.upsert_record(tune_cache.empty_doc(), _fake_record(hw)),
    )
    before = path.read_text()
    cfg = config_mod.apply_overrides(Config(), [
        f'data.batch={{"node_budget": {NODE_BUDGET}, '
        f'"edge_budget": {EDGE_BUDGET}}}',
    ])
    with caplog.at_level(
        logging.WARNING, logger="deepdfa_tpu.tune.driver"
    ):
        report = tune_driver.run_tune(
            cfg, serve_logs=None, manifest=None, out_path=path,
            skip_kernel=True,  # no kernel, no logs: nothing to record
        )
    assert not report["valid"]
    assert path.read_text() == before  # the good record survived
    assert any(
        "not persisting invalid" in r.message for r in caplog.records
    )


def test_record_for_config_tolerates_corrupt_records_list(
    tmp_path, caplog
):
    path = tmp_path / "tuned.json"
    path.write_text(json.dumps({"version": 1, "records": [None, "x"]}))
    cfg = config_mod.apply_overrides(Config(), [
        "tune.enabled=true", f"tune.path={json.dumps(str(path))}",
    ])
    with caplog.at_level(logging.WARNING, logger="deepdfa_tpu.tune.cache"):
        assert tune_cache.record_for_config(cfg, 64, 128) is None
    assert any(
        "no tuned record matches" in r.message for r in caplog.records
    )


def test_apply_to_config_sections(tmp_path):
    hw = tune_cache.hardware_key(NODE_BUDGET, EDGE_BUDGET)
    rec = _fake_record(hw)
    rec["kernel"] = {
        # the GGNN feature width for the default model (hidden 32,
        # concat_all) is 128 — the signature apply_to_config looks up
        f"{NODE_BUDGET}x{EDGE_BUDGET}x128": rec["kernel"].pop(
            "2048x8192x32"
        )
    }
    rec["ladders"]["seq_buckets"] = {
        "edges": [24, 64], "pow2_edges": [2, 64],
        "padding_waste": 0.1, "pow2_padding_waste": 0.2, "samples": 5,
    }
    path = tmp_path / "tuned.json"
    tune_cache.save_tuned(
        path, tune_cache.upsert_record(tune_cache.empty_doc(), rec)
    )
    cfg = config_mod.apply_overrides(Config(), [
        "tune.enabled=true", f"tune.path={json.dumps(str(path))}",
        f'data.batch={{"node_budget": {NODE_BUDGET}, '
        f'"edge_budget": {EDGE_BUDGET}}}',
        "data.seq_buckets=[16, 64]",  # anchors the max edge at 64
    ])
    tuned_cfg, report = tune_cache.apply_to_config(cfg)
    assert report["matched"]
    assert tuned_cfg.model.ggnn_kernel_block_nodes == 256
    assert tuned_cfg.model.ggnn_kernel_block_edges == 512
    assert tuned_cfg.data.seq_buckets == (24, 64)
    # the winner's scatter/accum ride along (the joint layout rule);
    # a pre-PR-16 record carries no winner_unroll, so the knob keeps
    # its per_step default — exactly the mode those searches timed
    assert tuned_cfg.model.ggnn_kernel_scatter == "fold"
    assert tuned_cfg.model.ggnn_kernel_accum == "fp32"
    assert tuned_cfg.model.ggnn_kernel_unroll == "per_step"
    # max_length drift: a config whose buckets top elsewhere keeps its
    # own edges (the serve capacity-guard's train-side twin)
    drifted = config_mod.apply_overrides(cfg, [
        "data.seq_buckets=[16, 128]",
    ])
    drifted_cfg, _ = tune_cache.apply_to_config(drifted)
    assert drifted_cfg.data.seq_buckets == (16, 128)
    # unset buckets: tuned edges never flip bucketing on by themselves
    unset = config_mod.apply_overrides(cfg, ["data.seq_buckets=[]"])
    unset_cfg, _ = tune_cache.apply_to_config(unset)
    assert unset_cfg.data.seq_buckets == ()
    # serve-side callers take only the kernel layout (bucket edges flow
    # through ScoringService so the hot-swap digest never moves)
    kern_cfg, _ = tune_cache.apply_to_config(
        cfg, sections=("kernel",)
    )
    assert kern_cfg.model.ggnn_kernel_block_nodes == 256
    assert kern_cfg.data.seq_buckets == cfg.data.seq_buckets  # untouched
    # the digest exclusion that makes that safe: NOTHING the tuner
    # writes (kernel layout, seq-bucket edges) ever moves the
    # registry's hot-swap admission digest — while a genuine feature
    # change still does
    from deepdfa_tpu.serve.registry import config_digest

    assert config_digest(kern_cfg) == config_digest(cfg)
    assert config_digest(tuned_cfg) == config_digest(cfg)
    feat_cfg = config_mod.apply_overrides(cfg, ["data.gtype=\"pdg\""])
    assert config_digest(feat_cfg) != config_digest(cfg)


def test_winner_unroll_flows_to_config(tmp_path):
    """A record whose winner carries the fused unroll writes
    model.ggnn_kernel_unroll through kernel_layout_from +
    apply_to_config — the fifth joint-layout axis."""
    hw = tune_cache.hardware_key(NODE_BUDGET, EDGE_BUDGET)
    rec = _fake_record(hw)
    sig = f"{NODE_BUDGET}x{EDGE_BUDGET}x128"
    rec["kernel"] = {sig: rec["kernel"].pop("2048x8192x32")}
    rec["kernel"][sig]["winner"] = "bn256-be512-fold-fp32-fused"
    rec["kernel"][sig]["winner_unroll"] = "fused"
    rec["kernel"][sig]["candidates"][0]["candidate"] = (
        "bn256-be512-fold-fp32-fused"
    )
    layout = tune_cache.kernel_layout_from(
        rec, NODE_BUDGET, EDGE_BUDGET, 128
    )
    assert layout["unroll"] == "fused"
    path = tmp_path / "tuned.json"
    tune_cache.save_tuned(
        path, tune_cache.upsert_record(tune_cache.empty_doc(), rec)
    )
    cfg = config_mod.apply_overrides(Config(), [
        "tune.enabled=true", f"tune.path={json.dumps(str(path))}",
        f'data.batch={{"node_budget": {NODE_BUDGET}, '
        f'"edge_budget": {EDGE_BUDGET}}}',
    ])
    tuned_cfg, report = tune_cache.apply_to_config(cfg)
    assert report["matched"]
    assert tuned_cfg.model.ggnn_kernel_unroll == "fused"
    # still a lowering-only knob: the hot-swap digest never moves
    from deepdfa_tpu.serve.registry import config_digest

    assert config_digest(tuned_cfg) == config_digest(cfg)


def test_gate_tuned_notes_axis_flips():
    """An unroll/accum/scatter flip between a round and its reference
    is a NOTE (the layout family changed), never a failure — the
    step-time check stays the arbiter."""
    from deepdfa_tpu.obs import bench_gate as bg

    hw = tune_cache.hardware_key(NODE_BUDGET, EDGE_BUDGET)
    base_doc = tune_cache.upsert_record(
        tune_cache.empty_doc(), _fake_record(hw, step_us=100.0)
    )
    trajectory = [
        {"source": "TUNED_r01.json", "round": 1, "record": base_doc}
    ]
    flipped = _fake_record(hw, step_us=95.0)
    sr = flipped["kernel"]["2048x8192x32"]
    sr["winner"] = "bn256-be512-fold-fp32-fused"
    sr["winner_unroll"] = "fused"
    sr["candidates"][0]["candidate"] = sr["winner"]
    doc = tune_cache.upsert_record(tune_cache.empty_doc(), flipped)
    res = bg.gate_tuned(doc, trajectory)
    assert res["verdict"] == "pass", res
    assert any(
        "winner_unroll flipped 'per_step' -> 'fused'" in n
        for n in res["notes"]
    ), res["notes"]


# ---------------------------------------------------------------------------
# tuned warmup ladder keeps the serving contracts


@pytest.fixture(scope="module")
def served_model():
    import jax

    from deepdfa_tpu.data import build_dataset, generate, to_examples
    from deepdfa_tpu.graphs.batch import pack
    from deepdfa_tpu.models import DeepDFA

    synth = generate(12, seed=5)
    specs, _ = build_dataset(
        to_examples(synth), train_ids=range(12), limit_all=50,
        limit_subkeys=50,
    )
    cfg = config_mod.apply_overrides(Config(), [
        'data.feat={"limit_all": 50, "limit_subkeys": 50}',
        "model.hidden_dim=8", "model.n_steps=2",
    ])
    model = DeepDFA.from_config(cfg.model, input_dim=cfg.data.feat.input_dim)
    params = model.init(
        jax.random.key(0), pack([], 1, NODE_BUDGET, EDGE_BUDGET)
    )
    return specs, model, params


def test_tuned_ladder_zero_recompiles_and_bit_parity(served_model):
    """A tuned (non-pow2) warmup ladder keeps BOTH serving contracts on
    the 8-virtual-device mesh: zero steady-state lowerings over
    arbitrary traffic, and every request's batched score EXACTLY equals
    its singleton score."""
    from deepdfa_tpu.serve.batcher import DynamicBatcher, GgnnExecutor

    specs, model, params = served_model
    executor = GgnnExecutor(
        model, lambda: params,
        node_budget=NODE_BUDGET, edge_budget=EDGE_BUDGET,
        max_batch_graphs=4, ladder=(1, 3, 4),
    )
    assert executor.sizes == (1, 3, 4)
    executor.warmup()
    n0 = executor.jit_lowerings()
    assert n0 == 3  # exactly the tuned rungs, nothing else
    assert executor.warmup() == {}  # idempotent

    alone = {}
    for s in specs:
        [req] = DynamicBatcher(executor, queue_limit=8).score_all([s])
        alone[s.graph_id] = req.result

    rng = np.random.default_rng(2)
    for _ in range(3):
        order = rng.permutation(len(specs))
        reqs = DynamicBatcher(executor, queue_limit=64).score_all(
            [specs[i] for i in order]
        )
        for i, req in zip(order, reqs):
            assert req.result == alone[specs[i].graph_id]
    assert executor.jit_lowerings() == n0  # zero steady-state lowerings


def test_tuned_rungs_cover_localize_ladder(served_model):
    """The acceptance census across the OTHER compiled surfaces: the
    localizer shares the executor's tuned rungs (ScoringService passes
    sizes=executor.sizes), so line attribution on tuned rungs also
    pins zero steady-state lowerings."""
    import numpy as np

    from deepdfa_tpu.serve.batcher import GgnnExecutor
    from deepdfa_tpu.serve.frontend import Features
    from deepdfa_tpu.serve.localize import GgnnLocalizer

    specs, model, params = served_model
    executor = GgnnExecutor(
        model, lambda: params,
        node_budget=NODE_BUDGET, edge_budget=EDGE_BUDGET,
        max_batch_graphs=4, ladder=(1, 3, 4),
    )
    executor.warmup()
    localizer = GgnnLocalizer(
        model, lambda: params,
        node_budget=NODE_BUDGET, edge_budget=EDGE_BUDGET,
        sizes=executor.sizes, method="saliency", top_k=3,
    )
    assert localizer.sizes == (1, 3, 4)
    localizer.warmup()
    n0 = localizer.jit_lowerings()
    assert n0 == 3
    feats = [
        Features(
            spec=s,
            node_lines=np.arange(1, s.num_nodes + 1, dtype=np.int32),
        )
        for s in specs[:5]
    ]
    out = localizer.attribute_all(feats)  # chunks of 3 + 2 -> rungs 3, 3
    assert len(out) == 5
    [single] = localizer.attribute([feats[0]])  # rung 1
    assert single[1], "ranked line attributions expected"
    assert localizer.jit_lowerings() == n0


def test_tuned_seq_buckets_cover_combined_ladder(served_model):
    """Fitted (non-pow2) seq-bucket edges — what the cascade's stage-2
    / combined ladder warms under tune.enabled — keep the combined
    executor's zero-steady-state-lowerings contract."""
    import jax

    from deepdfa_tpu.data.tokenizer import HashTokenizer
    from deepdfa_tpu.models import combined as cmb
    from deepdfa_tpu.models.transformer import TransformerConfig
    from deepdfa_tpu.serve.batcher import CombinedExecutor, DynamicBatcher

    tok = HashTokenizer(vocab_size=256)
    enc = TransformerConfig.tiny(
        vocab_size=tok.vocab_size, max_position_embeddings=68,
        num_layers=1, num_heads=2, hidden_size=8, intermediate_size=16,
    )
    mcfg = cmb.CombinedConfig(
        encoder=enc, graph_hidden_dim=8, graph_input_dim=52,
        use_graph=False,
    )
    params = cmb.init_params(mcfg, jax.random.key(0))
    executor = CombinedExecutor(
        mcfg, lambda: params, tok, seq_buckets=(24, 64),  # fitted edges
        token_budget=256, node_budget=256, edge_budget=1024,
    )
    executor.warmup()
    n0 = executor.jit_lowerings()
    assert n0 == 2
    texts = [
        "int f(int x){return x;}",
        "void g(){int a=1; int b=2; int c=a+b; (void)c;}",
    ]
    payloads = [(tok.encode(t, max_length=64), None) for t in texts]
    reqs = DynamicBatcher(executor, queue_limit=8).score_all(payloads)
    assert all(0.0 <= r.result <= 1.0 for r in reqs)
    assert executor.jit_lowerings() == n0


def test_ladder_clamped_to_capacity(served_model):
    from deepdfa_tpu.serve.batcher import _ladder_sizes

    assert _ladder_sizes((3, 5, 99), 8) == (3, 5, 8)
    assert _ladder_sizes(None, 8) == (1, 2, 4, 8)
    assert _ladder_sizes((8,), 8) == (8,)


def test_ladder_waste_gauge_emitted(served_model):
    """The blind-spot satellite: executing a partial chunk lands
    per-rung real/padded counters and the serve/ladder_waste gauge in
    the registry (declared in SCHEMA, rendered by diag)."""
    from deepdfa_tpu.obs import metrics as obs_metrics
    from deepdfa_tpu.serve.batcher import DynamicBatcher, GgnnExecutor

    specs, model, params = served_model
    executor = GgnnExecutor(
        model, lambda: params,
        node_budget=NODE_BUDGET, edge_budget=EDGE_BUDGET,
        max_batch_graphs=8,
    )
    executor.warmup()
    before_real = obs_metrics.REGISTRY.counter(
        "serve/ladder/G8/real_rows"
    ).value
    before_padded = obs_metrics.REGISTRY.counter(
        "serve/ladder/G8/padded_rows"
    ).value
    # 5 requests pad to the G8 rung: the pow2 blind spot
    DynamicBatcher(executor, queue_limit=16).score_all(specs[:5])
    snap = obs_metrics.REGISTRY.snapshot()
    assert snap["serve/ladder/G8/real_rows"] - before_real == 5.0
    assert snap["serve/ladder/G8/padded_rows"] - before_padded == 3.0
    assert 0.0 < snap["serve/ladder_waste"] < 1.0
    for tag in (
        "serve/ladder/G8/real_rows", "serve/ladder/G8/padded_rows",
        "serve/ladder_waste",
    ):
        assert obs_metrics.declared(tag), tag


# ---------------------------------------------------------------------------
# the TUNED_r* trajectory gate


def test_gate_tuned_pass_regression_and_fit_vs_pow2():
    from deepdfa_tpu.obs import bench_gate as bg

    hw = tune_cache.hardware_key(NODE_BUDGET, EDGE_BUDGET)
    base_doc = tune_cache.upsert_record(
        tune_cache.empty_doc(), _fake_record(hw, step_us=100.0)
    )
    trajectory = [
        {"source": "TUNED_r01.json", "round": 1, "record": base_doc}
    ]
    ok_doc = tune_cache.upsert_record(
        tune_cache.empty_doc(), _fake_record(hw, step_us=105.0)
    )
    assert bg.gate_tuned(ok_doc, trajectory)["verdict"] == "pass"
    # winner step time regressed past tolerance
    slow_doc = tune_cache.upsert_record(
        tune_cache.empty_doc(), _fake_record(hw, step_us=200.0)
    )
    res = bg.gate_tuned(slow_doc, trajectory)
    assert res["verdict"] == "fail"
    assert "regression" in res["failure_classes"]
    # a fit that LOSES to its own pow2 baseline fails absolutely
    losing = tune_cache.upsert_record(
        tune_cache.empty_doc(), _fake_record(hw, waste=0.5)
    )
    res2 = bg.gate_tuned(losing, [])
    assert res2["verdict"] == "fail"
    # schema damage is an error class
    res3 = bg.gate_tuned({"version": 1, "records": []}, trajectory)
    assert "error" in res3["failure_classes"]
    # the committed repo trajectory parses and the newest round gates
    import pathlib

    repo = pathlib.Path(__file__).parents[1]
    committed = tune_cache.load_tuned_trajectory(repo)
    assert any(
        isinstance(e.get("record"), dict) for e in committed
    ), "a TUNED_r*.json round must be committed"
    newest = [e for e in committed if isinstance(e.get("record"), dict)][-1]
    verdict = bg.gate_tuned(
        newest["record"], committed, exclude_source=newest["source"]
    )
    assert verdict["verdict"] == "pass", verdict


# ---------------------------------------------------------------------------
# CLI acceptance (subprocess, the tier-1 drive)


def test_tune_cli_smoke(tmp_path):
    """`deepdfa-tpu tune --smoke`: a real search over the reduced
    candidate set, a schema-valid tuned.json whose ladder fit beats
    pow2, validated again through `check_obs_schema.py --tuned` and
    gated through `bench_gate.py --tuned`."""
    import pathlib
    import subprocess
    import sys

    res = run_cli(tmp_path, "tune", "--smoke", timeout=300)
    report = json.loads(
        [l for l in res.stdout.splitlines() if l.startswith("{")][-1]
    )
    assert report["valid"], report
    assert report["winner"]
    assert (
        report["tuned_ladder_padding_waste"]
        < report["pow2_ladder_padding_waste"]
    )
    tuned_path = report["tuned_path"]
    repo = pathlib.Path(__file__).parents[1]
    for script, args in (
        ("check_obs_schema.py", ["--tuned", tuned_path]),
        # gate against an EMPTY trajectory root: the committed
        # TUNED_r15 shares this hardware key, and wall-clock step time
        # vs a different box/load is exactly the round-over-round
        # comparison the DRIVER box owns — under pytest load it flakes
        # (observed: winner_step_us past tolerance purely from CPU
        # contention). Absolute checks (schema, fit-vs-pow2,
        # search-seconds bound) still run and must pass.
        ("bench_gate.py", ["--tuned", tuned_path,
                           "--root", str(tmp_path)]),
    ):
        proc = subprocess.run(
            [sys.executable, str(repo / "scripts" / script), *args],
            capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == 0, (script, proc.stdout, proc.stderr)
