"""Eval subsystem: localization metrics, dependences, coverage, profiling."""

import numpy as np
import pytest

from deepdfa_tpu.eval import (
    RankedExample,
    aggregate_report,
    compiled_cost,
    coverage,
    ifa,
    profile_model,
    statement_report,
    top_k_accuracy,
)
from deepdfa_tpu.frontend import parse_function
from deepdfa_tpu.frontend.deps import (
    control_dependences,
    data_dependences,
    dependent_lines,
)


def test_topk_and_ifa():
    exs = [
        RankedExample(np.array([0.9, 0.1, 0.5]), np.array([False, True, False])),
        RankedExample(np.array([0.9, 0.1, 0.5]), np.array([True, False, False])),
        RankedExample(np.array([0.2, 0.1, 0.5]), np.array([False, False, False])),
    ]
    # ex0: true line ranked 3rd; ex1: ranked 1st; ex2 has no truth (skipped)
    assert top_k_accuracy(exs, k=1) == 0.5
    assert top_k_accuracy(exs, k=3) == 1.0
    assert ifa(exs) == 1.0  # (2 + 0) / 2
    rep = statement_report(exs)
    assert 0.0 < rep["effort_at_20_recall"] <= 1.0
    assert rep["recall_at_1_loc"] >= 0.0


def test_data_dependences():
    cpg = parse_function(
        """
int f(int a) {
    int x = a + 1;
    int y = x * 2;
    return y;
}
"""
    )
    dd = data_dependences(cpg)
    codes = {
        (cpg.nodes[s].code, cpg.nodes[d].code)
        for s, d in dd
    }
    # y = x * 2 depends on x = a + 1
    assert any(s == "x = a + 1" and "y" in d for s, d in codes), codes
    # return y depends on y = x * 2
    assert any(s == "y = x * 2" and "return" in d for s, d in codes), codes


def test_control_dependences():
    cpg = parse_function(
        """
int g(int a) {
    int r = 0;
    if (a > 0) {
        r = 1;
    }
    return r;
}
"""
    )
    cd = control_dependences(cpg)
    pairs = {
        (cpg.nodes[s].code, cpg.nodes[d].code) for s, d in cd
    }
    # r = 1 is control dependent on the a > 0 branch
    assert any("a > 0" in s and d == "r = 1" for s, d in pairs), pairs
    # return r is NOT control dependent on the branch (post-dominates)
    assert not any("a > 0" in s and d == "return r" for s, d in pairs), pairs


def test_dependent_lines_closure():
    code = """
int h(int a) {
    int x = a;
    if (x > 2) {
        x = 5;
    }
    return x;
}
"""
    cpg = parse_function(code)
    # target: the condition line (line 4 in this string: "if (x > 2) {")
    deps = dependent_lines(cpg, {4})
    assert 5 in deps  # x = 5 is control-dependent on the condition
    assert 3 in deps  # x = a is the reaching def used by the condition


def test_coverage_stats(rng):
    from deepdfa_tpu.graphs import GraphSpec

    feats = np.zeros((10, 4), np.int32)
    feats[0, 1] = 1  # unknown
    feats[1, 1] = 5  # known
    feats[2, 1] = 7  # known
    s = GraphSpec(0, feats, np.zeros((10,), np.int32),
                  np.zeros((0,), np.int32), np.zeros((0,), np.int32), 0.0)
    st = coverage([s])
    assert st.n_def_nodes == 3
    assert st.n_known == 2
    assert abs(st.known_coverage - 2 / 3) < 1e-9
    assert st.def_rate == 0.3


def test_profiling_cost_and_report(tmp_path):
    import jax.numpy as jnp

    def f(x):
        return (x @ x).sum()

    x = np.eye(64, dtype=np.float32)
    cost = compiled_cost(f, x)
    assert cost["flops"] > 0
    rec = profile_model(f, (x,), examples_per_call=64, out_path=tmp_path / "p.jsonl")
    assert rec["ms_per_example"] > 0
    agg = aggregate_report(tmp_path / "p.jsonl")
    assert agg["total_examples"] == 64
    assert agg["total_gflops"] > 0


def test_per_example_ifa_matches_mean():
    import numpy as np

    from deepdfa_tpu.eval.statements import RankedExample, ifa, per_example_ifa

    exs = [
        RankedExample(np.array([3.0, 2.0, 1.0]), np.array([False, True, False])),
        RankedExample(np.array([1.0, 5.0]), np.array([False, True])),
        RankedExample(np.array([1.0]), np.array([False])),  # no positives
    ]
    vals = per_example_ifa(exs)
    assert vals == [1, 0]
    assert ifa(exs) == 0.5


def test_xprof_trace_writes_device_trace(tmp_path):
    import jax
    import jax.numpy as jnp

    from deepdfa_tpu.eval import xprof_trace

    with xprof_trace(tmp_path / "xprof"):
        jax.block_until_ready(jnp.ones((8, 8)) @ jnp.ones((8, 8)))
    dumped = list((tmp_path / "xprof").rglob("*.xplane.pb"))
    assert dumped, list((tmp_path / "xprof").rglob("*"))
