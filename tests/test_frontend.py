"""Frontend tests: parser -> CPG -> CFG -> reaching defs -> features."""

import json

import numpy as np
import pytest

from deepdfa_tpu.frontend import (
    ReachingDefinitions,
    build_vocabs,
    decl_features,
    encode_nodes,
    graph_features,
    is_decl,
    parse_function,
)
from deepdfa_tpu.frontend.cpg import CFG
from deepdfa_tpu.frontend.tokens import tokenize

SIMPLE = """
int add(int a, int b) {
    int sum = a + b;
    return sum;
}
"""

BRANCHY = """
int f(int n, char *buf) {
    int i = 0;
    int total = 0;
    while (i < n) {
        if (buf[i] == 'x') {
            total += 1;
        } else {
            total -= 1;
        }
        i++;
    }
    return total;
}
"""

VULNY = """
void copy(char *dst, const char *src, int len) {
    char tmp[64];
    int n = strlen(src);
    if (n > len) {
        n = len;
    }
    memcpy(tmp, src, n);
    strcpy(dst, tmp);
}
"""


def test_tokenizer_basics():
    toks = tokenize('int x = 0xFF + 1.5e-3; /* c */ char *s = "a\\"b"; // y\n')
    texts = [t.text for t in toks if t.kind != "eof"]
    assert "0xFF" in texts and "1.5e-3" in texts
    assert '"a\\"b"' in texts
    assert "/*" not in " ".join(texts)
    # line numbers survive comments
    code = "int a;\n/* multi\nline */\nint b;"
    toks = tokenize(code)
    b_tok = [t for t in toks if t.text == "b"][0]
    assert b_tok.line == 4


def test_parse_simple_function():
    cpg = parse_function(SIMPLE)
    assert cpg.method_name == "add"
    labels = {n.label for n in cpg.nodes}
    assert {"METHOD", "METHOD_RETURN", "METHOD_PARAMETER_IN", "LOCAL",
            "IDENTIFIER", "CALL", "RETURN"} <= labels
    # the assignment call exists with joern name, and its first ARGUMENT is sum
    assigns = [n for n in cpg.nodes if n.name == "<operator>.assignment"]
    assert len(assigns) == 1
    args = cpg.arguments(assigns[0].id)
    assert cpg.nodes[args[0]].code == "sum"
    assert cpg.nodes[args[0]].type_full_name == "int"
    # CFG connects METHOD ... METHOD_RETURN
    cfg_nodes = cpg.cfg_nodes()
    assert cpg.method_id in cfg_nodes
    assert cpg.method_return_id in cfg_nodes


def _cfg_reachable(cpg):
    seen = set()
    stack = [cpg.method_id]
    while stack:
        n = stack.pop()
        if n in seen:
            continue
        seen.add(n)
        stack.extend(cpg.successors(n, CFG))
    return seen


def test_cfg_branch_join():
    cpg = parse_function(BRANCHY)
    reach = _cfg_reachable(cpg)
    assert cpg.method_return_id in reach
    # while loop: the condition node has a back edge (is its own ancestor)
    rd = ReachingDefinitions(cpg)
    in_sets = rd.solve()
    # defs of i: "i = 0" and "i++" — at the loop condition both reach
    less_than = [
        n.id for n in cpg.nodes if n.name == "<operator>.lessThan"
    ]
    assert less_than
    vars_at_cond = {d.var for d in in_sets[less_than[0]]}
    assert "i" in vars_at_cond
    codes = {d.code for d in in_sets[less_than[0]] if d.var == "i"}
    assert codes == {"i = 0", "i++"}


def test_reaching_defs_kill():
    cpg = parse_function(
        """
int g(int a) {
    int x = 1;
    x = 2;
    return x;
}
"""
    )
    rd = ReachingDefinitions(cpg)
    assert len(rd.domain) == 2
    in_sets = rd.solve()
    ret = [n.id for n in cpg.nodes if n.label == "RETURN"][0]
    reaching = {d.code for d in in_sets[ret]}
    # x = 1 is killed by x = 2 before the return
    assert reaching == {"x = 2"}


def test_reaching_defs_branches_merge():
    cpg = parse_function(
        """
int h(int a) {
    int x = 1;
    if (a) {
        x = 2;
    }
    return x;
}
"""
    )
    rd = ReachingDefinitions(cpg)
    in_sets = rd.solve()
    ret = [n.id for n in cpg.nodes if n.label == "RETURN"][0]
    reaching = {d.code for d in in_sets[ret] if d.var == "x"}
    assert reaching == {"x = 1", "x = 2"}


def test_is_decl_and_datatype():
    cpg = parse_function(VULNY)
    decls = [n.id for n in cpg.nodes if is_decl(cpg, n.id)]
    # n = strlen(src); n = len; (tmp decl has no initializer)
    codes = {cpg.nodes[d].code for d in decls}
    assert "n = strlen(src)" in codes
    assert "n = len" in codes
    feats = {cpg.nodes[d].code: decl_features(cpg, d) for d in decls}
    f1 = feats["n = strlen(src)"]
    assert ("datatype", "int") in f1
    assert ("api", "strlen") in f1
    f2 = feats["n = len"]
    assert ("datatype", "int") in f2


def test_datatype_recursion_through_accessors():
    cpg = parse_function(
        """
void t(struct foo *p, int i) {
    int arr[10];
    p->x = 1;
    arr[i] = 2;
    *p = 3;
}
"""
    )
    decls = {
        cpg.nodes[n.id].code: n.id for n in cpg.nodes if is_decl(cpg, n.id)
    }
    f = dict((k, dict(decl_features(cpg, v))) for k, v in decls.items())
    assert f["p->x = 1"]["datatype"] == "struct foo*"
    assert f["arr[i] = 2"]["datatype"] == "int[]"
    assert f["*p = 3"]["datatype"] == "struct foo*"


def test_inc_dec_are_defs():
    cpg = parse_function("void u(int k) { k++; --k; }")
    rd = ReachingDefinitions(cpg)
    assert {d.var for d in rd.domain} == {"k"}
    assert len(rd.domain) == 2


def test_features_hash_and_vocab_indexing():
    cpgs = [parse_function(VULNY), parse_function(BRANCHY), parse_function(SIMPLE)]
    per_graph = [
        {nid: decl_features(c, nid) for nid in (n.id for n in c.nodes) if is_decl(c, nid)}
        for c in cpgs
    ]
    train_fields = [f for g in per_graph for f in g.values()]
    vocabs = build_vocabs(train_fields, limit_all=10, limit_subkeys=10)
    assert set(vocabs) == {"api", "datatype", "literal", "operator"}
    v = vocabs["datatype"]
    assert v.input_dim == 12
    # every train hash encodes to >= 2 (known) since vocab covers all
    for fields in train_fields:
        idx = v.encode(fields)
        assert idx == 0 or idx >= 2
    # unseen hash -> UNKNOWN (index 1)
    weird = [("datatype", "quux_t***")]
    assert v.encode(weird) == 1
    # not a def -> 0
    assert v.encode(None) == 0
    # roundtrip
    v2 = type(v).from_json(v.to_json())
    assert v2.encode(weird) == 1
    assert v2.hash_index == v.hash_index

    # encode_nodes builds the [n, 4] matrix aligned with node id order
    cpg = cpgs[0]
    ids = [n.id for n in cpg.nodes]
    mat = encode_nodes(vocabs, per_graph[0], ids)
    assert mat.shape == (len(ids), 4)
    def_rows = [i for i, nid in enumerate(ids) if nid in per_graph[0]]
    assert (mat[def_rows] > 0).any()
    non_def = [i for i, nid in enumerate(ids) if nid not in per_graph[0]]
    assert (mat[non_def] == 0).all()


def test_unknown_statement_recovery():
    cpg = parse_function(
        """
int weird(int a) {
    int x = 1;
    __asm__ volatile("nop" ::: );
    return x;
}
"""
    )
    # parse succeeded and the function is intact around the weird line
    assert cpg.method_name == "weird"
    rd = ReachingDefinitions(cpg)
    assert {d.var for d in rd.domain} == {"x"}


def test_switch_and_goto():
    cpg = parse_function(
        """
int s(int a) {
    int r = 0;
    switch (a) {
    case 1:
        r = 1;
        break;
    case 2:
        r = 2;
    default:
        r = 3;
    }
    if (r == 3) goto out;
    r = 4;
out:
    return r;
}
"""
    )
    rd = ReachingDefinitions(cpg)
    in_sets = rd.solve()
    ret = [n.id for n in cpg.nodes if n.label == "RETURN"][0]
    reaching = {d.code for d in in_sets[ret] if d.var == "r"}
    # r=0 killed on all paths through the switch (default catches all),
    # r=1 / r=2 / r=3 / r=4 can reach the label
    assert "r = 4" in reaching
    assert "r = 1" in reaching
    assert "r = 3" in reaching
    # r = 2 falls through to default which kills it
    assert "r = 2" not in reaching


def test_stage2_hash_matches_reference_format():
    cpg = parse_function(VULNY)
    hashes = graph_features(cpg)
    assert hashes
    for h in hashes.values():
        d = json.loads(h)
        assert set(d) == {"api", "datatype", "literal", "operator"}
        for v in d.values():
            assert v == sorted(v)


def test_stage2_hash_golden_values():
    """GOLDEN: the exact stage-2 hash strings for a frozen fixture.

    The abstract-dataflow feature definition silently determines model F1
    (SURVEY.md §7 hard part 4) — any change to decl detection, datatype
    recursion, subkey collection, or hash serialization must show up here
    as a conscious golden update, never an accident."""
    cpg = parse_function(VULNY)
    by_code = {
        cpg.nodes[nid].code: h for nid, h in graph_features(cpg).items()
    }
    assert by_code == {
        "n = strlen(src)": (
            '{"api": ["strlen"], "datatype": ["int"], "literal": [], '
            '"operator": []}'
        ),
        "n = len": (
            '{"api": [], "datatype": ["int"], "literal": [], "operator": []}'
        ),
    }


def test_datatype_recursion_failure_drops_all_fields():
    """Reference error contract (abstract_dataflow_full.py:127-166): when
    the LHS datatype recursion hits an unhandled shape it raises, aborting
    field collection — the node gets NO hash even though it has literal /
    api descendants. Nodes with resolvable LHS are unaffected."""
    code = (
        "int f(int *a, int x) {\n"
        "  *(g(a)) = x + 1;\n"
        "  int y = x;\n"
        "  return y;\n"
        "}"
    )
    cpg = parse_function(code)
    by_code = {
        cpg.nodes[nid].code: h for nid, h in graph_features(cpg).items()
    }
    assert set(by_code) == {"y = x"}


def test_stage2_hash_golden_values_cxx():
    """GOLDEN: C++ fixture (operator/new/literal/qualified-datatype mix)."""
    code = (
        "int f(base::List* items, int len) {\n"
        "  base::Value* out = NULL;\n"
        "  char* p = new char[16];\n"
        "  int k = len * 2 + items->size();\n"
        "  return k;\n"
        "}"
    )
    cpg = parse_function(code)
    by_code = {
        cpg.nodes[nid].code: h for nid, h in graph_features(cpg).items()
    }
    assert by_code == {
        "out = NULL": (
            '{"api": [], "datatype": ["base::Value*"], "literal": [], '
            '"operator": []}'
        ),
        "p = new char[16]": (
            '{"api": [], "datatype": ["char*"], "literal": ["16"], '
            '"operator": ["new"]}'
        ),
        # the method-call receiver chain is absorbed into the api name
        # (items->size), so no indirectFieldAccess operator appears
        "k = len * 2 + items->size()": (
            '{"api": ["items->size"], "datatype": ["int"], '
            '"literal": ["2"], "operator": ["addition", "multiplication"]}'
        ),
    }
