"""Experiment-matrix runner (run_exp.py role)."""

import pytest

import json

from deepdfa_tpu.train.experiments import (
    Run,
    expand_matrix,
    load_matrix,
    parse_result,
    run_matrix,
)

# heavy compiles / subprocesses: excluded from the default fast lane
# (pyproject addopts); run via `pytest -m slow` or `pytest -m ""`
pytestmark = pytest.mark.slow


def test_expand_matrix_tags_and_seeds():
    runs = expand_matrix(["deepdfa", "clone"], seeds=[0, 1],
                         overrides=["train.max_epochs=1"])
    assert len(runs) == 4
    names = [r.name for r in runs]
    assert "deepdfa_seed1" in names and "clone_seed0" in names
    r = runs[0]
    assert r.cmd == "train"
    assert "train.seed=0" in r.args
    assert f"run_name={r.name}" in r.args
    assert "train.max_epochs=1" in r.args


def test_parse_result_variants():
    assert parse_result('x\n{"f1": 0.5}\n') == {"f1": 0.5}
    assert parse_result("best: {'val_f1': 0.9}\n") == {"val_f1": 0.9}
    assert parse_result("no json here") is None
    # last JSON line wins
    out = parse_result('{"a": 1}\n{"b": 2}')
    assert out == {"b": 2}


def test_load_and_run_matrix(tmp_path):
    spec = [{"name": "r1", "cmd": "doesnotmatter", "args": ["--x"]}]
    p = tmp_path / "matrix.json"
    p.write_text(json.dumps(spec))
    runs = load_matrix(p)
    assert runs == [Run(name="r1", cmd="doesnotmatter", args=("--x",))]

    # dry-run never spawns subprocesses
    summaries = run_matrix(runs, tmp_path / "out", dry_run=True)
    assert summaries == [{"name": "r1", "dry_run": True}]


def test_run_matrix_executes_and_summarizes(tmp_path, monkeypatch):
    """A real (tiny) subprocess run: use the cli's own --help-free path by
    running a trivial matrix against `python -c`-style failure and assert
    rc + log capture (no training in unit tests)."""
    runs = [Run(name="bad", cmd="definitely-not-a-command", args=())]
    summaries = run_matrix(runs, tmp_path / "out")
    assert summaries[0]["rc"] != 0
    assert (tmp_path / "out" / "bad.log").exists()
    lines = (tmp_path / "out" / "summary.jsonl").read_text().splitlines()
    assert json.loads(lines[0])["name"] == "bad"
