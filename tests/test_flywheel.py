"""Data-flywheel unit tests (deepdfa_tpu/flywheel/, docs/flywheel.md)
— the pure halves without a fleet or a model: the promotion judge and
rank-AUC, the comparator's windowed stats, the router-side sampler's
deterministic period + backpressure drop, the fleet-log record shapes,
the log-driven promotion decision, the traffic-weighted retraining
helpers, and the default-off contract (flywheel off leaves the router
and the heartbeat envelope byte-identical). The full loop — shadow
ride, auto-promotion through the real rollout gates, rollback on an
injected bad candidate — lives in `fleet --smoke`
(fleet/smoke.py:run_flywheel_smoke, tests/test_fleet_cli.py)."""

import json
import time
from pathlib import Path

import pytest

from deepdfa_tpu.core import config as config_mod
from deepdfa_tpu.fleet.router import (
    DEMOTION_REASONS,
    FleetLog,
    ReplicaView,
    SHADOW_EVENTS,
    router_from_config,
    validate_fleet_log,
)
from deepdfa_tpu.flywheel import promote as promote_mod, shadow as shadow_mod
from deepdfa_tpu.flywheel.retrain import (
    band_of,
    example_weights,
    select_weighted,
    traffic_weights_from_log,
)
from deepdfa_tpu.obs import metrics as obs_metrics


# ---------------------------------------------------------------------------
# judge + rank_auc: the one decision function everything shares


def test_rank_auc_orders_and_ties():
    # perfect separation -> 1.0; inverted -> 0.0; ties split
    assert shadow_mod.rank_auc([1, 1, 0, 0], [0.9, 0.8, 0.2, 0.1]) == 1.0
    assert shadow_mod.rank_auc([1, 1, 0, 0], [0.1, 0.2, 0.8, 0.9]) == 0.0
    assert shadow_mod.rank_auc([1, 0], [0.5, 0.5]) == 0.5


def test_rank_auc_one_class_is_undefined():
    # an all-negative (or all-positive) window must NOT read as 0.5 —
    # judge() falls back to agreement instead of promoting on noise
    assert shadow_mod.rank_auc([0, 0], [0.1, 0.9]) is None
    assert shadow_mod.rank_auc([1, 1], [0.1, 0.9]) is None


BOUNDS = dict(
    min_samples=10, promote_margin=0.02, demote_margin=0.05,
    drift_bound=0.25,
)


def test_judge_sample_floor_first():
    # even a drifting, trailing candidate holds below the floor:
    # nothing is decidable on noise
    action, reason = shadow_mod.judge(
        {"samples": 9, "prob_drift": 0.9, "auc_candidate": 0.1,
         "auc_incumbent": 0.9}, **BOUNDS,
    )
    assert (action, reason) == ("hold", "insufficient_samples")


def test_judge_drift_gate_beats_auc():
    # mirrors the PR-14 swap-time refusal: a walked-away candidate is
    # demoted even when its AUC looks better
    action, reason = shadow_mod.judge(
        {"samples": 64, "prob_drift": 0.3, "auc_candidate": 0.9,
         "auc_incumbent": 0.6}, **BOUNDS,
    )
    assert (action, reason) == ("demote", "drift")


@pytest.mark.parametrize("auc_c,auc_i,expect", [
    (0.75, 0.70, ("promote", "auc_margin")),
    (0.60, 0.70, ("demote", "trailing")),
    (0.71, 0.70, ("hold", "within_margin")),
])
def test_judge_auc_margins(auc_c, auc_i, expect):
    assert shadow_mod.judge(
        {"samples": 64, "prob_drift": 0.01, "auc_candidate": auc_c,
         "auc_incumbent": auc_i}, **BOUNDS,
    ) == expect


def test_judge_unlabeled_never_promotes():
    # agreement only says "the same", not "better"
    assert shadow_mod.judge(
        {"samples": 64, "prob_drift": 0.01, "agreement": 1.0}, **BOUNDS,
    ) == ("hold", "unlabeled")
    assert shadow_mod.judge(
        {"samples": 64, "prob_drift": 0.01, "agreement": 0.5}, **BOUNDS,
    ) == ("demote", "trailing")


def test_judge_reasons_are_schema_valid():
    # every demote reason judge() can emit must be a declared demotion
    # reason, or record_demotion would raise on the verdict
    for stats in (
        {"samples": 64, "prob_drift": 0.9},
        {"samples": 64, "auc_candidate": 0.1, "auc_incumbent": 0.9},
        {"samples": 64, "agreement": 0.0},
    ):
        action, reason = shadow_mod.judge(stats, **BOUNDS)
        if action == "demote":
            assert reason in DEMOTION_REASONS


# ---------------------------------------------------------------------------
# ShadowComparator: windowed stats


def test_comparator_window_and_stats():
    comp = shadow_mod.ShadowComparator(window=4)
    for i in range(8):
        # last 4 rows: agree on 2 of 4, labels present
        p = 0.9 if i % 2 else 0.1
        comp.add(p, 1.0 - p if i >= 6 else p, label=i % 2, lag_s=0.1 * i)
    stats = comp.stats()
    assert stats["total"] == 8 and stats["samples"] == 4
    assert stats["agreement"] == 0.5
    assert stats["labeled"] == 4
    assert stats["lag_s"] == pytest.approx(0.7)
    assert "auc_candidate" in stats and "auc_incumbent" in stats


def test_comparator_empty_stats():
    assert shadow_mod.ShadowComparator().stats() == {
        "samples": 0, "total": 0,
    }


# ---------------------------------------------------------------------------
# record emitters: schema-valid by construction, loud otherwise


def test_record_helpers_raise_on_bad_vocabulary(tmp_path):
    log = FleetLog(tmp_path / "fleet_log.jsonl")
    try:
        with pytest.raises(ValueError):
            shadow_mod.record_shadow(log, "liftoff", "cand")
        with pytest.raises(ValueError):
            shadow_mod.record_demotion(log, "cand", "vibes")
        shadow_mod.record_shadow(log, "ride_start", "cand")
        shadow_mod.record_promotion(log, "cand", rollout_ok=True)
        shadow_mod.record_demotion(log, "cand", "trailing")
    finally:
        log.close()
    result = validate_fleet_log(tmp_path / "fleet_log.jsonl")
    assert result["ok"], result["problems"]
    assert result["shadow"] == 1
    assert result["promotions"] == 1
    assert result["demotions"] == 1


def test_validate_fleet_log_rejects_bad_flywheel_records(tmp_path):
    path = tmp_path / "fleet_log.jsonl"
    path.write_text(
        json.dumps({"shadow": {"event": "liftoff", "candidate": "c",
                               "t_unix": 1.0}}) + "\n"
        + json.dumps({"demotion": {"candidate": "c", "reason": "vibes",
                                   "t_unix": 1.0}}) + "\n"
        + json.dumps({"promotion": {"t_unix": 1.0}}) + "\n"
    )
    result = validate_fleet_log(path)
    assert not result["ok"]
    assert len(result["problems"]) == 3


def test_shadow_events_and_reasons_vocabulary():
    assert SHADOW_EVENTS == ("ride_start", "window", "ride_end")
    assert "rollout_halted" in DEMOTION_REASONS
    assert "insufficient_samples" in DEMOTION_REASONS


# ---------------------------------------------------------------------------
# ShadowSampler: deterministic period, label capture, bounded inflight


def test_sampler_every_kth_and_labels(tmp_path):
    sampler = shadow_mod.ShadowSampler(tmp_path, sample_rate=0.5)
    try:
        for i in range(6):
            sampler.observe(f"r{i}", {"code": f"int f{i}();",
                                      "label": i % 2}, 0.5, tenant="t")
    finally:
        sampler.close()
    lines = [
        json.loads(ln)["shadow_sample"]
        for ln in (tmp_path / shadow_mod.SAMPLES_FILE).read_text()
        .splitlines()
    ]
    # period 2: the 2nd, 4th, 6th observed requests are sampled
    assert [s["id"] for s in lines] == ["r1", "r3", "r5"]
    assert [s["seq"] for s in lines] == [1, 2, 3]
    assert all(s["label"] == 1 for s in lines)


def test_sampler_skips_unscorable(tmp_path):
    sampler = shadow_mod.ShadowSampler(tmp_path, sample_rate=1.0)
    try:
        assert not sampler.observe("a", {"code": None}, 0.5)
        assert not sampler.observe("b", {"code": "int f();"}, None)
        assert not sampler.observe("c", "not a dict", 0.5)
        assert sampler.observe("d", {"code": "int f();"}, 0.5)
    finally:
        sampler.close()


def test_sampler_zero_rate_samples_nothing(tmp_path):
    sampler = shadow_mod.ShadowSampler(tmp_path, sample_rate=0.0)
    try:
        assert not sampler.observe("a", {"code": "int f();"}, 0.5)
    finally:
        sampler.close()
    assert (tmp_path / shadow_mod.SAMPLES_FILE).read_text() == ""


def test_sampler_drops_past_max_inflight(tmp_path):
    # delta, not REGISTRY.reset(): reset orphans Counter objects other
    # subsystems captured at construction (e.g. the shared FeatureCache)
    dropped = obs_metrics.REGISTRY.counter("shadow/dropped")
    before = dropped.value
    # scorer acknowledged nothing: after max_inflight appends the
    # sampler DROPS (counted) instead of growing an unbounded mirror
    # buffer inside the router
    (tmp_path / shadow_mod.PROGRESS_FILE).write_text(
        json.dumps({"scored": 0})
    )
    sampler = shadow_mod.ShadowSampler(
        tmp_path, sample_rate=1.0, max_inflight=2,
        progress_refresh_s=0.0,
    )
    try:
        appended = sum(
            sampler.observe(f"r{i}", {"code": "int f();"}, 0.5)
            for i in range(5)
        )
    finally:
        sampler.close()
    assert appended == 2
    assert dropped.value == before + 3.0


def test_scorer_consumes_stream_and_emits_window(tmp_path):
    sampler = shadow_mod.ShadowSampler(tmp_path, sample_rate=1.0)
    log = FleetLog(tmp_path / "fleet_log.jsonl")
    # candidate = incumbent + 0.2: separable labels -> candidate AUC
    # equals incumbent AUC, agreement dented by the shift
    scorer = shadow_mod.ShadowScorer(
        tmp_path, "cand", "incumbent",
        lambda code: 0.2 + 0.05 * len(code), log=log,
        window=4, min_samples=4, promote_margin=0.01,
        demote_margin=0.05, drift_bound=1.0,
    )
    try:
        for i in range(4):
            sampler.observe(
                f"r{i}", {"code": "x" * (i + 1), "label": int(i >= 2)},
                0.05 * (i + 1),
            )
        assert scorer.poll() == 4
        assert scorer.windows == 1
        assert scorer.comparator.stats()["labeled"] == 4
        # the ack doc moved: the sampler's backpressure window advanced
        progress = json.loads(
            (tmp_path / shadow_mod.PROGRESS_FILE).read_text()
        )
        assert progress["scored"] == 4
    finally:
        scorer_stats = scorer.comparator.stats()
        log.close()
        sampler.close()
    assert scorer_stats["samples"] == 4
    result = validate_fleet_log(tmp_path / "fleet_log.jsonl")
    assert result["ok"], result["problems"]
    assert result["shadow"] == 1  # exactly one window record


# ---------------------------------------------------------------------------
# promotion decision from the log (the CLI/watcher path, no fleet)


def _ride_log(tmp_path, verdict_stats):
    log = FleetLog(tmp_path / "fleet_log.jsonl")
    try:
        shadow_mod.record_shadow(log, "ride_start", "cand")
        shadow_mod.record_shadow(log, "window", "cand", **verdict_stats)
    finally:
        log.close()
    return tmp_path / "fleet_log.jsonl"


def test_decide_from_log_promotes_on_margin(tmp_path):
    path = _ride_log(tmp_path, {
        "samples": 64, "prob_drift": 0.01,
        "auc_candidate": 0.8, "auc_incumbent": 0.7,
    })
    action, reason, stats = promote_mod.decide_from_log(
        path, "cand", **BOUNDS,
    )
    assert (action, reason) == ("promote", "auc_margin")
    assert stats["samples"] == 64


def test_decide_from_log_unknown_candidate_holds(tmp_path):
    path = _ride_log(tmp_path, {"samples": 64})
    action, reason, _ = promote_mod.decide_from_log(
        path, "somebody-else", **BOUNDS,
    )
    assert (action, reason) == ("hold", "insufficient_samples")


def test_decide_from_log_firing_alert_vetoes(tmp_path):
    # a firing shadow_regression alert (obs/alerts.py default rule)
    # demotes regardless of the window stats: mid-ride degradation
    # outranks a stale good comparison
    path = _ride_log(tmp_path, {
        "samples": 64, "auc_candidate": 0.9, "auc_incumbent": 0.5,
    })
    log = FleetLog(path)
    try:
        # the AlertEngine's transition-record shape (obs/alerts.py
        # `_record`): the rule name rides under "rule"
        log.append({"alert": {
            "rule": "shadow_regression", "state": "firing",
            "kind": "counter_rate", "window": "300s", "observed": 1.0,
            "threshold": 0.0, "for_s": 0.0,
            "t_unix": round(time.time(), 3),
        }})
    finally:
        log.close()
    action, reason, _ = promote_mod.decide_from_log(path, "cand", **BOUNDS)
    assert (action, reason) == ("demote", "alert")


def test_shadow_regression_rule_in_default_catalog():
    from deepdfa_tpu.obs.alerts import default_rules

    names = [r.name for r in default_rules()]
    assert "shadow_regression" in names


# ---------------------------------------------------------------------------
# default-off contract: flywheel off leaves the fleet path untouched


def test_router_flywheel_off_by_default(tmp_path):
    cfg = config_mod.Config()
    assert cfg.fleet.flywheel is False
    router = router_from_config(cfg, tmp_path / "fleet")
    try:
        assert router.flywheel is None
    finally:
        router.close()


def test_router_flywheel_wired_when_on(tmp_path):
    cfg = config_mod.apply_overrides(config_mod.Config(), [
        "fleet.flywheel=true", "fleet.flywheel_sample_rate=1.0",
    ])
    router = router_from_config(cfg, tmp_path / "fleet")
    try:
        assert router.flywheel is not None
        assert router.flywheel.period == 1
    finally:
        router.close()
    # close() tore the sampler down with the router
    assert router.flywheel is None


def test_shadow_replica_never_routable(tmp_path):
    now = time.time()
    hb = {"replica_id": "r0", "host": "h", "port": 1, "state": "ready",
          "t_unix": now}
    assert ReplicaView(dict(hb)).routable(10.0, now)
    view = ReplicaView({**hb, "shadow": True})
    assert view.shadow and not view.routable(10.0, now)
    # and the rollout controller skips it too: swapping the shadow
    # would score the comparison stream against itself
    from deepdfa_tpu.fleet import heartbeat
    from deepdfa_tpu.fleet.rollout import _ready_replicas

    heartbeat.write_heartbeat(tmp_path, "r0", "h", 1)
    heartbeat.write_heartbeat(tmp_path, "rs", "h", 2,
                              info={"shadow": True})
    assert sorted(_ready_replicas(tmp_path, 10.0)) == ["r0"]


def test_heartbeat_envelope_unchanged_by_default(tmp_path):
    # a non-shadow ReplicaWorker heartbeat carries no `shadow` key at
    # all — the default envelope is byte-identical to pre-flywheel
    view = ReplicaView({"replica_id": "r0", "host": "h", "port": 1,
                        "state": "ready", "t_unix": time.time()})
    assert "shadow" not in view.info


def test_schema_declares_flywheel_tags():
    for tag in ("shadow/samples", "shadow/dropped", "shadow/scored",
                "shadow/score_errors", "shadow/windows",
                "shadow/regressions", "shadow/agreement",
                "shadow/prob_drift", "shadow/lag_s",
                "shadow_agreement", "shadow_sample_lag_s",
                "flywheel/promote", "flywheel/demote", "flywheel/hold",
                "promotion/count", "demotion/count"):
        assert obs_metrics.declared(tag), tag


def test_bench_gate_bounds_shadow_metrics():
    from deepdfa_tpu.obs import bench_gate

    assert bench_gate.ABSOLUTE_UPPER_BOUNDS[
        "shadow_overhead_fraction"
    ] == 0.02
    assert "shadow_agreement" in bench_gate.DEFAULT_TOLERANCES
    assert "shadow_sample_lag_s" in bench_gate.LOWER_IS_BETTER


# ---------------------------------------------------------------------------
# retraining helpers: traffic profile -> weights -> selection


def test_traffic_weights_from_log(tmp_path):
    path = tmp_path / "fleet_log.jsonl"
    lines = [
        json.dumps({"request": {"id": f"q{i}", "status": 200,
                                "tenant": "interactive",
                                "prob": 0.05 + 0.1 * (i % 3)}})
        for i in range(6)
    ]
    lines.append(json.dumps({"request": {"id": "shed", "status": 503}}))
    lines.append("{torn")
    path.write_text("\n".join(lines) + "\n")
    profile = traffic_weights_from_log(path)
    assert profile["requests"] == 7
    assert profile["scored"] == 6
    assert profile["tenants"]["interactive"] == 6
    assert profile["tenants"]["default"] == 1  # the tenant-less shed
    assert sum(profile["prob_bands"]) == 6
    assert profile["prob_bands"][0] == 2  # the 0.05 scores


def test_band_of_clamps():
    assert band_of(-0.5) == 0
    assert band_of(0.05) == 0
    assert band_of(0.95) == 9
    assert band_of(1.5) == 9


def test_example_weights_floor_keeps_empty_bands():
    bands = [0] * 10
    bands[9] = 90
    w = example_weights([0.95, 0.05], bands)
    # hot band carries the traffic mass; empty band floored, not erased
    assert w[0] == 1.0
    assert 0 < w[1] < w[0]


def test_select_weighted_deterministic_and_proportional():
    weights = [1.0, 0.0, 3.0]
    picks = select_weighted(weights, 8, seed=3)
    assert picks == select_weighted(weights, 8, seed=3)
    assert len(picks) == 8
    assert 1 not in picks  # zero-weight index never drawn
    assert picks.count(2) > picks.count(0)


# ---------------------------------------------------------------------------
# diag section: the ride timeline + history rebuilt from records


def test_flywheel_section_from_records():
    from deepdfa_tpu.obs.diag import flywheel_section

    records = [
        {"shadow": {"event": "ride_start", "candidate": "c",
                    "incumbent": "incumbent", "t_unix": 1.0}},
        {"shadow": {"event": "window", "candidate": "c", "samples": 32,
                    "agreement": 0.9, "verdict": "promote",
                    "t_unix": 2.0}},
        {"shadow": {"event": "ride_end", "candidate": "c",
                    "t_unix": 3.0}},
        {"demotion": {"candidate": "old", "reason": "trailing",
                      "t_unix": 0.5}},
        {"promotion": {"candidate": "c", "rollout_ok": True,
                       "swapped": 2, "t_unix": 4.0}},
    ]
    section = flywheel_section(records)
    ride = section["rides"]["c"]
    assert ride["incumbent"] == "incumbent"
    assert ride["windows"] == 1 and ride["ended"]
    assert ride["timeline"][0]["verdict"] == "promote"
    assert [h["kind"] for h in section["history"]] == [
        "demotion", "promotion",
    ]
    assert flywheel_section([]) == {}
