"""Pallas-fused GGNN kernel (nn/ggnn_kernel.py) — numerics contract,
gradients, and the zero-steady-state-recompile invariant.

The contract under test (docs/ggnn_kernel.md):
- fp32 + fold scatter under the interpreter is BIT-IDENTICAL to the lax
  path under jit, for the whole DeepDFA forward, across the serve
  warmup ladder (including all-padding edge slots and single-node
  graphs) and for multi-etype graphs;
- the bf16 accumulation policy stays inside its documented bound;
- the custom_vjp gradients match jax.grad of the lax path;
- enabling the kernel adds no program signatures: train, serve scoring,
  and localization stay at zero steady-state recompiles (the PR-2/PR-5
  `jit_lowerings` guard plus the kernel's own trace census).
"""

import dataclasses
import json
import logging

import numpy as np
import pytest

from deepdfa_tpu.graphs import GraphSpec, pack
from deepdfa_tpu.nn import GatedGraphConv
from deepdfa_tpu.nn import ggnn_kernel as gk


def _random_graphs(rng, count=3, max_nodes=12):
    graphs = []
    for gid in range(count):
        n = int(rng.integers(3, max_nodes))
        e = int(rng.integers(2, 3 * n))
        graphs.append(
            GraphSpec(
                graph_id=gid,
                node_feats=rng.integers(0, 5, (n, 4)).astype(np.int32),
                node_vuln=np.zeros((n,), np.int32),
                edge_src=rng.integers(0, n, (e,)).astype(np.int32),
                edge_dst=rng.integers(0, n, (e,)).astype(np.int32),
                label=float(gid % 2),
            )
        )
    return graphs


def _single_node_graph(gid=0):
    return GraphSpec(
        graph_id=gid,
        node_feats=np.zeros((1, 4), np.int32),
        node_vuln=np.zeros((1,), np.int32),
        edge_src=np.zeros((0,), np.int32),
        edge_dst=np.zeros((0,), np.int32),
        label=1.0,
    )


def _model(hidden=8, n_steps=2, **kw):
    from deepdfa_tpu.models import DeepDFA

    return DeepDFA(input_dim=52, hidden_dim=hidden, n_steps=n_steps, **kw)


def _assert_bitwise(got, want, what):
    got = np.asarray(got, np.float32)
    want = np.asarray(want, np.float32)
    assert np.array_equal(
        got.view(np.uint32), want.view(np.uint32)
    ), f"{what}: max abs diff {np.abs(got - want).max()}"


def _warmup_ladder(rng):
    """The serve executor's batch shapes: every ladder size, including
    the all-padding batch every executor warms with and a single-node
    graph."""
    return {
        1: [[_single_node_graph()]],
        2: [_random_graphs(rng, 2), []],  # [] = all-padding warmup batch
        4: [_random_graphs(rng, 4)],
    }


def test_conv_bit_identical_across_warmup_ladder(rng):
    """The fused-step program is BIT-IDENTICAL to the jitted lax
    GatedGraphConv across the serve warmup ladder — the fold scatter
    reproduces sorted segment_sum's exact left fold, gather-then-
    transform equals transform-then-gather row-wise, and row-blocked
    GRU matmuls equal the full-table ones. This is the layer-program
    contract docs/ggnn_kernel.md states; the whole-model comparison
    below is last-ulp only (see its docstring for why)."""
    import jax

    node_budget, edge_budget = 512, 2048
    d, n_steps = 32, 5  # flagship step count, 4*hidden width
    conv = GatedGraphConv(out_features=d, n_steps=n_steps)
    conv_k = GatedGraphConv(out_features=d, n_steps=n_steps, use_kernel=True)
    init_batch = pack(_random_graphs(rng), 4, node_budget, edge_budget)
    feat0 = rng.standard_normal((node_budget, d)).astype(np.float32)
    params = conv.init(jax.random.key(0), init_batch, feat0)
    params_k = conv_k.init(jax.random.key(0), init_batch, feat0)
    # identical param trees by construction (parameter-only twins)
    for a, b in zip(
        jax.tree.leaves(params), jax.tree.leaves(params_k), strict=True
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    f_lax = jax.jit(lambda b, f: conv.apply(params, b, f))
    f_k = jax.jit(lambda b, f: conv_k.apply(params, b, f))
    for size, cases in _warmup_ladder(rng).items():
        for graphs in cases:
            batch = pack(graphs, size, node_budget, edge_budget)
            feat = rng.standard_normal(
                (node_budget, d)
            ).astype(np.float32)
            _assert_bitwise(
                f_k(batch, feat), f_lax(batch, feat),
                f"ladder size {size} ({len(graphs)} graphs)",
            )


def test_model_last_ulp_across_warmup_ladder(rng):
    """Whole-model DeepDFA logits, kernel vs lax, across the ladder.

    NOT asserted bitwise, deliberately: XLA CPU fuses each path's
    surrounding ops context-dependently (FMA formation around the
    embedding/pooling boundaries moves the last bits of BOTH paths —
    verified by comparing each path standalone vs embedded), so
    whole-program bit equality between two different HLO graphs is not
    a property XLA offers. The layer program IS pinned bitwise above;
    here the logits must agree to last-ulp float32."""
    import jax

    node_budget, edge_budget = 512, 2048
    m_lax = _model(n_steps=3)
    m_k = _model(n_steps=3, ggnn_kernel=True)
    init_batch = pack(_random_graphs(rng), 4, node_budget, edge_budget)
    params = m_lax.init(jax.random.key(0), init_batch)
    f_lax = jax.jit(lambda p, b: m_lax.apply(p, b))
    f_k = jax.jit(lambda p, b: m_k.apply(p, b))
    for size, cases in _warmup_ladder(rng).items():
        for graphs in cases:
            batch = pack(graphs, size, node_budget, edge_budget)
            np.testing.assert_allclose(
                np.asarray(f_k(params, batch)),
                np.asarray(f_lax(params, batch)),
                rtol=1e-5, atol=1e-6,
                err_msg=f"ladder size {size} ({len(graphs)} graphs)",
            )


def test_conv_multi_etype_bit_identical(rng):
    import jax

    d, n_steps, n, e = 8, 3, 10, 20
    g = GraphSpec(
        graph_id=0,
        node_feats=rng.integers(0, 5, (n, 4)).astype(np.int32),
        node_vuln=np.zeros((n,), np.int32),
        edge_src=rng.integers(0, n, (e,)).astype(np.int32),
        edge_dst=rng.integers(0, n, (e,)).astype(np.int32),
        label=0.0,
        edge_type=rng.integers(0, 3, (e,)).astype(np.int32),
    )
    batch = pack([g], 1, 16, 48)
    feats = rng.standard_normal((16, d)).astype(np.float32)
    conv = GatedGraphConv(out_features=d, n_steps=n_steps, n_etypes=3)
    conv_k = GatedGraphConv(
        out_features=d, n_steps=n_steps, n_etypes=3, use_kernel=True
    )
    params = conv.init(jax.random.key(7), batch, feats)
    want = jax.jit(lambda f: conv.apply(params, batch, f))(feats)
    got = jax.jit(lambda f: conv_k.apply(params, batch, f))(feats)
    _assert_bitwise(got, want, "n_etypes=3")


def test_fused_unroll_bit_identical_across_warmup_ladder(rng):
    """The whole-unroll fused kernel (all n_steps inside ONE
    pallas_call, state ping-ponged in VMEM) is BIT-IDENTICAL to the
    per-step kernel — and therefore to the lax path under fold —
    across the full serve warmup ladder, all-padding and single-node
    batches included. Fusion moves WHERE h lives between steps, not
    one arithmetic op."""
    import jax

    node_budget, edge_budget = 512, 2048
    d, n_steps = 32, 5
    conv = GatedGraphConv(out_features=d, n_steps=n_steps)
    conv_step = GatedGraphConv(
        out_features=d, n_steps=n_steps, use_kernel=True
    )
    conv_fused = GatedGraphConv(
        out_features=d, n_steps=n_steps, use_kernel=True,
        kernel_unroll="fused",
    )
    init_batch = pack(_random_graphs(rng), 4, node_budget, edge_budget)
    feat0 = rng.standard_normal((node_budget, d)).astype(np.float32)
    params = conv.init(jax.random.key(0), init_batch, feat0)
    f_lax = jax.jit(lambda b, f: conv.apply(params, b, f))
    f_step = jax.jit(lambda b, f: conv_step.apply(params, b, f))
    f_fused = jax.jit(lambda b, f: conv_fused.apply(params, b, f))
    for size, cases in _warmup_ladder(rng).items():
        for graphs in cases:
            batch = pack(graphs, size, node_budget, edge_budget)
            feat = rng.standard_normal(
                (node_budget, d)
            ).astype(np.float32)
            got = f_fused(batch, feat)
            _assert_bitwise(
                got, f_step(batch, feat),
                f"fused vs per-step, ladder size {size}",
            )
            _assert_bitwise(
                got, f_lax(batch, feat),
                f"fused vs lax, ladder size {size}",
            )


def test_bf16_policy_within_bound(rng):
    """The bf16 message-side policy (halved gather traffic, f32
    accumulation, f32 GRU state) stays inside the documented bound for
    both scatter modes."""
    import jax

    batch = pack(_random_graphs(rng), 4, 512, 2048)
    m_lax = _model()
    params = m_lax.init(jax.random.key(0), batch)
    want = np.asarray(jax.jit(lambda b: m_lax.apply(params, b))(batch))
    scale = max(float(np.abs(want).max()), 1e-6)
    for scatter in ("fold", "mxu"):
        m_bf16 = _model(
            ggnn_kernel=True, ggnn_kernel_scatter=scatter,
            ggnn_kernel_accum="bf16",
        )
        got = np.asarray(
            jax.jit(lambda b: m_bf16.apply(params, b))(batch)
        )
        rel = float(np.abs(got - want).max()) / scale
        assert rel < 0.05, f"bf16/{scatter} rel err {rel}"
        assert rel > 0.0  # the policy is actually engaged


def test_int8_policy_within_bound(rng):
    """True int8 MXU activations (per-row table scales, per-channel
    weight scales, int32 accumulation) stay inside INT8_DRIFT_BOUND
    for both scatter modes, per-step AND fused — and the bound is the
    SAME constant the tuner and the bench gate enforce."""
    import jax

    batch = pack(_random_graphs(rng), 4, 512, 2048)
    m_lax = _model()
    params = m_lax.init(jax.random.key(0), batch)
    want = np.asarray(jax.jit(lambda b: m_lax.apply(params, b))(batch))
    scale = max(float(np.abs(want).max()), 1e-6)
    for scatter in ("fold", "mxu"):
        for unroll in ("per_step", "fused"):
            m_int8 = _model(
                ggnn_kernel=True, ggnn_kernel_scatter=scatter,
                ggnn_kernel_accum="int8", ggnn_kernel_unroll=unroll,
            )
            got = np.asarray(
                jax.jit(lambda b: m_int8.apply(params, b))(batch)
            )
            rel = float(np.abs(got - want).max()) / scale
            assert rel < gk.INT8_DRIFT_BOUND, (
                f"int8/{scatter}/{unroll} rel err {rel}"
            )
            assert rel > 0.0  # the quantizer is actually engaged


def test_int8_and_vmem_constants_pinned():
    """The mirroring idiom's enforcement: the admission bound and the
    VMEM budget are each declared once next to the kernel and mirrored
    into the jax-free tuner/gate modules — these pins are what lets
    the mirrors exist without cross-layer imports."""
    from deepdfa_tpu.obs import bench_gate as bg
    from deepdfa_tpu.tune import kernel as tune_kernel

    assert gk.INT8_DRIFT_BOUND == tune_kernel.INT8_TOLERANCE
    assert gk.INT8_DRIFT_BOUND == bg.ABSOLUTE_UPPER_BOUNDS[
        "ggnn_kernel_int8_rel_err"
    ]
    assert gk.VMEM_LIMIT_BYTES == tune_kernel.DEFAULT_VMEM_LIMIT_BYTES
    # the tuner's fuller working-set estimate dominates the kernel's
    # own residency term at any signature, so an enumerate survivor is
    # always admitted by resolve_unroll — no mislabeled fused rows
    for n, d, steps in ((512, 32, 5), (2048, 128, 5), (16384, 128, 5)):
        cand = tune_kernel.Candidate(64, 128, "fold", "fp32", "fused")
        assert tune_kernel.estimate_vmem_bytes(
            n, 128, d, cand, n_steps=steps
        ) >= gk.fused_residency_bytes(n, d, "fp32", steps)


def test_resolve_unroll_admission():
    """The fused-unroll admission contract: unknown mode raises,
    per_step passes through, scan_steps and VMEM overflow both
    downgrade with a reason naming the rule."""
    common = dict(n=512, d=32, n_steps=5, accum="fp32")
    with pytest.raises(ValueError, match="unknown ggnn_kernel unroll"):
        gk.resolve_unroll("chunked", scan_steps=False, **common)
    assert gk.resolve_unroll(
        "per_step", scan_steps=False, **common
    ) == ("per_step", "")
    assert gk.resolve_unroll("fused", scan_steps=False, **common) == (
        "fused", ""
    )
    mode, why = gk.resolve_unroll("fused", scan_steps=True, **common)
    assert mode == "per_step" and "scan_steps" in why
    # scan at a single step has nothing to unroll differently: admitted
    assert gk.resolve_unroll(
        "fused", n=512, d=32, n_steps=1, accum="fp32", scan_steps=True
    ) == ("fused", "")
    mode, why = gk.resolve_unroll(
        "fused", scan_steps=False, vmem_limit_bytes=1024, **common
    )
    assert mode == "per_step" and "VMEM budget" in why
    # int8 residency adds the quantized shadow + row scales
    assert gk.fused_residency_bytes(512, 32, "int8", 5) > (
        gk.fused_residency_bytes(512, 32, "fp32", 5)
    )


def test_fused_fallback_is_loud(rng, caplog, monkeypatch):
    """A config that asks for the fused unroll but cannot have it
    (VMEM overflow here) serves the per-step kernel with identical
    numerics — and says so: a warning naming the reason plus the
    ggnn_kernel/fused_fallbacks counter."""
    import jax

    from deepdfa_tpu.obs import metrics as obs_metrics

    monkeypatch.setattr(gk, "VMEM_LIMIT_BYTES", 1024)
    batch = pack(_random_graphs(rng), 4, 512, 2048)
    m_lax = _model()
    m_fused = _model(ggnn_kernel=True, ggnn_kernel_unroll="fused")
    params = m_lax.init(jax.random.key(0), batch)
    before = obs_metrics.REGISTRY.counter(
        "ggnn_kernel/fused_fallbacks"
    ).value
    with caplog.at_level(
        logging.WARNING, logger="deepdfa_tpu.nn.ggnn_kernel"
    ):
        got = jax.jit(lambda b: m_fused.apply(params, b))(batch)
    assert any(
        "fused unroll unavailable" in r.message and "VMEM" in r.message
        for r in caplog.records
    ), caplog.records
    assert obs_metrics.REGISTRY.counter(
        "ggnn_kernel/fused_fallbacks"
    ).value > before
    # the fallback resolves to the per-step kernel's exact program, so
    # bitwise holds against it (vs the lax model whole-model logits are
    # only last-ulp: XLA fuses surrounding ops context-dependently)
    m_step = _model(ggnn_kernel=True)
    _assert_bitwise(
        got, jax.jit(lambda b: m_step.apply(params, b))(batch),
        "fallback per-step output",
    )


def test_fused_scan_steps_falls_back_loudly(rng, caplog):
    """scan_steps asked for a bounded trace; the fused backward
    re-unrolls every step, so the combination downgrades to the
    per-step kernel under lax.scan — loudly — and the scanned forward
    stays bit-identical to the per-step-kernel twin (the exact program
    the fallback resolves to)."""
    import jax

    node_budget, edge_budget, d = 512, 2048, 32
    conv_step = GatedGraphConv(
        out_features=d, n_steps=3, scan_steps=True, use_kernel=True
    )
    conv_both = GatedGraphConv(
        out_features=d, n_steps=3, scan_steps=True, use_kernel=True,
        kernel_unroll="fused",
    )
    batch = pack(_random_graphs(rng), 4, node_budget, edge_budget)
    feat = rng.standard_normal((node_budget, d)).astype(np.float32)
    # init through the unroll twin: the param tree is identical and
    # flax cannot create the GRU's submodules inside lax.scan in
    # mutable init mode (the test_nn_parity scan pattern)
    conv_init = GatedGraphConv(out_features=d, n_steps=3)
    params = conv_init.init(jax.random.key(0), batch, feat)
    with caplog.at_level(
        logging.WARNING, logger="deepdfa_tpu.nn.ggnn_kernel"
    ):
        got = jax.jit(lambda b, f: conv_both.apply(params, b, f))(
            batch, feat
        )
    assert any(
        "scan_steps" in r.message for r in caplog.records
    ), caplog.records
    _assert_bitwise(
        got,
        jax.jit(lambda b, f: conv_step.apply(params, b, f))(batch, feat),
        "fused-under-scan fallback",
    )


def test_fused_grads_bit_identical_to_per_step(rng):
    """The fused unroll's custom_vjp (chain residuals + per-step
    backward sweeps in reverse) produces BIT-IDENTICAL cotangents to
    the per-step kernel chain, whole model, every param leaf — and
    therefore matches the lax path inside the per-step bound."""
    import jax
    import jax.numpy as jnp

    batch = pack(_random_graphs(rng), 4, 512, 2048)
    m_step = _model(n_steps=3, ggnn_kernel=True)
    m_fused = _model(
        n_steps=3, ggnn_kernel=True, ggnn_kernel_unroll="fused"
    )
    params = m_step.init(jax.random.key(0), batch)
    labels = jnp.asarray(batch.graph_label)

    def loss(model, p):
        logits = model.apply(p, batch)
        return jnp.sum(
            jnp.where(
                jnp.asarray(batch.graph_mask),
                (jax.nn.sigmoid(logits) - labels) ** 2, 0.0,
            )
        )

    g_step = jax.jit(jax.grad(lambda p: loss(m_step, p)))(params)
    g_fused = jax.jit(jax.grad(lambda p: loss(m_fused, p)))(params)
    flat_step = jax.tree_util.tree_leaves_with_path(g_step)
    flat_fused = jax.tree.leaves(g_fused)
    for (path, want), got in zip(flat_step, flat_fused, strict=True):
        _assert_bitwise(
            got, want, f"grad {jax.tree_util.keystr(path)}"
        )


def test_grads_match_lax_path(rng):
    """custom_vjp gradients vs jax.grad of the lax path, whole model
    (embedding + fused steps + pooling + head), every param leaf."""
    import jax
    import jax.numpy as jnp

    batch = pack(_random_graphs(rng), 4, 512, 2048)
    m_lax = _model()
    m_k = _model(ggnn_kernel=True)
    params = m_lax.init(jax.random.key(0), batch)
    labels = jnp.asarray(batch.graph_label)

    def loss(model, p):
        logits = model.apply(p, batch)
        return jnp.sum(
            jnp.where(
                jnp.asarray(batch.graph_mask),
                (jax.nn.sigmoid(logits) - labels) ** 2, 0.0,
            )
        )

    g_lax = jax.jit(jax.grad(lambda p: loss(m_lax, p)))(params)
    g_k = jax.jit(jax.grad(lambda p: loss(m_k, p)))(params)
    flat_lax = jax.tree_util.tree_leaves_with_path(g_lax)
    flat_k = jax.tree.leaves(g_k)
    assert len(flat_lax) == len(flat_k)
    for (path, want), got in zip(flat_lax, flat_k, strict=True):
        want = np.asarray(want)
        got = np.asarray(got)
        scale = max(float(np.abs(want).max()), 1e-8)
        err = float(np.abs(got - want).max()) / scale
        assert err < 1e-3, f"{jax.tree_util.keystr(path)}: rel err {err}"


def test_kernel_rejects_edge_sharding():
    import jax

    conv = GatedGraphConv(
        out_features=4, n_steps=1, use_kernel=True, axis_name="dp"
    )
    g = _single_node_graph()
    batch = pack([g], 1, 8, 16)
    feats = np.zeros((8, 4), np.float32)
    with pytest.raises(ValueError, match="edge-sharded"):
        conv.init(jax.random.key(0), batch, feats)


@pytest.mark.parametrize("unroll", ("per_step", "fused"))
def test_zero_steady_state_recompiles_train(rng, tmp_path, unroll):
    """Two epochs at one batch signature with the kernel on (both
    unroll modes): the lowering census after epoch 1 never grows, and
    the epoch record carries the per-signature compile/step
    counters."""
    import jax  # noqa: F401

    from deepdfa_tpu.core import Config, config as config_mod
    from deepdfa_tpu.data import build_dataset, generate, to_examples
    from deepdfa_tpu.graphs import shard_bucket_batches
    from deepdfa_tpu.models import DeepDFA
    from deepdfa_tpu.train import GraphTrainer

    synth = generate(8, seed=0)
    specs, _ = build_dataset(
        to_examples(synth), train_ids=range(8), limit_all=50,
        limit_subkeys=50,
    )
    cfg = config_mod.apply_overrides(Config(), [
        "train.max_epochs=2",
        "model.hidden_dim=8", "model.n_steps=2",
        "model.ggnn_kernel=true",
        f"model.ggnn_kernel_unroll={json.dumps(unroll)}",
    ])
    from deepdfa_tpu.core.config import MeshConfig
    from deepdfa_tpu.parallel import make_mesh

    model = DeepDFA.from_config(cfg.model, input_dim=52)
    trainer = GraphTrainer(
        model, cfg,
        mesh=make_mesh(MeshConfig(dp=1), devices=jax.devices()[:1]),
    )

    def batches(_e=0):
        return shard_bucket_batches(
            specs, 1, 4, 1024, 4096, oversized="raise"
        )

    gk.reset_signature_stats()
    state = trainer.init_state(next(iter(batches())))
    records = []
    trainer.fit(state, batches, log_fn=records.append)
    epoch_recs = [r for r in records if "ggnn_kernel" in r]
    assert len(epoch_recs) == 2
    first, second = (r["ggnn_kernel"] for r in epoch_recs)
    sig_keys = [k for k in first if k.startswith("signatures/")]
    assert sig_keys, first
    # epoch 2 re-traces nothing: the census is frozen after epoch 1
    for k in sig_keys:
        assert second[k] == first[k], (k, first, second)
    assert second["lowerings"] == first["lowerings"]
    assert second["device_steps"] == first["device_steps"] > 0


@pytest.mark.parametrize("unroll", ("per_step", "fused"))
def test_zero_steady_state_recompiles_serve_and_localize(rng, unroll):
    """Warmed GgnnExecutor + GgnnLocalizer with the kernel enabled
    (both unroll modes): arbitrary request mixes trigger no lowering
    after warmup, on either ladder (the PR-5/PR-7 invariant, now with
    the fused step — or the whole fused unroll — inside)."""
    import jax

    from deepdfa_tpu.serve.batcher import GgnnExecutor
    from deepdfa_tpu.serve.frontend import Features
    from deepdfa_tpu.serve.localize import GgnnLocalizer

    node_budget, edge_budget = 512, 2048
    model = _model(ggnn_kernel=True, ggnn_kernel_unroll=unroll)
    init_batch = pack(_random_graphs(rng), 4, node_budget, edge_budget)
    params = model.init(jax.random.key(0), init_batch)

    ex = GgnnExecutor(
        model, lambda: params, node_budget, edge_budget,
        max_batch_graphs=4,
    )
    ex.warmup()
    loc = GgnnLocalizer(
        model, lambda: params, node_budget, edge_budget,
        sizes=ex.sizes, method="saliency", n_steps=2,
    )
    loc.warmup()
    warm_lowerings = (ex.jit_lowerings(), loc.jit_lowerings())
    census = gk.signature_stats()
    assert census  # the kernel actually traced during warmup

    for count in (1, 3, 2, 4, 1):
        graphs = _random_graphs(rng, count)
        probs = ex.execute("graph", graphs)
        assert probs.shape == (count,)
        feats = [
            Features(spec=g, node_lines=np.arange(1, g.num_nodes + 1))
            for g in graphs
        ]
        out = loc.attribute(feats)
        assert len(out) == count
    assert (ex.jit_lowerings(), loc.jit_lowerings()) == warm_lowerings
    assert gk.signature_stats() == census

    # served-vs-offline parity rides the existing contract: the warmed
    # executable IS ggnn_score_fn jitted — spot-check one singleton
    from deepdfa_tpu.eval.localize import ggnn_score_fn

    g = _random_graphs(rng, 1)[0]
    offline = jax.jit(ggnn_score_fn("saliency", model, 2))(
        params, pack([g], 1, node_budget, edge_budget)
    )
    prob, lines = loc.attribute(
        [Features(spec=g, node_lines=np.arange(1, g.num_nodes + 1))]
    )[0]
    assert prob == float(np.asarray(offline[0])[0])


def test_schema_declares_kernel_tags():
    from deepdfa_tpu.obs.metrics import declared

    for tag in (
        "ggnn_kernel/lowerings",
        "ggnn_kernel/device_steps",
        "ggnn_kernel/signatures/512x2048x32",
        "obs/ggnn_kernel/lowerings",
        "roofline/gather_gbps_measured",
    ):
        assert declared(tag), tag


def test_bench_scatter_smoke(rng):
    """Tier-1 end-to-end (the bench_prefetch convention):
    scripts/bench_scatter.py --smoke asserts the numerics contract and
    emits the gate fields bench.py --child-scatter records."""
    from tests.conftest import load_script_module

    bench_scatter = load_script_module("bench_scatter")
    rec = bench_scatter.run_smoke()
    assert rec["ggnn_kernel_rel_err"] == 0.0
    assert rec["ggnn_step_us"] > 0 and rec["ggnn_lax_step_us"] > 0
    # the ISSUE-16 variants ride the same record: fused unroll timed
    # (bit-identical off-TPU, asserted inside run_smoke) and the int8
    # drift measured under the admission bound the gate enforces
    assert rec["ggnn_unroll_step_us"] > 0
    assert rec["ggnn_kernel_unroll_rel_err"] == 0.0
    assert rec["ggnn_kernel_int8_ok"] is True
    assert rec["ggnn_kernel_int8_rel_err"] <= gk.INT8_DRIFT_BOUND
    assert rec["ggnn_unroll_speedup"] > 0
    assert "ggnn_mfu" in rec or "ggnn_roofline_error" in rec
    if "ggnn_mfu" in rec:
        # the ceiling probes mirror their measurements into the
        # declared roofline/* gauges (obs/metrics.py SCHEMA)
        from deepdfa_tpu.obs import metrics as obs_metrics

        snap = obs_metrics.REGISTRY.snapshot()
        assert "roofline/matmul_tflops_measured" in snap
        assert "roofline/gather_gbps_measured" in snap
