"""IVDetect per-line feature dump (eval/ivdetect.py) vs the reference's
feature_extraction semantics (DDFA/sastvd/helpers/evaluate.py:19-191).

Note the IVDetect tokeniser (frontend/tokenise.py, a cited port) drops
single-character subtokens — expectations below use multi-char names.
"""

import json

from deepdfa_tpu.eval.ivdetect import (
    dump_features,
    feature_extraction_code,
)

CODE = """int scale(int nval, int kval) {
  int acc = 0;
  int step = kval + 1;
  if (nval > 10) {
    acc = nval * step;
  }
  return acc;
}
"""


def rows_by_line(code=CODE):
    rows, pdg = feature_extraction_code(code)
    return {r.line: r for r in rows}, pdg


def test_every_statement_line_has_a_row():
    rows, _ = rows_by_line()
    # line 6 is a lone closing brace: no nodes, no row (as in the
    # reference, whose nodes df has nothing there either)
    assert {2, 3, 4, 5, 7} <= set(rows)
    assert 6 not in rows


def test_subseq_is_tokenised_line_code_with_decl_type_prefix():
    rows, _ = rows_by_line()
    toks = rows[3].subseq.split()
    # longest code on line 3 is "step = kval + 1"; declared type prefixes
    assert toks[0] == "int"
    assert "step" in toks and "kval" in toks
    assert "=" not in rows[3].subseq  # tokenisation strips punctuation


def test_nametypes_pairs_types_with_identifiers():
    rows, _ = rows_by_line()
    toks = rows[2].nametypes.split()
    assert "int" in toks and "acc" in toks


def test_intra_line_ast_is_rooted_and_indexed():
    rows, _ = rows_by_line()
    parents, children, codes = rows[5].ast
    n = len(codes)
    assert len(parents) == len(children) > 0
    assert all(0 <= i < n for i in parents + children)
    # re-rooting: every non-zero node is reachable as a child
    assert set(range(1, n)) <= set(children)


def test_data_context_follows_reaching_defs():
    rows, _ = rows_by_line()
    # step (line 3) flows into line 5's assignment; line 5 flows into the
    # return on line 7. Symmetrized, line 5's data context has both.
    assert 3 in rows[5].data
    assert 7 in rows[5].data
    assert 5 in rows[3].data  # undirected view


def test_control_context_ties_branch_body_to_condition():
    rows, _ = rows_by_line()
    assert 4 in rows[5].control  # line 5 is control-dependent on the if
    assert 5 in rows[4].control  # symmetrized


def test_pdg_edges_are_line_level_and_consistent():
    rows, (src, dst) = rows_by_line()
    assert len(src) == len(dst) > 0
    lines = set(rows)
    assert set(src) <= lines and set(dst) <= lines


def test_dump_features_json_roundtrip(tmp_path):
    out = tmp_path / "feat.json"
    dump_features(CODE, out)
    rec = json.loads(out.read_text())
    assert {"lines", "pdg_edges"} <= set(rec)
    assert [row["line"] for row in rec["lines"]] == sorted(
        row["line"] for row in rec["lines"]
    )
    assert all(
        {"line", "subseq", "ast", "nametypes", "data", "control"}
        <= set(row)
        for row in rec["lines"]
    )
