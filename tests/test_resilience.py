"""Resilience runtime (train/resilience.py): step-granular
checkpoint/resume equivalence, divergence-guard skip/rollback counters,
watchdog stall detection, preemption handling, and the hardened
checkpoint manifest (ISSUE 3). All tier-1, CPU, in-process."""

import dataclasses
import json
import time

import numpy as np
import pytest

from deepdfa_tpu.core import Config, MeshConfig, config as config_mod
from deepdfa_tpu.core.config import ResilienceConfig
from deepdfa_tpu.core.ioutil import atomic_write_text, with_retries
from deepdfa_tpu.graphs import GraphSpec, shard_bucket_batches
from deepdfa_tpu.train.resilience import (
    DivergenceError,
    Preempted,
    ResilientRunner,
    ResumeCursor,
    StepCheckpointer,
    Watchdog,
)


def _graphs(n=24, seed=0):
    rng = np.random.default_rng(seed)
    out = []
    for gid in range(n):
        m = int(rng.integers(4, 10))
        feats = rng.integers(2, 20, (m, 4)).astype(np.int32)
        vuln = np.zeros((m,), np.int32)
        if gid % 2 == 0:
            feats[0, 0] = 7
            vuln[0] = 1
        out.append(GraphSpec(
            graph_id=gid, node_feats=feats, node_vuln=vuln,
            edge_src=np.arange(m - 1, dtype=np.int32),
            edge_dst=np.arange(1, m, dtype=np.int32),
            label=float(vuln.max()),
        ))
    return out


def _batches(specs):
    return list(shard_bucket_batches(
        specs, num_shards=1, num_graphs=4, node_budget=64, edge_budget=256,
    ))


RES_CFG = (
    'train.resilience={"enabled": true, "step_checkpoint_every": 2, '
    '"guard_lag": 1}'
)


def _cfg(*extra):
    return config_mod.apply_overrides(Config(), [
        "model.hidden_dim=8",
        "train.max_epochs=3",
        "train.prefetch_batches=0",
        "train.log_every_steps=1",
        RES_CFG,
        *extra,
    ])


@pytest.fixture(scope="module")
def tiny():
    """(cfg, model, mesh, batches_fn) — one compile for the module."""
    import jax

    from deepdfa_tpu.models import DeepDFA
    from deepdfa_tpu.parallel import make_mesh

    cfg = _cfg()
    model = DeepDFA.from_config(cfg.model, input_dim=32)
    mesh = make_mesh(MeshConfig(dp=1), devices=jax.devices()[:1])
    specs = _graphs()
    return cfg, model, mesh, lambda _e: _batches(specs)


def _fit(tiny, ckpt_dir, injector=None, cfg=None, log=None):
    from deepdfa_tpu.train import GraphTrainer

    base_cfg, model, mesh, batches = tiny
    cfg = cfg if cfg is not None else base_cfg
    trainer = GraphTrainer(model, cfg, mesh=mesh)
    state = trainer.init_state(batches(0)[0])
    runner = ResilientRunner(cfg.train.resilience, ckpt_dir, seed=cfg.train.seed)
    stream = batches if injector is None else (
        lambda e: injector.wrap(batches(e))
    )
    state = trainer.fit(state, stream, log_fn=log, resilience=runner)
    return state, runner


# -- crash/resume equivalence (the tentpole acceptance test) ----------------


def test_sigterm_resume_reproduces_uninterrupted_trajectory(tiny, tmp_path):
    """Kill mid-epoch via the fault harness, resume from the manifest,
    and the merged per-step loss trajectory is BIT-IDENTICAL to an
    uninterrupted run of the same config/seed."""
    from deepdfa_tpu.testing.faults import FaultInjector, FaultPlan

    ref = []
    _fit(tiny, tmp_path / "ref",
         log=lambda r: ref.append((r["step"], r["loss"])) if "loss" in r else None)
    assert len(ref) >= 10
    kill_at = len(ref) // 2

    run_dir = tmp_path / "faulted"
    first = []
    with pytest.raises(Preempted):
        _fit(tiny, run_dir,
             injector=FaultInjector(FaultPlan(sigterm_at_step=kill_at)),
             log=lambda r: first.append((r["step"], r["loss"])) if "loss" in r else None)
    manifest = json.loads((run_dir / "resume.json").read_text())
    assert manifest["step"] == kill_at
    assert manifest["reason"] == "preempt"

    second = []
    _, runner = _fit(tiny, run_dir,
                     log=lambda r: second.append((r["step"], r["loss"])) if "loss" in r else None)
    assert runner.resumed_from_step == kill_at
    assert first + second == ref  # bit-exact float equality, on purpose


def test_completed_run_resume_is_noop(tiny, tmp_path):
    """finish() leaves a final cursor past the last epoch, so re-running
    a COMPLETED run trains zero further steps (idempotent completion)."""
    steps_a: list = []
    _fit(tiny, tmp_path / "done",
         log=lambda r: steps_a.append(r) if "loss" in r else None)
    steps_b: list = []
    _, runner = _fit(tiny, tmp_path / "done",
                     log=lambda r: steps_b.append(r) if "loss" in r else None)
    assert steps_a and not steps_b
    assert runner.resumed_from_step == steps_a[-1]["step"]


def test_resume_step_continuity_after_guard_skip(tiny, tmp_path):
    """A guard-skipped step leaves state.step one behind the host/data
    step; resume must continue from the DATA cursor (manifest step), or
    RNG folding, checkpoint cadence, and tag ordering drift after every
    skip."""
    from deepdfa_tpu.testing.faults import FaultInjector, FaultPlan

    run_dir = tmp_path / "skip-resume"
    with pytest.raises(Preempted):
        _fit(tiny, run_dir, injector=FaultInjector(FaultPlan(
            nan_at_steps=frozenset({3}), sigterm_at_step=6,
        )))
    man = json.loads((run_dir / "resume.json").read_text())
    assert man["step"] == 6  # the data cursor, NOT state.step (== 5)

    steps: list[int] = []
    _fit(tiny, run_dir,
         log=lambda r: steps.append(r["step"]) if "loss" in r else None)
    assert steps and steps[0] == 7  # continues at the cursor, no rewind
    final = json.loads((run_dir / "resume.json").read_text())
    assert final["reason"] == "final"
    assert final["step"] == steps[-1]


def test_resume_refuses_foreign_seed(tiny, tmp_path):
    cfg, model, mesh, batches = tiny
    _fit(tiny, tmp_path / "seeded")
    other = dataclasses.replace(
        cfg, train=dataclasses.replace(cfg.train, seed=cfg.train.seed + 1)
    )
    _, runner = _fit(tiny, tmp_path / "seeded", cfg=other)
    # foreign manifest ignored: the run trained from scratch
    assert runner.resumed_from_step == 0


# -- divergence guard -------------------------------------------------------


def test_guard_skips_nan_steps_and_keeps_params_finite(tiny, tmp_path):
    import jax

    from deepdfa_tpu.testing.faults import FaultInjector, FaultPlan

    records: list = []
    state, runner = _fit(
        tiny, tmp_path / "nan",
        injector=FaultInjector(FaultPlan(nan_at_steps=frozenset({3, 4}))),
        log=lambda r: records.append(r) if "train_loss" in r else None,
    )
    assert runner.skipped_steps == 2
    assert runner.rollbacks == 0
    leaves = jax.tree.leaves(jax.device_get(state.params))
    assert all(np.isfinite(x).all() for x in leaves)
    # the survived epoch's aggregate excludes the poisoned losses — a
    # self-healed epoch must not report train_loss=NaN
    assert records and all(np.isfinite(r["train_loss"]) for r in records)
    assert records[0]["skipped_steps"] == 2


def test_guard_rolls_back_after_k_consecutive_bad_steps(tiny, tmp_path):
    from deepdfa_tpu.testing.faults import FaultInjector, FaultPlan

    cfg = _cfg(
        'train.resilience={"enabled": true, "step_checkpoint_every": 2, '
        '"guard_lag": 0, "max_consecutive_bad": 2, "rollback_budget": 3, '
        '"lr_cooldown": 0.25}'
    )
    state, runner = _fit(
        tiny, tmp_path / "rb", cfg=cfg,
        injector=FaultInjector(
            FaultPlan(nan_at_steps=frozenset({4, 5, 6}))
        ),
    )
    assert runner.skipped_steps == 3
    # 2 consecutive bad -> one rollback (counter resets), 3rd bad alone
    # stays under the threshold
    assert runner.rollbacks == 1
    assert runner.lr_scale() == 0.25
    assert runner.record()["rollbacks"] == 1


def test_guard_rollback_budget_exhaustion_raises(tiny, tmp_path):
    from deepdfa_tpu.testing.faults import FaultInjector, FaultPlan

    cfg = _cfg(
        'train.resilience={"enabled": true, "step_checkpoint_every": 2, '
        '"guard_lag": 0, "max_consecutive_bad": 1, "rollback_budget": 1}'
    )
    with pytest.raises(DivergenceError):
        _fit(
            tiny, tmp_path / "budget", cfg=cfg,
            injector=FaultInjector(
                FaultPlan(nan_at_steps=frozenset(range(2, 12)))
            ),
        )


def test_guard_state_survives_preemption(tiny, tmp_path):
    """A cooled-down LR and spent rollback budget ride the resume
    manifest — a preempt/diverge cycle cannot restart at full LR with a
    fresh budget forever."""
    from deepdfa_tpu.testing.faults import FaultInjector, FaultPlan

    cfg = _cfg(
        'train.resilience={"enabled": true, "step_checkpoint_every": 2, '
        '"guard_lag": 0, "max_consecutive_bad": 1, "rollback_budget": 5, '
        '"lr_cooldown": 0.5}'
    )
    run_dir = tmp_path / "guard-resume"
    with pytest.raises(Preempted):
        _fit(tiny, run_dir, cfg=cfg, injector=FaultInjector(FaultPlan(
            nan_at_steps=frozenset({3}), sigterm_at_step=6,
        )))
    man = json.loads((run_dir / "resume.json").read_text())
    assert man["guard"] == {
        "lr_scale": 0.5, "rollbacks": 1, "skipped_steps": 1,
    }
    _, runner = _fit(tiny, run_dir, cfg=cfg)
    assert runner.lr_scale() == 0.5
    assert runner.rollbacks == 1 and runner.skipped_steps == 1


def test_combined_train_step_public_contract_under_guard():
    """With the guard built in, CombinedTrainer.train_step still returns
    the legacy (state, loss) pair for external callers (bench scripts);
    the fit loop opts into the ok flag with with_ok=True."""
    import jax

    from deepdfa_tpu.data.text import collate_shards
    from deepdfa_tpu.models import combined as cmb
    from deepdfa_tpu.models.transformer import TransformerConfig
    from deepdfa_tpu.parallel import make_mesh
    from deepdfa_tpu.train.combined_loop import CombinedTrainer

    cfg = _cfg()
    mcfg = cmb.CombinedConfig(
        encoder=TransformerConfig.tiny(
            vocab_size=64, max_position_embeddings=20, num_layers=1,
            hidden_size=16, num_heads=2,
        ),
        graph_hidden_dim=8, graph_input_dim=102, use_graph=False,
    )
    mesh = make_mesh(MeshConfig(dp=1), devices=jax.devices()[:1])
    trainer = CombinedTrainer(cfg, mcfg, mesh=mesh, total_steps=2)
    assert trainer.guard_active
    rng = np.random.default_rng(0)
    mat = rng.integers(5, 60, (4, 16)).astype(np.int32)
    batch = collate_shards(
        mat, [0, 1, 0, 1], [0, 1, 2, 3], {}, num_shards=1,
        rows_per_shard=4, node_budget=32, edge_budget=64, pad_id=1,
    )
    out = trainer.train_step(
        trainer.init_state(), trainer.place_batch(batch), jax.random.key(0)
    )
    assert len(out) == 2  # legacy contract preserved
    out = trainer.train_step(
        out[0], trainer.place_batch(batch), jax.random.key(1), 1.0,
        with_ok=True,
    )
    assert len(out) == 3 and bool(jax.device_get(out[2]))


# -- watchdog ---------------------------------------------------------------


def test_watchdog_fires_on_silence_with_stage_attribution(tmp_path):
    fired = []
    wd = Watchdog(
        timeout_s=0.2, on_stall=fired.append,
        diagnostic_path=tmp_path / "diag.json",
        first_step_grace_s=0.2,
    )
    wd.start()
    try:
        wd.beat("input", step=7)
        time.sleep(1.0)
    finally:
        wd.stop()
    assert len(fired) == 1
    diag = fired[0]
    assert diag["stalled_stage"] == "input"
    assert diag["step"] == 7
    on_disk = json.loads((tmp_path / "diag.json").read_text())
    assert on_disk["stalled_stage"] == "input"


def test_watchdog_first_step_grace_covers_compiles(tmp_path):
    """Silence during the FIRST step (jit compile) is tolerated up to
    the grace bound; after step_done() the steady-state timeout rules."""
    fired = []
    wd = Watchdog(
        timeout_s=0.1, on_stall=fired.append, first_step_grace_s=5.0
    )
    wd.start()
    try:
        wd.beat("device")
        time.sleep(0.5)  # past timeout_s, within the first-step grace
        assert not fired
        wd.step_done()
        wd.beat("device")
        time.sleep(0.5)
    finally:
        wd.stop()
    assert len(fired) == 1 and fired[0]["stalled_stage"] == "device"


def test_watchdog_stays_quiet_under_heartbeats(tmp_path):
    fired = []
    wd = Watchdog(
        timeout_s=0.3, on_stall=fired.append, first_step_grace_s=0.3
    )
    wd.start()
    try:
        for _ in range(8):
            wd.beat("device")
            time.sleep(0.05)
    finally:
        wd.stop()
    assert not fired


def test_watchdog_detects_stalled_input_in_fit(tiny, tmp_path):
    """A stalled source trips the watchdog with the input stage blamed
    (injected on_stall; the default hard-aborts the process)."""
    import jax

    from deepdfa_tpu.data.prefetch import device_placer
    from deepdfa_tpu.testing.faults import StalledSource
    from deepdfa_tpu.train import GraphTrainer

    cfg = _cfg(
        "train.max_epochs=1",
        'train.resilience={"enabled": true, "step_checkpoint_every": 0, '
        '"watchdog_timeout_s": 0.5}',
    )
    _, model, mesh, batches = tiny
    trainer = GraphTrainer(model, cfg, mesh=mesh)
    state = trainer.init_state(batches(0)[0])
    # warm both guarded-step signatures (init sharding + post-step
    # sharding) OUTSIDE the watchdog window: the first-step compile takes
    # seconds and would trip a 0.5s watchdog as a device stall
    placer = device_placer(mesh)
    warm = trainer.init_state(batches(0)[0])
    for _ in range(2):
        warm, _loss, _ok = trainer.train_step_guarded(
            warm, placer(batches(0)[0]), 1.0
        )
    jax.block_until_ready(warm.params)
    stalled = StalledSource(batches(0), n_good=2)
    fired = []

    def on_stall(diag):
        fired.append(diag)
        stalled.release()  # un-wedge so the test finishes

    runner = ResilientRunner(
        cfg.train.resilience, tmp_path / "wd", seed=0, on_stall=on_stall
    )
    trainer.fit(state, lambda e: stalled, resilience=runner)
    assert fired and fired[0]["stalled_stage"] == "input"
    assert "pipeline" in fired[0]  # PipelineStats snapshot attached


# -- step checkpointer ------------------------------------------------------


def _dummy_state():
    return {"w": np.arange(6, dtype=np.float32).reshape(2, 3)}


def test_step_checkpointer_retention_and_latest(tmp_path):
    ck = StepCheckpointer(tmp_path, keep_last=2)
    for s in (2, 4, 6):
        ck.save(_dummy_state(), ResumeCursor(0, s, s), seed=1)
    tags = sorted(p.name for p in tmp_path.glob("step-*") if p.is_dir())
    assert tags == ["step-00000004", "step-00000006"]  # keep-last-2
    latest = ck.latest()
    assert latest["step"] == 6 and latest["seed"] == 1
    restored = ck.restore(latest, _dummy_state())
    np.testing.assert_array_equal(restored["w"], _dummy_state()["w"])


def test_step_checkpointer_rebuilds_corrupt_resume_manifest(tmp_path):
    ck = StepCheckpointer(tmp_path, keep_last=3)
    ck.save(_dummy_state(), ResumeCursor(1, 3, 8), seed=0)
    (tmp_path / "resume.json").write_text("{truncated")
    latest = StepCheckpointer(tmp_path).latest()
    assert latest is not None and latest["step"] == 8
    # and the manifest was re-written durably
    assert json.loads((tmp_path / "resume.json").read_text())["step"] == 8


def test_step_checkpointer_ignores_save_without_sidecar(tmp_path):
    ck = StepCheckpointer(tmp_path, keep_last=3)
    ck.save(_dummy_state(), ResumeCursor(0, 1, 2), seed=0)
    # a crash mid-save leaves a dir but no sidecar: never the resume point
    (tmp_path / "step-00000009").mkdir()
    (tmp_path / "resume.json").unlink()
    assert StepCheckpointer(tmp_path).latest()["step"] == 2


# -- hardened epoch CheckpointManager (satellite) ---------------------------


def test_checkpoint_manifest_atomic_and_corruption_tolerant(tmp_path):
    from deepdfa_tpu.train import CheckpointManager

    mgr = CheckpointManager(tmp_path, monitor="val_loss", mode="min")
    params = _dummy_state()
    assert mgr.save("epoch-0000", params, {"val_loss": 1.0}, step=1)
    assert mgr.save("epoch-0001", params, {"val_loss": 0.5}, step=2)
    # corrupt the manifest the way a crash mid-write used to
    (tmp_path / "manifest.json").write_text('{"best": {"tag"')
    rebuilt = CheckpointManager(tmp_path, monitor="val_loss", mode="min")
    tags = [e["tag"] for e in rebuilt._manifest["history"]]
    assert tags == ["epoch-0000", "epoch-0001"]
    # best dir survived and is restorable even with metrics unknown
    restored = rebuilt.restore("best", _dummy_state())
    np.testing.assert_array_equal(restored["w"], params["w"])
    # with no recorded metric, the next save wins best (safe direction)
    assert rebuilt.save("epoch-0002", params, {"val_loss": 9.0}, step=3)


def test_checkpoint_keep_last_retention(tmp_path):
    from deepdfa_tpu.train import CheckpointManager

    mgr = CheckpointManager(
        tmp_path, monitor="val_loss", mode="min", keep_last=2
    )
    params = _dummy_state()
    for i, v in enumerate([3.0, 2.0, 1.0, 4.0]):
        mgr.save(f"epoch-{i:04d}", params, {"val_loss": v}, step=i)
    on_disk = sorted(
        p.name for p in tmp_path.iterdir()
        if p.is_dir() and p.name != "best"
    )
    assert on_disk == ["epoch-0002", "epoch-0003"]
    # best (epoch-0002's weights) survives retention via the best dir
    assert mgr.best_metrics() == {"val_loss": 1.0}
    mgr.restore("best", _dummy_state())


# -- ioutil -----------------------------------------------------------------


def test_atomic_write_text_replaces_and_leaves_no_tmp(tmp_path):
    p = tmp_path / "m.json"
    atomic_write_text(p, "one")
    atomic_write_text(p, "two")
    assert p.read_text() == "two"
    assert [q.name for q in tmp_path.iterdir()] == ["m.json"]


def test_with_retries_retries_then_succeeds_and_raises():
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise OSError("transient")
        return "ok"

    assert with_retries(flaky, retries=3, backoff_s=0.001) == "ok"
    assert len(calls) == 3
    with pytest.raises(OSError):
        with_retries(
            lambda: (_ for _ in ()).throw(OSError("always")),
            retries=1, backoff_s=0.001,
        )


# -- fault plan parsing -----------------------------------------------------


def test_fault_plan_parsing_and_env():
    from deepdfa_tpu.testing.faults import injector_from_env, parse_plan

    plan = parse_plan("sigterm@12, nan@3,nan@4,stall@5")
    assert plan.sigterm_at_step == 12
    assert plan.nan_at_steps == frozenset({3, 4})
    assert plan.stall_at_step == 5
    with pytest.raises(ValueError):
        parse_plan("explode@1")
    assert injector_from_env(env={}) is None
    inj = injector_from_env(env={"DEEPDFA_FAULTS": "nan@2"})
    assert inj is not None and inj.plan.nan_at_steps == frozenset({2})


def test_injected_stream_preserves_source_stage():
    from deepdfa_tpu.testing.faults import FaultInjector, FaultPlan

    class S:
        source_stage = "load"

        def __iter__(self):
            return iter(range(3))

    wrapped = FaultInjector(FaultPlan()).wrap(S())
    assert wrapped.source_stage == "load"
    assert list(wrapped) == [0, 1, 2]
