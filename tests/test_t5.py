"""T5 encoder parity vs HF + DefectModel behavior."""

import numpy as np
import pytest

from deepdfa_tpu.models import t5 as t5m

# heavy compiles / subprocesses: excluded from the default fast lane
# (pyproject addopts); run via `pytest -m slow` or `pytest -m ""`
pytestmark = pytest.mark.slow


def test_matches_hf_t5_encoder(rng):
    torch = pytest.importorskip("torch")
    from transformers import T5Config as HFT5Config, T5EncoderModel

    hf_cfg = HFT5Config(
        vocab_size=256,
        d_model=64,
        num_layers=2,
        num_heads=4,
        d_kv=16,
        d_ff=128,
        relative_attention_num_buckets=32,
        relative_attention_max_distance=128,
        dropout_rate=0.0,
        feed_forward_proj="relu",
    )
    tm = T5EncoderModel(hf_cfg).eval()

    cfg = t5m.T5Config.tiny(dropout_rate=0.0, remat=False)
    params = t5m.params_from_hf_torch(cfg, tm.state_dict())

    ids = rng.integers(3, 256, (2, 20))
    ids[:, -4:] = 0  # pad
    ids[:, -5] = 2  # eos
    mask = (ids != 0).astype(np.int64)

    with torch.no_grad():
        want = tm(
            input_ids=torch.tensor(ids), attention_mask=torch.tensor(mask)
        ).last_hidden_state.numpy()
    got = np.asarray(t5m.encode(cfg, params, ids.astype(np.int32)))
    # compare non-pad positions (HF computes pad rows too but they are
    # masked downstream)
    valid = mask.astype(bool)
    np.testing.assert_allclose(got[valid], want[valid], rtol=2e-4, atol=2e-4)


def test_eos_pool_picks_last_eos():
    import jax.numpy as jnp

    cfg = t5m.T5Config.tiny()
    hidden = jnp.arange(2 * 6 * 4, dtype=jnp.float32).reshape(2, 6, 4)
    ids = np.zeros((2, 6), np.int32)
    ids[0, 2] = 2
    ids[0, 4] = 2  # last eos at 4
    # row 1 has no eos -> falls back to last position
    out = np.asarray(t5m.eos_pool(cfg, hidden, ids))
    np.testing.assert_array_equal(out[0], np.asarray(hidden[0, 4]))
    np.testing.assert_array_equal(out[1], np.asarray(hidden[1, 5]))


def test_defect_forward_with_graphs(rng):
    import jax

    from deepdfa_tpu.data import build_dataset, generate, to_examples
    from deepdfa_tpu.graphs import pack

    cfg = t5m.DefectConfig(
        encoder=t5m.T5Config.tiny(dropout_rate=0.0, remat=False),
        graph_hidden_dim=8,
        graph_input_dim=52,
    )
    params = t5m.init_defect_params(cfg, jax.random.key(0))
    n = 4
    synth = generate(n, vuln_rate=0.5, seed=3)
    specs, _ = build_dataset(
        to_examples(synth), train_ids=range(n), limit_all=50, limit_subkeys=50
    )
    gb = pack(specs[:n], n, 1024, 4096)
    ids = rng.integers(3, 256, (n, 16)).astype(np.int32)
    ids[:, -1] = 2
    logits = t5m.defect_forward(
        cfg, params, ids, graph_batch=gb, has_graph=np.ones((n,), bool)
    )
    assert logits.shape == (n, 2)
    assert np.isfinite(np.asarray(logits)).all()
    # graph zeroing changes the logits
    logits2 = t5m.defect_forward(
        cfg, params, ids, graph_batch=gb, has_graph=np.zeros((n,), bool)
    )
    assert not np.allclose(np.asarray(logits), np.asarray(logits2))
    # text-only config must error clearly without a graph
    with pytest.raises(ValueError):
        t5m.defect_forward(cfg, params, ids)
