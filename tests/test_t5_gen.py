"""T5 seq2seq generation: HF parity (teacher-forced + greedy) and beam search."""

import numpy as np
import pytest

from deepdfa_tpu.models import t5 as t5m
from deepdfa_tpu.models import t5_gen as gen

# heavy compiles / subprocesses: excluded from the default fast lane
# (pyproject addopts); run via `pytest -m slow` or `pytest -m ""`
pytestmark = pytest.mark.slow


def _tiny_pair():
    torch = pytest.importorskip("torch")
    from transformers import T5Config as HFT5Config, T5ForConditionalGeneration

    hf_cfg = HFT5Config(
        vocab_size=256,
        d_model=64,
        num_layers=2,
        num_decoder_layers=2,
        num_heads=4,
        d_kv=16,
        d_ff=128,
        relative_attention_num_buckets=32,
        relative_attention_max_distance=128,
        dropout_rate=0.0,
        feed_forward_proj="relu",
        decoder_start_token_id=0,
        eos_token_id=2,
        pad_token_id=0,
    )
    tm = T5ForConditionalGeneration(hf_cfg).eval()
    cfg = gen.GenConfig(
        encoder=t5m.T5Config.tiny(dropout_rate=0.0, remat=False),
        max_target_length=16,
    )
    params = gen.gen_params_from_hf_torch(cfg, tm.state_dict())
    return torch, tm, cfg, params


def _ids(rng, shape):
    ids = rng.integers(3, 256, shape)
    ids[:, -3:] = 0
    ids[:, -4] = 2  # eos
    return ids.astype(np.int32)


def test_teacher_forced_logits_match_hf(rng):
    torch, tm, cfg, params = _tiny_pair()
    src = _ids(rng, (2, 12))
    tgt = _ids(rng, (2, 8))
    with torch.no_grad():
        want = tm(
            input_ids=torch.tensor(src, dtype=torch.long),
            attention_mask=torch.tensor((src != 0).astype(np.int64)),
            labels=torch.tensor(tgt, dtype=torch.long),
        ).logits.numpy()
    got = np.asarray(gen.seq2seq_logits(cfg, params, src, tgt))
    # non-pad target positions only (pad rows diverge via the decoder
    # self-attn mask convention but never reach the loss)
    valid = tgt != 0
    np.testing.assert_allclose(got[valid], want[valid], rtol=2e-3, atol=2e-3)


def test_greedy_decode_matches_hf_generate(rng):
    torch, tm, cfg, params = _tiny_pair()
    src = _ids(rng, (3, 12))
    with torch.no_grad():
        want = tm.generate(
            torch.tensor(src, dtype=torch.long),
            attention_mask=torch.tensor((src != 0).astype(np.int64)),
            max_length=12,
            num_beams=1,
            do_sample=False,
        ).numpy()
    got = np.asarray(gen.greedy_decode(cfg, params, src, max_length=11))
    want_trim = gen.trim_at_eos(want[:, 1:], eos_id=2)  # drop start token
    got_trim = gen.trim_at_eos(got, eos_id=2)
    assert got_trim == want_trim


def test_beam_search_shapes_and_improves_on_greedy(rng):
    torch, tm, cfg, params = _tiny_pair()
    src = _ids(rng, (2, 10))
    out = np.asarray(gen.beam_search(cfg, params, src, beam_size=4, max_length=8))
    assert out.shape == (2, 8)
    assert out.dtype == np.int32

    # beam-4 sequence log-prob must be >= greedy sequence log-prob
    def seq_logprob(tgt_row):
        tgt = np.zeros((1, 8), np.int32)
        toks = tgt_row + [2]
        tgt[0, : len(toks)] = toks
        logits = np.asarray(gen.seq2seq_logits(cfg, params, src[:1], tgt))
        logp = logits - np.log(np.exp(logits).sum(-1, keepdims=True))
        return sum(
            logp[0, i, t] for i, t in enumerate(tgt[0]) if t != 0
        )

    greedy = gen.trim_at_eos(
        np.asarray(gen.greedy_decode(cfg, params, src[:1], max_length=7)), 2
    )[0]
    beam = gen.trim_at_eos(out[:1], 2)[0]
    if greedy != beam and len(greedy) < 7 and len(beam) < 7:
        assert seq_logprob(beam) >= seq_logprob(greedy) - 1e-4


def test_untied_lm_head_parity(rng):
    torch = pytest.importorskip("torch")
    from transformers import T5Config as HFT5Config, T5ForConditionalGeneration

    hf_cfg = HFT5Config(
        vocab_size=256, d_model=64, num_layers=2, num_decoder_layers=2,
        num_heads=4, d_kv=16, d_ff=128, dropout_rate=0.0,
        feed_forward_proj="relu", tie_word_embeddings=False,
        decoder_start_token_id=0, eos_token_id=2, pad_token_id=0,
    )
    tm = T5ForConditionalGeneration(hf_cfg).eval()
    cfg = gen.GenConfig(encoder=t5m.T5Config.tiny(dropout_rate=0.0, remat=False))
    params = gen.gen_params_from_hf_torch(cfg, tm.state_dict())
    assert "lm_head" in params["decoder"]

    src = _ids(rng, (2, 12))
    tgt = _ids(rng, (2, 8))
    with torch.no_grad():
        want = tm(
            input_ids=torch.tensor(src, dtype=torch.long),
            attention_mask=torch.tensor((src != 0).astype(np.int64)),
            labels=torch.tensor(tgt, dtype=torch.long),
        ).logits.numpy()
    got = np.asarray(gen.seq2seq_logits(cfg, params, src, tgt))
    valid = tgt != 0
    np.testing.assert_allclose(got[valid], want[valid], rtol=2e-3, atol=2e-3)


def test_loss_masks_pads(rng):
    _, _, cfg, params = _tiny_pair()
    src = _ids(rng, (2, 10))
    tgt = _ids(rng, (2, 6))
    loss, n_tok = gen.seq2seq_loss(cfg, params, src, tgt)
    assert np.isfinite(float(loss))
    assert int(n_tok) == int((tgt != 0).sum())

    # extending targets with pads must not change the loss
    tgt_padded = np.concatenate([tgt, np.zeros((2, 4), np.int32)], axis=1)
    loss2, _ = gen.seq2seq_loss(cfg, params, src, tgt_padded)
    np.testing.assert_allclose(float(loss), float(loss2), rtol=1e-5)
