"""Type registry: typedef chains + struct member leaf expansion
(get_type.sc:4-52 role)."""

from deepdfa_tpu.frontend.typeinfo import TypeRegistry

SRC = """
typedef unsigned long size_t;
typedef size_t my_size;

struct Point { int x; int y; };
typedef struct Point PointT;

struct Inner { char *name; size_t len; };
struct Outer {
    struct Inner first;
    struct Point p;
    double weight;
    struct Outer *next;
};

typedef struct { int fd; } Handle;
enum Color { RED, GREEN };
"""


def _reg():
    return TypeRegistry.from_source(SRC)


def test_alias_chain_resolution():
    reg = _reg()
    assert reg.resolve_alias("size_t") == "unsigned long"
    assert reg.resolve_alias("my_size") == "unsigned long"  # two hops
    assert reg.resolve_alias("PointT") == "Point"
    assert reg.resolve_alias("unknown_t") == "unknown_t"


def test_alias_cycle_is_safe():
    reg = _reg()
    reg.aliases["a"] = "b"
    reg.aliases["b"] = "a"
    assert reg.resolve_alias("a") in ("a", "b")


def test_struct_members_recorded():
    reg = _reg()
    assert "Point" in reg.structs
    assert reg.structs["Point"].member_types == ["int", "int"]
    assert "Inner" in reg.structs
    assert "Outer" in reg.structs


def test_member_leaf_types_recursive():
    reg = _reg()
    leaves = reg.member_leaf_types("Outer")
    # Inner -> {char, unsigned long}; Point -> {int}; weight -> double;
    # the recursive Outer* pointer must not loop
    assert "char" in leaves
    assert "int" in leaves
    assert "double" in leaves
    assert "unsigned long" in leaves
    assert "Outer" not in leaves


def test_external_and_memberless_leaves():
    reg = _reg()
    # unknown type = external leaf, returned as-is
    assert reg.member_leaf_types("FILE") == ["FILE"]
    # enum = memberless internal leaf
    assert reg.member_leaf_types("Color") == ["Color"]
    # anonymous-struct typedef resolves through the generated tag
    leaves = reg.member_leaf_types("Handle")
    assert leaves == ["int"]


def test_garbage_input_yields_empty_registry():
    reg = TypeRegistry.from_source("@#$ not C at all {{{")
    assert reg.aliases == {} and reg.structs == {}
    assert reg.resolve_alias("x") == "x"


def test_function_pointer_typedef_not_poisoning():
    reg = TypeRegistry.from_source(
        "typedef int (*cmp)(int a, int b);\n"
        "typedef void fn(char c);\n"
        "typedef unsigned int uint;\n"
    )
    # function/function-pointer typedefs are skipped, never mis-keyed by a
    # parameter name
    assert "b" not in reg.aliases and "c" not in reg.aliases
    assert "cmp" not in reg.aliases and "fn" not in reg.aliases
    assert reg.resolve_alias("uint") == "unsigned int"
