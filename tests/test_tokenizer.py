"""Tokenizer tests: hash fallback invariants + BPE vs HF oracle."""

from pathlib import Path

import numpy as np
import pytest

from deepdfa_tpu.data.tokenizer import BpeTokenizer, HashTokenizer

_REF_BPE = Path("/root/reference/LineVul/linevul/bpe_tokenizer")


def test_hash_tokenizer_contract():
    tok = HashTokenizer(vocab_size=256)
    ids = tok.encode("int main(void) { return 0; }", max_length=16)
    assert ids.shape == (16,)
    assert ids[0] == tok.cls_id
    assert tok.sep_id in ids
    assert (ids < 256).all()
    # deterministic
    np.testing.assert_array_equal(
        ids, tok.encode("int main(void) { return 0; }", max_length=16)
    )
    # padding fills the tail
    assert (ids[np.argmax(ids == tok.sep_id) + 1 :] == tok.pad_id).all()


def test_hash_tokenizer_truncation():
    tok = HashTokenizer(vocab_size=256)
    long = "x = 1; " * 500
    ids = tok.encode(long, max_length=32)
    assert ids.shape == (32,)
    assert ids[-1] == tok.sep_id or tok.sep_id in ids


@pytest.mark.skipif(not _REF_BPE.exists(), reason="no local BPE assets")
def test_bpe_matches_hf_tokenizer():
    from transformers import RobertaTokenizerFast

    hf = RobertaTokenizerFast(
        vocab_file=str(_REF_BPE / "bpe_tokenizer-vocab.json"),
        merges_file=str(_REF_BPE / "bpe_tokenizer-merges.txt"),
    )
    tok = BpeTokenizer(
        _REF_BPE / "bpe_tokenizer-vocab.json",
        _REF_BPE / "bpe_tokenizer-merges.txt",
    )
    samples = [
        "int main(void) { return 0; }",
        "static void copy(char *dst, const char *src) { strcpy(dst, src); }",
        'printf("hello %d\\n", x);',
        "for (i = 0; i < n; i++) total += a[i];",
    ]
    for s in samples:
        want = hf(s, max_length=64, padding="max_length", truncation=True)[
            "input_ids"
        ]
        got = tok.encode(s, max_length=64)
        assert got.tolist() == want, s
