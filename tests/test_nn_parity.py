"""Numerical parity of the JAX GGNN against the reference semantics.

DGL's GatedGraphConv is: per step, a_v = sum_{(u,v)} W h_u followed by
h_v = torch.nn.GRUCell(a_v, h_v); GlobalAttentionPooling is a per-graph
softmax of gate_nn(h) times h (SURVEY.md §2.1 GGNN model row). DGL itself is
not installable here, so the oracle below implements exactly those equations
with torch (whose GRUCell is the one DGL calls), on unpadded graphs, and we
check the padded static-shape JAX path reproduces it to float32 tolerance.
"""

import numpy as np
import torch

from deepdfa_tpu.graphs import GraphSpec, pack
from deepdfa_tpu.nn import GatedGraphConv, GlobalAttentionPooling, GRUCell


def torch_ggc_reference(h0, src, dst, W, b, gru: torch.nn.GRUCell, n_steps):
    """DGL GatedGraphConv semantics on one unpadded graph."""
    h = h0.clone()
    n = h.shape[0]
    for _ in range(n_steps):
        m = h @ W.T + b
        a = torch.zeros_like(h)
        a.index_add_(0, dst, m[src])
        h = gru(a, h)
    return h


def test_grucell_matches_torch(rng):
    import jax

    d = 16
    cell = GRUCell(d)
    x = rng.standard_normal((7, d)).astype(np.float32)
    h = rng.standard_normal((7, d)).astype(np.float32)
    params = cell.init(jax.random.key(0), x, h)

    tcell = torch.nn.GRUCell(d, d)
    # copy flax params into torch: flax kernel [in, 3D] -> torch weight [3D, in]
    with torch.no_grad():
        tcell.weight_ih.copy_(
            torch.tensor(np.asarray(params["params"]["input_proj"]["kernel"]).T)
        )
        tcell.weight_hh.copy_(
            torch.tensor(np.asarray(params["params"]["hidden_proj"]["kernel"]).T)
        )
        tcell.bias_ih.copy_(
            torch.tensor(np.asarray(params["params"]["input_proj"]["bias"]))
        )
        tcell.bias_hh.copy_(
            torch.tensor(np.asarray(params["params"]["hidden_proj"]["bias"]))
        )
        want = tcell(torch.tensor(x), torch.tensor(h)).numpy()
    got = np.asarray(cell.apply(params, x, h))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_gated_graph_conv_matches_reference(rng):
    import jax

    d, n_steps = 8, 5
    graphs = []
    for gid in range(3):
        n = int(rng.integers(3, 12))
        e = int(rng.integers(2, 3 * n))
        graphs.append(
            GraphSpec(
                graph_id=gid,
                node_feats=rng.integers(0, 5, (n, 4)).astype(np.int32),
                node_vuln=np.zeros((n,), np.int32),
                edge_src=rng.integers(0, n, (e,)).astype(np.int32),
                edge_dst=rng.integers(0, n, (e,)).astype(np.int32),
                label=0.0,
            )
        )
    batch = pack(graphs, num_graphs=4, node_budget=64, edge_budget=256)

    feats = rng.standard_normal((64, d)).astype(np.float32)
    conv = GatedGraphConv(out_features=d, n_steps=n_steps)
    params = conv.init(jax.random.key(1), batch, feats)
    got = np.asarray(conv.apply(params, batch, feats))

    p = params["params"]
    W = torch.tensor(np.asarray(p["etype_0"]["kernel"]).T)
    b = torch.tensor(np.asarray(p["etype_0"]["bias"]))
    gru = torch.nn.GRUCell(d, d)
    with torch.no_grad():
        gru.weight_ih.copy_(torch.tensor(np.asarray(p["GRUCell_0"]["input_proj"]["kernel"]).T))
        gru.weight_hh.copy_(torch.tensor(np.asarray(p["GRUCell_0"]["hidden_proj"]["kernel"]).T))
        gru.bias_ih.copy_(torch.tensor(np.asarray(p["GRUCell_0"]["input_proj"]["bias"])))
        gru.bias_hh.copy_(torch.tensor(np.asarray(p["GRUCell_0"]["hidden_proj"]["bias"])))

        # run the oracle per graph on unpadded arrays WITH self loops,
        # mirroring the reference's add_self_loop at graph build time
        off = 0
        for g in graphs:
            n = g.num_nodes
            src = np.concatenate([g.edge_src, np.arange(n)])
            dst = np.concatenate([g.edge_dst, np.arange(n)])
            want = torch_ggc_reference(
                torch.tensor(feats[off : off + n]),
                torch.tensor(src),
                torch.tensor(dst),
                W,
                b,
                gru,
                n_steps,
            ).numpy()
            np.testing.assert_allclose(
                got[off : off + n], want, rtol=2e-4, atol=2e-5
            )
            off += n


def test_gated_graph_conv_scan_matches_unroll(rng):
    """scan_steps=True is the same function: identical param structure
    (step 1 runs eagerly in the outer scope) and matching forward/
    gradients to float32 fusion tolerance — only the compiled program
    shrinks."""
    import jax
    import jax.numpy as jnp

    d = 8
    graphs = []
    for gid in range(3):
        n = int(rng.integers(3, 12))
        e = int(rng.integers(2, 3 * n))
        graphs.append(
            GraphSpec(
                graph_id=gid,
                node_feats=rng.integers(0, 5, (n, 4)).astype(np.int32),
                node_vuln=np.zeros((n,), np.int32),
                edge_src=rng.integers(0, n, (e,)).astype(np.int32),
                edge_dst=rng.integers(0, n, (e,)).astype(np.int32),
                label=0.0,
            )
        )
    batch = pack(graphs, num_graphs=4, node_budget=64, edge_budget=256)
    feats = rng.standard_normal((64, d)).astype(np.float32)

    unroll = GatedGraphConv(out_features=d, n_steps=5)
    scan = GatedGraphConv(out_features=d, n_steps=5, scan_steps=True)
    params = unroll.init(jax.random.key(1), batch, feats)
    # same param tree is valid for both forms
    out_u = np.asarray(unroll.apply(params, batch, feats))
    out_s = np.asarray(scan.apply(params, batch, feats))
    np.testing.assert_allclose(out_u, out_s, rtol=1e-4, atol=1e-6)

    def loss(fn, p):
        return jnp.sum(fn.apply(p, batch, feats) ** 2)

    g_u = jax.grad(lambda p: loss(unroll, p))(params)
    g_s = jax.grad(lambda p: loss(scan, p))(params)
    for ku, ks in zip(
        jax.tree.leaves(g_u), jax.tree.leaves(g_s), strict=True
    ):
        # atol covers near-zero bias-grad elements: the scan body
        # (raw-math over the param twins) fuses differently from the
        # unrolled module calls, so reductions reassociate at f32
        np.testing.assert_allclose(
            np.asarray(ku), np.asarray(ks), rtol=1e-4, atol=1e-5
        )


def test_attention_pooling_matches_reference(rng):
    import jax

    d = 8
    graphs = []
    for gid in range(3):
        n = int(rng.integers(2, 10))
        graphs.append(
            GraphSpec(
                graph_id=gid,
                node_feats=np.zeros((n, 4), np.int32),
                node_vuln=np.zeros((n,), np.int32),
                edge_src=np.zeros((0,), np.int32),
                edge_dst=np.zeros((0,), np.int32),
                label=0.0,
            )
        )
    batch = pack(graphs, num_graphs=4, node_budget=32, edge_budget=64)
    feats = rng.standard_normal((32, d)).astype(np.float32)

    pool = GlobalAttentionPooling()
    params = pool.init(jax.random.key(2), batch, feats)
    got = np.asarray(pool.apply(params, batch, feats))
    assert got.shape == (4, d)

    W = np.asarray(params["params"]["gate_nn"]["kernel"])
    b = np.asarray(params["params"]["gate_nn"]["bias"])
    off = 0
    for gi, g in enumerate(graphs):
        n = g.num_nodes
        f = feats[off : off + n]
        gate = f @ W + b
        attn = np.exp(gate - gate.max())
        attn = attn / attn.sum()
        want = (attn * f).sum(axis=0)
        np.testing.assert_allclose(got[gi], want, rtol=1e-5, atol=1e-6)
        off += n
    # padded graph slot pools to zero
    np.testing.assert_allclose(got[3], 0.0, atol=1e-6)


def test_gated_graph_conv_multi_etype_relation_masking(rng):
    """n_etypes > 1: each relation's transform sees only its own edges.

    Oracle-free checks against the single-type conv (whose own parity is
    pinned above): (a) a typed graph whose edges are ALL type 0 must equal
    the n_etypes=1 conv sharing the etype_0/GRU params; (b) edges all of
    type 1 must equal the single-type conv run with etype_1's transform.
    DGL API role: dgl.nn.GatedGraphConv(..., n_etypes) + etypes argument.
    """
    import dataclasses as dc

    import jax

    d, n_steps, n, e = 8, 3, 10, 20
    base = GraphSpec(
        graph_id=0,
        node_feats=rng.integers(0, 5, (n, 4)).astype(np.int32),
        node_vuln=np.zeros((n,), np.int32),
        edge_src=rng.integers(0, n, (e,)).astype(np.int32),
        edge_dst=rng.integers(0, n, (e,)).astype(np.int32),
        label=0.0,
    )
    feats = rng.standard_normal((16, d)).astype(np.float32)
    conv3 = GatedGraphConv(out_features=d, n_steps=n_steps, n_etypes=3)
    conv1 = GatedGraphConv(out_features=d, n_steps=n_steps)

    def run3(etype_value):
        g = dc.replace(
            base, edge_type=np.full((e,), etype_value, np.int32)
        )
        batch = pack([g], num_graphs=1, node_budget=16, edge_budget=48)
        params = conv3.init(jax.random.key(7), batch, feats)
        return params, np.asarray(conv3.apply(params, batch, feats))

    batch1 = pack([base], num_graphs=1, node_budget=16, edge_budget=48)

    params, got0 = run3(0)
    p = params["params"]
    params1 = {"params": {"etype_0": p["etype_0"], "GRUCell_0": p["GRUCell_0"]}}
    want0 = np.asarray(conv1.apply(params1, batch1, feats))
    np.testing.assert_allclose(got0, want0, rtol=1e-5, atol=1e-6)

    # all-type-1 edges: only the etype_1 transform fires on real edges...
    params, got1 = run3(1)
    p = params["params"]
    # ...but self-loops (added at pack time) are type 0, so the oracle is
    # a 2-type conv with the same params minus the never-used etype_2
    conv2 = GatedGraphConv(out_features=d, n_steps=n_steps, n_etypes=2)
    params2 = {
        "params": {
            "etype_0": p["etype_0"],
            "etype_1": p["etype_1"],
            "GRUCell_0": p["GRUCell_0"],
        }
    }
    g1 = dc.replace(base, edge_type=np.full((e,), 1, np.int32))
    b1 = pack([g1], num_graphs=1, node_budget=16, edge_budget=48)
    want1 = np.asarray(conv2.apply(params2, b1, feats))
    np.testing.assert_allclose(got1, want1, rtol=1e-5, atol=1e-6)
    # and it differs from the all-type-0 run (the transforms are distinct)
    assert np.abs(got1 - got0).max() > 1e-4


def test_gated_graph_conv_multi_etype_needs_ids(rng):
    import jax
    import pytest

    conv = GatedGraphConv(out_features=4, n_steps=2, n_etypes=2)
    g = GraphSpec(
        graph_id=0,
        node_feats=np.zeros((4, 4), np.int32),
        node_vuln=np.zeros((4,), np.int32),
        edge_src=np.array([0, 1], np.int32),
        edge_dst=np.array([1, 2], np.int32),
        label=0.0,
    )
    batch = pack([g], num_graphs=1, node_budget=8, edge_budget=16)
    feats = np.zeros((8, 4), np.float32)
    with pytest.raises(ValueError, match="edge-type ids"):
        conv.init(jax.random.key(0), batch, feats)
