"""Golden pins for the digit-exact java/c_sharp dataflow match.

Every expected triple list below was hand-derived by executing the
reference's DFG logic on paper — DFG_java (CodeT5/evaluator/CodeBLEU/
parser/DFG.py:180-355), DFG_csharp (DFG.py:356-538), tree_to_variable_
index (parser/utils.py:80-92) and the get_data_flow filter/merge +
normalize pipeline (dataflow_match.py:70-150). tree-sitter itself is
not installed in this image, so the goldens cite the branch of DFG.py
each behavior traces to.

Determinism note: the reference merges duplicate triples with
`list(set(parent_codes))` (DFG.py:295-300, dataflow_match.py:104-107),
so the ORDER of a merged multi-parent list is str-hash dependent in the
reference itself (varies with PYTHONHASHSEED). The pins therefore
canonicalize parent-code lists by sorting — content equality, which is
the strongest property the reference's own output holds across runs.
Parent-INDEX lists are sorted ints in both implementations and are
pinned verbatim.
"""

import pytest

from deepdfa_tpu.eval.dfg_parity import (
    corpus_dataflow_match,
    dfg_extract,
    get_data_flow,
    normalize_dataflow,
    parse_snippet,
    remove_comments,
)


def extract(code: str, lang: str):
    dfg, states = dfg_extract(parse_snippet(code, lang), lang, {})
    return [canon_t(t) for t in dfg], states


def canon_t(t):
    return (t[0], t[1], t[2], sorted(t[3]), sorted(t[4]))


def canon_all(ts):
    return [canon_t(t) for t in ts]


# ---------------------------------------------------------------------------
# java
# ---------------------------------------------------------------------------


def test_java_decl_no_value():
    # DFG.py:203-209: bare declarator -> comesFrom [],[] and a def state
    dfg, states = extract("int x;", "java")
    assert dfg == [("x", 1, "comesFrom", [], [])]
    assert states == {"x": [1]}
    # filter (dataflow_match.py:85-95): no parents anywhere -> dropped
    assert get_data_flow("int x;", "java") == []


def test_java_decl_with_literal_value():
    # DFG.py:211-222: declarator with value -> comesFrom pairs; the
    # literal participates as a parent (tree_to_variable_index keeps
    # named-literal leaves, utils.py:80-92)
    dfg, states = extract("int x = 5;", "java")
    assert dfg == [
        ("x", 1, "comesFrom", ["5"], [3]),
        ("5", 3, "comesFrom", [], []),
    ]
    assert states == {"x": [1]}


def test_java_chained_decls_and_assignment():
    dfg, states = extract("int x = 5;\nint y = x + 2;\nx = y;", "java")
    assert dfg == [
        ("x", 1, "comesFrom", ["5"], [3]),
        ("5", 3, "comesFrom", [], []),
        ("y", 6, "comesFrom", ["x"], [8]),
        ("y", 6, "comesFrom", ["2"], [10]),
        ("x", 8, "comesFrom", ["x"], [1]),   # use reached by def@1
        ("2", 10, "comesFrom", [], []),
        ("x", 12, "computedFrom", ["y"], [14]),  # DFG.py:224-238
        ("y", 14, "comesFrom", ["y"], [6]),
    ]
    assert states == {"x": [12], "y": [6]}


def test_java_compound_assignment_reads_rhs_only():
    # `+=` is a plain assignment_expression to the DFG: the left side
    # is written, never read (DFG.py:224-238 has no compound case)
    dfg, states = extract("x += y;", "java")
    assert dfg == [
        ("x", 0, "computedFrom", ["y"], [2]),
        ("y", 2, "comesFrom", [], []),
    ]
    assert states == {"x": [0], "y": [2]}


def test_java_update_expression():
    # DFG.py:239-247: i++ -> computedFrom itself
    dfg, states = extract("i++;", "java")
    assert dfg == [("i", 0, "computedFrom", ["i"], [0])]
    assert states == {"i": [0]}


def test_java_if_else_merges_branch_states():
    # DFG.py:248-279: consequence runs on current_states, the else
    # branch on the PRISTINE pre-if states; the merged state carries
    # every branch's defs, so a later use comes from all three defs
    code = "int a = b;\nif (c) { a = 1; } else { a = 2; }\nint d = a;"
    dfg, states = extract(code, "java")
    assert ("a", 25, "comesFrom", ["a"], [1, 10, 17]) in dfg
    assert ("d", 23, "comesFrom", ["a"], [25]) in dfg
    assert states["a"] == [1, 10, 17]


def test_java_else_if_chain():
    code = (
        "if (c) { a = 1; } else if (d) { a = 2; } else { a = 3; }\n"
        "int e = a;"
    )
    dfg, states = extract(code, "java")
    # nested else-if: the alternative is itself an if_statement run on
    # pristine states (DFG.py:267-270); the final use sees all 3 defs
    assert ("a", 31, "comesFrom", ["a"], [5, 16, 23]) in dfg
    assert states["a"] == [5, 16, 23]


def test_java_for_loop_two_passes():
    # DFG.py:280-302: pass 1 over all children, pass 2 over children
    # AFTER the local_variable_declaration, then dedup-merge
    dfg, states = extract(
        "for (int i = 0; i < n; i++) { s = s + i; }", "java"
    )
    assert dfg == [
        ("i", 3, "comesFrom", ["0"], [5]),
        ("0", 5, "comesFrom", [], []),
        # pass1 sees def@3, pass2 sees the i++ def@11 -> merged
        ("i", 7, "comesFrom", ["i"], [3, 11]),
        # n is fresh in pass 1 ([],[]) and a self-parent in pass 2
        ("n", 9, "comesFrom", ["n"], [9]),
        ("i", 11, "computedFrom", ["i"], [11]),
        ("s", 15, "computedFrom", ["i", "s"], [17, 19]),
        ("s", 17, "comesFrom", ["s"], [15]),
        ("i", 19, "comesFrom", ["i"], [11]),
    ]
    assert states == {"i": [11], "n": [9], "s": [15]}


def test_java_enhanced_for_two_rounds():
    # DFG.py:303-326: name computedFrom value, two rounds, merged
    dfg, states = extract("for (int v : xs) { t += v; }", "java")
    assert dfg == [
        ("v", 3, "computedFrom", ["xs"], [5]),
        ("xs", 5, "comesFrom", ["xs"], [5]),  # round 2 self-parent
        ("t", 8, "computedFrom", ["v"], [10]),
        ("v", 10, "comesFrom", ["v"], [3]),
    ]
    assert states == {"v": [3], "xs": [5], "t": [8]}


def test_java_while_two_passes():
    # DFG.py:327-340: every child visited twice, then merged
    dfg, states = extract("while (i < n) { i = i + 1; }", "java")
    assert dfg == [
        ("i", 2, "comesFrom", ["i"], [7]),  # pass2: body def reaches cond
        ("n", 4, "comesFrom", ["n"], [4]),
        ("i", 7, "computedFrom", ["1", "i"], [9, 11]),
        ("i", 9, "comesFrom", ["i"], [2, 7]),
        ("1", 11, "comesFrom", [], []),
    ]
    assert states == {"i": [7], "n": [4]}


def test_java_do_while_is_generic_single_pass():
    # do_statement is in NO special list (DFG.py:188) -> one generic
    # pass; the body's first `i` use precedes any def, so it has no
    # parents, and the condition sees only the body's def
    dfg, states = extract("do { i = i + 1; } while (i < n);", "java")
    assert dfg == [
        ("i", 2, "computedFrom", ["i"], [4]),
        ("i", 2, "computedFrom", ["1"], [6]),
        ("i", 4, "comesFrom", [], []),
        ("1", 6, "comesFrom", [], []),
        ("i", 11, "comesFrom", ["i"], [2]),
        ("n", 13, "comesFrom", [], []),
    ]
    assert states == {"i": [2], "n": [13]}


def test_java_method_params_define():
    # formal parameters are plain identifier leaves -> they def via the
    # leaf rule (DFG.py:191-199); the method NAME is an identifier too
    # and participates (tree-sitter treats it no differently)
    dfg, states = extract("int add(int a, int b) { return a + b; }", "java")
    assert dfg == [
        ("add", 1, "comesFrom", [], []),
        ("a", 4, "comesFrom", [], []),
        ("b", 7, "comesFrom", [], []),
        ("a", 11, "comesFrom", ["a"], [4]),
        ("b", 13, "comesFrom", ["b"], [7]),
    ]
    assert states == {"add": [1], "a": [4], "b": [7]}


def test_java_call_and_field_access_leaves_participate():
    # method/field names are identifier leaves; assignment's RHS
    # variable index list includes them (a faithful quirk)
    dfg, _ = extract("y = o.f(x);", "java")
    assert dfg == [
        ("y", 0, "computedFrom", ["o"], [2]),
        ("y", 0, "computedFrom", ["f"], [4]),
        ("y", 0, "computedFrom", ["x"], [6]),
        ("o", 2, "comesFrom", [], []),
        ("f", 4, "comesFrom", [], []),
        ("x", 6, "comesFrom", [], []),
    ]


def test_java_type_identifiers_participate_but_filter_out():
    # `String` is an identifier leaf (not a keyword): it enters states
    # and emits a parentless triple, which the get_data_flow filter
    # then drops (dataflow_match.py:85-95) because nothing refers to it
    dfg, states = extract('String s = "hi";', "java")
    assert dfg == [
        ("String", 0, "comesFrom", [], []),
        ("s", 1, "comesFrom", ['"hi"'], [3]),
        ('"hi"', 3, "comesFrom", [], []),
    ]
    assert states == {"String": [0], "s": [1]}
    kept = canon_all(get_data_flow('String s = "hi";', "java"))
    assert ("String", 0, "comesFrom", [], []) not in kept
    assert ("s", 1, "comesFrom", ['"hi"'], [3]) in kept


def test_java_null_is_named_true_false_are_not():
    # null lifts to a null_literal token (type != text -> participates);
    # true/false are anonymous in the grammar (type == text -> invisible)
    dfg, _ = extract("Object o = null;", "java")
    assert ("o", 1, "comesFrom", ["null"], [3]) in dfg
    dfg2, states2 = extract("boolean b = true;", "java")
    assert dfg2 == []  # no variable leaves at all on the RHS
    # ...but the declarator still defs b (the states write sits outside
    # the per-value loop, DFG.py:221): boolean(0) b(1) =(2) true(3)
    assert states2 == {"b": [1]}


def test_java_chained_assignment():
    dfg, states = extract("x = y = z;", "java")
    assert dfg == [
        ("x", 0, "computedFrom", ["y"], [2]),
        ("x", 0, "computedFrom", ["z"], [4]),
        ("y", 2, "computedFrom", ["z"], [4]),
        ("z", 4, "comesFrom", [], []),
    ]
    assert states == {"x": [0], "y": [2], "z": [4]}


def test_java_cast_skips_type_keyword():
    dfg, _ = extract("int y = (int) x;", "java")
    assert dfg == [
        ("y", 1, "comesFrom", ["x"], [6]),
        ("x", 6, "comesFrom", [], []),
    ]


def test_java_array_assignment_left_indices_all_written():
    # tree_to_variable_index(left) over `a[i]` yields BOTH a and i:
    # both become computedFrom targets and neither is read (faithful)
    dfg, states = extract("a[i] = b[j] + 1;", "java")
    assert dfg == [
        ("a", 0, "computedFrom", ["b"], [5]),
        ("a", 0, "computedFrom", ["j"], [7]),
        ("a", 0, "computedFrom", ["1"], [10]),
        ("i", 2, "computedFrom", ["b"], [5]),
        ("i", 2, "computedFrom", ["j"], [7]),
        ("i", 2, "computedFrom", ["1"], [10]),
        ("b", 5, "comesFrom", [], []),
        ("j", 7, "comesFrom", [], []),
        ("1", 10, "comesFrom", [], []),
    ]
    assert states == {"a": [0], "i": [2], "b": [5], "j": [7]}


# ---------------------------------------------------------------------------
# c_sharp
# ---------------------------------------------------------------------------


def test_csharp_decl_equals_value_clause_shape():
    # DFG_csharp def branch (DFG.py:377-402): declarator children are
    # [identifier, equals_value_clause]; same comesFrom output as java
    dfg, states = extract("int x = 5;", "c_sharp")
    assert dfg == [
        ("x", 1, "comesFrom", ["5"], [3]),
        ("5", 3, "comesFrom", [], []),
    ]
    assert states == {"x": [1]}


def test_csharp_postfix_is_increment_prefix_is_not():
    # DFG.py:359: increment_statement=['postfix_unary_expression'] —
    # ++j is a prefix_unary_expression and falls through to the
    # generic branch (just a leaf use)
    dfg, states = extract("i++;\n++j;", "c_sharp")
    assert dfg == [
        ("i", 0, "computedFrom", ["i"], [0]),
        ("j", 4, "comesFrom", [], []),
    ]
    assert states == {"i": [0], "j": [4]}
    # and the parentless ++j use filters out downstream
    assert canon_all(get_data_flow("i++;\n++j;", "c_sharp")) == [
        ("i", 0, "computedFrom", ["i"], [0])
    ]


def test_csharp_for_loop_second_pass_never_fires():
    # The c# grammar names the for initializer `variable_declaration`,
    # but DFG_csharp's second-pass trigger checks for
    # "local_variable_declaration" verbatim (DFG.py:470) — so unlike
    # java, NO loop-back triples appear. Quirk replicated, not fixed.
    dfg, states = extract(
        "for (int i = 0; i < n; i++) { s += i; }", "c_sharp"
    )
    assert dfg == [
        ("i", 3, "comesFrom", ["0"], [5]),
        ("0", 5, "comesFrom", [], []),
        ("i", 7, "comesFrom", ["i"], [3]),   # only the init def: 1 pass
        ("n", 9, "comesFrom", [], []),       # never becomes self-parent
        ("i", 11, "computedFrom", ["i"], [11]),
        ("s", 15, "computedFrom", ["i"], [17]),
        ("i", 17, "comesFrom", ["i"], [11]),
    ]
    assert states == {"i": [11], "n": [9], "s": [15]}


def test_csharp_vs_java_for_loop_differ():
    """The same source text scores differently between the two
    languages — the divergence IS reference behavior."""
    code = "for (int i = 0; i < n; i++) { s = s + i; }"
    dj, _ = extract(code, "java")
    dc, _ = extract(code, "c_sharp")
    assert dj != dc
    assert ("n", 9, "comesFrom", ["n"], [9]) in dj      # java pass 2
    assert ("n", 9, "comesFrom", [], []) in dc          # c# single pass


def test_csharp_foreach():
    # DFG.py:481-508: left computedFrom right, two rounds, merged
    dfg, states = extract("foreach (int v in xs) { t += v; }", "c_sharp")
    assert dfg == [
        ("v", 3, "computedFrom", ["xs"], [5]),
        ("xs", 5, "comesFrom", ["xs"], [5]),
        ("t", 8, "computedFrom", ["v"], [10]),
        ("v", 10, "comesFrom", ["v"], [3]),
    ]
    assert states == {"v": [3], "xs": [5], "t": [8]}


def test_csharp_while_two_passes():
    dfg, states = extract("while (i < n) { i = i + 1; }", "c_sharp")
    assert dfg == [
        ("i", 2, "comesFrom", ["i"], [7]),
        ("n", 4, "comesFrom", ["n"], [4]),
        ("i", 7, "computedFrom", ["1", "i"], [9, 11]),
        ("i", 9, "comesFrom", ["i"], [2, 7]),
        ("1", 11, "comesFrom", [], []),
    ]
    assert states == {"i": [7], "n": [4]}


def test_csharp_chained_assignment():
    dfg, _ = extract("x = y = z;", "c_sharp")
    assert dfg == [
        ("x", 0, "computedFrom", ["y"], [2]),
        ("x", 0, "computedFrom", ["z"], [4]),
        ("y", 2, "computedFrom", ["z"], [4]),
        ("z", 4, "comesFrom", [], []),
    ]


def test_csharp_true_invisible_string_participates():
    dfg, states = extract('string s = "hi";\nbool b = true;', "c_sharp")
    assert dfg == [
        ("s", 1, "comesFrom", ['"hi"'], [3]),
        ('"hi"', 3, "comesFrom", [], []),
    ]
    # b still defs (the states write is outside the value loop,
    # DFG.py:399) even though `true` contributes no parents
    assert states == {"s": [1], "b": [6]}


# ---------------------------------------------------------------------------
# pipeline: filter, merge, normalize, score
# ---------------------------------------------------------------------------


def test_get_data_flow_merges_by_index():
    # dataflow_match.py:100-110: one entry per token index, parent
    # code/idx sets unioned
    kept = canon_all(
        get_data_flow("int x = 5;\nint y = x + 2;\nx = y;", "java")
    )
    assert ("y", 6, "comesFrom", ["2", "x"], [8, 10]) in kept


def test_normalize_sequential_renaming():
    # dataflow_match.py:129-145: parents renamed before the target var,
    # names assigned in first-appearance order
    norm = normalize_dataflow(get_data_flow("x = y;\nz = x;", "java"))
    # y@1 appears first as x's parent -> var_0; x -> var_1; z -> var_2
    assert ("var_1", "computedFrom", ["var_0"]) in norm
    assert ("var_2", "computedFrom", ["var_1"]) in norm


def test_score_self_match_is_one():
    code = "int a = b;\nfor (int i = 0; i < a; i++) { b += i; }"
    assert corpus_dataflow_match([[code]], [code], "java") == 1.0


def test_score_alpha_renaming_invariant():
    ref = "int total = start;\ntotal += delta;"
    cand = "int sum = s0;\nsum += d;"
    assert corpus_dataflow_match([[ref]], [cand], "java") == 1.0


def test_score_partial_match_fraction():
    # ref has 4 surviving triples (x=5 pair + y=x pair);
    # a candidate missing the second statement matches only x's pair
    ref = "int x = 5;\nint y = x;"
    cand = "int x = 5;"
    score = corpus_dataflow_match([[ref]], [cand], "java")
    ref_n = len(get_data_flow(ref, "java"))
    match_n = len(get_data_flow(cand, "java"))
    assert score == pytest.approx(match_n / ref_n)


def test_score_degenerate_zero_when_ref_has_no_flows():
    assert corpus_dataflow_match([["int x;"]], ["int x;"], "java") == 0.0


def test_comment_stripping_matches_reference_regex():
    # utils.py:50-66 'java' branch: comments -> one space, strings
    # protected, blank lines dropped
    src = 'int a = 1; // c\n/* multi\nline */\nString s = "// not";'
    out = remove_comments(src)
    assert "// c" not in out and "multi" not in out
    assert '"// not"' in out
    assert "" not in [ln for ln in out.split("\n")]
    # a commented-out def must not produce triples
    assert corpus_dataflow_match(
        [["int x = y;\n// x = z;"]], ["int x = y;"], "java"
    ) == 1.0


def test_codebleu_integration_uses_parity_path():
    from deepdfa_tpu.eval.codebleu import corpus_dataflow_match as cdm

    code = "int x = a;\nx += b;"
    assert cdm([[code]], [code], lang="java") == 1.0
    assert cdm([[code]], [code], lang="c_sharp") == 1.0
