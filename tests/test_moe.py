"""Mixture-of-experts FFN + expert parallelism."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepdfa_tpu.core import MeshConfig
from deepdfa_tpu.parallel import make_mesh
from deepdfa_tpu.parallel.moe import (
    MoEConfig,
    capacity,
    init_moe_params,
    moe_ffn,
    moe_ffn_ep,
)


@pytest.fixture(scope="module")
def setup():
    cfg = MoEConfig(hidden_size=16, intermediate_size=32, num_experts=4,
                    top_k=2)
    params = init_moe_params(cfg, jax.random.key(0))
    x = jax.random.normal(jax.random.key(1), (24, 16))
    return cfg, params, x


def test_moe_routes_topk_and_is_finite(setup):
    cfg, params, x = setup
    out, aux = jax.jit(lambda p, x: moe_ffn(cfg, p, x))(params, x)
    assert out.shape == x.shape
    assert np.isfinite(np.asarray(out)).all()
    # balanced-ish routing keeps aux near 1 (its minimum is 1 for top-1;
    # just require finiteness and positivity here)
    assert float(aux) > 0


def test_moe_capacity_drops_overflow(setup):
    """With capacity 1, most tokens lose their expert slot; output norm
    shrinks vs ample capacity but stays finite (residual-path semantics:
    dropped tokens contribute zero)."""
    cfg, params, x = setup
    ample, _ = moe_ffn(cfg, params, x)
    tight, _ = moe_ffn(cfg, params, x, cap=1)
    assert np.isfinite(np.asarray(tight)).all()
    assert np.linalg.norm(np.asarray(tight)) < np.linalg.norm(
        np.asarray(ample)
    )


def test_moe_dense_equivalence_with_full_capacity(setup):
    """With capacity >= N every chosen token is kept: the MoE output must
    equal the hand-computed gated sum of its top-k experts' FFNs."""
    cfg, params, x = setup
    out, _ = moe_ffn(cfg, params, x, cap=x.shape[0])
    logits = np.asarray(x @ params["router"])
    probs = np.asarray(jax.nn.softmax(logits, -1))
    want = np.zeros_like(np.asarray(x))
    for i in range(x.shape[0]):
        top = np.argsort(-logits[i])[: cfg.top_k]
        g = probs[i][top] / probs[i][top].sum()
        for w, e in zip(g, top):
            h = np.asarray(
                jax.nn.gelu(x[i] @ params["w1"][e] + params["b1"][e])
            )
            want[i] += w * (h @ np.asarray(params["w2"][e])
                            + np.asarray(params["b2"][e]))
    np.testing.assert_allclose(np.asarray(out), want, rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("ep", [2, 4])
def test_moe_ep_matches_single_device(setup, ep):
    cfg, params, x = setup
    mesh = make_mesh(MeshConfig(dp=1, ep=ep), devices=jax.devices()[:ep])
    want, aux1 = moe_ffn(cfg, params, x)
    got, aux2 = jax.jit(
        lambda p, x: moe_ffn_ep(cfg, p, x, mesh)
    )(params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-6)
    np.testing.assert_allclose(float(aux2), float(aux1), rtol=1e-5)


def test_moe_ep_gradients_match(setup):
    cfg, params, x = setup
    mesh = make_mesh(MeshConfig(dp=1, ep=2), devices=jax.devices()[:2])

    def loss_single(p):
        out, aux = moe_ffn(cfg, p, x)
        return jnp.sum(out**2) + 0.01 * aux

    def loss_ep(p):
        out, aux = moe_ffn_ep(cfg, p, x, mesh)
        return jnp.sum(out**2) + 0.01 * aux

    g1 = jax.jit(jax.grad(loss_single))(params)
    g2 = jax.jit(jax.grad(loss_ep))(params)
    for a, b in zip(jax.tree.leaves(g1), jax.tree.leaves(g2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=1e-5)


def test_moe_ep_rejects_indivisible(setup):
    cfg, params, x = setup
    mesh = make_mesh(MeshConfig(dp=1, ep=3), devices=jax.devices()[:3])
    with pytest.raises(ValueError, match="not divisible"):
        moe_ffn_ep(cfg, params, x, mesh)


def test_capacity_formula():
    cfg = MoEConfig(hidden_size=4, intermediate_size=8, num_experts=4,
                    top_k=2, capacity_factor=1.0)
    assert capacity(cfg, 16) == 8  # 2*16/4
