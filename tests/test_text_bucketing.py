"""Sequence-length bucketing for the combined/text path (ISSUE 2):
pad-to-bucket planning + token-budget batch sizing must preserve the
exact example multiset of the fixed pad-to-max collation, the shared
pad-id table must keep collaters and encoders in agreement, and a
misconfigured bucket edge must fail loudly at the encoder."""

import numpy as np
import pytest

from deepdfa_tpu.core.config import PAD_ID_BY_FAMILY
from deepdfa_tpu.data.text import (
    TextBatchPlan,
    batch_token_counts,
    bucketed_collate_batches,
    collate,
    collate_plan,
    collate_shards,
    plan_bucketed_batches,
    rows_for_bucket,
    token_lengths,
)
from deepdfa_tpu.data.tokenizer import HashTokenizer
from deepdfa_tpu.graphs.batch import GraphSpec


def make_spec(rng, gid: int, n_nodes: int = 4, label: float = 0.0):
    n_edges = max(1, n_nodes - 1)
    return GraphSpec(
        graph_id=gid,
        node_feats=rng.integers(0, 5, (n_nodes, 4)).astype(np.int32),
        node_vuln=np.zeros((n_nodes,), np.int32),
        edge_src=rng.integers(0, n_nodes, (n_edges,)).astype(np.int32),
        edge_dst=rng.integers(0, n_nodes, (n_edges,)).astype(np.int32),
        label=label,
    )


def make_rows(rng, n: int, max_t: int, pad_id: int):
    """Right-padded token rows with lognormal-ish real lengths >= 1."""
    lengths = np.clip(
        rng.lognormal(2.5, 1.0, n).astype(np.int64) + 1, 1, max_t
    )
    rows = np.full((n, max_t), pad_id, np.int32)
    for i, ln in enumerate(lengths):
        # real tokens are never pad_id, so token_lengths can recover ln
        vals = rng.integers(4, 500, ln).astype(np.int32)
        rows[i, :ln] = np.where(vals == pad_id, pad_id + 3, vals)
    return rows, lengths


# ---------------------------------------------------------------------------
# primitives


def test_token_lengths_roundtrip(rng):
    pad = PAD_ID_BY_FAMILY["roberta"]
    rows, lengths = make_rows(rng, 40, 64, pad)
    np.testing.assert_array_equal(token_lengths(rows, pad), lengths)


def test_token_lengths_all_pad_row():
    pad = 1
    rows = np.full((3, 8), pad, np.int32)
    rows[1, :2] = [5, 6]
    np.testing.assert_array_equal(token_lengths(rows, pad), [0, 2, 0])


def test_rows_for_bucket_formula():
    # rows x T <= budget, split over shards, floor at 1
    assert rows_for_bucket(64, 8192, 1) == 128
    assert rows_for_bucket(512, 8192, 1) == 16
    assert rows_for_bucket(512, 8192, 4) == 4
    assert rows_for_bucket(512, 100, 8) == 1  # degrade, never zero


def test_batch_token_counts(rng):
    pad = 1
    rows, lengths = make_rows(rng, 8, 32, pad)
    mask = np.zeros((8,), bool)
    mask[:5] = True
    real, padded, n = batch_token_counts(rows, mask, pad)
    assert real == int(lengths[:5].sum())
    assert padded == rows.size
    assert n == 5


# ---------------------------------------------------------------------------
# planner


def test_planner_rejects_bad_buckets():
    with pytest.raises(ValueError, match="ascending"):
        list(plan_bucketed_batches([4], [0], (64, 32), 128, 1, 8, 8))
    with pytest.raises(ValueError, match="ascending"):
        list(plan_bucketed_batches([4], [0], (32, 32), 128, 1, 8, 8))
    with pytest.raises(ValueError):
        list(plan_bucketed_batches([4], [0], (), 128, 1, 8, 8))


def test_planner_rejects_overlong_row():
    with pytest.raises(ValueError, match="exceeds the"):
        list(plan_bucketed_batches([65], [7], (32, 64), 128, 1, 8, 8))


def test_planner_signature_and_capacity(rng):
    pad = 1
    _, lengths = make_rows(rng, 64, 64, pad)
    buckets, budget, shards = (16, 32, 64), 128, 2
    plans = list(
        plan_bucketed_batches(
            lengths, list(range(64)), buckets, budget, shards, 8, 8
        )
    )
    assert plans, "planner emitted nothing"
    seen = set()
    for p in plans:
        assert p.seq_len in buckets
        # the ONE formula: rows per shard from the token budget
        assert p.rows_per_shard == rows_for_bucket(p.seq_len, budget, shards)
        assert len(p.example_ids) <= p.rows_per_shard * shards
        # every row's real length fits its bucket edge
        for eid in p.example_ids:
            assert lengths[eid] <= p.seq_len
            assert eid not in seen
            seen.add(eid)
    assert seen == set(range(64))  # exact partition, nothing dropped


def test_planner_deterministic_and_stats(rng):
    pad = 1
    _, lengths = make_rows(rng, 50, 64, pad)
    args = (lengths, list(range(50)), (16, 64), 256, 1, 8, 8)
    s1: dict = {}
    s2: dict = {}
    p1 = list(plan_bucketed_batches(*args, stats=s1))
    p2 = list(plan_bucketed_batches(*args, stats=s2))
    assert p1 == p2  # cache-replayable: deterministic in input order
    assert s1 == s2
    assert s1["rows"] == 50
    assert s1["batches"] == len(p1)
    assert s1["real_tokens"] == int(np.asarray(lengths).sum())
    # padded counts the FULL static shape (capacity x edge) per batch
    assert s1["padded_tokens"] == sum(
        rows_for_bucket(p.seq_len, 256, 1) * p.seq_len for p in p1
    )
    assert sum(s1["by_bucket"].values()) == 50


# ---------------------------------------------------------------------------
# bucketed collation vs fixed collation


def _multiset(batch, pad_id):
    """{(example_id-slot, label, unpadded-token-tuple)} for valid rows."""
    out = []
    ids = np.asarray(batch.input_ids).reshape(-1, batch.input_ids.shape[-1])
    labels = np.asarray(batch.labels).reshape(-1)
    mask = np.asarray(batch.row_mask).reshape(-1)
    for i in range(len(mask)):
        if not mask[i]:
            continue
        row = ids[i]
        ln = int(token_lengths(row[None], pad_id)[0])
        out.append((int(labels[i]), tuple(int(x) for x in row[:ln])))
    return sorted(out)


@pytest.mark.parametrize("num_shards", [1, 2])
def test_bucketed_collation_preserves_multiset(rng, num_shards):
    """Property (ISSUE 2): bucketed collation preserves the exact
    multiset of (label, unpadded token_ids) vs unbucketed collation, and
    has_graph matches graph availability when budgets are ample."""
    pad = PAD_ID_BY_FAMILY["roberta"]
    n, max_t = 60, 64
    rows, lengths = make_rows(rng, n, max_t, pad)
    token_ids = {i: rows[i] for i in range(n)}
    labels = {i: int(i % 2) for i in range(n)}
    # every third example has no extracted graph (has_graph degrade path)
    graphs = {i: make_spec(rng, i) for i in range(n) if i % 3}

    fixed = collate_shards(
        rows, [labels[i] for i in range(n)], list(range(n)), graphs,
        num_shards=num_shards, rows_per_shard=-(-n // num_shards),
        node_budget=4096, edge_budget=16384, pad_id=pad,
    )
    stats: dict = {}
    bucketed = list(
        bucketed_collate_batches(
            token_ids, labels, list(range(n)), graphs,
            (16, 32, 64), 256, num_shards, 4096, 16384, pad_id=pad,
            lengths=lengths, stats=stats,
        )
    )
    got = sorted(sum((_multiset(b, pad) for b in bucketed), []))
    want = _multiset(fixed, pad)
    assert got == want

    # has_graph tracks availability exactly (ample budgets: no degrade):
    # the count of graph-carrying valid rows matches availability, and
    # every carried slot holds an available graph's id
    hg_count = 0
    for b in bucketed:
        ids = np.asarray(b.graphs.graph_ids).reshape(-1)
        hg = np.asarray(b.has_graph).reshape(-1)
        mask = np.asarray(b.row_mask).reshape(-1)
        for i in range(len(mask)):
            if mask[i] and hg[i]:
                assert int(ids[i]) in graphs
                hg_count += 1
    assert hg_count == len(graphs)
    assert hg_count == int(
        np.asarray(fixed.has_graph).sum()
    )  # degrade behaviour identical to the fixed path
    total_real = sum(
        batch_token_counts(b.input_ids, b.row_mask, pad)[0] for b in bucketed
    )
    assert total_real == int(np.asarray(lengths).sum())
    assert stats["real_tokens"] == total_real


def test_has_graph_availability_matches_fixed_path(rng):
    """Row-degrade semantics are collate()'s own, unchanged: with ample
    budgets has_graph == availability; with a tight budget the degrade
    still happens per-batch (never a crash)."""
    pad = PAD_ID_BY_FAMILY["roberta"]
    n = 24
    rows, lengths = make_rows(rng, n, 32, pad)
    token_ids = {i: rows[i] for i in range(n)}
    labels = {i: 0 for i in range(n)}
    graphs = {i: make_spec(rng, i, n_nodes=6) for i in range(n) if i % 2}

    for b in bucketed_collate_batches(
        token_ids, labels, list(range(n)), graphs, (32,), 128, 1,
        4096, 16384, pad_id=pad, lengths=lengths,
    ):
        hg = np.asarray(b.has_graph).reshape(-1)
        ids = np.asarray(b.graphs.graph_ids).reshape(-1)
        mask = np.asarray(b.row_mask).reshape(-1)
        for r in range(len(mask)):
            if mask[r] and hg[r]:
                assert int(ids[r]) in graphs

    # tight node budget: some available graphs degrade to has_graph=False
    tight = list(
        bucketed_collate_batches(
            token_ids, labels, list(range(n)), graphs, (32,), 128, 1,
            8, 64, pad_id=pad, lengths=lengths,
        )
    )
    degraded = sum(
        int((~np.asarray(b.has_graph).reshape(-1)
             & np.asarray(b.row_mask).reshape(-1)).sum())
        for b in tight
    )
    assert degraded > n // 2  # budget 8 nodes cannot hold 6-node graphs


def test_collate_plan_matches_collate_shards(rng):
    """A plan materializes through the standard collater: same bytes as
    calling collate_shards on the plan's rows directly."""
    pad = PAD_ID_BY_FAMILY["roberta"]
    rows, lengths = make_rows(rng, 12, 32, pad)
    token_ids = {i: rows[i] for i in range(12)}
    labels = {i: int(i % 2) for i in range(12)}
    graphs = {i: make_spec(rng, i) for i in range(12)}
    plan = TextBatchPlan(
        example_ids=tuple(range(10)), seq_len=32, rows_per_shard=5,
        num_shards=2, node_budget=512, edge_budget=2048,
    )
    got = collate_plan(plan, token_ids, labels, graphs, pad)
    want = collate_shards(
        rows[:10], [labels[i] for i in range(10)], list(range(10)),
        graphs, num_shards=2, rows_per_shard=5, node_budget=512,
        edge_budget=2048, pad_id=pad,
    )
    np.testing.assert_array_equal(got.input_ids, want.input_ids)
    np.testing.assert_array_equal(got.labels, want.labels)
    np.testing.assert_array_equal(got.row_mask, want.row_mask)
    np.testing.assert_array_equal(got.has_graph, want.has_graph)
    np.testing.assert_array_equal(
        got.graphs.node_feats, want.graphs.node_feats
    )


# ---------------------------------------------------------------------------
# shared pad-id table (satellite)


def test_pad_id_table_matches_tokenizers_and_encoders():
    from deepdfa_tpu.models.t5 import T5Config
    from deepdfa_tpu.models.transformer import TransformerConfig

    assert HashTokenizer().pad_id == PAD_ID_BY_FAMILY["roberta"]
    assert HashTokenizer(t5_frame=True).pad_id == PAD_ID_BY_FAMILY["t5"]
    assert TransformerConfig().pad_token_id == PAD_ID_BY_FAMILY["roberta"]
    assert T5Config().pad_token_id == PAD_ID_BY_FAMILY["t5"]


def test_collate_default_pad_matches_roberta_family(rng):
    pad = PAD_ID_BY_FAMILY["roberta"]
    rows, _ = make_rows(rng, 4, 8, pad)
    b = collate(
        rows, [0, 1, 0, 1], list(range(4)), {}, batch_rows=6,
        node_budget=64, edge_budget=256,
    )
    # padding rows are filled with the family pad id
    assert (np.asarray(b.input_ids)[4:] == pad).all()


# ---------------------------------------------------------------------------
# encoder capacity guards (satellite)


def test_transformer_position_guard_raises():
    import jax

    from deepdfa_tpu.models import transformer as tfm

    cfg = tfm.TransformerConfig.tiny(max_position_embeddings=20)
    params = tfm.init_params(cfg, jax.random.key(0))
    ids = np.full((2, 32), 7, np.int32)  # 32 + pad_id 1 > 20 - 1
    with pytest.raises(ValueError, match="max_position_embeddings"):
        tfm.encode(cfg, params, ids)
    # a fitting length passes
    tfm.encode(cfg, params, np.full((2, 8), 7, np.int32))


def test_t5_sequence_length_guard_raises():
    import dataclasses

    import jax

    from deepdfa_tpu.models import t5 as t5m

    cfg = dataclasses.replace(t5m.T5Config.tiny(), max_sequence_length=16)
    params = t5m.init_params(cfg, jax.random.key(0))
    with pytest.raises(ValueError, match="max_sequence_length"):
        t5m.encode(cfg, params, np.full((2, 32), 7, np.int32))
    t5m.encode(cfg, params, np.full((2, 16), 7, np.int32))  # at the bound


# ---------------------------------------------------------------------------
# loss equivalence (acceptance): bucketed pad target vs 512-pad


def test_bucketed_logits_match_512_pad(rng):
    """Per-example logits from a bucket-edge-padded batch match the
    unbucketed 512-pad batch within fp tolerance: attention masks out
    pad, CLS pooling reads position 0, and RoBERTa position ids depend
    only on the row index — so the pad target is numerically inert."""
    import jax

    from deepdfa_tpu.models import combined as cmb
    from deepdfa_tpu.models.transformer import TransformerConfig

    pad = PAD_ID_BY_FAMILY["roberta"]
    n = 8
    rows, lengths = make_rows(rng, n, 48, pad)
    wide = np.full((n, 512), pad, np.int32)
    wide[:, :48] = rows
    graphs = {i: make_spec(rng, i) for i in range(n)}
    labels = list(range(n))

    cfg = cmb.CombinedConfig(
        encoder=TransformerConfig.tiny(
            dropout_rate=0.0, max_position_embeddings=516
        ),
        graph_hidden_dim=8,
        graph_input_dim=6,
    )
    params = cmb.init_params(cfg, jax.random.key(0))

    def logits_of(token_mat):
        b = collate(
            token_mat, labels, list(range(n)), graphs, batch_rows=n,
            node_budget=256, edge_budget=1024, pad_id=pad,
        )
        return np.asarray(
            cmb.forward(cfg, params, b.input_ids, b.graphs, b.has_graph)
        )

    wide_logits = logits_of(wide)
    narrow_logits = logits_of(rows[:, :64])  # bucket edge 64 >= max len
    np.testing.assert_allclose(
        narrow_logits, wide_logits, rtol=1e-4, atol=1e-4
    )


def test_text_pool_and_cache_roundtrip(rng, tmp_path):
    """The spawn-pool collater and the packed-batch cache's TextBatch
    branch are bit-identical to inline collation — every leaf, nested
    graph leaves included, and the full stream length."""
    from deepdfa_tpu.data.mp_pack import TextMpPacker
    from deepdfa_tpu.data.packed_cache import (
        PackedBatchCache,
        cache_key,
        text_corpus_digest,
    )
    from deepdfa_tpu.data.text import TEXT_ARRAY_FIELDS
    from deepdfa_tpu.graphs.batch import ARRAY_FIELDS

    pad = PAD_ID_BY_FAMILY["roberta"]
    n, max_t = 40, 64
    rows, lengths = make_rows(rng, n, max_t, pad)
    token_ids = {i: rows[i] for i in range(n)}
    labels = {i: int(i % 2) for i in range(n)}
    graphs = {i: make_spec(rng, i) for i in range(n) if i % 3}
    args = ((16, 32, 64), 256, 2, 4096, 16384)

    def leaves(b):
        out = [np.asarray(getattr(b, f)) for f in TEXT_ARRAY_FIELDS]
        for f in ARRAY_FIELDS:
            v = getattr(b.graphs, f)
            if v is not None:
                out.append(np.asarray(v))
        return out

    def same(a, b):
        la, lb = leaves(a), leaves(b)
        return len(la) == len(lb) and all(map(np.array_equal, la, lb))

    inline = list(
        bucketed_collate_batches(
            token_ids, labels, list(range(n)), graphs, *args, pad_id=pad
        )
    )
    assert len(inline) > 1

    with TextMpPacker(token_ids, labels, graphs, pad_id=pad, workers=2) as p:
        pooled = list(p.bucketed_batches(list(range(n)), *args))
    assert len(pooled) == len(inline)
    assert all(same(a, b) for a, b in zip(pooled, inline))

    cache = PackedBatchCache(tmp_path)
    key = cache_key(
        dict(kind="text", pad_id=pad), text_corpus_digest(token_ids, labels)
    )
    list(cache.write_through(key, iter(inline)))
    replayed = list(cache.replay(key))
    assert len(replayed) == len(inline)
    assert all(same(a, b) for a, b in zip(replayed, inline))
    assert all(
        int(a.graphs.num_graphs) == int(b.graphs.num_graphs)
        for a, b in zip(replayed, inline)
    )
